"""Edge-hub tier of the hierarchical aggregation tree.

The flat topology terminates EVERY connection on one root hub and folds
every upload on one server process — PR 10 proved 10k virtual clients
on that shape, and its profile names the wall: the root's work is
O(connections) on the socket side and O(uploads) on the fold side.  An
``EdgeHubManager`` splits both axes the way the reference's
``hierarchical``/``TurboAggregate`` families do: it runs a LOCAL
``TcpHub`` that terminates a slice of the federation's muxers/clients,
folds their uploads with the same O(1) streaming aggregation the root
runs (``core.tree.tree_fold_weighted`` — the identical fp64 num/den
arithmetic), and uplinks ONE pre-folded ``(sum n·model, sum n)`` pair
per round (``MSG_TYPE_E2S_PARTIAL``).  fp64 addition is exact at
training magnitudes, so the root adding partial sums reproduces the
flat fold BIT-FOR-BIT — the tree-vs-flat byte-identity pin.

Composition over the extra hop (each leg crossed exactly once per edge
link):

- **downlink**: the uplink connection registers every downstream node
  id (hello v2, ``comm/edge.EdgeUplinkBackend``), so the root hub's
  mcast dedup/mux wraps/stripes/shm lanes treat the edge like a muxer;
  the edge re-fans each broadcast to its own connections through its
  local hub, which stripes/lanes independently.
- **uplink**: model uploads fold locally; everything else (telemetry
  digests, resync requests, stats) forwards upstream verbatim with the
  origin sender preserved.  Resync replies (unicast S2C frames) forward
  downstream unchanged — recovery semantics stay root-authoritative.
- **fallback-to-flat**: an upload the edge cannot fold (no decode base
  after a restart, a stale/unknown round) forwards upstream RAW,
  counted (``edge.flat_fallbacks{reason=}``), never silently dropped —
  the root's own firewalls remain the authority on it.

Defense composition: per-upload screening (norm clip / outlier reject /
client-level DP) is a pure function of (upload, base, seed, round,
slot) and runs AT THE EDGE, identical to the flat run's screening.
Connection-cap grouping keeps the flat granularity by tagging each
partial with its edge-local connection group.  Buffered estimators
(median/trimmed-mean) need the raw per-client trees at the root and do
NOT compose — the constructor refuses them loudly.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Set

import jax
import numpy as np

from fedml_tpu.algorithms.fedavg_cross_device import (
    SERVER,
    UploadRejected,
    decode_validated_upload,
    reconstruct_sync_model,
)
from fedml_tpu.analysis.locks import assert_held, make_lock
from fedml_tpu.comm.backend import NodeManager
from fedml_tpu.comm.edge import EdgeUplinkBackend, mux_nodes
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_CONTRIBUTORS,
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
    MSG_ARG_KEY_ROUND_INDEX,
    MSG_TYPE_C2S_RESYNC,
    MSG_TYPE_C2S_SEND_MODEL,
    MSG_TYPE_C2S_SEND_STATS,
    MSG_TYPE_C2S_TELEMETRY,
    MSG_TYPE_E2S_PARTIAL,
    MSG_TYPE_S2C_FINISH,
    MSG_TYPE_S2C_INIT_CONFIG,
    MSG_TYPE_S2C_SYNC_MODEL,
    Message,
    tree_to_wire,
)
from fedml_tpu.core import tree as treelib
from fedml_tpu.obs import flight
from fedml_tpu.obs.telemetry import get_telemetry


class _DownlinkIntake(NodeManager):
    """Handler shim on the UPLINK backend: broadcasts and unicast
    replies arriving from the root."""

    def __init__(self, edge: "EdgeHubManager", backend):
        self._edge = edge  # before super(): init registers handlers
        super().__init__(backend)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_S2C_INIT_CONFIG, self._edge._on_downlink_model)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL, self._edge._on_downlink_model)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_FINISH, self._edge._on_finish)


class _LocalIntake(NodeManager):
    """Handler shim on the LOCAL backend (node 0 of the edge's own
    hub): the cohort's uplink traffic."""

    def __init__(self, edge: "EdgeHubManager", backend):
        self._edge = edge
        super().__init__(backend)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL, self._edge._on_upload)
        # non-model uplink traffic is transparent: forwarded upstream
        # with the origin sender preserved, so the root's stats plane
        # and resync protocol see exactly the flat topology's frames
        for mt in (MSG_TYPE_C2S_TELEMETRY, MSG_TYPE_C2S_RESYNC,
                   MSG_TYPE_C2S_SEND_STATS):
            self.register_message_receive_handler(
                mt, self._edge._forward_up)


class EdgeHubManager:
    """One edge hub: local ``TcpHub`` + local server endpoint (node 0)
    terminating a downstream cohort, an ``EdgeUplinkBackend`` to the
    root, and the partial-fold state machine between them.

    Threading: ``_on_upload``/decode-pool workers, the uplink reader
    (``_on_downlink_model``), and the local-deadline Timer share the
    round state under ``_fold_lock`` (declared in ``_GUARDED_BY``,
    enforced by fedlint's lock-discipline rule).  Partials are BUILT
    under the lock and SENT outside it, the server's send discipline.
    """

    _GUARDED_BY = {
        "_expected": "_fold_lock",
        "_reported": "_fold_lock",
        "_groups": "_fold_lock",
        "_flush_now": "_fold_lock",
        "_passthrough": "_fold_lock",
        "_inflight": "_fold_lock",
    }

    def __init__(
        self,
        uplink: EdgeUplinkBackend,
        local_backend,
        local_hub,
        template,
        *,
        round_timeout: Optional[float] = None,
        deadline_frac: float = 0.75,
        decode_workers: int = 0,
        defense=None,
        seed: int = 0,
        delta_base_window: int = 4,
        crash_at_round: Optional[int] = None,
    ):
        self._uplink = uplink
        self._local = local_backend
        self._hub = local_hub
        self._template = template
        self._all_ids: Set[int] = set(uplink.node_ids)
        self.round_timeout = round_timeout
        # the edge's partial must reach the root BEFORE the root's own
        # deadline fires, so the local flush deadline is a fraction of
        # the round timeout (late locals still uplink as singleton
        # partials — the root's stale firewall is the authority)
        self.deadline_frac = max(0.1, min(0.95, float(deadline_frac)))
        self.seed = seed
        self.crash_at_round = crash_at_round
        from fedml_tpu.robust import DefenseConfig, RobustAggregator

        if isinstance(defense, dict):
            defense = DefenseConfig(**defense)
        self.defense = defense if (defense is not None
                                   and defense.enabled) else None
        if self.defense is not None and self.defense.buffered:
            # median/trimmed-mean need every raw per-client tree at the
            # ROOT close; a pre-folded pair cannot feed them — refuse,
            # don't run undefended
            raise ValueError(
                "tree topology requires a streaming-composable defense "
                "(buffered median/trimmed_mean need raw uploads at the "
                "root — run those on the flat topology)"
            )
        self._robust = (RobustAggregator(self.defense, seed=seed)
                        if self.defense is not None else None)
        self._conn_cap = (self.defense.conn_cap
                          if self.defense is not None else 0.0)
        # round state (all under _fold_lock)
        self._fold_lock = make_lock("EdgeHubManager._fold_lock")
        self._round: Optional[int] = None
        self._base = None
        self._bases: "OrderedDict[int, object]" = OrderedDict()
        self._window = max(1, int(delta_base_window))
        self._passthrough = False
        self._expected: Set[int] = set()
        self._reported: Set[int] = set()
        # conn group (None = fused) -> [acc_tree, n_sum, {node: n}];
        # accumulates since the last flush — an edge may flush several
        # disjoint partials per round (ack groups, late stragglers)
        self._groups: Dict[Optional[str], list] = {}
        # dispatched-but-unsettled uploads (decode pool depth + inline
        # folds in progress).  NOT reset per round: every increment at
        # intake is balanced by exactly one decrement when the fold
        # settles (folded, stale, or rejected), even across a rollover
        self._inflight = 0
        self._flush_now = False
        self._deadline_timer: Optional[threading.Timer] = None
        self._finished = threading.Event()
        self.decode_workers = max(0, int(decode_workers))
        if self.decode_workers:
            from concurrent.futures import ThreadPoolExecutor

            self._decode_pool = ThreadPoolExecutor(
                max_workers=self.decode_workers,
                thread_name_prefix="edge-decode",
            )
        else:
            self._decode_pool = None
        self._downlink_mgr = _DownlinkIntake(self, uplink)
        self._local_mgr = _LocalIntake(self, local_backend)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._conn_cap > 0:
            # connection attribution for the cap grouping: the edge's
            # LOCAL hub is the authority on its cohort's physical
            # connections (same pre-run synchronous fetch as the root)
            fetch = getattr(self._local, "fetch_conn_map", None)
            if fetch is not None:
                self._robust.set_conn_map(fetch())
        self._local.run_in_thread()

    def run(self) -> None:
        """Block on the uplink reader until FINISH tears us down."""
        self._uplink.run()

    # -- downlink -----------------------------------------------------------
    def _on_downlink_model(self, msg: Message) -> None:
        nodes = mux_nodes(msg)
        if nodes is None and msg.receiver != -1:
            # unicast reply for one downstream node (a resync full
            # model): pure forward — recovery stays root-authoritative
            try:
                self._local.send_message(msg)
            except OSError:
                get_telemetry().inc("comm.send_failed",
                                    msg_type=msg.type)
                logging.warning(
                    "edge %d: could not forward %s down to node %d",
                    self._uplink.node_id, msg.type, msg.receiver,
                )
            return
        round_idx = msg.get(MSG_ARG_KEY_ROUND_INDEX)
        if (self.crash_at_round is not None and round_idx is not None
                and int(round_idx) == int(self.crash_at_round)):
            # chaos edge_hub_crash: die exactly like a crashed client
            # process — flight dump first (force: the black box's last
            # words ARE the point), then a hard non-zero exit
            import os

            flight.trigger("crash", round_idx=int(round_idx),
                           reason="chaos edge_hub crash", force=True)
            os._exit(137)
        leftovers = []
        with self._fold_lock:
            if round_idx is not None and round_idx != self._round:
                # round rollover: anything still unflushed belongs to
                # the PREVIOUS round — uplink it anyway (counted; the
                # root's stale firewall decides), then reset
                if any(ent[2] for ent in self._groups.values()):
                    leftovers = self._build_partials_locked("rollover")
                self._open_round_locked(msg, int(round_idx))
            self._expected.update(int(n) for n in (nodes or ()))
        # re-fan OUTSIDE the lock: the local hub stripes/lanes this to
        # the cohort independently — the broadcast crosses each tier's
        # wire exactly once
        targets = [int(n) for n in (nodes or sorted(self._all_ids))]
        try:
            self._local.send_multicast(msg, targets)
        except OSError:
            get_telemetry().inc("comm.send_failed", msg_type=msg.type)
            logging.warning(
                "edge %d: could not re-fan %s to %d local nodes (their "
                "round rides the deadlines)", self._uplink.node_id,
                msg.type, len(targets),
            )
        self._send_partials(leftovers)

    def _open_round_locked(self, msg: Message, round_idx: int) -> None:  # fedlint: holds=_fold_lock
        """Reset per-round state and reconstruct the decode base from
        the round's FIRST sync frame (later ack-group frames only
        extend ``_expected``)."""
        assert_held(self._fold_lock, "EdgeHubManager._open_round_locked")
        self._round = round_idx
        self._expected = set()
        self._reported = set()
        self._groups = {}
        self._flush_now = False
        self._passthrough = False
        try:
            variables, self._window = reconstruct_sync_model(
                msg, self._template, self._bases, self._window
            )
        except Exception:
            logging.exception("edge %d: sync reconstruction failed for "
                              "round %d", self._uplink.node_id, round_idx)
            variables = None
        if variables is None:
            # no decode base (delta against an uncached round after an
            # edge restart): this round runs in pass-through — every
            # upload forwards upstream raw, counted per upload.  The
            # base self-heals on the next full frame the root sends.
            self._base = None
            self._passthrough = True
            logging.warning(
                "edge %d: no decode base for round %d — pass-through "
                "(uploads forward upstream raw)", self._uplink.node_id,
                round_idx,
            )
        else:
            if msg.get("delta_window") is None:
                # plain full-mode frame: reconstruct returns views into
                # the transport buffer (only delta mode caches an owned
                # copy); the base must outlive this delivery scope
                variables = jax.tree_util.tree_map(
                    lambda l: np.array(l, copy=True), variables
                )
            self._base = variables
        if self._robust is not None and self._conn_cap > 0:
            # refresh connection attribution once per round (async
            # reply, current by the first fold — the root's discipline)
            req = getattr(self._local, "request_conn_map", None)
            if req is not None:
                req()
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
        if self.round_timeout is not None:
            t = threading.Timer(
                self.deadline_frac * self.round_timeout,
                self._on_deadline, args=(round_idx,),
            )
            t.daemon = True
            self._deadline_timer = t
            t.start()

    def _on_deadline(self, round_gen: int) -> None:
        msgs = []
        with self._fold_lock:
            if round_gen != self._round:
                return  # stale timer: that round already rolled over
            self._flush_now = True  # late folds flush as singletons
            if any(ent[2] for ent in self._groups.values()):
                msgs = self._build_partials_locked("deadline")
        self._send_partials(msgs)

    def _on_finish(self, msg: Message) -> None:
        """Re-fan FINISH to the cohort, wait for it to drain, tear the
        tier down (runs on the uplink reader thread — blocking it is
        fine, the uplink's work is over)."""
        if self._finished.is_set():
            return
        self._finished.set()
        targets = [int(n) for n in (mux_nodes(msg)
                                    or sorted(self._all_ids))]
        try:
            self._local.send_multicast(msg, targets)
        except OSError:
            logging.warning("edge %d: could not re-fan FINISH",
                            self._uplink.node_id)
        # let the cohort receive FINISH and hang up before the local
        # hub dies under them (connections floor is our own node-0
        # endpoint); bounded — stragglers are the launcher's problem
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            try:
                if self._hub.stats().get("connections", 0) <= 1:
                    break
            except Exception:
                break
            time.sleep(0.1)
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
        if self._decode_pool is not None:
            self._decode_pool.shutdown(wait=False)
        self._local.stop()
        self._hub.stop()
        self._uplink.stop()

    # -- uplink (cohort traffic) --------------------------------------------
    def _forward_up(self, msg: Message) -> None:
        """Transparent upstream forward preserving the origin sender —
        the root sees the flat topology's exact frame."""
        try:
            self._uplink._send_message_as(msg, msg.sender)
        except OSError:
            get_telemetry().inc("comm.send_failed", msg_type=msg.type)
            logging.warning(
                "edge %d: could not forward %s from node %d upstream",
                self._uplink.node_id, msg.type, msg.sender,
            )

    def _on_upload(self, msg: Message) -> None:
        reply_round = msg.get(MSG_ARG_KEY_ROUND_INDEX)
        tel = get_telemetry()
        with self._fold_lock:
            foldable = (self._round is not None
                        and reply_round is not None
                        and int(reply_round) == self._round
                        and not self._passthrough
                        and self._base is not None)
            if foldable and msg.sender in self._reported:
                # duplicate (chaos redelivery): the streaming fold
                # cannot un-fold the first copy — drop, counted, same
                # as the root's duplicate screen
                tel.inc("faults.observed", kind="duplicate_upload",
                        msg_type=MSG_TYPE_C2S_SEND_MODEL)
                return
            if foldable:
                self._reported.add(msg.sender)
                self._inflight += 1
                base = self._base
            else:
                # fallback-to-flat: counted, never silent — the raw
                # upload forwards upstream and the root's firewalls
                # (stale/corrupt/defense) remain the authority on it
                if self._round is None or reply_round is None:
                    reason = "no_round"
                elif self._passthrough or self._base is None:
                    reason = "no_base"
                else:
                    reason = "stale_round"
                self._reported.add(msg.sender)
        if not foldable:
            tel.inc("edge.flat_fallbacks", reason=reason)
            self._forward_up(msg)
            return
        if self._decode_pool is not None:
            unpin = msg.pin_payload()
            try:
                self._decode_pool.submit(
                    self._fold_upload_pinned, msg, base,
                    int(reply_round), unpin,
                )
            except RuntimeError:
                # pool already shut down (FINISH teardown raced a
                # straggler): settle the dispatch so the inflight
                # count stays balanced
                unpin()
                self._note_upload_done()
            return
        self._fold_upload(msg, base, int(reply_round))

    def _fold_upload_pinned(self, msg, base, reply_round, unpin) -> None:
        try:
            self._fold_upload(msg, base, reply_round)
        finally:
            unpin()

    def _fold_upload(self, msg: Message, base, reply_round: int) -> None:
        try:
            self._fold_upload_inner(msg, base, reply_round)
        except Exception:
            logging.exception("edge %d: upload decode/fold failed for "
                              "node %d", self._uplink.node_id, msg.sender)
            self._reject(msg.sender, "undecodable_upload")
        finally:
            # EVERY dispatched upload settles here — folded, stale, or
            # rejected — which is where the flush decision lives
            self._note_upload_done()

    def _fold_upload_inner(self, msg: Message, base,
                           reply_round: int) -> None:
        t0 = time.perf_counter()
        try:
            # THE shared intake (fedavg_cross_device): same decode,
            # same delta semantics, same non-finite firewall as the
            # root — a bad upload dies at this tier, counted the same
            variables, n = decode_validated_upload(msg, base)
        except UploadRejected as bad:
            self._reject(msg.sender, bad.kind)
            return
        defense_flags = None
        group: Optional[str] = None
        if self._robust is not None:
            # per-upload screening is a pure function of (upload, base,
            # seed, round, slot) — bit-identical to the flat run's
            screened, defense_flags = self._robust.screen(
                variables, base, round_idx=reply_round,
                slot=msg.sender - 1,
            )
            if screened is None:
                self._reject(msg.sender, "outlier_upload")
                return
            variables = screened
            if self._conn_cap > 0:
                fn = getattr(self._local, "conn_map", None)
                if callable(fn):
                    self._robust.set_conn_map(fn())
                group = self._robust.conn_key(msg.sender)
        tel = get_telemetry()
        tel.observe("span.decode_s", time.perf_counter() - t0)
        with self._fold_lock:
            if self._round != reply_round:
                # round rolled over while decoding: too late to fold —
                # counted as a stale observation, the root's deadline
                # accounting already gave up on this reporter
                tel.inc("faults.observed", kind="stale_upload",
                        msg_type=MSG_TYPE_C2S_SEND_MODEL)
                return
            ent = self._groups.setdefault(group, [None, 0.0, {}])
            t1 = time.perf_counter()
            # the SAME fp64 fold the root runs on raw uploads — this
            # accumulator IS the flat fold restricted to this cohort,
            # which is what makes the uplinked num/den compose exactly
            ent[0] = treelib.tree_fold_weighted(ent[0], variables, n)
            ent[1] += float(n)
            ent[2][msg.sender] = float(n)
            tel.observe("span.agg_fold_s", time.perf_counter() - t1)
            if self._robust is not None:
                self._robust.note_upload(defense_flags)
            tel.inc("edge.folded_uploads")

    def _note_upload_done(self) -> None:
        """Flush decision, taken when the intake PIPELINE drains — not
        at intake time.  ``_reported`` fills as fast as frames arrive
        while the decode pool is still working, so flushing on
        reported-set coverage alone emits one premature "complete"
        partial plus a singleton "late" cascade for everything still
        in the pool: O(cohort) uplink frames, the exact cost this tier
        exists to remove.  Waiting for ``_inflight == 0`` batches the
        round into O(conn groups) partials and also covers the
        last-upload-rejected case (a reject settles the pipeline and
        releases whatever DID fold)."""
        msgs = []
        with self._fold_lock:
            self._inflight -= 1
            if self._inflight > 0:
                return
            have = any(ent[2] for ent in self._groups.values())
            if self._flush_now:
                if have:
                    msgs = self._build_partials_locked("late")
            elif (have and self._expected
                    and self._reported >= self._expected):
                msgs = self._build_partials_locked("complete")
        self._send_partials(msgs)

    def _reject(self, sender: int, kind: str) -> None:
        """Edge twin of the root's ``_reject_upload``: counted on the
        same series, black-boxed, excluded from the partial."""
        get_telemetry().inc("faults.observed", kind=kind,
                            msg_type=MSG_TYPE_C2S_SEND_MODEL)
        flight.note("faults", "observed", what=kind, sender=sender)
        flight.trigger("reject", round_idx=self._round or 0,
                       reason=f"{kind} from node {sender} (edge tier)")
        logging.warning(
            "edge %d: rejected %s from node %d (excluded from the "
            "partial)", self._uplink.node_id, kind, sender,
        )

    # -- partial flush ------------------------------------------------------
    def _build_partials_locked(self, reason: str) -> list:  # fedlint: holds=_fold_lock
        """Materialize every non-empty accumulator group as one
        E2S_PARTIAL message and reset them (caller holds the fold
        lock; the SEND happens outside it)."""
        assert_held(self._fold_lock,
                    "EdgeHubManager._build_partials_locked")
        msgs = []
        for group in sorted(self._groups, key=lambda g: (g is None, g or "")):
            acc, n_sum, contrib = self._groups[group]
            if not contrib:
                continue
            m = Message(MSG_TYPE_E2S_PARTIAL, self._uplink.node_id,
                        SERVER)
            # fp64 leaves survive the v2 wiretree dtype-preserving —
            # the root decodes against an fp64 template, so the
            # accumulator crosses the wire bit-exactly
            m.add_params(MSG_ARG_KEY_MODEL_PARAMS, tree_to_wire(acc))
            m.add_params(MSG_ARG_KEY_NUM_SAMPLES, float(n_sum))
            m.add_params(MSG_ARG_KEY_ROUND_INDEX, self._round)
            m.add_params(MSG_ARG_KEY_CONTRIBUTORS,
                         {str(k): float(v)
                          for k, v in sorted(contrib.items())})
            if group is not None:
                # cap grouping at flat granularity: the root keys its
                # per-conn accumulator by this tag
                m.add_params("conn_group",
                             f"edge{self._uplink.node_id}:{group}")
            msgs.append((m, reason))
        self._groups = {}
        if reason in ("complete", "deadline"):
            # the round's main flush happened: any later local
            # straggler uplinks immediately as a singleton partial
            self._flush_now = True
        return msgs

    def _send_partials(self, msgs: list) -> None:
        if not msgs:
            return
        tel = get_telemetry()
        for m, reason in msgs:
            try:
                self._uplink.send_message(m)
            except OSError:
                tel.inc("comm.send_failed",
                        msg_type=MSG_TYPE_E2S_PARTIAL)
                logging.warning(
                    "edge %d: could not uplink partial (%s, round %s) — "
                    "the root's deadline covers the cohort",
                    self._uplink.node_id, reason,
                    m.get(MSG_ARG_KEY_ROUND_INDEX),
                )
                continue
            tel.inc("edge.uplink_frames", reason=reason)
            try:
                tel.inc("edge.uplink_bytes",
                        sum(len(p) for p in m.to_frame_parts()))
            except Exception:
                pass
            flight.note("edge", "partial_uplinked", reason=reason,
                        round_idx=m.get(MSG_ARG_KEY_ROUND_INDEX),
                        contributors=len(
                            m.get(MSG_ARG_KEY_CONTRIBUTORS) or {}))

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        snap = get_telemetry().snapshot()["counters"]
        return {
            "folded_uploads": sum(
                v for k, v in snap.items()
                if k.startswith("edge.folded_uploads")),
            "uplink_frames": sum(
                v for k, v in snap.items()
                if k.startswith("edge.uplink_frames")),
            "uplink_bytes": sum(
                v for k, v in snap.items()
                if k.startswith("edge.uplink_bytes")),
            "flat_fallbacks": sum(
                v for k, v in snap.items()
                if k.startswith("edge.flat_fallbacks")),
        }
