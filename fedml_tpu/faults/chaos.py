"""``ChaosBackend`` — fault-injecting ``CommBackend`` wrapper.

Wraps ANY transport (the deterministic inproc bus or the TCP hub
backend) and applies a ``FaultPlan`` on both paths:

- **send**: ``send_message`` consults the plan before handing the frame
  to the inner transport — drop, corrupt (NaN-fill a model leaf),
  duplicate, delay/reorder, or sever the connection after sending;
- **notify (recv)**: the wrapper registers itself as the inner
  backend's observer and re-delivers to ITS observers, applying the
  plan's recv mix on the way — a delayed inbound upload is exactly the
  post-deadline straggler frame the server must stale-reject.

Delay semantics per transport:

- inproc: hold the message for ``delay_msgs`` subsequent messages on the
  same path, and flush any still-held messages when the bus quiesces
  (``InprocBus.add_quiesce_hook``) — a "late arrival" in the
  synchronous simulation, with a fully deterministic delivery trace;
- tcp: a daemon ``threading.Timer`` re-injects after ``delay_s`` wall
  seconds (real transports are allowed real nondeterminism; the
  determinism contract is the inproc trace).

Telemetry: every injected fault increments
``faults.injected{action=...,msg_type=...}`` on the process registry, so
chaos runs can assert ``observed == injected`` against the tolerance
layer's ``faults.observed``/``hub.dropped_frames`` counters.  The
wrapper does NOT double-count ``comm.*`` series: sends are recorded by
the inner transport, receives by the inner ``_notify``.
"""

from __future__ import annotations

import base64
import logging
import threading
from typing import Callable, List, Optional

import numpy as np

from fedml_tpu.analysis.locks import make_lock
from fedml_tpu.comm.backend import CommBackend, Observer
from fedml_tpu.comm.message import NDARRAY_KEY, WIRETREE_KEY, Message
from fedml_tpu.faults.plan import FaultPlan
from fedml_tpu.obs import flight, trace_ctx
from fedml_tpu.obs.telemetry import get_telemetry


def _is_float_dtype(dt: np.dtype) -> bool:
    """True for any dtype that can hold a NaN: native floats (kind 'f')
    AND the ml_dtypes extras (bfloat16 etc. register as kind 'V')."""
    return dt.kind == "f" or dt.name.startswith(("bfloat16", "float8"))


def _nan_leaf_twin(leaf) -> Optional[object]:
    """NaN-filled COPY of one wiretree leaf, or None if the leaf holds
    no float payload.  Handles every wire generation: v1 b64 dicts, v2
    raw arrays, and codec entries (whose float sub-arrays — scales /
    values — NaN-fill, so the decoded update is non-finite and the
    server's corrupt-upload firewall fires exactly as for raw faults)."""
    from fedml_tpu.comm.message import _np_dtype

    if isinstance(leaf, dict) and NDARRAY_KEY in leaf:
        dt = _np_dtype(leaf.get("dtype", "float32"))
        if not _is_float_dtype(dt):
            return None
        bad = np.full(leaf.get("shape") or (), np.nan, dtype=dt)
        return {**leaf, NDARRAY_KEY: base64.b64encode(bad.tobytes()).decode()}
    if isinstance(leaf, dict) and "enc" in leaf:
        enc = leaf["enc"]
        for name, arr in enc.items():
            a = np.asarray(arr)
            if _is_float_dtype(a.dtype):
                return {**leaf,
                        "enc": {**enc, name: np.full_like(a, np.nan)}}
        return None
    a = np.asarray(leaf) if hasattr(leaf, "dtype") else None
    if a is not None and _is_float_dtype(a.dtype):
        return np.full_like(a, np.nan)
    return None


def _scaled_leaf_twin(leaf, factor: float) -> Optional[object]:
    """COPY of one wiretree leaf with every float payload multiplied by
    ``factor`` (the Byzantine upload mutation), or None if the leaf
    holds no float payload.  Same leaf-form coverage as
    ``_nan_leaf_twin``: v1 b64 dicts, v2 raw arrays, and codec entries
    — for codecs every float sub-array scales (decode is linear in
    each: qsgd/bf16 scales, top-k values), so the DECODED update is
    exactly ``factor ×`` the honest one."""
    from fedml_tpu.comm.message import _np_dtype

    def scaled(a: np.ndarray) -> np.ndarray:
        # promote-multiply-cast: ml_dtypes (bf16) payloads survive
        return (np.asarray(a, np.float32) * factor).astype(a.dtype)

    if isinstance(leaf, dict) and NDARRAY_KEY in leaf:
        dt = _np_dtype(leaf.get("dtype", "float32"))
        if not _is_float_dtype(dt):
            return None
        buf = np.frombuffer(
            base64.b64decode(leaf[NDARRAY_KEY]), dtype=dt
        ).reshape(leaf.get("shape") or ())
        return {**leaf,
                NDARRAY_KEY: base64.b64encode(
                    scaled(buf).tobytes()).decode()}
    if isinstance(leaf, dict) and "enc" in leaf:
        enc = dict(leaf["enc"])
        hit = False
        for name, arr in leaf["enc"].items():
            a = np.asarray(arr)
            if _is_float_dtype(a.dtype):
                enc[name] = scaled(a)
                hit = True
        return {**leaf, "enc": enc} if hit else None
    a = np.asarray(leaf) if hasattr(leaf, "dtype") else None
    if a is not None and _is_float_dtype(a.dtype):
        return scaled(a)
    return None


def attack_message(msg: Message, factor: float) -> Optional[Message]:
    """Copy-on-write Byzantine mutation: multiply EVERY float leaf of
    the first wire pytree in the params (the model payload) by
    ``factor`` — ``-1`` is the sign-flip attack, ``±k`` the
    scaled-gradient attack.  Unlike ``corrupt_message`` (one NaN leaf,
    caught by the finite firewall) the result is FINITE and plausible:
    only the robust aggregation layer can bound or reject it.  Returns
    the mutated COPY, or None if nothing mutable — shared param dicts
    are never touched in place."""
    for key, value in msg.params.items():
        if not (isinstance(value, dict) and WIRETREE_KEY in value):
            continue
        leaves = value.get("leaves") or []
        new_leaves = [
            (t if t is not None else l)
            for l, t in ((l, _scaled_leaf_twin(l, factor)) for l in leaves)
        ]
        if all(t is l for t, l in zip(new_leaves, leaves)):
            continue
        twin = Message()
        twin.params = dict(msg.params)
        twin.params[key] = {**value, "leaves": new_leaves}
        # unmutated leaves are SHARED with the original — a slab-backed
        # payload's residency travels with the twin (pin machinery)
        twin._region = msg._region
        return twin
    return None


def corrupt_message(msg: Message, rng) -> Optional[Message]:
    """Copy-on-write payload corruption: NaN-fill one float leaf of the
    first wire pytree found in the params (the model payload).  Returns
    the corrupted COPY, or None if nothing corruptible — shared param
    dicts are never mutated in place (on inproc the same objects travel
    to the receiver)."""
    for key, value in msg.params.items():
        if not (isinstance(value, dict) and WIRETREE_KEY in value):
            continue
        leaves = value.get("leaves") or []
        twins = [(i, t) for i, t in
                 ((i, _nan_leaf_twin(l)) for i, l in enumerate(leaves))
                 if t is not None]
        if not twins:
            continue
        i, twin_leaf = twins[rng.randrange(len(twins))]
        new_leaves = list(leaves)
        new_leaves[i] = twin_leaf
        twin = Message()
        twin.params = dict(msg.params)
        twin.params[key] = {**value, "leaves": new_leaves}
        twin._region = msg._region  # shared uncorrupted leaves: see above
        return twin
    return None


class _Bridge(Observer):
    """Inner backend's observer: routes deliveries through the chaos
    recv path (the wrapper itself stays a CommBackend, not an
    Observer)."""

    def __init__(self, chaos: "ChaosBackend"):
        self.chaos = chaos

    def receive_message(self, msg_type: str, msg: Message) -> None:
        self.chaos._on_inner_message(msg)


class ChaosBackend(CommBackend):
    """Fault-injecting decorator around an inner ``CommBackend``.

    Node managers attach to THIS backend; the inner transport keeps its
    protocol behavior (registration, reconnect, telemetry) untouched.
    ``trace`` records every chaos decision as
    ``(direction, msg_type, seq, actions)`` tuples — the deterministic
    delivery trace ``tests/test_faults.py`` pins across runs.
    """

    # lock-discipline contract (fedlint): sends run on the caller's
    # thread, recv faults on the inner backend's reader thread, delay
    # release on Timer threads — sequence numbers, held messages, AND
    # the decision trace are all cross-thread state
    _GUARDED_BY = {
        "_seq": "_lock",
        "_held": "_lock",
        "trace": "_lock",
    }

    def __init__(self, inner: CommBackend, plan: FaultPlan,
                 telemetry=None):
        super().__init__(inner.node_id)
        self.inner = inner
        self.plan = plan
        self.telemetry = telemetry or get_telemetry()
        self.trace: List[tuple] = []
        self._seq = {}  # (direction, msg_type) -> next sequence number
        self._held = {"send": [], "recv": []}  # [remaining, msg] entries
        self._lock = make_lock("ChaosBackend._lock")
        # wall-clock transports (tcp) delay via timers; the inproc bus
        # delays via held-message ticks + a quiesce flush
        bus = getattr(inner, "bus", None)
        self._deterministic = bus is not None
        if bus is not None and hasattr(bus, "add_quiesce_hook"):
            bus.add_quiesce_hook(self.flush_held)
        # stripe-level faults (direction="stripe" rules): install the
        # per-stripe hook on the inner transport's reassembly path —
        # a dropped stripe becomes an index gap, a corrupted one a crc
        # mismatch, and EITHER kills the whole logical frame without
        # wedging reassembly (the TcpBackend contract this exercises).
        # Transports without striping accept the hook as a no-op.
        if any(r.direction == "stripe" for r in plan.rules):
            inner.set_stripe_fault_hook(self._stripe_fault)
        inner.add_observer(_Bridge(self))

    # -- fault application --------------------------------------------------
    def _decide_traced(self, direction: str, msg_type: str, round_idx,
                       receiver=None):
        """Allocate the next per-(direction, msg_type) sequence number,
        consult the plan, and append the decision to the pinned trace —
        all in ONE critical section.  Sends (caller thread) and recv
        faults (reader thread) interleave; with the seq allocated in a
        separate lock scope from the append, the thread holding seq N
        can lose the race to the thread holding N+1 and the trace
        records them out of order — nondeterministic run-to-run, which
        is exactly what the pinned-trace contract forbids.  The plan
        decision is pure computation (rule matching + a seq-derived
        rng), so holding the lock across it is cheap and lock-leaf."""
        with self._lock:
            key = (direction, msg_type)
            seq = self._seq.get(key, 0)
            self._seq[key] = seq + 1
            acts = self.plan.decide(
                self.node_id, direction, msg_type, seq, round_idx,
                receiver=receiver,
            )
            self.trace.append(
                (direction, msg_type, seq,
                 tuple(a["action"] for a in acts) or ("deliver",))
            )
        if acts:
            # flight-recorder fault ring: only the decisions that DID
            # something (deliver-only would drown the signal)
            flight.note("faults", "decision", direction=direction,
                        msg_type=msg_type, seq=seq, round=round_idx,
                        actions=[a["action"] for a in acts])
        return seq, acts

    def _inject(self, action: str, msg_type: str) -> None:
        self.telemetry.inc("faults.injected", action=action, msg_type=msg_type)
        flight.note("faults", "injected", action=action, msg_type=msg_type)
        # one bundle per injecting process per rate-limit window: chaos
        # scenarios come back with black-box evidence from BOTH sides
        # (the injector here, the tolerance layer's observed triggers)
        flight.trigger("chaos_fault", reason=action)

    def _stripe_fault(self, msg_type: str, sid, idx, chunk):
        """Per-stripe decision on the inner transport's reassembly path
        (see ``TcpBackend.set_stripe_fault_hook``): returns ``None`` to
        swallow the stripe (the reassembler sees a gap) or the —
        possibly corrupted — chunk.  Decisions ride the same seeded
        per-(direction, msg_type) sequence stream and the same pinned
        trace as message-level faults."""
        if not self.plan.applies_to(msg_type):
            return chunk
        _seq, acts = self._decide_traced("stripe", msg_type, None)
        for a in acts:
            if a["action"] == "drop":
                self._inject("drop_stripe", msg_type)
                return None
            if a["action"] == "corrupt":
                self._inject("corrupt_stripe", msg_type)
                bad = bytearray(chunk)
                if bad:
                    bad[0] ^= 0xFF  # any bit flip: the crc32 must catch it
                chunk = bytes(bad)
        return chunk

    def _apply(self, direction: str, msg: Message,
               forward: Callable[[Message], None], receiver=None) -> None:
        msg_type = msg.type
        if not self.plan.applies_to(msg_type):
            forward(msg)
            self._tick(direction)
            return
        seq, acts = self._decide_traced(direction, msg_type,
                                        msg.get("round_idx"),
                                        receiver=receiver)
        if any(a["action"] == "drop" for a in acts):
            self._inject("drop", msg_type)
            self._tick(direction)
            return
        self._route(direction, msg, forward, acts, seq)

    def _route(self, direction: str, msg: Message,
               forward: Callable[[Message], None], acts, seq: int) -> None:
        """Execute an already-decided non-drop action list on one
        message (the post-decision half of ``_apply``, shared with the
        per-receiver multicast path).  ``seq`` seeds the corrupt rng —
        the same per-message stream the decision drew from."""
        msg_type = msg.type
        disconnect = False
        delay = None
        new_hold = None
        for a in acts:
            kind = a["action"]
            if kind == "corrupt":
                twin = corrupt_message(
                    msg, self.plan.rng_for(self.node_id, direction,
                                           msg_type, seq, salt="corrupt")
                )
                if twin is not None:
                    msg = twin
                    self._inject("corrupt", msg_type)
            elif kind in ("sign_flip", "scale_grad"):
                # Byzantine upload mutation (finite, plausible — the
                # finite firewall will NOT catch it; that is the point)
                factor = (-1.0 if kind == "sign_flip"
                          else float(a.get("attack_scale", 10.0)))
                twin = attack_message(msg, factor)
                if twin is not None:
                    msg = twin
                    self._inject(kind, msg_type)
            elif kind == "duplicate":
                self._inject("duplicate", msg_type)
                # the extra copy gets its own trace identity (copy+1,
                # fresh clone => fresh frame encoding): the two
                # deliveries are distinguishable in the merged timeline
                # and neither aliases the other's hop stamps (untraced
                # messages pass through fork_copy unchanged)
                forward(trace_ctx.fork_copy(msg))
            elif kind in ("delay", "reorder"):
                delay = a
            elif kind == "disconnect":
                disconnect = True
        if delay is not None:
            self._inject(delay["action"], msg_type)
            if self._deterministic:
                new_hold = [max(1, int(delay.get("delay_msgs", 1))),
                            msg, forward]
                with self._lock:
                    self._held[direction].append(new_hold)
            else:
                # the timer outlives the transport's delivery scope: a
                # slab-backed payload (shm lane) must be pinned until
                # the re-injection ran, or the ring could reclaim the
                # bytes under the delayed consumer (no-op off-lane)
                unpin = msg.pin_payload()

                def _deliver_late(m=msg, release=unpin):
                    try:
                        forward(m)
                    finally:
                        release()

                t = threading.Timer(
                    float(delay.get("delay_s", 0.05)), _deliver_late
                )
                t.daemon = True
                t.start()
        else:
            forward(msg)
        # age PRIOR holds only: the entry added by THIS call must survive
        # its own tick, or delay_msgs=1 (reorder) would release the
        # message immediately in its original position — a silent no-op
        self._tick(direction, skip=new_hold)
        if disconnect:
            dropper = getattr(self.inner, "drop_connection", None)
            if dropper is not None:
                self._inject("disconnect", msg_type)
                dropper()

    def _tick(self, direction: str, skip=None) -> None:
        """One message moved on this path: age held messages (except
        ``skip``, the hold this very call created), release the ones
        whose delay expired.  Release runs AFTER the current message
        forwarded, so a delay_msgs=1 hold is a true swap with the next
        message — the reorder semantics."""
        release = []
        with self._lock:
            remaining = []
            for entry in self._held[direction]:
                if entry is skip:
                    remaining.append(entry)
                    continue
                entry[0] -= 1
                (release if entry[0] <= 0 else remaining).append(entry)
            self._held[direction] = remaining
        for _, msg, forward in release:
            forward(msg)

    def flush_held(self) -> bool:
        """Release every held message (the bus ran dry: a held upload
        now arrives 'late', after whatever deadline logic already ran).
        Returns True if anything was released — the quiesce-hook
        contract."""
        with self._lock:
            held = self._held["send"] + self._held["recv"]
            self._held = {"send": [], "recv": []}
        for _, msg, forward in held:
            forward(msg)
        return bool(held)

    # -- CommBackend surface ------------------------------------------------
    def send_message(self, msg: Message) -> None:
        # attach the trace ctx BEFORE fault application (the inner
        # transport would only do it at its own send): a duplicate's
        # fork_copy needs an existing ctx to give the extra copy its
        # own identity — without this both inproc deliveries would
        # share one params dict and alias their hop stamps
        trace_ctx.ensure(msg, self.node_id)
        self._apply("send", msg, self.inner.send_message,
                    receiver=msg.receiver)

    def send_multicast(self, msg: Message, receivers) -> None:
        """Per-receiver fault application on a broadcast: the plan is
        consulted once per receiver (its own sequence number, exactly
        the stream the K-unicast loop would have drawn), so a drop rule
        for node 3 drops ONLY node 3's copy.  Clean receivers still
        ride the inner transport's native fan-out in one frame; faulted
        copies peel off onto the unicast path as per-receiver clones
        (shared payload objects — nothing re-encoded)."""
        receivers = [int(r) for r in receivers]
        if not receivers:
            return
        trace_ctx.ensure(msg, self.node_id)  # see send_message
        if not self.plan.applies_to(msg.type):
            self.inner.send_multicast(msg, receivers)
            # one tick PER RECEIVER, exactly like the K-unicast loop
            # this replaced — held-message aging must not depend on
            # whether the plan happens to cover this broadcast's type
            for _ in receivers:
                self._tick("send")
            return
        clean = []
        for r in receivers:
            seq, acts = self._decide_traced("send", msg.type,
                                            msg.get("round_idx"), receiver=r)
            if any(a["action"] == "drop" for a in acts):
                self._inject("drop", msg.type)
                self._tick("send")
                continue
            if not acts:
                clean.append(r)
                self._tick("send")
                continue
            self._route("send", msg.clone_for(r),
                        self.inner.send_message, acts, seq)
        if clean:
            self.inner.send_multicast(msg, clean)

    def _deliver(self, msg: Message) -> None:
        # inner._notify already recorded comm.recv for this frame —
        # deliver straight to OUR observers without re-counting
        for obs in list(self._observers):
            obs.receive_message(msg.type, msg)

    def _on_inner_message(self, msg: Message) -> None:
        if self.plan.straggler_sleep_s > 0.0 and not self._deterministic:
            import time

            time.sleep(self.plan.straggler_sleep_s)
        if self.plan.recv_spec is None and not any(
            r.direction == "recv" for r in self.plan.rules
        ):
            self._deliver(msg)
            return
        try:
            self._apply("recv", msg, self._deliver)
        except Exception:
            # a chaos bug must degrade to delivery, not kill the reader
            logging.exception("chaos recv path failed; delivering as-is")
            self._deliver(msg)

    def run(self) -> None:
        self.inner.run()

    def run_in_thread(self):
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self.inner.stop()

    def __getattr__(self, name):
        # transport extras (await_peers, drop_connection, bus, ...)
        # resolve against the wrapped backend; __dict__ lookup avoids
        # recursing before __init__ assigned self.inner
        inner = self.__dict__.get("inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)
