"""Deterministic fault schedules — the chaos layer's source of truth.

The reference FedML has exactly one failure behavior:
``MPI.COMM_WORLD.Abort()`` (SURVEY.md §5.2) — one dead client kills the
federation.  Real cross-device FL must treat dropouts, stragglers,
duplicated/late frames, and corrupted payloads as the COMMON case, so
this module gives the runtime something to be tolerant *of*: a seeded,
reproducible schedule of faults that ``ChaosBackend``
(``fedml_tpu/faults/chaos.py``) applies to a node's message traffic and
that ``tools/chaos_run.py`` applies at the process level (SIGKILL a
client at round r, restart the hub).

Determinism contract: a ``FaultPlan`` is a pure function of
``(seed, node, direction, msg_type, sequence_number)`` plus the explicit
``FaultRule`` schedule — NO wall clock, NO process-global RNG.  Two runs
that present the same message sequence to the same plan draw the same
faults, which is what lets ``tests/test_faults.py`` assert that a chaos
run's delivery trace is bit-reproducible and that ``observed ==
injected`` accounting closes.

Stdlib-only on purpose (mirrors ``obs/telemetry.py``): the plan is
shipped to worker subprocesses as JSON through the ``FEDML_TPU_CHAOS``
environment variable, and the hub/tools must be able to parse it without
importing jax.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
from typing import Dict, Optional, Sequence, Tuple

# actions a plan can inject on a message path.  The last two are the
# ADVERSARIAL (Byzantine) mutations, not transport faults: sign_flip
# multiplies every float leaf of a model payload by -1, scale_grad by
# ``attack_scale`` — the classic malicious-client upload mutations
# (Blanchard et al. 2017's omniscient adversary family) the robust
# aggregation layer (``fedml_tpu/robust``) defends against.  A rule set
# covering every virtual node of one muxer IS the malicious-muxer
# (Sybil) scenario: one compromised process mutating a whole cohort's
# uploads through one connection.
ACTIONS = ("drop", "delay", "duplicate", "reorder", "corrupt",
           "disconnect", "sign_flip", "scale_grad")
ATTACK_ACTIONS = ("sign_flip", "scale_grad")

# message types faultable by default: the model-bearing control plane.
# S2C_FINISH is deliberately exempt — dropping it leaves a client's
# reader thread blocked forever, which is a harness deadlock, not an
# interesting fault (a real crashed client is modeled by crash_at_round).
DEFAULT_FAULTABLE = (
    "S2C_INIT_CONFIG",
    "S2C_SYNC_MODEL",
    "C2S_SEND_MODEL",
)

ENV_VAR = "FEDML_TPU_CHAOS"


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Probabilistic fault mix, drawn independently per message.

    ``drop`` short-circuits the rest (a dropped frame can't also be
    duplicated).  ``reorder`` is delay-by-one-message; ``delay`` holds a
    message for ``delay_msgs`` subsequent messages on the deterministic
    inproc bus and for ``delay_s`` wall seconds on TCP.  ``disconnect``
    severs the node's hub socket after the send (exercising
    auto-reconnect); it is a no-op on inproc.
    """

    drop_prob: float = 0.0
    corrupt_prob: float = 0.0
    duplicate_prob: float = 0.0
    reorder_prob: float = 0.0
    delay_prob: float = 0.0
    disconnect_prob: float = 0.0
    delay_msgs: int = 1
    delay_s: float = 0.05

    def any_prob(self) -> bool:
        return any(
            p > 0.0
            for p in (
                self.drop_prob, self.corrupt_prob, self.duplicate_prob,
                self.reorder_prob, self.delay_prob, self.disconnect_prob,
            )
        )


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One scheduled fault: fires on every message matching ALL set
    fields (``None`` = wildcard).  ``round`` matches the message's
    ``round_idx`` param, so "drop client 2's upload in round 1" is
    expressible exactly.  ``receiver`` matches the message's receiver
    id — on a MULTICAST fan-out the plan is consulted once per
    receiver, so "drop node 3's copy of the sync" drops exactly that
    copy and nobody else's (``ChaosBackend.send_multicast``)."""

    action: str
    node: Optional[int] = None
    msg_type: Optional[str] = None
    round: Optional[int] = None
    direction: str = "send"
    receiver: Optional[int] = None
    delay_msgs: int = 1
    delay_s: float = 0.05
    # adversarial mutations only: the multiplier scale_grad applies to
    # every float leaf of the upload (sign_flip is a fixed -1; a
    # NEGATIVE attack_scale composes both — the "scaled sign-flip"
    # arm of the robust-aggregation evidence campaign)
    attack_scale: float = 10.0

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r} (one of {ACTIONS})"
            )
        if self.action in ATTACK_ACTIONS and self.direction == "stripe":
            raise ValueError(
                f"{self.action} is a whole-payload mutation; stripe "
                "granularity only supports drop|corrupt"
            )
        if self.direction not in ("send", "recv", "stripe"):
            raise ValueError(
                f"direction must be send|recv|stripe: {self.direction!r}"
            )
        if self.direction == "stripe" and self.action not in ("drop",
                                                              "corrupt"):
            # a stripe is a wire fragment: it can be lost or garbled,
            # but delay/duplicate/reorder/disconnect are whole-message
            # semantics — at stripe granularity they would only model
            # transports TCP cannot be (the stream is ordered)
            raise ValueError(
                f"stripe faults support drop|corrupt only: {self.action!r}"
            )
        if self.direction == "stripe" and self.round is not None:
            raise ValueError(
                "stripe rules cannot filter by round: the stripe hook "
                "runs before the inner frame (and its round_idx) is "
                "reassembled"
            )

    def matches(self, node, direction, msg_type, round_idx,
                receiver=None) -> bool:
        return (
            self.direction == direction
            and (self.node is None or self.node == node)
            and (self.msg_type is None or self.msg_type == msg_type)
            and (self.round is None or self.round == round_idx)
            and (self.receiver is None or self.receiver == receiver)
        )


class FaultPlan:
    """Seeded per-(round x node x message-type) fault schedule.

    ``send_spec``/``recv_spec`` are the probabilistic mixes applied on a
    node's send and deliver (notify) paths; ``rules`` are explicit
    scheduled faults; ``crash_at_round`` maps node id -> round at which
    the process hard-exits (``tools/chaos_run.py`` / the
    ``--crash-at-round`` client flag); ``straggler_sleep_s`` is a
    per-delivery sleep, the message-level twin of ``--train-delay``.
    ``roles`` names which process roles (client/server) wrap their
    backend when the plan arrives via the environment.
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        send_spec: Optional[FaultSpec] = None,
        recv_spec: Optional[FaultSpec] = None,
        rules: Sequence[FaultRule] = (),
        msg_types: Optional[Sequence[str]] = DEFAULT_FAULTABLE,
        roles: Sequence[str] = ("client",),
        crash_at_round: Optional[Dict[int, int]] = None,
        straggler_sleep_s: float = 0.0,
    ):
        self.seed = int(seed)
        self.send_spec = send_spec
        self.recv_spec = recv_spec
        self.rules = tuple(rules)
        self.msg_types = None if msg_types is None else tuple(msg_types)
        # a rule that NAMES a message type must fire even when that type
        # is outside the plan-level spec filter (the filter guards the
        # probabilistic mix; an explicit schedule is an explicit ask).
        # Wildcard rules (msg_type=None) stay inside msg_types — they
        # must not silently reach S2C_FINISH and deadlock shutdown.
        self._rule_types = frozenset(
            r.msg_type for r in self.rules if r.msg_type is not None
        )
        self.roles = tuple(roles)
        self.crash_at_round = dict(crash_at_round or {})
        self.straggler_sleep_s = float(straggler_sleep_s)

    # -- decision -----------------------------------------------------------
    def applies_to(self, msg_type: str) -> bool:
        return (
            self.msg_types is None
            or msg_type in self.msg_types
            or msg_type in self._rule_types
        )

    def rng_for(self, node: int, direction: str, msg_type: str,
                seq: int, salt: str = "") -> random.Random:
        """Deterministic stream per message identity.  Seeding Random
        with a STRING hashes it through sha512 (stable across processes,
        unlike ``hash()`` which is salted per interpreter)."""
        return random.Random(
            f"{self.seed}|{node}|{direction}|{msg_type}|{seq}|{salt}"
        )

    def decide(self, node: int, direction: str, msg_type: str, seq: int,
               round_idx: Optional[int] = None,
               receiver: Optional[int] = None) -> list:
        """Actions for the ``seq``-th ``msg_type`` message this node
        moves in ``direction`` (``receiver`` scopes receiver-filtered
        rules; a multicast consults the plan once per receiver).
        Returns a list of action dicts, possibly empty (= deliver
        untouched)."""
        acts = []
        for rule in self.rules:
            if rule.matches(node, direction, msg_type, round_idx, receiver):
                acts.append({
                    "action": rule.action,
                    "delay_msgs": rule.delay_msgs,
                    "delay_s": rule.delay_s,
                    "attack_scale": rule.attack_scale,
                })
        # the probabilistic mixes model whole-message faults — stripe
        # decisions come from explicit stripe rules only
        if direction == "stripe":
            spec = None
        else:
            spec = self.send_spec if direction == "send" else self.recv_spec
        # the probabilistic mix stays inside msg_types even when an
        # explicit rule admitted this type past applies_to
        spec_applies = self.msg_types is None or msg_type in self.msg_types
        if spec is not None and spec.any_prob() and spec_applies:
            rng = self.rng_for(node, direction, msg_type, seq)
            # fixed draw order = reproducible stream
            if rng.random() < spec.drop_prob:
                return [{"action": "drop"}]
            if rng.random() < spec.corrupt_prob:
                acts.append({"action": "corrupt"})
            if rng.random() < spec.duplicate_prob:
                acts.append({"action": "duplicate"})
            if rng.random() < spec.reorder_prob:
                acts.append({"action": "reorder", "delay_msgs": 1,
                             "delay_s": spec.delay_s})
            elif rng.random() < spec.delay_prob:
                acts.append({"action": "delay",
                             "delay_msgs": spec.delay_msgs,
                             "delay_s": spec.delay_s})
            if rng.random() < spec.disconnect_prob:
                acts.append({"action": "disconnect"})
        # a scheduled drop still short-circuits everything else
        if any(a["action"] == "drop" for a in acts):
            return [{"action": "drop"}]
        return acts

    # -- (de)serialization --------------------------------------------------
    def to_json(self) -> str:
        def spec_dict(s):
            return None if s is None else dataclasses.asdict(s)

        return json.dumps({
            "seed": self.seed,
            "send": spec_dict(self.send_spec),
            "recv": spec_dict(self.recv_spec),
            "rules": [dataclasses.asdict(r) for r in self.rules],
            "msg_types": None if self.msg_types is None else list(self.msg_types),
            "roles": list(self.roles),
            "crash_at_round": {str(k): v for k, v in self.crash_at_round.items()},
            "straggler_sleep_s": self.straggler_sleep_s,
        })

    @classmethod
    def from_json(cls, payload: str) -> "FaultPlan":
        d = json.loads(payload)

        def spec(v):
            return None if not v else FaultSpec(**v)

        msg_types = d.get("msg_types", DEFAULT_FAULTABLE)
        return cls(
            d.get("seed", 0),
            send_spec=spec(d.get("send")),
            recv_spec=spec(d.get("recv")),
            rules=[FaultRule(**r) for r in d.get("rules", ())],
            msg_types=None if msg_types is None else tuple(msg_types),
            roles=tuple(d.get("roles", ("client",))),
            crash_at_round={int(k): int(v)
                            for k, v in (d.get("crash_at_round") or {}).items()},
            straggler_sleep_s=d.get("straggler_sleep_s", 0.0),
        )

    @classmethod
    def from_env(cls, var: str = ENV_VAR) -> Optional["FaultPlan"]:
        """The subprocess ingestion path: ``tools/chaos_run.py`` ships
        the plan to workers as JSON in ``FEDML_TPU_CHAOS``."""
        payload = os.environ.get(var)
        return cls.from_json(payload) if payload else None
