"""Open-loop traffic models — a reproducible day of production churn.

Every scenario before this one is closed-loop: clients train when the
server says so and the only timing variance is what ``FaultPlan``
injects per message.  Production cross-device traffic is open-loop —
devices arrive on their own clock, differ 100x in speed, flap
mid-round, and follow diurnal load curves — and the async buffered
server (``--round-mode async``) exists precisely to degrade gracefully
under that arrival process.  This module is the arrival process: a
seeded ``TrafficModel`` that, for every ``(node, round)`` pair, decides
the node's upload delay, whether it is offline this round, and whether
its connection flaps, so that "a day of churn" is a deterministic chaos
scenario instead of a flake.

Determinism contract (same as ``faults/plan.py``): every decision is a
pure function of ``(seed, node, round)`` plus the explicit model
parameters — NO wall clock, NO process-global RNG.  Two runs with the
same model replay the same traffic day bit-identically, which is what
``schedule_digest`` pins in tests and what makes the FEDBUFF evidence
campaign's sync-vs-async comparison a controlled experiment (both arms
see the IDENTICAL arrival trace).

Stdlib-only on purpose: the model ships to worker subprocesses as JSON
through ``FEDML_TPU_TRAFFIC``, parsed before jax imports.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import random
from typing import Optional, Sequence, Tuple

ENV_VAR = "FEDML_TPU_TRAFFIC"

# default device speed classes: (name, population_fraction, delay_mult).
# The multiplier scales the node's drawn delay — a "slow" device takes
# 4x the base compute time of a "fast" one, the order-of-magnitude
# spread cross-device measurement studies report.
DEFAULT_SPEED_CLASSES = (
    ("fast", 0.5, 1.0),
    ("mid", 0.3, 2.0),
    ("slow", 0.2, 4.0),
)


class TrafficModel:
    """Seeded per-(node x round) arrival process.

    Per-round, per-node draws (fixed order, one rng stream per
    ``(seed, node, round)`` identity — see ``decide``):

    - base delay: ``base_delay_s`` plus exponential jitter of mean
      ``jitter_s``, scaled by the node's speed class and the diurnal
      load factor for the round;
    - straggler: with ``straggler_prob``, a Pareto(shape) draw scaled
      by ``straggler_scale_s`` and capped at ``straggler_cap_s`` is
      ADDED — the heavy tail that makes a synchronous barrier's p99
      collapse while the async server just discounts the late fold;
    - offline: with ``churn_prob`` the node skips the round entirely
      (left the population; rejoins whenever a later draw says so);
    - flap: with ``flap_prob`` the node's connection drops and redials
      mid-round (PR 13's ``rebind_connection()`` is the primitive).

    The diurnal factor ``1 + amplitude*sin(2*pi*round/period)``
    multiplies delays AND churn/flap probabilities: at the load peak
    everything is slower and flakier at once, which is what the
    ``overload_burst`` chaos scenario spikes.
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        base_delay_s: float = 0.0,
        jitter_s: float = 0.0,
        straggler_prob: float = 0.0,
        straggler_shape: float = 1.5,
        straggler_scale_s: float = 0.2,
        straggler_cap_s: float = 10.0,
        churn_prob: float = 0.0,
        flap_prob: float = 0.0,
        diurnal_amplitude: float = 0.0,
        diurnal_period_rounds: int = 24,
        speed_classes: Sequence[Tuple[str, float, float]] = DEFAULT_SPEED_CLASSES,
        roles: Sequence[str] = ("client", "muxer"),
    ):
        self.seed = int(seed)
        self.base_delay_s = float(base_delay_s)
        self.jitter_s = float(jitter_s)
        self.straggler_prob = float(straggler_prob)
        self.straggler_shape = float(straggler_shape)
        self.straggler_scale_s = float(straggler_scale_s)
        self.straggler_cap_s = float(straggler_cap_s)
        self.churn_prob = float(churn_prob)
        self.flap_prob = float(flap_prob)
        self.diurnal_amplitude = float(diurnal_amplitude)
        self.diurnal_period_rounds = int(diurnal_period_rounds)
        self.speed_classes = tuple(
            (str(n), float(f), float(m)) for n, f, m in speed_classes
        )
        self.roles = tuple(roles)
        if self.straggler_shape <= 0:
            raise ValueError(
                f"straggler_shape must be > 0: {self.straggler_shape!r}"
            )
        if self.diurnal_period_rounds <= 0:
            raise ValueError(
                f"diurnal_period_rounds must be > 0: "
                f"{self.diurnal_period_rounds!r}"
            )
        frac = sum(f for _, f, _ in self.speed_classes)
        if self.speed_classes and not 0.999 <= frac <= 1.001:
            raise ValueError(
                f"speed class fractions must sum to 1: {frac!r}"
            )

    def any_traffic(self) -> bool:
        return any(
            p > 0.0
            for p in (
                self.base_delay_s, self.jitter_s, self.straggler_prob,
                self.churn_prob, self.flap_prob,
            )
        )

    # -- decision -----------------------------------------------------------
    def rng_for(self, node: int, kind: str, seq: int) -> random.Random:
        """Deterministic stream per decision identity.  Seeding Random
        with a STRING hashes it through sha512 (stable across
        processes, unlike ``hash()`` which is salted per interpreter —
        same discipline as ``FaultPlan.rng_for``)."""
        return random.Random(f"{self.seed}|{node}|{kind}|{seq}")

    def speed_class(self, node: int) -> Tuple[str, float]:
        """A node's device class is a permanent property: one draw per
        node lifetime, not per round."""
        if not self.speed_classes:
            return ("fast", 1.0)
        u = self.rng_for(node, "class", 0).random()
        acc = 0.0
        for name, fraction, mult in self.speed_classes:
            acc += fraction
            if u < acc:
                return (name, mult)
        name, _, mult = self.speed_classes[-1]
        return (name, mult)

    def diurnal_factor(self, round_idx: int) -> float:
        if self.diurnal_amplitude <= 0.0:
            return 1.0
        phase = 2.0 * math.pi * (round_idx % self.diurnal_period_rounds) \
            / self.diurnal_period_rounds
        return max(0.0, 1.0 + self.diurnal_amplitude * math.sin(phase))

    def decide(self, node: int, round_idx: int) -> dict:
        """The arrival decision for ``node`` in ``round_idx``:
        ``{"delay_s", "offline", "rebind", "class", "straggler"}``.
        Fixed draw order on one rng stream = reproducible trace."""
        rng = self.rng_for(node, "round", round_idx)
        cls_name, cls_mult = self.speed_class(node)
        load = self.diurnal_factor(round_idx)
        # draw order: offline, flap, jitter, straggler — ALWAYS all
        # four, so a parameter change to one knob cannot shift the
        # stream another knob reads (replay stability across configs
        # with the same non-zero knobs)
        offline = rng.random() < min(1.0, self.churn_prob * load)
        rebind = rng.random() < min(1.0, self.flap_prob * load)
        delay = self.base_delay_s
        if self.jitter_s > 0.0:
            delay += rng.expovariate(1.0 / self.jitter_s)
        else:
            rng.random()
        straggler = False
        if rng.random() < self.straggler_prob:
            straggler = True
            # Pareto: heavy-tailed — the p99-destroying draw
            tail = self.straggler_scale_s * rng.paretovariate(
                self.straggler_shape)
            delay += min(tail, self.straggler_cap_s)
        delay *= cls_mult * load
        return {
            "delay_s": delay,
            "offline": offline,
            "rebind": rebind,
            "class": cls_name,
            "straggler": straggler,
        }

    def schedule_digest(self, nodes: Sequence[int], rounds: int) -> str:
        """sha256 over the full decision trace for ``nodes`` x
        ``rounds`` — the replay-determinism probe tests and the
        traffic campaign pin (same seed => same digest, byte-for-byte)."""
        h = hashlib.sha256()
        for r in range(rounds):
            for node in sorted(nodes):
                d = self.decide(node, r)
                h.update(
                    f"{node}|{r}|{d['class']}|{d['offline']}|{d['rebind']}|"
                    f"{d['straggler']}|{d['delay_s']:.12e}".encode()
                )
        return h.hexdigest()

    # -- (de)serialization --------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "base_delay_s": self.base_delay_s,
            "jitter_s": self.jitter_s,
            "straggler_prob": self.straggler_prob,
            "straggler_shape": self.straggler_shape,
            "straggler_scale_s": self.straggler_scale_s,
            "straggler_cap_s": self.straggler_cap_s,
            "churn_prob": self.churn_prob,
            "flap_prob": self.flap_prob,
            "diurnal_amplitude": self.diurnal_amplitude,
            "diurnal_period_rounds": self.diurnal_period_rounds,
            "speed_classes": [list(c) for c in self.speed_classes],
            "roles": list(self.roles),
        })

    @classmethod
    def from_json(cls, payload: str) -> "TrafficModel":
        d = json.loads(payload)
        return cls(
            d.get("seed", 0),
            base_delay_s=d.get("base_delay_s", 0.0),
            jitter_s=d.get("jitter_s", 0.0),
            straggler_prob=d.get("straggler_prob", 0.0),
            straggler_shape=d.get("straggler_shape", 1.5),
            straggler_scale_s=d.get("straggler_scale_s", 0.2),
            straggler_cap_s=d.get("straggler_cap_s", 10.0),
            churn_prob=d.get("churn_prob", 0.0),
            flap_prob=d.get("flap_prob", 0.0),
            diurnal_amplitude=d.get("diurnal_amplitude", 0.0),
            diurnal_period_rounds=d.get("diurnal_period_rounds", 24),
            speed_classes=[
                tuple(c) for c in d.get("speed_classes",
                                        DEFAULT_SPEED_CLASSES)
            ],
            roles=tuple(d.get("roles", ("client", "muxer"))),
        )

    @classmethod
    def from_env(cls, var: str = ENV_VAR) -> Optional["TrafficModel"]:
        """Subprocess ingestion: ``launch()`` ships the model to
        workers as JSON in ``FEDML_TPU_TRAFFIC``."""
        payload = os.environ.get(var)
        return cls.from_json(payload) if payload else None
