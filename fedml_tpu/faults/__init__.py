"""Deterministic fault injection — the chaos layer (PAPER.md robustness
story; reference FedML's only failure path is ``MPI.COMM_WORLD.Abort()``).

- ``plan``  — seeded ``FaultPlan``/``FaultSpec``/``FaultRule`` schedules
  (stdlib-only; shipped to subprocesses via ``FEDML_TPU_CHAOS``);
- ``chaos`` — ``ChaosBackend``, the transport wrapper applying a plan on
  send/notify paths of inproc and tcp.

Process-level injection (SIGKILL at round r, hub restart) lives with the
process orchestration: ``experiments/distributed_fedavg.py`` and
``tools/chaos_run.py``.
"""

from fedml_tpu.faults.plan import (
    ACTIONS,
    ATTACK_ACTIONS,
    DEFAULT_FAULTABLE,
    ENV_VAR,
    FaultPlan,
    FaultRule,
    FaultSpec,
)
from fedml_tpu.faults.chaos import ChaosBackend, attack_message, corrupt_message

__all__ = [
    "ACTIONS",
    "ATTACK_ACTIONS",
    "DEFAULT_FAULTABLE",
    "ENV_VAR",
    "ChaosBackend",
    "FaultPlan",
    "FaultRule",
    "FaultSpec",
    "attack_message",
    "corrupt_message",
]
