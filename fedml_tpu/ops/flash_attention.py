"""Pallas TPU flash-attention kernel.

The hot op of the transformer family (``models/transformer.py``):
softmax(QKᵀ/√d)V computed blockwise in VMEM with online-softmax
accumulation — no [L, L] score matrix ever hits HBM.  This is the
single-device attention path; the ring path
(``parallel/ring_attention.py``) keeps its own lax blockwise inner loop
because merging shards needs raw (m, l, o) online-softmax partials and
global position offsets, which this kernel does not expose.

Layout per pallas core: one (batch·head) slice [L, D]; the caller vmaps
over batch and heads.  Grid = (q_blocks, kv_blocks) with the kv axis
iterated innermost ("arbitrary" semantics) so the VMEM scratch (m, l,
acc) carries across kv steps of one q block — the standard TPU flash
pattern from the pallas guide (grid/scratch/`pl.when` sections).

``flash_attention(..., interpret=True)`` runs the same kernel on CPU
(tests); ``blockwise_attention`` remains the lax fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                  acc_scr, *, sm_scale: float, causal: bool, block_q: int,
                  block_k: int):
    qi = pl.program_id(0)
    ki = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a KV block strictly above the diagonal contributes nothing;
    # skip its matmuls entirely (half the work for long sequences)
    visible = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(visible)
    def _compute():
        q = q_ref[:]            # [BQ, D]
        k = k_ref[:]            # [BK, D]
        v = v_ref[:]            # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale            # [BQ, BK]

        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            s = jnp.where(kpos <= qpos, s, NEG_INF)

        m_prev = m_scr[:, :1]                       # [BQ, 1]
        l_prev = l_scr[:, :1]
        m_cur = s.max(axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                      # [BQ, BK]
        alpha = jnp.exp(m_prev - m_new)             # [BQ, 1]
        l_new = l_prev * alpha + p.sum(axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[:] = (acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)).astype(
            o_ref.dtype
        )
        # log-sum-exp per query row — the softmax statistic the custom
        # backward needs to recompute p without re-running the online max.
        # Single-lane output: the m/l scratch is lane-replicated, but
        # writing all 128 lanes to HBM costs 512B/row of pure waste
        # (ADVICE r2) — Mosaic takes a (block_q, 1) block fine.
        lse_ref[:] = m_scr[:, :1] + jnp.log(jnp.maximum(l_scr[:, :1], 1e-30))


def _flash_single(q, k, v, *, causal, block_q, block_k, interpret):
    """Flash attention for one [L, D] head slice."""
    Lq, D = q.shape
    Lk = k.shape[0]
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    if Lq % block_q or Lk % block_k:
        raise ValueError(
            f"sequence ({Lq},{Lk}) must divide blocks ({block_q},{block_k})"
        )
    grid = (Lq // block_q, Lk // block_k)
    sm_scale = 1.0 / (D ** 0.5)

    scratch_shapes = [
        pltpu.VMEM((block_q, 128), jnp.float32),   # running max m
        pltpu.VMEM((block_q, 128), jnp.float32),   # running sum l
        pltpu.VMEM((block_q, D), jnp.float32),     # output accumulator
    ]

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, D), lambda qi, ki: (qi, 0)),
            pl.BlockSpec((block_k, D), lambda qi, ki: (ki, 0)),
            pl.BlockSpec((block_k, D), lambda qi, ki: (ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_q, D), lambda qi, ki: (qi, 0)),
            pl.BlockSpec((block_q, 1), lambda qi, ki: (qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Lq, D), q.dtype),
            jax.ShapeDtypeStruct((Lq, 1), jnp.float32),
        ],
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        **kwargs,
    )(q, k, v)
    return out, lse[:, 0]


def _flash_bwd_single(q, k, v, o, lse, do, dlse, *, causal, block_k,
                      sm_scale):
    """Exact flash backward for one [L, D] head slice in KV blocks —
    O(L) memory (no [L, L] residuals; p is recomputed per block
    pair from the forward's saved log-sum-exp).  Standard formulas:

        p_ij  = exp(s_ij - lse_i)
        dv_j  = pᵀ dO           dp_ij = dO_i · v_j
        ds_ij = p_ij (dp_ij - D_i),   D_i = dO_i · O_i
        dq_i  = scale · Σ_j ds_ij k_j
        dk_j  = scale · Σ_i ds_ij q_i

    Causal blocks above the diagonal DO run their (zero-producing)
    matmuls here, unlike the forward kernel's block skip — a version
    that bounded a fori_loop to each q block's visible KV prefix was
    tried and measured ~6x SLOWER (313 ms vs 52 ms at L=4096): the
    per-iteration dynamic_update_slice of the full [Lk, D] dk/dv
    accumulators inside a while carry costs far more than the skipped
    matmuls save.  The straight KV scan below emits dk/dv as stacked
    scan outputs instead, which XLA handles well.
    """
    L, Dm = q.shape
    Lk = k.shape[0]
    bs = min(block_k, Lk)
    n_blocks = Lk // bs
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    Drow = (dof * o.astype(jnp.float32)).sum(-1)        # [L]
    qpos = jnp.arange(L)

    def body(dq, j):
        kb = jax.lax.dynamic_slice_in_dim(k, j * bs, bs).astype(jnp.float32)
        vb = jax.lax.dynamic_slice_in_dim(v, j * bs, bs).astype(jnp.float32)
        s = (qf @ kb.T) * sm_scale                      # [L, bs]
        if causal:
            kpos = j * bs + jnp.arange(bs)
            s = jnp.where(kpos[None, :] <= qpos[:, None], s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                   # [L, bs]
        dv_j = p.T @ dof                                # [bs, D]
        dp = dof @ vb.T                                 # [L, bs]
        # dlse: the lse OUTPUT's cotangent (nonzero when the caller uses
        # lse, e.g. the ring merge weights) — d lse_i / d s_ij = p_ij
        ds = p * (dp - Drow[:, None] + dlse[:, None])
        dq = dq + (ds @ kb) * sm_scale
        dk_j = (ds.T @ qf) * sm_scale                   # [bs, D]
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros((L, Dm), jnp.float32)
    dq, (dks, dvs) = jax.lax.scan(body, dq0, jnp.arange(n_blocks))
    dk = dks.reshape(Lk, Dm)
    dv = dvs.reshape(Lk, Dm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_heads_impl(q, k, v, causal, block_q, block_k, interpret):
    run = functools.partial(
        _flash_single, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    # vmap over a LEADING head axis: pallas prepends the batch dim to the
    # grid, keeping each block's trailing dims tile-aligned ([L, D])
    qh, kh, vh = (t.swapaxes(0, 1) for t in (q, k, v))
    out, lse = jax.vmap(run)(qh, kh, vh)
    return out.swapaxes(0, 1), lse  # out [L, H, D], lse [H, L]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, causal, block_q, block_k, interpret):
    """Differentiable (o, lse) pair — the ring path consumes BOTH (the
    merge weights are lse functions), so the backward carries the lse
    cotangent too (one extra ``p * dlse`` term in ds)."""
    return _flash_heads_impl(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_heads_impl(q, k, v, causal, block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    del block_q, interpret
    q, k, v, out, lse = res
    do, dlse = g
    sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    run = functools.partial(
        _flash_bwd_single, causal=causal, block_k=block_k,
        sm_scale=sm_scale,
    )
    swap = lambda t: t.swapaxes(0, 1)  # noqa: E731
    dq, dk, dv = jax.vmap(run)(
        swap(q), swap(k), swap(v), swap(out), lse, swap(do),
        dlse.astype(jnp.float32),
    )
    return swap(dq), swap(dk), swap(dv)


flash_attention_with_lse.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention over [L, H, D] (no batch; vmap for batches).

    Drop-in for ``parallel.ring_attention.blockwise_attention`` where
    shapes divide the block sizes.  DIFFERENTIABLE: the custom backward
    recomputes p per KV block from the kernel's saved log-sum-exp — an
    exact O(L)-memory gradient, so the training path never materializes
    [L, L] (tests/test_flash_attention.py pins grads against dense
    attention).
    """
    out, _ = flash_attention_with_lse(
        q, k, v, causal, block_q, block_k, interpret
    )
    return out


def flash_attn_fn(block_q: int = 128, block_k: int = 128,
                  interpret: bool = False):
    """Adapter matching the TransformerLM ``attn_fn`` signature."""

    def attn(q, k, v, causal):
        return flash_attention(q, k, v, causal=causal, block_q=block_q,
                               block_k=block_k, interpret=interpret)

    return attn


def pick_block(length: int, preferred: int = 1024) -> int:
    """Largest power-of-two block <= preferred that divides ``length``
    (0 if none >= 128 divides it — caller should fall back to the lax
    blockwise path).

    Measured on one v5e chip (bf16, B=4 H=8 D=64, dispatch amortized by
    a fused 50-iteration scan): 1024-blocks run 4.4/5.0/9.7 ms per call
    at L=1k/4k/8k vs 4.4/9.0/23.1 ms for the XLA blockwise scan — parity
    at 1k, 2.4x at 8k.  SMALL blocks are actively bad on TPU (256-blocks
    measured 4-8x slower than 1024): the (q, kv) grid then has too many
    tiny kernel invocations for the scalar core to schedule.
    """
    b = preferred
    while b >= 128:
        if length % b == 0:
            return b
        b //= 2
    return 0
