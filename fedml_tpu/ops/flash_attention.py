"""Pallas TPU flash-attention kernel.

The hot op of the transformer family (``models/transformer.py``):
softmax(QKᵀ/√d)V computed blockwise in VMEM with online-softmax
accumulation — no [L, L] score matrix ever hits HBM.  This is the
single-device attention path; the ring path
(``parallel/ring_attention.py``) keeps its own lax blockwise inner loop
because merging shards needs raw (m, l, o) online-softmax partials and
global position offsets, which this kernel does not expose.

Layout per pallas core: one (batch·head) slice [L, D]; the caller vmaps
over batch and heads.  Grid = (q_blocks, kv_blocks) with the kv axis
iterated innermost ("arbitrary" semantics) so the VMEM scratch (m, l,
acc) carries across kv steps of one q block — the standard TPU flash
pattern from the pallas guide (grid/scratch/`pl.when` sections).

``flash_attention(..., interpret=True)`` runs the same kernel on CPU
(tests); ``blockwise_attention`` remains the lax fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                  *, sm_scale: float, causal: bool, block_q: int,
                  block_k: int):
    qi = pl.program_id(0)
    ki = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a KV block strictly above the diagonal contributes nothing;
    # skip its matmuls entirely (half the work for long sequences)
    visible = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(visible)
    def _compute():
        q = q_ref[:]            # [BQ, D]
        k = k_ref[:]            # [BK, D]
        v = v_ref[:]            # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale            # [BQ, BK]

        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            s = jnp.where(kpos <= qpos, s, NEG_INF)

        m_prev = m_scr[:, :1]                       # [BQ, 1]
        l_prev = l_scr[:, :1]
        m_cur = s.max(axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                      # [BQ, BK]
        alpha = jnp.exp(m_prev - m_new)             # [BQ, 1]
        l_new = l_prev * alpha + p.sum(axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[:] = (acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)).astype(
            o_ref.dtype
        )


def _flash_single(q, k, v, *, causal, block_q, block_k, interpret):
    """Flash attention for one [L, D] head slice."""
    Lq, D = q.shape
    Lk = k.shape[0]
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    if Lq % block_q or Lk % block_k:
        raise ValueError(
            f"sequence ({Lq},{Lk}) must divide blocks ({block_q},{block_k})"
        )
    grid = (Lq // block_q, Lk // block_k)
    sm_scale = 1.0 / (D ** 0.5)

    scratch_shapes = [
        pltpu.VMEM((block_q, 128), jnp.float32),   # running max m
        pltpu.VMEM((block_q, 128), jnp.float32),   # running sum l
        pltpu.VMEM((block_q, D), jnp.float32),     # output accumulator
    ]

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, D), lambda qi, ki: (qi, 0)),
            pl.BlockSpec((block_k, D), lambda qi, ki: (ki, 0)),
            pl.BlockSpec((block_k, D), lambda qi, ki: (ki, 0)),
        ],
        out_specs=pl.BlockSpec((block_q, D), lambda qi, ki: (qi, 0)),
        out_shape=jax.ShapeDtypeStruct((Lq, D), q.dtype),
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        **kwargs,
    )(q, k, v)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention over [L, H, D] (no batch; vmap for batches).

    Drop-in for ``parallel.ring_attention.blockwise_attention`` where
    shapes divide the block sizes.
    """
    run = functools.partial(
        _flash_single, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    # vmap over a LEADING head axis: pallas prepends the batch dim to the
    # grid, keeping each block's trailing dims tile-aligned ([L, D])
    qh, kh, vh = (t.swapaxes(0, 1) for t in (q, k, v))
    out = jax.vmap(run)(qh, kh, vh)
    return out.swapaxes(0, 1)


def flash_attn_fn(block_q: int = 128, block_k: int = 128,
                  interpret: bool = False):
    """Adapter matching the TransformerLM ``attn_fn`` signature."""

    def attn(q, k, v, causal):
        return flash_attention(q, k, v, causal=causal, block_q=block_q,
                               block_k=block_k, interpret=interpret)

    return attn
