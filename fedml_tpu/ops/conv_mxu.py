"""Pallas TPU implicit-GEMM conv kernel for narrow-channel 3×3 stages.

The north-star workload (ResNet-56, ``models/resnet.py``) runs its 3×3
convs at channel widths 16/32/64: a 128-lane MXU executes them at
12.5/25/50% output-lane occupancy, and round 5 measured every classic
dense retiling (s2d2/s2d3/pad32) as a net loss — any transform that
widens lanes also inflates K or shrinks M (PROFILE.md round-5 table).
This kernel attacks the one axis those transforms could not reach: it
formulates the conv as an **implicit GEMM**

    patches(x)  : [M = N·Ho·Wo, K = 9·Cin]   (gathered in VMEM)
    kernel      : [K, Cout]
    out         : [M, Cout] = patches @ kernel

so the contraction depth grows 9× (Cin=16 → K=144: two K-tiles instead
of one eighth of one) and the huge M axis — which XLA's conv tiling
fragments across the spatial dims — is packed densely into MXU rows.
The lane-starved Cout axis is untouched (that is the structural part of
the ceiling); the bet is purely on M/K packing efficiency.

Fusion: an optional per-channel affine + ReLU epilogue
(``mul``/``add``/``relu``) and optional per-channel moment outputs
(sum, sum-of-squares of the emitted activations).  The moments path is
what the train loop uses: BatchNorm's batch statistics come out of the
conv kernel itself instead of a separate full-tensor ``reduce_sum``
re-read of the activations from HBM — the 7.2% ``reduce_sum`` share in
PROFILE.md's round-2 accounting is partly that re-read.

Differentiability: ``conv3x3`` / ``conv3x3_moments`` carry a
``jax.custom_vjp``.  The backward is the first-cut XLA-conv form the
issue allows — dgrad/wgrad are emitted by XLA's own conv-transpose
rules (which lower to GEMMs on TPU) via a ``jax.vjp`` whose unused
primal is dead-code-eliminated under jit; the moments cotangents fold
into the output cotangent analytically (d sum → broadcast, d sumsq →
2·y) before the transpose convs run.  A Pallas dgrad/wgrad pair is the
follow-up once the forward has a measured win.

CPU/testing: ``interpret=None`` auto-selects Pallas interpret mode off
the TPU backend (the ``ops/flash_attention.py`` precedent), so the full
parity suite (``tests/test_conv_mxu.py``) runs in tier-1 on CPU and the
faked-mesh tests keep passing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_DN = ("NHWC", "HWIO", "NHWC")

# target GEMM-row count per kernel invocation: at least 4 MXU row-tiles
# of 128 so the systolic array's fill/drain amortizes; stage 3's 8×8
# maps pack 8 images per program to reach it
_TARGET_M = 512


def _pick_block_n(n: int, out_hw: int) -> int:
    """Images per kernel invocation: the largest divisor of ``n`` whose
    patch matrix stays modest while M = block_n·Ho·Wo reaches
    ``_TARGET_M`` (single-image for the big stage-1 maps)."""
    bn = 1
    while bn * out_hw < _TARGET_M and (n % (bn * 2) == 0):
        bn *= 2
    return bn


def _conv_kernel(x_ref, w_ref, mul_ref, add_ref, *out_refs, stride: int,
                 relu: bool, moments: bool):
    """One grid step: gather 9 shifted taps of a padded image block into
    the [M, 9·Cin] patch scratch, run ONE MXU matmul against the
    [9·Cin, Cout] kernel, apply the affine(+ReLU) epilogue, and emit the
    block's per-channel moment partials.

    The tap gather is a strided ``lax.slice`` of the VMEM-resident
    padded block — stride 1 for the dense stages; stride 2 reads the
    even-center windows of the baseline's explicit-padding convention
    (out[i] ← padded rows 2i..2i+2), so the stride-2 stage transitions
    compute the identical function."""
    if moments:
        o_ref, sum_ref, sq_ref, patch = out_refs
    else:
        o_ref, patch = out_refs
    bn, ho, wo, co = o_ref.shape
    ci = x_ref.shape[-1]
    xb = x_ref[:]                                   # (bn, H+2, W+2, Ci)
    for t in range(9):
        ty, tx = divmod(t, 3)
        tap = jax.lax.slice(
            xb,
            (0, ty, tx, 0),
            (bn, ty + stride * (ho - 1) + 1, tx + stride * (wo - 1) + 1, ci),
            (1, stride, stride, 1),
        )                                           # (bn, Ho, Wo, Ci)
        patch[:, t * ci:(t + 1) * ci] = tap.reshape(bn * ho * wo, ci)
    acc = jax.lax.dot_general(
        patch[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                               # (M, Co) fp32
    y = acc * mul_ref[:] + add_ref[:]
    if relu:
        y = jnp.maximum(y, 0.0)
    yc = y.astype(o_ref.dtype)
    o_ref[:] = yc.reshape(bn, ho, wo, co)
    if moments:
        # moments of the EMITTED activations (post-cast, post-epilogue):
        # exactly the values train-mode BatchNorm reduces over, so the
        # fp32 stats match the baseline's astype(float32) reduction
        yf = yc.astype(jnp.float32)
        sum_ref[:] = jnp.sum(yf, axis=0, keepdims=True)
        sq_ref[:] = jnp.sum(yf * yf, axis=0, keepdims=True)


def conv3x3_mxu(x, w, *, stride: int = 1, mul=None, add=None,
                relu: bool = False, moments: bool = False,
                block_n: int | None = None, interpret: bool | None = None):
    """Raw (non-differentiable) implicit-GEMM 3×3 SAME conv.

    x [N, H, W, Cin] · w [3, 3, Cin, Cout], explicit padding 1 each
    side, stride ∈ {1, 2} — the baseline ``_XConv`` convention
    (even-center windows at stride 2).  ``mul``/``add`` [Cout] fuse a
    per-channel fp32 affine into the epilogue (BN-affine in eval form),
    ``relu`` fuses the activation, ``moments=True`` additionally
    returns per-channel (sum, sumsq) of the emitted output.

    Returns ``out`` or ``(out, sum, sumsq)``.
    """
    n, h, wdim, ci = x.shape
    if w.shape[:2] != (3, 3) or w.shape[2] != ci:
        raise ValueError(f"need a [3,3,{ci},Co] kernel, got {w.shape}")
    if stride not in (1, 2):
        raise ValueError(f"stride must be 1 or 2, got {stride}")
    if h % stride or wdim % stride:
        raise ValueError(f"spatial dims {(h, wdim)} must divide stride")
    co = w.shape[3]
    ho, wo = h // stride, wdim // stride
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_n is None:
        block_n = _pick_block_n(n, ho * wo)
    if n % block_n:
        raise ValueError(f"batch {n} must divide block_n {block_n}")
    m = block_n * ho * wo

    x_pad = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    # (3, 3, Ci, Co) → (9·Ci, Co): row t·Ci+c is tap (ty, tx)=divmod(t,3),
    # input channel c — the exact column order the tap gather writes
    w2 = w.astype(x.dtype).reshape(9 * ci, co)
    mul_arr = (jnp.ones((1, co), jnp.float32) if mul is None
               else jnp.asarray(mul, jnp.float32).reshape(1, co))
    add_arr = (jnp.zeros((1, co), jnp.float32) if add is None
               else jnp.asarray(add, jnp.float32).reshape(1, co))

    grid = (n // block_n,)
    kernel = functools.partial(
        _conv_kernel, stride=stride, relu=relu, moments=moments
    )
    out_shape = [jax.ShapeDtypeStruct((n, ho, wo, co), x.dtype)]
    out_specs = [pl.BlockSpec((block_n, ho, wo, co),
                              lambda g: (g, 0, 0, 0))]
    if moments:
        out_shape += [jax.ShapeDtypeStruct((grid[0], co), jnp.float32)] * 2
        out_specs += [pl.BlockSpec((1, co), lambda g: (g, 0))] * 2
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel",),
        )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, h + 2, wdim + 2, ci),
                         lambda g: (g, 0, 0, 0)),
            pl.BlockSpec((9 * ci, co), lambda g: (0, 0)),
            pl.BlockSpec((1, co), lambda g: (0, 0)),
            pl.BlockSpec((1, co), lambda g: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((m, 9 * ci), x.dtype)],
        interpret=interpret,
        **kwargs,
    )(x_pad, w2, mul_arr, add_arr)
    if moments:
        y, s, sq = out
        return y, s.sum(axis=0), sq.sum(axis=0)
    return out[0]


def _xla_conv3x3(x, w, stride: int):
    """The XLA conv computing the identical function — the parity
    reference AND the source of the first-cut backward (its transpose
    rules emit the dgrad/wgrad GEMMs)."""
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(1, 1), (1, 1)], dimension_numbers=_DN
    )


def _conv_vjp(x, w, stride, dy):
    """dgrad/wgrad via XLA's conv-transpose rules.  The vjp's unused
    primal conv is dead code under jit, so this costs exactly the two
    transpose convs."""
    _, vjp = jax.vjp(lambda xx, ww: _xla_conv3x3(xx, ww, stride), x, w)
    return vjp(dy)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def conv3x3(x, w, stride: int = 1, block_n: int | None = None,
            interpret: bool | None = None):
    """Differentiable implicit-GEMM 3×3 conv (Pallas forward, XLA-GEMM
    backward).  Drop-in for the baseline ``lax.conv_general_dilated``
    call in ``models/resnet_tpu._XConv`` (explicit padding 1, NHWC)."""
    return conv3x3_mxu(x, w, stride=stride, block_n=block_n,
                       interpret=interpret)


def _conv3x3_fwd(x, w, stride, block_n, interpret):
    return conv3x3(x, w, stride, block_n, interpret), (x, w)


def _conv3x3_bwd(stride, block_n, interpret, res, dy):
    del block_n, interpret
    x, w = res
    return _conv_vjp(x, w, stride, dy)


conv3x3.defvjp(_conv3x3_fwd, _conv3x3_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def conv3x3_moments(x, w, stride: int = 1, block_n: int | None = None,
                    interpret: bool | None = None):
    """``conv3x3`` fused with per-channel moment emission: returns
    ``(out, sum, sumsq)`` where sum/sumsq reduce the emitted output
    over every (image, row, col) position in fp32 — the quantities
    train-mode BatchNorm needs, produced without a second full-tensor
    HBM read.  Differentiable in all three outputs (the BN mean/var
    gradient flows through the moment cotangents)."""
    return conv3x3_mxu(x, w, stride=stride, moments=True, block_n=block_n,
                       interpret=interpret)


def _conv3x3_moments_fwd(x, w, stride, block_n, interpret):
    y, s, sq = conv3x3_moments(x, w, stride, block_n, interpret)
    return (y, s, sq), (x, w, y)


def _conv3x3_moments_bwd(stride, block_n, interpret, res, g):
    del block_n, interpret
    x, w, y = res
    dy, ds, dsq = g
    # fold the moment cotangents into the output cotangent analytically:
    #   sum_c  = Σ_m y[m, c]   → d y += ds[c]  (broadcast)
    #   sumsq_c = Σ_m y[m, c]² → d y += 2·y·dsq[c]
    # accumulated in fp32 then cast at the same point the baseline's
    # astype(float32) BN-stat chain casts its cotangent
    dy_eff = (dy.astype(jnp.float32)
              + ds[None, None, None, :]
              + 2.0 * y.astype(jnp.float32) * dsq[None, None, None, :]
              ).astype(y.dtype)
    return _conv_vjp(x, w, stride, dy_eff)


conv3x3_moments.defvjp(_conv3x3_moments_fwd, _conv3x3_moments_bwd)
