"""Update-compression subsystem: deterministic codecs + error feedback.

See ``codecs.py`` for the codec registry (qsgd8/qsgd4/topk/bf16) and
``error_feedback.py`` for the host-side EF recurrence.  Wire format
integration lives in ``fedml_tpu/comm/message.py`` (wiretree v2);
compiled-engine integration in ``fedml_tpu/algorithms/fedavg.py``
(``make_round_fn(codec=..., error_feedback=...)``).
"""

from fedml_tpu.compress.codecs import (
    BCAST_STREAM,
    COMPRESS_STREAM,
    Bf16Codec,
    IdentityCodec,
    LeafCodec,
    QsgdCodec,
    TopKCodec,
    decode_tree,
    encode_tree,
    encoded_nbytes,
    get_codec,
    roundtrip_tree,
    wire_decode_tree,
    wire_encode_tree,
    wire_tree_digest,
)
from fedml_tpu.compress.error_feedback import ErrorFeedback
from fedml_tpu.compress.sharded import (
    sharded_entry_nbytes,
    sharded_wire_digest,
    wire_decode_tree_sharded,
    wire_encode_tree_sharded,
)

__all__ = [
    "BCAST_STREAM",
    "COMPRESS_STREAM",
    "Bf16Codec",
    "ErrorFeedback",
    "IdentityCodec",
    "LeafCodec",
    "QsgdCodec",
    "TopKCodec",
    "decode_tree",
    "encode_tree",
    "encoded_nbytes",
    "get_codec",
    "roundtrip_tree",
    "sharded_entry_nbytes",
    "sharded_wire_digest",
    "wire_decode_tree",
    "wire_decode_tree_sharded",
    "wire_encode_tree",
    "wire_encode_tree_sharded",
    "wire_tree_digest",
]
