"""Error-feedback residual state (host side).

Lossy codecs bias the aggregate: what the server reconstructs is
``decode(encode(update))``, and the per-round quantization/sparsification
error would otherwise be lost forever (top-k without EF simply never
ships small coordinates).  Error feedback (Seide et al. 2014; Karimireddy
et al. 2019) keeps the error: the client carries

    residual_{t+1} = (update_t + residual_t) - decode(encode(update_t + residual_t))

and folds it into the NEXT round's update, so every coordinate is
eventually transmitted and convergence matches the uncompressed run to
first order.

This class is the host-side form used by the cross-device client
(``fedavg_cross_device.FedAvgClientManager``); the compiled engine
threads the same recurrence through ``ServerState.residuals`` on device
(``fedml_tpu.algorithms.fedavg.make_round_fn``).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

PyTree = Any


class ErrorFeedback:
    """Residual accumulator for ONE participant's update stream."""

    def __init__(self):
        self._residual: Optional[PyTree] = None

    def fold_in(self, delta: PyTree) -> PyTree:
        """``delta + residual`` (fp32); identity on the first round."""
        import jax

        if self._residual is None:
            return jax.tree_util.tree_map(
                lambda d: np.asarray(d, np.float32), delta
            )
        return jax.tree_util.tree_map(
            lambda d, r: np.asarray(d, np.float32) + r,
            delta, self._residual,
        )

    def absorb(self, folded: PyTree, decoded: PyTree) -> None:
        """Store ``folded - decoded`` — the error the wire dropped."""
        import jax

        self._residual = jax.tree_util.tree_map(
            lambda f, d: np.asarray(f, np.float32)
            - np.asarray(d, np.float32),
            folded, decoded,
        )

    def reset(self) -> None:
        self._residual = None
