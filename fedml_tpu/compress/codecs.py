"""Deterministic update codecs: quantization, sparsification, bf16.

Cross-device FL's production bottleneck at the reference's scale
(342k-client StackOverflow row) is uplink BYTES, not FLOPs — and the
wire until now shipped every update as float32 inflated 4/3x by base64
(``comm/message.py`` v1).  This module provides the lossy half of the
fix: three composable update codecs from the communication-efficiency
lineage the paper sits in (Konečný et al. 2016 structured updates;
QSGD, Alistarh et al. 2017 stochastic quantization):

- ``qsgd8`` / ``qsgd4`` (aliases ``int8`` / ``int4``) — QSGD-style
  stochastic uniform quantization with per-chunk max-abs scales.
  Unbiased per element (``E[decode(encode(x))] == x``), worst-case
  per-element error ``chunk_max / levels``.
- ``topk<rate>`` (e.g. ``topk0.01``) — magnitude top-k sparsification:
  indices + exact values, everything else zero.  Biased; REQUIRES
  error feedback to converge.
- ``bf16`` — bfloat16 cast (deterministic, ~2x, no rng).
- ``none`` — identity (fp32 passthrough; the control arm).

Determinism contract (the PR-3 chaos-trace reproducibility contract
extended to payload bytes): every stochastic draw derives from the
caller's ``jax.random`` key via ``fold_in`` — no process RNG, no wall
clock — so the same (seed, round, slot) stream produces BIT-identical
encoded buffers in any process (pinned by
``tests/test_compress.py::test_encode_bits_identical_across_processes``).

Two forms per codec, sharing ONE implementation:

- on-device: ``encode(x, key)`` / ``decode(enc, shape, dtype)`` are
  pure jnp functions, jit/vmap-compatible (static shapes — chunk
  counts and top-k widths derive from leaf shapes), usable inside the
  compiled round engine (``fedml_tpu.algorithms.fedavg.make_round_fn``);
- wire: ``wire_encode_tree`` / ``wire_decode_tree`` run the same
  functions and materialize numpy arrays for the wiretree-v2 frame
  codec (``comm/message.py``), plus int4 nibble-packing that only
  exists on the wire.

Error feedback (EF): ``residual = update - decode(encode(update))``
carried by the CALLER across rounds and folded into the next update
before encoding — the standard fix for the bias of lossy codecs.  The
engine threads it through ``ServerState.residuals``; the cross-device
client keeps a host-side copy (``fedavg_cross_device``).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from fedml_tpu.comm.message import NDARRAY_KEY

PyTree = Any

# sub-stream index for compression randomness under the round key:
# fold_in(k_round, 0) = training, 1 = aggregation noise (make_round_fn),
# 2 = update compression — per-client keys then fold in the GLOBAL slot
# id, so streams never collide across uses or devices
COMPRESS_STREAM = 2
# 3 = downlink broadcast compression (fedavg_cross_device delta mode):
# ONE stream per round for the server's chain-update encode — no slot
# fold (the broadcast is cohort-shared), disjoint from every per-client
# stream above
BCAST_STREAM = 3

_CHUNK = 256  # per-chunk scale granularity (fp32 scale per 256 values)


def _f32(x):
    import jax.numpy as jnp

    return x.astype(jnp.float32)


class LeafCodec:
    """One leaf's encode/decode pair.  ``encode`` returns a flat dict of
    arrays (the encoded payload); ``decode`` reconstructs the leaf from
    it given the (static) original shape.  Both are jnp-pure."""

    name: str = "?"
    stochastic: bool = False  # True: encode consumes the rng key

    def encode(self, x, key) -> Dict[str, Any]:
        raise NotImplementedError

    def decode(self, enc: Dict[str, Any], shape: Tuple[int, ...]):
        raise NotImplementedError

    # wire hooks: pack/unpack numpy payloads (default: passthrough)
    def wire_pack(self, enc: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return enc

    def wire_unpack(self, enc: Dict[str, np.ndarray],
                    shape: Tuple[int, ...]) -> Dict[str, np.ndarray]:
        return enc


class IdentityCodec(LeafCodec):
    name = "none"

    def encode(self, x, key):
        del key
        return {"v": _f32(x).reshape(-1)}

    def decode(self, enc, shape):
        return enc["v"].reshape(shape)


class Bf16Codec(LeafCodec):
    name = "bf16"

    def encode(self, x, key):
        import jax.numpy as jnp

        del key
        return {"v": _f32(x).reshape(-1).astype(jnp.bfloat16)}

    def decode(self, enc, shape):
        return _f32(enc["v"]).reshape(shape)


class QsgdCodec(LeafCodec):
    """QSGD stochastic uniform quantization, per-chunk max-abs scale.

    ``q = floor(x / scale * L + u)`` with ``u ~ U[0, 1)`` is unbiased
    for both signs (``E[floor(y + u)] = y``); values land in
    ``[-L, L]`` and ship as int8 (int4 packs two per byte on the wire).
    A zero chunk (scale 0) encodes to zeros via a safe divisor.
    """

    stochastic = True

    def __init__(self, bits: int):
        assert bits in (4, 8)
        self.bits = bits
        self.name = f"qsgd{bits}"
        self.levels = 7 if bits == 4 else 127

    def encode(self, x, key):
        import jax
        import jax.numpy as jnp

        flat = _f32(x).reshape(-1)
        n = flat.shape[0]
        m = -(-n // _CHUNK)  # ceil chunks
        pad = m * _CHUNK - n
        chunks = jnp.pad(flat, (0, pad)).reshape(m, _CHUNK)
        scale = jnp.max(jnp.abs(chunks), axis=1)  # [m]
        safe = jnp.where(scale > 0, scale, 1.0)
        y = chunks / safe[:, None] * self.levels  # in [-L, L]
        u = jax.random.uniform(key, chunks.shape)
        q = jnp.clip(jnp.floor(y + u), -self.levels, self.levels)
        # truncate to the true length: padded tail bytes are pure waste
        # on the wire (a 7-element leaf must not cost a 256-byte chunk)
        return {"q": q.astype(jnp.int8).reshape(-1)[:n], "scale": scale}

    def decode(self, enc, shape):
        import jax.numpy as jnp

        n = 1
        for d in shape:
            n *= d
        m = -(-n // _CHUNK)
        q = jnp.pad(_f32(enc["q"]), (0, m * _CHUNK - n)).reshape(m, _CHUNK)
        scale = _f32(enc["scale"])
        out = q * (scale[:, None] / self.levels)
        return out.reshape(-1)[:n].reshape(shape)

    # -- int4 wire packing: two values per byte ------------------------------
    def wire_pack(self, enc):
        if self.bits != 4:
            return enc
        q = np.asarray(enc["q"], np.int8)
        u = (q.astype(np.int16) + 8).astype(np.uint8)  # [-7,7] -> [1,15]
        if u.size % 2:
            u = np.concatenate([u, np.zeros(1, np.uint8)])
        packed = ((u[0::2] << 4) | u[1::2]).astype(np.uint8)
        return {"q4": packed, "scale": np.asarray(enc["scale"]),
                "qn": np.asarray(q.size, np.int64)}

    def wire_unpack(self, enc, shape):
        if self.bits != 4 or "q4" not in enc:
            return enc
        packed = np.asarray(enc["q4"], np.uint8)
        qn = int(enc["qn"])
        u = np.empty(packed.size * 2, np.uint8)
        u[0::2] = packed >> 4
        u[1::2] = packed & 0x0F
        q = (u[:qn].astype(np.int16) - 8).astype(np.int8)
        return {"q": q, "scale": np.asarray(enc["scale"])}


class TopKCodec(LeafCodec):
    """Magnitude top-k: ``k = max(1, round(rate * size))`` largest-|x|
    entries ship as (int32 index, fp32 value); decode scatters into
    zeros.  Deterministic (no rng).  Biased — run with error feedback."""

    def __init__(self, rate: float):
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"topk rate must be in (0, 1], got {rate}")
        self.rate = rate
        self.name = f"topk{rate:g}"

    def _k(self, n: int) -> int:
        return max(1, min(n, int(round(self.rate * n))))

    def encode(self, x, key):
        import jax
        import jax.numpy as jnp

        del key
        flat = _f32(x).reshape(-1)
        k = self._k(flat.shape[0])
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        idx = jnp.sort(idx)  # canonical order: stable wire bytes
        return {"idx": idx.astype(jnp.int32), "val": flat[idx]}

    def decode(self, enc, shape):
        import jax.numpy as jnp

        n = 1
        for d in shape:
            n *= d
        zeros = jnp.zeros((n,), jnp.float32)
        return zeros.at[enc["idx"]].set(_f32(enc["val"])).reshape(shape)


def get_codec(name: Optional[str]) -> Optional[LeafCodec]:
    """Codec registry: ``none``/''/None, ``bf16``, ``int8``/``qsgd8``,
    ``int4``/``qsgd4``, ``topk<rate>`` (default rate 0.01)."""
    if name is None or name in ("", "none", "fp32"):
        return None
    if name == "bf16":
        return Bf16Codec()
    if name in ("int8", "qsgd8"):
        return QsgdCodec(8)
    if name in ("int4", "qsgd4"):
        return QsgdCodec(4)
    if name.startswith("topk"):
        rate = name[len("topk"):]
        return TopKCodec(float(rate) if rate else 0.01)
    raise ValueError(
        f"unknown codec {name!r} (known: none, bf16, int8/qsgd8, "
        "int4/qsgd4, topk<rate>)"
    )


# --- tree-level plumbing (shared by engine and wire) ------------------------

def _leaf_keys(key, num_leaves: int):
    import jax

    return [jax.random.fold_in(key, i) for i in range(num_leaves)]


def encode_tree(codec: LeafCodec, tree: PyTree, key) -> List[Dict[str, Any]]:
    """Encode every leaf; returns encodings aligned to
    ``jax.tree_util.tree_leaves(tree)`` order."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    return [codec.encode(l, k)
            for l, k in zip(leaves, _leaf_keys(key, len(leaves)))]


def decode_tree(codec: LeafCodec, encs: List[Dict[str, Any]],
                like: PyTree) -> PyTree:
    """Decode against a structural template (shapes/treedef from
    ``like``); every decoded leaf is fp32."""
    import jax

    leaves_like, treedef = jax.tree_util.tree_flatten(like)
    assert len(encs) == len(leaves_like), "codec/treedef leaf count mismatch"
    out = [codec.decode(e, tuple(np.shape(ref)))
           for e, ref in zip(encs, leaves_like)]
    return jax.tree_util.tree_unflatten(treedef, out)


def roundtrip_tree(codec: LeafCodec, tree: PyTree, key) -> PyTree:
    """decode(encode(tree)) in one call — the engine's lossy view of an
    update (what the server will reconstruct from the wire)."""
    return decode_tree(codec, encode_tree(codec, tree, key), tree)


# --- wire forms (numpy payloads for wiretree v2) ----------------------------

def wire_encode_tree(codec: LeafCodec, tree: PyTree, key) -> List[dict]:
    """Per-leaf wire entries: ``{"enc": {name: np.ndarray}, "shape",
    "dtype"}`` — raw arrays, so the v2 frame codec ships them as
    length-prefixed binary buffers (no base64)."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    out = []
    for l, k in zip(leaves, _leaf_keys(key, len(leaves))):
        enc = codec.encode(l, k)
        enc_np = {name: np.asarray(v) for name, v in enc.items()}
        out.append({
            "enc": codec.wire_pack(enc_np),
            "shape": list(np.shape(l)),
            "dtype": str(np.asarray(l).dtype),
        })
    return out


def wire_decode_tree(codec: LeafCodec, entries: List[dict],
                     like: PyTree) -> PyTree:
    """Inverse of ``wire_encode_tree`` (numpy, host-side): decodes each
    leaf to fp32 in the template's treedef."""
    import jax

    leaves_like, treedef = jax.tree_util.tree_flatten(like)
    assert len(entries) == len(leaves_like), "wire/treedef leaf count mismatch"
    out = []
    for e, ref in zip(entries, leaves_like):
        shape = tuple(e.get("shape") or np.shape(ref))
        enc = {name: np.asarray(v) for name, v in e["enc"].items()}
        dec = codec.decode(codec.wire_unpack(enc, shape), shape)
        out.append(np.asarray(dec, np.float32))
    return jax.tree_util.tree_unflatten(treedef, out)


def encoded_nbytes(codec: Optional[LeafCodec], tree: PyTree) -> int:
    """Exact wire payload bytes of the encoded tree (buffers only, no
    envelope) — static given shapes, so drivers can account compressed
    traffic without re-encoding every round."""
    import jax

    if codec is None:
        return sum(int(np.prod(np.shape(l), dtype=np.int64)) * 4
                   for l in jax.tree_util.tree_leaves(tree))
    key = _dummy_key()
    total = 0
    for entry in wire_encode_tree(codec, tree, key):
        total += sum(int(np.asarray(v).nbytes)
                     for v in entry["enc"].values())
    return total


def _dummy_key():
    import jax

    return jax.random.PRNGKey(0)


def wire_tree_digest(wire_obj: dict) -> str:
    """sha256 over a wiretree's payload buffers in leaf order — the
    reproducibility probe: two runs at the same seed must produce
    IDENTICAL encoded uploads, and this digest is how a federation run
    proves it without capturing multi-MB frames."""
    h = hashlib.sha256()
    for leaf in wire_obj.get("leaves", ()):
        if isinstance(leaf, dict) and "enc" in leaf:
            for name in sorted(leaf["enc"]):
                h.update(np.ascontiguousarray(
                    np.asarray(leaf["enc"][name])).tobytes())
        elif isinstance(leaf, dict) and NDARRAY_KEY in leaf:
            h.update(str(leaf[NDARRAY_KEY]).encode())
        else:
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()
