"""Per-shard wire encode: compress a rule-sharded update WITHOUT
gathering it.

The plain wire path (``codecs.wire_encode_tree``) flattens each leaf —
for a model laid out over an ``mp`` mesh axis by the partition-rule
engine (``parallel/partition.py``) that flatten IS an all-gather, and
the whole point of sharding (a model bigger than one chip) dies at the
first compressed upload.  This module encodes each device-local shard
independently:

- shard enumeration is ``arr.addressable_shards`` deduped by index
  (replication over ``dp`` yields copies) and sorted by slice start —
  a platform-independent deterministic order;
- shard ``j`` of leaf ``i`` draws its codec randomness from
  ``fold_in(fold_in(key, i), j)`` — so the encoded bytes of a shard
  are BIT-IDENTICAL to a single-device encode of that shard's slice
  with the same key (pinned by ``tests/test_shard_rules.py``), and no
  two shards ever share a stream;
- only ``shard.data`` (the device-local block) is ever materialized —
  the full leaf never is, which the byte accounting in
  ``tools/fed_shard_run.py`` asserts (sum of shard elements == leaf
  elements, one visit each).

Wire format per leaf: ``{"shards": [{"enc": .., "index": [[lo,hi]..],
"shape": [..]}, ..], "shape": [..], "dtype": ".."}`` — a strict
superset of the v2 entry, decodable shard-by-shard into a zeros
canvas (``wire_decode_tree_sharded``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from fedml_tpu.compress.codecs import LeafCodec, _leaf_keys

PyTree = Any


def _norm_index(index, shape) -> Tuple[Tuple[int, int], ...]:
    """A shard's ``.index`` (tuple of slices, possibly open) as
    concrete ``(lo, hi)`` bounds."""
    out = []
    for sl, n in zip(index, shape):
        lo = 0 if sl.start is None else int(sl.start)
        hi = int(n) if sl.stop is None else int(sl.stop)
        out.append((lo, hi))
    return tuple(out)


def shard_slices(arr) -> List[Tuple[Tuple[Tuple[int, int], ...], Any]]:
    """Deduped ``(bounds, data)`` pairs for one (possibly sharded)
    array, sorted by slice start.  Replicated copies (same bounds on
    several devices) appear once; a host numpy array is one full-cover
    pseudo-shard, so the encoder is total over both worlds."""
    shape = np.shape(arr)
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        full = tuple((0, int(n)) for n in shape)
        return [(full, arr)]
    seen: Dict[Tuple, Any] = {}
    for s in shards:
        bounds = _norm_index(s.index, shape)
        seen.setdefault(bounds, s.data)
    return [(b, seen[b]) for b in sorted(seen)]


def wire_encode_tree_sharded(codec: LeafCodec, tree: PyTree,
                             key) -> List[dict]:
    """Per-leaf sharded wire entries.  Leaf ``i``'s shard ``j``
    encodes ``fold_in(fold_in(key, i), j)`` over the DEVICE-LOCAL
    block only — no gather, and bytes pinned to the single-device
    encode of the same slice."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    out = []
    for leaf, k_leaf in zip(leaves, _leaf_keys(key, len(leaves))):
        entry_shards = []
        for j, (bounds, data) in enumerate(shard_slices(leaf)):
            k_shard = jax.random.fold_in(k_leaf, j)
            enc = codec.encode(np.asarray(data), k_shard)
            enc_np = {name: np.asarray(v) for name, v in enc.items()}
            entry_shards.append({
                "enc": codec.wire_pack(enc_np),
                "index": [[lo, hi] for lo, hi in bounds],
                "shape": [hi - lo for lo, hi in bounds],
            })
        dt = getattr(leaf, "dtype", None)  # np.asarray(leaf) would gather
        out.append({
            "shards": entry_shards,
            "shape": list(np.shape(leaf)),
            "dtype": str(dt if dt is not None
                         else np.result_type(type(leaf))),
        })
    return out


def wire_decode_tree_sharded(codec: LeafCodec, entries: List[dict],
                             like: PyTree) -> PyTree:
    """Decode sharded entries into full fp32 leaves on the host: each
    shard decodes into its slice of a zeros canvas."""
    import jax

    leaves_like, treedef = jax.tree_util.tree_flatten(like)
    assert len(entries) == len(leaves_like), \
        "sharded wire/treedef leaf count mismatch"
    out = []
    for e, ref in zip(entries, leaves_like):
        shape = tuple(e.get("shape") or np.shape(ref))
        canvas = np.zeros(shape, np.float32)
        for sh in e["shards"]:
            bounds = [tuple(b) for b in sh["index"]]
            sub_shape = tuple(hi - lo for lo, hi in bounds)
            enc = {name: np.asarray(v) for name, v in sh["enc"].items()}
            dec = np.asarray(
                codec.decode(codec.wire_unpack(enc, sub_shape), sub_shape),
                np.float32,
            )
            sel = tuple(slice(lo, hi) for lo, hi in bounds)
            canvas[sel] = dec
        out.append(canvas)
    return jax.tree_util.tree_unflatten(treedef, out)


def sharded_entry_nbytes(entry: dict) -> List[int]:
    """Wire payload bytes per shard of one leaf entry (buffers only)."""
    return [
        sum(int(np.asarray(v).nbytes) for v in sh["enc"].values())
        for sh in entry["shards"]
    ]


def sharded_wire_digest(entries: List[dict]) -> str:
    """sha256 over every shard's payload buffers in (leaf, shard)
    order — the sharded sibling of ``codecs.wire_tree_digest``."""
    import hashlib

    h = hashlib.sha256()
    for e in entries:
        for sh in e["shards"]:
            for name in sorted(sh["enc"]):
                h.update(np.ascontiguousarray(
                    np.asarray(sh["enc"][name])).tobytes())
    return h.hexdigest()
