"""DP×SP federated rounds: long-context clients on a (clients, sp) mesh.

Composes the two first-class axes of this framework: the FedAvg clients
axis (one FL client per mesh row, masked weighted psum aggregation —
``parallel/spmd.py``) and sequence parallelism (each client's token
sequences sharded over the ``sp`` axis with ring attention —
``parallel/ring_attention.py``).  The result is federated fine-tuning
over sequences LONGER than one chip's attention memory: every client's
local update runs as an sp-way SPMD program, and the cross-client
aggregation rides the same compiled round.  The reference has no
analogue on either axis (SURVEY.md §2.6, §5.7).

Correctness structure (all inside ONE shard_map over both axes):

- model params are REPLICATED over ``sp``; each shard computes the
  gradient through its own token shard, so a cross-shard combine is
  inserted as an optax transform ahead of the client optimizer
  (``pmean_gradients`` — MEAN, because the psum-transpose identity
  already scales each shard's cotangent by the axis size), which keeps
  the replicas bit-identical after every step.
- the loss is globally normalized: per-shard masked sums are psum'd
  over ``sp`` before the division (``make_sp_loss_fn``), so token counts
  on other shards weigh the local gradient correctly.
- causal positions are global: the transformer's ``pos_offset_fn`` adds
  ``axis_index(sp) * L_local``, and attention is the exact ring
  (lax blockwise or the pallas flash ring).
- aggregation across clients is ``make_round_fn``'s masked weighted
  psum with ``axis_name="clients"`` — unchanged.

Parity is pinned against a single-device oracle running the same round
on the full-length model (``tests/test_dp_sp.py``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.parallel.compat import shard_map
from fedml_tpu.algorithms.fedavg import ServerState, make_round_fn
from fedml_tpu.core.client import make_local_update
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.models.base import ModelBundle
from fedml_tpu.models.transformer import TransformerLM

PyTree = Any


def make_dp_sp_mesh(
    n_clients_axis: int, n_sp: int, *, devices=None
) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = n_clients_axis * n_sp
    if n > len(devices):
        raise ValueError(
            f"mesh {n_clients_axis}x{n_sp} needs {n} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(devices[:n]).reshape(n_clients_axis, n_sp)
    return Mesh(arr, axis_names=("clients", "sp"))


def pmean_gradients(axis: str) -> optax.GradientTransformation:
    """Combine replicated-parameter gradients across ``axis`` BEFORE the
    optimizer.  Each shard's AD only covers its own token shard's paths
    through the shared params, so a cross-shard combine is required to
    keep the replicas identical — and it must be pMEAN, not psum:
    JAX transposes ``lax.psum`` to ``lax.psum``, so differentiating the
    globally-psum'd loss already hands every shard an axis-size-scaled
    cotangent (the classic psum-gradient identity), and the mean exactly
    cancels that factor.  Pinned against the single-device oracle in
    tests/test_dp_sp.py — a psum here was measured as a uniform
    axis_size× gradient inflation."""

    def update(grads, state, params=None):
        del params
        return jax.tree_util.tree_map(
            lambda g: lax.pmean(g, axis), grads
        ), state

    return optax.GradientTransformation(lambda _: (), update)


def make_sp_loss_fn(axis: str, base: LossFn = masked_softmax_ce) -> LossFn:
    """Globally-normalized loss over a sequence-sharded batch: psum the
    masked sums over ``axis``, divide once — so every shard's local
    gradient carries the correct global weight, and the metrics each
    shard reports are already the full-sequence totals."""

    def loss_fn(logits, y, mask):
        _, aux = base(logits, y, mask)
        s = lax.psum(aux["loss_sum"], axis)
        c = lax.psum(aux["count"], axis)
        corr = lax.psum(aux["correct"], axis)
        loss = s / jnp.maximum(c, 1.0)
        return loss, {"loss_sum": s, "correct": corr, "count": c}

    return loss_fn


def sp_transformer_bundle(
    *,
    vocab_size: int,
    embed_dim: int,
    num_heads: int,
    num_layers: int,
    max_len: int,
    axis: str = "sp",
    attn_impl: str = "lax",
    block_size: int = 512,
    flash_block: Optional[int] = None,
    flash_interpret: bool = False,
) -> ModelBundle:
    """TransformerLM whose attention is the ring over ``axis`` and whose
    positions are shard-global — valid ONLY inside shard_map."""
    from fedml_tpu.parallel.ring_attention import (
        ring_attention,
        ring_flash_attention,
    )

    if attn_impl not in ("lax", "flash"):
        raise ValueError(f"attn_impl must be 'lax' or 'flash', got {attn_impl!r}")
    if attn_impl == "flash" and block_size != 512:
        # same guard as sequence_parallel_lm: block_size tunes the LAX
        # ring's KV chunking; the flash path's pallas block is
        # flash_block — reject the silent-ignore trap at the shared layer
        raise ValueError(
            "block_size applies to attn_impl='lax' only; tune the flash "
            "path with flash_block"
        )
    attn_fn = (
        (lambda q, k, v, causal: ring_flash_attention(
            q, k, v, axis, causal=causal, block=flash_block,
            interpret=flash_interpret))
        if attn_impl == "flash"
        else (lambda q, k, v, causal: ring_attention(
            q, k, v, axis, causal=causal, block_size=block_size))
    )
    module = TransformerLM(
        vocab_size=vocab_size, embed_dim=embed_dim, num_heads=num_heads,
        num_layers=num_layers, max_len=max_len, attn_fn=attn_fn,
        pos_offset_fn=lambda L: lax.axis_index(axis) * L,
    )
    # input_shape is the LOCAL token shard; init must happen OUTSIDE the
    # mesh with the plain reference module (sequence.py convention)
    return ModelBundle(module=module, input_shape=(max_len,),
                       input_dtype=jnp.int32)


def make_dp_sp_round_fn(
    mesh: Mesh,
    *,
    vocab_size: int,
    embed_dim: int,
    num_heads: int,
    num_layers: int,
    max_len: int,
    optimizer: optax.GradientTransformation,
    epochs: int = 1,
    compute_dtype=None,
    attn_impl: str = "lax",
    block_size: int = 512,
    flash_block: Optional[int] = None,
    flash_interpret: bool = False,
    donate: bool = True,
):
    """Build the DP×SP FedAvg round.

    round_fn(state, x, y, mask, num_samples, participation, slot_ids)
    with x/y [C, steps, B, L] (L divisible by the sp axis), mask
    [C, steps, B] per-sequence.  Returns (round_fn, shard_data,
    init_fn): ``init_fn(rng)`` initializes params with the plain
    full-length module (identical tree), ``shard_data`` lays the packed
    block out on the mesh (sequence dim over ``sp``).
    """
    bundle = sp_transformer_bundle(
        vocab_size=vocab_size, embed_dim=embed_dim, num_heads=num_heads,
        num_layers=num_layers, max_len=max_len, attn_impl=attn_impl,
        block_size=block_size, flash_block=flash_block,
        flash_interpret=flash_interpret,
    )
    # gradient pmean over sp BEFORE the client optimizer (see
    # pmean_gradients for why mean, not sum)
    opt = optax.chain(pmean_gradients("sp"), optimizer)
    local_update = make_local_update(
        bundle, opt, epochs, make_sp_loss_fn("sp"),
        compute_dtype=compute_dtype,
    )
    inner = make_round_fn(local_update, axis_name="clients")

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(),                          # state replicated
            P("clients", None, None, "sp"),   # x tokens
            P("clients", None, None, "sp"),   # y targets
            P("clients"),                 # per-sequence mask
            P("clients"),                 # num_samples
            P("clients"),                 # participation
            P("clients"),                 # slot ids
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def dp_sp_round(state, x, y, mask, num_samples, participation, slot_ids):
        return inner(state, x, y, mask, num_samples, participation, slot_ids)

    def init_fn(rng: jax.Array) -> PyTree:
        ref = TransformerLM(
            vocab_size=vocab_size, embed_dim=embed_dim,
            num_heads=num_heads, num_layers=num_layers, max_len=max_len,
        )
        dummy = jnp.zeros((1, max_len), jnp.int32)
        return ref.init({"params": rng}, dummy, train=False)

    def shard_data(arrays):
        x, y, mask, num_samples, participation, slot_ids = arrays
        cl = NamedSharding(mesh, P("clients"))
        seq = NamedSharding(mesh, P("clients", None, None, "sp"))
        return (
            jax.device_put(jnp.asarray(x), seq),
            jax.device_put(jnp.asarray(y), seq),
            jax.device_put(jnp.asarray(mask), cl),
            jax.device_put(jnp.asarray(num_samples), cl),
            jax.device_put(jnp.asarray(participation), cl),
            jax.device_put(jnp.asarray(slot_ids), cl),
        )

    round_fn = jax.jit(dp_sp_round, donate_argnums=(0,) if donate else ())
    return round_fn, shard_data, init_fn
