"""Tensor parallelism: Megatron-style sharding of the transformer MLP
and attention projections over a ``tp`` mesh axis.

The reference has no tensor parallelism at all (SURVEY.md §2.6 —
TP/PP/SP "absent"); the rebuild's mesh reserves a model axis for it.
This module implements TP the idiomatic XLA way: instead of hand-writing
collectives, we annotate PARAMETER shardings (column-parallel up
projections, row-parallel down projections) with ``NamedSharding`` and
let the GSPMD partitioner insert the all-reduces — the "pick a mesh,
annotate shardings, let XLA insert collectives" recipe.

Sharding plan per transformer block (embed dim E, heads H):

- attention qkv projection kernel  [E, 3E]  → P(None, tp)   (column)
- attention output kernel          [E, E]   → P(tp, None)   (row; psum)
- MLP up kernel                    [E, 4E]  → P(None, tp)   (column)
- MLP up bias                      [4E]     → P(tp)
- MLP down kernel                  [4E, E]  → P(tp, None)   (row; psum)
- embeddings / LayerNorms / small biases    → replicated

Composable with the ``clients`` axis: a mesh of shape
(clients, tp) runs FL rounds where each client's forward/backward is
itself tensor-sharded.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.models.transformer import transformer_lm

PyTree = Any


def make_tp_mesh(n_devices: Optional[int] = None, axis: str = "tp") -> Mesh:
    from fedml_tpu.parallel.spmd import make_1d_mesh

    return make_1d_mesh(n_devices, axis)


def _path_names(path) -> Tuple[str, ...]:
    names = []
    for k in path:
        if hasattr(k, "key"):
            names.append(str(k.key))
        elif hasattr(k, "idx"):
            names.append(str(k.idx))
        else:
            names.append(str(k))
    return tuple(names)


def tp_param_spec(variables: PyTree, axis: str = "tp") -> PyTree:
    """PartitionSpec tree for a ``TransformerLM`` variables pytree."""

    def spec_for(path, leaf):
        names = _path_names(path)
        in_attn = any("MultiHeadAttention" in n for n in names)
        in_block = any(n.startswith("Block_") for n in names)
        leaf_name = names[-1]
        # which Dense inside its parent scope
        dense = next((n for n in names if n.startswith("Dense_")), None)
        if leaf_name == "kernel" and dense is not None:
            if in_attn:
                # qkv (Dense_0) column-parallel, output (Dense_1) row-parallel
                return P(None, axis) if dense == "Dense_0" else P(axis, None)
            if in_block:
                # MLP up (Dense_0) column-parallel, down (Dense_1) row-parallel
                return P(None, axis) if dense == "Dense_0" else P(axis, None)
        if leaf_name == "bias" and dense == "Dense_0" and in_block and not in_attn:
            return P(axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, variables)


def shard_tp_params(mesh: Mesh, variables: PyTree, axis: str = "tp") -> PyTree:
    """device_put the variables with the TP sharding plan."""
    specs = tp_param_spec(variables, axis)
    return jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)), variables, specs
    )


def tensor_parallel_lm(
    mesh: Mesh,
    *,
    vocab_size: int = 256,
    embed_dim: int = 128,
    num_heads: int = 4,
    num_layers: int = 2,
    seq_len: int = 256,
    axis: str = "tp",
):
    """Build (bundle, shard_params, apply, train_step) with TP shardings.

    ``shard_params(variables)`` lays the params out on the mesh;
    ``apply(variables, tokens)`` is the jitted forward (logits
    replicated); ``train_step(variables, tokens, targets, lr)`` is one
    jitted SGD step on the causal-LM loss whose gradients and updated
    params KEEP the TP sharding — XLA inserts the psums for the
    row-parallel matmuls in both passes.
    """
    bundle = transformer_lm(
        vocab_size=vocab_size, embed_dim=embed_dim, num_heads=num_heads,
        num_layers=num_layers, seq_len=seq_len,
    )

    def shard_params(variables: PyTree) -> PyTree:
        return shard_tp_params(mesh, variables, axis)

    @jax.jit
    def apply(variables, tokens):
        logits = bundle.apply_eval(variables, tokens)
        return jax.lax.with_sharding_constraint(logits, NamedSharding(mesh, P()))

    def loss_fn(variables, tokens, targets):
        logits = bundle.apply_eval(variables, tokens)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(
            logp, targets[..., None].astype(jnp.int32), axis=-1
        )[..., 0]
        return nll.mean()

    @jax.jit
    def train_step(variables, tokens, targets, lr):
        loss, grads = jax.value_and_grad(loss_fn)(variables, tokens, targets)
        new_vars = jax.tree_util.tree_map(
            lambda p, g: p - lr * g.astype(p.dtype), variables, grads
        )
        return new_vars, loss

    return bundle, shard_params, apply, train_step
