"""Expert parallelism: GShard-style mixture-of-experts FFN with
``all_to_all`` token dispatch over an ``ep`` mesh axis.

Completes the parallelism matrix the reference lacks entirely
(SURVEY.md §2.6 — TP/PP/SP/EP all "absent"): one expert's FFN weights
live on each device, tokens are data-sharded over the same axis, and a
pair of ``lax.all_to_all`` collectives routes each token to its top-1
expert and back.  Shapes are static: each token gets a position in its
expert's queue via a one-hot cumsum, tokens past ``capacity`` are
dropped (standard GShard semantics — the combine weight is zero, so a
dropped token contributes its residual path only).

All dispatch/combine math is einsum on one-hot masks — MXU-friendly,
no gathers/scatters with data-dependent shapes.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.parallel.compat import shard_map

PyTree = Any


def make_ep_mesh(n_devices: Optional[int] = None, axis: str = "ep") -> Mesh:
    from fedml_tpu.parallel.spmd import make_1d_mesh

    return make_1d_mesh(n_devices, axis)


def init_moe_params(
    key: jax.Array, num_experts: int, d_model: int, d_hidden: int
) -> PyTree:
    """Per-expert FFN weights stacked on a leading experts axis + gate."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / np.sqrt(d_model)
    scale_out = 1.0 / np.sqrt(d_hidden)
    return {
        "gate": jax.random.normal(k3, (d_model, num_experts)) * scale_in,
        "w_in": jax.random.normal(k1, (num_experts, d_model, d_hidden)) * scale_in,
        "w_out": jax.random.normal(k2, (num_experts, d_hidden, d_model)) * scale_out,
    }


def shard_moe_params(mesh: Mesh, params: PyTree, axis: str = "ep") -> PyTree:
    """Experts sharded one-per-device-group; gate replicated."""
    return {
        "gate": jax.device_put(params["gate"], NamedSharding(mesh, P())),
        "w_in": jax.device_put(params["w_in"], NamedSharding(mesh, P(axis))),
        "w_out": jax.device_put(params["w_out"], NamedSharding(mesh, P(axis))),
    }


def _expert_ffn(w_in, w_out, x):
    return jnp.maximum(x @ w_in, 0.0) @ w_out


def make_moe_ffn(mesh: Mesh, capacity: int, axis: str = "ep"):
    """Build ``apply(params, x)`` for a top-1 MoE FFN.

    - params from ``init_moe_params`` with num_experts == mesh size,
      sharded by ``shard_moe_params``.
    - x: [T, d_model] tokens, sharded over ``axis`` on dim 0 (T divisible
      by the axis size).
    Returns [T, d_model]: gate_prob · FFN_{top1}(token), zeros for
    capacity-dropped tokens (callers add the residual).
    """
    E = mesh.shape[axis]

    def local(params, x):
        # params local shard: w_in/w_out [1, d, h]; gate replicated
        w_in, w_out = params["w_in"][0], params["w_out"][0]
        logits = x @ params["gate"]  # [t, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(logits, axis=-1)  # [t] top-1
        gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

        onehot_e = jax.nn.one_hot(expert, E, dtype=x.dtype)  # [t, E]
        # queue position of each token within its expert (local queue)
        pos = jnp.cumsum(onehot_e, axis=0) - onehot_e  # [t, E] rank if routed
        pos = (pos * onehot_e).sum(axis=1)  # [t]
        keep = (pos < capacity).astype(x.dtype)
        onehot_c = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=x.dtype)
        # dispatch mask [t, E, capacity]
        dispatch = onehot_e[:, :, None] * onehot_c[:, None, :] * keep[:, None, None]

        expert_in = jnp.einsum("tec,td->ecd", dispatch, x)  # [E, cap, d]
        # route: each device sends slot e to device e, receives [E, cap, d]
        # where dim 0 is now the SOURCE device
        routed = lax.all_to_all(
            expert_in, axis, split_axis=0, concat_axis=0, tiled=True
        )
        expert_out = _expert_ffn(w_in, w_out, routed.reshape(E * capacity, -1))
        expert_out = expert_out.reshape(E, capacity, -1)
        # route back: slot s returns to source device s
        returned = lax.all_to_all(
            expert_out, axis, split_axis=0, concat_axis=0, tiled=True
        )
        out = jnp.einsum("tec,ecd->td", dispatch, returned)
        return out * gate[:, None]

    param_specs = {"gate": P(), "w_in": P(axis), "w_out": P(axis)}
    sharded = shard_map(
        local, mesh=mesh, in_specs=(param_specs, P(axis)), out_specs=P(axis),
        check_vma=False,
    )

    def apply(params, x):
        n_experts = params["w_in"].shape[0]
        if n_experts != E:
            # P(axis) would hand each device a multi-expert shard of
            # which only [0] runs, and the gate would route tokens to
            # experts that never execute — wrong results, no error
            raise ValueError(
                f"params have {n_experts} experts but ep mesh size is {E}; "
                "one expert per device is required"
            )
        if x.shape[0] % E:
            raise ValueError(f"token count {x.shape[0]} not divisible by ep={E}")
        return sharded(params, x)

    return jax.jit(apply)


def moe_reference(params: PyTree, x: jax.Array) -> jax.Array:
    """Serial oracle (no capacity drops): gate_prob · FFN_{top1}(token)."""
    logits = x @ params["gate"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(logits, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    def one(tok, e, g):
        y = _expert_ffn(params["w_in"][e], params["w_out"][e], tok)
        return y * g

    return jax.vmap(one)(x, expert, gate)
