"""Ring attention — sequence/context parallelism over a device mesh.

The reference has no attention at all (SURVEY.md §5.7: 2-layer LSTMs,
80-char windows); this module is the TPU-native long-context substrate
the rebuild adds so the mesh design scales past it.  Design follows the
public ring-attention recipe (Liu et al. 2023, blockwise online-softmax
attention with K/V blocks rotating around the ICI ring):

- ``blockwise_attention``: single-device chunked attention with online
  softmax — O(seq) memory, exact (not approximate).
- ``ring_attention``: inside ``shard_map`` over a sequence-sharded axis,
  each device holds one Q/K/V shard; after attending its local block,
  K/V shards rotate via ``lax.ppermute`` (ICI neighbor exchange) for
  ``axis_size - 1`` steps while local attention accumulates (m, l, o)
  online-softmax state.  Compute overlaps communication since each
  step's matmuls and the permute are independent XLA ops the scheduler
  pipelines.
- causal masking uses GLOBAL positions (shard offset = axis index), so
  the sharded result equals dense causal attention up to float addition
  order.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, bias):
    """One (q-block, kv-block) attention contribution.

    q [Lq, H, D], k/v [Lk, H, D], bias [Lq, Lk] additive (0 / -inf mask).
    Returns (m [Lq,H], l [Lq,H], o [Lq,H,D]) online-softmax partials.
    """
    d = q.shape[-1]
    s = jnp.einsum("qhd,khd->hqk", q, k) / jnp.sqrt(d).astype(q.dtype)
    s = s + bias[None, :, :]
    m = s.max(axis=-1)                      # [H, Lq]
    p = jnp.exp(s - m[..., None])           # [H, Lq, Lk]
    l = p.sum(axis=-1)                      # [H, Lq]
    o = jnp.einsum("hqk,khd->qhd", p, v)    # [Lq, H, D]
    return m.swapaxes(0, 1), l.swapaxes(0, 1), o


def _merge(m1, l1, o1, m2, l2, o2):
    """Merge two online-softmax partial states."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    o = o1 * a1[..., None] + o2 * a2[..., None]
    return m, l, o


def _partial_attention(q, k, v, *, causal, block_size, q_offset, kv_offset):
    """(m, l, o) partials of Q [Lq,H,D] against K/V [Lk,H,D], scanned in
    KV blocks.  Pads ragged K/V to a block multiple and masks the pad —
    the ONE shared inner loop for both the single-device and ring paths.

    ``q_offset``/``kv_offset`` are GLOBAL positions of the first
    query/key; kv_offset may be a traced value (ring path).
    """
    Lq, H, D = q.shape
    Lk = k.shape[0]
    bs = min(block_size, Lk)
    n_blocks = (Lk + bs - 1) // bs
    pad = n_blocks * bs - Lk
    if pad:
        k = jnp.pad(k, ((0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, pad), (0, 0), (0, 0)))

    qpos = q_offset + jnp.arange(Lq)

    def body(carry, i):
        m, l, o = carry
        kb = lax.dynamic_slice_in_dim(k, i * bs, bs)
        vb = lax.dynamic_slice_in_dim(v, i * bs, bs)
        # local (unshifted) key index for pad masking; global for causal
        local_kpos = i * bs + jnp.arange(bs)
        bias = jnp.where(local_kpos[None, :] < Lk, 0.0, NEG_INF)
        if causal:
            kpos = kv_offset + local_kpos
            bias = bias + jnp.where(
                kpos[None, :] <= qpos[:, None], 0.0, NEG_INF
            )
        else:
            bias = jnp.broadcast_to(bias, (Lq, bs))
        mb, lb, ob = _block_attn(q, kb, vb, bias.astype(q.dtype))
        return _merge(m, l, o, mb, lb, ob), None

    # derive carry inits from q so they inherit q's varying-manual-axes
    # type under shard_map (JAX ≥0.9 typed vma; a fresh jnp.full would
    # be unvarying and fail lax.scan's carry typecheck on the ring path)
    zero = jnp.zeros_like(q[:, :, 0])       # [Lq, H]
    m0 = zero + jnp.asarray(NEG_INF, q.dtype)
    l0 = zero
    o0 = jnp.zeros_like(q)
    (m, l, o), _ = lax.scan(body, (m0, l0, o0), jnp.arange(n_blocks))
    return m, l, o


def _normalize(m, l, o):
    del m
    return o / jnp.maximum(l, 1e-30)[..., None]


def blockwise_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *,
    causal: bool = False,
    block_size: int = 512,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jax.Array:
    """Exact attention over [L, H, D] tensors in KV blocks (O(L) memory).

    ``q_offset``/``kv_offset`` are the global positions of the first
    query/key — how ring shards express causal masks.
    """
    return _normalize(*_partial_attention(
        q, k, v, causal=causal, block_size=block_size,
        q_offset=q_offset, kv_offset=kv_offset,
    ))


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    block_size: int = 512,
) -> jax.Array:
    """Sequence-parallel exact attention INSIDE shard_map.

    Each device holds the local shard [L_local, H, D] of a sequence
    sharded over ``axis_name``.  The local K/V block is attended first;
    then K/V rotate left around the ring for ``axis_size - 1`` steps so
    every query attends every key with no wasted final exchange.
    Returns the local output shard.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    L = q.shape[0]
    q_offset = my_idx * L

    perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]

    # step 0: the resident (local) K/V shard
    state = _partial_attention(
        q, k, v, causal=causal, block_size=block_size,
        q_offset=q_offset, kv_offset=my_idx * L,
    )

    def step(carry, i):
        m, l, o, kc, vc = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        # after i rotations the resident shard started at device my+i
        src = (my_idx + i) % axis_size
        mb, lb, ob = _partial_attention(
            q, kc, vc, causal=causal, block_size=block_size,
            q_offset=q_offset, kv_offset=src * L,
        )
        m, l, o = _merge(m, l, o, mb, lb, ob)
        return (m, l, o, kc, vc), None

    (m, l, o, _, _), _ = lax.scan(
        step, (*state, k, v), jnp.arange(1, axis_size)
    )
    return _normalize(m, l, o)


def ring_flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    block: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Ring attention whose per-step local attention is the pallas flash
    kernel (``ops/flash_attention.py``) instead of the lax blockwise scan
    — same rotation schedule and exact math, ~2x the per-step attention
    rate at long shard lengths on TPU.

    The cross-shard structure removes the need for global positions
    inside the kernel: under causal masking a source shard from an
    EARLIER ring rank is fully visible to every local query (non-causal
    step), a LATER rank contributes nothing (its lse is forced to -inf
    before the merge, costing one wasted kernel run the SPMD lockstep
    requires anyway — exactly like the lax path's fully-masked steps),
    and only the resident step is causal.  Per-source normalized outputs
    merge by log-sum-exp weights:

        m = max(lse_a, lse_b);  w_s = exp(lse_s - m)
        o = (w_a o_a + w_b o_b) / (w_a + w_b);  lse = m + log(w_a + w_b)

    Equivalence with the lax ring and dense attention is pinned in
    interpret mode (``tests/test_ring_attention.py``); default ``block``
    is ``pick_block`` of the shard length.
    """
    from fedml_tpu.ops.flash_attention import (
        flash_attention_with_lse,
        pick_block,
    )

    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    L = q.shape[0]
    b = block or pick_block(L)
    if not b:
        raise ValueError(
            f"shard length {L} has no >=128 power-of-two block; use the "
            "lax ring_attention"
        )

    def flash(qq, kk, vv, c):
        # the custom_vjp pair: differentiable through BOTH o and lse
        # (the merge weights below are lse functions)
        o, lse = flash_attention_with_lse(qq, kk, vv, c, b, b, interpret)
        return o.astype(jnp.float32), lse  # o [L, H, D], lse [H, L]

    # step 0: the resident shard (the only causal step)
    o, lse = flash(q, k, v, causal)

    perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]

    def step(carry, i):
        o, lse, kc, vc = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        src = (my_idx + i) % axis_size
        o_s, lse_s = flash(q, kc, vc, False)
        if causal:
            # later ranks' keys are all in this query shard's future
            lse_s = jnp.where(src < my_idx, lse_s, NEG_INF)
        m = jnp.maximum(lse, lse_s)
        wa = jnp.exp(lse - m)                       # [H, L]
        wb = jnp.exp(lse_s - m)
        den = jnp.maximum(wa + wb, 1e-30)
        waT = (wa / den).T[:, :, None]              # [L, H, 1]
        wbT = (wb / den).T[:, :, None]
        o = waT * o + wbT * o_s
        lse = m + jnp.log(den)
        return (o, lse, kc, vc), None

    (o, lse, _, _), _ = lax.scan(
        step, (o, lse, k, v), jnp.arange(1, axis_size)
    )
    return o.astype(q.dtype)


def dense_attention(q, k, v, *, causal: bool = False) -> jax.Array:
    """Reference implementation for tests: plain softmax(QKᵀ)V, [L, H, D]."""
    d = q.shape[-1]
    s = jnp.einsum("qhd,khd->hqk", q, k) / jnp.sqrt(d).astype(q.dtype)
    if causal:
        L, Lk = q.shape[0], k.shape[0]
        mask = jnp.tril(jnp.ones((L, Lk), bool))
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v)
