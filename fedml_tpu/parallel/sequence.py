"""Sequence parallelism: a transformer forward sharded over a mesh axis.

Composes ``shard_map`` + ``ring_attention`` so one logical sequence is
split across devices on the ICI ring: activations and KV blocks live
sharded, attention rotates K/V with ``ppermute``, and parameters stay
replicated.  Positions are globalized per shard, so the sharded forward
equals the single-device forward exactly.

This is the long-context capability the reference lacks entirely
(SURVEY.md §2.6 "TP/PP/SP/... absent") and the mesh axis the rest of
the framework reserves for it.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.parallel.compat import shard_map

from fedml_tpu.models.transformer import TransformerLM
from fedml_tpu.parallel.ring_attention import (ring_attention,
                                               ring_flash_attention)

PyTree = Any


def make_sequence_mesh(n_devices: Optional[int] = None,
                       axis: str = "sp") -> Mesh:
    from fedml_tpu.parallel.spmd import make_1d_mesh

    return make_1d_mesh(n_devices, axis)


def sequence_parallel_lm(
    mesh: Mesh,
    *,
    vocab_size: int = 256,
    embed_dim: int = 128,
    num_heads: int = 4,
    num_layers: int = 2,
    max_len: int = 2048,
    block_size: int = 512,
    axis: str = "sp",
    attn_impl: str = "lax",
    flash_block: Optional[int] = None,
    flash_interpret: bool = False,
    remat: bool = False,
):
    """Build (module, init, apply) where ``apply(variables, tokens)``
    runs the forward with the sequence dim sharded over ``axis``.

    tokens: [B, L] with L divisible by the axis size.  Returns logits
    [B, L, V] (reassembled from shards by shard_map's out_spec).
    """
    if attn_impl not in ("lax", "flash"):
        raise ValueError(
            f"attn_impl must be 'lax' or 'flash', got {attn_impl!r}"
        )
    if attn_impl == "flash" and block_size != 512:
        # block_size tunes the LAX ring's KV chunking; the flash path's
        # pallas block is flash_block (pick_block default).  Reject the
        # silent-ignore trap instead of guessing which one was meant.
        raise ValueError(
            "block_size applies to attn_impl='lax' only; tune the flash "
            "path with flash_block"
        )
    module = TransformerLM(
        vocab_size=vocab_size, embed_dim=embed_dim, num_heads=num_heads,
        num_layers=num_layers, max_len=max_len, remat=remat,
        # "flash": the pallas-kernel ring path (ring_flash_attention) —
        # ~2x per-step attention at long shard lengths on TPU pods;
        # "lax" (default) is the portable blockwise ring.  flash_block
        # overrides pick_block; flash_interpret runs the kernel's CPU
        # interpreter (tests on the faked mesh).
        attn_fn=(
            (lambda q, k, v, causal: ring_flash_attention(
                q, k, v, axis, causal=causal, block=flash_block,
                interpret=flash_interpret))
            if attn_impl == "flash"
            else (lambda q, k, v, causal: ring_attention(
                q, k, v, axis, causal=causal, block_size=block_size))
        ),
        pos_offset_fn=lambda L: lax.axis_index(axis) * L,
    )

    def init(rng: jax.Array, sample_len: int = 128) -> PyTree:
        """Initialize OUTSIDE the mesh with plain blockwise attention —
        shapes/params are identical, only the attention impl differs."""
        ref = TransformerLM(
            vocab_size=vocab_size, embed_dim=embed_dim, num_heads=num_heads,
            num_layers=num_layers, max_len=max_len,
        )
        dummy = jnp.zeros((1, sample_len), jnp.int32)
        return ref.init({"params": rng}, dummy, train=False)

    def _local_forward(variables, tokens):
        return module.apply(variables, tokens, train=False)

    # check_vma only off for the flash path: pallas_call carries no vma
    # metadata on its out_shape under JAX 0.9's typed varying axes.  The
    # lax ring KEEPS the check — its carry inits were explicitly written
    # to satisfy vma typing (ring_attention.py), and the trace-time type
    # error is the guard against regressing that.
    sharded = shard_map(
        _local_forward, mesh=mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=P(None, axis, None),
        check_vma=(attn_impl != "flash"),
    )

    def apply(variables, tokens):
        # static-shape check: raises at trace time, before any clamped
        # positional-table gather could silently degrade output
        if tokens.shape[1] > max_len:
            raise ValueError(
                f"sequence length {tokens.shape[1]} exceeds max_len "
                f"{max_len}: positional table would clamp silently"
            )
        return sharded(variables, tokens)

    return module, init, jax.jit(apply)
