"""Partition-rule sharding engine: ordered ``(regex → PartitionSpec)``
tables matched against param-tree path names.

The per-consumer sharding heuristics in ``parallel/tensor.py``
(``tp_param_spec``) and ``parallel/gspmd.py`` hard-code ONE layout for
ONE model family.  This module replaces them with data: a rule table is
an ordered list of ``(pattern, spec)`` pairs; each leaf's '/'-joined
path (``params/Block_0/Dense_1/kernel``) is matched with ``re.search``
and the FIRST matching rule wins — the fmengine/EasyLM lineage of
GSPMD sharding, where the layout of a whole model family fits in a
dozen visible lines instead of a tree of if/elifs.  Scalars (ndim 0)
are always replicated; an explicit ``_unmatched`` policy decides
whether unmatched leaves replicate or raise.

Canonical tables ship for the two model families the bench drives:
``fedllm`` (the ``models/transformer.py`` LM: vocab-sharded embedding,
column/row attention and MLP projections, replicated LayerNorms) and
``resnet`` (output-channel-sharded convs).  Custom tables load from
JSON (``resolve_rules``).

On top of the matcher sit the appliers: ``shard_by_rules`` lays a
pytree out on a ``(dp, mp)`` mesh (``parallel/mesh.py``);
``server_state_sharding`` extends the plan to the full
``ServerState`` — optimizer moments via the generalized
``gspmd.opt_state_sharding_like`` and the EF residual store with its
leading client axis on ``dp``; ``make_rule_round_fn`` jits the FedAvg
round with the packed client block over ``dp`` and the model over
``mp``; ``cohort_shardings`` produces the sharding tuple the muxed
cohort engine (``algorithms/fedavg_mux.py``) feeds to
``jit_sharded`` so thousands of virtual clients and a tensor-sharded
model run in ONE jit step.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from fedml_tpu.parallel.mesh import DP_AXIS, MP_AXIS

PyTree = Any

UNMATCHED_REPLICATE = "replicate"
UNMATCHED_RAISE = "raise"


class RuleTable(NamedTuple):
    """An ordered partition-rule table.

    ``rules`` are ``(pattern, spec_dims)`` pairs where ``spec_dims`` is
    the PartitionSpec as a plain tuple (``(None, "mp")``) so the table
    is importable without jax; ``unmatched`` is ``"replicate"`` or
    ``"raise"``.
    """

    name: str
    rules: Tuple[Tuple[str, Tuple], ...]
    unmatched: str = UNMATCHED_REPLICATE


# fedllm transformer (models/transformer.py): paths look like
#   params/wte/embedding                                  [V, E]
#   params/wpe/embedding                                  [S, E]
#   params/Block_i/MultiHeadAttention_0/Dense_0/kernel    [E, 3E] qkv
#   params/Block_i/MultiHeadAttention_0/Dense_1/kernel    [E, E]  out
#   params/Block_i/Dense_0/{kernel,bias}                  [E, 4E] mlp up
#   params/Block_i/Dense_1/kernel                         [4E, E] mlp down
#   params/Block_i/LayerNorm_{0,1}/{scale,bias}
#   params/ln_f/{scale,bias}                              final norm
# Megatron plan: qkv/up column-parallel, out/down row-parallel (GSPMD
# inserts the psum), embedding vocab-sharded (weight tying makes the
# logits matmul row-parallel for free), norms replicated.
FEDLLM_RULES = RuleTable(
    name="fedllm",
    rules=(
        (r"wte/embedding", (MP_AXIS, None)),
        (r"wpe/embedding", (None, None)),
        (r"MultiHeadAttention_\d+/Dense_0/kernel", (None, MP_AXIS)),
        (r"MultiHeadAttention_\d+/Dense_1/kernel", (MP_AXIS, None)),
        (r"Block_\d+/Dense_0/kernel", (None, MP_AXIS)),
        (r"Block_\d+/Dense_0/bias", (MP_AXIS,)),
        (r"Block_\d+/Dense_1/kernel", (MP_AXIS, None)),
        # row-parallel down projection: bias adds AFTER the psum, so it
        # replicates
        (r"Block_\d+/Dense_1/bias", ()),
        (r"LayerNorm_\d+|ln_f", ()),
    ),
    unmatched=UNMATCHED_REPLICATE,
)

# CIFAR ResNets (models/resnet.py): output-channel-sharded convs and
# classifier, BatchNorm params/stats replicated (they're per-channel
# vectors small enough that sharding buys nothing and complicates the
# running-stats update).
RESNET_RULES = RuleTable(
    name="resnet",
    rules=(
        (r"Conv_\d+/kernel", (None, None, None, MP_AXIS)),
        (r"Dense_\d+/kernel", (None, MP_AXIS)),
        (r"Dense_\d+/bias", (MP_AXIS,)),
        (r"BatchNorm_\d+|batch_stats", ()),
    ),
    unmatched=UNMATCHED_REPLICATE,
)

_NAMED_TABLES = {t.name: t for t in (FEDLLM_RULES, RESNET_RULES)}


def resolve_rules(name_or_path: str) -> RuleTable:
    """A canonical table by name (``fedllm``, ``resnet``) or a custom
    one from a JSON file::

        {"_unmatched": "raise",
         "rules": [["Dense_\\\\d+/kernel", [null, "mp"]], ...]}
    """
    if name_or_path in _NAMED_TABLES:
        return _NAMED_TABLES[name_or_path]
    try:
        with open(name_or_path) as f:
            doc = json.load(f)
    except OSError:
        raise ValueError(
            f"unknown rule table {name_or_path!r}: not a canonical name "
            f"({sorted(_NAMED_TABLES)}) and not a readable JSON file"
        ) from None
    unmatched = doc.get("_unmatched", UNMATCHED_REPLICATE)
    if unmatched not in (UNMATCHED_REPLICATE, UNMATCHED_RAISE):
        raise ValueError(
            f"rule file {name_or_path}: _unmatched must be "
            f"'{UNMATCHED_REPLICATE}' or '{UNMATCHED_RAISE}', "
            f"got {unmatched!r}"
        )
    rules = []
    for entry in doc.get("rules", ()):
        pattern, dims = entry
        re.compile(pattern)  # fail loud at load, not first match
        rules.append((str(pattern), tuple(dims)))
    return RuleTable(name=name_or_path, rules=tuple(rules),
                     unmatched=unmatched)


def _leaf_path(path) -> str:
    from fedml_tpu.parallel.tensor import _path_names

    return "/".join(_path_names(path))


def _spec_of(dims: Sequence):
    from jax.sharding import PartitionSpec as P

    return P(*dims)


def match_partition_rules(table: RuleTable, tree: PyTree) -> PyTree:
    """PartitionSpec tree for ``tree`` under ``table``: first
    ``re.search`` match on the '/'-joined path wins; ndim-0 leaves are
    always replicated; a matched spec with more dims than the leaf has
    is a table bug and raises; unmatched leaves follow
    ``table.unmatched``."""
    import jax
    from jax.sharding import PartitionSpec as P

    compiled = [(re.compile(p), dims) for p, dims in table.rules]

    def spec_for(path, leaf):
        name = _leaf_path(path)
        ndim = np.ndim(leaf)
        if ndim == 0:
            return P()
        for pat, dims in compiled:
            if pat.search(name):
                if len(dims) > ndim:
                    raise ValueError(
                        f"rule table {table.name!r}: pattern "
                        f"{pat.pattern!r} gives {len(dims)}-dim spec "
                        f"{tuple(dims)} for {ndim}-dim leaf {name!r}"
                    )
                return _spec_of(dims)
        if table.unmatched == UNMATCHED_RAISE:
            raise ValueError(
                f"rule table {table.name!r}: no rule matches leaf "
                f"{name!r} and _unmatched=raise"
            )
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, tree)


def rule_coverage(table: RuleTable, tree: PyTree) -> Dict[str, Any]:
    """Per-rule match accounting for the evidence file: how many leaves
    (and parameters) each rule claimed, which paths fell through, and
    the sharded/replicated split."""
    import jax

    compiled = [(re.compile(p), dims) for p, dims in table.rules]
    per_rule = [
        {"pattern": p, "spec": list(dims), "leaves": 0, "params": 0,
         "example": None}
        for p, dims in table.rules
    ]
    unmatched: List[str] = []
    sharded = replicated = 0
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    for path, leaf in leaves:
        name = _leaf_path(path)
        size = int(np.prod(np.shape(leaf), dtype=np.int64))
        if np.ndim(leaf) == 0:
            replicated += 1
            continue
        for i, (pat, dims) in enumerate(compiled):
            if pat.search(name):
                per_rule[i]["leaves"] += 1
                per_rule[i]["params"] += size
                if per_rule[i]["example"] is None:
                    per_rule[i]["example"] = name
                if any(d is not None for d in dims):
                    sharded += 1
                else:
                    replicated += 1
                break
        else:
            unmatched.append(name)
            replicated += 1
    return {
        "table": table.name,
        "unmatched_policy": table.unmatched,
        "rules": per_rule,
        "unmatched_paths": unmatched,
        "leaves_total": len(leaves),
        "leaves_sharded": sharded,
        "leaves_replicated": replicated,
    }


def validate_divisibility(tree: PyTree, specs: PyTree,
                          axis_sizes: Dict[str, int]) -> None:
    """Every sharded dim must divide evenly by the product of its mesh
    axes — GSPMD would silently pad instead, which wastes chips and
    (worse) hides a wrong rule.  Raises naming the leaf, dim and axis."""
    import jax
    from jax.sharding import PartitionSpec as P

    leaves = jax.tree_util.tree_leaves_with_path(tree)
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    for (path, leaf), spec in zip(leaves, spec_leaves):
        shape = np.shape(leaf)
        for dim, entry in enumerate(spec):
            if entry is None:
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            factor = 1
            for ax in axes:
                if ax not in axis_sizes:
                    raise ValueError(
                        f"leaf {_leaf_path(path)!r}: spec names mesh "
                        f"axis {ax!r}, mesh has {sorted(axis_sizes)}"
                    )
                factor *= int(axis_sizes[ax])
            if shape[dim] % factor:
                raise ValueError(
                    f"leaf {_leaf_path(path)!r}: dim {dim} of shape "
                    f"{tuple(shape)} not divisible by mesh axes "
                    f"{axes} (size {factor})"
                )


def named_sharding_tree(mesh, specs: PyTree) -> PyTree:
    """PartitionSpec tree → NamedSharding tree on ``mesh``."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_by_rules(mesh, tree: PyTree, table: RuleTable) -> Tuple[PyTree, PyTree]:
    """Lay ``tree`` out on ``mesh`` under ``table``: validate
    divisibility, then ``device_put`` each leaf with its
    ``NamedSharding``.  Returns ``(sharded_tree, specs)``."""
    import jax

    specs = match_partition_rules(table, tree)
    validate_divisibility(tree, specs,
                          {k: int(v) for k, v in mesh.shape.items()})
    shardings = named_sharding_tree(mesh, specs)
    return jax.device_put(tree, shardings), specs


def jit_sharded(fn, *, in_shardings=None, out_shardings=None, **jit_kwargs):
    """The partition-rule engine's jit entry point: ``jax.jit`` with
    sharding annotations.  Exists as a named wrapper so fedlint's
    jit-purity root scan covers every function compiled through the
    sharding subsystem (``analysis/jit_purity.py`` lists it in
    ``JIT_TRANSFORMS``)."""
    import jax

    if in_shardings is not None:
        jit_kwargs["in_shardings"] = in_shardings
    if out_shardings is not None:
        jit_kwargs["out_shardings"] = out_shardings
    return jax.jit(fn, **jit_kwargs)


# --- ServerState / round-engine integration ---------------------------------

def server_state_sharding(mesh, variables_template: PyTree,
                          table: RuleTable, *,
                          opt_state_template: Optional[PyTree] = None,
                          error_feedback: bool = False):
    """ServerState-shaped tree of shardings under ``table``: variables
    by rules, optimizer moments via the shape-matching
    ``gspmd.opt_state_sharding_like`` reusing the SAME rule-derived
    specs, EF residuals (leading ``[num_clients, ...]`` axis) with the
    client axis on ``dp`` and the param dims inheriting the param's
    spec.  Scalars (round_idx, key) replicate."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedml_tpu.algorithms.fedavg import ServerState
    from fedml_tpu.parallel.gspmd import opt_state_sharding_like

    specs = match_partition_rules(table, variables_template)
    var_sharding = named_sharding_tree(mesh, specs)
    repl = NamedSharding(mesh, P())
    if opt_state_template is not None:
        opt_sharding = opt_state_sharding_like(
            mesh, variables_template, opt_state_template, pspec=specs
        )
    else:
        opt_sharding = repl
    if error_feedback:
        import jax

        residual_sharding = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, P(DP_AXIS, *s)), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
    else:
        residual_sharding = ()
    return ServerState(
        variables=var_sharding,
        opt_state=opt_sharding,
        round_idx=repl,
        key=repl,
        residuals=residual_sharding,
    ), specs


def make_rule_round_fn(
    mesh,
    local_update,
    variables_template: PyTree,
    table: RuleTable = FEDLLM_RULES,
    *,
    server_update=None,
    aggregate_transform=None,
    opt_state_template: Optional[PyTree] = None,
    codec=None,
    error_feedback: bool = False,
    exact_aggregation: bool = True,
):
    """jit the FedAvg round on a ``(dp, mp)`` mesh with the packed
    client block over ``dp`` and the model laid out by ``table``.

    The rule-driven sibling of ``gspmd.make_dp_tp_round_fn``: same
    round function (``make_round_fn(client_axis_impl="vmap")``, no
    axis_name — GSPMD derives the cross-client reduce from the
    annotations), but the layout comes from the table instead of the
    transformer-only heuristic, and the in-engine compression path
    (``codec`` name or LeafCodec, plus ``error_feedback``) keeps its
    residual store sharded — client rows on ``dp``, param dims like
    the params.

    ``exact_aggregation`` (default on) makes the dp-sharded round
    BIT-identical to the single-device one: the per-client heavy
    compute stays sharded, but the cross-client weighted sum runs as
    a shard_map'd REPLICATED einsum (every device gathers the update
    stack and computes the full reduction locally, same shape → same
    kernel → same bits as one device) and the tiny ``[K]`` weight
    vectors stay replicated throughout.  Left to the GSPMD
    partitioner, the einsum may partial-sum the K axis per device —
    reassociating the fp32 reduction and breaking the sha256 parity
    pins (a with_sharding_constraint on the operand is NOT enough;
    the partitioner may still split the reduction).  Costs an
    all-gather of the update stack per round; set False at scale
    where allclose is enough.

    Returns ``(round_fn, shard_state, shard_data)``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedml_tpu.algorithms.fedavg import make_round_fn
    from fedml_tpu.compress import get_codec

    if isinstance(codec, str):
        codec = get_codec(codec)

    repl = NamedSharding(mesh, P())
    kwargs = {}
    if exact_aggregation:

        def exact_agg(w, cv):
            # sequential scan over the K axis, NOT einsum: a reduction's
            # accumulation strategy (lane splits, partial sums per
            # device, horizontal adds) is a partitioner/fusion decision,
            # so the "same" einsum can reassociate between the 1-device
            # and SPMD lowerings (measured on CPU host meshes).  The
            # scan carry chain is explicitly ordered, its xs interface
            # MATERIALIZES the weighted update stack (a while-loop
            # operand is a real buffer — fusions cannot duplicate the
            # decode chain past it with different contraction choices,
            # another measured 1-ulp source), and a sequential loop is
            # not partitionable, so GSPMD all-gathers the stack and
            # every device runs the identical full-K reduction.  A
            # shard_map(P() -> P()) wrapper is NOT equivalent: its
            # boundary changes the producer fusions and was measured to
            # break bit-parity where this form holds it.
            weighted = jax.tree_util.tree_map(
                lambda l: w.reshape((-1,) + (1,) * (l.ndim - 1))
                * l.astype(jnp.float32),
                cv,
            )

            def body(acc, row):
                return jax.tree_util.tree_map(jnp.add, acc, row), None

            zeros = jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape[1:], jnp.float32), cv
            )
            acc, _ = jax.lax.scan(body, zeros, weighted)
            return acc

        kwargs["aggregate_impl"] = exact_agg

    if server_update is not None:
        kwargs["server_update"] = server_update
    if codec is not None:
        kwargs["codec"] = codec
        kwargs["error_feedback"] = error_feedback
    inner = make_round_fn(
        local_update,
        aggregate_transform=aggregate_transform,
        client_axis_impl="vmap",
        **kwargs,
    )

    state_sharding, specs = server_state_sharding(
        mesh, variables_template, table,
        opt_state_template=opt_state_template,
        error_feedback=codec is not None and error_feedback,
    )
    validate_divisibility(variables_template, specs,
                          {k: int(v) for k, v in mesh.shape.items()})
    data_sharding = NamedSharding(mesh, P(DP_AXIS))
    # (x, y, mask) carry the client compute and shard over dp; the [K]
    # scalar vectors (num_samples, participation, slot_ids) stay
    # replicated in exact mode so weight products and their sums keep
    # single-device reduction order
    scalar_sharding = repl if exact_aggregation else data_sharding
    arg_shardings = (data_sharding, data_sharding, data_sharding,
                     scalar_sharding, scalar_sharding, scalar_sharding)

    def shard_state(state):
        return jax.device_put(state, state_sharding)

    def shard_data(arrays):
        return tuple(jax.device_put(np.asarray(a), s)
                     for a, s in zip(arrays, arg_shardings))

    round_fn = jit_sharded(
        inner,
        in_shardings=(state_sharding,) + arg_shardings,
        out_shardings=(state_sharding, repl),
        donate_argnums=(0,),
    )
    return round_fn, shard_state, shard_data


def cohort_shardings(mesh, variables_template: PyTree, table: RuleTable):
    """Sharding tuple for the muxed cohort engine's ONE jit step:
    broadcast variables by rules over ``mp``, every per-client stacked
    array (data rows, rng keys, the vmapped output tree and its metric
    dict) with the cohort axis on ``dp``.

    Returns ``(var_in, data, var_out, stacked)`` where ``stacked`` is
    the plain ``P("dp")`` sharding usable as a pytree prefix for the
    metrics dict.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    specs = match_partition_rules(table, variables_template)
    validate_divisibility(variables_template, specs,
                          {k: int(v) for k, v in mesh.shape.items()})
    var_in = named_sharding_tree(mesh, specs)
    stacked = NamedSharding(mesh, P(DP_AXIS))
    import jax

    var_out = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, P(DP_AXIS, *s)), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return var_in, stacked, var_out, stacked
