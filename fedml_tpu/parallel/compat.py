"""Version-tolerant jax API surface for the parallel/ package.

The repo targets the modern ``jax.shard_map`` entry point (typed-vma
era: ``check_vma=`` kwarg), but supported build environments pin back
to jax 0.4.x where the transform only exists as
``jax.experimental.shard_map.shard_map`` and the same knob is spelled
``check_rep=``.  Every shard_map call site in the package imports the
transform from HERE so the whole spmd/ring/pipeline/expert family runs
on either generation instead of dying with AttributeError at import.

The wrapper keeps the modern calling convention: pass ``check_vma=``
and it is forwarded verbatim on new jax and translated to
``check_rep=`` on old jax (the two knobs gate the same replication /
varying-manual-axes check, renamed across the migration).
"""

from __future__ import annotations

import jax

_NATIVE = getattr(jax, "shard_map", None)
if _NATIVE is None:
    from jax.experimental.shard_map import shard_map as _EXPERIMENTAL
else:
    _EXPERIMENTAL = None

# jax.enable_x64 was promoted out of jax.experimental on the same
# migration; core/mpc.py's finite-field arithmetic needs it under
# either name
enable_x64 = getattr(jax, "enable_x64", None)
if enable_x64 is None:
    from jax.experimental import enable_x64  # noqa: F401


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """``jax.shard_map`` on new jax, ``jax.experimental.shard_map`` on
    0.4.x — one modern signature for both (see module doc)."""
    if _NATIVE is not None:
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return _NATIVE(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kwargs)
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return _EXPERIMENTAL(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, **kwargs)
