"""One dp×mp device mesh over the federation: cohort rows on ``dp``,
model tensors on ``mp``.

Every other mesh in ``parallel/`` is special-cased to its consumer —
``spmd.make_1d_mesh`` (clients axis for shard_map rounds),
``gspmd.make_dp_tp_mesh`` (clients×model for the cross-silo round
engine).  This module is the user-facing knob: ONE ``--mesh dp,mp``
string parsed once and handed to the partition-rule engine
(``parallel/partition.py``), which lays the fedllm model over ``mp``
and the virtual-client cohort (the vmap axis of the PR-10 muxed
engine) over ``dp`` in the same jit step.

CPU host-mesh howto (no accelerator required): set
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` BEFORE the
first jax import and the host platform exposes 8 CpuDevices — enough
to pin sharded-vs-replicated byte identity (``tests/test_shard_rules``)
and exercise every collective the partitioner inserts.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

DP_AXIS = "dp"
MP_AXIS = "mp"

HOST_MESH_HINT = (
    "set XLA_FLAGS=--xla_force_host_platform_device_count=<n> before "
    "the first jax import to expose n host devices"
)


def parse_mesh_spec(
    spec: str, device_count: Optional[int] = None
) -> Tuple[int, int]:
    """Parse ``--mesh`` strings into ``(dp, mp)``.

    Accepted forms: ``"4,2"``, ``"dp=4,mp=2"`` (order-free), and
    ``"auto,2"`` / ``"-1,2"`` where the auto dimension absorbs every
    device the other doesn't claim.  At most one dimension may be
    auto.  ``device_count=None`` defers to ``jax.device_count()``.
    """
    parts = [p.strip() for p in str(spec).split(",") if p.strip()]
    if len(parts) != 2:
        raise ValueError(
            f"mesh spec {spec!r} must have exactly two dimensions "
            "(dp,mp), e.g. '8,1' or 'dp=8,mp=1'"
        )
    dims = {}
    for i, part in enumerate(parts):
        name = (DP_AXIS, MP_AXIS)[i]
        if "=" in part:
            name, _, part = part.partition("=")
            name = name.strip()
            part = part.strip()
            if name not in (DP_AXIS, MP_AXIS):
                raise ValueError(
                    f"mesh spec {spec!r}: unknown axis {name!r} "
                    f"(want {DP_AXIS}/{MP_AXIS})"
                )
        if name in dims:
            raise ValueError(f"mesh spec {spec!r} names {name!r} twice")
        if part in ("auto", "-1"):
            dims[name] = -1
        else:
            try:
                dims[name] = int(part)
            except ValueError:
                raise ValueError(
                    f"mesh spec {spec!r}: dimension {part!r} is not an "
                    "integer (or 'auto')"
                ) from None
    if DP_AXIS not in dims or MP_AXIS not in dims:
        raise ValueError(
            f"mesh spec {spec!r} must name both {DP_AXIS} and {MP_AXIS}"
        )
    dp, mp = dims[DP_AXIS], dims[MP_AXIS]
    if dp == -1 and mp == -1:
        raise ValueError(f"mesh spec {spec!r}: only one axis may be auto")
    if dp == -1 or mp == -1:
        if device_count is None:
            import jax

            device_count = jax.device_count()
        fixed = mp if dp == -1 else dp
        if fixed <= 0 or device_count % fixed:
            raise ValueError(
                f"mesh spec {spec!r}: {device_count} devices not "
                f"divisible by fixed axis {fixed}"
            )
        auto = device_count // fixed
        dp, mp = (auto, mp) if dp == -1 else (dp, auto)
    if dp <= 0 or mp <= 0:
        raise ValueError(f"mesh spec {spec!r}: axes must be positive")
    return dp, mp


def make_dp_mp_mesh(dp: int, mp: int, *, devices: Optional[Sequence] = None):
    """A ``Mesh`` with axes ``("dp", "mp")`` over the first dp*mp
    devices.  Raises loud — with the host-mesh hint — when the
    platform doesn't have enough."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices) if devices is not None else jax.devices()
    n = dp * mp
    if n > len(devices):
        raise ValueError(
            f"mesh {dp}x{mp} needs {n} devices, have {len(devices)} "
            f"({HOST_MESH_HINT})"
        )
    arr = np.array(devices[:n]).reshape(dp, mp)
    return Mesh(arr, axis_names=(DP_AXIS, MP_AXIS))


def mesh_from_spec(spec: str, *, devices: Optional[Sequence] = None):
    """``parse_mesh_spec`` + ``make_dp_mp_mesh`` in one call."""
    count = len(devices) if devices is not None else None
    dp, mp = parse_mesh_spec(spec, device_count=count)
    return make_dp_mp_mesh(dp, mp, devices=devices)


def describe_mesh(mesh) -> dict:
    """JSON-friendly summary for evidence files and logs."""
    return {
        "axes": {name: int(size) for name, size in mesh.shape.items()},
        "devices": int(mesh.devices.size),
        "platform": str(mesh.devices.flat[0].platform),
    }
