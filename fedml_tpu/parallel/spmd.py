"""SPMD execution of federated rounds over a device mesh.

This is the ComManager replacement the BASELINE.json north star names:
the reference's one-MPI-process-per-participant layout
(``FedAvgAPI.py:10-25`` + ``run_fedavg_distributed_pytorch.sh:19-23``)
becomes one SPMD program on a ``clients`` mesh axis.  Model sync is
replication (no explicit broadcast messages); upload + aggregate is a
masked weighted ``lax.psum``; subsampling is a collective mask.  A
``model`` axis is reserved in the mesh so tensor/pipeline extensions
don't force a redesign (SURVEY.md §2.6).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.algorithms.fedavg import ServerState, make_round_fn
from fedml_tpu.core.client import LocalUpdateFn

PyTree = Any


def make_1d_mesh(n_devices: Optional[int] = None, axis: str = "x") -> Mesh:
    """1-D mesh over the first n devices (shared by the tp/pp/sp/ep
    constructors)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} present"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def make_client_mesh(
    num_devices: Optional[int] = None, *, model_axis: int = 1, devices=None
) -> Mesh:
    """Mesh with a ``clients`` data axis and a reserved ``model`` axis."""
    devices = devices if devices is not None else jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    n = len(devices)
    assert n % model_axis == 0
    arr = np.array(devices).reshape(n // model_axis, model_axis)
    return Mesh(arr, axis_names=("clients", "model"))


def make_spmd_round_fn(
    mesh: Mesh,
    local_update: LocalUpdateFn,
    *,
    server_update=None,
    aggregate_transform=None,
    donate: bool = True,
):
    """shard_map the round over the ``clients`` mesh axis.

    Data layout: the packed client block [C, steps, B, ...] is sharded on
    its leading axis; each device vmaps over its local C/D clients, then
    the weighted tree-sums are psum'd across the axis.  Server state is
    fully replicated, so the returned new state is identical on every
    device — broadcast of the next round's model is free.
    """
    kwargs = {}
    if server_update is not None:
        kwargs["server_update"] = server_update
    inner = make_round_fn(
        local_update,
        aggregate_transform=aggregate_transform,
        axis_name="clients",
        **kwargs,
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(),  # state replicated
            P("clients"),  # x
            P("clients"),  # y
            P("clients"),  # mask
            P("clients"),  # num_samples
            P("clients"),  # participation
            P("clients"),  # global slot ids
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def spmd_round(state, x, y, mask, num_samples, participation, slot_ids):
        return inner(state, x, y, mask, num_samples, participation, slot_ids)

    return jax.jit(spmd_round, donate_argnums=(0,) if donate else ())


def shard_client_block(mesh: Mesh, pack_arrays):
    """device_put packed [C, ...] arrays sharded over the clients axis."""
    sharding = NamedSharding(mesh, P("clients"))
    return tuple(jax.device_put(jnp.asarray(a), sharding) for a in pack_arrays)


def replicate(mesh: Mesh, tree: PyTree) -> PyTree:
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)
