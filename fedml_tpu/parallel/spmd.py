"""SPMD execution of federated rounds over a device mesh.

This is the ComManager replacement the BASELINE.json north star names:
the reference's one-MPI-process-per-participant layout
(``FedAvgAPI.py:10-25`` + ``run_fedavg_distributed_pytorch.sh:19-23``)
becomes one SPMD program on a ``clients`` mesh axis.  Model sync is
replication (no explicit broadcast messages); upload + aggregate is a
masked weighted ``lax.psum``; subsampling is a collective mask.  A
``model`` axis is reserved in the mesh so tensor/pipeline extensions
don't force a redesign (SURVEY.md §2.6).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.parallel.compat import shard_map
from fedml_tpu.algorithms.fedavg import make_round_fn
from fedml_tpu.core.client import LocalUpdateFn

PyTree = Any


def make_1d_mesh(n_devices: Optional[int] = None, axis: str = "x") -> Mesh:
    """1-D mesh over the first n devices (shared by the tp/pp/sp/ep
    constructors)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} present"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def make_client_mesh(
    num_devices: Optional[int] = None, *, model_axis: int = 1, devices=None
) -> Mesh:
    """Mesh with a ``clients`` data axis and a reserved ``model`` axis."""
    devices = devices if devices is not None else jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    n = len(devices)
    assert n % model_axis == 0
    arr = np.array(devices).reshape(n // model_axis, model_axis)
    return Mesh(arr, axis_names=("clients", "model"))


def make_spmd_round_fn(
    mesh: Mesh,
    local_update: LocalUpdateFn,
    *,
    server_update=None,
    aggregate_transform=None,
    donate: bool = True,
):
    """shard_map the round over the ``clients`` mesh axis.

    Data layout: the packed client block [C, steps, B, ...] is sharded on
    its leading axis; each device vmaps over its local C/D clients, then
    the weighted tree-sums are psum'd across the axis.  Server state is
    fully replicated, so the returned new state is identical on every
    device — broadcast of the next round's model is free.
    """
    kwargs = {}
    if server_update is not None:
        kwargs["server_update"] = server_update
    inner = make_round_fn(
        local_update,
        aggregate_transform=aggregate_transform,
        axis_name="clients",
        **kwargs,
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(),  # state replicated
            P("clients"),  # x
            P("clients"),  # y
            P("clients"),  # mask
            P("clients"),  # num_samples
            P("clients"),  # participation
            P("clients"),  # global slot ids
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def spmd_round(state, x, y, mask, num_samples, participation, slot_ids):
        return inner(state, x, y, mask, num_samples, participation, slot_ids)

    return jax.jit(spmd_round, donate_argnums=(0,) if donate else ())


def shard_client_block(mesh: Mesh, pack_arrays):
    """device_put packed [C, ...] arrays sharded over the clients axis."""
    sharding = NamedSharding(mesh, P("clients"))
    return tuple(jax.device_put(jnp.asarray(a), sharding) for a in pack_arrays)


def _devices_by_clients_index(mesh: Mesh):
    """mesh.devices grouped by clients-axis index, regardless of where
    the ``clients`` axis sits in ``mesh.axis_names`` (positional
    ``mesh.devices[i]`` would silently walk the wrong axis for a
    ('model', 'clients') mesh)."""
    ax = mesh.axis_names.index("clients")
    moved = np.moveaxis(np.asarray(mesh.devices), ax, 0)
    return [list(moved[i].flat) for i in range(moved.shape[0])]


def host_client_range(
    mesh: Mesh,
    num_slots: int,
    *,
    process_index: Optional[int] = None,
    host_of_device=None,
) -> range:
    """The contiguous client-slot range owned by this host's devices.

    Under ``NamedSharding(mesh, P("clients"))`` slot ``k`` lives on the
    devices at clients-axis index ``k // (num_slots / n_clients_axis)``.
    A host's slots are the union over its devices — the per-rank
    partition of the reference's distributed loaders
    (``cifar10/data_loader.py:201-233``), derived from the mesh instead
    of an MPI rank argument.

    ``host_of_device`` maps a device to its host id (default: the real
    ``device.process_index``); tests inject a fake mapping to simulate a
    multi-host pod on a single-process CPU mesh.
    """
    if host_of_device is None:
        host_of_device = lambda d: d.process_index  # noqa: E731
    if process_index is None:
        process_index = jax.process_index()
    n_cl = mesh.shape["clients"]
    if num_slots % n_cl:
        raise ValueError(f"{num_slots} slots not divisible by clients axis {n_cl}")
    block = num_slots // n_cl
    dev_rows = _devices_by_clients_index(mesh)
    mine = [
        i
        for i in range(n_cl)
        if any(host_of_device(d) == process_index for d in dev_rows[i])
    ]
    if not mine:
        return range(0)
    lo, hi = min(mine), max(mine)
    if mine != list(range(lo, hi + 1)):
        raise ValueError(
            "host's devices are not contiguous along the clients axis; "
            "reorder the mesh so each host owns one slot range"
        )
    return range(lo * block, (hi + 1) * block)


def shard_client_block_local(
    mesh: Mesh,
    num_slots: int,
    shards_by_slot_start,
):
    """Assemble globally-sharded [C, ...] arrays from per-host blocks.

    ``shards_by_slot_start`` maps a slot start to the tuple of host
    arrays covering a contiguous slot range (each host contributes the
    range from its ``host_client_range`` and NEVER materializes the
    rest).  The global ``jax.Array`` is built with
    ``jax.make_array_from_single_device_arrays``, whose contract is
    exactly this: every process supplies only its addressable shards.
    (A single-process test passes all ranges, split across simulated
    hosts upstream.)
    """
    sharding = NamedSharding(mesh, P("clients"))
    n_cl = mesh.shape["clients"]
    block = num_slots // n_cl
    if not shards_by_slot_start:
        # A host whose devices are outside this mesh owns no slot range
        # (host_client_range -> range(0)) — but such a host also has no
        # addressable shards here and cannot legally participate in a
        # computation over this mesh at all; assembling from it is a
        # caller bug, not a degenerate case to paper over.
        raise ValueError(
            "no slot ranges supplied; a host with host_client_range() == "
            "range(0) has no devices in this mesh and must not join its "
            "computations"
        )
    n_arrays = len(next(iter(shards_by_slot_start.values())))
    # slot start -> (host array tuple, offset of that device block inside it)
    covering = {}
    for start, arrays in shards_by_slot_start.items():
        rows = np.asarray(arrays[0]).shape[0]
        if start % block or rows % block:
            raise ValueError(
                f"range [{start}, {start + rows}) is not aligned to the "
                f"per-device block of {block} slots"
            )
        for i in range(start // block, (start + rows) // block):
            covering[i * block] = (arrays, i * block - start)
    dev_rows = _devices_by_clients_index(mesh)
    out = []
    for j in range(n_arrays):
        buffers = []
        sample = None
        for i in range(n_cl):
            entry = covering.get(i * block)
            if entry is None:
                continue  # another host's range (its process supplies it)
            arrays, off = entry
            piece = jnp.asarray(np.asarray(arrays[j])[off : off + block])
            sample = piece
            for d in dev_rows[i]:
                buffers.append(jax.device_put(piece, d))
        global_shape = (num_slots,) + tuple(sample.shape[1:])
        out.append(
            jax.make_array_from_single_device_arrays(
                global_shape, sharding, buffers
            )
        )
    return tuple(out)


def replicate(mesh: Mesh, tree: PyTree) -> PyTree:
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


# ---------------------------------------------------------------------------
# Hierarchical (two-tier) FL on a nested (group, clients) mesh
# ---------------------------------------------------------------------------


def make_group_mesh(num_groups: int, n_devices: Optional[int] = None) -> Mesh:
    """Nested mesh for two-tier FL: ``group`` (slow axis — slices/DCN)
    × ``clients`` (fast axis — chips within a slice/ICI)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if n % num_groups:
        raise ValueError(f"{n} devices not divisible into {num_groups} groups")
    arr = np.array(devices).reshape(num_groups, n // num_groups)
    return Mesh(arr, axis_names=("group", "clients"))


def hierarchical_pack(dataset, groups, batch_size, steps_per_epoch, seed):
    """Stack per-group device-resident packs into one [G*C, ...] block
    in group-major order (the ``P(("group", "clients"))`` layout), plus
    the matching global slot ids.  Uses the exact per-group pack the
    host simulation builds (``HierarchicalSimulation._group_pack``), so
    the SPMD program sees bit-identical client shards."""
    from fedml_tpu.core.types import device_resident_pack

    sizes = {g: len(ids) for g, ids in groups.items()}
    if len(set(sizes.values())) != 1:
        raise ValueError(
            f"nested-mesh hierarchical FL needs equal group sizes, got "
            f"{sizes}; pad the grouping or drop stragglers"
        )
    blocks, all_ids = [], []
    for g in sorted(groups):
        ids = np.asarray(groups[g])
        args, _ = device_resident_pack(
            dataset, ids, batch_size, steps_per_epoch=steps_per_epoch,
            seed=seed,
        )
        blocks.append(args)
        all_ids.append(ids)
    stacked = tuple(
        jnp.concatenate([jnp.asarray(b[i]) for b in blocks], axis=0)
        for i in range(len(blocks[0]))
    )
    return stacked, np.concatenate(all_ids)


def make_hierarchical_spmd_round_fn(
    mesh: Mesh,
    local_update: LocalUpdateFn,
    *,
    group_comm_round: int,
    server_update=None,
    aggregate_transform=None,
):
    """One GLOBAL hierarchical round as ONE shard_map program on a
    (``group``, ``clients``) mesh — the SURVEY §2.6 mapping the host
    simulation (``algorithms/hierarchical.py``) documents: every group
    starts from the global model, runs ``group_comm_round`` in-group
    FedAvg rounds whose aggregation is a masked-psum over the
    ``clients`` axis ONLY (intra-slice, rides ICI), and the global tier
    is one sample-weighted psum over the ``group`` axis (inter-slice,
    rides DCN) at the end.  Reference semantics:
    ``standalone/hierarchical_fl/trainer.py:43-69`` +
    ``group.py:24-46``.

    Parity contract (certified in the driver dryrun and
    ``tests/test_spmd.py``): with data laid out by ``hierarchical_pack``
    this program's output equals ``HierarchicalSimulation.run_round``
    exactly — same per-group key schedule
    (``fold_in(state.key, 1000 + g)``), same in-group round_idx base
    (``round_idx * group_comm_round``), same group weights (the group's
    total sample count).
    """
    kwargs = {}
    if server_update is not None:
        kwargs["server_update"] = server_update
    inner = make_round_fn(
        local_update,
        aggregate_transform=aggregate_transform,
        axis_name="clients",
        **kwargs,
    )
    from fedml_tpu.algorithms.fedavg import ServerState

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(),                      # state replicated
            P(("group", "clients")),  # x   [G*C, steps, B, ...]
            P(("group", "clients")),  # y
            P(("group", "clients")),  # mask
            P(("group", "clients")),  # num_samples
            P(("group", "clients")),  # participation
            P(("group", "clients")),  # global slot ids
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def hier_round(state, x, y, mask, num_samples, participation, slot_ids):
        g = jax.lax.axis_index("group")
        gstate = ServerState(
            variables=state.variables,
            opt_state=state.opt_state,
            round_idx=state.round_idx * group_comm_round,
            key=jax.random.fold_in(state.key, 1000 + g),
        )

        def in_group_round(gs, _):
            return inner(gs, x, y, mask, num_samples, participation,
                         slot_ids)

        gstate, ms = jax.lax.scan(
            in_group_round, gstate, None, length=group_comm_round
        )
        # global tier: group models weighted by the group's TOTAL sample
        # count (reference group.py aggregates over the whole group)
        group_total = jax.lax.psum(num_samples.sum(), "clients")
        num = jax.tree_util.tree_map(
            lambda leaf: jax.lax.psum(
                group_total * leaf.astype(jnp.float32), "group"
            ),
            gstate.variables,
        )
        den = jax.lax.psum(group_total, "group")
        new_vars = jax.tree_util.tree_map(
            lambda s, ref: (s / jnp.maximum(den, 1e-12)).astype(ref.dtype),
            num,
            state.variables,
        )
        # host parity: metrics accumulate over EVERY in-group round of
        # every group (inner already psums across clients)
        metrics = {k: jax.lax.psum(v.sum(), "group") for k, v in ms.items()}
        new_state = ServerState(
            variables=new_vars,
            opt_state=state.opt_state,
            round_idx=state.round_idx + 1,
            key=state.key,
        )
        return new_state, metrics

    return jax.jit(hier_round)
