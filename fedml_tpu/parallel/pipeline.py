"""Pipeline parallelism: GPipe-style microbatched stage pipeline over a
``pp`` mesh axis.

The reference's only inter-layer model split is SplitNN's 2-stage
client/server relay, which crosses a PROCESS boundary twice per
mini-batch (``split_nn/client.py:24-34``, ``server.py:40-59`` — SURVEY.md
§3.3 calls it the latency-critical pattern).  Here the generalization to
S stages runs as ONE compiled SPMD program: each device owns one stage's
parameters, activations rotate stage→stage+1 with ``lax.ppermute`` on
the ICI ring, and microbatches keep every stage busy outside the
fill/drain bubble.  The schedule is the standard masked-tick loop:
at tick t, stage s computes microbatch (t − s); invalid ticks are
bubbles masked with ``jnp.where`` (no data-dependent control flow, so
XLA compiles a single static loop).

Differentiable end-to-end: ``ppermute``'s transpose is the reverse
permute, so ``jax.grad`` through ``apply`` yields per-stage parameter
gradients — pipeline-parallel training, not just inference.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.parallel.compat import shard_map

PyTree = Any

# stage_fn(stage_params, x[B, ...]) -> y[B, ...]  (same activation shape
# across stage boundaries, as in equal-depth transformer stages)
StageFn = Callable[[PyTree, jax.Array], jax.Array]


def make_pp_mesh(n_devices: Optional[int] = None, axis: str = "pp") -> Mesh:
    from fedml_tpu.parallel.spmd import make_1d_mesh

    return make_1d_mesh(n_devices, axis)


def stack_stage_params(stage_params_list) -> PyTree:
    """Stack S per-stage param pytrees along a new leading axis (the axis
    ``shard_stage_params`` lays out one-stage-per-device)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *stage_params_list
    )


def shard_stage_params(mesh: Mesh, stacked: PyTree, axis: str = "pp") -> PyTree:
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda v: jax.device_put(v, sharding), stacked
    )


def make_gpipe(mesh: Mesh, stage_fn: StageFn, axis: str = "pp"):
    """Build ``apply(stacked_stage_params, x_microbatches)``.

    - ``stacked_stage_params``: leaves [S, ...], sharded one stage per
      device on ``axis`` (see ``stack_stage_params``/``shard_stage_params``).
    - ``x_microbatches``: [M, B, ...] replicated; M microbatches.
    Returns y [M, B, ...] (replicated), equal to running the S stages
    sequentially over each microbatch.
    """
    S = mesh.shape[axis]

    def local(params_local, x):
        sid = lax.axis_index(axis)
        p = jax.tree_util.tree_map(lambda a: a[0], params_local)
        M = x.shape[0]
        ticks = M + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            recv, out = carry
            # stage 0 injects fresh microbatch t; others consume what
            # stage s-1 computed last tick
            x_t = lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, M - 1), keepdims=False
            )
            inp = jnp.where(sid == 0, x_t, recv)
            y = stage_fn(p, inp)
            nxt = lax.ppermute(y, axis, perm)
            # last stage emits microbatch t-(S-1) once it's valid
            out_idx = t - (S - 1)
            valid = (sid == S - 1) & (out_idx >= 0)
            oi = jnp.clip(out_idx, 0, M - 1)
            emitted = lax.dynamic_update_index_in_dim(out, y, oi, 0)
            out = jnp.where(valid, emitted, out)
            return (nxt, out), None

        init = (jnp.zeros(x.shape[1:], x.dtype), jnp.zeros_like(x))
        (_, out), _ = lax.scan(tick, init, jnp.arange(ticks))
        # outputs live on the last stage only; psum-broadcast to all
        out = lax.psum(
            jnp.where(sid == S - 1, out, jnp.zeros_like(out)), axis
        )
        return out

    sharded = shard_map(
        local, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
        check_vma=False,
    )
    jitted = jax.jit(sharded)

    def apply(stacked_stage_params, x_microbatches):
        n_stages = jax.tree_util.tree_leaves(stacked_stage_params)[0].shape[0]
        if n_stages != S:
            # P(axis) would silently hand each device a multi-stage shard
            # of which only [0] runs — wrong results, no error
            raise ValueError(
                f"stacked stage count {n_stages} != pp mesh size {S}; "
                "one stage per device is required"
            )
        return jitted(stacked_stage_params, x_microbatches)

    return apply


def serial_reference(stage_fn: StageFn, stacked: PyTree, x: jax.Array):
    """Run the same stages sequentially (the correctness oracle)."""
    S = jax.tree_util.tree_leaves(stacked)[0].shape[0]

    def one_mb(xb):
        h = xb
        for s in range(S):
            p = jax.tree_util.tree_map(lambda a: a[s], stacked)
            h = stage_fn(p, h)
        return h

    return jax.vmap(one_mb)(x)
