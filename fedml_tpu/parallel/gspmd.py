"""DP×TP federated rounds on a 2-D (clients, model) mesh via GSPMD.

The shard_map round (``parallel/spmd.py``) keeps server state fully
replicated — right for the small-model FL matrix, impossible for models
that don't fit one chip.  This module runs the SAME round function
(``algorithms.fedavg.make_round_fn``) under plain ``jit`` with sharding
annotations instead: the packed client block is sharded over the
``clients`` axis, the transformer parameters over the ``model`` axis
(Megatron column/row plan from ``parallel/tensor.py``), and the GSPMD
partitioner derives every collective — client-parallel local scans,
tensor-sharded matmuls inside each client's forward/backward, and the
cross-client weighted aggregation — from those annotations alone.

This is the cross-silo "federated fine-tuning of a model bigger than
one chip" capability; the reference's process-per-client MPI design has
no analogue (SURVEY.md §2.6).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.algorithms.fedavg import ServerState, make_round_fn
from fedml_tpu.core.client import LocalUpdateFn
from fedml_tpu.parallel.tensor import tp_param_spec

PyTree = Any


def make_dp_tp_mesh(
    n_clients_axis: int, n_model_axis: int, *, devices=None
) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = n_clients_axis * n_model_axis
    if n > len(devices):
        raise ValueError(
            f"mesh {n_clients_axis}x{n_model_axis} needs {n} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(devices[:n]).reshape(n_clients_axis, n_model_axis)
    return Mesh(arr, axis_names=("clients", "model"))


def opt_state_sharding_like(
    mesh: Mesh,
    variables_template: PyTree,
    opt_state_template: PyTree,
    axis: str = "model",
    *,
    pspec: Optional[PyTree] = None,
) -> PyTree:
    """Sharding tree for server-optimizer state whose leaves mirror the
    parameters (FedAdam/FedYogi moments): each opt leaf with the shape
    of some param leaf inherits that param's TP spec; everything else
    (counts, scalars) is replicated.  Shape-based matching is a
    heuristic — two same-shaped params with different specs resolve to
    whichever appears first, which only changes layout, not values.

    ``pspec`` overrides the param spec tree (the partition-rule engine
    in ``parallel/partition.py`` passes its rule-derived specs here);
    the default keeps the transformer TP heuristic."""
    if pspec is None:
        pspec = tp_param_spec(variables_template, axis)
    shape_to_spec = {}
    for leaf, spec in zip(
        jax.tree_util.tree_leaves(variables_template),
        jax.tree_util.tree_leaves(pspec, is_leaf=lambda x: isinstance(x, P)),
    ):
        shape_to_spec.setdefault(np.shape(leaf), spec)
    return jax.tree_util.tree_map(
        lambda l: NamedSharding(mesh, shape_to_spec.get(np.shape(l), P())),
        opt_state_template,
    )


def make_dp_tp_round_fn(
    mesh: Mesh,
    local_update: LocalUpdateFn,
    variables_template: PyTree,
    *,
    server_update=None,
    aggregate_transform=None,
    opt_state_sharding: Optional[PyTree] = None,
):
    """jit the FedAvg round with data over ``clients`` and transformer
    params over ``model``.

    ``variables_template`` (an unsharded init) fixes the param sharding
    plan.  Returns (round_fn, shard_state, shard_data):
    ``shard_state(state)`` lays server state out on the mesh;
    ``shard_data(arrays)`` shards the packed client block.  The returned
    state from ``round_fn`` keeps the same shardings (donated input).

    When a ``server_update`` carries parameter-sized optimizer state
    (FedAdam moments), pass ``opt_state_sharding`` (see
    ``opt_state_sharding_like``) — the default replicates opt_state,
    which would defeat the bigger-than-one-chip purpose for such state.
    """
    kwargs = {}
    if server_update is not None:
        kwargs["server_update"] = server_update
    # no axis_name: aggregation is the einsum over the packed K axis —
    # GSPMD partitions it over `clients` and inserts the reduce itself.
    # vmap (not lax.map) over the client axis so the partitioner can
    # split the K dim across the mesh instead of serializing it.
    inner = make_round_fn(
        local_update,
        aggregate_transform=aggregate_transform,
        client_axis_impl="vmap",
        **kwargs,
    )

    pspec = tp_param_spec(variables_template, axis="model")
    var_sharding = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), pspec
    )
    repl = NamedSharding(mesh, P())
    data_sharding = NamedSharding(mesh, P("clients"))

    state_sharding = ServerState(
        variables=var_sharding,
        opt_state=opt_state_sharding if opt_state_sharding is not None else repl,
        round_idx=repl,
        key=repl,
    )

    def shard_state(state: ServerState) -> ServerState:
        return jax.device_put(state, state_sharding)

    def shard_data(arrays):
        return tuple(jax.device_put(np.asarray(a), data_sharding)
                     for a in arrays)

    round_fn = jax.jit(
        inner,
        in_shardings=(state_sharding, data_sharding, data_sharding,
                      data_sharding, data_sharding, data_sharding,
                      data_sharding),
        out_shardings=(state_sharding, repl),
        donate_argnums=(0,),
    )
    return round_fn, shard_state, shard_data
