"""Masked loss / metric functions.

Padding-by-wrapping (core.types.pack_clients) means every batch may
contain duplicate "pad" samples; all losses here take a ``mask`` and
normalize by the real-sample count so padded slots contribute exactly
zero gradient and zero metric weight.  This replaces the reference's
reliance on torch DataLoader ragged last batches
(``MyModelTrainer.py:44-52``).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

# A LossFn maps (logits, targets, mask) -> (mean_loss, aux_metrics)
LossFn = Callable[[jax.Array, jax.Array, jax.Array], Tuple[jax.Array, dict]]


def softmax_ce_logits(logits: jax.Array, y: jax.Array) -> jax.Array:
    """Per-example cross-entropy with integer targets (no mask) — the
    plain ``nn.CrossEntropyLoss`` used where batches are full-shape
    (SplitNN server, ``split_nn/server.py:21``)."""
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), y.astype(jnp.int32)
    )


def masked_softmax_ce(logits: jax.Array, y: jax.Array, mask: jax.Array):
    """Cross-entropy with integer targets; mean over mask.

    Handles both [B, C] classification and [B, T, C] sequence shapes
    (Shakespeare/StackOverflow next-token tasks); for sequences the mask
    is broadcast over time unless given per-token.
    """
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, y[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if nll.ndim > mask.ndim:
        mask = jnp.broadcast_to(mask[..., None], nll.shape)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    pred = jnp.argmax(logits, axis=-1)
    correct = ((pred == y) * mask).sum()
    return loss, {"loss_sum": (nll * mask).sum(), "correct": correct, "count": mask.sum()}


def _bce_elements(logits: jax.Array, yf: jax.Array) -> jax.Array:
    """Numerically stable per-element BCE-with-logits."""
    return (
        jnp.maximum(logits, 0) - logits * yf
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def masked_bce_logits(logits: jax.Array, y: jax.Array, mask: jax.Array):
    """Binary cross-entropy on logits (VFL / lending-club binary tasks)."""
    logits = logits.astype(jnp.float32).reshape(y.shape)
    yf = y.astype(jnp.float32)
    per = _bce_elements(logits, yf)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (per * mask).sum() / denom
    pred = (logits > 0).astype(yf.dtype)
    correct = ((pred == yf) * mask).sum()
    return loss, {"loss_sum": (per * mask).sum(), "correct": correct, "count": mask.sum()}


def masked_multilabel_bce(logits: jax.Array, y: jax.Array, mask: jax.Array):
    """Multi-label tag prediction: per-sample BCE summed over the label
    axis, plus the reference's exact-match / precision / recall metrics
    (``standalone/fedavg/my_model_trainer_tag_prediction.py:24,54-96``:
    ``nn.BCELoss(reduction='sum')`` on sigmoid outputs; ``predicted =
    (pred > .5)``; "correct" counts samples whose ENTIRE tag vector
    matches).

    Shapes: logits [B, C] (or [..., C]), y multi-hot [..., C] float,
    mask [...] per-sample.  Loss = masked MEAN over samples of the
    per-sample label-summed BCE.

    Deliberate deviation from the reference TRAINING objective: the
    reference optimizes the raw ``reduction='sum'`` value, so its
    gradient scales with the per-client batch/sample count and its
    published stackoverflow_lr lr is tuned to that scale.  Here the loss
    is the per-sample mean (count-invariant gradients — the convention
    every other loss in this module follows, and the one that keeps one
    lr meaningful across heterogeneous client sizes).  Reference lr
    values for this task must be rescaled by the per-client batch size
    (lr_here ≈ lr_ref × batch_size); the sum is still reported as
    ``loss_sum`` so METRICS match the reference exactly.  See
    PARITY.md §losses.
    """
    logits = logits.astype(jnp.float32).reshape(y.shape)
    yf = y.astype(jnp.float32)
    per = _bce_elements(logits, yf).sum(axis=-1)  # BCELoss(sum) per sample
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (per * mask).sum() / denom
    pred = (logits > 0.0).astype(jnp.float32)  # sigmoid(z) > .5  ⇔  z > 0
    exact = jnp.all(pred == yf, axis=-1).astype(jnp.float32)
    tp = (yf * pred).sum(axis=-1)
    precision = tp / (pred.sum(axis=-1) + 1e-13)
    recall = tp / (yf.sum(axis=-1) + 1e-13)
    return loss, {
        "loss_sum": (per * mask).sum(),
        "correct": (exact * mask).sum(),
        "count": mask.sum(),
        "precision_sum": (precision * mask).sum(),
        "recall_sum": (recall * mask).sum(),
    }


def masked_kd_kl(
    student_logits: jax.Array,
    teacher_logits: jax.Array,
    mask: jax.Array,
    temperature: float = 3.0,
) -> jax.Array:
    """Knowledge-distillation KL with temperature, mean over mask.

    Matches the reference's ``KL_Loss`` (``fedgkt/utils.py``):
    ``T² · KL(softmax(teacher/T) ‖ softmax(student/T))``.
    """
    t = temperature
    logp_s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t, axis=-1)
    p_t = jax.nn.softmax(teacher_logits.astype(jnp.float32) / t, axis=-1)
    logp_t = jax.nn.log_softmax(teacher_logits.astype(jnp.float32) / t, axis=-1)
    per = (p_t * (logp_t - logp_s)).sum(axis=-1) * (t * t)
    denom = jnp.maximum(mask.sum(), 1.0)
    return (per * mask).sum() / denom


def masked_mse(preds: jax.Array, y: jax.Array, mask: jax.Array):
    preds = preds.astype(jnp.float32).reshape(y.shape)
    per = jnp.square(preds - y.astype(jnp.float32))
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (per * mask).sum() / denom
    return loss, {"loss_sum": (per * mask).sum(), "correct": jnp.zeros(()), "count": mask.sum()}
