"""Staleness-weight math for the async buffered server — np|jnp
polymorphic, one copy for every execution mode.

The synchronous barrier's stale firewall (``FedAvgServerManager
._is_stale``) REJECTS any upload whose echoed round is not the current
one.  FedBuff-style async aggregation keeps honest late work instead:
an upload computed against base round ``b`` folding into round ``r``
is discounted by ``w(r - b)`` — down-weighted, not discarded — with
the reject firewall retained as the hard outer bound
(``--max-staleness``).

Like ``core/robust.py``, every function here is a pure formula over
whichever array namespace the caller passes (``xp=np`` on the server's
host fold path, ``xp=jnp`` inside a jitted transform), so the server
and any compiled twin compute the SAME weight from the same delta and
tests can pin the two against one numpy oracle.

Exactness contract: ``w(0) == 1.0`` for every policy, and the
``w == 1.0`` fast path multiplies by a float64 ``1.0`` — fp-exact —
which is what lets the async-vs-sync byte-identity pin hold when all
arrivals are current (the equivalence anchor every mode change ships).
"""

from __future__ import annotations

import jax.numpy as jnp

# staleness-weight policies the server accepts (--stale-policy):
# - poly: w(d) = (1 + d)^-alpha — the FedBuff/FedAsync polynomial
#   family; alpha=0 degenerates to w≡1 (the byte-identity arm)
# - const: w(d) = 1 inside the window, 0 beyond it — a hard
#   constant-window cut that still FOLDS in-window stragglers at full
#   weight (the reject firewall handled out-of-window ones upstream)
STALENESS_POLICIES = ("poly", "const")


def staleness_weight(delta, policy: str = "poly", *, alpha: float = 0.5,
                     window: int = 0, xp=jnp):
    """Discount weight for an upload ``delta`` rounds stale.

    ``delta`` may be a scalar or an array of round gaps (``r - b``);
    negative deltas (an upload from the future — unreachable past the
    reject firewall) clamp to 0.  Returns values in [0, 1] with
    ``w(0) == 1.0`` exactly.
    """
    if policy not in STALENESS_POLICIES:
        raise ValueError(
            f"unknown staleness policy {policy!r} "
            f"(one of {STALENESS_POLICIES})"
        )
    d = xp.maximum(xp.asarray(delta, xp.float64), 0.0)
    if policy == "const":
        return xp.where(d <= float(window), 1.0, 0.0)
    if alpha < 0:
        raise ValueError(f"poly staleness alpha must be >= 0: {alpha!r}")
    # (1 + d)^-alpha; alpha == 0 gives exactly 1.0 for every delta
    # (x**0 == 1.0 in IEEE 754), so the w≡1 anchor needs no branch
    return (1.0 + d) ** (-float(alpha))


def effective_weight(n, delta, policy: str = "poly", *, alpha: float = 0.5,
                     window: int = 0, xp=jnp):
    """The fold weight the streaming accumulator uses: ``w(delta) * n``.

    Exactness: at ``delta == 0`` (or ``w == 1.0``) the product is
    ``1.0 * n`` — fp-exact, so a run whose arrivals are all current
    folds the IDENTICAL float64 weights the synchronous barrier folds.
    """
    w = staleness_weight(delta, policy, alpha=alpha, window=window, xp=xp)
    return w * xp.asarray(n, xp.float64)
