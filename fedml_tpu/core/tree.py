"""Pytree utilities: the TPU replacement for per-key python loops.

The reference's server aggregation iterates over ``state_dict`` keys in
Python (``FedAVGAggregator.py:72-80``); here every whole-model operation
is a single ``jax.tree_util.tree_map`` so XLA sees one fused program —
O(1) dispatches regardless of model depth (SURVEY.md §7 design table).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

PyTree = Any


def tree_zeros_like(tree: PyTree) -> PyTree:
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def tree_add(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree_util.tree_map(jnp.add, a, b)


def tree_sub(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree_util.tree_map(jnp.subtract, a, b)


def tree_scale(tree: PyTree, s) -> PyTree:
    return jax.tree_util.tree_map(lambda x: x * s, tree)


def tree_weighted_sum(trees: Sequence[PyTree], weights) -> PyTree:
    """sum_i w_i * tree_i  (host-side list version, used by inproc backend)."""
    acc = tree_scale(trees[0], weights[0])
    for t, w in zip(trees[1:], weights[1:]):
        acc = jax.tree_util.tree_map(lambda a, x, w=w: a + x * w, acc, t)
    return acc


def tree_fold_weighted(acc: PyTree, tree: PyTree, w) -> PyTree:
    """One step of a streaming weighted sum: ``acc + w * tree`` per
    leaf, accumulated host-side in float64 (``acc=None`` starts a new
    accumulator).  This is the cross-device server's O(model)-memory
    aggregation primitive: uploads fold in as they ARRIVE instead of
    being buffered until the round closes.  Numpy (not jnp) on purpose:
    the fold runs under the server's round lock on the backend reader
    thread, and a host memcpy-bound add must not pay a device dispatch."""
    import numpy as np

    w64 = np.float64(w)
    if acc is None:
        return jax.tree_util.tree_map(
            lambda x: w64 * np.asarray(x, np.float64), tree
        )
    return jax.tree_util.tree_map(
        lambda a, x: a + w64 * np.asarray(x, np.float64), acc, tree
    )


def tree_finalize_weighted_mean(acc: PyTree, total, like: PyTree) -> PyTree:
    """Close a ``tree_fold_weighted`` accumulator: ``acc / total`` cast
    back to each leaf dtype of ``like`` (the model template)."""
    import numpy as np

    t64 = np.float64(total)
    return jax.tree_util.tree_map(
        lambda a, l: (a / t64).astype(np.asarray(l).dtype), acc, like
    )


def tree_weighted_mean(trees: Sequence[PyTree], weights) -> PyTree:
    """Buffered reference for the streaming pair above: fold every tree
    with its RAW weight, then normalize by ``sum(weights)``.  Same ops
    in the same order as the per-arrival fold, so a streaming server is
    bit-identical to this — the leaf-exactness pin in tests/test_comm."""
    acc = None
    for t, w in zip(trees, weights):
        acc = tree_fold_weighted(acc, t, w)
    return tree_finalize_weighted_mean(acc, sum(float(w) for w in weights),
                                       trees[0])


def tree_vdot(a: PyTree, b: PyTree) -> jax.Array:
    leaves = jax.tree_util.tree_map(
        lambda x, y: jnp.vdot(x.astype(jnp.float32), y.astype(jnp.float32)), a, b
    )
    return jax.tree_util.tree_reduce(jnp.add, leaves)


def tree_sq_norm(tree: PyTree) -> jax.Array:
    leaves = jax.tree_util.tree_map(
        lambda x: jnp.sum(jnp.square(x.astype(jnp.float32))), tree
    )
    return jax.tree_util.tree_reduce(jnp.add, leaves)


def tree_norm(tree: PyTree) -> jax.Array:
    return jnp.sqrt(tree_sq_norm(tree))


def tree_size(tree: PyTree) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def tree_ravel(tree: PyTree) -> jax.Array:
    """Flatten a pytree to one 1-D vector (robust aggregation, MPC codecs)."""
    return jnp.concatenate(
        [jnp.ravel(x).astype(jnp.float32) for x in jax.tree_util.tree_leaves(tree)]
    )


def tree_unravel(tree_like: PyTree, vec: jax.Array) -> PyTree:
    leaves, treedef = jax.tree_util.tree_flatten(tree_like)
    out, off = [], 0
    for leaf in leaves:
        n = leaf.size
        out.append(jnp.reshape(vec[off : off + n], leaf.shape).astype(leaf.dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def tree_cast(tree: PyTree, dtype) -> PyTree:
    return jax.tree_util.tree_map(lambda x: x.astype(dtype), tree)


def tree_cast_floats(tree: PyTree, dtype) -> PyTree:
    """Cast only floating-point leaves (mixed-precision compute casts;
    integer leaves such as token ids / step counters pass through)."""
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree,
    )


def tree_cast_like(tree: PyTree, ref: PyTree) -> PyTree:
    """Cast every leaf of ``tree`` to the dtype of the same leaf in ``ref``
    (restores master dtypes after a low-precision forward pass)."""
    return jax.tree_util.tree_map(lambda x, r: x.astype(r.dtype), tree, ref)


def tree_stack(trees: Sequence[PyTree]) -> PyTree:
    """Stack a list of identically-shaped pytrees along a new axis 0."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def tree_index(tree: PyTree, i) -> PyTree:
    """Take slice i along axis 0 of every leaf."""
    return jax.tree_util.tree_map(lambda x: x[i], tree)
