"""Structured metrics, named timing spans, and profiler hooks.

The reference logs manual wall-clock spans to wandb/python-logging
scattered through the code (SURVEY.md §5.1/§5.5: aggregate time
``FedAVGAggregator.py:59,85-86``, message send span
``FedAvgServerManager.py:93-102``, client compute time
``MyModelTrainer.py:42,66-71``, round wall-clock
``FedAVGAggregator.py:100-101,154``).  Here one sink owns all of it:

- ``MetricsLogger``: ``log(dict)`` → JSON-lines file + python logging
  + optional wandb, with the standard keys (round/epoch/spans).
- ``span(name)``: context manager producing the same named spans as the
  reference (``time_aggregate``, ``time_round``, ...).
- ``trace(dir)``: ``jax.profiler`` trace context for TPU timelines.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("fedml_tpu")


class MetricsLogger:
    def __init__(
        self,
        run_dir: Optional[str] = None,
        use_wandb: bool = False,
        wandb_kwargs: Optional[dict] = None,
    ):
        self.run_dir = run_dir
        self._fh = None
        if run_dir:
            os.makedirs(run_dir, exist_ok=True)
            self._fh = open(os.path.join(run_dir, "metrics.jsonl"), "a")
        self._wandb = None
        if use_wandb:
            try:
                import wandb

                if wandb.run is None:
                    wandb.init(**(wandb_kwargs or {}))
                self._wandb = wandb
            except Exception:
                logger.warning("wandb requested but unavailable; file/log only")
        self.spans: Dict[str, float] = {}

    def log(self, metrics: Dict[str, Any], step: Optional[int] = None) -> None:
        record = dict(metrics)
        if step is not None:
            record.setdefault("round", step)
        if self.spans:
            record.update({f"time_{k}": v for k, v in self.spans.items()})
            self.spans = {}
        record.setdefault("ts", time.time())
        logger.info("metrics %s", json.dumps(record, default=float))
        if self._fh:
            self._fh.write(json.dumps(record, default=float) + "\n")
            self._fh.flush()
        if self._wandb:
            self._wandb.log(record, step=step)

    @contextlib.contextmanager
    def span(self, name: str):
        """Named wall-clock span, attached to the next ``log`` call —
        the reference's manual time-logging pattern, centralized."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.spans[name] = self.spans.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def close(self):
        if self._fh:
            self._fh.close()
            self._fh = None


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/fedml_tpu_trace"):
    """``jax.profiler`` trace context (open with TensorBoard/XProf)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def setup_logging(rank: Optional[int] = None, level=logging.INFO) -> None:
    """Per-process format including the process rank — reference
    ``main_fedavg.py:286-289``."""
    tag = f"[rank {rank}] " if rank is not None else ""
    logging.basicConfig(
        level=level,
        format=f"%(asctime)s {tag}%(name)s %(levelname)s: %(message)s",
    )
