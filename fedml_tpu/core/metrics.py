"""Structured metrics, named timing spans, and profiler hooks.

The reference logs manual wall-clock spans to wandb/python-logging
scattered through the code (SURVEY.md §5.1/§5.5: aggregate time
``FedAVGAggregator.py:59,85-86``, message send span
``FedAvgServerManager.py:93-102``, client compute time
``MyModelTrainer.py:42,66-71``, round wall-clock
``FedAVGAggregator.py:100-101,154``).  Here one sink owns all of it:

- ``MetricsLogger``: ``log(dict)`` → JSON-lines file + python logging
  + optional wandb, with the standard keys (round/epoch/spans).  A
  context manager with idempotent ``close()``; the record stream also
  carries the process-wide ``obs.telemetry`` registry (counter
  snapshots via ``log_telemetry``, compile/trace events drained as
  their own ``kind``-tagged records) so one ``metrics.jsonl`` is the
  whole story ``tools/trace_summary.py`` reads.
- ``span(name)``: context manager producing the same named spans as the
  reference (``time_aggregate``, ``time_round``, ...); each span also
  feeds the ``span.<name>_s`` telemetry histogram.
- ``trace(dir)``: ``jax.profiler`` trace context for TPU timelines,
  defaulting into the logger's ``run_dir``.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from typing import Any, Dict, Optional

from fedml_tpu.obs.telemetry import Telemetry, get_telemetry

logger = logging.getLogger("fedml_tpu")


class MetricsLogger:
    def __init__(
        self,
        run_dir: Optional[str] = None,
        use_wandb: bool = False,
        wandb_kwargs: Optional[dict] = None,
        telemetry: Optional[Telemetry] = None,
        filename: str = "metrics.jsonl",
    ):
        self.run_dir = run_dir
        self.telemetry = telemetry or get_telemetry()
        self._fh = None
        if run_dir:
            os.makedirs(run_dir, exist_ok=True)
            # ``filename`` lets every federation PROCESS log into one
            # shared run_dir without interleaving: hub/server/clients
            # each append to their own metrics-node<id>.jsonl, and
            # tools/fed_timeline.py merges the set
            self._fh = open(os.path.join(run_dir, filename), "a")
        self._wandb = None
        if use_wandb:
            try:
                import wandb

                if wandb.run is None:
                    wandb.init(**(wandb_kwargs or {}))
                self._wandb = wandb
            except Exception:
                logger.warning("wandb requested but unavailable; file/log only")
        self.spans: Dict[str, float] = {}

    def _write(self, record: dict) -> None:
        # serialize once, and only when someone is listening: with no
        # JSONL file and logging above INFO this is a no-op, so the
        # always-on round instrumentation costs nothing in quiet runs
        if self._fh is None and not logger.isEnabledFor(logging.INFO):
            return
        line = json.dumps(record, default=float)
        logger.info("metrics %s", line)
        if self._fh:
            self._fh.write(line + "\n")
            self._fh.flush()

    def log(self, metrics: Dict[str, Any], step: Optional[int] = None) -> dict:
        record = dict(metrics)
        if step is not None:
            record.setdefault("round", step)
        # pending spans attach to ROUND rows only: an event record
        # (kind=trace/compile/...) logged mid-round must not steal the
        # in-flight time_* spans from the next round row
        if self.spans and "kind" not in record:
            record.update(self.pop_spans())
        record.setdefault("ts", time.time())  # fedlint: disable=determinism -- MetricsLogger IS the obs layer's writer (lives in core/ for import-order reasons); ts is record metadata
        self._write(record)
        if self._wandb:
            self._wandb.log(record, step=step)
        return record

    def log_telemetry(self) -> dict:
        """Merge the telemetry registry into the record stream: pending
        events (compile, trace_rounds, ...) become their own records,
        then one ``kind=telemetry`` snapshot of every counter / gauge /
        histogram is written.  Call at eval boundaries and at shutdown."""
        for ev in self.telemetry.drain_events():
            self._write(ev)
        record = {"kind": "telemetry", "ts": time.time(),  # fedlint: disable=determinism -- snapshot-record wall stamp (obs-role module); nothing replays it
                  **self.telemetry.snapshot()}
        self._write(record)
        return record

    def flush_events(self) -> int:
        """Drain pending telemetry events into the record stream WITHOUT
        the counter snapshot ``log_telemetry`` appends.  The registry's
        event ring is bounded (4096): a long traced federation run emits
        tens of ``trace_hop`` events per round, so an exit-time-only
        drain silently evicts the earliest chains — and the single
        ``clock_sync`` event, stamped at dial time, goes first, which
        would skew every stamp of that process in the merged timeline.
        Call this on a timer (``distributed_fedavg`` worker processes
        do) and keep ``log_telemetry`` for the final snapshot."""
        n = 0
        for ev in self.telemetry.drain_events():
            self._write(ev)
            n += 1
        return n

    @contextlib.contextmanager
    def span(self, name: str):
        """Named wall-clock span, attached to the next ``log`` call —
        the reference's manual time-logging pattern, centralized.
        Repeated spans of one name ACCUMULATE until popped (a round that
        packs twice reports the sum); each individual span additionally
        lands in the ``span.<name>_s`` telemetry histogram."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.spans[name] = self.spans.get(name, 0.0) + dt
            self.telemetry.observe(f"span.{name}_s", dt)

    def pop_spans(self) -> Dict[str, float]:
        """Pending spans as ``time_<name>`` keys; clears the accumulator."""
        out = {f"time_{k}": v for k, v in self.spans.items()}
        self.spans = {}
        return out

    def close(self) -> None:
        """Idempotent: safe to call twice, safe after ``with`` exit."""
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None, logger: Optional[MetricsLogger] = None):
    """``jax.profiler`` trace context (open with TensorBoard/XProf).

    ``log_dir`` defaults to ``<logger.run_dir>/trace`` when a logger
    with a run_dir is given (so the trace lands next to metrics.jsonl),
    else ``/tmp/fedml_tpu_trace``; the chosen path is logged into the
    metrics stream so the run record points at its own trace.
    """
    import jax

    if log_dir is None:
        if logger is not None and logger.run_dir:
            log_dir = os.path.join(logger.run_dir, "trace")
        else:
            log_dir = "/tmp/fedml_tpu_trace"
    if logger is not None:
        logger.log({"kind": "trace", "trace_dir": log_dir})
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def setup_logging(rank: Optional[int] = None, level=logging.INFO) -> None:
    """Per-process format including the process rank — reference
    ``main_fedavg.py:286-289``."""
    tag = f"[rank {rank}] " if rank is not None else ""
    logging.basicConfig(
        level=level,
        format=f"%(asctime)s {tag}%(name)s %(levelname)s: %(message)s",
    )
