"""Per-round client sampling → participation masks.

The reference samples clients on the server each round with
``np.random.seed(round_idx); np.random.choice(...)``
(``FedAVGAggregator.py:89-97``).  TPU-natively, sampling becomes a
deterministic function of (key, round) via ``jax.random.fold_in`` and the
result is expressed as a boolean participation mask over the full client
axis, so subsampling is just a collective mask inside the aggregation
psum — unsampled chips contribute zeros and no control flow diverges.

The fork's hardcoded post-init sampling formula
(``FedAvgServerManager.py:66-75``) is a known defect (SURVEY.md §7) and is
deliberately NOT replicated: every round uses seeded uniform sampling.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def sample_clients(
    key: jax.Array, round_idx, num_clients: int, num_per_round: int
) -> jax.Array:
    """Seeded uniform choice of ``num_per_round`` distinct client ids.

    Jit-safe (round_idx may be traced). Equals the reference's
    ``client_sampling`` semantics (uniform, without replacement,
    deterministic per round); returns int32 ids of shape [num_per_round].
    If all clients participate, returns arange (reference ``:92-93``).
    """
    if num_per_round >= num_clients:
        return jnp.arange(num_clients, dtype=jnp.int32)
    k = jax.random.fold_in(key, round_idx)
    perm = jax.random.permutation(k, num_clients)
    return perm[:num_per_round].astype(jnp.int32)


def participation_mask(
    key: jax.Array, round_idx, num_clients: int, num_per_round: int
) -> jax.Array:
    """[num_clients] float mask with exactly ``num_per_round`` ones."""
    ids = sample_clients(key, round_idx, num_clients, num_per_round)
    return jnp.zeros(num_clients, jnp.float32).at[ids].set(1.0)


def mask_and_ids(
    key: jax.Array, round_idx, num_clients: int, num_per_round: int
) -> Tuple[jax.Array, jax.Array]:
    ids = sample_clients(key, round_idx, num_clients, num_per_round)
    mask = jnp.zeros(num_clients, jnp.float32).at[ids].set(1.0)
    return mask, ids


def eligible_participation_mask(
    key: jax.Array, round_idx, participation: jax.Array, num_per_round: int
) -> jax.Array:
    """Seeded uniform draw of ``min(num_per_round, #eligible)`` distinct
    clients among ``participation > 0``, returned as a mask.

    Top-K over iid uniform scores is a uniform K-subset, so for a fully
    eligible cohort this has the same distribution as
    ``participation_mask``; unlike intersecting an unconditional draw
    with the eligibility mask, it can never come up empty while any
    client is eligible (an empty cohort would make the round's weighted
    average undefined and zero the global model).
    """
    k = jax.random.fold_in(jax.random.fold_in(key, round_idx), 0x5A11)
    num_per_round = min(int(num_per_round), int(participation.shape[0]))
    scores = jax.random.uniform(k, participation.shape)
    scores = jnp.where(participation > 0, scores, -1.0)
    _, idx = jax.lax.top_k(scores, num_per_round)
    mask = jnp.zeros_like(participation).at[idx].set(1.0)
    # ineligible slots can only be picked when eligible < K; strip them
    return mask * (participation > 0)


def host_sample_ids(
    seed: int, round_idx: int, num_clients: int, num_per_round: int
):
    """Host-side (numpy) per-round cohort sampling — the single source
    of truth for every round driver (simulation, DP×TP loop), so runs
    with the same seed are cohort-comparable across execution modes."""
    import numpy as np

    if num_per_round >= num_clients:
        return np.arange(num_clients)
    rng = np.random.RandomState(seed * 100003 + round_idx)
    return np.sort(rng.choice(num_clients, num_per_round, replace=False))


def inject_dropout(
    key: jax.Array, round_idx, participation: jax.Array, drop_prob: float
) -> jax.Array:
    """Failure injection: each participating client independently drops
    with ``drop_prob`` (straggler/crash simulation — the failure model
    the reference lacks entirely, SURVEY.md §5.3).

    Because aggregation is a participation-masked weighted sum, a
    dropped client's contribution is EXACTLY excluded (weight zero) —
    the round result equals a round that never sampled it, which
    ``tests/test_fedavg.py`` asserts.  Never drops everyone: if all
    sampled clients would die, the first sampled one is kept (a round
    with zero weight has no defined average).
    """
    k = jax.random.fold_in(jax.random.fold_in(key, round_idx), 0x0D0D)
    survive = jax.random.bernoulli(
        k, 1.0 - drop_prob, participation.shape
    ).astype(participation.dtype)
    dropped = participation * survive
    # keep one participant alive if the draw killed them all
    any_alive = dropped.sum() > 0
    first_idx = jnp.argmax(participation)  # first sampled client
    rescue = jnp.zeros_like(participation).at[first_idx].set(
        participation[first_idx]
    )
    return jnp.where(any_alive, dropped, rescue)
