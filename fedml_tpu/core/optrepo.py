"""Server optimizer registry.

Reference: ``fedml_api/distributed/fedopt/optrepo.py:7-60`` discovers
``torch.optim`` subclasses by reflection so ``--server_optimizer`` can
name any of them.  The TPU-native equivalent is a name → optax
constructor registry; FedAdam/FedYogi/FedAvgM (Reddi et al., Adaptive
Federated Optimization) come from optax transforms applied to the
aggregated pseudo-gradient (``FedOptAggregator.set_model_global_grads``,
``FedOptAggregator.py:110-118``).
"""

from __future__ import annotations

from typing import Callable, Dict

import optax

_REGISTRY: Dict[str, Callable[..., optax.GradientTransformation]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name.lower()] = fn
        return fn

    return deco


@register("sgd")
def _sgd(lr: float = 1.0, momentum: float = 0.0, **kw):
    return optax.sgd(lr, momentum=momentum if momentum else None)


@register("avgm")
@register("fedavgm")
def _avgm(lr: float = 1.0, momentum: float = 0.9, **kw):
    return optax.sgd(lr, momentum=momentum)


@register("adam")
@register("fedadam")
def _adam(lr: float = 1e-2, b1: float = 0.9, b2: float = 0.99, eps: float = 1e-3, **kw):
    # eps=1e-3 is the Adaptive-FedOpt paper default (tau)
    return optax.adam(lr, b1=b1, b2=b2, eps=eps)


@register("yogi")
@register("fedyogi")
def _yogi(lr: float = 1e-2, b1: float = 0.9, b2: float = 0.99, eps: float = 1e-3, **kw):
    return optax.yogi(lr, b1=b1, b2=b2, eps=eps)


@register("adagrad")
@register("fedadagrad")
def _adagrad(lr: float = 1e-2, eps: float = 1e-3, **kw):
    return optax.adagrad(lr, eps=eps)


@register("lamb")
def _lamb(lr: float = 1e-3, **kw):
    return optax.lamb(lr)


def get_server_optimizer(name: str, **kwargs) -> optax.GradientTransformation:
    try:
        return _REGISTRY[name.lower()](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown server optimizer {name!r}; have {sorted(_REGISTRY)}"
        ) from None


def names():
    return sorted(_REGISTRY)
