"""The client-side local training operator.

TPU-native replacement for the reference's ``MyModelTrainer.train``
Python epoch/batch loop (``fedml_api/distributed/fedavg/MyModelTrainer.py:26-71``
and ``standalone/fedavg/my_model_trainer_classification.py:17-54``):
a jit-compiled ``lax.scan`` over epochs × fixed-shape batches, vmappable
over a packed client axis and shard_mappable over a device mesh.

Matches the reference's semantics:
- the client optimizer is constructed fresh every round (``MyModelTrainer.py:33-41``);
- per-epoch reshuffling of the local dataset (torch DataLoader shuffle=True);
- optional proximal term for FedProx (``fedprox/MyModelTrainer.py:41-60``),
  computed over parameters only — the reference's buffer/parameter index
  misalignment (SURVEY.md §7 "known defects") is not replicated;
- optional global-norm gradient clipping
  (``my_model_trainer_classification.py:44``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.core import tree as treelib
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.models.base import ModelBundle

PyTree = Any


def _scale_by_amsgrad_torch(
    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8
) -> optax.GradientTransformation:
    """torch.optim.Adam(amsgrad=True) semantics exactly: the running max
    is over the RAW second moment, and bias correction divides the max
    (optax.amsgrad maxes the bias-corrected nu instead, which diverges
    from torch over the first steps — verified numerically)."""

    def init(params):
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"count": jnp.zeros((), jnp.int32), "mu": zeros,
                "nu": zeros, "nu_max": zeros}

    def update(updates, state, params=None):
        del params
        t = state["count"] + 1
        mu = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g, state["mu"], updates)
        nu = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1 - b2) * g * g, state["nu"], updates)
        nu_max = jax.tree_util.tree_map(jnp.maximum, state["nu_max"], nu)
        c1 = 1 - b1 ** t.astype(jnp.float32)
        c2 = 1 - b2 ** t.astype(jnp.float32)
        out = jax.tree_util.tree_map(
            lambda m, v: (m / c1) / (jnp.sqrt(v / c2) + eps), mu, nu_max)
        return out, {"count": t, "mu": mu, "nu": nu, "nu_max": nu_max}

    return optax.GradientTransformation(init, update)


def make_client_optimizer(
    name: str = "sgd",
    lr: float = 0.03,
    *,
    momentum: float = 0.0,
    weight_decay: Optional[float] = None,
    grad_clip: Optional[float] = None,
) -> optax.GradientTransformation:
    """The reference's client optimizers: SGD (+momentum/wd) or amsgrad Adam
    (``MyModelTrainer.py:33-41``).

    ``weight_decay=None`` means "optimizer default" (0 for sgd, the
    reference's 1e-4 for adam); an explicit 0.0 is honored as zero so
    wd=0 runs are reproducible.
    """
    chain = []
    if grad_clip is not None:
        chain.append(optax.clip_by_global_norm(grad_clip))
    if name == "sgd":
        if weight_decay:
            chain.append(optax.add_decayed_weights(weight_decay))
        chain.append(optax.sgd(lr, momentum=momentum if momentum else None))
    elif name == "adam":
        # reference default: torch.optim.Adam(lr, weight_decay=0.0001,
        # amsgrad=True) (MyModelTrainer.py:38-40).  torch's weight_decay
        # is COUPLED L2 (wd*p added to the gradient before the adam
        # update), so add_decayed_weights goes BEFORE the scaling — not
        # decoupled adamw
        wd = 1e-4 if weight_decay is None else weight_decay
        if wd:
            chain.append(optax.add_decayed_weights(wd))
        chain.append(_scale_by_amsgrad_torch())
        # scale_by_learning_rate = scale(-lr), and also accepts an optax
        # schedule (count -> lr) like the sgd branch does
        chain.append(optax.scale_by_learning_rate(lr))
    else:
        raise ValueError(f"unknown client optimizer: {name}")
    return optax.chain(*chain)


@dataclasses.dataclass
class LocalUpdateFn:
    """Callable local update plus metadata the algorithms need."""

    fn: Callable  # (variables, x, y, mask, rng) -> (variables, metrics)
    epochs: int

    def __call__(self, variables, x, y, mask, rng):
        return self.fn(variables, x, y, mask, rng)


def make_local_update(
    bundle: ModelBundle,
    optimizer: optax.GradientTransformation,
    epochs: int,
    loss_fn: LossFn = masked_softmax_ce,
    *,
    prox_mu: float = 0.0,
    shuffle: bool = True,
    augment_fn: Optional[Callable] = None,
    compute_dtype: Optional[Any] = None,
    unroll: int = 1,
) -> LocalUpdateFn:
    """Build the pure local-update function for one client.

    Args shapes (one client): x [steps, B, ...], y [steps, B], mask [steps, B].
    Returns (new_variables, metrics) where metrics carries summed
    loss/correct/count over the final epoch — mirroring what the
    reference logs per client (``MyModelTrainer.py:55-66``).

    ``compute_dtype`` (e.g. ``jnp.bfloat16``) enables mixed precision:
    the forward/backward pass runs with params and inputs cast to that
    dtype so matmuls/convs hit the MXU at full rate, while the master
    params, optimizer state, gradients, and loss stay float32 (losses
    upcast logits internally).  Mutable state (BatchNorm stats) is cast
    back to its master dtype each step so the scan carry stays stable.
    """

    def loss_and_logits(params, other_vars, global_params, x, y, m, rng):
        variables = {**other_vars, "params": params}
        if compute_dtype is not None:
            cvars = treelib.tree_cast_floats(variables, compute_dtype)
            cx = (
                x.astype(compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating)
                else x
            )
            logits, new_vars = bundle.apply_train(cvars, cx, rng)
            new_vars = treelib.tree_cast_like(new_vars, variables)
        else:
            logits, new_vars = bundle.apply_train(variables, x, rng)
        loss, aux = loss_fn(logits, y, m)
        if prox_mu:
            sq = treelib.tree_sq_norm(treelib.tree_sub(params, global_params))
            loss = loss + 0.5 * prox_mu * sq
        return loss, (new_vars, aux)

    grad_fn = jax.value_and_grad(loss_and_logits, has_aux=True)

    def local_update(variables, x, y, mask, rng):
        steps, bsz = x.shape[0], x.shape[1]
        n = steps * bsz
        global_params = variables["params"]
        opt_state = optimizer.init(variables["params"])

        def epoch_body(carry, ep):
            variables, opt_state = carry
            ek = jax.random.fold_in(rng, ep)
            if shuffle:
                perm = jax.random.permutation(jax.random.fold_in(ek, 0), n)
                xs = x.reshape(n, *x.shape[2:])[perm].reshape(x.shape)
                ys = y.reshape(n, *y.shape[2:])[perm].reshape(y.shape)
                ms = mask.reshape(n)[perm].reshape(mask.shape)
            else:
                xs, ys, ms = x, y, mask
            if augment_fn is not None:
                # fresh augmentation for every sample once per EPOCH —
                # exactly the reference's torchvision semantics (each
                # sample is transformed once per pass) — applied to the
                # whole epoch tensor in ONE call.  Per-STEP augmentation
                # is semantically identical but ~15x slower end-to-end:
                # the augment's ~6 threefry/elementwise kernels cost
                # ~1.5 ms per scan step on v5e (latency-, not
                # bandwidth-bound), which at north-star scale (15,600
                # steps/round) added ~25 s/round and pushed the round
                # over the ~70 s device-execution deadline (measured;
                # one whole-epoch call costs ~0.1 ms for 5,000 images)
                flat = augment_fn(
                    jax.random.fold_in(ek, n + 1),
                    xs.reshape(n, *x.shape[2:]),
                )
                xs = flat.reshape(x.shape)

            def step_body(carry, batch):
                variables, opt_state = carry
                bx, by, bm, bi = batch
                sk = jax.random.fold_in(ek, bi + 1)
                others = {k: v for k, v in variables.items() if k != "params"}
                (loss, (new_vars, aux)), grads = grad_fn(
                    variables["params"], others, global_params, bx, by, bm, sk
                )
                updates, new_opt = optimizer.update(
                    grads, opt_state, variables["params"]
                )
                params = optax.apply_updates(variables["params"], updates)
                # batches that are entirely padding must be true no-ops
                has_real = (bm.sum() > 0).astype(jnp.float32)
                params = jax.tree_util.tree_map(
                    lambda new, old: has_real * new + (1 - has_real) * old,
                    params,
                    variables["params"],
                )
                new_vars = {**new_vars, "params": params}
                aux = {**aux, "step": has_real}
                return (new_vars, new_opt), aux

            # unroll>1 trades compiled-code size for fewer while-loop
            # iterations: the TPU loop bookkeeping is ~0.3ms/iteration,
            # a measurable share of a ~4ms step (profiled on v5e)
            (variables, opt_state), auxs = jax.lax.scan(
                step_body,
                (variables, opt_state),
                (xs, ys, ms, jnp.arange(steps)),
                unroll=unroll,
            )
            return (variables, opt_state), auxs

        if epochs == 1:
            # elide the outer while loop entirely: the TPU scalar-core
            # bookkeeping for a length-1 scan is pure overhead (the
            # PROFILE.md `while` share), and E=1 is the reference's
            # default benchmark regime.  fold_in(rng, 0) keeps the RNG
            # stream identical to the scan path.
            (variables, _), auxs0 = epoch_body((variables, opt_state), 0)
            auxs = jax.tree_util.tree_map(lambda a: a[None], auxs0)
        else:
            (variables, _), auxs = jax.lax.scan(
                epoch_body, (variables, opt_state), jnp.arange(epochs)
            )
        metrics = {
            "loss_sum": auxs["loss_sum"][-1].sum(),
            "correct": auxs["correct"][-1].sum(),
            "count": auxs["count"][-1].sum(),
            # exact optimizer steps executed across ALL epochs (pad-only
            # batches are no-ops and excluded) — FedNova's tau_i
            "steps": auxs["step"].sum(),
        }
        return variables, metrics

    return LocalUpdateFn(fn=local_update, epochs=epochs)


def make_evaluator(bundle: ModelBundle, loss_fn: LossFn = masked_softmax_ce):
    """Jit-able eval over a padded batch pack [steps, B, ...] → summed metrics.

    Evaluation stays float32 even when training uses a low-precision
    compute_dtype: metric fidelity is worth the one fp32 forward."""

    def evaluate(variables, x, y, mask):
        def body(carry, batch):
            bx, by, bm = batch
            logits = bundle.apply_eval(variables, bx)
            _, aux = loss_fn(logits, by, bm)
            return carry, aux

        _, auxs = jax.lax.scan(body, (), (x, y, mask))
        return {k: v.sum() for k, v in auxs.items()}

    return jax.jit(evaluate)


def eval_summary(res) -> dict:
    """Summed evaluator metrics → the test_{acc,loss,count} record every
    driver reports (shared so the simulation and DP×TP paths can't
    drift apart)."""
    count = float(res["count"])
    out = {
        "test_acc": float(res["correct"]) / max(count, 1.0),
        "test_loss": float(res["loss_sum"]) / max(count, 1.0),
        "test_count": count,
    }
    # multi-label tasks (losses.masked_multilabel_bce) also report the
    # reference's precision/recall (my_model_trainer_tag_prediction.py:88-93)
    if "precision_sum" in res:
        out["test_precision"] = float(res["precision_sum"]) / max(count, 1.0)
        out["test_recall"] = float(res["recall_sum"]) / max(count, 1.0)
    return out
