"""Non-IID data partitioners.

Re-implements the semantics of the reference's partition schemes:

- Dirichlet / LDA partition with a min-size retry loop
  (``/root/reference/fedml_core/non_iid_partition/noniid_partition.py:6-63``
  and ``fedml_api/data_preprocessing/cifar10/data_loader.py:113-163``).
- ``homo`` uniform partition (same file, ``:126-129``).
- LEAF-style power-law partition used by the MNIST benchmark
  (pre-partitioned JSON in the reference; here generated directly).

All partitioners return ``Dict[int, np.ndarray]`` of sample indices —
the ``net_dataidx_map`` of the reference — and are host-side numpy by
design: partitioning is a one-off host task, not a TPU op.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def record_data_stats(
    y: np.ndarray, client_idx: Dict[int, np.ndarray], num_classes: int
) -> Dict[int, Dict[int, int]]:
    """Per-client class histogram (reference ``record_data_stats``,
    ``noniid_partition.py:66-74``)."""
    stats = {}
    for c, idx in client_idx.items():
        labels, counts = np.unique(y[idx], return_counts=True)
        stats[c] = {int(l): int(n) for l, n in zip(labels, counts)}
    return stats


def homo_partition(n_samples: int, num_clients: int, seed: int = 0) -> Dict[int, np.ndarray]:
    """Uniform random equal split (reference ``partition == "homo"``)."""
    rng = np.random.RandomState(seed)
    idx = rng.permutation(n_samples)
    return {c: np.sort(part) for c, part in enumerate(np.array_split(idx, num_clients))}


def dirichlet_partition(
    y: np.ndarray,
    num_clients: int,
    alpha: float,
    *,
    min_size_bound: int = 10,
    seed: int = 0,
    max_retries: int = 1000,
) -> Dict[int, np.ndarray]:
    """Latent-Dirichlet-allocation partition with min-size retry.

    Semantics of the reference's
    ``non_iid_partition_with_dirichlet_distribution`` (noniid_partition.py:6-63):
    for each class k, draw proportions p ~ Dir(alpha) over clients, cap any
    client already holding >= N/num_clients samples to 0 before normalizing,
    then split class-k indices by the cumulative proportions; retry the whole
    draw until every client holds at least ``min_size_bound`` samples.
    """
    rng = np.random.RandomState(seed)
    n = len(y)
    classes = np.unique(y)
    min_size = 0
    retries = 0
    idx_batch = [[] for _ in range(num_clients)]
    while min_size < min_size_bound:
        if retries > max_retries:
            raise RuntimeError(
                f"dirichlet_partition: could not reach min client size "
                f"{min_size_bound} after {max_retries} retries "
                f"(alpha={alpha}, clients={num_clients}, n={n})"
            )
        retries += 1
        idx_batch = [[] for _ in range(num_clients)]
        for k in classes:
            idx_k = np.where(y == k)[0]
            rng.shuffle(idx_k)
            proportions = rng.dirichlet(np.repeat(alpha, num_clients))
            # cap clients already at their fair share (reference :46-48)
            proportions = np.array(
                [
                    p * (len(idx_j) < n / num_clients)
                    for p, idx_j in zip(proportions, idx_batch)
                ]
            )
            proportions = proportions / proportions.sum()
            splits = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
            for c, part in enumerate(np.split(idx_k, splits)):
                idx_batch[c].extend(part.tolist())
        min_size = min(len(b) for b in idx_batch)

    out = {}
    for c in range(num_clients):
        b = np.array(idx_batch[c], dtype=np.int64)
        rng.shuffle(b)
        out[c] = b
    return out


def powerlaw_partition(
    y: np.ndarray,
    num_clients: int,
    *,
    alpha: float = 1.5,
    min_samples: int = 10,
    seed: int = 0,
) -> Dict[int, np.ndarray]:
    """LEAF-style power-law sizes with class-skewed contents.

    The reference's MNIST benchmark consumes LEAF's pre-generated
    power-law JSON partition (``MNIST/data_loader.py:8-123``); the
    generator itself lives outside the repo.  This reproduces its shape:
    client sizes follow a power law, and each client draws predominantly
    from a small number of classes (2, LEAF's default for MNIST).
    """
    rng = np.random.RandomState(seed)
    n = len(y)
    classes = np.unique(y)
    sizes = rng.pareto(alpha, num_clients) + 1.0
    sizes = np.maximum((sizes / sizes.sum() * (n - num_clients * min_samples)).astype(int)
                       + min_samples, min_samples)

    by_class = {int(k): list(rng.permutation(np.where(y == k)[0])) for k in classes}
    out: Dict[int, np.ndarray] = {}
    for c in range(num_clients):
        picked = []
        ks = rng.choice(classes, size=min(2, len(classes)), replace=False)
        want = int(sizes[c])
        for j, k in enumerate(ks):
            take = want - len(picked) if j == len(ks) - 1 else want // len(ks)
            pool = by_class[int(k)]
            got = pool[:take]
            by_class[int(k)] = pool[take:]
            picked.extend(got)
        if len(picked) < min_samples:  # pool ran dry — top up from anything left
            leftovers = [i for pool in by_class.values() for i in pool]
            rng.shuffle(leftovers)
            need = min_samples - len(picked)
            picked.extend(leftovers[:need])
            used = set(picked[-need:])
            for k in by_class:
                by_class[k] = [i for i in by_class[k] if i not in used]
        out[c] = np.array(picked, dtype=np.int64)
    return out


def partition_data(
    y: np.ndarray,
    num_clients: int,
    method: str = "hetero",
    alpha: float = 0.5,
    seed: int = 0,
) -> Dict[int, np.ndarray]:
    """Dispatch matching the reference's ``partition_data`` switch
    (``cifar10/data_loader.py:113-163``)."""
    if method in ("homo", "iid"):
        return homo_partition(len(y), num_clients, seed=seed)
    if method in ("hetero", "noniid", "dirichlet", "lda"):
        return dirichlet_partition(y, num_clients, alpha, seed=seed)
    if method in ("power_law", "powerlaw"):
        return powerlaw_partition(y, num_clients, seed=seed)
    raise ValueError(f"unknown partition method: {method}")
