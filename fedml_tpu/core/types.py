"""Typed federated data contract.

The reference's universal data contract is the 8-tuple returned by every
``load_partition_data_<dataset>`` function (see
``/root/reference/fedml_api/data_preprocessing/cifar10/data_loader.py:235-269``
and the ``load_data`` switch at
``fedml_experiments/distributed/fedavg/main_fedavg.py:108-214``):

    [train_data_num, test_data_num, train_data_global, test_data_global,
     train_data_local_num_dict, train_data_local_dict, test_data_local_dict,
     class_num]

Here that contract becomes one typed, framework-owned structure,
``FedDataset``, holding numpy arrays on the host plus per-client index
lists.  Device-side, heterogeneous per-client data must become fixed
shape to be jit/SPMD-friendly, so ``ClientBatches`` packs K clients into
``[K, steps, batch, ...]`` arrays with a sample mask (pad-by-wrapping so
BatchNorm statistics never see zero images; the mask zeroes duplicate
samples out of losses and counts).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

Array = Any  # np.ndarray or jax.Array


@dataclasses.dataclass
class FedDataset:
    """Host-side federated dataset: global arrays + per-client partitions."""

    train_x: np.ndarray
    train_y: np.ndarray
    test_x: np.ndarray
    test_y: np.ndarray
    # client id -> indices into train_x / test_x
    train_client_idx: Dict[int, np.ndarray]
    test_client_idx: Optional[Dict[int, np.ndarray]]
    num_classes: int
    name: str = "dataset"

    @property
    def num_clients(self) -> int:
        return len(self.train_client_idx)

    @property
    def train_data_num(self) -> int:
        return int(self.train_x.shape[0])

    @property
    def test_data_num(self) -> int:
        # 0 when the dataset ships no held-out split (test arrays None
        # — e.g. stackoverflow real-h5 without *_test.h5); evaluation
        # itself is refused with an actionable message in
        # batch_eval_pack
        return 0 if self.test_x is None else int(self.test_x.shape[0])

    def client_sample_counts(self) -> np.ndarray:
        """[num_clients] number of training samples per client."""
        return np.array(
            [len(self.train_client_idx[c]) for c in range(self.num_clients)],
            dtype=np.int32,
        )

    def subset_for_clients(self, client_ids: Sequence[int]) -> "FedDataset":
        """Host-local view holding ONLY the named clients' rows.

        The reference's distributed loaders materialize just the local
        rank's partition (``load_partition_data_distributed_cifar10``,
        ``/root/reference/fedml_api/data_preprocessing/cifar10/data_loader.py:201-233``);
        this is the same contract for a pod: each host calls
        ``subset_for_clients(host_client_range(...))`` and never holds —
        or, with a loader's ``client_filter``, never parses — the other
        hosts' data.  Client keys KEEP their original ids (only the row
        indices are compacted), so ``pack_clients`` on the subset is
        bit-identical to packing the same clients from the full dataset
        (per-client pack seeding is id-keyed).  Test rows are kept whole
        when there is no per-client test split (every host evaluates the
        global test set), and subset per-client otherwise.
        """
        client_ids = list(client_ids)
        missing = [c for c in client_ids if c not in self.train_client_idx]
        if missing:
            raise KeyError(f"clients not in dataset: {missing}")
        order = np.concatenate(
            [np.asarray(self.train_client_idx[c], np.int64) for c in client_ids]
        ) if client_ids else np.zeros((0,), np.int64)
        new_idx: Dict[int, np.ndarray] = {}
        off = 0
        for c in client_ids:
            n = len(self.train_client_idx[c])
            new_idx[c] = np.arange(off, off + n)
            off += n
        if self.test_client_idx is None:
            test_x, test_y, new_test_idx = self.test_x, self.test_y, None
        else:
            t_order = np.concatenate(
                [np.asarray(self.test_client_idx[c], np.int64) for c in client_ids]
            ) if client_ids else np.zeros((0,), np.int64)
            test_x, test_y = self.test_x[t_order], self.test_y[t_order]
            new_test_idx = {}
            t_off = 0
            for c in client_ids:
                n = len(self.test_client_idx[c])
                new_test_idx[c] = np.arange(t_off, t_off + n)
                t_off += n
        return FedDataset(
            train_x=self.train_x[order],
            train_y=self.train_y[order],
            test_x=test_x,
            test_y=test_y,
            train_client_idx=new_idx,
            test_client_idx=new_test_idx,
            num_classes=self.num_classes,
            name=self.name,
        )

    def legacy_tuple(self, batch_size: int) -> Tuple:
        """The reference's 8-tuple, for parity-checking and migration.

        ``train_data_global``/locals are lists of (x, y) numpy batches, the
        shape the reference's torch DataLoaders would yield.
        """
        if self.test_x is None or self.test_y is None:
            # same actionable refusal as batch_eval_pack — the 8-tuple
            # has test slots, so a no-test-split dataset can't fill it
            batch_eval_pack(self.test_x, self.test_y, batch_size)

        def batches(x, y):
            return [
                (x[i : i + batch_size], y[i : i + batch_size])
                for i in range(0, len(x), batch_size)
            ]

        train_local_num = {c: len(ix) for c, ix in self.train_client_idx.items()}
        train_local = {
            c: batches(self.train_x[ix], self.train_y[ix])
            for c, ix in self.train_client_idx.items()
        }
        if self.test_client_idx is not None:
            test_local = {
                c: batches(self.test_x[ix], self.test_y[ix])
                for c, ix in self.test_client_idx.items()
            }
        else:
            test_local = {c: batches(self.test_x, self.test_y)
                          for c in self.train_client_idx}
        return (
            self.train_data_num,
            self.test_data_num,
            batches(self.train_x, self.train_y),
            batches(self.test_x, self.test_y),
            train_local_num,
            train_local,
            test_local,
            self.num_classes,
        )


@dataclasses.dataclass
class ClientBatches:
    """Fixed-shape device-ready pack of K clients' local training data.

    x:    [K, steps, batch, ...feature]
    y:    [K, steps, batch]
    mask: [K, steps, batch]  1.0 for a real sample, 0.0 for a wrapped pad
    num_samples: [K] true (unpadded) per-client sample counts
    """

    x: Array
    y: Array
    mask: Array
    num_samples: Array

    @property
    def num_clients(self) -> int:
        return int(self.x.shape[0])

    @property
    def steps_per_epoch(self) -> int:
        return int(self.x.shape[1])

    @property
    def batch_size(self) -> int:
        return int(self.x.shape[2])


# reusable gather targets for fixed-geometry round loops; one buffer per
# role tag, replaced when the requested geometry changes — bounded at
# (number of tags) live buffers no matter how many shapes a sweep visits
_pack_buffer_cache = threading.local()


def _gather_target(tag: str, shape, dtype, reuse: bool):
    if not reuse:
        return None
    # Thread-local cache: two threads packing concurrently get distinct
    # buffers, so the consume-before-repack contract (pack_clients
    # docstring) only has to hold within one thread.
    cache = getattr(_pack_buffer_cache, "bufs", None)
    if cache is None:
        cache = _pack_buffer_cache.bufs = {}
    # tag keeps roles distinct: x and y packs with identical shape+dtype
    # must not share one buffer
    shape = tuple(shape)
    dtype = np.dtype(dtype)
    buf = cache.get(tag)
    if buf is None or buf.shape != shape or buf.dtype != dtype:
        buf = np.empty(shape, dtype)
        cache[tag] = buf
    return buf


def pack_clients(
    dataset: FedDataset,
    client_ids: Sequence[int],
    batch_size: int,
    *,
    steps_per_epoch: Optional[int] = None,
    seed: int = 0,
    reuse_buffers: bool = False,
) -> ClientBatches:
    """Pack the named clients' train shards into one fixed-shape block.

    Heterogeneous client sizes (the SPMD hard part — SURVEY.md §7) are
    resolved by wrapping indices (np.resize) up to a common
    ``steps_per_epoch * batch_size`` length; the mask marks only the first
    ``n_c`` slots per client as real.  Wrapped duplicates keep BatchNorm
    inputs realistic while contributing zero loss/weight.

    ``reuse_buffers=True`` gathers into process-cached host buffers
    instead of fresh allocations — ~4x faster per round (allocation +
    page-fault churn dominates the copy).  Only safe when the caller
    consumes the pack before the next same-shape pack_clients call
    (e.g. immediately device_puts it, as the round drivers do): the
    returned arrays are OVERWRITTEN by that next call.
    """
    from fedml_tpu.native import gather_rows

    counts = [len(dataset.train_client_idx[c]) for c in client_ids]
    if steps_per_epoch is None:
        steps_per_epoch = max(1, int(np.ceil(max(max(counts), 1) / batch_size)))
    total = steps_per_epoch * batch_size
    K = len(client_ids)

    # pass 1 (cheap): per-client wrapped index lists + masks
    wrapped_all = np.zeros((K, total), dtype=np.int64)
    mask = np.zeros((K, total), dtype=np.float32)
    ns = np.zeros(K, dtype=np.float32)
    for k, c in enumerate(client_ids):
        # per-client seeding: a client's pack is identical whether packed
        # alone (cross-device manager) or in a cohort (simulation/SPMD)
        rng = np.random.RandomState((seed * 1000003 + int(c) * 7919 + 1) % (2**31))
        idx = np.asarray(dataset.train_client_idx[c])
        n = len(idx)
        if n:
            # gather_rows clamps out-of-range rows (segfault defense), so
            # validate here to keep the old fancy-indexing error behavior
            lo, hi = int(idx.min()), int(idx.max())
            if lo < 0 or hi >= len(dataset.train_x):
                raise IndexError(
                    f"client {c} sample indices [{lo}, {hi}] out of range "
                    f"for train_x with {len(dataset.train_x)} rows"
                )
            # empty clients keep sample 0 / mask 0 and contribute nothing
            wrapped_all[k] = np.resize(rng.permutation(idx), total)
            mask[k, : min(n, total)] = 1.0
            ns[k] = min(n, total)

    # pass 2 (hot): one fused row gather per tensor straight into the
    # packed block — threaded C++ when available, numpy otherwise
    feat_shape = dataset.train_x.shape[1:]
    x_out = _gather_target(
        "x", (K * total, *feat_shape), dataset.train_x.dtype, reuse_buffers
    )
    x = gather_rows(dataset.train_x, wrapped_all, x_out).reshape(
        K, steps_per_epoch, batch_size, *feat_shape
    )
    # y may carry trailing dims (sequence targets [N, T], tag vectors)
    y_out = _gather_target(
        "y",
        (K * total, *dataset.train_y.shape[1:]),
        dataset.train_y.dtype,
        reuse_buffers,
    )
    y = gather_rows(dataset.train_y, wrapped_all, y_out).reshape(
        K, steps_per_epoch, batch_size, *dataset.train_y.shape[1:]
    )

    return ClientBatches(
        x=x,
        y=y,
        mask=mask.reshape(K, steps_per_epoch, batch_size),
        num_samples=ns,
    )


def device_resident_pack(
    dataset: FedDataset,
    ids,
    batch_size: int,
    *,
    steps_per_epoch: int,
    seed: int,
    mesh=None,
    cohort_axis: str = "dp",
) -> Tuple[Tuple, np.ndarray]:
    """Pack a cohort ONCE and put it on device for the whole run — the
    shared primitive behind every driver's resident-cohort cache
    (``FedAvgSimulation._device_pack`` documents the rationale and the
    measured per-round transfer cost it removes).

    Returns ``((x, y, mask, num_samples) device arrays, host
    num_samples)`` — callers that weight aggregation on host keep the
    numpy copy instead of reading the device array back every round.

    ``mesh`` (a dp×mp mesh from ``parallel/mesh.py``) shards the
    leading client axis of every packed array over ``cohort_axis``
    instead of leaving the block on one device — each dp slice of the
    mesh then holds only its own clients' rows, which is what lets the
    partition-rule round engine scale the resident cohort past one
    chip's HBM.

    ``reuse_buffers`` only off-CPU: the TPU device_put is a real copy,
    so the reused host buffer is free once block_until_ready returns
    (ALL transfers — x AND y share the reuse cache); on CPU device_put
    can be ZERO-COPY and a cached block could alias the reuse buffer
    and be silently overwritten by the next cohort's pack.
    """
    import jax
    import jax.numpy as jnp

    pack = pack_clients(
        dataset, ids, batch_size, steps_per_epoch=steps_per_epoch,
        seed=seed, reuse_buffers=jax.default_backend() != "cpu",
    )
    host_ns = np.asarray(pack.num_samples).copy()
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        target = NamedSharding(mesh, PartitionSpec(cohort_axis))
        args = tuple(
            jax.device_put(np.asarray(a), target)
            for a in (pack.x, pack.y, pack.mask, pack.num_samples)
        )
    else:
        args = tuple(
            jax.device_put(jnp.asarray(a))
            for a in (pack.x, pack.y, pack.mask, pack.num_samples)
        )
    jax.block_until_ready(args)
    return args, host_ns


def cohort_steps_per_epoch(dataset: FedDataset, batch_size: int) -> int:
    """Pack geometry shared by every cohort driver: steps to cover the
    LARGEST client at ``batch_size`` (smaller clients pad-by-wrapping).

    Equivalence-critical: the simulation, the multi-process federation
    entry, and the experiment dispatcher must all pack with the same
    geometry or their parameter-level equivalence oracles diverge — one
    definition, three callers.
    """
    counts = dataset.client_sample_counts()
    return max(1, int(np.ceil(max(int(counts.max()), 1) / batch_size)))


def batch_eval_pack(
    x: np.ndarray, y: np.ndarray, batch_size: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad (by wrapping) an eval set to a whole number of batches.

    Returns (x_batched [steps, B, ...], y_batched [steps, B], mask).
    """
    if x is None or y is None:
        # loaders return None test arrays when a dataset ships no
        # held-out split (e.g. stackoverflow real-h5 without
        # *_test.h5) — refuse with the actionable message instead of
        # an opaque len(None) deep in driver construction
        raise ValueError(
            "dataset has no test split (test arrays are None): fetch "
            "the *_test.h5 file or evaluate on a dataset that ships "
            "one — evaluating on training data is not a fallback"
        )
    n = len(x)
    steps = max(1, int(np.ceil(n / batch_size)))
    total = steps * batch_size
    idx = np.resize(np.arange(n), total)
    mask = np.zeros(total, dtype=np.float32)
    mask[:n] = 1.0
    return (
        x[idx].reshape(steps, batch_size, *x.shape[1:]),
        y[idx].reshape(steps, batch_size, *y.shape[1:]),
        mask.reshape(steps, batch_size),
    )
