"""Robust aggregation defenses — THE one copy of the defense math.

Reference ``fedml_core/robustness/robust_aggregation.py``:
- ``vectorize_weight`` flattens all parameters EXCLUDING BatchNorm
  running statistics (``:28-29``) for norm computation;
- norm-difference clipping ``w_t + clip(w_local − w_t)`` with bound
  ``norm_bound`` (``:38-49``);
- weak differential privacy: add N(0, stddev²) noise (``:51-55``).

Classic grounding beyond the reference: coordinate-wise median and
trimmed mean are the Byzantine-robust estimators of Blanchard et
al. (NeurIPS 2017) / Yin et al. (ICML 2018); norm clipping + noise is
the backdoor defense of Sun et al. ("Can You Really Backdoor Federated
Learning?", 2019).

Every function here is polymorphic over the array module (``xp`` =
``jax.numpy`` or ``numpy``): the SAME formula runs

- stacked + jit'd inside the compiled round engine as the
  ``aggregate_transform`` hook (``make_robust_transform``, xp=jnp), and
- per-upload on the cross-device server's host hot path
  (``fedml_tpu.robust.defense``, xp=np — no device dispatch under the
  round lock).

That is the dedup contract: the sim layer and the real-TCP server
cannot drift because there is no second copy to drift
(``tests/test_robust_agg.py`` pins np-vs-jnp parity).

Sub-stream discipline: aggregation-defense randomness (weak-DP /
client-level DP noise) lives on the ``AGG_STREAM`` fold_in sub-stream
of the round key — ``fold_in(fold_in(fold_in(key, round), AGG_STREAM),
slot)`` — exactly the per-slot keys ``make_round_fn`` derives for its
``aggregate_transform`` rngs, so server-side DP noise is bit-identical
to the compiled engine's weak-DP noise for the same (seed, round,
slot) and reproducible across processes (the ``compress/`` key
discipline).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

PyTree = Any

# fold_in sub-stream indices under the round key (see
# algorithms/fedavg.make_round_fn and compress/codecs.COMPRESS_STREAM):
# 0 = training, 1 = aggregation noise (this module), 2 = compression
AGG_STREAM = 1

_NORM_EPS = 1e-12


def param_delta_sq_norms(global_params: PyTree, stacked_params: PyTree,
                         *, xp=jnp):
    """[K] squared L2 norm of (w_i − w_global), over parameters only (BN
    stats are a separate collection in our variables tree and never
    enter here — the reference's ``vectorize_weight`` exclusion)."""
    sq = jax.tree_util.tree_map(
        lambda g, s: xp.sum(
            xp.square(s.astype(xp.float32) - g[None].astype(xp.float32)),
            axis=tuple(range(1, s.ndim)),
        ),
        global_params,
        stacked_params,
    )
    return sum(jax.tree_util.tree_leaves(sq))


def param_delta_norms(global_params: PyTree, stacked_params: PyTree,
                      *, xp=jnp):
    """[K] L2 norm of (w_i − w_global) — see ``param_delta_sq_norms``."""
    return xp.sqrt(param_delta_sq_norms(global_params, stacked_params, xp=xp))


def clip_factor(norms, norm_bound: float, *, xp=jnp):
    """Per-client clip scale ``min(1, bound / max(norm, eps))`` — the
    norm-difference-clipping formula, shared by every caller."""
    return xp.minimum(1.0, norm_bound / xp.maximum(norms, _NORM_EPS))


def clip_stacked_params(global_params: PyTree, stacked_params: PyTree,
                        norm_bound: float, *, xp=jnp) -> PyTree:
    """Norm-difference clipping over a stacked [K, ...] params tree:
    ``w_t + scale_k * (w_k − w_t)`` with ``scale_k`` from
    ``clip_factor``.  Works identically for K=1 host-side screening and
    a full cohort inside jit."""
    norms = param_delta_norms(global_params, stacked_params, xp=xp)
    scale = clip_factor(norms, norm_bound, xp=xp)  # [K]
    return jax.tree_util.tree_map(
        lambda g, s: (
            g[None].astype(xp.float32)
            + xp.einsum(
                "k,k...->k...",
                scale,
                s.astype(xp.float32) - g[None].astype(xp.float32),
            )
        ).astype(s.dtype),
        global_params,
        stacked_params,
    )


def clip_client_updates(
    global_vars: PyTree, stacked_client_vars: PyTree, norm_bound: float,
    *, xp=jnp,
) -> PyTree:
    """Per-client norm-difference clipping of parameter deltas."""
    clipped = clip_stacked_params(
        global_vars["params"], stacked_client_vars["params"], norm_bound,
        xp=xp,
    )
    return {**stacked_client_vars, "params": clipped}


def noise_params(key: jax.Array, client_params: PyTree,
                 stddev: float) -> PyTree:
    """Gaussian noise on ONE client's parameters — the per-client atom
    both ``add_weak_dp_noise`` (vmapped, in-jit) and the cross-device
    server's client-level DP (host-side, per upload) draw from.  Always
    ``jax.random`` (threefry is exact integer math): the same key gives
    bit-identical noise in any process."""
    leaves, treedef = jax.tree_util.tree_flatten(client_params)
    keys = jax.random.split(key, len(leaves))
    out = [
        (l.astype(jnp.float32) + stddev * jax.random.normal(k, l.shape)).astype(
            l.dtype
        )
        for l, k in zip(leaves, keys)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def agg_noise_key(seed_key: jax.Array, round_idx, slot) -> jax.Array:
    """The aggregation-defense key for (round, GLOBAL slot): the exact
    per-slot stream ``make_round_fn`` hands its ``aggregate_transform``
    — one derivation for the engine, the sim and the server."""
    k_round = jax.random.fold_in(seed_key, round_idx)
    return jax.random.fold_in(
        jax.random.fold_in(k_round, AGG_STREAM), slot
    )


def add_weak_dp_noise(
    stacked_client_vars: PyTree, rngs: jax.Array, stddev: float
) -> PyTree:
    """Gaussian noise on each client's parameters (weak-DP defense).

    ``rngs`` is [K] per-client keys (derived from GLOBAL slot ids by the
    round engine) so noise is independent per client even when the
    client block is sharded across devices.
    """
    noised = jax.vmap(lambda k, p: noise_params(k, p, stddev))(
        rngs, stacked_client_vars["params"]
    )
    return {**stacked_client_vars, "params": noised}


def coordinate_median(stacked_params: PyTree, *, xp=jnp) -> PyTree:
    """Coordinate-wise median across the client axis: [K, ...] → [...].
    The Byzantine-robust location estimator — up to ⌈K/2⌉−1 arbitrary
    uploads move each coordinate at most to the next honest value."""
    return jax.tree_util.tree_map(
        lambda s: xp.median(s.astype(xp.float32), axis=0).astype(s.dtype),
        stacked_params,
    )


def trimmed_mean(stacked_params: PyTree, trim_frac: float,
                 *, xp=jnp) -> PyTree:
    """Coordinate-wise trimmed mean: sort each coordinate across the K
    clients, drop ``floor(trim_frac * K)`` from EACH end, average the
    rest.  ``trim_frac`` < 0.5; robust to that fraction of Byzantine
    clients per coordinate (Yin et al. 2018)."""
    if not 0.0 <= trim_frac < 0.5:
        raise ValueError(f"trim_frac must be in [0, 0.5): {trim_frac!r}")

    def one(s):
        k = s.shape[0]
        # trim_frac < 0.5 guarantees 2·cut < k for every k >= 1, so at
        # least one row always survives the trim
        cut = int(trim_frac * k)
        srt = xp.sort(s.astype(xp.float32), axis=0)
        kept = srt[cut:k - cut] if cut else srt
        return xp.mean(kept, axis=0).astype(s.dtype)

    return jax.tree_util.tree_map(one, stacked_params)


def robust_center(defense_type: str, stacked_params: PyTree,
                  *, trim_frac: float = 0.2, xp=jnp) -> PyTree:
    """The buffered-mode estimator dispatch: one name → one formula,
    used verbatim by the sim transform (xp=jnp, in-jit) and the
    cross-device server's buffered close (xp=np, host-side)."""
    if defense_type == "median":
        return coordinate_median(stacked_params, xp=xp)
    if defense_type == "trimmed_mean":
        return trimmed_mean(stacked_params, trim_frac, xp=xp)
    raise ValueError(
        f"unknown buffered defense {defense_type!r} "
        "(expected 'median' or 'trimmed_mean')"
    )


DEFENSE_TYPES = ("norm_diff_clipping", "weak_dp", "median", "trimmed_mean")


def make_robust_transform(
    defense_type: str = "norm_diff_clipping",
    *,
    norm_bound: float = 30.0,
    stddev: float = 0.025,
    trim_frac: float = 0.2,
):
    """Aggregate-transform hook: (old_vars, stacked, weights, rngs[K]) → stacked.

    Defense knobs mirror the reference CLI
    (``main_fedavg_robust.py:56-62``): ``norm_diff_clipping`` or
    ``weak_dp`` (which clips then noises, ``FedAvgRobustAggregator.py:166-220``)
    — plus the buffered Byzantine estimators ``median`` /
    ``trimmed_mean``, expressed in the SAME hook shape: every client's
    entry is replaced by the robust center, so the engine's downstream
    weighted mean of identical entries IS the center and one hook
    signature serves all four defenses.
    """

    if defense_type not in DEFENSE_TYPES:
        raise ValueError(
            f"unknown defense_type {defense_type!r}; "
            f"expected one of {DEFENSE_TYPES}"
        )

    def transform(global_vars, stacked, weights, rngs):
        del weights
        if defense_type in ("median", "trimmed_mean"):
            center = robust_center(
                defense_type, stacked["params"], trim_frac=trim_frac
            )
            broadcast = jax.tree_util.tree_map(
                lambda c, s: jnp.broadcast_to(c[None], s.shape).astype(
                    s.dtype
                ),
                center, stacked["params"],
            )
            return {**stacked, "params": broadcast}
        stacked = clip_client_updates(global_vars, stacked, norm_bound)
        if defense_type == "weak_dp":
            stacked = add_weak_dp_noise(stacked, rngs, stddev)
        return stacked

    return transform
