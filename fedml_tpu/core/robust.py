"""Robust aggregation defenses.

Reference ``fedml_core/robustness/robust_aggregation.py``:
- ``vectorize_weight`` flattens all parameters EXCLUDING BatchNorm
  running statistics (``:28-29``) for norm computation;
- norm-difference clipping ``w_t + clip(w_local − w_t)`` with bound
  ``norm_bound`` (``:38-49``);
- weak differential privacy: add N(0, stddev²) noise (``:51-55``).

Here both are pure functions over stacked client variable pytrees,
usable as the round engine's ``aggregate_transform`` hook so the
defense runs inside the same compiled program as the psum.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

PyTree = Any


def _param_diff_norms(global_params: PyTree, stacked_params: PyTree) -> jax.Array:
    """[K] L2 norm of (w_i − w_global), over parameters only (BN stats are
    a separate collection in our variables tree and never enter here)."""
    sq = jax.tree_util.tree_map(
        lambda g, s: jnp.sum(
            jnp.square(s.astype(jnp.float32) - g[None].astype(jnp.float32)),
            axis=tuple(range(1, s.ndim)),
        ),
        global_params,
        stacked_params,
    )
    return jnp.sqrt(sum(jax.tree_util.tree_leaves(sq)))


def clip_client_updates(
    global_vars: PyTree, stacked_client_vars: PyTree, norm_bound: float
) -> PyTree:
    """Per-client norm-difference clipping of parameter deltas."""
    norms = _param_diff_norms(global_vars["params"], stacked_client_vars["params"])
    scale = jnp.minimum(1.0, norm_bound / jnp.maximum(norms, 1e-12))  # [K]
    clipped = jax.tree_util.tree_map(
        lambda g, s: (
            g[None].astype(jnp.float32)
            + jnp.einsum(
                "k,k...->k...",
                scale,
                s.astype(jnp.float32) - g[None].astype(jnp.float32),
            )
        ).astype(s.dtype),
        global_vars["params"],
        stacked_client_vars["params"],
    )
    return {**stacked_client_vars, "params": clipped}


def add_weak_dp_noise(
    stacked_client_vars: PyTree, rngs: jax.Array, stddev: float
) -> PyTree:
    """Gaussian noise on each client's parameters (weak-DP defense).

    ``rngs`` is [K] per-client keys (derived from GLOBAL slot ids by the
    round engine) so noise is independent per client even when the
    client block is sharded across devices.
    """

    def noise_one(key, client_params):
        leaves, treedef = jax.tree_util.tree_flatten(client_params)
        keys = jax.random.split(key, len(leaves))
        out = [
            (l.astype(jnp.float32) + stddev * jax.random.normal(k, l.shape)).astype(
                l.dtype
            )
            for l, k in zip(leaves, keys)
        ]
        return jax.tree_util.tree_unflatten(treedef, out)

    noised = jax.vmap(noise_one)(rngs, stacked_client_vars["params"])
    return {**stacked_client_vars, "params": noised}


def make_robust_transform(
    defense_type: str = "norm_diff_clipping",
    *,
    norm_bound: float = 30.0,
    stddev: float = 0.025,
):
    """Aggregate-transform hook: (old_vars, stacked, weights, rngs[K]) → stacked.

    Defense knobs mirror the reference CLI
    (``main_fedavg_robust.py:56-62``): ``norm_diff_clipping`` or
    ``weak_dp`` (which clips then noises, ``FedAvgRobustAggregator.py:166-220``).
    """

    if defense_type not in ("norm_diff_clipping", "weak_dp"):
        raise ValueError(
            f"unknown defense_type {defense_type!r}; "
            "expected 'norm_diff_clipping' or 'weak_dp'"
        )

    def transform(global_vars, stacked, weights, rngs):
        del weights
        stacked = clip_client_updates(global_vars, stacked, norm_bound)
        if defense_type == "weak_dp":
            stacked = add_weak_dp_noise(stacked, rngs, stddev)
        return stacked

    return transform
