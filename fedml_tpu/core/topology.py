"""Topology managers for decentralized FL.

Reference ``fedml_core/distributed/topology/``:
- ``SymmetricTopologyManager.generate_topology``
  (``symmetric_topology_manager.py:21-52``): ring + Watts–Strogatz-style
  random symmetric links (``neighbor_num`` per node), row-normalized to
  a doubly-stochastic-ish mixing matrix.
- ``AsymmetricTopologyManager`` (``asymmetric_topology_manager.py:23-74``):
  same undirected base, then randomly deletes directed links and
  row-normalizes — rows no longer match columns.
- ``BaseTopologyManager`` API (``base_topology_manager.py:4-23``):
  in/out neighbor index and weight queries per node.

The matrices are built host-side with numpy/networkx (one-off setup, not
a TPU op); the gossip round consumes them as a dense [N,N] mixing matrix
(``einsum`` on-device) or as ppermute schedules for sparse rings.
"""

from __future__ import annotations

from typing import List

import networkx as nx
import numpy as np


class BaseTopologyManager:
    """In/out neighbor queries over a row-stochastic mixing matrix."""

    topology: np.ndarray  # [N, N]; row i = weights node i uses to mix IN

    def get_in_neighbor_idx_list(self, node_index: int) -> List[int]:
        return [
            j for j in range(self.n) if self.topology[node_index, j] > 0 and j != node_index
        ]

    def get_out_neighbor_idx_list(self, node_index: int) -> List[int]:
        return [
            i for i in range(self.n) if self.topology[i, node_index] > 0 and i != node_index
        ]

    def get_in_neighbor_weights(self, node_index: int) -> List[float]:
        return self.topology[node_index].tolist()

    def get_out_neighbor_weights(self, node_index: int) -> List[float]:
        return self.topology[:, node_index].tolist()

    @property
    def n(self) -> int:
        return self.topology.shape[0]


def _ring_plus_random(n: int, neighbor_num: int, seed: int) -> np.ndarray:
    """Symmetric 0/1 adjacency: ring + random extra symmetric links,
    self-loops included (a node always keeps its own model)."""
    if n == 1:
        return np.ones((1, 1))
    # connected Watts-Strogatz ring lattice with k neighbors, then add
    # random symmetric links like the reference's second phase
    k = max(2, min(neighbor_num, n - 1))
    g = nx.watts_strogatz_graph(n, k if k % 2 == 0 else k + 1, 0.0, seed=seed)
    adj = nx.to_numpy_array(g)
    rng = np.random.RandomState(seed)
    extra = max(0, neighbor_num - 2)
    for i in range(n):
        candidates = [j for j in range(n) if j != i and adj[i, j] == 0]
        rng.shuffle(candidates)
        for j in candidates[:extra]:
            adj[i, j] = adj[j, i] = 1
    np.fill_diagonal(adj, 1)
    return adj


class SymmetricTopologyManager(BaseTopologyManager):
    """Undirected topology, row-normalized to uniform neighbor weights."""

    def __init__(self, n: int, neighbor_num: int = 2, seed: int = 0):
        self._n = n
        self.neighbor_num = neighbor_num
        self.seed = seed
        self.topology = np.zeros((n, n))

    def generate_topology(self):
        adj = _ring_plus_random(self._n, self.neighbor_num, self.seed)
        self.topology = adj / adj.sum(axis=1, keepdims=True)
        return self.topology


class AsymmetricTopologyManager(BaseTopologyManager):
    """Symmetric base with randomly deleted directed links (reference's
    ``undirected_neighbor_num`` then per-row pruning), row-normalized."""

    def __init__(
        self,
        n: int,
        undirected_neighbor_num: int = 3,
        out_directed_neighbor: int = 2,
        seed: int = 0,
    ):
        self._n = n
        self.undirected_neighbor_num = undirected_neighbor_num
        self.out_directed_neighbor = out_directed_neighbor
        self.seed = seed
        self.topology = np.zeros((n, n))

    def generate_topology(self):
        adj = _ring_plus_random(self._n, self.undirected_neighbor_num, self.seed)
        rng = np.random.RandomState(self.seed + 1)
        n = self._n
        for i in range(n):
            # ring links (i±1) are never pruned: the directed graph must
            # stay strongly connected or PushSum weights collapse onto a
            # sink node (u_i → 0 ⇒ z_i/u_i diverges)
            ring = {(i - 1) % n, (i + 1) % n}
            extra = [j for j in range(n) if j != i and adj[i, j] > 0 and j not in ring]
            rng.shuffle(extra)
            for j in extra[self.out_directed_neighbor:]:
                adj[i, j] = 0
        np.fill_diagonal(adj, 1)
        self.topology = adj / adj.sum(axis=1, keepdims=True)
        return self.topology


def ring_topology(n: int) -> np.ndarray:
    """Plain ring mixing matrix (1/3 self, 1/3 left, 1/3 right) — the
    sparse case that maps to ``lax.ppermute`` on an ICI ring."""
    w = np.zeros((n, n))
    for i in range(n):
        w[i, i] = w[i, (i - 1) % n] = w[i, (i + 1) % n] = 1.0
    return w / w.sum(axis=1, keepdims=True)
