"""Checkpoint/resume for federated training state.

The reference has NO training-state checkpointing — only static
pretrained weight loading at model construction
(``model/cv/resnet.py:202-224``; SURVEY.md §5.4).  Here the full round
state — (global variables, server optimizer state, round index, RNG
key) — is one explicit pytree, so persistence is orbax on that tree:
resume == load + continue, bit-identical.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

PyTree = Any


class UnreadableCheckpoint(Exception):
    """An on-disk checkpoint artifact that cannot be decoded (truncated
    by a crash, garbage bytes, half-synced step dir) — distinct from a
    TEMPLATE mismatch, which is a caller config error and always raises."""


def _flatten_for_npz(tree: PyTree) -> dict:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    out["__treedef__"] = np.frombuffer(
        repr(treedef).encode(), dtype=np.uint8
    )
    return out


def _check_leaf_shapes(template: PyTree, restored: PyTree) -> None:
    """Orbax StandardRestore and the npz path both match tree structure
    but not leaf shapes; a checkpoint from a differently-sized model
    would otherwise surface only as a distant jit shape error."""
    bad = []

    def cmp(path, tpl, val):
        if tuple(np.shape(tpl)) != tuple(np.shape(val)):
            bad.append(
                f"{jax.tree_util.keystr(path)}: saved {np.shape(val)} "
                f"vs template {np.shape(tpl)}"
            )

    jax.tree_util.tree_map_with_path(cmp, template, restored)
    if bad:
        raise ValueError(
            "checkpoint leaf shapes do not match the restore template "
            "(was the model built with different hyperparameters?):\n "
            + "\n ".join(bad)
        )


class CheckpointManager:
    """Orbax-backed checkpoint manager with an npz fallback.

    Usage::

        mgr = CheckpointManager(dir, max_to_keep=3)
        mgr.save(step, state)           # state: any pytree (e.g. ServerState)
        state = mgr.restore(like=state) # latest step, template for structure
        mgr.latest_step()
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._mgr = None
        if os.environ.get("FEDML_TPU_NPZ_CKPT") == "1":
            # forced npz fallback: lets tests (and orbax-less deploys)
            # exercise the atomic-write/skip-corrupt path on a box where
            # orbax happens to be installed
            self._ocp = None
            return
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True
                ),
            )
        except Exception:
            self._ocp = None  # npz fallback

    # ---- orbax path ---------------------------------------------------
    def save(self, step: int, state: PyTree) -> None:
        state = jax.tree_util.tree_map(np.asarray, state)
        if self._mgr is not None:
            self._mgr.save(
                step, args=self._ocp.args.StandardSave(state)
            )
            self._mgr.wait_until_finished()
            return
        # write-then-rename: np.savez straight to the final path would
        # leave a TRUNCATED ckpt_<latest>.npz if the process dies
        # mid-save — corrupting exactly the checkpoint resume wants.
        # os.replace is atomic on POSIX, so the final name only ever
        # holds a complete archive.
        final = os.path.join(self.directory, f"ckpt_{step}.npz")
        tmp = final + ".tmp"
        try:
            with open(tmp, "wb") as fh:
                np.savez(fh, **_flatten_for_npz(state))
            os.replace(tmp, final)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        self._gc_npz()

    def latest_step(self) -> Optional[int]:
        if self._mgr is not None:
            return self._mgr.latest_step()
        steps = self._npz_steps()
        return max(steps) if steps else None

    def restore(self, like: PyTree, step: Optional[int] = None) -> PyTree:
        """Restore ``step`` (default: latest READABLE) with ``like`` as
        the structure/dtype template.

        With no explicit ``step``, unreadable checkpoints (truncated by
        a crash, half-synced, garbage bytes) are SKIPPED with a warning
        and the next-newest step is tried — a fault-tolerant run must
        not die on the artifact a previous crash left behind.  Template
        mismatches (wrong treedef / leaf shapes: a checkpoint from a
        DIFFERENT model) still raise — that is a config error, not
        corruption.  An explicit ``step`` raises on any failure."""
        if step is not None:
            return self._restore_step(step, like)
        steps = self._all_steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        last_err: Optional[Exception] = None
        for s in sorted(steps, reverse=True):
            try:
                return self._restore_step(s, like)
            except UnreadableCheckpoint as e:  # corrupt artifact: try older
                import logging

                last_err = e
                logging.warning(
                    "checkpoint step %d in %s is unreadable (%s) — "
                    "trying the previous one", s, self.directory,
                    e.__cause__ or e,
                )
        raise FileNotFoundError(
            f"no READABLE checkpoint in {self.directory} "
            f"(steps tried: {sorted(steps, reverse=True)})"
        ) from last_err

    def _all_steps(self):
        if self._mgr is not None:
            return list(self._mgr.all_steps())
        return self._npz_steps()

    def _restore_step(self, step: int, like: PyTree) -> PyTree:
        template = jax.tree_util.tree_map(np.asarray, like)
        if self._mgr is not None:
            try:
                restored = self._mgr.restore(
                    step, args=self._ocp.args.StandardRestore(template)
                )
            except ValueError:
                raise  # orbax structure mismatch: config error
            except Exception as e:  # half-written step dir etc.
                raise UnreadableCheckpoint(
                    f"orbax step {step} unreadable"
                ) from e
        else:
            leaves, treedef = jax.tree_util.tree_flatten(template)
            # decode failures classify as "unreadable" (skipped by the
            # latest-readable scan); only a CLEANLY-read treedef that
            # disagrees is a template/config error.  The treedef is
            # compared BEFORE indexing template-counted leaf keys —
            # otherwise a complete archive from a SMALLER model would
            # KeyError on leaf_<i> and masquerade as corruption.
            path = os.path.join(self.directory, f"ckpt_{step}.npz")
            try:
                with np.load(path) as z:
                    saved_def = bytes(z["__treedef__"]).decode()
                    raw = None
                    if saved_def == repr(treedef):
                        raw = [np.array(z[f"leaf_{i}"])
                               for i in range(len(leaves))]
            except Exception as e:
                raise UnreadableCheckpoint(
                    f"npz step {step} unreadable"
                ) from e
            if raw is None:
                raise ValueError(
                    "checkpoint tree structure does not match the restore "
                    f"template:\n saved: {saved_def}\n template: {treedef!r}"
                )
            restored = jax.tree_util.tree_unflatten(treedef, raw)
        _check_leaf_shapes(template, restored)
        # match the template's leaf dtypes/types (jnp arrays where needed)
        return jax.tree_util.tree_map(
            lambda tpl, val: np.asarray(val, dtype=np.asarray(tpl).dtype),
            like, restored,
        )

    # ---- npz fallback helpers ----------------------------------------
    def _npz_steps(self):
        # strict ckpt_<int>.npz match: a stray ckpt_old.npz or backup
        # copy in the directory must not crash latest_step()/restore()
        # (the skip-unreadable machinery is pointless if step LISTING
        # dies on garbage first)
        import re

        steps = []
        for f in os.listdir(self.directory):
            m = re.fullmatch(r"ckpt_(\d+)\.npz", f)
            if m:
                steps.append(int(m.group(1)))
        return steps

    def _gc_npz(self):
        steps = sorted(self._npz_steps())
        for s in steps[: -self.max_to_keep]:
            os.remove(os.path.join(self.directory, f"ckpt_{s}.npz"))
