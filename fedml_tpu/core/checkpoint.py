"""Checkpoint/resume for federated training state.

The reference has NO training-state checkpointing — only static
pretrained weight loading at model construction
(``model/cv/resnet.py:202-224``; SURVEY.md §5.4).  Here the full round
state — (global variables, server optimizer state, round index, RNG
key) — is one explicit pytree, so persistence is orbax on that tree:
resume == load + continue, bit-identical.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

PyTree = Any


def _flatten_for_npz(tree: PyTree) -> dict:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    out["__treedef__"] = np.frombuffer(
        repr(treedef).encode(), dtype=np.uint8
    )
    return out


def _check_leaf_shapes(template: PyTree, restored: PyTree) -> None:
    """Orbax StandardRestore and the npz path both match tree structure
    but not leaf shapes; a checkpoint from a differently-sized model
    would otherwise surface only as a distant jit shape error."""
    bad = []

    def cmp(path, tpl, val):
        if tuple(np.shape(tpl)) != tuple(np.shape(val)):
            bad.append(
                f"{jax.tree_util.keystr(path)}: saved {np.shape(val)} "
                f"vs template {np.shape(tpl)}"
            )

    jax.tree_util.tree_map_with_path(cmp, template, restored)
    if bad:
        raise ValueError(
            "checkpoint leaf shapes do not match the restore template "
            "(was the model built with different hyperparameters?):\n "
            + "\n ".join(bad)
        )


class CheckpointManager:
    """Orbax-backed checkpoint manager with an npz fallback.

    Usage::

        mgr = CheckpointManager(dir, max_to_keep=3)
        mgr.save(step, state)           # state: any pytree (e.g. ServerState)
        state = mgr.restore(like=state) # latest step, template for structure
        mgr.latest_step()
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._mgr = None
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True
                ),
            )
        except Exception:
            self._ocp = None  # npz fallback

    # ---- orbax path ---------------------------------------------------
    def save(self, step: int, state: PyTree) -> None:
        state = jax.tree_util.tree_map(np.asarray, state)
        if self._mgr is not None:
            self._mgr.save(
                step, args=self._ocp.args.StandardSave(state)
            )
            self._mgr.wait_until_finished()
            return
        np.savez(
            os.path.join(self.directory, f"ckpt_{step}.npz"),
            **_flatten_for_npz(state),
        )
        self._gc_npz()

    def latest_step(self) -> Optional[int]:
        if self._mgr is not None:
            return self._mgr.latest_step()
        steps = self._npz_steps()
        return max(steps) if steps else None

    def restore(self, like: PyTree, step: Optional[int] = None) -> PyTree:
        """Restore ``step`` (default: latest) with ``like`` as the
        structure/dtype template."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        template = jax.tree_util.tree_map(np.asarray, like)
        if self._mgr is not None:
            restored = self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(template)
            )
        else:
            z = np.load(os.path.join(self.directory, f"ckpt_{step}.npz"))
            leaves, treedef = jax.tree_util.tree_flatten(template)
            saved_def = bytes(z["__treedef__"]).decode()
            if saved_def != repr(treedef):
                raise ValueError(
                    "checkpoint tree structure does not match the restore "
                    f"template:\n saved: {saved_def}\n template: {treedef!r}"
                )
            restored = jax.tree_util.tree_unflatten(
                treedef, [z[f"leaf_{i}"] for i in range(len(leaves))]
            )
        _check_leaf_shapes(template, restored)
        # match the template's leaf dtypes/types (jnp arrays where needed)
        return jax.tree_util.tree_map(
            lambda tpl, val: np.asarray(val, dtype=np.asarray(tpl).dtype),
            like, restored,
        )

    # ---- npz fallback helpers ----------------------------------------
    def _npz_steps(self):
        return [
            int(f[len("ckpt_"):-len(".npz")])
            for f in os.listdir(self.directory)
            if f.startswith("ckpt_") and f.endswith(".npz")
        ]

    def _gc_npz(self):
        steps = sorted(self._npz_steps())
        for s in steps[: -self.max_to_keep]:
            os.remove(os.path.join(self.directory, f"ckpt_{s}.npz"))
