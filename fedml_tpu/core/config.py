"""Typed config tree with CLI override.

The reference configures every experiment through per-main argparse
blocks (~20 flags each, canonical set at
``fedml_experiments/distributed/fedavg/main_fedavg.py:46-105``) plus
positional shell wrappers.  Here one dataclass is the single source of
truth: ``cli_parser`` derives an argparse parser from any dataclass's
fields (names, types, defaults, docstrings), so every experiment main is
``cfg = parse_config(ExperimentConfig, argv)`` and the run record is
``asdict(cfg)`` — serialized, diffable, reproducible (SURVEY.md §5.6).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import typing
from typing import Any, Optional, Sequence, Type, TypeVar, get_args, get_origin

T = TypeVar("T")


def _field_types(cls) -> dict:
    """Resolved annotations (PEP 563 postpones them to strings)."""
    try:
        return typing.get_type_hints(cls)
    except Exception:
        return {f.name: f.type for f in dataclasses.fields(cls)}


def _arg_type(ftype):
    origin = get_origin(ftype)
    if origin is not None:  # Optional[X] / Union
        args = [a for a in get_args(ftype) if a is not type(None)]
        if len(args) == 1:
            return _arg_type(args[0])
        return str
    if ftype is bool:
        return None  # handled as flag pair
    return ftype


def cli_parser(
    cls: Type, parser: Optional[argparse.ArgumentParser] = None,
    prefix: str = "",
) -> argparse.ArgumentParser:
    """Build (or extend) an argparse parser from a dataclass.

    Nested dataclass fields become dotted flags (``--server.lr``).
    Booleans get ``--flag`` / ``--no-flag`` pairs.
    """
    parser = parser or argparse.ArgumentParser(
        description=(cls.__doc__ or "").strip().splitlines()[0]
        if cls.__doc__ else None
    )
    hints = _field_types(cls)
    for f in dataclasses.fields(cls):
        name = f"{prefix}{f.name}"
        ftype = hints.get(f.name, f.type)
        if dataclasses.is_dataclass(ftype if isinstance(ftype, type) else None):
            cli_parser(ftype, parser, prefix=f"{name}.")
            continue
        default = (
            f.default
            if f.default is not dataclasses.MISSING
            else (f.default_factory() if f.default_factory is not dataclasses.MISSING else None)
        )
        if dataclasses.is_dataclass(type(default)):
            cli_parser(type(default), parser, prefix=f"{name}.")
            continue
        atype = _arg_type(ftype) if isinstance(ftype, type) or get_origin(ftype) else str
        if ftype is bool or atype is None and isinstance(default, bool):
            group = parser.add_mutually_exclusive_group()
            group.add_argument(f"--{name}", dest=name, action="store_true",
                               default=default)
            group.add_argument(f"--no-{name}", dest=name, action="store_false")
        else:
            if not callable(atype):
                atype = str
            parser.add_argument(f"--{name}", type=atype, default=default)
    return parser


def parse_config(cls: Type[T], argv: Optional[Sequence[str]] = None) -> T:
    """Parse argv into an instance of the dataclass ``cls``."""
    ns = vars(cli_parser(cls).parse_args(argv))

    def build(c, prefix=""):
        kwargs = {}
        hints = _field_types(c)
        for f in dataclasses.fields(c):
            name = f"{prefix}{f.name}"
            hint = hints.get(f.name, f.type)
            ft = hint if isinstance(hint, type) else None
            default = (
                f.default if f.default is not dataclasses.MISSING
                else (f.default_factory() if f.default_factory is not dataclasses.MISSING else None)
            )
            if dataclasses.is_dataclass(ft):
                kwargs[f.name] = build(ft, prefix=f"{name}.")
            elif dataclasses.is_dataclass(type(default)):
                kwargs[f.name] = build(type(default), prefix=f"{name}.")
            else:
                kwargs[f.name] = ns.get(name, default)
        return c(**kwargs)

    return build(cls)


def config_to_json(cfg: Any) -> str:
    """Serialize any (nested) dataclass config to one JSON line — the
    run record."""
    return json.dumps(dataclasses.asdict(cfg), default=str, sort_keys=True)
