"""Finite-field MPC primitives for secure aggregation (TurboAggregate).

Reference: ``fedml_api/distributed/turboaggregate/mpc_function.py`` —
``modular_inv:4``, ``gen_Lagrange_coeffs:38``, ``BGW_encoding:62``,
``BGW_decoding:91``, ``LCC_encoding*:110-193``, ``LCC_decoding:196``,
``Gen_Additive_SS:216``.

TPU-native design: coefficient generation (tiny, O(N²) scalar field
ops) stays on host in exact Python/numpy integers; the bulk
encode/decode — the O(N·m·d) share matmuls — run as jnp int64 ops
under jit.  With a prime p < 2³¹ every product of two residues is
< 2⁶², so an int64 multiply-accumulate with a mod after every term
never overflows; the accumulation is a ``lax.scan`` over the (small)
share dimension, vectorized over everything else.  Fixed-point
quantization maps float updates into the field with negatives as
p − |v| (two's-complement-style), so aggregation in the field equals
quantized aggregation in the reals — tested exactly.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax

from fedml_tpu.parallel.compat import enable_x64
import jax.numpy as jnp
import numpy as np

# Mersenne prime 2^31 - 1: largest field with overflow-free int64 modmul.
DEFAULT_PRIME = (1 << 31) - 1


# --- host-side exact scalar field math (coefficient generation) -------------

def modular_inv(a: int, p: int = DEFAULT_PRIME) -> int:
    """a⁻¹ mod p (Fermat; p prime). Exact Python ints — no overflow."""
    return pow(int(a) % p, p - 2, p)


def field_div(num: int, den: int, p: int = DEFAULT_PRIME) -> int:
    return (int(num) % p) * modular_inv(den, p) % p


def gen_lagrange_coeffs(
    alphas: Sequence[int], betas: Sequence[int], p: int = DEFAULT_PRIME
) -> np.ndarray:
    """U[i, j] = ∏_{o≠j} (αᵢ − β_o) / (β_j − β_o) mod p
    (reference ``gen_Lagrange_coeffs``, exact semantics, exact ints)."""
    alphas = [int(a) % p for a in alphas]
    betas = [int(b) % p for b in betas]
    U = np.zeros((len(alphas), len(betas)), dtype=np.int64)
    for i, a in enumerate(alphas):
        for j, bj in enumerate(betas):
            num, den = 1, 1
            for o in betas:
                if o != bj:
                    num = num * ((a - o) % p) % p
                    den = den * ((bj - o) % p) % p
            U[i, j] = field_div(num, den, p)
    return U


# --- device-side bulk share arithmetic --------------------------------------
#
# All jnp work below runs under ``enable_x64()`` (compat shim): without the x64
# flag jnp silently truncates int64 → int32, which corrupts the field
# math.  The context is entered per public call; compiled int64 kernels
# are cached as usual.

@partial(jax.jit, static_argnames=("p",))
def _coeff_combine(U: jax.Array, X: jax.Array, p: int) -> jax.Array:
    def body(acc, uj_xj):
        u_j, x_j = uj_xj  # [N], [...]
        term = (u_j.reshape((-1,) + (1,) * x_j.ndim) * x_j[None]) % p
        return (acc + term) % p, None

    acc0 = jnp.zeros((U.shape[0],) + X.shape[1:], jnp.int64)
    acc, _ = jax.lax.scan(body, acc0, (U.T, X))
    return acc


def coeff_combine(U, X, p: int = DEFAULT_PRIME) -> jax.Array:
    """Y[i] = Σ_j U[i, j]·X[j] mod p, overflow-free.

    U: [N, S] residues; X: [S, ...] residues; Y: [N, ...].  A scan over
    the S share terms with a mod per step keeps every intermediate
    < 2⁶² + 2³¹ in int64.
    """
    with enable_x64():
        U = jnp.asarray(np.asarray(U), jnp.int64) % p
        X = jnp.asarray(np.asarray(X), jnp.int64) % p
        return _coeff_combine(U, X, p)


def _lcc_grids(n: int, s: int, p: int) -> Tuple[np.ndarray, np.ndarray]:
    """(alphas[n], betas[s]) for LCC: betas are the interpolation points,
    alphas the share evaluation points.

    DELIBERATE DEFECT FIX vs the reference: ``LCC_encoding:122-125``
    centers both ranges, making β ⊂ α — a worker whose α equals β_j
    holds data chunk j in PLAINTEXT, so the T random chunks protect
    nothing for those workers.  LCC privacy requires the grids disjoint;
    here betas = 0..s−1 and alphas = s..s+n−1.
    """
    betas = np.arange(0, s)
    alphas = np.arange(s, s + n)
    return (
        np.mod(alphas, p).astype(np.int64),
        np.mod(betas, p).astype(np.int64),
    )


# --- BGW (Shamir) secret sharing --------------------------------------------

def bgw_encode(x: jax.Array, n: int, t: int, key: jax.Array,
               p: int = DEFAULT_PRIME) -> jax.Array:
    """Degree-t Shamir shares of ``x`` (field residues, any shape) for
    n parties at points α=1..n: share_i = Σ_k R_k·αᵢᵏ with R_0 = x
    (reference ``BGW_encoding:62-76``)."""
    with enable_x64():
        x = jnp.asarray(np.asarray(x), jnp.int64) % p
        R = jax.random.randint(key, (t,) + x.shape, 0, p, dtype=jnp.int64)
        coeffs = jnp.concatenate([x[None], R], axis=0)  # [t+1, ...]
    alphas = np.arange(1, n + 1, dtype=np.int64) % p
    # Vandermonde α_i^k mod p, exact on host
    V = np.ones((n, t + 1), dtype=np.int64)
    for k in range(1, t + 1):
        V[:, k] = V[:, k - 1] * alphas % p
    return coeff_combine(V, coeffs, p)


def bgw_decode(shares: jax.Array, worker_idx: Sequence[int],
               p: int = DEFAULT_PRIME) -> jax.Array:
    """Reconstruct the secret from ≥ t+1 shares via Lagrange at 0
    (reference ``BGW_decoding:91-108``; ``worker_idx`` are 0-based)."""
    alphas = [(i + 1) % p for i in worker_idx]
    lam = gen_lagrange_coeffs([0], alphas, p)  # [1, R]
    return coeff_combine(lam, shares, p)[0]


# --- LCC (Lagrange coded computing) -----------------------------------------

def lcc_encode(x: jax.Array, n: int, k: int, t: int, key: jax.Array,
               p: int = DEFAULT_PRIME) -> jax.Array:
    """Split ``x`` (leading dim divisible by k) into k chunks + t random
    chunks, interpolate through β-points, evaluate at n α-points
    (reference ``LCC_encoding:110-135``).  Returns [n, m/k, ...]."""
    with enable_x64():
        x = jnp.asarray(np.asarray(x), jnp.int64) % p
        m = x.shape[0]
        assert m % k == 0, f"leading dim {m} not divisible by K={k}"
        chunks = x.reshape((k, m // k) + x.shape[1:])
        if t > 0:
            R = jax.random.randint(
                key, (t,) + tuple(chunks.shape[1:]), 0, p, dtype=jnp.int64
            )
            chunks = jnp.concatenate([chunks, R], axis=0)
    alphas, betas = _lcc_grids(n, k + t, p)
    U = gen_lagrange_coeffs(alphas, betas, p)
    return coeff_combine(U, chunks, p)


def lcc_decode(shares: jax.Array, worker_idx: Sequence[int], n: int,
               num_chunks: int, p: int = DEFAULT_PRIME) -> jax.Array:
    """Recover ALL ``num_chunks`` = K+T interpolated chunk rows from the
    shares of ≥ num_chunks workers in ``worker_idx`` (reference
    ``LCC_decoding:196-212``).  The first K rows (after reshape) are the
    data chunks; callers slice off the trailing T random rows.  Pass the
    SAME K+T used at encode time — a smaller grid silently reconstructs
    garbage.  Returns [num_chunks·m', ...]."""
    alphas, betas = _lcc_grids(n, num_chunks, p)
    alpha_eval = [int(alphas[i]) for i in worker_idx]
    U = gen_lagrange_coeffs(betas, alpha_eval, p)
    out = coeff_combine(U, shares, p)
    return out.reshape((-1,) + tuple(out.shape[2:]))


# --- additive secret sharing -------------------------------------------------

def additive_shares(x: jax.Array, n: int, key: jax.Array,
                    p: int = DEFAULT_PRIME) -> jax.Array:
    """n shares summing to x mod p (reference ``Gen_Additive_SS:216-227``)."""
    with enable_x64():
        x = jnp.asarray(np.asarray(x), jnp.int64) % p
        r = jax.random.randint(key, (n - 1,) + tuple(x.shape), 0, p, dtype=jnp.int64)
        last = (x - r.sum(axis=0) % p) % p
        return jnp.concatenate([r, last[None]], axis=0)


def field_sum(shares, p: int = DEFAULT_PRIME) -> jax.Array:
    """Σ over the leading axis, mod p (server-side share aggregation)."""
    with enable_x64():
        s = jnp.asarray(np.asarray(shares), jnp.int64) % p

        def body(acc, row):
            return (acc + row) % p, None

        acc, _ = jax.lax.scan(body, jnp.zeros(s.shape[1:], jnp.int64), s)
        return acc


# --- fixed-point quantization (host boundary, exact float64) -----------------

def quantize(x, scale: float = 2.0 ** 16, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Float → field: round(x·scale), negatives as p − |·|.  Values must
    satisfy |x|·scale·n_parties < p/2 for exact aggregate recovery."""
    v = np.round(np.asarray(x, np.float64) * scale).astype(np.int64)
    return np.mod(np.where(v < 0, v + p, v), p)


def dequantize(v, scale: float = 2.0 ** 16, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Field → float, centered lift: residues > p/2 are negative."""
    v = np.mod(np.asarray(v, np.int64), p)
    signed = np.where(v > p // 2, v - p, v)
    return signed.astype(np.float64) / scale
