"""Federated EMNIST (FEMNIST) and fed_CIFAR100 — TFF h5 natural-user
partitions.

Reference: ``fedml_api/data_preprocessing/FederatedEMNIST/data_loader.py``
(h5 files ``fed_emnist_train.h5``/``fed_emnist_test.h5`` with an
``examples/<client_id>/{pixels,label}`` group per writer, 3400 clients,
62 classes) and ``fed_cifar100/data_loader.py:17-21`` (500 train / 100
test clients, ``image``/``label`` keys).  Natural partition = one h5
group per client; no synthetic re-partitioning is applied when real
files exist.  Offline fallback: synthetic stand-ins with the same
shapes and client counts (scaled down).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from fedml_tpu.core.types import FedDataset
from fedml_tpu.data.synthetic import (
    match_pixel_moments,
    synthetic_classification,
)


def _load_h5_clients(path: str, x_key: str, y_key: str):
    import h5py

    xs, ys, idx = [], [], {}
    off = 0
    with h5py.File(path, "r") as f:
        ex = f["examples"]
        for c, cid in enumerate(sorted(ex.keys())):
            g = ex[cid]
            x = np.asarray(g[x_key])
            y = np.asarray(g[y_key], np.int32)
            xs.append(x)
            ys.append(y)
            idx[c] = np.arange(off, off + len(y))
            off += len(y)
    return np.concatenate(xs), np.concatenate(ys), idx


def load_femnist(
    data_dir: str = "./data/FederatedEMNIST/datasets",
    num_clients: int = 3400,
    only_digits: bool = False,
    seed: int = 0,
    standin_label_noise: float = 0.0,
    standin_max_clients: int = 100,
) -> FedDataset:
    """``standin_label_noise`` / ``standin_max_clients`` apply ONLY to
    the offline synthetic stand-in (the label-noise ceiling makes
    convergence evidence non-saturating, and the benchmark row's full
    3400-client population needs the cap lifted); real TFF h5 data is
    never modified."""
    tr = os.path.join(data_dir, "fed_emnist_train.h5")
    te = os.path.join(data_dir, "fed_emnist_test.h5")
    classes = 10 if only_digits else 62
    if os.path.exists(tr) and os.path.exists(te):
        train_x, train_y, train_idx = _load_h5_clients(tr, "pixels", "label")
        test_x, test_y, test_idx = _load_h5_clients(te, "pixels", "label")
        if train_x.ndim == 3:
            train_x, test_x = train_x[..., None], test_x[..., None]
        return FedDataset(
            train_x=train_x.astype(np.float32), train_y=train_y,
            test_x=test_x.astype(np.float32), test_y=test_y,
            train_client_idx=train_idx, test_client_idx=test_idx,
            num_classes=classes, name="femnist",
        )
    n_cl = min(num_clients, standin_max_clients)
    ds = synthetic_classification(
        num_train=n_cl * 60,
        num_test=min(n_cl * 10, 20000),
        input_shape=(28, 28, 1), num_classes=classes,
        num_clients=n_cl, partition="power_law", seed=seed,
        label_noise=standin_label_noise,
        name="femnist(synthetic-standin)",
    )
    # real LEAF FEMNIST shards span ~10-450 samples/user; the lognormal
    # power-law tail can mint a 4000-sample monster client, and the
    # fixed pack geometry (steps = the GLOBAL max shard / batch, one
    # compile for the whole run) would pad every sampled cohort block to
    # that outlier — ~99% padding compute.  Cap shards at the real
    # distribution's scale.
    cap = 450
    ds.train_client_idx = {
        c: idx[:cap] for c, idx in ds.train_client_idx.items()
    }
    # real FEMNIST pixel moments: the reference feeds TFF h5 "pixels"
    # straight into training with no normalization
    # (fedml_api/data_preprocessing/FederatedEMNIST/data_loader.py),
    # and TFF federated EMNIST stores [0,1] floats in the WHITE-
    # background convention (x = 1 - ink) — mean 1-.1736 = .8264,
    # std .3317 from the published EMNIST ink constants.  Matching the
    # second moment alone NaN'd at the reference lr=.1 (the DC mean
    # carries ~86% of E[x²]; see synthetic.match_pixel_moments).
    return match_pixel_moments(ds, 1.0 - 0.1736, 0.3317)


def load_fed_cifar100(
    data_dir: str = "./data/fed_cifar100/datasets",
    seed: int = 0,
    num_clients: int = 50,
    standin_label_noise: float = 0.0,
    standin_natural_stats: bool = False,
) -> FedDataset:
    """``num_clients`` / ``standin_label_noise`` shape ONLY the offline
    synthetic stand-in (TFF fed-CIFAR100 brings its own natural
    500-client partition of ~100 samples each); real h5 data is never
    modified.  The stand-in's unit-variance features already match the
    reference's normalized pixels (``fed_cifar100/utils.py:16``
    Normalize(mean, std) ⇒ E[x²] ≈ 1) — no pixel-scale correction.
    ``standin_natural_stats`` gives the prototypes the smooth /
    flip-symmetric statistics that keep the reference's crop+flip train
    transform (``utils.py:13-16``) label-preserving, as for the
    CIFAR-10 stand-in."""
    tr = os.path.join(data_dir, "fed_cifar100_train.h5")
    te = os.path.join(data_dir, "fed_cifar100_test.h5")
    if os.path.exists(tr) and os.path.exists(te):
        train_x, train_y, train_idx = _load_h5_clients(tr, "image", "label")
        test_x, test_y, test_idx = _load_h5_clients(te, "image", "label")
        return FedDataset(
            train_x=train_x.astype(np.float32) / 255.0, train_y=train_y,
            test_x=test_x.astype(np.float32) / 255.0, test_y=test_y,
            train_client_idx=train_idx, test_client_idx=test_idx,
            num_classes=100, name="fed_cifar100",
        )
    return synthetic_classification(
        num_train=num_clients * 100, num_test=min(num_clients * 20, 10000),
        input_shape=(24, 24, 3),
        num_classes=100, num_clients=num_clients, partition="homo",
        seed=seed, label_noise=standin_label_noise,
        smooth_sigma=2.0 if standin_natural_stats else 0.0,
        flip_symmetric=standin_natural_stats,
        name="fed_cifar100(synthetic-standin)",
    )
