"""StackOverflow federated datasets: next-word prediction (NWP) and
tag prediction (logistic regression, LR).

Reference: ``fedml_api/data_preprocessing/stackoverflow_nwp/data_loader.py``
(h5, 342 477 users, 10 000-word vocab + pad/bos/eos/oov → 10 004,
20-token windows) and ``stackoverflow_lr/data_loader.py`` (bag-of-words
10 000 features, 500 tags, multi-label).  Offline fallback: synthetic
stand-ins with matching shapes; the NWP stand-in uses a vocab random
walk so next-token structure is learnable.
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np

from fedml_tpu.core.types import FedDataset

NWP_VOCAB = 10000
NWP_EXTENDED = NWP_VOCAB + 4  # pad/bos/eos/oov, reference rnn.py:39-47
NWP_SEQ_LEN = 20
LR_FEATURES = 10000
LR_TAGS = 500


def zipf_weights(vocab: int, s: float = 1.1) -> np.ndarray:
    """Zipf(s) unigram distribution over token ids (rank = id).  Real
    text is zipfian; a UNIFORM-unigram chain was measured unlearnable
    at the reference row's SGD lr (r5 pilot: loss 9.211→9.207 over 100
    rounds at lr 10^-0.5, 3x faster at lr 1.0 but still glacial, NaN
    at 3.0) — every one of the 10k classes needs its own averaged-over
    -clients signal.  Zipf jumps give the head words the same
    many-sightings-per-round head start real NWP training has."""
    q = 1.0 / np.arange(1, vocab + 1, dtype=np.float64) ** s
    return q / q.sum()


def _peaked_chain(rng, n: int, vocab: int, eta: float,
                  chunk: int = 1 << 25,
                  jump_q: "np.ndarray | None" = None,
                  ) -> "tuple[np.ndarray, np.ndarray]":
    """Length-n peaked Markov chain over [0, vocab): follow a fixed
    permutation with prob 1−η, jump uniform with prob η — the
    calibrated-text methodology of ``data/shakespeare.py``.  Returns
    ``(chain, perm)`` — the permutation is the Bayes predictor, with
    accuracy ceiling (1−η) + η·E[q(perm(cur))] (= (1−η) + η/vocab for
    uniform jumps; ``jump_q`` draws jump targets from a given unigram
    distribution instead, e.g. ``zipf_weights``).
    (Shakespeare's in-place sampler is deliberately NOT refactored onto
    this helper: its exact RNG stream is what the rev'd stand-in data
    and r4 artifacts were produced from — changing its draw order would
    silently invalidate them.  The two ceilings are pinned by separate
    tests.)

    Vectorized over jump segments — within a segment the chain is
    deterministic (ids[s+k] = perm^k(ids[s])), so a perm-power table up
    to the longest segment resolves every position at once — and
    generated in ``chunk``-sized pieces whose first element continues
    the previous chunk's walk, keeping transient host memory O(chunk)
    instead of several full-length float64/int64 temporaries (review
    r5: the 342k-client preset's ~1e9 positions would otherwise peak
    tens of GB over the ~3.7 GB result)."""
    if eta <= 0.0:
        # a jump-free chain is one global permutation cycle: the
        # perm-power table would be O(n · vocab), and the "ceiling"
        # would be 1.0 — not a calibrated task
        raise ValueError(f"peaked chain needs jump rate eta > 0, got {eta}")
    perm = rng.permutation(vocab).astype(np.int32)
    cdf = None if jump_q is None else np.cumsum(jump_q)
    out = np.empty(n, np.int32)
    carry = None
    done = 0
    while done < n:
        m = min(chunk, n - done)
        jump = rng.rand(m) < eta
        if cdf is None:
            unif = rng.randint(0, vocab, size=m).astype(np.int32)
        else:  # jump targets ~ jump_q (zipf): inverse-CDF sampling
            unif = np.searchsorted(cdf, rng.rand(m)).astype(np.int32)
            np.clip(unif, 0, vocab - 1, out=unif)
        # chunk boundary: index 0 is always a segment start for the
        # bookkeeping, but its VALUE follows the chain dynamics — the
        # drawn jump[0] decides uniform (keep unif[0]) vs continue the
        # previous chunk's walk (perm[carry]); the very first chunk has
        # no carry and starts with a uniform draw
        if carry is not None and not bool(jump[0]):
            unif[0] = perm[carry]
        jump[0] = True
        starts = np.flatnonzero(jump)
        seg_start = starts[np.cumsum(jump) - 1]
        k = (np.arange(m, dtype=np.int64) - seg_start).astype(np.int32)
        powers = np.empty((int(k.max()) + 1, vocab), np.int32)
        powers[0] = np.arange(vocab, dtype=np.int32)
        for p in range(1, powers.shape[0]):
            powers[p] = perm[powers[p - 1]]
        out[done:done + m] = powers[k, unif[seg_start]]
        carry = out[done + m - 1]
        done += m
    return out, perm


def nwp_chain_ceiling(eta: float, vocab: int = NWP_VOCAB) -> float:
    """Bayes next-token accuracy of the peaked chain: predict
    perm(cur); right when the chain followed the permutation (1−η) or
    when a jump landed there by chance (η/vocab)."""
    return (1.0 - eta) + eta / vocab


def _parse_nwp_h5(path: str, num_clients: int):
    """Windows + per-client index from one TFF-layout h5 (``examples/
    <client>/tokens``) — shared by the train and test splits."""
    import h5py

    xs, ys, idx = [], [], {}
    off = 0
    with h5py.File(path, "r") as f:
        ex = f["examples"]
        for c, cid in enumerate(sorted(ex.keys())[: num_clients or None]):
            toks = np.asarray(ex[cid]["tokens"])  # already int windows
            kept = 0
            for row in toks:
                row = np.asarray(row, np.int32)[: NWP_SEQ_LEN + 1]
                if len(row) < 2:
                    continue
                pad = NWP_SEQ_LEN + 1 - len(row)
                row = np.pad(row, (0, pad))
                xs.append(row[:-1])
                ys.append(row[1:])
                kept += 1
            idx[c] = np.arange(off, off + kept)
            off += kept
    return xs, ys, idx


def load_stackoverflow_nwp(
    data_dir: str = "./data/stackoverflow/datasets",
    num_clients: int = 10,
    sequences_per_client: int = 32,
    seed: int = 0,
    standin_peak_eta: float = None,
    standin_test_sequences: int = 2000,
    standin_zipf_s: float = 1.1,
) -> FedDataset:
    h5path = os.path.join(data_dir, "stackoverflow_nwp.pkl")
    tr = os.path.join(data_dir, "stackoverflow_train.h5")
    if os.path.exists(tr):
        xs, ys, idx = _parse_nwp_h5(tr, num_clients)
        # the reference evaluates on the SEPARATE held-out split
        # (stackoverflow_test.h5); evaluating on the first 64 training
        # windows would silently report train accuracy as test accuracy
        # (ADVICE r5) — with no test file present the test arrays are
        # None so any eval attempt fails loudly instead
        te = os.path.join(data_dir, "stackoverflow_test.h5")
        test_x = test_y = None
        if os.path.exists(te):
            txs, tys, _ = _parse_nwp_h5(te, num_clients)
            if txs:  # an empty/unusable split stays None (same refusal)
                test_x = np.stack(txs).astype(np.int32)
                test_y = np.stack(tys).astype(np.int32)
        return FedDataset(
            train_x=np.stack(xs).astype(np.int32),
            train_y=np.stack(ys).astype(np.int32),
            test_x=test_x,
            test_y=test_y,
            train_client_idx=idx, test_client_idx=None,
            num_classes=NWP_EXTENDED, name="stackoverflow_nwp",
        )
    del h5path
    rng = np.random.RandomState(seed)

    if standin_peak_eta is not None:
        # benchmark-grade stand-in (reference row README.md:57 —
        # 342,477 clients): a SHARED peaked chain over the 10k real-word
        # ids (+4 offset past pad/bos/eos/oov) sliced into 21-token
        # windows; shard sizes are clipped-lognormal (LEAF-style
        # heterogeneity in size, iid in distribution — same honesty
        # note as the shakespeare stand-in).  Size scale: median ~100,
        # mean ~130 — the real TFF partition averages ~397
        # sequences/client (135.8M examples / 342 477 users), so the
        # stand-in's per-round token volume is ~1/3 of the real row's;
        # going full-scale would cost ~13 GB of host generation per
        # run for no extra signal (recorded as a deviation in the
        # convergence artifact).  Stored int16 (vocab 10 004 < 2^15):
        # the full 342k-client population is ~3.7 GB instead of ~7.4.
        sizes = np.clip(
            rng.lognormal(mean=4.6, sigma=0.8, size=num_clients), 16, 512
        ).astype(np.int64)
        total = int(sizes.sum()) + standin_test_sequences
        q = (zipf_weights(NWP_VOCAB, standin_zipf_s)
             if standin_zipf_s else None)
        chain, perm = _peaked_chain(
            rng, total * (NWP_SEQ_LEN + 1), NWP_VOCAB, standin_peak_eta,
            jump_q=q,
        )
        # Bayes next-token accuracy of THIS chain (predict perm(cur)):
        # right when the chain followed the permutation (1−η) plus the
        # chance a jump landed there — η·q(perm(cur)) averaged over the
        # chain's own stationary distribution (empirical over a 1M-token
        # sample; exactly η/V when jumps are uniform)
        eta = standin_peak_eta
        if q is None:
            ceiling = (1.0 - eta) + eta / NWP_VOCAB
        else:
            cur = chain[: 1 << 20]
            ceiling = float((1.0 - eta) + eta * np.mean(q[perm[cur]]))
        win = (chain + 4).reshape(total, NWP_SEQ_LEN + 1).astype(np.int16)
        bounds = np.concatenate([[0], np.cumsum(sizes)])
        idx = {c: np.arange(bounds[c], bounds[c + 1])
               for c in range(num_clients)}
        test = win[bounds[-1]:]
        ds = FedDataset(
            train_x=win[:bounds[-1], :-1], train_y=win[:bounds[-1], 1:],
            test_x=test[:, :-1], test_y=test[:, 1:],
            train_client_idx=idx, test_client_idx=None,
            num_classes=NWP_EXTENDED,
            name="stackoverflow_nwp(synthetic-standin)",
        )
        ds.standin_bayes_ceiling = round(ceiling, 6)
        return ds

    def block(n):
        steps = rng.randint(-50, 51, size=n * (NWP_SEQ_LEN + 1))
        ids = (np.cumsum(steps) % NWP_VOCAB + 4).astype(np.int32)
        ids = ids.reshape(n, NWP_SEQ_LEN + 1)
        return ids[:, :-1], ids[:, 1:]

    xs, ys, idx = [], [], {}
    off = 0
    for c in range(num_clients):
        x, y = block(sequences_per_client)
        xs.append(x)
        ys.append(y)
        idx[c] = np.arange(off, off + len(x))
        off += len(x)
    tx, ty = block(64)
    return FedDataset(
        train_x=np.concatenate(xs), train_y=np.concatenate(ys),
        test_x=tx, test_y=ty, train_client_idx=idx, test_client_idx=None,
        num_classes=NWP_EXTENDED, name="stackoverflow_nwp(synthetic-standin)",
    )


def load_stackoverflow_lr(
    data_dir: str = "./data/stackoverflow_lr/datasets",
    num_clients: int = 10,
    samples_per_client: int = 32,
    num_features: int = LR_FEATURES,
    num_tags: int = LR_TAGS,
    seed: int = 0,
) -> FedDataset:
    """Multi-label tag prediction: x = normalized bag-of-words
    [N, num_features], y = multi-hot tags [N, num_tags].  Task loss:
    ``losses.masked_multilabel_bce`` (exact-match/precision/recall
    metrics) — ``registry.task_loss_for_dataset`` wires it for every
    driver."""
    tr = os.path.join(data_dir, "stackoverflow_lr_train.h5")
    if os.path.exists(tr):
        import h5py

        with h5py.File(tr, "r") as f:
            x = np.asarray(f["x"], np.float32)
            y = np.asarray(f["y"], np.float32)
            idx = {
                int(c): np.asarray(v)
                for c, v in enumerate(np.asarray(f["client_ptr"]))
            }
        # held-out split only (ADVICE r5: the first-64-training-rows
        # fallback was eval-on-train); None test arrays make an eval
        # without the real test h5 fail loudly
        te = os.path.join(data_dir, "stackoverflow_lr_test.h5")
        test_x = test_y = None
        if os.path.exists(te):
            with h5py.File(te, "r") as f:
                test_x = np.asarray(f["x"], np.float32)
                test_y = np.asarray(f["y"], np.float32)
        return FedDataset(
            train_x=x, train_y=y, test_x=test_x, test_y=test_y,
            train_client_idx=idx, test_client_idx=None,
            num_classes=num_tags, name="stackoverflow_lr",
        )
    rng = np.random.RandomState(seed)
    n = num_clients * samples_per_client
    # sparse bags-of-words + tags correlated with the strongest features
    x = np.zeros((n + 64, num_features), np.float32)
    y = np.zeros((n + 64, num_tags), np.float32)
    w = rng.randn(num_features, num_tags).astype(np.float32) * 0.3
    for i in range(n + 64):
        nz = rng.randint(3, 12)
        feats = rng.randint(0, num_features, nz)
        x[i, feats] = 1.0 / nz
        logits = x[i] @ w
        y[i, np.argsort(-logits)[: rng.randint(1, 4)]] = 1.0
    idx = {
        c: np.arange(c * samples_per_client, (c + 1) * samples_per_client)
        for c in range(num_clients)
    }
    return FedDataset(
        train_x=x[:n], train_y=y[:n], test_x=x[n:], test_y=y[n:],
        train_client_idx=idx, test_client_idx=None,
        num_classes=num_tags, name="stackoverflow_lr(synthetic-standin)",
    )
