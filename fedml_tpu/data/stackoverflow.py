"""StackOverflow federated datasets: next-word prediction (NWP) and
tag prediction (logistic regression, LR).

Reference: ``fedml_api/data_preprocessing/stackoverflow_nwp/data_loader.py``
(h5, 342 477 users, 10 000-word vocab + pad/bos/eos/oov → 10 004,
20-token windows) and ``stackoverflow_lr/data_loader.py`` (bag-of-words
10 000 features, 500 tags, multi-label).  Offline fallback: synthetic
stand-ins with matching shapes; the NWP stand-in uses a vocab random
walk so next-token structure is learnable.
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np

from fedml_tpu.core.types import FedDataset

NWP_VOCAB = 10000
NWP_EXTENDED = NWP_VOCAB + 4  # pad/bos/eos/oov, reference rnn.py:39-47
NWP_SEQ_LEN = 20
LR_FEATURES = 10000
LR_TAGS = 500


def load_stackoverflow_nwp(
    data_dir: str = "./data/stackoverflow/datasets",
    num_clients: int = 10,
    sequences_per_client: int = 32,
    seed: int = 0,
) -> FedDataset:
    h5path = os.path.join(data_dir, "stackoverflow_nwp.pkl")
    tr = os.path.join(data_dir, "stackoverflow_train.h5")
    if os.path.exists(tr):
        import h5py

        xs, ys, idx = [], [], {}
        off = 0
        with h5py.File(tr, "r") as f:
            ex = f["examples"]
            for c, cid in enumerate(sorted(ex.keys())[: num_clients or None]):
                toks = np.asarray(ex[cid]["tokens"])  # already int windows
                kept = 0
                for row in toks:
                    row = np.asarray(row, np.int32)[: NWP_SEQ_LEN + 1]
                    if len(row) < 2:
                        continue
                    pad = NWP_SEQ_LEN + 1 - len(row)
                    row = np.pad(row, (0, pad))
                    xs.append(row[:-1])
                    ys.append(row[1:])
                    kept += 1
                idx[c] = np.arange(off, off + kept)
                off += kept
        return FedDataset(
            train_x=np.stack(xs).astype(np.int32),
            train_y=np.stack(ys).astype(np.int32),
            test_x=np.stack(xs[:64]).astype(np.int32),
            test_y=np.stack(ys[:64]).astype(np.int32),
            train_client_idx=idx, test_client_idx=None,
            num_classes=NWP_EXTENDED, name="stackoverflow_nwp",
        )
    del h5path
    rng = np.random.RandomState(seed)

    def block(n):
        steps = rng.randint(-50, 51, size=n * (NWP_SEQ_LEN + 1))
        ids = (np.cumsum(steps) % NWP_VOCAB + 4).astype(np.int32)
        ids = ids.reshape(n, NWP_SEQ_LEN + 1)
        return ids[:, :-1], ids[:, 1:]

    xs, ys, idx = [], [], {}
    off = 0
    for c in range(num_clients):
        x, y = block(sequences_per_client)
        xs.append(x)
        ys.append(y)
        idx[c] = np.arange(off, off + len(x))
        off += len(x)
    tx, ty = block(64)
    return FedDataset(
        train_x=np.concatenate(xs), train_y=np.concatenate(ys),
        test_x=tx, test_y=ty, train_client_idx=idx, test_client_idx=None,
        num_classes=NWP_EXTENDED, name="stackoverflow_nwp(synthetic-standin)",
    )


def load_stackoverflow_lr(
    data_dir: str = "./data/stackoverflow_lr/datasets",
    num_clients: int = 10,
    samples_per_client: int = 32,
    num_features: int = LR_FEATURES,
    num_tags: int = LR_TAGS,
    seed: int = 0,
) -> FedDataset:
    """Multi-label tag prediction: x = normalized bag-of-words
    [N, num_features], y = multi-hot tags [N, num_tags].  Task loss:
    ``losses.masked_multilabel_bce`` (exact-match/precision/recall
    metrics) — ``registry.task_loss_for_dataset`` wires it for every
    driver."""
    tr = os.path.join(data_dir, "stackoverflow_lr_train.h5")
    if os.path.exists(tr):
        import h5py

        with h5py.File(tr, "r") as f:
            x = np.asarray(f["x"], np.float32)
            y = np.asarray(f["y"], np.float32)
            idx = {
                int(c): np.asarray(v)
                for c, v in enumerate(np.asarray(f["client_ptr"]))
            }
        return FedDataset(
            train_x=x, train_y=y, test_x=x[:64], test_y=y[:64],
            train_client_idx=idx, test_client_idx=None,
            num_classes=num_tags, name="stackoverflow_lr",
        )
    rng = np.random.RandomState(seed)
    n = num_clients * samples_per_client
    # sparse bags-of-words + tags correlated with the strongest features
    x = np.zeros((n + 64, num_features), np.float32)
    y = np.zeros((n + 64, num_tags), np.float32)
    w = rng.randn(num_features, num_tags).astype(np.float32) * 0.3
    for i in range(n + 64):
        nz = rng.randint(3, 12)
        feats = rng.randint(0, num_features, nz)
        x[i, feats] = 1.0 / nz
        logits = x[i] @ w
        y[i, np.argsort(-logits)[: rng.randint(1, 4)]] = 1.0
    idx = {
        c: np.arange(c * samples_per_client, (c + 1) * samples_per_client)
        for c in range(num_clients)
    }
    return FedDataset(
        train_x=x[:n], train_y=y[:n], test_x=x[n:], test_y=y[n:],
        train_client_idx=idx, test_client_idx=None,
        num_classes=num_tags, name="stackoverflow_lr(synthetic-standin)",
    )
