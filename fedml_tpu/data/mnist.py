"""MNIST federated loader.

Reference: ``fedml_api/data_preprocessing/MNIST/data_loader.py:8-123``
reads LEAF's pre-partitioned power-law JSON (1000 users).  Here the
loader reads, in order of preference: LEAF ``train/``+``test/`` JSON
directories (the reference's format — users become the natural client
partition), raw MNIST IDX, or ``mnist.npz``, partitioning the raw
formats with the power-law partitioner
(``fedml_tpu.core.partition.powerlaw_partition``); with no files on disk
(this environment has no egress) it falls back to a matched-shape
synthetic stand-in so every pipeline stays runnable end-to-end.
"""

from __future__ import annotations

import gzip
import json
import os
import struct
from typing import Optional

import numpy as np

from fedml_tpu.core.partition import partition_data
from fedml_tpu.core.types import FedDataset
from fedml_tpu.data.synthetic import (
    match_pixel_moments,
    synthetic_classification,
)


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _find(data_dir: str, names) -> Optional[str]:
    for n in names:
        for cand in (os.path.join(data_dir, n), os.path.join(data_dir, n + ".gz")):
            if os.path.exists(cand):
                return cand
    return None


def _leaf_json_dir(d: str):
    if not os.path.isdir(d):
        return None
    files = sorted(f for f in os.listdir(d) if f.endswith(".json"))
    return [os.path.join(d, f) for f in files] or None


def _read_leaf_users(paths):
    """LEAF JSON: {"users": [...], "user_data": {u: {"x": [[784 floats
    in 0..1]], "y": [labels]}}} (reference MNIST/data_loader.py:8-43).
    Returns {user_id: (x, y)} in file-then-user order."""
    users = {}
    for p in paths:
        with open(p) as f:
            data = json.load(f)
        for user in data["users"]:
            ud = data["user_data"][user]
            users[user] = (
                np.asarray(ud["x"], np.float32),
                np.asarray(ud["y"], np.int32),
            )
    if not users:
        raise ValueError("no users in LEAF files")
    return users


def _stack_leaf(users, order, flatten: bool):
    """Concatenate the given users' shards in ``order``; users absent
    from ``users`` get an empty index set so train/test client slots
    always refer to the SAME user id."""
    xs, ys, idx = [], [], {}
    off = 0
    for c, user in enumerate(order):
        if user not in users:
            idx[c] = np.arange(0)
            continue
        ux, uy = users[user]
        if not flatten:
            ux = ux.reshape(len(uy), 28, 28, 1)
        xs.append(ux)
        ys.append(uy)
        idx[c] = np.arange(off, off + len(uy))
        off += len(uy)
    shape = (0, 784) if flatten else (0, 28, 28, 1)
    x = np.concatenate(xs) if xs else np.zeros(shape, np.float32)
    y = np.concatenate(ys) if ys else np.zeros((0,), np.int32)
    return x, y, idx


def load_mnist(
    data_dir: str = "./data/mnist",
    num_clients: int = 1000,
    partition: str = "power_law",
    partition_alpha: float = 0.5,
    flatten: bool = True,
    seed: int = 0,
    standin_label_noise: float = 0.0,
) -> FedDataset:
    """``standin_label_noise`` applies ONLY to the offline synthetic
    stand-in (an irreducible-error ceiling so convergence evidence
    cannot saturate, VERDICT r2 missing #1); real LEAF/IDX/npz data is
    never modified."""
    leaf_tr = _leaf_json_dir(os.path.join(data_dir, "train"))
    leaf_te = _leaf_json_dir(os.path.join(data_dir, "test"))
    if leaf_tr and leaf_te:
        try:
            tr_users = _read_leaf_users(leaf_tr)
            te_users = _read_leaf_users(leaf_te)
        except (KeyError, ValueError, json.JSONDecodeError):
            # not actually LEAF-format json — fall through to IDX/npz
            pass
        else:
            # client slots keyed by TRAIN user order; the test split is
            # matched by user id (a user with no test file entry gets an
            # empty test partition, never another user's data)
            order = list(tr_users.keys())
            train_x, train_y, train_idx = _stack_leaf(tr_users, order, flatten)
            test_x, test_y, test_idx = _stack_leaf(te_users, order, flatten)
            return FedDataset(
                train_x=train_x, train_y=train_y,
                test_x=test_x, test_y=test_y,
                train_client_idx=train_idx, test_client_idx=test_idx,
                num_classes=10, name="mnist",
            )

    tr_x = _find(data_dir, ["train-images-idx3-ubyte", "train-images.idx3-ubyte"])
    tr_y = _find(data_dir, ["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"])
    te_x = _find(data_dir, ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"])
    te_y = _find(data_dir, ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"])
    npz = _find(data_dir, ["mnist.npz"])

    if tr_x and tr_y and te_x and te_y:
        train_x = _read_idx(tr_x).astype(np.float32) / 255.0
        train_y = _read_idx(tr_y).astype(np.int32)
        test_x = _read_idx(te_x).astype(np.float32) / 255.0
        test_y = _read_idx(te_y).astype(np.int32)
        train_x = train_x[..., None]
        test_x = test_x[..., None]
    elif npz:
        z = np.load(npz)
        train_x = z["x_train"].astype(np.float32) / 255.0
        train_y = z["y_train"].astype(np.int32)
        test_x = z["x_test"].astype(np.float32) / 255.0
        test_y = z["y_test"].astype(np.int32)
        if train_x.ndim == 3:
            train_x, test_x = train_x[..., None], test_x[..., None]
    else:
        ds = synthetic_classification(
            num_train=60000 if num_clients >= 100 else 6000,
            num_test=10000 if num_clients >= 100 else 1000,
            input_shape=(28, 28, 1),
            num_classes=10,
            num_clients=num_clients,
            partition=partition,
            partition_alpha=partition_alpha,
            label_noise=standin_label_noise,
            seed=seed,
            name="mnist(synthetic-standin)",
        )
        if partition == "power_law":
            # real LEAF MNIST power-law shards are tens-to-hundreds of
            # samples; the lognormal tail can mint a ~2700-sample
            # client, and the fixed pack geometry (steps = the GLOBAL
            # max shard) would pad every sampled cohort block to that
            # outlier — ~95% padding compute + an ~85 MB/round transfer
            # (measured; see data/emnist.py for the same fix)
            cap = 500
            ds.train_client_idx = {
                c: idx[:cap] for c, idx in ds.train_client_idx.items()
            }
        # real MNIST pixel moments (mean .1307 / std .3081, the
        # published torchvision normalization constants) so the
        # reference row's lr transfers — see match_pixel_moments
        ds = match_pixel_moments(ds, 0.1307, 0.3081)
        if flatten:
            ds.train_x = ds.train_x.reshape(len(ds.train_x), -1)
            ds.test_x = ds.test_x.reshape(len(ds.test_x), -1)
        return ds

    if flatten:
        train_x = train_x.reshape(len(train_x), -1)
        test_x = test_x.reshape(len(test_x), -1)

    client_idx = partition_data(train_y, num_clients, partition, partition_alpha, seed)
    return FedDataset(
        train_x=train_x,
        train_y=train_y,
        test_x=test_x,
        test_y=test_y,
        train_client_idx=client_idx,
        test_client_idx=None,
        num_classes=10,
        name="mnist",
    )
