"""Shakespeare next-character datasets (LEAF json + TFF h5 variants).

Reference: ``fedml_api/data_preprocessing/shakespeare/data_loader.py``
(LEAF ``all_data_*.json`` with per-user 80-char windows, char vocab from
``language_utils.py``) and ``fed_shakespeare/data_loader.py`` (TFF h5,
``snippets`` per client, sequence targets).  The 90-symbol vocabulary
(86 chars + pad/OOV/BOS/EOS) follows ``language_utils.py:11-20``.

Outputs: LEAF variant → x [N, 80] int32, y [N] (final next char);
TFF variant → x [N, 80], y [N, 80] (per-position next char, matching
``RNNOriginalFedAvg(seq_output=True)``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from fedml_tpu.core.types import FedDataset

# language_utils.py:11-17 — the TFF text-generation tutorial vocabulary
CHAR_VOCAB = (
    "dhlptx@DHLPTX $(,048cgkoswCGKOSW[_#'/37;?bfjnrvzBFJNRVZ\"&*.26:"
    "\naeimquyAEIMQUY]!%)-159\r"
)
PAD, OOV, BOS, EOS = 0, len(CHAR_VOCAB) + 1, len(CHAR_VOCAB) + 2, len(CHAR_VOCAB) + 3
VOCAB_SIZE = len(CHAR_VOCAB) + 4  # 90
SEQ_LEN = 80

_CHAR_TO_ID = {c: i + 1 for i, c in enumerate(CHAR_VOCAB)}


def encode_text(s: str) -> np.ndarray:
    return np.asarray([_CHAR_TO_ID.get(c, OOV) for c in s], np.int32)


def _windows(text_ids: np.ndarray, seq_len: int = SEQ_LEN):
    """Non-overlapping (x, y) next-char windows over one client's text."""
    n = (len(text_ids) - 1) // seq_len
    xs, ys = [], []
    for i in range(n):
        xs.append(text_ids[i * seq_len : (i + 1) * seq_len])
        ys.append(text_ids[i * seq_len + 1 : (i + 1) * seq_len + 1])
    return xs, ys


def _from_leaf_json(train_path: str, test_path: str) -> FedDataset:
    def load(path):
        xs, ys, idx = [], [], {}
        off = 0
        with open(path) as f:
            data = json.load(f)
        for c, user in enumerate(data["users"]):
            ux = [encode_text(s) for s in data["user_data"][user]["x"]]
            # LEAF y: single next char per 80-char window
            uy = [
                _CHAR_TO_ID.get(s[0], OOV) if s else OOV
                for s in data["user_data"][user]["y"]
            ]
            xs.extend(ux)
            ys.extend(uy)
            idx[c] = np.arange(off, off + len(uy))
            off += len(uy)
        x = np.stack([np.pad(v[:SEQ_LEN], (0, max(0, SEQ_LEN - len(v))))
                      for v in xs]).astype(np.int32)
        return x, np.asarray(ys, np.int32), idx

    tx, ty, tidx = load(train_path)
    ex, ey, eidx = load(test_path)
    return FedDataset(
        train_x=tx, train_y=ty, test_x=ex, test_y=ey,
        train_client_idx=tidx, test_client_idx=eidx,
        num_classes=VOCAB_SIZE, name="shakespeare",
    )


def _synthetic_text(num_clients: int, windows_per_client: int, seq: bool,
                    seed: int, name: str,
                    peak_eta: Optional[float] = None,
                    test_windows: Optional[int] = None) -> FedDataset:
    rng = np.random.RandomState(seed)
    nchars = VOCAB_SIZE - 4  # the real char ids 1..86 (pad/OOV/BOS/EOS out)
    if peak_eta is not None:
        # Peaked first-order Markov chain for CONVERGENCE evidence: with
        # prob 1-η the next char is a fixed random permutation σ(prev),
        # else uniform over the vocab.  Bayes-optimal next-char accuracy
        # is exactly (1-η) + η/nchars — the same documented-ceiling
        # methodology as the label-noise image stand-ins
        # (data/synthetic.py), for a sequence task where "flip the
        # label" has no direct analogue.
        perm = rng.permutation(nchars)

        def sample(n):
            # vectorized over jump segments: between jumps the chain is
            # deterministic (ids[s+k] = perm^k(ids[s])), so build a
            # perm-power table up to the longest segment and index it —
            # equivalent to walking the chain per character over the
            # same pre-drawn jump/uniform arrays
            first = rng.randint(nchars)
            jump = rng.rand(n) < peak_eta
            unif = rng.randint(0, nchars, size=n)
            starts = np.concatenate(
                [[0], np.flatnonzero(jump[1:]) + 1])
            start_val = np.concatenate([[first], unif[starts[1:]]])
            seg = np.zeros(n, np.int64)
            seg[starts[1:]] = 1
            seg = np.cumsum(seg)
            k = np.arange(n) - starts[seg]
            ptab = np.empty((int(k.max()) + 1, nchars), np.int64)
            ptab[0] = np.arange(nchars)
            for t in range(1, len(ptab)):
                ptab[t] = perm[ptab[t - 1]]
            ids = ptab[k, start_val[seg]]
            return (ids + 1).astype(np.int32)
    else:
        # Markov-ish synthetic text: random walk over the vocab keeps
        # next-char structure learnable, unlike iid noise
        def sample(n):
            steps = rng.randint(-3, 4, size=n)
            ids = np.clip(np.cumsum(steps) % nchars, 0,
                          nchars - 1) + 1
            return ids.astype(np.int32)

    def block(n_windows):
        text = sample(n_windows * SEQ_LEN + 1)
        xs, ys = _windows(text)
        x = np.stack(xs)
        if seq:
            y = np.stack(ys)
        else:
            y = np.asarray([w[-1] for w in ys], np.int32)
        return x, y

    if peak_eta is not None:
        # LEAF's realistic partition is heterogeneous in SHARD SIZE
        # (roles speak wildly different amounts of text); mirror that
        # with lognormal window counts clipped to [4, windows_per_client]
        # — the distributional signal itself stays one shared chain
        # (documented as iid across clients in the convergence artifact)
        sizes = np.clip(
            np.exp(rng.normal(np.log(max(windows_per_client // 3, 4)),
                              0.8, num_clients)),
            4, windows_per_client).astype(int)
    else:
        sizes = np.full(num_clients, windows_per_client)
    xs, ys, idx = [], [], {}
    off = 0
    for c in range(num_clients):
        x, y = block(int(sizes[c]))
        xs.append(x)
        ys.append(y)
        idx[c] = np.arange(off, off + len(y))
        off += len(y)
    tx, t_y = block(test_windows if test_windows is not None
                    else max(windows_per_client, 8))
    return FedDataset(
        train_x=np.concatenate(xs), train_y=np.concatenate(ys),
        test_x=tx, test_y=t_y, train_client_idx=idx, test_client_idx=None,
        num_classes=VOCAB_SIZE, name=name,
    )


def load_shakespeare(
    data_dir: str = "./data/shakespeare",
    num_clients: int = 10,
    windows_per_client: int = 16,
    seed: int = 0,
    standin_peak_eta: Optional[float] = None,
    standin_test_windows: Optional[int] = None,
) -> FedDataset:
    """LEAF variant: y = one next char per window.

    ``standin_peak_eta`` / ``standin_test_windows`` apply ONLY to the
    offline synthetic stand-in: the former switches the random-walk
    text to the peaked Markov chain with a documented Bayes ceiling
    (see ``_synthetic_text``), the latter sizes the held-out window set
    (convergence evidence needs more than the default handful); real
    LEAF json is never modified."""
    tr = os.path.join(data_dir, "train")
    te = os.path.join(data_dir, "test")
    if os.path.isdir(tr) and os.path.isdir(te):
        trj = [os.path.join(tr, f) for f in sorted(os.listdir(tr))
               if f.endswith(".json")]
        tej = [os.path.join(te, f) for f in sorted(os.listdir(te))
               if f.endswith(".json")]
        if trj and tej:
            return _from_leaf_json(trj[0], tej[0])
    return _synthetic_text(num_clients, windows_per_client, seq=False,
                           seed=seed, name="shakespeare(synthetic-standin)",
                           peak_eta=standin_peak_eta,
                           test_windows=standin_test_windows)


def load_fed_shakespeare(
    data_dir: str = "./data/fed_shakespeare/datasets",
    num_clients: int = 10,
    windows_per_client: int = 16,
    seed: int = 0,
) -> FedDataset:
    """TFF variant: y = per-position next char [N, 80]."""
    tr = os.path.join(data_dir, "shakespeare_train.h5")
    te = os.path.join(data_dir, "shakespeare_test.h5")
    if os.path.exists(tr) and os.path.exists(te):
        import h5py

        def load(path):
            xs, ys, idx = [], [], {}
            off = 0
            with h5py.File(path, "r") as f:
                ex = f["examples"]
                for c, cid in enumerate(sorted(ex.keys())):
                    text = b"".join(np.asarray(ex[cid]["snippets"]).tolist())
                    ids = encode_text(text.decode("utf-8", "ignore"))
                    wx, wy = _windows(ids)
                    if not wx:
                        continue
                    xs.extend(wx)
                    ys.extend(wy)
                    idx[len(idx)] = np.arange(off, off + len(wx))
                    off += len(wx)
            return (np.stack(xs).astype(np.int32),
                    np.stack(ys).astype(np.int32), idx)

        tx, ty, tidx = load(tr)
        ex_, ey, eidx = load(te)
        return FedDataset(
            train_x=tx, train_y=ty, test_x=ex_, test_y=ey,
            train_client_idx=tidx, test_client_idx=eidx,
            num_classes=VOCAB_SIZE, name="fed_shakespeare",
        )
    return _synthetic_text(num_clients, windows_per_client, seq=True,
                           seed=seed,
                           name="fed_shakespeare(synthetic-standin)")
