"""Tabular datasets: UCI streams (decentralized online learning),
lending-club loan and NUS-WIDE (vertical FL).

Reference: ``fedml_api/data_preprocessing/UCI/`` (SUSY, room-occupancy
CSV streams consumed by ``standalone/decentralized``),
``lending_club_loan/`` and ``NUS_WIDE/`` (guest/host feature-split
tables for classical VFL).  Loaders read CSVs when present, otherwise
emit synthetic stand-ins with the reference's shapes.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np

from fedml_tpu.core.types import FedDataset


def _read_csv(path: str, label_col: int = 0, skip_header: int = 0):
    data = np.genfromtxt(path, delimiter=",", skip_header=skip_header)
    y = data[:, label_col]
    x = np.delete(data, label_col, axis=1)
    return x.astype(np.float32), y.astype(np.int32)


def load_uci_stream(
    name: str = "SUSY",
    data_dir: str = "./data/UCI",
    num_clients: int = 8,
    samples_per_client: int = 64,
    seed: int = 0,
) -> FedDataset:
    """Streaming binary-classification rows for DOL (reference
    ``standalone/decentralized`` SUSY/room-occupancy).  Row order is
    preserved — DOL consumes it as a stream and reports regret."""
    path = os.path.join(data_dir, f"{name}.csv")
    if os.path.exists(path):
        x, y = _read_csv(path, label_col=0)
        y = (y > 0).astype(np.int32)
        # small real files: shrink the holdout so the split stays valid
        holdout = min(64, max(1, len(x) // 5))
    else:
        rng = np.random.RandomState(seed)
        dim = 18 if name.upper() == "SUSY" else 5
        n = num_clients * samples_per_client + 64
        w = rng.randn(dim).astype(np.float32)
        x = rng.randn(n, dim).astype(np.float32)
        y = (x @ w + 0.3 * rng.randn(n) > 0).astype(np.int32)
        name = f"{name}(synthetic-standin)"
        holdout = 64  # n was sized for exactly this, keeping
        # samples_per_client contractual on the synthetic path
    n_train = len(x) - holdout
    per = n_train // num_clients
    idx = {c: np.arange(c * per, (c + 1) * per) for c in range(num_clients)}
    return FedDataset(
        train_x=x[:n_train], train_y=y[:n_train],
        test_x=x[n_train:], test_y=y[n_train:],
        train_client_idx=idx, test_client_idx=None,
        num_classes=2, name=f"uci_{name}",
    )


def load_lending_club(
    data_dir: str = "./data/lending_club_loan",
    num_hosts: int = 1,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, list]:
    """VFL table: returns (X, y, feature_splits) where feature_splits
    gives each party's column slice (guest first) — the reference splits
    loan features between one guest (with labels) and hosts
    (``lending_club_loan/lending_club_dataset.py``)."""
    path = os.path.join(data_dir, "loan_processed.npz")
    if os.path.exists(path):
        z = np.load(path)
        x, y = z["x"].astype(np.float32), z["y"].astype(np.int32)
    else:
        rng = np.random.RandomState(seed)
        n, d = 512, 24
        w = rng.randn(d).astype(np.float32)
        x = rng.randn(n, d).astype(np.float32)
        y = (x @ w > 0).astype(np.int32)
    d = x.shape[1]
    parties = num_hosts + 1
    cuts = np.linspace(0, d, parties + 1).astype(int)
    splits = [slice(cuts[i], cuts[i + 1]) for i in range(parties)]
    return x, y, splits


def load_nus_wide(
    data_dir: str = "./data/NUS_WIDE",
    binary_label: int = 1,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, list]:
    """NUS-WIDE VFL split: guest = 634-d low-level image features,
    host = 1000-d tag features (reference ``NUS_WIDE/nus_wide_dataset.py``)."""
    path = os.path.join(data_dir, "nus_wide_processed.npz")
    if os.path.exists(path):
        z = np.load(path)
        x, y = z["x"].astype(np.float32), z["y"].astype(np.int32)
        guest_dim = int(z.get("guest_dim", 634))
    else:
        rng = np.random.RandomState(seed)
        n, guest_dim, host_dim = 256, 64, 100
        x = rng.randn(n, guest_dim + host_dim).astype(np.float32)
        w = rng.randn(guest_dim + host_dim).astype(np.float32)
        y = (x @ w > 0).astype(np.int32)
    return x, y, [slice(0, guest_dim), slice(guest_dim, x.shape[1])]
