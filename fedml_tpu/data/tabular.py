"""Tabular datasets: UCI streams (decentralized online learning),
lending-club loan and NUS-WIDE (vertical FL).

Reference: ``fedml_api/data_preprocessing/UCI/`` (SUSY, room-occupancy
CSV streams consumed by ``standalone/decentralized``),
``lending_club_loan/`` and ``NUS_WIDE/`` (guest/host feature-split
tables for classical VFL).  Loaders read CSVs when present, otherwise
emit synthetic stand-ins with the reference's shapes.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np

from fedml_tpu.core.types import FedDataset


def _read_csv(path: str, label_col: int = 0, skip_header: int = 0):
    data = np.genfromtxt(path, delimiter=",", skip_header=skip_header)
    y = data[:, label_col]
    x = np.delete(data, label_col, axis=1)
    return x.astype(np.float32), y.astype(np.int32)


def load_uci_stream(
    name: str = "SUSY",
    data_dir: str = "./data/UCI",
    num_clients: int = 8,
    samples_per_client: int = 64,
    seed: int = 0,
) -> FedDataset:
    """Streaming binary-classification rows for DOL (reference
    ``standalone/decentralized`` SUSY/room-occupancy).  Row order is
    preserved — DOL consumes it as a stream and reports regret."""
    path = os.path.join(data_dir, f"{name}.csv")
    if os.path.exists(path):
        x, y = _read_csv(path, label_col=0)
        y = (y > 0).astype(np.int32)
        # small real files: shrink the holdout so the split stays valid
        holdout = min(64, max(1, len(x) // 5))
    else:
        rng = np.random.RandomState(seed)
        dim = 18 if name.upper() == "SUSY" else 5
        n = num_clients * samples_per_client + 64
        w = rng.randn(dim).astype(np.float32)
        x = rng.randn(n, dim).astype(np.float32)
        y = (x @ w + 0.3 * rng.randn(n) > 0).astype(np.int32)
        name = f"{name}(synthetic-standin)"
        holdout = 64  # n was sized for exactly this, keeping
        # samples_per_client contractual on the synthetic path
    n_train = len(x) - holdout
    per = n_train // num_clients
    idx = {c: np.arange(c * per, (c + 1) * per) for c in range(num_clients)}
    return FedDataset(
        train_x=x[:n_train], train_y=y[:n_train],
        test_x=x[n_train:], test_y=y[n_train:],
        train_client_idx=idx, test_client_idx=None,
        num_classes=2, name=f"uci_{name}",
    )


# ---------------------------------------------------------------------------
# Lending-club raw-CSV pipeline — the reference's full feature
# engineering (``lending_club_loan/lending_club_dataset.py:10-123``).
# The categorical→ordinal maps and feature groups below are dataset
# constants copied from the reference (``lending_club_dataset.py:10-31``,
# ``lending_club_feature_group.py``) — the pipeline code is original.
# ---------------------------------------------------------------------------

LOAN_BAD_STATUS = frozenset([
    "Charged Off", "Default",
    "Does not meet the credit policy. Status:Charged Off",
    "In Grace Period", "Late (16-30 days)", "Late (31-120 days)",
])  # loan_condition(), lending_club_dataset.py:48-55
LOAN_CATEGORY_MAPS: Dict[str, Dict[str, float]] = {
    "grade": {"A": 6, "B": 5, "C": 4, "D": 3, "E": 2, "F": 1, "G": 0},
    "emp_length": {"": 0, "< 1 year": 1, "1 year": 2, "2 years": 2,
                   "3 years": 2, "4 years": 3, "5 years": 3, "6 years": 3,
                   "7 years": 4, "8 years": 4, "9 years": 4, "10+ years": 5},
    "home_ownership": {"RENT": 0, "MORTGAGE": 1, "OWN": 2, "ANY": 3,
                       "NONE": 3, "OTHER": 3},
    "verification_status": {"Not Verified": 0, "Source Verified": 1,
                            "Verified": 2},
    "term": {" 36 months": 0, " 60 months": 1},
    "initial_list_status": {"w": 0, "f": 1},
    "purpose": {"debt_consolidation": 0, "credit_card": 0,
                "small_business": 1, "educational": 2, "car": 3, "other": 3,
                "vacation": 3, "house": 3, "home_improvement": 3,
                "major_purchase": 3, "medical": 3, "renewable_energy": 3,
                "moving": 3, "wedding": 3},
    "application_type": {"Individual": 0, "Joint App": 1},
    "disbursement_method": {"Cash": 0, "DirectPay": 1},
}
LOAN_QUALIFICATION_FEAT = [
    "grade", "emp_length", "home_ownership", "annual_inc_comp",
    "verification_status", "total_rev_hi_lim", "tot_hi_cred_lim",
    "total_bc_limit", "total_il_high_credit_limit",
]
LOAN_LOAN_FEAT = ["loan_amnt", "term", "initial_list_status", "purpose",
                  "application_type", "disbursement_method"]
LOAN_DEBT_FEAT = [
    "int_rate", "installment", "revol_bal", "revol_util", "out_prncp",
    "recoveries", "dti", "dti_joint", "tot_coll_amt", "mths_since_rcnt_il",
    "total_bal_il", "il_util", "max_bal_bc", "all_util", "bc_util",
    "total_bal_ex_mort", "revol_bal_joint", "mo_sin_old_il_acct",
    "mo_sin_old_rev_tl_op", "mo_sin_rcnt_rev_tl_op", "mort_acc",
    "num_rev_tl_bal_gt_0", "percent_bc_gt_75",
]
LOAN_REPAYMENT_FEAT = [
    "num_sats", "num_bc_sats", "pct_tl_nvr_dlq", "bc_open_to_buy",
    "last_pymnt_amnt", "total_pymnt", "total_pymnt_inv", "total_rec_prncp",
    "total_rec_int", "total_rec_late_fee", "tot_cur_bal", "avg_cur_bal",
]
LOAN_MULTI_ACC_FEAT = [
    "num_il_tl", "num_op_rev_tl", "num_rev_accts", "num_actv_rev_tl",
    "num_tl_op_past_12m", "open_rv_12m", "open_rv_24m", "open_acc_6m",
    "open_act_il", "open_il_12m", "open_il_24m", "total_acc",
    "inq_last_6mths", "open_acc", "inq_fi", "inq_last_12m",
    "acc_open_past_24mths",
]
LOAN_MAL_BEHAVIOR_FEAT = [
    "num_tl_120dpd_2m", "num_tl_30dpd", "num_tl_90g_dpd_24m",
    "pub_rec_bankruptcies", "mths_since_recent_revol_delinq",
    "num_accts_ever_120_pd", "mths_since_recent_bc_dlq",
    "chargeoff_within_12_mths", "collections_12_mths_ex_med",
    "mths_since_last_major_derog", "acc_now_delinq", "pub_rec",
    "mths_since_last_delinq", "delinq_2yrs", "delinq_amnt", "tax_liens",
]
LOAN_ALL_FEATURES = (LOAN_QUALIFICATION_FEAT + LOAN_LOAN_FEAT
                     + LOAN_DEBT_FEAT + LOAN_REPAYMENT_FEAT
                     + LOAN_MULTI_ACC_FEAT + LOAN_MAL_BEHAVIOR_FEAT)
# party A (guest) owns qualification+loan features, party B the rest
# (loan_load_two_party_data, lending_club_dataset.py:144-145); because
# LOAN_ALL_FEATURES lists A's features first, A is a column PREFIX
LOAN_PARTY_A_DIM = len(LOAN_QUALIFICATION_FEAT) + len(LOAN_LOAN_FEAT)


def standardize_columns(x: np.ndarray) -> np.ndarray:
    """sklearn StandardScaler semantics (population std, zero-variance
    columns scale by 1) — ``normalize()``, lending_club_dataset.py:34-37."""
    mean = x.mean(axis=0)
    std = x.std(axis=0)
    std = np.where(std == 0, 1.0, std)
    return ((x - mean) / std).astype(np.float32)


def _loan_field(row: Dict[str, str], col: str) -> float:
    """One engineered cell: categorical→ordinal via the maps, numeric
    parse otherwise, NaN for missing (filled with -99 downstream,
    ``process_data``, lending_club_dataset.py:115-118)."""
    if col == "annual_inc_comp":
        # compute_annual_income (lending_club_dataset.py:57-60): joint
        # income when the joint verification status matches.  A missing
        # joint status is NaN in pandas and NaN == anything is False,
        # so empty never matches.
        joint = row.get("verification_status_joint") or None
        if joint is not None and row.get("verification_status", "") == joint:
            raw = row.get("annual_inc_joint", "")
        else:
            raw = row.get("annual_inc", "")
    else:
        raw = row.get(col, "")
    m = LOAN_CATEGORY_MAPS.get(col)
    if m is not None:
        return float(m.get(raw if raw is not None else "", np.nan))
    try:
        return float(raw)
    except (TypeError, ValueError):
        return np.nan


def load_lending_club_raw(csv_path: str) -> Tuple[np.ndarray, np.ndarray]:
    """The reference's ``prepare_data`` + ``process_data`` pipeline
    (lending_club_dataset.py:100-123): loan.csv → good/bad target from
    loan_status, composite annual income, issue_year==2018 filter,
    categorical digitization, the 81-column feature selection,
    fillna(-99), per-column standardization.  Returns (x [N, 81],
    y [N] int 0=Good/1=Bad)."""
    import csv as _csv
    import re as _re

    xs, ys = [], []
    with open(csv_path, newline="") as f:
        for row in _csv.DictReader(f):
            m = _re.search(r"(\d{4})", row.get("issue_d", "") or "")
            if m is None or int(m.group(1)) != 2018:  # issue_year filter
                continue
            ys.append(1 if row.get("loan_status") in LOAN_BAD_STATUS else 0)
            xs.append([_loan_field(row, c) for c in LOAN_ALL_FEATURES])
    x = np.asarray(xs, np.float64)
    x = np.where(np.isnan(x), -99.0, x)  # fillna(-99)
    return standardize_columns(x), np.asarray(ys, np.int32)


def load_lending_club(
    data_dir: str = "./data/lending_club_loan",
    num_hosts: int = 1,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, list]:
    """VFL table: returns (X, y, feature_splits) where feature_splits
    gives each party's column slice (guest first) — the reference splits
    loan features between one guest (with labels) and hosts
    (``lending_club_loan/lending_club_dataset.py:141-162``).

    Formats, in order: raw ``loan.csv`` (full reference feature
    engineering, ``load_lending_club_raw``), preprocessed
    ``loan_processed.npz``, synthetic stand-in."""
    raw = os.path.join(data_dir, "loan.csv")
    path = os.path.join(data_dir, "loan_processed.npz")
    if os.path.exists(raw):
        x, y = load_lending_club_raw(raw)
        # reference party split: A = qualification+loan prefix, B = rest;
        # extra hosts subdivide B (three-party mode halves it,
        # loan_load_three_party_data)
        d = x.shape[1]
        cuts = np.linspace(LOAN_PARTY_A_DIM, d, num_hosts + 1).astype(int)
        splits = [slice(0, LOAN_PARTY_A_DIM)] + [
            slice(cuts[i], cuts[i + 1]) for i in range(num_hosts)
        ]
        return x, y, splits
    if os.path.exists(path):
        z = np.load(path)
        x, y = z["x"].astype(np.float32), z["y"].astype(np.int32)
    else:
        rng = np.random.RandomState(seed)
        n, d = 512, 24
        w = rng.randn(d).astype(np.float32)
        x = rng.randn(n, d).astype(np.float32)
        y = (x @ w > 0).astype(np.int32)
    d = x.shape[1]
    parties = num_hosts + 1
    cuts = np.linspace(0, d, parties + 1).astype(int)
    splits = [slice(cuts[i], cuts[i + 1]) for i in range(parties)]
    return x, y, splits


def load_nus_wide_raw(
    data_dir: str,
    selected_labels: Optional[list] = None,
    top_k: int = 2,
    dtype: str = "Train",
) -> Tuple[np.ndarray, np.ndarray, int]:
    """The reference's raw NUS-WIDE parsing
    (``NUS_WIDE/nus_wide_dataset.py:8-62``):

    - ``Groundtruth/AllLabels/Labels_<label>.txt`` → per-label positive
      counts, top-k selection (``get_top_k_labels``);
    - ``Groundtruth/TrainTestLabels/Labels_<label>_<dtype>.txt`` → 0/1
      rows; with >1 labels keep rows where EXACTLY one fires;
    - ``Low_Level_Features/<dtype>_Normalized_*`` (space-separated,
      trailing-blank column dropped) concatenated → guest's 634-d image
      features;
    - ``NUS_WID_Tags/<dtype>_Tags1k.dat`` (tab-separated) → host's
      1000-d tag features;
    - y = 1 where the FIRST selected label fires, else 0 (the
      reference's two-party loader, ``:84-94``, with neg_label=0 —
      our BCE losses take {0,1} rather than its {-1,1}).

    Returns (x = [guest | host] columns standardized per party,
    y, guest_dim)."""
    gt = os.path.join(data_dir, "Groundtruth")
    if selected_labels is None:
        counts = {}
        all_dir = os.path.join(gt, "AllLabels")
        for fname in sorted(os.listdir(all_dir)):
            label = fname[:-4].split("_")[-1]
            vals = np.loadtxt(os.path.join(all_dir, fname), dtype=np.int64,
                              ndmin=1)
            counts[label] = int((vals == 1).sum())
        selected_labels = [
            k for k, _ in sorted(counts.items(), key=lambda kv: kv[1],
                                 reverse=True)[:top_k]
        ]
    cols = [
        np.loadtxt(
            os.path.join(gt, "TrainTestLabels",
                         f"Labels_{label}_{dtype}.txt"),
            dtype=np.int64, ndmin=1,
        )
        for label in selected_labels
    ]
    labels = np.stack(cols, axis=1)  # [N, k]
    keep = (labels.sum(axis=1) == 1) if labels.shape[1] > 1 \
        else np.ones(len(labels), bool)

    feat_dir = os.path.join(data_dir, "Low_Level_Features")
    feats = []
    for fname in sorted(os.listdir(feat_dir)):
        if fname.startswith(f"{dtype}_Normalized"):
            block = np.genfromtxt(os.path.join(feat_dir, fname),
                                  dtype=np.float64, ndmin=2)
            # trailing separator yields an all-NaN column (reference
            # dropna(axis=1)); drop any fully-NaN columns
            block = block[:, ~np.all(np.isnan(block), axis=0)]
            feats.append(block)
    xa = np.concatenate(feats, axis=1)[keep]
    tags = np.genfromtxt(
        os.path.join(data_dir, "NUS_WID_Tags", f"{dtype}_Tags1k.dat"),
        delimiter="\t", dtype=np.float64, ndmin=2,
    )
    tags = tags[:, ~np.all(np.isnan(tags), axis=0)][keep]
    y = (labels[keep][:, 0] == 1).astype(np.int32)
    x = np.concatenate(
        [standardize_columns(xa), standardize_columns(tags)], axis=1
    )
    return x, y, xa.shape[1]


def load_nus_wide(
    data_dir: str = "./data/NUS_WIDE",
    binary_label: int = 1,
    seed: int = 0,
    selected_labels: Optional[list] = None,
) -> Tuple[np.ndarray, np.ndarray, list]:
    """NUS-WIDE VFL split: guest = 634-d low-level image features,
    host = 1000-d tag features (reference ``NUS_WIDE/nus_wide_dataset.py``).
    Formats, in order: the raw Groundtruth/Low_Level_Features/Tags tree
    (``load_nus_wide_raw``), preprocessed npz, synthetic stand-in."""
    if os.path.isdir(os.path.join(data_dir, "Groundtruth")):
        x, y, guest_dim = load_nus_wide_raw(
            data_dir, selected_labels=selected_labels
        )
        return x, y, [slice(0, guest_dim), slice(guest_dim, x.shape[1])]
    path = os.path.join(data_dir, "nus_wide_processed.npz")
    if os.path.exists(path):
        z = np.load(path)
        x, y = z["x"].astype(np.float32), z["y"].astype(np.int32)
        guest_dim = int(z.get("guest_dim", 634))
    else:
        rng = np.random.RandomState(seed)
        n, guest_dim, host_dim = 256, 64, 100
        x = rng.randn(n, guest_dim + host_dim).astype(np.float32)
        w = rng.randn(guest_dim + host_dim).astype(np.float32)
        y = (x @ w > 0).astype(np.int32)
    return x, y, [slice(0, guest_dim), slice(guest_dim, x.shape[1])]
