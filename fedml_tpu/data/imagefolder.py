"""PIL-backed image-folder parsing shared by the ImageNet / Landmarks /
CINIC-10 loaders.

Reference semantics reproduced here:

- class-per-subdirectory trees with alphabetically sorted class names
  and sorted file walks (``fedml_api/data_preprocessing/ImageNet/
  datasets.py:21-54`` ``find_classes``/``make_dataset``), so a given
  tree yields the same (path, label) order as the reference;
- CSV user→image maps with ``user_id,image_id,class`` columns, rows
  grouped per user in first-appearance order and concatenated into one
  contiguous array per user (``Landmarks/data_loader.py:125-161``
  ``get_mapping_per_user``), images at ``<data_dir>/<image_id>.jpg``
  (``Landmarks/datasets.py:46-49``).

Decoding departs from the reference deliberately: torchvision's
per-sample ``RandomResizedCrop``/``RandomHorizontalFlip``/``Cutout``
transforms are AUGMENTATION, not parsing — in this framework they run
on-device inside the compiled local update (``data/augment.py``), so
host-side decode is a deterministic resize + normalize producing fixed
[N, H, W, C] float32 arrays the packers can ship to HBM once.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Sequence, Tuple

import numpy as np

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def find_classes(root: str) -> Tuple[List[str], Dict[str, int]]:
    """Sorted subdirectory names → class indices (reference
    ``datasets.py:21-25``)."""
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )
    return classes, {c: i for i, c in enumerate(classes)}


def scan_class_tree(
    root: str, max_per_class: int = 0
) -> Tuple[List[str], np.ndarray, List[str]]:
    """Walk ``root/<class>/**`` in sorted order (reference
    ``datasets.py:28-54`` ``make_dataset``): returns (paths, labels,
    classes) with samples grouped per class in class order — the
    contiguous layout the reference's ``net_dataidx_map`` ranges rely
    on.  ``max_per_class`` (0 = all) bounds decode volume: the loaders
    materialize decoded images as one host array (the packers ship
    arrays to HBM), so full-size ImageNet (~770 GB at 224²) must come
    in capped, pre-resized, or via the npz route — see
    ``data/imagenet.py``."""
    classes, class_to_idx = find_classes(root)
    paths: List[str] = []
    labels: List[int] = []
    for target in classes:
        d = os.path.join(root, target)
        kept = 0
        for sub, _, fnames in sorted(os.walk(d)):
            for fname in sorted(fnames):
                if fname.lower().endswith(IMG_EXTENSIONS):
                    if max_per_class and kept >= max_per_class:
                        break
                    paths.append(os.path.join(sub, fname))
                    labels.append(class_to_idx[target])
                    kept += 1
    return paths, np.asarray(labels, np.int32), classes


def decode_images(
    paths: Sequence[str],
    image_size: int,
    mean: Sequence[float],
    std: Sequence[float],
) -> np.ndarray:
    """PIL-decode + RGB-convert (reference ``pil_loader``,
    ``datasets.py:57-61``) + deterministic resize + normalize →
    [N, H, W, 3] float32."""
    from PIL import Image

    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    out = np.empty((len(paths), image_size, image_size, 3), np.float32)
    for i, p in enumerate(paths):
        with open(p, "rb") as f:
            img = Image.open(f).convert("RGB")
        if img.size != (image_size, image_size):
            img = img.resize((image_size, image_size), Image.BILINEAR)
        out[i] = np.asarray(img, np.float32) / 255.0
    return (out - mean) / std


def contiguous_class_clients(
    labels: np.ndarray, num_classes: int, num_clients: int
) -> Dict[int, np.ndarray]:
    """The reference's ImageNet federated split: clients own contiguous
    class blocks (``data_loader.py:154-162``: client_number=1000 → one
    class each, 100 → ten classes each).  Generalized to any
    ``num_clients`` dividing into near-equal class blocks."""
    per = max(1, num_classes // num_clients)
    return {
        c: np.where(
            (labels >= c * per)
            & (labels < ((c + 1) * per if c < num_clients - 1 else num_classes))
        )[0]
        for c in range(num_clients)
    }


def read_user_map_csv(path: str) -> List[Dict[str, str]]:
    """The reference's ``_read_csv`` (``Landmarks/data_loader.py:20-29``)
    with its column contract enforced."""
    with open(path, "r") as f:
        rows = list(csv.DictReader(f))
    expected = ("user_id", "image_id", "class")
    if rows and not all(col in rows[0] for col in expected):
        raise ValueError(
            "The mapping file must contain user_id, image_id and class "
            f"columns. The existing columns are {','.join(rows[0])}"
        )
    return rows


def group_rows_per_user(
    rows: List[Dict[str, str]],
) -> Tuple[List[Dict[str, str]], Dict[int, np.ndarray]]:
    """``get_mapping_per_user`` semantics (``Landmarks/data_loader.py:
    125-161``): group rows by user in first-appearance order, concatenate
    per-user blocks, return (flat rows, client → contiguous indices)."""
    per_user: Dict[str, List[Dict[str, str]]] = {}
    for row in rows:
        per_user.setdefault(row["user_id"], []).append(row)
    flat: List[Dict[str, str]] = []
    client_idx: Dict[int, np.ndarray] = {}
    off = 0
    for user_id, items in per_user.items():
        client_idx[int(user_id)] = np.arange(off, off + len(items))
        off += len(items)
        flat.extend(items)
    return flat, client_idx
