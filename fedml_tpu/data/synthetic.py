"""Synthetic federated datasets.

Two generators:

- ``synthetic_alpha_beta`` — the LEAF Synthetic(α,β) logistic-regression
  benchmark used by the reference
  (``fedml_api/data_preprocessing/synthetic_1_1/data_loader.py``; numbers
  at ``benchmark/README.md:14``): per-client model w_c ~ N(u_c, 1),
  u_c ~ N(0, α); per-client feature mean b_c ~ N(B_c, 1), B_c ~ N(0, β);
  features x ~ N(b_c, Σ) with Σ_jj = j^{-1.2}; labels argmax(softmax(Wx+b)).
- ``synthetic_classification`` — a generic learnable class-prototype
  dataset used as the offline stand-in when a real dataset's files are
  not on disk (this environment has no network egress; loaders fall back
  to matched-shape synthetic data and say so).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from fedml_tpu.core.partition import partition_data
from fedml_tpu.core.types import FedDataset


def synthetic_alpha_beta(
    alpha: float = 1.0,
    beta: float = 1.0,
    num_clients: int = 30,
    dim: int = 60,
    num_classes: int = 10,
    seed: int = 0,
) -> FedDataset:
    rng = np.random.RandomState(seed)
    samples_per_client = (
        np.random.RandomState(seed + 1).lognormal(4, 2, num_clients).astype(int) + 50
    )
    diag = np.array([(j + 1) ** -1.2 for j in range(dim)])

    xs, ys, owner = [], [], []
    for c in range(num_clients):
        u_c = rng.normal(0, alpha)
        B_c = rng.normal(0, beta)
        W = rng.normal(u_c, 1, (num_classes, dim))
        b = rng.normal(u_c, 1, num_classes)
        v_c = rng.normal(B_c, 1, dim)
        n = int(samples_per_client[c])
        x = rng.multivariate_normal(v_c, np.diag(diag), n).astype(np.float32)
        y = np.argmax(x @ W.T + b, axis=1).astype(np.int32)
        xs.append(x)
        ys.append(y)
        owner.extend([c] * n)

    x = np.concatenate(xs)
    y = np.concatenate(ys)
    owner = np.array(owner)
    n_total = len(x)
    test_rng = np.random.RandomState(seed + 2)
    test_mask = np.zeros(n_total, bool)
    test_mask[test_rng.choice(n_total, n_total // 10, replace=False)] = True

    train_idx_global = np.where(~test_mask)[0]
    remap = -np.ones(n_total, np.int64)
    remap[train_idx_global] = np.arange(len(train_idx_global))
    client_idx = {
        c: remap[np.where((owner == c) & ~test_mask)[0]] for c in range(num_clients)
    }
    return FedDataset(
        train_x=x[~test_mask],
        train_y=y[~test_mask],
        test_x=x[test_mask],
        test_y=y[test_mask],
        train_client_idx=client_idx,
        test_client_idx=None,
        num_classes=num_classes,
        name=f"synthetic_{alpha}_{beta}",
    )


def match_pixel_moments(ds: FedDataset, mean: float, std: float) -> FedDataset:
    """Affinely map a stand-in's features to a real dataset's pixel
    mean AND std (one global scalar + offset on signal and noise alike,
    so the task's Bayes error and the label-noise ceiling are
    untouched).

    Why both moments matter — two measured failures on the real chip:

    - **Scale**: the raw generator emits per-pixel second moment
      ≈ 1+σ² (‖x‖ ≈ 36 for 784 dims) vs real MNIST's [0,1] pixels at
      E[x²] ≈ .112 (‖x‖ ≈ 9.4).  First-layer gradients scale with
      ‖x‖², so the reference MNIST-LR lr=.03 ran ~16× hot and
      oscillated in a .41–.56 band for 400 rounds
      (CONVERGENCE_r04_mnist_lr_unscaled_negative.json).
    - **Placement**: matching the second moment ALONE mis-places it for
      white-background datasets.  TFF FEMNIST pixels (x = 1-ink) have
      E[x²] ≈ .79, but ~86% of that is a DC mean (.826²) and only .11
      is variance; a zero-mean stand-in carrying the whole .79 as
      VARIANCE feeds ~7× the real per-pixel signal power into the
      first conv layer — the reference lr=.1 NaN'd within 75 rounds
      (r4, femnist_cnn first attempt).  Matching mean and std puts the
      DC where the real data has it."""
    cur_mean = float(np.mean(ds.train_x, dtype=np.float64))
    cur_std = float(np.std(ds.train_x, dtype=np.float64))
    s = np.float32(std / cur_std)
    off = np.float32(mean - cur_mean * (std / cur_std))
    ds.train_x = ds.train_x * s + off
    ds.test_x = ds.test_x * s + off
    return ds


def _gaussian_blur_hw(a: np.ndarray, sigma: float) -> np.ndarray:
    """Separable Gaussian blur over the H, W axes of [..., H, W, C]
    (reflect padding), in plain numpy — no scipy dependency."""
    if a.ndim < 4:
        # (num_classes, H, W, C) minimum: with a flat input_shape the
        # axis arithmetic below would blur the feature axis and then the
        # CLASS axis, silently collapsing class separation
        raise ValueError(
            "smooth_sigma requires an image-shaped input_shape (H, W, C); "
            f"got prototype array of shape {a.shape}"
        )
    radius = max(1, int(3.0 * sigma))
    t = np.arange(-radius, radius + 1)
    k = np.exp(-(t**2) / (2.0 * sigma**2))
    k /= k.sum()

    def conv_axis(x, axis):
        xp = np.concatenate(
            [np.flip(x.take(range(1, radius + 1), axis=axis), axis=axis),
             x,
             np.flip(x.take(range(x.shape[axis] - radius - 1,
                                  x.shape[axis] - 1), axis=axis),
                     axis=axis)],
            axis=axis,
        )
        out = np.zeros_like(x)
        for i, w in enumerate(k):
            out += w * xp.take(range(i, i + x.shape[axis]), axis=axis)
        return out

    return conv_axis(conv_axis(a, a.ndim - 3), a.ndim - 2)


def synthetic_classification(
    num_train: int = 6000,
    num_test: int = 1000,
    input_shape=(28, 28, 1),
    num_classes: int = 10,
    num_clients: int = 10,
    partition: str = "hetero",
    partition_alpha: float = 0.5,
    noise: float = 0.8,
    label_noise: float = 0.0,
    seed: int = 0,
    name: str = "synthetic",
    smooth_sigma: float = 0.0,
    flip_symmetric: bool = False,
) -> FedDataset:
    """Class-prototype Gaussian data with the same shapes as a real dataset.

    ``label_noise`` = η flips that fraction of labels (train AND test,
    independently drawn) to a uniformly random WRONG class: a model that
    perfectly learns the clean prototypes still scores only ≈ 1−η test
    accuracy, giving the task a documented irreducible-error ceiling —
    saturating trajectories can't distinguish a correct FedAvg from a
    subtly wrong one (VERDICT r2 missing #1).  Partitioning uses the
    NOISY labels, as real noisy data would.

    ``smooth_sigma`` / ``flip_symmetric`` give the class signal the two
    statistics of natural images that make the reference's augmentation
    recipe (RandomCrop + RandomHorizontalFlip + Cutout,
    ``fedml_api/data_preprocessing/cifar10/data_loader.py:57-99``)
    label-PRESERVING: spatial smoothness (a few-pixel crop shift keeps
    prototype autocorrelation exp(-d²/4σ²) instead of zero, as for iid
    pixels) and horizontal-flip invariance (p ← (p + flip_W(p))/√2, so a
    flipped sample carries the same class signal).  Measured on the real
    chip: with iid-pixel prototypes the augmented north-star run is
    pinned at chance (train acc 0.11 after 12 rounds) — the recipe
    erases an iid-pixel signal entirely.  Prototypes are post-processed
    only (re-normalized to unit per-pixel std), so the RNG stream and
    every default-parameter output are unchanged."""
    rng = np.random.RandomState(seed)
    protos = rng.normal(0, 1, (num_classes, *input_shape)).astype(np.float32)
    if smooth_sigma > 0.0:
        protos = _gaussian_blur_hw(protos, smooth_sigma)
    if flip_symmetric:
        protos = (protos + protos[:, :, ::-1, :]) / np.sqrt(2.0)
    if smooth_sigma > 0.0 or flip_symmetric:
        # restore unit per-pixel signal std so `noise` keeps meaning
        # the same signal-to-noise ratio as the unsmoothed task
        protos /= protos.std(axis=(1, 2, 3), keepdims=True)
        protos = protos.astype(np.float32)

    def make(n, sd):
        r = np.random.RandomState(sd)
        y = r.randint(0, num_classes, n).astype(np.int32)
        x = protos[y] + r.normal(0, noise, (n, *input_shape)).astype(np.float32)
        if label_noise > 0.0:
            flip = r.rand(n) < label_noise
            # uniform over the num_classes-1 WRONG classes
            y = np.where(
                flip,
                (y + 1 + r.randint(0, num_classes - 1, n)) % num_classes,
                y,
            ).astype(np.int32)
        return x.astype(np.float32), y

    train_x, train_y = make(num_train, seed + 10)
    test_x, test_y = make(num_test, seed + 11)
    client_idx = partition_data(
        train_y, num_clients, partition, partition_alpha, seed=seed
    )
    return FedDataset(
        train_x=train_x,
        train_y=train_y,
        test_x=test_x,
        test_y=test_y,
        train_client_idx=client_idx,
        test_client_idx=None,
        num_classes=num_classes,
        name=name,
    )
