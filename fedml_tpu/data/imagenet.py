"""ImageNet (ILSVRC2012) and Google Landmarks (gld23k/gld160k)
federated loaders.

Reference: ``fedml_api/data_preprocessing/ImageNet/data_loader.py``
(JPEG folder tree ``train/<class>/``+``val/<class>/``, 1000 classes,
clients = contiguous class blocks) and ``Landmarks/data_loader.py``
(CSV mapping ``user_id,image_id,class`` → ``<image_id>.jpg`` files:
natural per-photographer partition, 233 clients for gld23k).  Both
real on-disk formats are parsed here with PIL (``data/imagefolder.py``;
fixture-tested with generated JPEGs in ``tests/test_data_fixtures.py``).
Fallbacks, in order: a preprocessed ``.npz`` (``x_train/y_train/
x_test/y_test`` [+ ``user_train`` client ids]), then a synthetic
stand-in with matching geometry (zero-egress environments).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from fedml_tpu.core.partition import partition_data
from fedml_tpu.core.types import FedDataset
from fedml_tpu.data.synthetic import synthetic_classification

# reference ImageNet/data_loader.py:41-43
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)
# reference Landmarks/data_loader.py:98-100
LANDMARKS_MEAN = (0.5, 0.5, 0.5)
LANDMARKS_STD = (0.5, 0.5, 0.5)


def _from_npz(path: str, num_classes: int, num_clients: int, name: str,
              seed: int) -> FedDataset:
    z = np.load(path)
    train_x = z["x_train"].astype(np.float32)
    train_y = z["y_train"].astype(np.int32)
    test_x = z["x_test"].astype(np.float32)
    test_y = z["y_test"].astype(np.int32)
    if "user_train" in z:
        users = np.asarray(z["user_train"])
        idx = {
            c: np.where(users == u)[0]
            for c, u in enumerate(np.unique(users))
        }
    else:
        idx = partition_data(train_y, num_clients, "homo", 0.5, seed)
    return FedDataset(
        train_x=train_x, train_y=train_y, test_x=test_x, test_y=test_y,
        train_client_idx=idx, test_client_idx=None,
        num_classes=num_classes, name=name,
    )


def _from_folder_tree(
    data_dir: str, num_clients: int, image_size: int, name: str,
    mean, std, test_subdir: str = "val", max_per_class: int = 0,
) -> FedDataset:
    """The reference's ImageNet on-disk format: ``train/<class>/*.jpg``
    + ``val/<class>/*.jpg`` (``ImageNet/datasets.py:92-97``), clients =
    contiguous class blocks (``data_loader.py:154-162``).

    Memory model: decoded images land in ONE host float32 array (the
    cohort packers ship arrays to HBM), so this path fits subsets /
    downsized trees — full ILSVRC2012 at 224² is ~770 GB and must be
    capped (``max_per_class``), decoded at a smaller ``image_size``, or
    preprocessed into the sharded npz route."""
    from fedml_tpu.data.imagefolder import (contiguous_class_clients,
                                            decode_images, scan_class_tree)

    train_paths, train_y, classes = scan_class_tree(
        os.path.join(data_dir, "train"), max_per_class=max_per_class
    )
    train_x = decode_images(train_paths, image_size, mean, std)
    test_root = os.path.join(data_dir, test_subdir)
    if os.path.isdir(test_root):
        test_paths, test_y, _ = scan_class_tree(
            test_root, max_per_class=max_per_class
        )
        test_x = decode_images(test_paths, image_size, mean, std)
    else:
        # no val/ tree: a STRIDED slice of the class-grouped train walk
        # (paths[:64] would be one class — accuracy on it is meaningless)
        # reusing the already-decoded rows
        sel = np.linspace(0, len(train_y) - 1,
                          min(64, len(train_y))).astype(int)
        test_x, test_y = train_x[sel], train_y[sel]
    num_classes = len(classes)
    return FedDataset(
        train_x=train_x, train_y=train_y, test_x=test_x, test_y=test_y,
        train_client_idx=contiguous_class_clients(
            train_y, num_classes, min(num_clients, num_classes)
        ),
        test_client_idx=None, num_classes=num_classes, name=name,
    )


def load_imagenet(
    data_dir: str = "./data/ImageNet",
    num_clients: int = 100,
    image_size: int = 224,
    seed: int = 0,
    max_per_class: int = 0,
) -> FedDataset:
    if os.path.isdir(os.path.join(data_dir, "train")):
        return _from_folder_tree(
            data_dir, num_clients, image_size, "imagenet",
            IMAGENET_MEAN, IMAGENET_STD, max_per_class=max_per_class,
        )
    path = os.path.join(data_dir, "imagenet_federated.npz")
    if os.path.exists(path):
        return _from_npz(path, 1000, num_clients, "imagenet", seed)
    return synthetic_classification(
        num_train=num_clients * 16, num_test=64,
        input_shape=(image_size, image_size, 3), num_classes=1000,
        num_clients=num_clients, partition="homo", seed=seed,
        name="imagenet(synthetic-standin)",
    )


def _from_user_map_csv(
    data_dir: str, train_map: str, test_map: str, image_size: int,
    num_classes: int, name: str,
) -> FedDataset:
    """The reference's Landmarks on-disk format: CSV rows
    ``user_id,image_id,class`` mapped to ``<data_dir>/<image_id>.jpg``
    (``Landmarks/data_loader.py:125-161``, ``datasets.py:46-49``)."""
    import csv

    from fedml_tpu.data.imagefolder import (decode_images,
                                            group_rows_per_user,
                                            read_user_map_csv)

    rows, client_idx = group_rows_per_user(read_user_map_csv(train_map))
    if os.path.exists(test_map):
        # the TEST split is NOT user-partitioned: the reference reads it
        # with a plain _read_csv and touches only image_id/class
        # (load_partition_data_landmarks, data_loader.py:206;
        # datasets.py:46-49) — enforce only those columns
        with open(test_map, "r") as f:
            test_rows = list(csv.DictReader(f))
        if test_rows and not all(
            c in test_rows[0] for c in ("image_id", "class")
        ):
            raise ValueError(
                "test mapping must contain image_id and class columns; "
                f"got {','.join(test_rows[0])}"
            )
    else:
        test_rows = rows[:64]

    def arrays(rs):
        paths = [os.path.join(data_dir, f"{r['image_id']}.jpg") for r in rs]
        y = np.asarray([int(r["class"]) for r in rs], np.int32)
        return decode_images(
            paths, image_size, LANDMARKS_MEAN, LANDMARKS_STD
        ), y

    train_x, train_y = arrays(rows)
    test_x, test_y = arrays(test_rows)
    classes = int(max(train_y.max(initial=0), test_y.max(initial=0))) + 1
    return FedDataset(
        train_x=train_x, train_y=train_y, test_x=test_x, test_y=test_y,
        train_client_idx=client_idx, test_client_idx=None,
        num_classes=max(num_classes, classes), name=name,
    )


def load_landmarks(
    data_dir: str = "./data/gld",
    variant: str = "gld23k",   # 233 clients / 203 classes (reference)
    image_size: int = 224,
    seed: int = 0,
    train_map: Optional[str] = None,
    test_map: Optional[str] = None,
) -> FedDataset:
    num_clients, num_classes = (233, 203) if variant == "gld23k" else (1262, 2028)
    # reference map-file names (main_fedavg.py:170-171 gld23k,
    # :185-186 gld160k); images live under <data_dir>/images
    trn, tst = (
        ("mini_gld_train_split.csv", "mini_gld_test.csv")
        if variant == "gld23k" else ("federated_train.csv", "test.csv")
    )
    train_map = train_map or os.path.join(data_dir, trn)
    test_map = test_map or os.path.join(data_dir, tst)
    if os.path.exists(train_map):
        return _from_user_map_csv(
            os.path.join(data_dir, "images"), train_map, test_map,
            image_size, num_classes, variant,
        )
    path = os.path.join(data_dir, f"{variant}_federated.npz")
    if os.path.exists(path):
        return _from_npz(path, num_classes, num_clients, variant, seed)
    small = min(num_clients, 50)
    return synthetic_classification(
        num_train=small * 12, num_test=48,
        input_shape=(image_size, image_size, 3), num_classes=num_classes,
        num_clients=small, partition="power_law", seed=seed,
        name=f"{variant}(synthetic-standin)",
    )
