"""ImageNet (ILSVRC2012) and Google Landmarks (gld23k/gld160k)
federated loaders.

Reference: ``fedml_api/data_preprocessing/ImageNet/data_loader.py``
(folder tree, 1000 classes, uniform client split) and ``Landmarks/``
(CSV mapping ``user_id → image file``: natural per-photographer
partition, 233 clients for gld23k).  Raw JPEG decoding needs PIL which
this offline build treats as optional: when a preprocessed ``.npz``
(``x_train/y_train/x_test/y_test`` [+ ``user_train`` client ids]) is
present it is used, otherwise a synthetic stand-in with matching
geometry is returned.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from fedml_tpu.core.partition import partition_data
from fedml_tpu.core.types import FedDataset
from fedml_tpu.data.synthetic import synthetic_classification


def _from_npz(path: str, num_classes: int, num_clients: int, name: str,
              seed: int) -> FedDataset:
    z = np.load(path)
    train_x = z["x_train"].astype(np.float32)
    train_y = z["y_train"].astype(np.int32)
    test_x = z["x_test"].astype(np.float32)
    test_y = z["y_test"].astype(np.int32)
    if "user_train" in z:
        users = np.asarray(z["user_train"])
        idx = {
            c: np.where(users == u)[0]
            for c, u in enumerate(np.unique(users))
        }
    else:
        idx = partition_data(train_y, num_clients, "homo", 0.5, seed)
    return FedDataset(
        train_x=train_x, train_y=train_y, test_x=test_x, test_y=test_y,
        train_client_idx=idx, test_client_idx=None,
        num_classes=num_classes, name=name,
    )


def load_imagenet(
    data_dir: str = "./data/ImageNet",
    num_clients: int = 100,
    image_size: int = 224,
    seed: int = 0,
) -> FedDataset:
    path = os.path.join(data_dir, "imagenet_federated.npz")
    if os.path.exists(path):
        return _from_npz(path, 1000, num_clients, "imagenet", seed)
    return synthetic_classification(
        num_train=num_clients * 16, num_test=64,
        input_shape=(image_size, image_size, 3), num_classes=1000,
        num_clients=num_clients, partition="homo", seed=seed,
        name="imagenet(synthetic-standin)",
    )


def load_landmarks(
    data_dir: str = "./data/gld",
    variant: str = "gld23k",   # 233 clients / 203 classes (reference)
    image_size: int = 224,
    seed: int = 0,
) -> FedDataset:
    num_clients, num_classes = (233, 203) if variant == "gld23k" else (1262, 2028)
    path = os.path.join(data_dir, f"{variant}_federated.npz")
    if os.path.exists(path):
        return _from_npz(path, num_classes, num_clients, variant, seed)
    small = min(num_clients, 50)
    return synthetic_classification(
        num_train=small * 12, num_test=48,
        input_shape=(image_size, image_size, 3), num_classes=num_classes,
        num_clients=small, partition="power_law", seed=seed,
        name=f"{variant}(synthetic-standin)",
    )
