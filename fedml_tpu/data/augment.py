"""Jit-compiled image augmentation.

The reference applies torchvision transforms per sample on the host
(``fedml_api/data_preprocessing/cifar10/data_loader.py:57-99``:
RandomCrop(32, padding=4), RandomHorizontalFlip, normalize, Cutout(16)).
Host-side per-sample python transforms would serialize the input
pipeline; here the same augmentations are a vectorized jax function
applied ONCE PER EPOCH to the whole shuffled epoch tensor inside the
compiled local update (see ``core.client.make_local_update``, which
documents why per-epoch, not per-step), so they fuse into the compiled
round and cost no host↔device traffic.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def make_image_augment(
    pad: int = 4,
    flip: bool = True,
    cutout: Optional[int] = 16,
) -> Callable:
    """Returns ``augment(rng, x)`` for x [B, H, W, C] (already normalized).

    Random crop via pad + per-sample one-hot SELECTION MATMULS,
    horizontal flip via mask-select, Cutout via a clipped square mask —
    all batched and jit-safe.

    The crop deliberately avoids every gather formulation: on v5e a
    vmapped ``dynamic_slice`` costs ~63 ms, advanced-indexing gather
    ~63 ms, and ``take_along_axis`` ~615 ms for a 4992-image epoch,
    because per-sample dynamic offsets go through the scalar/gather
    path.  Expressing the same shift as two one-hot einsums
    (``[B,H,H+2p] @ [B,H+2p,W+2p,C] @ [B,W,W+2p]``) puts it on the MXU:
    ~0.5 ms — 130x faster, numerically identical selection.
    """

    def augment(rng, x):
        B, H, W, C = x.shape
        k_crop, k_flip, k_cut = jax.random.split(rng, 3)

        if pad:
            xp = jnp.pad(
                x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="constant"
            )
            offs = jax.random.randint(k_crop, (B, 2), 0, 2 * pad + 1)
            # one-hot selection matrices: sy[b, i, I] = 1 iff I = i + dy_b
            sy = (
                jnp.arange(H)[None, :, None] + offs[:, 0][:, None, None]
                == jnp.arange(H + 2 * pad)[None, None, :]
            ).astype(x.dtype)
            sx = (
                jnp.arange(W)[None, :, None] + offs[:, 1][:, None, None]
                == jnp.arange(W + 2 * pad)[None, None, :]
            ).astype(x.dtype)
            x = jnp.einsum("bwJ,bhJc->bhwc", sx,
                           jnp.einsum("bhI,bIJc->bhJc", sy, xp))

        if flip:
            do = jax.random.bernoulli(k_flip, 0.5, (B, 1, 1, 1))
            x = jnp.where(do, x[:, :, ::-1, :], x)

        if cutout:
            cy = jax.random.randint(k_cut, (B,), 0, H)
            cx = jax.random.randint(jax.random.fold_in(k_cut, 1), (B,), 0, W)
            ys = jnp.arange(H)[None, :, None]
            xs = jnp.arange(W)[None, None, :]
            half = cutout // 2
            inside = (
                (ys >= (cy[:, None, None] - half))
                & (ys < (cy[:, None, None] + half))
                & (xs >= (cx[:, None, None] - half))
                & (xs < (cx[:, None, None] + half))
            )
            x = x * (1.0 - inside[..., None].astype(x.dtype))

        return x

    return augment


def cifar_augment() -> Callable:
    """The reference CIFAR recipe: crop(pad 4) + flip + Cutout(16)."""
    return make_image_augment(pad=4, flip=True, cutout=16)
