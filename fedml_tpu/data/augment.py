"""Jit-compiled image augmentation.

The reference applies torchvision transforms per sample on the host
(``fedml_api/data_preprocessing/cifar10/data_loader.py:57-99``:
RandomCrop(32, padding=4), RandomHorizontalFlip, normalize, Cutout(16)).
Host-side per-sample python transforms would serialize the input
pipeline; here the same augmentations are a vectorized jax function
applied to each [B, H, W, C] batch inside the compiled local-update
step (see ``core.client.make_local_update(augment_fn=...)``), so they
fuse with the forward pass and cost no host↔device traffic.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def make_image_augment(
    pad: int = 4,
    flip: bool = True,
    cutout: Optional[int] = 16,
) -> Callable:
    """Returns ``augment(rng, x)`` for x [B, H, W, C] (already normalized).

    Random crop via pad+dynamic_slice, horizontal flip via mask-select,
    Cutout via a clipped square mask — all batched and jit-safe.
    """

    def augment(rng, x):
        B, H, W, C = x.shape
        k_crop, k_flip, k_cut = jax.random.split(rng, 3)

        if pad:
            xp = jnp.pad(
                x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="constant"
            )
            offs = jax.random.randint(k_crop, (B, 2), 0, 2 * pad + 1)

            def crop_one(img, off):
                return jax.lax.dynamic_slice(
                    img, (off[0], off[1], 0), (H, W, C)
                )

            x = jax.vmap(crop_one)(xp, offs)

        if flip:
            do = jax.random.bernoulli(k_flip, 0.5, (B, 1, 1, 1))
            x = jnp.where(do, x[:, :, ::-1, :], x)

        if cutout:
            cy = jax.random.randint(k_cut, (B,), 0, H)
            cx = jax.random.randint(jax.random.fold_in(k_cut, 1), (B,), 0, W)
            ys = jnp.arange(H)[None, :, None]
            xs = jnp.arange(W)[None, None, :]
            half = cutout // 2
            inside = (
                (ys >= (cy[:, None, None] - half))
                & (ys < (cy[:, None, None] + half))
                & (xs >= (cx[:, None, None] - half))
                & (xs < (cx[:, None, None] + half))
            )
            x = x * (1.0 - inside[..., None].astype(x.dtype))

        return x

    return augment


def cifar_augment() -> Callable:
    """The reference CIFAR recipe: crop(pad 4) + flip + Cutout(16)."""
    return make_image_augment(pad=4, flip=True, cutout=16)
