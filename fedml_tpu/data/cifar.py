"""CIFAR-10 / CIFAR-100 / CINIC-10 federated loaders.

Reference: ``fedml_api/data_preprocessing/cifar10/data_loader.py`` (and
the cifar100/cinic10 twins): ``partition_data`` with ``homo`` (uniform)
or ``hetero`` (Dirichlet α) schemes (``:113-163``), per-channel
normalization constants (``:57-99``), 8-tuple emission (``:235-269``).
Here the loaders read the standard python pickles / image folders from
``data_dir`` when present and otherwise fall back to a matched-shape
synthetic stand-in (no egress), emitting the typed ``FedDataset``.
Train-time augmentation lives in ``data.augment`` (jit-compiled), not in
the loader.
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Optional, Tuple

import numpy as np

from fedml_tpu.core.partition import partition_data
from fedml_tpu.core.types import FedDataset
from fedml_tpu.data.synthetic import synthetic_classification

# reference normalization constants (cifar10/data_loader.py:60-63 etc.)
CIFAR10_MEAN, CIFAR10_STD = (0.4914, 0.4822, 0.4465), (0.2470, 0.2435, 0.2616)
CIFAR100_MEAN, CIFAR100_STD = (0.5071, 0.4865, 0.4409), (0.2673, 0.2564, 0.2762)
CINIC10_MEAN, CINIC10_STD = (0.47889522, 0.47227842, 0.43047404), (
    0.24205776, 0.23828046, 0.25874835)


def _normalize(x: np.ndarray, mean, std) -> np.ndarray:
    return ((x / 255.0) - np.asarray(mean, np.float32)) / np.asarray(
        std, np.float32
    )


def _load_cifar10_pickles(d: str):
    def batch(name):
        with open(os.path.join(d, name), "rb") as f:
            z = pickle.load(f, encoding="latin1")
        x = z["data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x.astype(np.float32), np.asarray(z["labels"], np.int32)

    xs, ys = zip(*[batch(f"data_batch_{i}") for i in range(1, 6)])
    tx, ty = batch("test_batch")
    return np.concatenate(xs), np.concatenate(ys), tx, ty


def _load_cifar100_pickles(d: str):
    def batch(name):
        with open(os.path.join(d, name), "rb") as f:
            z = pickle.load(f, encoding="latin1")
        x = z["data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x.astype(np.float32), np.asarray(z["fine_labels"], np.int32)

    x, y = batch("train")
    tx, ty = batch("test")
    return x, y, tx, ty


def _load_generic(data_dir: str, name: str):
    """npz fallback layout: {name}.npz with x_train/y_train/x_test/y_test."""
    p = os.path.join(data_dir, f"{name}.npz")
    if os.path.exists(p):
        z = np.load(p)
        return (z["x_train"].astype(np.float32), z["y_train"].astype(np.int32),
                z["x_test"].astype(np.float32), z["y_test"].astype(np.int32))
    return None


def _build(
    arrays: Optional[Tuple], mean, std, num_classes: int, name: str,
    num_clients: int, partition: str, partition_alpha: float, seed: int,
    synthetic_size: Tuple[int, int], normalized: bool = False,
) -> FedDataset:
    if arrays is None:
        return synthetic_classification(
            num_train=synthetic_size[0], num_test=synthetic_size[1],
            input_shape=(32, 32, 3), num_classes=num_classes,
            num_clients=num_clients, partition=partition,
            partition_alpha=partition_alpha, seed=seed,
            name=f"{name}(synthetic-standin)",
        )
    train_x, train_y, test_x, test_y = arrays
    if not normalized:
        train_x = _normalize(train_x, mean, std)
        test_x = _normalize(test_x, mean, std)
    client_idx = partition_data(
        train_y, num_clients, partition, partition_alpha, seed
    )
    return FedDataset(
        train_x=train_x, train_y=train_y, test_x=test_x, test_y=test_y,
        train_client_idx=client_idx, test_client_idx=None,
        num_classes=num_classes, name=name,
    )


def load_cifar10(
    data_dir: str = "./data/cifar10", num_clients: int = 10,
    partition: str = "hetero", partition_alpha: float = 0.5, seed: int = 0,
) -> FedDataset:
    sub = os.path.join(data_dir, "cifar-10-batches-py")
    d = sub if os.path.isdir(sub) else data_dir
    arrays = None
    if os.path.exists(os.path.join(d, "data_batch_1")):
        arrays = _load_cifar10_pickles(d)
    else:
        arrays = _load_generic(data_dir, "cifar10")
    return _build(arrays, CIFAR10_MEAN, CIFAR10_STD, 10, "cifar10",
                  num_clients, partition, partition_alpha, seed,
                  (50000, 10000) if arrays else (5000, 1000))


def load_cifar100(
    data_dir: str = "./data/cifar100", num_clients: int = 10,
    partition: str = "hetero", partition_alpha: float = 0.5, seed: int = 0,
) -> FedDataset:
    sub = os.path.join(data_dir, "cifar-100-python")
    d = sub if os.path.isdir(sub) else data_dir
    arrays = None
    if os.path.exists(os.path.join(d, "train")):
        arrays = _load_cifar100_pickles(d)
    else:
        arrays = _load_generic(data_dir, "cifar100")
    return _build(arrays, CIFAR100_MEAN, CIFAR100_STD, 100, "cifar100",
                  num_clients, partition, partition_alpha, seed,
                  (50000, 10000) if arrays else (5000, 1000))


def load_cinic10(
    data_dir: str = "./data/cinic10", num_clients: int = 10,
    partition: str = "hetero", partition_alpha: float = 0.5, seed: int = 0,
) -> FedDataset:
    """CINIC-10 ships as an ImageFolder tree (``train/<class>/*.png`` +
    ``test/<class>/*.png``, reference ``cinic10/data_loader.py:218-226``)
    — parsed with PIL here, normalized in the same decode pass with the
    CINIC constants.  Fallbacks: the npz layout, then the synthetic
    stand-in."""
    if os.path.isdir(os.path.join(data_dir, "train")):
        from fedml_tpu.data.imagefolder import decode_images, scan_class_tree

        tr_paths, tr_y, classes = scan_class_tree(
            os.path.join(data_dir, "train")
        )
        tr_x = decode_images(tr_paths, 32, CINIC10_MEAN, CINIC10_STD)
        te_dir = os.path.join(data_dir, "test")
        if os.path.isdir(te_dir):
            te_paths, te_y, _ = scan_class_tree(te_dir)
            te_x = decode_images(te_paths, 32, CINIC10_MEAN, CINIC10_STD)
        else:
            # strided slice across the class-grouped walk (a [:64] prefix
            # would be a one-class test set), reusing decoded rows
            sel = np.linspace(0, len(tr_y) - 1, min(64, len(tr_y))).astype(int)
            te_x, te_y = tr_x[sel], tr_y[sel]
        arrays = (tr_x, tr_y, te_x, te_y)
        return _build(arrays, CINIC10_MEAN, CINIC10_STD, 10, "cinic10",
                      num_clients, partition, partition_alpha, seed,
                      (5000, 1000), normalized=True)
    arrays = _load_generic(data_dir, "cinic10")
    return _build(arrays, CINIC10_MEAN, CINIC10_STD, 10, "cinic10",
                  num_clients, partition, partition_alpha, seed,
                  (90000, 90000) if arrays else (5000, 1000))
