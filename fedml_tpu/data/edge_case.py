"""Backdoor / edge-case poisoned datasets.

Reference ``fedml_api/data_preprocessing/edge_case_examples/data_loader.py:283-360``
loads pre-built poisoned sets (southwest-airline CIFAR backdoors,
ARDIS-7 MNIST digits, green cars) where out-of-distribution examples
are labeled with an attacker-chosen target class.

Two attack shapes are provided:

- **Edge-case / OOD label-flip** (``make_edge_case_backdoor``) — the
  reference's semantics mirrored exactly (``data_loader.py:380-440``):
  sample N out-of-distribution images (southwest planes), label them all
  ``target_label`` (9 = CIFAR "truck"), mix with M downsampled clean
  samples into the attacker's training set; the targeted-task test set
  is the OOD *test* images, all labeled ``target_label``.  The real
  southwest/ARDIS archives are external downloads unavailable in this
  zero-egress environment; ``load_edge_case_images`` reads them
  (pickled uint8 image arrays) when present, and
  ``synthetic_ood_images`` generates a stand-in distribution otherwise.
- **Pixel-trigger backdoor** (``make_backdoor``) — a pattern stamped on
  real samples, relabeled to ``target_label`` (the classic BadNets
  shape, used by the robust-aggregation tests).

Both produce the attacker's training mixture and the backdoor test set
used for targeted-accuracy measurement (``FedAvgRobustAggregator``
"targeted task" eval, SURVEY.md §2 row 13).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Optional, Tuple

import numpy as np

from fedml_tpu.core.types import FedDataset


def stamp_trigger(x: np.ndarray, intensity: float = 1.0) -> np.ndarray:
    """Stamp a 3×3 checker trigger in the bottom-right corner (image
    data [N,H,W,C]) or spike the last 3 features (flat data [N,D])."""
    x = x.copy()
    if x.ndim >= 3:
        for di in range(3):
            for dj in range(3):
                if (di + dj) % 2 == 0:
                    x[:, -1 - di, -1 - dj, ...] = intensity
    else:
        x[:, -3:] = intensity
    return x


@dataclasses.dataclass
class PoisonedData:
    train_x: np.ndarray  # attacker's mixed local training set
    train_y: np.ndarray
    backdoor_test_x: np.ndarray  # triggered held-out samples
    backdoor_test_y: np.ndarray  # all = target_label


def make_backdoor(
    dataset: FedDataset,
    attacker_client: int,
    target_label: int = 0,
    poison_fraction: float = 0.3,
    intensity: float = 1.0,
    seed: int = 0,
) -> PoisonedData:
    rng = np.random.RandomState(seed)
    idx = np.asarray(dataset.train_client_idx[attacker_client])
    honest_x = dataset.train_x[idx]
    honest_y = dataset.train_y[idx]
    n_poison = max(1, int(len(idx) * poison_fraction))
    src = rng.choice(len(idx), n_poison, replace=False)
    poison_x = stamp_trigger(honest_x[src], intensity)
    poison_y = np.full(n_poison, target_label, dtype=honest_y.dtype)

    # mixture, shuffled — the attacker still trains on honest data too
    mix_x = np.concatenate([honest_x, poison_x])
    mix_y = np.concatenate([honest_y, poison_y])
    order = rng.permutation(len(mix_x))

    # targeted-task eval: triggered test samples whose TRUE label differs
    not_target = dataset.test_y != target_label
    bt_x = stamp_trigger(dataset.test_x[not_target], intensity)
    bt_y = np.full(int(not_target.sum()), target_label, dtype=dataset.test_y.dtype)
    return PoisonedData(
        train_x=mix_x[order],
        train_y=mix_y[order],
        backdoor_test_x=bt_x,
        backdoor_test_y=bt_y,
    )


# ---------------------------------------------------------------------------
# edge-case (OOD label-flip) attack — the reference's southwest semantics
# ---------------------------------------------------------------------------

_TRAIN_PKL = "southwest_images_new_train.pkl"
_TEST_PKL = "southwest_images_new_test.pkl"


def load_edge_case_images(
    data_dir: str,
    train_name: str = _TRAIN_PKL,
    test_name: str = _TEST_PKL,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Read the reference's edge-case archives when present.

    Format (``data_loader.py:355-360``): each .pkl is a pickled uint8
    image ndarray ``[N, 32, 32, 3]``.  Returns float32 images scaled to
    [0, 1] (our pipelines' convention), or None if the files are absent
    (they are external downloads; this environment has no egress).
    """
    tr, te = os.path.join(data_dir, train_name), os.path.join(data_dir, test_name)
    if not (os.path.exists(tr) and os.path.exists(te)):
        return None
    with open(tr, "rb") as f:
        train = pickle.load(f)
    with open(te, "rb") as f:
        test = pickle.load(f)

    def norm(a):
        a = np.asarray(a)
        return a.astype(np.float32) / 255.0 if a.dtype == np.uint8 else a.astype(np.float32)

    return norm(train), norm(test)


def synthetic_ood_images(
    shape: Tuple[int, ...],
    num_train: int = 200,
    num_test: int = 100,
    seed: int = 7,
) -> Tuple[np.ndarray, np.ndarray]:
    """Offline stand-in for the southwest archive: one out-of-distribution
    prototype (not any class prototype of ``synthetic_classification``)
    plus noise — the same 'coherent cluster far from the training
    manifold' structure that makes edge-case attacks hard to detect."""
    rng = np.random.RandomState(seed)
    proto = rng.normal(3.0, 1.0, shape).astype(np.float32)  # shifted mean: OOD
    mk = lambda n: proto + rng.normal(0, 0.3, (n, *shape)).astype(np.float32)  # noqa: E731
    return mk(num_train), mk(num_test)


def make_edge_case_backdoor(
    dataset: FedDataset,
    ood_train: np.ndarray,
    ood_test: np.ndarray,
    target_label: int = 9,
    num_poison: int = 100,
    num_clean: int = 400,
    seed: int = 0,
) -> PoisonedData:
    """The reference's edge-case attack, exactly (``data_loader.py:380-440``):

    - sample ``num_poison`` (reference N=100) OOD train images without
      replacement, all labeled ``target_label`` (reference: 9, "southwest
      airplane -> label as truck");
    - downsample ``num_clean`` (reference M=400) clean train samples;
    - the attacker's set is their concatenation (the DataLoader shuffles;
      here the pack's per-client permutation does);
    - the targeted-task test set is the OOD *test* images, all labeled
      ``target_label`` (reference ``poisoned_testset``).
    """
    rng = np.random.RandomState(seed)
    n_poison = min(num_poison, len(ood_train))
    pick = rng.choice(len(ood_train), n_poison, replace=False)
    poison_x = ood_train[pick]
    poison_y = np.full(n_poison, target_label, dtype=dataset.train_y.dtype)

    n_clean = min(num_clean, len(dataset.train_x))
    clean_pick = rng.choice(len(dataset.train_x), n_clean, replace=False)
    clean_x = dataset.train_x[clean_pick]
    clean_y = dataset.train_y[clean_pick]

    return PoisonedData(
        train_x=np.concatenate([clean_x, poison_x]).astype(np.float32),
        train_y=np.concatenate([clean_y, poison_y]),
        backdoor_test_x=np.asarray(ood_test, np.float32),
        backdoor_test_y=np.full(len(ood_test), target_label,
                                dtype=dataset.test_y.dtype),
    )
