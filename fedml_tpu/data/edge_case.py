"""Backdoor / edge-case poisoned datasets.

Reference ``fedml_api/data_preprocessing/edge_case_examples/data_loader.py:283-713``
loads pre-built poisoned sets where out-of-distribution (or rare
in-distribution) examples are labeled with an attacker-chosen target
class.  All FIVE reference poison families are rebuilt behind one
``poison_type`` switch (``make_poisoned_dataset``):

- ``southwest`` (``:329-434``) — OOD Southwest-airline planes → CIFAR
  label 9 (truck); N=100 poison + 400 downsampled clean.
- ``southwest-da`` (``:436-541``) — same data, but the poison samples
  additionally carry Gaussian noise (the reference's
  ``AddGaussianNoise(0., 0.05)`` poison-side transform — data
  augmentation as duplicate-detection evasion).
- ``ardis`` (``:294-325``) — OOD ARDIS handwritten digit "7"s → MNIST
  label 1 (the pre-built ``poisoned_dataset_fraction_*`` /
  ``ardis_test_dataset.pt`` torch archives).
- ``howto`` (``:543-621``) — "How To Backdoor Federated Learning":
  CIFAR-10's OWN green-car images, selected by the paper's fixed train
  indices, → label 2 (bird); the targeted test set is the transformed
  green-car archive.
- ``greencar-neo`` (``:623-713``) — newly collected green-car images
  (``new_green_cars_*.pkl``), 100 sampled, → label 2; 400 clean.

Two attack shapes are provided:

- **Edge-case / OOD label-flip** (``make_edge_case_backdoor``) — the
  reference's semantics mirrored exactly (``data_loader.py:380-440``):
  sample N out-of-distribution images (southwest planes), label them all
  ``target_label`` (9 = CIFAR "truck"), mix with M downsampled clean
  samples into the attacker's training set; the targeted-task test set
  is the OOD *test* images, all labeled ``target_label``.  The real
  southwest/ARDIS archives are external downloads unavailable in this
  zero-egress environment; ``load_edge_case_images`` reads them
  (pickled uint8 image arrays) when present, and
  ``synthetic_ood_images`` generates a stand-in distribution otherwise.
- **Pixel-trigger backdoor** (``make_backdoor``) — a pattern stamped on
  real samples, relabeled to ``target_label`` (the classic BadNets
  shape, used by the robust-aggregation tests).

Both produce the attacker's training mixture and the backdoor test set
used for targeted-accuracy measurement (``FedAvgRobustAggregator``
"targeted task" eval, SURVEY.md §2 row 13).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Optional, Tuple

import numpy as np

from fedml_tpu.core.types import FedDataset


def stamp_trigger(x: np.ndarray, intensity: float = 1.0) -> np.ndarray:
    """Stamp a 3×3 checker trigger in the bottom-right corner (image
    data [N,H,W,C]) or spike the last 3 features (flat data [N,D])."""
    x = x.copy()
    if x.ndim >= 3:
        for di in range(3):
            for dj in range(3):
                if (di + dj) % 2 == 0:
                    x[:, -1 - di, -1 - dj, ...] = intensity
    else:
        x[:, -3:] = intensity
    return x


@dataclasses.dataclass
class PoisonedData:
    train_x: np.ndarray  # attacker's mixed local training set
    train_y: np.ndarray
    backdoor_test_x: np.ndarray  # triggered held-out samples
    backdoor_test_y: np.ndarray  # all = target_label


def make_backdoor(
    dataset: FedDataset,
    attacker_client: int,
    target_label: int = 0,
    poison_fraction: float = 0.3,
    intensity: float = 1.0,
    seed: int = 0,
) -> PoisonedData:
    rng = np.random.RandomState(seed)
    idx = np.asarray(dataset.train_client_idx[attacker_client])
    honest_x = dataset.train_x[idx]
    honest_y = dataset.train_y[idx]
    n_poison = max(1, int(len(idx) * poison_fraction))
    src = rng.choice(len(idx), n_poison, replace=False)
    poison_x = stamp_trigger(honest_x[src], intensity)
    poison_y = np.full(n_poison, target_label, dtype=honest_y.dtype)

    # mixture, shuffled — the attacker still trains on honest data too
    mix_x = np.concatenate([honest_x, poison_x])
    mix_y = np.concatenate([honest_y, poison_y])
    order = rng.permutation(len(mix_x))

    # targeted-task eval: triggered test samples whose TRUE label differs
    not_target = dataset.test_y != target_label
    bt_x = stamp_trigger(dataset.test_x[not_target], intensity)
    bt_y = np.full(int(not_target.sum()), target_label, dtype=dataset.test_y.dtype)
    return PoisonedData(
        train_x=mix_x[order],
        train_y=mix_y[order],
        backdoor_test_x=bt_x,
        backdoor_test_y=bt_y,
    )


# ---------------------------------------------------------------------------
# edge-case (OOD label-flip) attack — the reference's southwest semantics
# ---------------------------------------------------------------------------

_TRAIN_PKL = "southwest_images_new_train.pkl"
_TEST_PKL = "southwest_images_new_test.pkl"


def load_edge_case_images(
    data_dir: str,
    train_name: str = _TRAIN_PKL,
    test_name: str = _TEST_PKL,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Read the reference's edge-case archives when present.

    Format (``data_loader.py:355-360``): each .pkl is a pickled uint8
    image ndarray ``[N, 32, 32, 3]``.  Returns float32 images scaled to
    [0, 1] (our pipelines' convention), or None if the files are absent
    (they are external downloads; this environment has no egress).
    """
    tr, te = os.path.join(data_dir, train_name), os.path.join(data_dir, test_name)
    if not (os.path.exists(tr) and os.path.exists(te)):
        return None
    with open(tr, "rb") as f:
        train = pickle.load(f)
    with open(te, "rb") as f:
        test = pickle.load(f)

    def norm(a):
        a = np.asarray(a)
        return a.astype(np.float32) / 255.0 if a.dtype == np.uint8 else a.astype(np.float32)

    return norm(train), norm(test)


def synthetic_ood_images(
    shape: Tuple[int, ...],
    num_train: int = 200,
    num_test: int = 100,
    seed: int = 7,
) -> Tuple[np.ndarray, np.ndarray]:
    """Offline stand-in for the southwest archive: one out-of-distribution
    prototype (not any class prototype of ``synthetic_classification``)
    plus noise — the same 'coherent cluster far from the training
    manifold' structure that makes edge-case attacks hard to detect."""
    rng = np.random.RandomState(seed)
    proto = rng.normal(3.0, 1.0, shape).astype(np.float32)  # shifted mean: OOD
    mk = lambda n: proto + rng.normal(0, 0.3, (n, *shape)).astype(np.float32)  # noqa: E731
    return mk(num_train), mk(num_test)


def make_edge_case_backdoor(
    dataset: FedDataset,
    ood_train: np.ndarray,
    ood_test: np.ndarray,
    target_label: int = 9,
    num_poison: int = 100,
    num_clean: int = 400,
    seed: int = 0,
    shuffle: bool = True,
) -> PoisonedData:
    """The reference's edge-case attack, exactly (``data_loader.py:380-440``):

    - sample ``num_poison`` (reference N=100) OOD train images without
      replacement, all labeled ``target_label`` (reference: 9, "southwest
      airplane -> label as truck");
    - downsample ``num_clean`` (reference M=400) clean train samples;
    - the attacker's set is their SHUFFLED concatenation (the
      reference's DataLoader shuffles; shuffling here is load-bearing —
      ``FedAvgRobustSimulation._poison_slot_rows`` truncates the
      mixture to the cohort's fixed slot size by PREFIX, so an
      unshuffled clean-then-poison layout would silently drop the
      entire poison tail whenever the mixture outsizes the slot);
    - the targeted-task test set is the OOD *test* images, all labeled
      ``target_label`` (reference ``poisoned_testset``).

    ``shuffle=False`` keeps the clean-rows-then-poison-rows layout for
    callers that index the two blocks (the southwest-da noise stamp,
    fixture tests).
    """
    rng = np.random.RandomState(seed)
    n_poison = min(num_poison, len(ood_train))
    pick = rng.choice(len(ood_train), n_poison, replace=False)
    poison_x = ood_train[pick]
    poison_y = np.full(n_poison, target_label, dtype=dataset.train_y.dtype)

    n_clean = min(num_clean, len(dataset.train_x))
    clean_pick = rng.choice(len(dataset.train_x), n_clean, replace=False)
    clean_x = dataset.train_x[clean_pick]
    clean_y = dataset.train_y[clean_pick]

    mix_x = np.concatenate([clean_x, poison_x]).astype(np.float32)
    mix_y = np.concatenate([clean_y, poison_y])
    if shuffle:
        order = rng.permutation(len(mix_x))
        mix_x, mix_y = mix_x[order], mix_y[order]
    return PoisonedData(
        train_x=mix_x,
        train_y=mix_y,
        backdoor_test_x=np.asarray(ood_test, np.float32),
        backdoor_test_y=np.full(len(ood_test), target_label,
                                dtype=dataset.test_y.dtype),
    )


# ---------------------------------------------------------------------------
# the full reference poison-family matrix, behind one switch
# ---------------------------------------------------------------------------

POISON_FAMILIES = (
    "southwest", "southwest-da", "ardis", "howto", "greencar-neo",
)

# "How To Backdoor FL" green-car samples inside CIFAR-10's canonical
# train ordering (reference data_loader.py:563-566) — the howto attack
# poisons the host dataset's OWN rare samples, not an external archive.
HOWTO_GREEN_CAR_TRAIN_IDX = [
    874, 49163, 34287, 21422, 48003, 47001, 48030, 22984, 37533, 41336,
    3678, 37365, 19165, 34385, 41861, 39824, 561, 49588, 4528, 3378,
    38658, 38735, 19500, 9744, 47026, 1605, 389,
]
HOWTO_GREEN_CAR_TEST_IDX = [32941, 36005, 40138]

_GREENCAR_TRAIN_PKL = "new_green_cars_train.pkl"
_GREENCAR_TEST_PKL = "new_green_cars_test.pkl"
_GREENCAR_HOWTO_TEST_PKL = "green_car_transformed_test.pkl"
_ARDIS_TEST_PT = "ardis_test_dataset.pt"


def load_ardis_test(data_dir: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Read the reference's ARDIS targeted-test archive when present.

    Format (``data_loader.py:319-321``): a ``torch.load``-able object —
    either a dataset with ``.data``/``.targets`` (how the reference
    consumes it, via a DataLoader) or a raw image tensor/array.  Images
    come back float32 [N, 28, 28, 1] in [0, 1]."""
    path = os.path.join(data_dir, _ARDIS_TEST_PT)
    if not os.path.exists(path):
        return None
    import torch

    obj = torch.load(path, weights_only=False)
    if hasattr(obj, "data"):
        data = np.asarray(obj.data)
        targets = np.asarray(getattr(obj, "targets", np.ones(len(data))))
    else:
        data = np.asarray(obj)
        targets = np.ones(len(data))
    if data.dtype == np.uint8:
        data = data.astype(np.float32) / 255.0
    if data.ndim == 3:
        data = data[..., None]
    return data.astype(np.float32), targets.astype(np.int64)


def make_poisoned_dataset(
    dataset: FedDataset,
    poison_type: str = "southwest",
    data_dir: str = "",
    *,
    seed: int = 0,
    num_poison: Optional[int] = None,
    num_clean: Optional[int] = None,
    shuffle: bool = True,
) -> PoisonedData:
    """One switch over the reference's five poison families
    (``load_poisoned_dataset``, ``data_loader.py:283-713``), returning
    the attacker's mixed training set + the targeted-task test set.

    Real archives are read from ``data_dir`` when present (pickled uint8
    arrays for the CIFAR families, a torch .pt for ardis — the exact
    on-disk formats the reference downloads); otherwise the documented
    synthetic OOD stand-in fills in (zero-egress environment).

    Per-family deviations, deliberate and visible:

    - ``southwest-da``: the reference applies ``AddGaussianNoise(0, .05)``
      as a per-draw torchvision transform; here the noise is stamped
      once at construction (one fixed draw per poison sample).  The
      attack property — poison images that are not byte-identical to
      the archive, evading exact-duplicate defenses — is preserved.
    - ``howto`` on a stand-in dataset: the fixed green-car indices
      assume CIFAR-10's canonical ordering; on synthetic fallbacks they
      still select a deterministic rare subset, which keeps the
      attack's structure (host-distribution samples relabeled) without
      the real-image semantics.
    """
    rng = np.random.RandomState(seed)
    img_shape = dataset.train_x.shape[1:]

    def ood_or_standin(train_pkl, test_pkl, ood_seed):
        loaded = load_edge_case_images(data_dir, train_pkl, test_pkl) \
            if data_dir else None
        if loaded is not None:
            return loaded
        return synthetic_ood_images(img_shape, seed=ood_seed)

    def _shuffled(out):
        """One seed-deterministic permutation, shared across families
        at the same seed (southwest vs southwest-da outputs stay
        row-aligned for comparison)."""
        if not shuffle:
            return out
        order = np.random.RandomState(seed + 1).permutation(
            len(out.train_x))
        return dataclasses.replace(
            out, train_x=out.train_x[order], train_y=out.train_y[order])

    if poison_type in ("southwest", "southwest-da"):
        ood_train, ood_test = ood_or_standin(_TRAIN_PKL, _TEST_PKL, 7)
        out = make_edge_case_backdoor(
            dataset, ood_train, ood_test, target_label=9,
            num_poison=100 if num_poison is None else num_poison,
            num_clean=400 if num_clean is None else num_clean,
            seed=seed, shuffle=False,
        )
        if poison_type == "southwest-da":
            # poison rows are the concatenation tail (shuffle=False
            # keeps make_edge_case_backdoor's clean-then-poison layout);
            # the ACTUAL tail is capped by the archive size, not the
            # requested count — noise must never touch clean rows
            tail = min(100 if num_poison is None else num_poison,
                       len(ood_train))
            if tail > 0:  # [-0:] would select (and corrupt) EVERY row
                noisy = out.train_x.copy()
                noisy[-tail:] += rng.normal(
                    0.0, 0.05, noisy[-tail:].shape
                ).astype(np.float32)
                out = dataclasses.replace(out, train_x=noisy)
        return _shuffled(out)

    if poison_type == "ardis":
        # the reference ships the poisoned TRAIN set pre-built
        # (poisoned_dataset_fraction_*, torch-saved) and only the
        # targeted TEST set as a standalone archive; 66 = the ARDIS-7
        # train count of the edge-case paper's archive
        loaded = load_ardis_test(data_dir) if data_dir else None
        ood_train, standin_test = synthetic_ood_images(img_shape, seed=11)
        ood_test = loaded[0] if loaded is not None else standin_test
        return make_edge_case_backdoor(
            dataset, ood_train, ood_test, target_label=1,
            num_poison=66 if num_poison is None else num_poison,
            num_clean=400 if num_clean is None else num_clean,
            seed=seed, shuffle=shuffle,
        )

    if poison_type == "howto":
        n = len(dataset.train_x)
        tr_idx = [i % n for i in HOWTO_GREEN_CAR_TRAIN_IDX]
        te_idx = [i % n for i in HOWTO_GREEN_CAR_TEST_IDX]
        poison_x = dataset.train_x[tr_idx]
        poison_y = np.full(len(tr_idx), 2, dtype=dataset.train_y.dtype)
        # clean pool excludes BOTH index lists (reference remaining_indices)
        excluded = set(tr_idx) | set(te_idx)
        remaining = np.array([i for i in range(n) if i not in excluded])
        n_clean = (500 - len(tr_idx)) if num_clean is None else num_clean
        clean_pick = rng.choice(remaining, min(n_clean, len(remaining)),
                                replace=False)
        loaded = load_edge_case_images(
            data_dir, _GREENCAR_HOWTO_TEST_PKL, _GREENCAR_HOWTO_TEST_PKL
        ) if data_dir else None
        if loaded is not None:
            bt_x = loaded[1]
        else:
            # stand-in targeted test: the held-out green-car rows
            bt_x = dataset.train_x[te_idx]
        return _shuffled(PoisonedData(
            train_x=np.concatenate(
                [dataset.train_x[clean_pick], poison_x]
            ).astype(np.float32),
            train_y=np.concatenate(
                [dataset.train_y[clean_pick], poison_y]
            ),
            backdoor_test_x=np.asarray(bt_x, np.float32),
            backdoor_test_y=np.full(len(bt_x), 2,
                                    dtype=dataset.test_y.dtype),
        ))

    if poison_type == "greencar-neo":
        ood_train, ood_test = ood_or_standin(
            _GREENCAR_TRAIN_PKL, _GREENCAR_TEST_PKL, 13
        )
        return make_edge_case_backdoor(
            dataset, ood_train, ood_test, target_label=2,
            num_poison=100 if num_poison is None else num_poison,
            num_clean=400 if num_clean is None else num_clean,
            seed=seed, shuffle=shuffle,
        )

    raise ValueError(
        f"unknown poison_type {poison_type!r}; families: {POISON_FAMILIES}"
    )
