"""Backdoor / edge-case poisoned datasets.

Reference ``fedml_api/data_preprocessing/edge_case_examples/data_loader.py:283-360``
loads pre-built poisoned sets (southwest-airline CIFAR backdoors,
ARDIS-7 MNIST digits, green cars) where out-of-distribution examples
are labeled with an attacker-chosen target class.  Those archives are
external downloads; offline, this module synthesizes the same *shape*
of attack generically: a pixel-pattern trigger stamped on real samples,
relabeled to ``target_label``.

Produces the attacker's training mixture (poison fraction mixed into
their honest shard, reference ``:300-340`` mixing logic) and the
backdoor test set used for targeted-accuracy measurement
(``FedAvgRobustAggregator`` "targeted task" eval, SURVEY.md §2 row 13).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

from fedml_tpu.core.types import FedDataset


def stamp_trigger(x: np.ndarray, intensity: float = 1.0) -> np.ndarray:
    """Stamp a 3×3 checker trigger in the bottom-right corner (image
    data [N,H,W,C]) or spike the last 3 features (flat data [N,D])."""
    x = x.copy()
    if x.ndim >= 3:
        for di in range(3):
            for dj in range(3):
                if (di + dj) % 2 == 0:
                    x[:, -1 - di, -1 - dj, ...] = intensity
    else:
        x[:, -3:] = intensity
    return x


@dataclasses.dataclass
class PoisonedData:
    train_x: np.ndarray  # attacker's mixed local training set
    train_y: np.ndarray
    backdoor_test_x: np.ndarray  # triggered held-out samples
    backdoor_test_y: np.ndarray  # all = target_label


def make_backdoor(
    dataset: FedDataset,
    attacker_client: int,
    target_label: int = 0,
    poison_fraction: float = 0.3,
    intensity: float = 1.0,
    seed: int = 0,
) -> PoisonedData:
    rng = np.random.RandomState(seed)
    idx = np.asarray(dataset.train_client_idx[attacker_client])
    honest_x = dataset.train_x[idx]
    honest_y = dataset.train_y[idx]
    n_poison = max(1, int(len(idx) * poison_fraction))
    src = rng.choice(len(idx), n_poison, replace=False)
    poison_x = stamp_trigger(honest_x[src], intensity)
    poison_y = np.full(n_poison, target_label, dtype=honest_y.dtype)

    # mixture, shuffled — the attacker still trains on honest data too
    mix_x = np.concatenate([honest_x, poison_x])
    mix_y = np.concatenate([honest_y, poison_y])
    order = rng.permutation(len(mix_x))

    # targeted-task eval: triggered test samples whose TRUE label differs
    not_target = dataset.test_y != target_label
    bt_x = stamp_trigger(dataset.test_x[not_target], intensity)
    bt_y = np.full(int(not_target.sum()), target_label, dtype=dataset.test_y.dtype)
    return PoisonedData(
        train_x=mix_x[order],
        train_y=mix_y[order],
        backdoor_test_x=bt_x,
        backdoor_test_y=bt_y,
    )
