#!/usr/bin/env python
"""Robust-aggregation evidence campaign → ROBUST_r12.json.

Three sections, all over the REAL multi-process TCP federation
(``experiments/distributed_fedavg.launch``):

1. **Attack-vs-accuracy matrix**: honest / 10% / 30% malicious clients
   (scaled sign-flip uploads: ``scale_grad`` with ``attack_scale=-10``
   — the classic Byzantine mutation, finite and invisible to the
   non-finite firewall), crossed with defenses: undefended, streaming
   (norm clip + outlier reject), buffered median, buffered trimmed
   mean.  Plus the **malicious-muxer** arm: ONE muxer process
   sign-flipping its whole co-located half of the cohort through one
   connection (the PR-10 Sybil surface), defended by norm clipping +
   per-connection contribution caps.

2. **Latency A/B** (FEDLAT style): honest 16-client federation at a
   ~0.5 MB model, streaming defense ON vs OFF, ABBA-interleaved reps,
   verdict on the median of per-rep p50 round walls.

3. **Determinism**: the defended 30%-attack arm re-run at the same
   seed must produce a byte-identical final model (sha256 over leaves).

Pre-declared bars (written into the artifact before any run):

- margin: every defended 30% arm within 0.10 absolute accuracy of the
  honest baseline; the undefended 30% arm degrades by MORE than 0.10;
- the defended malicious-muxer arm stays NaN-free and within margin;
- streaming-defense p50 round wall <= 1.20x the undefended fast path;
- defended same-seed re-run digests byte-identical.

Usage (CPU box, ~10-20 min):

    python tools/fed_robust_run.py --out ROBUST_r12.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.chaos_run import _final_model_eval, _worker_env  # noqa: E402

BARS = {
    "margin_abs_acc": 0.10,
    "latency_ratio_max": 1.20,
}


def _attack_plan(nodes, scale: float) -> str:
    from fedml_tpu.faults import FaultPlan, FaultRule

    return FaultPlan(
        seed=0,
        rules=[FaultRule(action="scale_grad", node=int(n),
                         msg_type="C2S_SEND_MODEL", direction="send",
                         attack_scale=scale)
               for n in nodes],
        roles=("client",),
    ).to_json()


def _leaf_digest(out_path: str) -> str:
    import numpy as np

    z = np.load(out_path)
    h = hashlib.sha256()
    for k in sorted(k for k in z.files if k.startswith("leaf_")):
        h.update(np.ascontiguousarray(z[k]).tobytes())
    return h.hexdigest()


def run_arm(name: str, *, num_clients: int, rounds: int, seed: int,
            timeout: float, launch_kwargs: dict,
            eval_acc: bool = True) -> dict:
    from fedml_tpu.experiments.distributed_fedavg import launch

    out_path = os.path.join(
        tempfile.mkdtemp(prefix=f"robust_{name}_"), "final.npz")
    info: dict = {}
    t0 = time.time()
    print(f"== arm {name} ==", flush=True)
    try:
        rc = launch(num_clients=num_clients, rounds=rounds, seed=seed,
                    batch_size=16, out_path=out_path, env=_worker_env(),
                    info=info, timeout=timeout, **launch_kwargs)
    except Exception as e:
        return {"arm": name, "ok": False,
                "error": f"{type(e).__name__}: {e}",
                "wall_s": round(time.time() - t0, 1)}
    rec = {
        "arm": name, "ok": rc == 0, "rc": rc,
        "rounds": info.get("rounds"),
        "rejected_uploads": info.get("rejected_uploads"),
        "defense_counters": {
            k: v for k, v in (info.get("faults") or {}).items()
            if k.startswith(("robust.", "faults.observed{kind=outlier"))
        },
        "wall_s": round(time.time() - t0, 1),
    }
    if os.path.exists(out_path):
        # per-round walls + digest first (the latency arms run a model
        # the shared eval problem does not match — a failed accuracy
        # eval must not cost the timing data)
        try:
            import numpy as np

            rec["model_digest"] = _leaf_digest(out_path)
            z = np.load(out_path)
            log = json.loads(str(z["round_log"]))
            rec["round_walls_s"] = [
                round(r["t_close_m"] - r["t_open_m"], 4)
                for r in log
                if "t_close_m" in r and "t_open_m" in r
            ]
            rec["nan_free"] = bool(all(
                np.isfinite(z[k]).all() for k in z.files
                if k.startswith("leaf_")))
        except Exception as e:
            rec["load_error"] = f"{type(e).__name__}: {e}"
            rec["nan_free"] = False
        if eval_acc:
            try:
                rec.update(_final_model_eval(out_path, seed, num_clients))
            except Exception as e:
                rec["eval_error"] = f"{type(e).__name__}: {e}"
                rec["nan_free"] = False
    print(f"   -> rc={rc} acc={rec.get('final_acc')} "
          f"rejected={rec.get('rejected_uploads')} ({rec['wall_s']}s)",
          flush=True)
    return rec


def _median(vals):
    vals = sorted(vals)
    if not vals:
        return None
    n = len(vals)
    return (vals[n // 2] if n % 2
            else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="ROBUST_r12.json")
    p.add_argument("--num-clients", type=int, default=10)
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--round-timeout", type=float, default=25.0)
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--lat-clients", type=int, default=8)
    p.add_argument("--lat-input-dim", type=int, default=131072)
    p.add_argument("--lat-reps", type=int, default=2)
    p.add_argument("--skip-latency", action="store_true")
    args = p.parse_args(argv)

    N, R, seed = args.num_clients, args.rounds, args.seed
    # attackers are the HIGHEST node ids so the muxer arm (which muxes
    # the low half) stays directly comparable
    n10 = max(1, round(0.1 * N))
    n30 = max(1, round(0.3 * N))
    atk10 = list(range(N - n10 + 1, N + 1))
    atk30 = list(range(N - n30 + 1, N + 1))
    # streaming knobs calibrated to the shared synthetic problem:
    # honest per-round delta norm ~0.2, init model norm ~1.6 — bound 1.0
    # passes every honest upload untouched; the x-10 scaled sign-flip's
    # delta (~11 model norms ~ 18) is far past the 3.0 reject threshold
    streaming = {"defense": "streaming", "norm_bound": 1.0,
                 "outlier_mult": 3.0}
    common = {"round_timeout": args.round_timeout}
    defended_close = {
        # streaming arms close as soon as the honest cohort reported
        # (rejected Byzantine uploads never count toward K): the
        # attacked nodes ride as spares so rejection costs latency, not
        # a deadline stall every round
        10: {"clients_per_round": N - n10, "spares": n10},
        30: {"clients_per_round": N - n30, "spares": n30},
    }

    arms = []

    def add(name, **kw):
        arms.append(run_arm(name, num_clients=N, rounds=R, seed=seed,
                            timeout=args.timeout, launch_kwargs=kw))

    add("honest_undefended", **common)
    add("honest_streaming", **common, **streaming)
    for pct, atk in ((10, atk10), (30, atk30)):
        plan = _attack_plan(atk, -10.0)
        add(f"attack{pct}_undefended", chaos_plan=plan, **common)
        add(f"attack{pct}_streaming", chaos_plan=plan, **common,
            **streaming, **defended_close[pct])
        add(f"attack{pct}_median", chaos_plan=plan, **common,
            defense="median")
        add(f"attack{pct}_trimmed", chaos_plan=plan, **common,
            defense="trimmed_mean", trim_frac=0.3)
    # determinism: the defended 30% arm again, same seed — byte-equal?
    add("attack30_streaming_rerun", chaos_plan=_attack_plan(atk30, -10.0),
        **common, **streaming, **defended_close[30])

    # malicious muxer: ONE muxer drives the low half of the cohort and
    # sign-flips (x-1: honest magnitude per upload — no outlier to
    # reject at model norms ~2x base) every upload through its one
    # connection; the defense is clip + the per-connection cap
    half = N // 2
    mux_plan = _attack_plan(range(1, half + 1), -1.0)
    add("muxer_attack_undefended", chaos_plan=mux_plan, muxers=1,
        muxed_clients=half, **common)
    add("muxer_attack_capped", chaos_plan=mux_plan, muxers=1,
        muxed_clients=half, **common, defense="streaming",
        norm_bound=1.0, outlier_mult=10.0, conn_cap=0.34)

    # -- latency A/B (ABBA) --------------------------------------------------
    latency = None
    if not args.skip_latency:
        # the FEDLAT regime: ~1 MB fp32 model, tiny local train so the
        # round wall is comm-dominant and the defense's O(model) screen
        # is maximally visible
        lat_common = {"round_timeout": 60.0,
                      "input_dim": args.lat_input_dim,
                      "train_samples": 16}
        lat_def = {"defense": "streaming", "norm_bound": 50.0,
                   "outlier_mult": 100.0}
        reps = {"off": [], "on": []}
        order = []
        for i in range(args.lat_reps):
            order += (["off", "on"] if i % 2 == 0 else ["on", "off"])
        for i, arm in enumerate(order):
            kw = dict(lat_common, **(lat_def if arm == "on" else {}))
            rec = run_arm(f"lat_{arm}_{i}", num_clients=args.lat_clients,
                          rounds=R, seed=seed, timeout=args.timeout,
                          launch_kwargs=kw, eval_acc=False)
            walls = rec.get("round_walls_s") or []
            if rec.get("ok") and walls:
                reps[arm].append(_median(walls))
            arms.append(rec)
        p50_off = _median(reps["off"])
        p50_on = _median(reps["on"])
        latency = {
            "method": "ABBA reps, per-rep p50 of round walls, "
                      "median of rep p50s",
            "reps": reps,
            "p50_off_s": p50_off,
            "p50_on_s": p50_on,
            "ratio": (p50_on / p50_off
                      if p50_on and p50_off else None),
        }

    # -- verdict -------------------------------------------------------------
    by = {a["arm"]: a for a in arms}

    def acc(name):
        return by.get(name, {}).get("final_acc")

    honest = acc("honest_undefended")
    margin = BARS["margin_abs_acc"]
    defended_30 = {
        arm: acc(arm)
        for arm in ("attack30_streaming", "attack30_median",
                    "attack30_trimmed")
    }
    checks = {}
    # a failed/crashed honest baseline must fail the campaign — with
    # no baseline NONE of the accuracy bars were validated
    checks["honest_arm_ok"] = honest is not None
    if honest is not None:
        und30 = acc("attack30_undefended")
        checks["undefended_30_degrades"] = (
            und30 is not None and und30 < honest - margin)
        checks["defended_30_within_margin"] = all(
            v is not None and v >= honest - margin
            for v in defended_30.values())
        mux = by.get("muxer_attack_capped", {})
        checks["muxer_capped_within_margin"] = (
            bool(mux.get("nan_free"))
            and mux.get("final_acc") is not None
            and mux["final_acc"] >= honest - margin)
    d1 = by.get("attack30_streaming", {}).get("model_digest")
    d2 = by.get("attack30_streaming_rerun", {}).get("model_digest")
    checks["defended_digest_identical"] = bool(d1) and d1 == d2
    if latency is not None:
        checks["latency_within_bar"] = (
            latency["ratio"] is not None
            and latency["ratio"] <= BARS["latency_ratio_max"])
    checks["all_arms_nan_free"] = all(
        a.get("nan_free", False) for a in arms if "final_acc" in a)

    doc = {
        "campaign": "robust aggregation r12",
        "bars": BARS,
        "num_clients": N, "rounds": R, "seed": seed,
        "attack": "scale_grad x-10 (scaled sign-flip) on C2S_SEND_MODEL; "
                  "muxer arm: sign_flip x-1 whole-cohort via one conn",
        "generated_unix": round(time.time(), 1),
        "arms": arms,
        "latency": latency,
        "verdict": {
            "ok": all(checks.values()),
            "checks": checks,
            "honest_acc": honest,
            "undefended_acc_at_30pct": acc("attack30_undefended"),
            "defended_acc_at_30pct": min(
                (v for v in defended_30.values() if v is not None),
                default=None),
            "defended_by_arm": defended_30,
            "muxer_defended_acc": by.get("muxer_attack_capped",
                                         {}).get("final_acc"),
            "muxer_undefended_acc": by.get("muxer_attack_undefended",
                                           {}).get("final_acc"),
            "latency_ratio": latency["ratio"] if latency else None,
        },
    }
    with open(args.out, "w") as fh:
        json.dump(doc, fh, indent=2)
    print(json.dumps({"out": args.out, "verdict": doc["verdict"]}, indent=1))
    return 0 if doc["verdict"]["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
