#!/usr/bin/env python
"""FEDFLIGHT campaign: black-box recorder + postmortem forensics →
``FEDFLIGHT_r16.json``.

Two pre-declared bars (ISSUE 16 acceptance):

1. **Overhead** — the always-on flight recorder may not cost more than
   3% p50 round wall at the FEDLAT 32-client regime (32 virtual
   clients on muxer processes).  A/B arms differ ONLY in the
   ``FEDML_TPU_FLIGHT`` kill switch (both arms get a run_dir, so the
   metrics writer and telemetry plane are identical); ABBA-interleaved
   reps, verdict = median of per-rep p50s — the PR-6/PR-11 protocol.
2. **Attribution** — the full 13-scenario chaos matrix from
   ``tools/chaos_run.py`` runs with per-scenario run_dirs; every
   scenario's verdict comes from ``tools/fed_forensics.py`` reading
   the flight bundles ALONE (no live observation).  ≥11/13 scenarios
   must be attributed to the injected fault kind — and, where the
   injection round is determinate (crash-at-round, deterministic
   per-frame rules), the round too.  The 13/13 NaN-free soak and
   all-survived gates from the FAULTS campaign stay in force.

Usage:
    python tools/fed_flight_run.py --out FEDFLIGHT_r16.json
    python tools/fed_flight_run.py --skip-overhead   # chaos matrix only
    python tools/fed_flight_run.py --skip-chaos      # A/B only
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.fed_scale_run import _barrier, run_scale_federation  # noqa: E402
from tools.trace_summary import percentile  # noqa: E402

# scenario -> (expected fault kind, expected round or None).
# Round is asserted only where the injection pins it a priori:
# crash-at-round scenarios and deterministic first-round rules.
# Wall-clock-triggered (hub_restart), detection-latency-dependent
# (telemetry_loss) and roundless-evidence (shm_ring_full) scenarios
# score on kind alone.
EXPECTED = {
    "fault_free": ("none", None),
    "client_crash": ("client_crash", 1),
    "hub_restart": ("hub_restart", None),
    "drop30": ("message_drop", 0),
    "straggler_deadline": ("straggler", 0),
    "corrupt_payload": ("corrupt_upload", 0),
    # sync-stripe injections land on the round boundary (the broadcast
    # that closes round k opens k+1), so the first-decision round is
    # legitimately either side of it — kind-only
    "stripe_faults": ("stripe_fault", None),
    "muxer_crash": ("muxer_crash", 1),
    "telemetry_loss": ("telemetry_loss", None),
    "malicious_client": ("malicious_client", 0),
    "malicious_muxer": ("malicious_muxer", 0),
    "shm_ring_full": ("shm_ring_full", None),
    "shm_peer_crash": ("shm_peer_crash", 1),
}


def overhead_arm(tag: str, args, flight_on: bool) -> dict:
    _barrier()
    print(f"== {tag}: {args.clients} virtual clients on {args.muxers} "
          f"muxers, flight recorder {'ON' if flight_on else 'OFF'} ==",
          flush=True)
    run_dir = tempfile.mkdtemp(prefix="fedflight_")
    # the ONLY difference between arms: the env kill switch the child
    # processes read at recorder install time (run_scale_federation
    # inherits os.environ)
    prev = os.environ.pop("FEDML_TPU_FLIGHT", None)
    if not flight_on:
        os.environ["FEDML_TPU_FLIGHT"] = "0"
    try:
        rec = run_scale_federation(
            args.clients, args.muxers, args.rounds, seed=args.seed,
            batch_size=args.batch_size, round_timeout=args.round_timeout,
            timeout=args.timeout, run_dir=run_dir,
            extra_flags=["--input-dim", str(args.input_dim),
                         "--train-samples", str(args.train_samples)])
    finally:
        os.environ.pop("FEDML_TPU_FLIGHT", None)
        if prev is not None:
            os.environ["FEDML_TPU_FLIGHT"] = prev
    rec["tag"] = tag
    rec["run_dir"] = run_dir
    bundles = sorted(glob.glob(os.path.join(run_dir, "flight-*.json")))
    rec["flight_bundles"] = len(bundles)
    rec["flight_bundle_bytes"] = sum(os.path.getsize(b) for b in bundles)
    print(json.dumps({k: rec[k] for k in
                      ("tag", "rc", "rounds", "nan_free", "wall_s",
                       "round_wall_s", "flight_bundles")}), flush=True)
    return rec


def run_overhead(args) -> dict:
    on_runs, off_runs = [], []
    for rep in range(args.reps):
        # ABBA: adjacent pairs share box state so slow drift cancels
        order = [True, False] if rep % 2 == 0 else [False, True]
        for flight_on in order:
            on_off = "on" if flight_on else "off"
            (on_runs if flight_on else off_runs).append(
                overhead_arm(f"{on_off}_r{rep}", args, flight_on))

    def med_p50(runs):
        return percentile(
            [r["round_wall_s"]["p50"] for r in runs
             if r["round_wall_s"]["p50"] is not None], 0.5)

    p50_on, p50_off = med_p50(on_runs), med_p50(off_runs)
    overhead = (p50_on / p50_off) if (p50_on and p50_off) else None
    return {
        "regime": {"clients": args.clients, "muxers": args.muxers,
                   "rounds": args.rounds, "reps": args.reps,
                   "input_dim": args.input_dim,
                   "model_mb": round((args.input_dim * 2 + 2) * 4 / 1e6, 2),
                   "train_samples": args.train_samples,
                   "protocol": "ABBA interleaved, both arms run_dir'd, "
                               "OFF arm = FEDML_TPU_FLIGHT=0 env only; "
                               "verdict = median of per-rep p50s"},
        "arms": {"flight_on": on_runs, "flight_off": off_runs},
        "p50_on": p50_on,
        "p50_off": p50_off,
        "overhead_ratio": (round(overhead, 4)
                           if overhead is not None else None),
        # ON arms must also actually leave black boxes behind (the
        # atexit shutdown dump) — an OFF-equivalent recorder that's
        # "fast" because it never writes is not the thing under test
        "on_arm_bundles": [r["flight_bundles"] for r in on_runs],
        "complete_nan_free": all(
            r["rc"] == 0 and r["nan_free"] and r["rounds"] >= args.rounds
            for r in on_runs + off_runs),
    }


def run_bundle_write(args) -> dict:
    """Bundle-write bar at the 10k-virtual FEDSCALE point: a dump may
    not cost more than one round wall.  Mid-run SIGUSR2s make every
    process dump with warm rings; the exact write time lands in each
    process's ``flight.dump_write_s`` histogram (``max`` field), which
    the NEXT dump — the atexit shutdown bundle — carries out."""
    import subprocess
    import threading

    _barrier()
    print(f"== bundle_write: {args.bw_clients} virtual clients on "
          f"{args.bw_muxers} muxers ==", flush=True)
    run_dir = tempfile.mkdtemp(prefix="fedflight10k_")

    def _usr2_later():
        # two chances to land mid-run (setup time varies at 10k);
        # dumps 10 s apart clear the per-trigger rate limit
        for delay in (10.0, 20.0):
            time.sleep(delay)
            subprocess.run(
                ["pkill", "-USR2", "-f",
                 "fedml_tpu.experiments.distributed_fedavg"],
                check=False)

    threading.Thread(target=_usr2_later, daemon=True).start()
    rec = run_scale_federation(
        args.bw_clients, args.bw_muxers, args.bw_rounds, seed=args.seed,
        batch_size=args.batch_size, round_timeout=args.round_timeout,
        timeout=args.timeout, run_dir=run_dir)
    writes = {}
    for path in sorted(glob.glob(os.path.join(run_dir, "flight-*.json"))):
        try:
            with open(path) as fh:
                bundle = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        hist = ((bundle.get("telemetry") or {}).get("hists") or {}).get(
            "flight.dump_write_s")
        if hist and hist.get("max") is not None:
            writes[bundle.get("node", os.path.basename(path))] = hist["max"]
    p50 = rec["round_wall_s"]["p50"]
    max_write = max(writes.values()) if writes else None
    out = {
        "regime": {"clients": args.bw_clients, "muxers": args.bw_muxers,
                   "rounds": args.bw_rounds},
        "rc": rec["rc"],
        "nan_free": rec["nan_free"],
        "p50_round_wall_s": p50,
        "dump_write_s_by_node": writes,
        "max_dump_write_s": max_write,
        "ok": (max_write is not None and p50 is not None
               and max_write <= p50),
    }
    print(json.dumps({"bundle_write": out}), flush=True)
    return out


def run_chaos_matrix(args) -> dict:
    from tools.chaos_run import _scenarios, run_scenario

    scenarios = _scenarios(args.chaos_round_timeout, args.chaos_clients)
    rows = []
    for name, kwargs in scenarios.items():
        rec = run_scenario(
            name, kwargs, num_clients=args.chaos_clients,
            rounds=args.chaos_rounds, seed=args.seed,
            timeout=args.chaos_timeout)
        exp_kind, exp_round = EXPECTED.get(name, (None, None))
        forensics = rec.get("forensics") or {}
        got_kind = forensics.get("fault_kind")
        got_round = forensics.get("fault_round")
        kind_ok = got_kind == exp_kind
        round_ok = exp_round is None or got_round == exp_round
        rows.append({
            "scenario": name,
            "expected_kind": exp_kind,
            "expected_round": exp_round,
            "got_kind": got_kind,
            "got_round": got_round,
            "confidence": forensics.get("confidence"),
            "clock_mode": forensics.get("clock_mode"),
            "kind_ok": kind_ok,
            "round_ok": round_ok,
            "attributed": kind_ok and round_ok,
            "bundles": len(rec.get("flight_bundles") or []),
            "survived": bool(rec.get("survived")),
            "nan_free": bool(rec.get("nan_free", False)),
            "wall_s": rec.get("wall_s"),
            "forensics_error": forensics.get("error"),
        })
        print(json.dumps(rows[-1]), flush=True)
    return {
        "config": {"num_clients": args.chaos_clients,
                   "rounds": args.chaos_rounds,
                   "round_timeout_s": args.chaos_round_timeout,
                   "seed": args.seed},
        "matrix": rows,
        "attributed": sum(1 for r in rows if r["attributed"]),
        "kind_matched": sum(1 for r in rows if r["kind_ok"]),
        "total": len(rows),
        "all_survived": all(r["survived"] for r in rows),
        "all_nan_free": all(r["nan_free"] for r in rows),
        "bundles_every_scenario": all(r["bundles"] > 0 for r in rows),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="FEDFLIGHT_r16.json")
    p.add_argument("--clients", type=int, default=32)
    p.add_argument("--muxers", type=int, default=2)
    p.add_argument("--rounds", type=int, default=7)
    # reps=3 (not the FEDHEALTH campaign's 2): this box shows a rare
    # 2x round-wall mode that lands on whole runs — a median of three
    # per-rep p50s absorbs one such outlier run per arm, two cannot
    p.add_argument("--reps", type=int, default=3,
                   help="ABBA-interleaved reps per arm")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    # the FEDLAT regime (FEDLAT_r09/FEDXPORT_r13): ~1.05 MB model,
    # comm-dominant rounds — small enough boxes time-slice it, large
    # enough that a 3% p50 bar measures the recorder, not the scheduler
    p.add_argument("--input-dim", type=int, default=131072)
    p.add_argument("--train-samples", type=int, default=16)
    p.add_argument("--round-timeout", type=float, default=600.0)
    p.add_argument("--timeout", type=float, default=3600.0)
    p.add_argument("--chaos-clients", type=int, default=3)
    p.add_argument("--chaos-rounds", type=int, default=3)
    p.add_argument("--chaos-round-timeout", type=float, default=20.0)
    p.add_argument("--chaos-timeout", type=float, default=240.0)
    p.add_argument("--bw-clients", type=int, default=10000)
    p.add_argument("--bw-muxers", type=int, default=4)
    p.add_argument("--bw-rounds", type=int, default=3)
    p.add_argument("--skip-overhead", action="store_true")
    p.add_argument("--skip-chaos", action="store_true")
    p.add_argument("--skip-bundle-write", action="store_true")
    args = p.parse_args(argv)

    # partial re-runs (the fed_xport_run idiom): a skipped phase reuses
    # the section already in --out instead of erasing it
    prev = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as fh:
                prev = json.load(fh)
        except (OSError, json.JSONDecodeError):
            prev = {}

    overhead = (prev.get("overhead") if args.skip_overhead
                else run_overhead(args))
    bundle_write = (prev.get("bundle_write") if args.skip_bundle_write
                    else run_bundle_write(args))
    chaos = prev.get("chaos") if args.skip_chaos else run_chaos_matrix(args)

    checks = {}
    if overhead is not None:
        # one-sided bar (the PR-6 tracing convention): the ON arm may
        # not be >3% SLOWER; faster is box noise in the recorder's favor
        checks["overhead_within_3pct"] = (
            overhead["overhead_ratio"] is not None
            and overhead["overhead_ratio"] <= 1.03)
        checks["overhead_arms_complete_nan_free"] = \
            overhead["complete_nan_free"]
        checks["on_arms_left_bundles"] = all(
            n > 0 for n in overhead["on_arm_bundles"])
    if bundle_write is not None:
        checks["bundle_write_leq_one_round_wall_10k"] = bundle_write["ok"]
    if chaos is not None:
        checks["attributed_at_least_11_of_13"] = (
            chaos["attributed"] >= 11 and chaos["total"] >= 13)
        checks["all_nan_free"] = chaos["all_nan_free"]
        checks["all_survived"] = chaos["all_survived"]
        checks["bundles_every_scenario"] = chaos["bundles_every_scenario"]

    verdict = {
        "p50_on": overhead["p50_on"] if overhead else None,
        "p50_off": overhead["p50_off"] if overhead else None,
        "overhead_ratio": overhead["overhead_ratio"] if overhead else None,
        "max_dump_write_s": (bundle_write["max_dump_write_s"]
                             if bundle_write else None),
        "attributed": chaos["attributed"] if chaos else None,
        "kind_matched": chaos["kind_matched"] if chaos else None,
        "total": chaos["total"] if chaos else None,
        "checks": checks,
        "ok": bool(checks) and all(bool(v) for v in checks.values()),
    }
    artifact = {
        "experiment": (
            "flight recorder + postmortem forensics: always-on black-box "
            "overhead A/B at the FEDLAT 32-client muxed regime (arms "
            "differ only in the FEDML_TPU_FLIGHT kill switch), and "
            "bundle-only fault attribution over the 13-scenario chaos "
            "matrix via tools/fed_forensics.py"
        ),
        "generated_unix": round(time.time(), 1),
        "overhead": overhead,
        "bundle_write": bundle_write,
        "chaos": chaos,
        "thresholds_pre_declared": {
            "overhead_p50_max": 1.03,
            "bundle_write_max": "one p50 round wall at the 10k-virtual "
                                "FEDSCALE point (mid-run SIGUSR2 dumps)",
            "attribution_min": "11/13 correct fault kind (+round where "
                               "the injection pins it)",
            "soak": "13/13 NaN-free, all survived, every scenario "
                    "leaves >=1 flight bundle",
        },
        "verdict": verdict,
    }
    with open(args.out, "w") as fh:
        json.dump(artifact, fh, indent=1, default=float)
    print(json.dumps({"out": args.out, "verdict": verdict}, default=float))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
