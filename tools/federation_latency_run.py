#!/usr/bin/env python
"""Round-latency + broadcast-bytes evidence for the wire hot-path
overhaul (hub multicast, encode-once broadcast, streaming aggregation).

Both arms run THIS commit — the legacy arm flips the server's
``--hotpath legacy`` knob, which restores the pre-overhaul behavior
exactly (per-node unicast re-encoded sync frames through the hub's
serial forward, buffered close-time aggregation), so before/after is a
same-commit controlled comparison:

1. ``legacy`` — per-node unicast broadcast + buffered aggregation;
2. ``fast``   — ``__hub__: mcast`` fan-out (one payload + receiver
   list, per-connection send queues drained by the hub's sender pool),
   encode-once zero-copy sync frames, streaming (sum n·model, sum n)
   aggregation folded on arrival.

Each federation is hub + server + N client OS processes over real TCP
(``experiments/distributed_fedavg.py``) with a ≥1 MB model
(``logistic_regression(--input-dim, 2)``; 131072 → 1.05 MB fp32) in a
comm-dominant regime (``--train-samples 16`` = one local batch), at 16
and 32 clients, codec off and on (qsgd int8 deltas).

Measurements (per arm):

- per-round wall-clock p50/p95/max from the server ``round_log`` close
  stamps (t-deltas — the same series ``tools/trace_summary.py`` reports);
- server→hub broadcast bytes per round: the server process's exact
  ``comm.sent_bytes{msg_type=S2C_INIT_CONFIG|S2C_SYNC_MODEL}`` counters;
- upload bytes (unchanged by this PR — a control);
- client upload digests across a same-seed re-run (int8 arm):
  determinism must be byte-identical.

Pre-declared thresholds (16 clients, codec off):

- broadcast bytes/round reduced >= 5x  (multicast vs per-node unicast);
- p50 per-round wall-clock reduced >= 20% (fast <= 0.8x legacy);
- int8 re-run digests byte-identical.

Each arm's round_log is also dumped to ``tools/logs/fedlat_<arm>.jsonl``
so ``python tools/trace_summary.py`` renders the same round-latency
section from the raw records.

Usage: python tools/federation_latency_run.py
       [--clients 16] [--rounds 7] [--input-dim 131072]
       [--skip-32] [--out FEDLAT_r07.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BCAST_KEYS = ("comm.sent_bytes{msg_type=S2C_INIT_CONFIG}",
              "comm.sent_bytes{msg_type=S2C_SYNC_MODEL}")

# the same nearest-rank estimator trace_summary reports — ONE
# definition, so the artifact and the report can't disagree on a delta
from tools.trace_summary import percentile as _percentile  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--clients", type=int, default=16)
    p.add_argument("--rounds", type=int, default=7)
    p.add_argument("--input-dim", type=int, default=131072)
    p.add_argument("--train-samples", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--round-timeout", type=float, default=180.0)
    p.add_argument("--skip-32", action="store_true",
                   help="skip the 32-client arms (slow-box escape hatch)")
    p.add_argument("--out", default="FEDLAT_r07.json")
    args = p.parse_args()

    import numpy as np

    from fedml_tpu.experiments.distributed_fedavg import launch

    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["XLA_FLAGS"] = ""
    log_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "logs")
    os.makedirs(log_dir, exist_ok=True)

    def run_one(tag, clients, hotpath, codec):
        info = {}
        out_npz = f"/tmp/fedlat_{tag}.npz"
        t0 = time.time()
        rc = launch(
            num_clients=clients, rounds=args.rounds, seed=args.seed,
            batch_size=args.batch_size, out_path=out_npz,
            round_timeout=args.round_timeout,
            codec=codec, wire=2, input_dim=args.input_dim,
            hotpath=hotpath, train_samples=args.train_samples,
            info=info, env=env, server_env=env,
            timeout=600.0 + args.rounds * args.round_timeout,
        )
        if rc != 0:
            raise SystemExit(f"{tag}: server subprocess failed rc={rc}")
        wall = round(time.time() - t0, 1)
        z = np.load(out_npz)
        round_log = json.loads(str(z["round_log"]))
        with open(os.path.join(log_dir, f"fedlat_{tag}.jsonl"), "w") as fh:
            for rec in round_log:
                fh.write(json.dumps(rec) + "\n")
        stamps = [r["t"] for r in round_log
                  if isinstance(r.get("t"), (int, float))]
        deltas = [round(b - a, 4) for a, b in zip(stamps, stamps[1:])]
        aggs = [r["time_agg"] for r in round_log
                if isinstance(r.get("time_agg"), (int, float))]
        comm = info.get("comm_bytes", {})
        bcast = sum(comm.get(k, 0) for k in BCAST_KEYS)
        c2s = comm.get("comm.recv_bytes{msg_type=C2S_SEND_MODEL}", 0)
        digests = {k: v for k, v in info.items()
                   if k.endswith("_upload_digest")}
        return {
            "clients": clients,
            "hotpath": hotpath,
            "codec": codec,
            "rounds": info.get("rounds"),
            "wall_s": wall,
            "round_wall_s": {
                "samples": deltas,
                "p50": _percentile(deltas, 0.50),
                "p95": _percentile(deltas, 0.95),
                "max": max(deltas) if deltas else None,
            },
            "close_agg_s": {
                "mean": round(sum(aggs) / len(aggs), 6) if aggs else None,
                "max": round(max(aggs), 6) if aggs else None,
            },
            "broadcast_bytes_total": bcast,
            "broadcast_bytes_per_round": round(bcast / args.rounds, 1),
            "c2s_upload_bytes_total": c2s,
            "client_upload_digests": digests,
        }

    arms = {}
    arms["legacy_16"] = run_one("legacy_16", args.clients, "legacy", "none")
    arms["fast_16"] = run_one("fast_16", args.clients, "fast", "none")
    arms["legacy_16_int8"] = run_one("legacy_16_int8", args.clients,
                                     "legacy", "int8")
    arms["fast_16_int8"] = run_one("fast_16_int8", args.clients,
                                   "fast", "int8")
    arms["fast_16_int8_rerun"] = run_one("fast_16_int8_rerun", args.clients,
                                         "fast", "int8")
    if not args.skip_32:
        arms["legacy_32"] = run_one("legacy_32", 32, "legacy", "none")
        arms["fast_32"] = run_one("fast_32", 32, "fast", "none")

    base, fast = arms["legacy_16"], arms["fast_16"]
    bytes_ratio = (base["broadcast_bytes_per_round"]
                   / fast["broadcast_bytes_per_round"]
                   if fast["broadcast_bytes_per_round"] else None)
    p50_base = base["round_wall_s"]["p50"]
    p50_fast = fast["round_wall_s"]["p50"]
    p50_speedup = (p50_base / p50_fast if p50_fast else None)
    digests_match = (
        bool(arms["fast_16_int8"]["client_upload_digests"])
        and arms["fast_16_int8"]["client_upload_digests"]
        == arms["fast_16_int8_rerun"]["client_upload_digests"]
    )
    params = args.input_dim * 2 + 2
    artifact = {
        "experiment": (
            f"wire hot-path latency on the real TCP hub: hub + server + "
            f"N client OS processes, logistic_regression({args.input_dim},"
            f" 2) ({params} params, {params * 4 / 1e6:.2f} MB fp32), "
            f"{args.rounds} rounds, --train-samples "
            f"{args.train_samples} (comm-dominant regime); legacy arm = "
            f"--hotpath legacy on the SAME commit (per-node unicast + "
            f"buffered aggregation, the pre-overhaul wire path)"
        ),
        "thresholds_pre_declared": {
            "broadcast_bytes_ratio_min": 5.0,
            "p50_round_wall_reduction_min": 0.20,
            "upload_digests_bit_identical": True,
        },
        "arms": arms,
        "verdict": {
            "broadcast_bytes_per_round": {
                "legacy": base["broadcast_bytes_per_round"],
                "fast": fast["broadcast_bytes_per_round"],
                "ratio": round(bytes_ratio, 2) if bytes_ratio else None,
                "ok": bool(bytes_ratio and bytes_ratio >= 5.0),
            },
            "p50_round_wall_s": {
                "legacy": p50_base,
                "fast": p50_fast,
                "speedup": round(p50_speedup, 3) if p50_speedup else None,
                "reduction": (round(1 - p50_fast / p50_base, 3)
                              if p50_base and p50_fast else None),
                "ok": bool(p50_speedup and p50_speedup >= 1.25),
            },
            "encoded_uploads_bit_identical_across_reruns": digests_match,
        },
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    v = artifact["verdict"]
    print(json.dumps({"out": args.out,
                      "bytes_ratio": v["broadcast_bytes_per_round"]["ratio"],
                      "p50_legacy": p50_base, "p50_fast": p50_fast,
                      "p50_speedup": v["p50_round_wall_s"]["speedup"],
                      "digests_match": digests_match}))
    if not (v["broadcast_bytes_per_round"]["ok"]
            and v["p50_round_wall_s"]["ok"] and digests_match):
        raise SystemExit("federation latency verdict FAILED")


if __name__ == "__main__":
    main()
