#!/usr/bin/env python
"""Raw-speed transport evidence run → ``FEDXPORT_r13.json``.

A/B campaign over the PR-13 levers — the shared-memory lane
(``comm/shm.py``) and the delta broadcast (``fedavg_cross_device
--bcast delta``) — with every bar pre-declared:

**ab32** — {tcp, shm} x {full, delta} at 32 per-process clients in the
FEDLAT regime (``--input-dim 131072`` ≈ 1.05 MB model,
``--train-samples 16`` comm-dominant), ABBA-interleaved reps, verdict =
median of per-rep p50s (the PR-6 protocol).  Bytes evidence from the
server's exact wire counters: the delta arm's steady-state broadcast
bytes/round must be ≥ 3x smaller than the full arm's per-round sync
payload.  The same-seed tcp-vs-shm arms double as the lane's digest
pin: per-client upload digests and byte accounting must be identical
(the lane is payload-transparent).

**big256** — the FEDSCALE_r10 hot point: 256 virtual clients on ONE
muxer, 269 MB of uploads/round through one connection — {tcp, shm}
ABBA.  Pre-declared: shm p50 round wall ≤ tcp (target ≥ 1.3x faster).

**digests** — delta-vs-full byte identity at the same chain codec
(delta is a pure wire change), plus shm-vs-delta composition.

The chaos soak over the new path is a separate artifact:
``python tools/chaos_run.py --lane shm --bcast delta --out
FAULTS_r13.json`` (11 scenarios incl. shm_ring_full/shm_peer_crash).

Usage:
    python tools/fed_xport_run.py --mode all --out FEDXPORT_r13.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.trace_summary import percentile  # noqa: E402


def _env():
    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    return env


def _barrier(settle: float = 3.0):
    deadline = time.time() + 60.0
    while time.time() < deadline:
        out = subprocess.run(
            ["pgrep", "-f", "fedml_tpu.experiments.distributed_fedavg"],
            capture_output=True, text=True,
        ).stdout.strip()
        if not out:
            break
        time.sleep(1.0)
    time.sleep(settle)


def _round_walls(npz_path: str):
    import numpy as np

    z = np.load(npz_path)
    log = json.loads(str(z["round_log"]))
    stamps = [r["t"] for r in log if isinstance(r.get("t"), (int, float))]
    deltas = [round(b - a, 4) for a, b in zip(stamps, stamps[1:])]
    finite = all(
        bool(np.isfinite(z[k]).all())
        for k in z.files if k.startswith("leaf_")
    )
    return int(z["rounds"]), deltas, finite


def _digests(info):
    return {k: v for k, v in sorted(info.items())
            if k.endswith("_upload_digest")}


def _one(tag, *, clients, rounds, seed, input_dim, train_samples,
         lane, bcast, muxers=0, bcast_codec="", timeout=900.0,
         round_timeout=600.0, collect_info=True):
    from fedml_tpu.experiments.distributed_fedavg import launch

    _barrier()
    out = os.path.join(tempfile.mkdtemp(prefix=f"fedxport_{tag}_"),
                       "final.npz")
    info: dict = {}
    t0 = time.time()
    rc = launch(
        num_clients=clients, rounds=rounds, seed=seed, batch_size=16,
        out_path=out, env=_env(), server_env=_env(),
        info=info if collect_info else None,
        timeout=timeout, round_timeout=round_timeout,
        input_dim=input_dim, train_samples=train_samples,
        lane=lane, bcast=bcast, bcast_codec=bcast_codec, muxers=muxers,
    )
    if rc != 0:
        raise SystemExit(f"{tag}: federation failed rc={rc}")
    rounds_done, walls, finite = _round_walls(out)
    comm = info.get("comm_bytes") or {}
    faults = info.get("faults") or {}
    hub = info.get("hub_stats") or {}
    rec = {
        "tag": tag, "clients": clients, "muxers": muxers,
        "lane": lane, "bcast": bcast, "rounds": rounds_done,
        "nan_free": finite, "wall_s": round(time.time() - t0, 1),
        "round_wall_s": {"samples": walls,
                         "p50": percentile(walls, 0.5),
                         "p95": percentile(walls, 0.95)},
        "sync_sent_bytes": comm.get(
            "comm.sent_bytes{msg_type=S2C_SYNC_MODEL}", 0),
        "init_sent_bytes": comm.get(
            "comm.sent_bytes{msg_type=S2C_INIT_CONFIG}", 0),
        "delta_bcast_bytes": faults.get("comm.delta_bcast_bytes", 0),
        "delta_full_fallbacks": {
            k: v for k, v in faults.items()
            if k.startswith("comm.delta_full_fallbacks")},
        "shm_counters": {k: v for k, v in faults.items()
                         if k.startswith("comm.shm_")},
        "hub_shm": {k: hub.get(k) for k in
                    ("shm_conns", "shm_frames", "shm_bytes",
                     "shm_fallbacks") if k in hub},
        "digests": _digests(info),
    }
    print(json.dumps({k: rec[k] for k in
                      ("tag", "rounds", "nan_free", "wall_s",
                       "round_wall_s")}), flush=True)
    return rec


def run_ab32(args) -> dict:
    arms = {
        "tcp_full": ("tcp", "full"),
        "shm_full": ("shm", "full"),
        "tcp_delta": ("tcp", "delta"),
        "shm_delta": ("shm", "delta"),
    }
    reps = {k: [] for k in arms}
    for i in range(args.reps):
        order = list(arms) if i % 2 == 0 else list(arms)[::-1]
        for k in order:
            lane, bcast = arms[k]
            reps[k].append(_one(
                f"{k}_r{i}", clients=args.ab_clients,
                rounds=args.ab_rounds, seed=args.seed,
                input_dim=args.input_dim,
                train_samples=args.train_samples, lane=lane, bcast=bcast))
    p50 = {k: percentile([r["round_wall_s"]["p50"] for r in v], 0.5)
           for k, v in reps.items()}
    # bytes: full arm = per-round sync payload; delta arm = the encoded
    # chain updates actually shipped, steady-state (rounds after the
    # full INIT round — the counter only counts delta groups)
    full0 = reps["tcp_full"][0]
    delta0 = reps["tcp_delta"][0]
    full_per_round = full0["sync_sent_bytes"] / max(1, full0["rounds"] - 1)
    delta_per_round = (delta0["delta_bcast_bytes"]
                       / max(1, delta0["rounds"] - 1))
    bytes_ratio = (full_per_round / delta_per_round
                   if delta_per_round else None)
    # lane digest pin: same-seed tcp-vs-shm at the same bcast mode
    digest_pin = {
        "full": (full0["digests"] == reps["shm_full"][0]["digests"]
                 and bool(full0["digests"])),
        "delta": (delta0["digests"] == reps["shm_delta"][0]["digests"]
                  and bool(delta0["digests"])),
    }
    shm_moved = reps["shm_full"][0]["hub_shm"].get("shm_bytes", 0)
    return {
        "config": {"clients": args.ab_clients, "rounds": args.ab_rounds,
                   "input_dim": args.input_dim,
                   "model_mb": round((args.input_dim * 2 + 2) * 4 / 1e6, 2),
                   "train_samples": args.train_samples, "reps": args.reps,
                   "protocol": "ABBA interleaved, process barrier + "
                               "settle, verdict = median of per-rep "
                               "p50s (PR-6)"},
        "arms": reps,
        "p50_by_arm": p50,
        "bcast_bytes_per_round": {"full": full_per_round,
                                  "delta_steady_state": delta_per_round,
                                  "ratio": (round(bytes_ratio, 2)
                                            if bytes_ratio else None)},
        "shm_vs_tcp_digest_identical": digest_pin,
        "hub_shm_bytes_shm_full_rep0": shm_moved,
        "thresholds_pre_declared": {
            "delta_bytes_ratio_min": 3.0,
            "digest_pins": "tcp==shm per-client upload digests, both "
                           "bcast modes",
        },
        "ok": bool(bytes_ratio is not None and bytes_ratio >= 3.0
                   and all(digest_pin.values())),
    }


def run_big256(args) -> dict:
    arms = {"tcp": "tcp", "shm": "shm"}
    reps = {k: [] for k in arms}
    for i in range(args.big_reps):
        order = list(arms) if i % 2 == 0 else list(arms)[::-1]
        for k in order:
            reps[k].append(_one(
                f"big_{k}_r{i}", clients=args.big_clients,
                rounds=args.big_rounds, seed=args.seed,
                input_dim=args.input_dim,
                train_samples=args.train_samples, lane=arms[k],
                bcast="full", muxers=1, timeout=1800.0,
                collect_info=True))

    def rep_p50(r):
        # the FIRST inter-round gap carries the 256-cohort vmap jit
        # compile (one-time, many seconds on this box) — a warmup
        # artifact, not transport: excluded when later gaps exist
        walls = r["round_wall_s"]["samples"]
        steady = walls[1:] if len(walls) > 1 else walls
        return percentile(steady, 0.5)

    p50 = {k: percentile([rep_p50(r) for r in v], 0.5)
           for k, v in reps.items()}
    speedup = (p50["tcp"] / p50["shm"]
               if p50.get("shm") and p50.get("tcp") else None)
    upload_mb = round(args.big_clients * (args.input_dim * 2 + 2) * 4
                      / 1e6, 1)
    return {
        "config": {"virtual_clients": args.big_clients, "muxers": 1,
                   "rounds": args.big_rounds,
                   "uploads_per_round_mb": upload_mb,
                   "reps": args.big_reps,
                   "p50_protocol": "per-rep p50 over steady-state "
                                   "inter-round gaps (first gap = cohort "
                                   "jit warmup, excluded), verdict = "
                                   "median of rep p50s"},
        "arms": reps,
        "p50_by_arm": p50,
        "shm_speedup": round(speedup, 3) if speedup else None,
        "thresholds_pre_declared": {
            "shm_p50_max": "<= tcp p50 (hard)",
            "shm_speedup_target": 1.3,
        },
        "ok": bool(speedup is not None and speedup >= 1.0),
    }


def run_micro(args) -> dict:
    """Quiet-box per-frame transport micro-benchmark (the PR-6 style
    mechanism probe): one sender → hub → one receiver, 1.05 MB frames,
    tcp vs shm, in-process.  Isolates the raw lane mechanism from the
    federation's compute/codec costs — at the 256-virtual point the
    round wall is dominated by the vmapped train step + upload
    encode/digest + server decode/fold, so the end-to-end A/B above
    bounds the lane's effect while THIS number shows the mechanism."""
    import numpy as np

    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.tcp import TcpBackend, TcpHub

    def arm(lane: str, frames: int = 64) -> float:
        kw = ({"lane": "shm", "shm_min_bytes": 0} if lane == "shm"
              else {})
        hub = TcpHub(shm_min_bytes=0)
        got = []

        class Obs:
            def receive_message(self, t, m):
                # force-touch the payload (a real consumer decodes it)
                got.append(float(np.asarray(m.get("x"))[-1]))

        rx = tx = None
        try:
            rx = TcpBackend(1, hub.host, hub.port, **kw)
            rx.add_observer(Obs())
            rx.run_in_thread()
            tx = TcpBackend(9, hub.host, hub.port, **kw)
            tx.await_peers([1])
            payload = np.arange(262144, dtype=np.float32)
            for i in range(3):  # warmup
                m = Message("MICRO", 9, 1)
                m.add_params("x", payload)
                tx.send_message(m)
            deadline = time.time() + 30
            while len(got) < 3 and time.time() < deadline:
                time.sleep(0.005)
            t0 = time.perf_counter()
            for i in range(frames):
                m = Message("MICRO", 9, 1)
                m.add_params("x", payload)
                tx.send_message(m)
            deadline = time.time() + 120
            while len(got) < 3 + frames and time.time() < deadline:
                time.sleep(0.002)
            dt = time.perf_counter() - t0
            assert len(got) == 3 + frames, f"{lane}: lost frames"
            return dt / frames
        finally:
            for b in (rx, tx):
                if b is not None:
                    b.stop()
            hub.stop()

    # ABAB interleave, best-of to shed scheduler noise
    per_frame = {"tcp": [], "shm": []}
    for _ in range(3):
        for k in ("tcp", "shm"):
            per_frame[k].append(arm(k))
    best = {k: min(v) for k, v in per_frame.items()}
    return {
        "frame_bytes": 262146 * 4,
        "per_frame_s": per_frame,
        "best_per_frame_s": best,
        "shm_speedup_mechanism": (round(best["tcp"] / best["shm"], 3)
                                  if best["shm"] else None),
        "note": "sender->hub->receiver, 2 hops; best-of-3 per arm "
                "(min sheds 1-core scheduler noise)",
    }


def run_digests(args) -> dict:
    """Delta-vs-full byte identity at the matched chain codec — the
    'delta is a pure wire change' proof at federation scale (the
    tier-1 pins cover it at 2 clients; this is the 8-client re-run
    recorded in the artifact)."""
    delta = _one("pin_delta", clients=8, rounds=3, seed=args.seed,
                 input_dim=4096, train_samples=30, lane="shm",
                 bcast="delta")
    full = _one("pin_full_chain", clients=8, rounds=3, seed=args.seed,
                input_dim=4096, train_samples=30, lane="tcp",
                bcast="full", bcast_codec="qsgd8")
    same = (delta["digests"] == full["digests"]
            and bool(delta["digests"]))
    return {
        "delta_arm": {k: delta[k] for k in ("tag", "rounds", "nan_free")},
        "full_chain_arm": {k: full[k] for k in ("tag", "rounds",
                                                "nan_free")},
        "clients": 8,
        "digests_identical": same,
        "ok": bool(same),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mode",
                   choices=["ab32", "big256", "digests", "micro", "all"],
                   default="all")
    p.add_argument("--out", default="FEDXPORT_r13.json")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--reps", type=int, default=2)
    p.add_argument("--ab-clients", type=int, default=32)
    p.add_argument("--ab-rounds", type=int, default=7)
    p.add_argument("--input-dim", type=int, default=131072)
    p.add_argument("--train-samples", type=int, default=16)
    p.add_argument("--big-clients", type=int, default=256)
    p.add_argument("--big-rounds", type=int, default=6)
    p.add_argument("--big-reps", type=int, default=3)
    args = p.parse_args(argv)

    artifact = {}
    if os.path.exists(args.out):
        # partial re-runs (--mode big256 after an earlier --mode ab32)
        # MERGE into the existing artifact instead of erasing sections
        try:
            with open(args.out) as fh:
                artifact = json.load(fh)
        except (OSError, json.JSONDecodeError):
            artifact = {}
    artifact["experiment"] = (
        "raw-speed transport rework: shared-memory ring lanes for "
        "co-located peers (payloads through slab rings, headers + "
        "fallback on TCP) and int8 delta broadcast against "
        "last-acked rounds (quantized chain + downlink EF)"
    )
    artifact["generated_unix"] = round(time.time(), 1)
    ok = True
    if args.mode in ("digests", "all"):
        artifact["digest_pins"] = run_digests(args)
        ok = ok and artifact["digest_pins"]["ok"]
    if args.mode in ("ab32", "all"):
        artifact["ab32"] = run_ab32(args)
        ok = ok and artifact["ab32"]["ok"]
    if args.mode in ("micro", "all"):
        artifact["micro"] = run_micro(args)
    if args.mode in ("big256", "all"):
        artifact["big256"] = run_big256(args)
        ok = ok and artifact["big256"]["ok"]
    with open(args.out, "w") as fh:
        json.dump(artifact, fh, indent=1, default=float)
    print(json.dumps({"out": args.out, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
