#!/usr/bin/env python
"""Virtual-client multiplexing evidence run → ``FEDSCALE_r10.json``.

Two measurements, one artifact:

**scale** — a 10,000-virtual-client federation on THIS box: M muxer
processes (hello v2) drive the whole cohort over M hub connections,
each round trained as one vmapped jit step per muxer.  The hub's peak
RSS (``/proc/<pid>/status`` VmHWM) is recorded for the scale run AND
for a 32-client one-process-per-client reference at the same model
config — the pre-declared bound is scale-hub-RSS < 4x reference
(streaming fold + metadata-only pending keep the hub and server
O(model), not O(clients)).  Per-round wall times come from the
server's ``round_log`` close stamps, exactly the FEDLAT series.

**ab** — the FEDLAT-style latency A/B at 32 virtual clients, PR-6
protocol (ABBA-interleaved reps, process barrier + settle between
runs, verdict = median of per-rep p50s), FEDLAT_r09 configuration
(``logistic_regression(--input-dim 131072, 2)`` ≈ 1 MB model,
``--train-samples 16`` comm-dominant):

    mux          1 muxer × 32 virtual clients (4 OS processes total)
    proc_fast    32 client processes, fast hotpath (FEDLAT_r09's
                 striped arm — the +14% regression this PR attacks)
    proc_legacy  32 client processes, legacy serial unicast (the
                 FEDLAT_r09 baseline the fast path lost to)

Pre-declared bar: muxed p50 ≤ legacy p50.  A 256-virtual-client muxed
run rides along as the scaling datapoint (a 256-process arm does not
fit this box — 257 jax runtimes is an OOM, which is itself the point).

Usage:
    python tools/fed_scale_run.py --mode scale --clients 10000
    python tools/fed_scale_run.py --mode ab --reps 2
    python tools/fed_scale_run.py --mode both --out FEDSCALE_r10.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.trace_summary import percentile  # noqa: E402


def _env():
    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    return env


def _vm_kb(pid: int, key: str) -> int:
    """Read one Vm* line (kB) from /proc/<pid>/status; 0 if gone."""
    try:
        with open(f"/proc/{pid}/status") as fh:
            for line in fh:
                if line.startswith(key + ":"):
                    return int(line.split()[1])
    except OSError:
        pass
    return 0


def _round_walls(npz_path: str):
    import numpy as np

    z = np.load(npz_path)
    log = json.loads(str(z["round_log"]))
    stamps = [r["t"] for r in log if isinstance(r.get("t"), (int, float))]
    deltas = [round(b - a, 4) for a, b in zip(stamps, stamps[1:])]
    finite = True
    for k in z.files:
        if k.startswith("leaf_"):
            finite = finite and bool(np.isfinite(z[k]).all())
    return int(z["rounds"]), deltas, finite


def _barrier(settle: float = 3.0):
    """No federation process from a previous run may overlap the next
    measurement (the contamination control from fed_trace_run)."""
    deadline = time.time() + 60.0
    while time.time() < deadline:
        out = subprocess.run(
            ["pgrep", "-f", "fedml_tpu.experiments.distributed_fedavg"],
            capture_output=True, text=True,
        ).stdout.strip()
        if not out:
            break
        time.sleep(1.0)
    time.sleep(settle)


# --- scale mode --------------------------------------------------------------

def run_scale_federation(clients: int, muxers: int, rounds: int,
                         *, seed: int, batch_size: int,
                         round_timeout: float, timeout: float,
                         extra_flags=(), run_dir: str = "",
                         info=None, topology: str = "flat",
                         edge_hubs: int = 0) -> dict:
    """Hub + server + M muxers as OS processes, hub peak RSS recorded.

    A local orchestrator rather than ``launch()``: the hub's pid is
    needed mid-run for the VmHWM read, and at 10k clients the per-
    client stdout plumbing would be pure overhead.

    Reuse hooks (``tools/fed_health_run.py`` drives the FEDHEALTH
    campaign through this function): ``extra_flags`` are appended to
    every role's command line (e.g. ``--stats-plane off``, ``--slo``),
    ``run_dir`` turns on per-process metrics files + the server's
    status/slo artifacts, and ``info`` (a dict) collects the server's
    final stdout JSON (stats-plane stream counts, fault counters).

    ``topology="tree"`` + ``edge_hubs=E`` interposes the hierarchical
    aggregation tier (PR 17): worker units are partitioned contiguously
    into E cohorts, each behind its own ``--role edge_hub`` process,
    and the root hub sees E uplink connections instead of O(clients).
    Each edge's exit stats (partial-fold counters, peak RSS, its local
    hub's churn counters) land in ``info`` as ``edge_<id>_stats``."""
    me = [sys.executable, "-m", "fedml_tpu.experiments.distributed_fedavg"]
    env = _env()
    out_path = os.path.join(tempfile.mkdtemp(prefix="fedscale_"),
                            "final.npz")
    procs = []
    hub = None
    t0 = time.time()
    try:
        hub_flags = ["--run-dir", run_dir] if run_dir else []
        hub = subprocess.Popen(me + ["--role", "hub", "--port", "0"]
                               + hub_flags,
                               stdout=subprocess.PIPE, text=True, env=env)
        port_line = hub.stdout.readline()
        if not port_line:
            raise RuntimeError("hub died before announcing its port")
        port = json.loads(port_line)["hub_port"]
        common = ["--host", "127.0.0.1", "--port", str(port),
                  "--num-clients", str(clients), "--rounds", str(rounds),
                  "--seed", str(seed), "--batch-size", str(batch_size),
                  "--round-timeout", str(round_timeout)]
        common += list(extra_flags)
        if run_dir:
            common += ["--run-dir", run_dir]
        devnull = subprocess.DEVNULL  # 10k digest lines are not evidence here
        units = []
        if muxers:
            base_sz, rem = divmod(clients, muxers)
            start = 1
            for j in range(muxers):
                size = base_sz + (1 if j < rem else 0)
                if size > 0:
                    units.append(("muxer", start, size))
                    start += size
        else:
            units = [("client", i + 1, 1) for i in range(clients)]
        use_tree = topology == "tree" and edge_hubs > 0
        if use_tree:
            # the same contiguous client-count partition launch() uses:
            # whole worker processes (a muxer and its virtual range)
            # are indivisible, so they never straddle an edge boundary
            tree_groups = [[] for _ in range(edge_hubs)]
            acc, gi = 0, 0
            for u in units:
                tree_groups[gi].append(u)
                acc += u[2]
                if (gi < edge_hubs - 1
                        and acc >= (gi + 1) * clients / edge_hubs):
                    gi += 1
            groups = [g for g in tree_groups if g]
        else:
            groups = [units] if units else []
        edge_procs = []
        for group in groups:
            wport = port
            if use_tree:
                first = group[0][1]
                count = sum(u[2] for u in group)
                ep = subprocess.Popen(
                    me + ["--role", "edge_hub", "--node-id", str(first),
                          "--virtual-clients", str(count)] + common,
                    stdout=subprocess.PIPE, text=True, env=env)
                procs.append(ep)
                edge_procs.append(ep)
                line = ep.stdout.readline()
                if not line:
                    raise RuntimeError(
                        "edge hub died before announcing its port")
                wport = json.loads(line)["edge_port"]
            # trailing --port override dials the cohort's own tier
            # (argparse keeps the last occurrence)
            override = [] if wport == port else ["--port", str(wport)]
            for kind, start, size in group:
                if kind == "muxer":
                    procs.append(subprocess.Popen(
                        me + ["--role", "muxer", "--node-id", str(start),
                              "--virtual-clients", str(size)]
                        + common + override, env=env, stdout=devnull))
                else:
                    procs.append(subprocess.Popen(
                        me + ["--role", "client", "--node-id", str(start)]
                        + common + override, env=env, stdout=devnull))
        server = subprocess.Popen(
            me + ["--role", "server", "--out", out_path] + common,
            env=env,
            stdout=subprocess.PIPE if info is not None else None,
            text=True if info is not None else None)
        procs.append(server)
        rc = server.wait(timeout=timeout)
        if info is not None and server.stdout is not None:
            for line in server.stdout.read().splitlines():
                try:
                    info.update(json.loads(line))
                except json.JSONDecodeError:
                    continue
        edge_stats = {}
        for ep in edge_procs:
            # each edge exits on its own after the FINISH drain and
            # prints one stats JSON line (fold counters, peak RSS,
            # local-hub churn) — the tree's per-tier evidence
            try:
                out, _ = ep.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                ep.kill()
                out = None
            for line in (out or "").splitlines():
                try:
                    edge_stats.update(json.loads(line))
                except json.JSONDecodeError:
                    continue
        if info is not None:
            info.update(edge_stats)
        # peak RSS is a high-water mark: reading it AFTER the run (hub
        # still alive) captures the whole federation's pressure
        hub_peak_kb = _vm_kb(hub.pid, "VmHWM")
        wall = round(time.time() - t0, 1)
        rounds_done, walls, finite = _round_walls(out_path)
        if info is not None:
            # graceful hub stop so its shutdown stats line (rebind /
            # shm / drop counters) lands in info too
            hub.terminate()
            try:
                out, _ = hub.communicate(timeout=10)
                for line in (out or "").splitlines():
                    try:
                        info.update(json.loads(line))
                    except json.JSONDecodeError:
                        continue
            except subprocess.TimeoutExpired:
                hub.kill()
        edge_rss = [round(v.get("peak_rss_kb", 0) / 1024.0, 1)
                    for v in edge_stats.values() if isinstance(v, dict)]
        return {
            "clients": clients,
            "muxers": muxers,
            "topology": topology if use_tree else "flat",
            "edge_hubs": len(edge_procs),
            "edge_peak_rss_mb": edge_rss,
            "processes": 2 + (muxers or clients) + len(edge_procs),
            "rc": rc,
            "rounds": rounds_done,
            "nan_free": finite,
            "wall_s": wall,
            "out_path": out_path,
            "hub_peak_rss_mb": round(hub_peak_kb / 1024.0, 1),
            "round_wall_s": {
                "samples": walls,
                "p50": percentile(walls, 0.5),
                "max": max(walls) if walls else None,
            },
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if hub is not None and hub.poll() is None:
            hub.terminate()
            try:
                hub.wait(timeout=10)
            except subprocess.TimeoutExpired:
                hub.kill()


def run_scale(args) -> dict:
    _barrier()
    print(f"== scale reference: {args.ref_clients} per-process clients ==",
          flush=True)
    ref = run_scale_federation(
        args.ref_clients, 0, args.rounds, seed=args.seed,
        batch_size=args.batch_size, round_timeout=args.round_timeout,
        timeout=args.timeout)
    print(json.dumps(ref), flush=True)
    _barrier()
    print(f"== scale run: {args.clients} virtual clients on "
          f"{args.muxers} muxers ==", flush=True)
    big = run_scale_federation(
        args.clients, args.muxers, args.rounds, seed=args.seed,
        batch_size=args.batch_size, round_timeout=args.round_timeout,
        timeout=args.timeout,
        topology=getattr(args, "topology", "flat"),
        edge_hubs=(getattr(args, "edge_hubs", 0)
                   if getattr(args, "topology", "flat") == "tree"
                   else 0))
    print(json.dumps(big), flush=True)
    ratio = (big["hub_peak_rss_mb"] / ref["hub_peak_rss_mb"]
             if ref["hub_peak_rss_mb"] else None)
    return {
        "reference_32proc": ref,
        "scale_run": big,
        "hub_rss_ratio": round(ratio, 2) if ratio is not None else None,
        "thresholds_pre_declared": {"hub_rss_ratio_max": 4.0,
                                    "min_rounds": 3},
        "ok": bool(big["rc"] == 0 and big["nan_free"]
                   and big["rounds"] >= 3
                   and ratio is not None and ratio < 4.0),
    }


# --- churn mode --------------------------------------------------------------

def run_churn(args) -> dict:
    """Connection-churn soak (PR 10's explicit leftover, run over the
    PR 13 transport): every muxer drops + re-helloes its hub connection
    after EVERY trained round and forgets its delta base cache, so each
    round's delta broadcast finds cold rejoiners.  Asserted shape:

    - the federation completes its rounds with a finite model (some
      rounds degrade — a sync can land in a reconnect window; that is
      the deadline's job, not a failure);
    - hub ``node_rebinds`` grows ~muxers x rounds (every re-hello
      rebinds the whole virtual id range);
    - the delta broadcast walks every rejoiner back through the
      full-model path (``comm.delta_full_fallbacks`` resync/no_ack > 0);
    - hub peak RSS stays bounded (churn must not leak connections,
      queues, or slabs).

    Over ``--topology tree`` the rejoin-every-round muxers dial their
    EDGE hub, so the rebind churn lands on the edge tier (counted in
    each ``edge_<id>_stats.local_hub.node_rebinds``) while the root's
    uplink connections stay stable — the tree absorbing connection
    churn at the tier that terminates it is exactly the scaling claim.
    """
    _barrier()
    info: dict = {}
    flags = ["--bcast", "delta", "--rejoin-every-round",
             "--auto-reconnect", "1000", "--shm-min-bytes", "0"]
    if args.lane != "tcp":
        flags += ["--lane", args.lane]
    use_tree = getattr(args, "topology", "flat") == "tree"
    print(f"== churn soak: {args.churn_clients} virtual clients on "
          f"{args.churn_muxers} rejoin-every-round muxers, "
          f"{args.churn_rounds} rounds"
          + (f", {args.edge_hubs} edge hubs" if use_tree else "")
          + " ==", flush=True)
    res = run_scale_federation(
        args.churn_clients, args.churn_muxers, args.churn_rounds,
        seed=args.seed, batch_size=args.batch_size,
        round_timeout=args.churn_round_timeout, timeout=args.timeout,
        extra_flags=flags, info=info,
        topology=getattr(args, "topology", "flat"),
        edge_hubs=getattr(args, "edge_hubs", 0) if use_tree else 0)
    print(json.dumps(res), flush=True)
    hub_stats = info.get("hub_stats") or {}
    faults = info.get("faults") or {}
    fallbacks = {k.split("reason=")[-1].rstrip("}"): v
                 for k, v in faults.items()
                 if k.startswith("comm.delta_full_fallbacks")}
    rebinds = hub_stats.get("node_rebinds", 0)
    if use_tree:
        # the churn terminates at the edge tier: count rebinds there
        rebinds = sum(
            (v.get("local_hub") or {}).get("node_rebinds", 0)
            for k, v in info.items()
            if k.startswith("edge_") and k.endswith("_stats")
            and isinstance(v, dict))
    min_rebinds = args.churn_muxers * max(1, args.churn_rounds - 2)
    return {
        "run": res,
        "lane": args.lane,
        "topology": "tree" if use_tree else "flat",
        "node_rebinds": rebinds,
        "delta_full_fallbacks": fallbacks,
        "hub_stats": hub_stats,
        "server_counters": faults,
        "thresholds_pre_declared": {
            "min_node_rebinds": min_rebinds,
            "full_fallbacks_required": True,
            "hub_rss_mb_max": 256.0,
        },
        "ok": bool(res["rc"] == 0 and res["nan_free"]
                   and rebinds >= min_rebinds
                   and sum(fallbacks.values()) > 0
                   and res["hub_peak_rss_mb"] < 256.0),
    }


# --- ab mode -----------------------------------------------------------------

def run_ab(args) -> dict:
    from fedml_tpu.experiments.distributed_fedavg import launch

    env = _env()

    def one(tag: str, clients: int, muxers: int, hotpath: str) -> dict:
        _barrier()
        out = os.path.join(tempfile.mkdtemp(prefix=f"fedab_{tag}_"),
                           "final.npz")
        t0 = time.time()
        rc = launch(
            num_clients=clients, rounds=args.ab_rounds, seed=args.seed,
            batch_size=args.batch_size, out_path=out,
            round_timeout=args.round_timeout,
            codec="none", wire=2, input_dim=args.input_dim,
            hotpath=hotpath, train_samples=args.train_samples,
            muxers=muxers, env=env, server_env=env,
            timeout=600.0 + args.ab_rounds * args.round_timeout,
        )
        if rc != 0:
            raise SystemExit(f"{tag}: federation failed rc={rc}")
        rounds_done, walls, finite = _round_walls(out)
        rec = {"tag": tag, "clients": clients, "muxers": muxers,
               "hotpath": hotpath, "rounds": rounds_done,
               "nan_free": finite,
               "wall_s": round(time.time() - t0, 1),
               "round_wall_s": {"samples": walls,
                                "p50": percentile(walls, 0.5),
                                "p95": percentile(walls, 0.95)}}
        print(json.dumps(rec), flush=True)
        return rec

    arms = {"mux": ("mux", 1, "fast"),
            "proc_fast": ("proc_fast", 0, "fast"),
            "proc_legacy": ("proc_legacy", 0, "legacy")}
    reps = {k: [] for k in arms}
    # ABBA interleave (PR-6 protocol): adjacent pairs share box state,
    # so linear drift cancels instead of loading onto one arm
    for i in range(args.reps):
        order = list(arms) if i % 2 == 0 else list(arms)[::-1]
        for k in order:
            tag, muxers, hotpath = arms[k]
            reps[k].append(one(f"{tag}_r{i}", args.ab_clients,
                               muxers, hotpath))

    def pooled(rs):
        samples = [s for r in rs for s in r["round_wall_s"]["samples"]]
        return {"reps": len(rs),
                "per_rep_p50": [r["round_wall_s"]["p50"] for r in rs],
                "per_rep_wall_s": [r["wall_s"] for r in rs],
                "p50_pooled": percentile(samples, 0.5),
                "p95_pooled": percentile(samples, 0.95),
                "samples": samples}

    out = {k: pooled(v) for k, v in reps.items()}
    # verdict estimator: median of per-rep p50s (robust to one run
    # caught in the box's slow scheduling mode — fed_trace_run doc)
    p50 = {k: percentile(v["per_rep_p50"], 0.5) for k, v in out.items()}
    big = one(f"mux_{args.big_clients}", args.big_clients, args.big_muxers,
              "fast")
    return {
        "config": {
            "input_dim": args.input_dim,
            "model_mb": round((args.input_dim * 2 + 2) * 4 / 1e6, 2),
            "train_samples": args.train_samples,
            "rounds": args.ab_rounds,
            "reps": args.reps,
            "protocol": "ABBA interleaved, process barrier + settle, "
                        "verdict = median of per-rep p50s (PR-6)",
        },
        "arms_32": out,
        "p50_by_arm": p50,
        "big_muxed_datapoint": big,
        "thresholds_pre_declared": {
            "mux_p50_max": "<= proc_legacy p50 (close the FEDLAT_r09 "
                           "+14% gap)",
        },
        "verdict": {
            "mux_p50": p50.get("mux"),
            "proc_fast_p50": p50.get("proc_fast"),
            "proc_legacy_p50": p50.get("proc_legacy"),
            "mux_vs_legacy": (round(p50["mux"] / p50["proc_legacy"], 3)
                              if p50.get("proc_legacy") else None),
            "mux_vs_fast": (round(p50["mux"] / p50["proc_fast"], 3)
                            if p50.get("proc_fast") else None),
            "ok": bool(p50.get("mux") is not None
                       and p50.get("proc_legacy") is not None
                       and p50["mux"] <= p50["proc_legacy"]),
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mode", choices=["scale", "ab", "both", "churn"],
                   default="both")
    p.add_argument("--out", default="FEDSCALE_r10.json")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    # scale knobs
    p.add_argument("--clients", type=int, default=10000)
    p.add_argument("--muxers", type=int, default=4)
    p.add_argument("--ref-clients", type=int, default=32)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--round-timeout", type=float, default=600.0)
    p.add_argument("--timeout", type=float, default=3600.0)
    # ab knobs (FEDLAT_r09 regime)
    p.add_argument("--ab-clients", type=int, default=32)
    p.add_argument("--ab-rounds", type=int, default=7)
    p.add_argument("--reps", type=int, default=2)
    p.add_argument("--input-dim", type=int, default=131072)
    p.add_argument("--train-samples", type=int, default=16)
    p.add_argument("--big-clients", type=int, default=256)
    p.add_argument("--big-muxers", type=int, default=1)
    # churn knobs (PR 13; PR 17 raises the default to "high virtual
    # counts" — the PR-10 leftover — and adds the tree topology):
    # muxers re-hello every round over --lane
    p.add_argument("--lane", choices=["tcp", "shm"], default="shm")
    p.add_argument("--churn-clients", type=int, default=512)
    p.add_argument("--churn-muxers", type=int, default=2)
    p.add_argument("--churn-rounds", type=int, default=5)
    p.add_argument("--churn-round-timeout", type=float, default=60.0)
    # topology knobs (PR 17): run scale/churn over the hierarchical
    # aggregation tree — worker cohorts behind --edge-hubs edge tiers
    p.add_argument("--topology", choices=["flat", "tree"],
                   default="flat")
    p.add_argument("--edge-hubs", type=int, default=2)
    args = p.parse_args(argv)

    artifact = {
        "experiment": (
            "virtual-client multiplexing (hello v2 + muxer role + "
            "vmapped cohort engine): 10k-client scale proof with "
            "bounded hub RSS, and the FEDLAT-style muxed-vs-per-"
            "process latency A/B at 32 virtual clients"
        ),
        "generated_unix": round(time.time(), 1),
    }
    ok = True
    if args.mode in ("scale", "both"):
        artifact["scale"] = run_scale(args)
        ok = ok and artifact["scale"]["ok"]
    if args.mode in ("ab", "both"):
        artifact["latency_ab"] = run_ab(args)
        ok = ok and artifact["latency_ab"]["verdict"]["ok"]
    if args.mode == "churn":
        artifact["churn"] = run_churn(args)
        ok = ok and artifact["churn"]["ok"]
    with open(args.out, "w") as fh:
        json.dump(artifact, fh, indent=1, default=float)
    print(json.dumps({"out": args.out, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
