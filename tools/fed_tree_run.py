#!/usr/bin/env python
"""FEDTREE campaign driver (PR 17): the hierarchical-aggregation scale
proof, 100k toward 1M virtual clients on one box.

Three arms, one artifact (``FEDTREE_r17.json``):

1. **Digest pin** — a small tree federation vs the flat topology at the
   same seed: every per-client upload digest byte-identical and the
   final global model bit-equal (sha256 over the leaf bytes).  The
   num/den partial composes exactly, so the tree must be invisible in
   the bytes — the same acceptance shape PR 10 pinned for
   muxed-vs-per-process.
2. **Scale ladder** — at each virtual-client count: the flat topology
   (M muxers on the root hub) vs the tree (same M muxers behind E edge
   hubs).  Reported per point: root-hub peak RSS, p50 round wall,
   rounds completed, NaN-freedom, per-edge fold counters.
3. **Bars, pre-declared** — root-hub peak RSS below the flat run's at
   the same count; p50 round wall within ``--p50-factor`` (default
   1.5x) of flat; >= 3 rounds NaN-free.  ``ok`` is the AND across the
   ladder.

The ladder runs tiny per-client problems (``--train-samples 2``, 8-dim
model) because the claim under test is TOPOLOGY cost — connection
count, fold serialization, routing memory at the root — not training
throughput; PR 10 established the cohort engine's compute story.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.fed_scale_run import (  # noqa: E402
    _barrier, _env, run_scale_federation,
)


def _model_digest(npz_path: str) -> str:
    import numpy as np

    z = np.load(npz_path)
    h = hashlib.sha256()
    for k in sorted(z.files):
        if k.startswith("leaf_"):
            h.update(np.ascontiguousarray(z[k]).tobytes())
    return h.hexdigest()


def run_pin(args) -> dict:
    """Tree-vs-flat byte-identity at full participation: upload digests
    equal per client, final model sha256 equal."""
    from fedml_tpu.experiments.distributed_fedavg import launch

    res = {}
    for tag, tree in (("flat", False), ("tree", True)):
        _barrier()
        out = os.path.join(tempfile.mkdtemp(prefix=f"fedtree_pin_{tag}_"),
                           "final.npz")
        info: dict = {}
        kw = dict(topology="tree", edge_hubs=args.edge_hubs) if tree else {}
        rc = launch(num_clients=args.pin_clients, rounds=args.rounds,
                    seed=args.seed, batch_size=args.batch_size,
                    out_path=out, muxers=args.pin_muxers,
                    env=_env(), info=info, timeout=600.0, **kw)
        digests = {k: v for k, v in sorted(info.items())
                   if k.endswith("_upload_digest")}
        res[tag] = {"rc": rc, "upload_digests": digests,
                    "model_sha256": (_model_digest(out)
                                     if os.path.exists(out) else None)}
    pin_ok = bool(
        res["flat"]["rc"] == 0 and res["tree"]["rc"] == 0
        and len(res["flat"]["upload_digests"]) == args.pin_clients
        and res["flat"]["upload_digests"] == res["tree"]["upload_digests"]
        and res["flat"]["model_sha256"] is not None
        and res["flat"]["model_sha256"] == res["tree"]["model_sha256"])
    print(json.dumps({"pin_ok": pin_ok,
                      "model_sha256": res["flat"]["model_sha256"]}),
          flush=True)
    # the full digest maps are bulky and redundant once compared —
    # keep counts + equality verdicts, drop the bodies
    for tag in res:
        res[tag]["upload_digests"] = len(res[tag]["upload_digests"])
    return {"clients": args.pin_clients, "muxers": args.pin_muxers,
            "edge_hubs": args.edge_hubs, "rounds": args.rounds,
            "runs": res, "ok": pin_ok}


def run_point(args, clients: int) -> dict:
    """One ladder point: flat then tree at the same virtual count."""
    flags = ["--train-samples", str(args.train_samples)]
    point = {"clients": clients, "muxers": args.muxers,
             "edge_hubs": args.edge_hubs}
    for tag in ("flat", "tree"):
        _barrier()
        print(f"== {clients} virtual clients / {tag} ==", flush=True)
        info: dict = {}
        r = run_scale_federation(
            clients, args.muxers, args.rounds, seed=args.seed,
            batch_size=args.batch_size,
            round_timeout=args.round_timeout, timeout=args.timeout,
            extra_flags=flags, info=info,
            topology=tag, edge_hubs=(args.edge_hubs
                                     if tag == "tree" else 0))
        if tag == "tree":
            r["edge_stats"] = {
                k: v for k, v in info.items()
                if k.startswith("edge_") and k.endswith("_stats")}
        r.pop("out_path", None)
        point[tag] = r
        print(json.dumps({tag: {"rc": r["rc"], "rounds": r["rounds"],
                                "hub_peak_rss_mb": r["hub_peak_rss_mb"],
                                "p50": r["round_wall_s"]["p50"],
                                "wall_s": r["wall_s"]}}), flush=True)
    flat, tree = point["flat"], point["tree"]
    rss_ratio = (tree["hub_peak_rss_mb"] / flat["hub_peak_rss_mb"]
                 if flat["hub_peak_rss_mb"] else None)
    p50_f, p50_t = flat["round_wall_s"]["p50"], tree["round_wall_s"]["p50"]
    p50_factor = (p50_t / p50_f if (p50_f and p50_t) else None)
    folded = sum(
        (v or {}).get("folded_uploads", 0)
        for v in (tree.get("edge_stats") or {}).values()
        if isinstance(v, dict))
    fallbacks = sum(
        (v or {}).get("flat_fallbacks", 0)
        for v in (tree.get("edge_stats") or {}).values()
        if isinstance(v, dict))
    point.update({
        "root_rss_ratio_tree_vs_flat": (round(rss_ratio, 3)
                                        if rss_ratio is not None else None),
        "p50_factor_tree_vs_flat": (round(p50_factor, 3)
                                    if p50_factor is not None else None),
        "edge_folded_uploads": folded,
        "edge_flat_fallbacks": fallbacks,
        "ok": bool(
            flat["rc"] == 0 and tree["rc"] == 0
            and flat["nan_free"] and tree["nan_free"]
            and tree["rounds"] >= args.rounds
            and rss_ratio is not None and rss_ratio < 1.0
            and p50_factor is not None
            and p50_factor <= args.p50_factor),
    })
    return point


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="FEDTREE_r17.json")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--clients-ladder", default="100000",
                   help="comma-separated virtual-client counts "
                        "(the ISSUE regime: 100000 toward 1000000)")
    p.add_argument("--muxers", type=int, default=8)
    p.add_argument("--edge-hubs", type=int, default=4)
    p.add_argument("--train-samples", type=int, default=2)
    p.add_argument("--round-timeout", type=float, default=900.0)
    p.add_argument("--timeout", type=float, default=3600.0)
    p.add_argument("--p50-factor", type=float, default=1.5,
                   help="pre-declared bar: tree p50 round wall must be "
                        "within this factor of flat's")
    p.add_argument("--pin-clients", type=int, default=64)
    p.add_argument("--pin-muxers", type=int, default=2)
    p.add_argument("--skip-pin", action="store_true")
    args = p.parse_args(argv)

    ladder = [int(x) for x in args.clients_ladder.split(",") if x]
    artifact = {
        "experiment": (
            "hierarchical edge-hub aggregation tree: root-hub RSS and "
            "p50 round wall vs the flat topology at the same virtual-"
            "client count, plus the tree-vs-flat byte-identity pin"
        ),
        "generated_unix": round(time.time(), 1),
        "thresholds_pre_declared": {
            "root_rss_ratio_max": 1.0,
            "p50_factor_max": args.p50_factor,
            "min_rounds": args.rounds,
            "min_clients": 100_000,
        },
    }
    ok = True
    if not args.skip_pin:
        artifact["digest_pin"] = run_pin(args)
        ok = ok and artifact["digest_pin"]["ok"]
    artifact["ladder"] = [run_point(args, c) for c in ladder]
    ok = ok and all(pt["ok"] for pt in artifact["ladder"])
    ok = ok and max(ladder, default=0) >= 100_000
    artifact["ok"] = ok
    with open(args.out, "w") as fh:
        json.dump(artifact, fh, indent=1, default=float)
    print(json.dumps({"out": args.out, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
