#!/usr/bin/env python
"""FEDSHARD campaign driver (PR 19): the partition-rule sharding
engine's evidence file, ``FEDSHARD_r19.json``.

Five arms:

1. **Rule coverage** — the canonical tables (``fedllm``, ``resnet``)
   matched against their real model families: per-rule leaf/param
   counts, zero unmatched paths, every rule earning its keep (>= 1
   leaf).
2. **Digest pins, in-process** — the rule-driven round engine
   (``partition.make_rule_round_fn``) on host meshes dp in {1, 2, 8}
   (mp=1) vs the plain single-device engine, fp32 AND int8+EF: the
   final global model sha256 must be IDENTICAL across every cell.
   Each cell is a subprocess because
   ``--xla_force_host_platform_device_count`` must be set before jax
   initializes.  An mp=2 cell runs as allclose only — mp splits the
   matmul contraction dim, which reassociates fp32 reductions by
   construction (bit-parity over mp is not a claim this engine makes).
3. **Muxed pin** — the full federation (``distributed_fedavg.launch``)
   per-process vs muxed-on-host-mesh (``--mesh 4,1``): every client
   upload digest and every final-model leaf byte-identical.
4. **Per-shard wire bytes** — ``compress.sharded.wire_encode_tree_sharded``
   on a dp2 x mp2 mesh: each shard's packed buffers byte-identical to a
   single-device encode of that shard's slice under the same
   ``fold_in(fold_in(key, leaf), shard)`` stream, shard elements summing
   exactly to leaf elements (each element visited once — no gather, no
   overlap), decode roundtrip equal to the plain codec roundtrip.
5. **Cohort throughput** — the 256-virtual-client point, dp=1 vs dp=8
   host mesh, p50 round wall.  Target 2x; on this 1-core box host
   "devices" are threads multiplexed onto one core, so the bar is
   expected to MISS here and is reported honestly with the chip-sweep
   command deferred to PROFILE.md (the FEDXPORT_r13 precedent).

``ok`` is the AND of arms 1-4; arm 5 records ``met`` in its own
section.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_VOCAB = 64
_EMBED = 32
_HEADS = 2
_LAYERS = 1
_SEQ = 16


def _child_env(devices: int) -> dict:
    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices}"
        if devices > 1 else ""
    )
    return env


def _synthetic(seed: int, clients: int, steps: int, batch: int):
    """Deterministic host-side token data, identical in every child:
    x [K, steps, B, L] tokens, y next-token targets, mask ones."""
    import numpy as np

    rng = np.random.default_rng(seed)
    toks = rng.integers(
        0, _VOCAB, size=(clients, steps, batch, _SEQ + 1), dtype=np.int64
    )
    x = toks[..., :-1].astype(np.int32)
    y = toks[..., 1:].astype(np.int32)
    mask = np.ones((clients, steps, batch), np.float32)
    num_samples = np.full((clients,), steps * batch, np.float32)
    participation = np.ones((clients,), np.float32)
    slot_ids = np.arange(clients, dtype=np.int32)
    return x, y, mask, num_samples, participation, slot_ids


def _model_and_update(epochs: int = 1):
    import jax

    from fedml_tpu.core.client import make_client_optimizer, make_local_update
    from fedml_tpu.models.transformer import transformer_lm

    bundle = transformer_lm(
        vocab_size=_VOCAB, embed_dim=_EMBED, num_heads=_HEADS,
        num_layers=_LAYERS, seq_len=_SEQ,
    )
    opt = make_client_optimizer("sgd", 0.1)
    lu = make_local_update(bundle, opt, epochs=epochs)
    variables = bundle.init(jax.random.PRNGKey(0))
    return bundle, lu, variables


def _tree_digest(tree) -> str:
    import jax
    import numpy as np

    h = hashlib.sha256()
    for path, leaf in sorted(
        jax.tree_util.tree_leaves_with_path(tree),
        key=lambda kv: jax.tree_util.keystr(kv[0]),
    ):
        h.update(jax.tree_util.keystr(path).encode())
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


# --- child payloads (run under a fresh XLA_FLAGS) ---------------------------

def child_pin(args) -> dict:
    """One digest cell: rounds of the rule engine (or the plain
    single-device engine) over the shared synthetic federation; prints
    the final-model sha256."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.algorithms.fedavg import ServerState, make_round_fn
    from fedml_tpu.compress import get_codec
    from fedml_tpu.parallel.mesh import make_dp_mp_mesh
    from fedml_tpu.parallel.partition import FEDLLM_RULES, make_rule_round_fn

    clients, rounds = args.clients, args.rounds
    _, lu, variables = _model_and_update()
    codec = get_codec(args.codec or None)
    ef = bool(args.ef) and codec is not None
    residuals = ()
    if ef:
        residuals = jax.tree_util.tree_map(
            lambda l: jnp.zeros((clients,) + l.shape, jnp.float32),
            variables,
        )
    state = ServerState(
        variables=variables, opt_state=(),
        round_idx=jnp.zeros((), jnp.int32), key=jax.random.PRNGKey(args.seed),
        residuals=residuals,
    )
    data = _synthetic(args.seed, clients, steps=2, batch=2)
    if args.engine == "rules":
        mesh = make_dp_mp_mesh(args.dp, args.mp)
        round_fn, shard_state, shard_data = make_rule_round_fn(
            mesh, lu, variables, FEDLLM_RULES,
            codec=codec, error_feedback=ef,
        )
        state = shard_state(state)
    else:
        inner = make_round_fn(
            lu, client_axis_impl="vmap", codec=codec, error_feedback=ef,
        )
        round_fn = jax.jit(inner, donate_argnums=(0,))

        def shard_data(arrays):
            return tuple(jnp.asarray(a) for a in arrays)

    losses = []
    for _ in range(rounds):
        state, m = round_fn(state, *shard_data(data))
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    return {
        "engine": args.engine, "dp": args.dp, "mp": args.mp,
        "codec": args.codec or "fp32", "ef": bool(ef), "rounds": rounds,
        "devices": jax.device_count(),
        "digest": _tree_digest(state.variables),
        "losses": [round(v, 6) for v in losses],
        "nan_free": all(v == v for v in losses),
    }


def child_bytes(args) -> dict:
    """Per-shard wire-byte identity on a dp x mp mesh: every shard's
    packed buffers vs a single-device encode of the same slice, plus
    exact element accounting and decode-roundtrip equality."""
    import jax
    import numpy as np

    from fedml_tpu.compress import get_codec
    from fedml_tpu.compress.codecs import (
        _leaf_keys, wire_encode_tree,
    )
    from fedml_tpu.compress.sharded import (
        sharded_entry_nbytes, sharded_wire_digest, shard_slices,
        wire_decode_tree_sharded, wire_encode_tree_sharded,
    )
    from fedml_tpu.parallel.mesh import make_dp_mp_mesh
    from fedml_tpu.parallel.partition import FEDLLM_RULES, shard_by_rules

    codec = get_codec(args.codec)
    _, _, variables = _model_and_update()
    mesh = make_dp_mp_mesh(args.dp, args.mp)
    sharded, _specs = shard_by_rules(mesh, variables, FEDLLM_RULES)
    key = jax.random.PRNGKey(args.seed)
    entries = wire_encode_tree_sharded(codec, sharded, key)

    leaves = jax.tree_util.tree_leaves(sharded)
    host_leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(variables)]
    shard_match = element_match = True
    total_shards = 0
    multi_shard_leaves = 0
    wire_bytes = 0
    for i, (leaf, full, entry) in enumerate(
        zip(leaves, host_leaves, entries)
    ):
        k_leaf = list(_leaf_keys(key, len(leaves)))[i]
        slices = shard_slices(leaf)
        if len(slices) > 1:
            multi_shard_leaves += 1
        elems = 0
        for j, ((bounds, _data), sh) in enumerate(zip(slices, entry["shards"])):
            total_shards += 1
            sel = tuple(slice(lo, hi) for lo, hi in bounds)
            elems += int(np.prod([hi - lo for lo, hi in bounds]))
            ref = codec.wire_pack({
                name: np.asarray(v)
                for name, v in codec.encode(
                    np.asarray(full[sel]), jax.random.fold_in(k_leaf, j)
                ).items()
            })
            for name in sorted(set(ref) | set(sh["enc"])):
                a = np.asarray(ref.get(name))
                b = np.asarray(sh["enc"].get(name))
                if a.shape != b.shape or not np.array_equal(a, b):
                    shard_match = False
        if elems != int(np.prod(np.shape(full), dtype=np.int64)):
            element_match = False
        wire_bytes += sum(sharded_entry_nbytes(entry))

    decoded = wire_decode_tree_sharded(codec, entries, variables)
    plain_entries = wire_encode_tree(codec, variables, key)
    plain_bytes = sum(
        int(np.asarray(v).nbytes)
        for e in plain_entries for v in e["enc"].values()
    )
    finite = all(
        bool(np.isfinite(l).all()) for l in jax.tree_util.tree_leaves(decoded)
    )
    return {
        "codec": args.codec, "dp": args.dp, "mp": args.mp,
        "devices": jax.device_count(),
        "leaves": len(leaves),
        "multi_shard_leaves": multi_shard_leaves,
        "shards_total": total_shards,
        "per_shard_bytes_identical": bool(shard_match),
        "element_accounting_exact": bool(element_match),
        "decode_finite": finite,
        "wire_bytes_sharded": int(wire_bytes),
        "wire_bytes_plain": int(plain_bytes),
        "sharded_wire_digest": sharded_wire_digest(entries),
    }


def child_throughput(args) -> dict:
    """The 256-virtual-client cohort point: p50 round wall of the rule
    engine on this mesh width (first round = jit warmup, excluded)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.algorithms.fedavg import ServerState
    from fedml_tpu.parallel.mesh import make_dp_mp_mesh
    from fedml_tpu.parallel.partition import FEDLLM_RULES, make_rule_round_fn

    clients, rounds = args.clients, args.rounds
    _, lu, variables = _model_and_update()
    state = ServerState(
        variables=variables, opt_state=(),
        round_idx=jnp.zeros((), jnp.int32), key=jax.random.PRNGKey(args.seed),
    )
    mesh = make_dp_mp_mesh(args.dp, args.mp)
    round_fn, shard_state, shard_data = make_rule_round_fn(
        mesh, lu, variables, FEDLLM_RULES,
    )
    state = shard_state(state)
    data = shard_data(_synthetic(args.seed, clients, steps=1, batch=2))
    samples = []
    for r in range(rounds + 1):
        t0 = time.perf_counter()
        state, m = round_fn(state, *data)
        jax.block_until_ready(m["loss_sum"])
        if r:  # round 0 is compile
            samples.append(time.perf_counter() - t0)
    samples.sort()
    return {
        "dp": args.dp, "mp": args.mp, "clients": clients,
        "devices": jax.device_count(), "rounds_timed": rounds,
        "round_wall_s": [round(s, 4) for s in samples],
        "p50_s": round(samples[len(samples) // 2], 4),
    }


def _spawn(child: str, devices: int, timeout: float, **kw) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__), "--child", child]
    for k, v in kw.items():
        cmd += [f"--{k.replace('_', '-')}", str(v)]
    out = subprocess.run(
        cmd, env=_child_env(devices), capture_output=True, text=True,
        timeout=timeout, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"child {child} {kw} failed rc={out.returncode}:\n"
            f"{out.stdout[-2000:]}\n{out.stderr[-2000:]}"
        )
    return json.loads(out.stdout.splitlines()[-1])


# --- parent arms ------------------------------------------------------------

def run_coverage() -> dict:
    import jax

    from fedml_tpu.parallel.partition import (
        FEDLLM_RULES, RESNET_RULES, rule_coverage,
    )

    from fedml_tpu.models.resnet import resnet20

    _, _, tvars = _model_and_update()
    out = {"fedllm": rule_coverage(FEDLLM_RULES, tvars)}
    rvars = resnet20(num_classes=10).init(jax.random.PRNGKey(0))
    out["resnet"] = rule_coverage(RESNET_RULES, rvars)
    ok = True
    for name, cov in out.items():
        if cov["unmatched_paths"]:
            ok = False
        if any(r["leaves"] == 0 for r in cov["rules"]):
            ok = False
    out["ok"] = ok
    return out


def run_pins(args) -> dict:
    cells = []
    matrix = [
        ("plain", 1, 1, "", 0),
        ("rules", 1, 1, "", 0),
        ("rules", 2, 1, "", 0),
        ("rules", 8, 1, "", 0),
        ("plain", 1, 1, "int8", 1),
        ("rules", 1, 1, "int8", 1),
        ("rules", 2, 1, "int8", 1),
        ("rules", 8, 1, "int8", 1),
    ]
    for engine, dp, mp, codec, ef in matrix:
        cells.append(_spawn(
            "pin", devices=dp * mp, timeout=args.timeout,
            engine=engine, dp=dp, mp=mp, codec=codec, ef=ef,
            clients=args.pin_clients, rounds=args.pin_rounds, seed=args.seed,
        ))
    by_codec = {}
    for c in cells:
        by_codec.setdefault((c["codec"], c["ef"]), []).append(c)
    identical = {
        f"{codec}_ef{int(ef)}": len({c["digest"] for c in group}) == 1
        for (codec, ef), group in by_codec.items()
    }
    # mp=2 reassociates the contraction dim: allclose-only cell
    mp2 = _spawn(
        "pin", devices=8, timeout=args.timeout,
        engine="rules", dp=4, mp=2, codec="", ef=0,
        clients=args.pin_clients, rounds=args.pin_rounds, seed=args.seed,
    )
    ref = next(c for c in cells if c["engine"] == "plain" and not c["ef"])
    mp2_close = all(
        abs(a - b) < 1e-3
        for a, b in zip(mp2["losses"], ref["losses"])
    )
    ok = (all(identical.values()) and all(c["nan_free"] for c in cells)
          and mp2["nan_free"] and mp2_close)
    return {
        "cells": cells,
        "identical_within_codec": identical,
        "mp2_cell": {**mp2, "losses_allclose_vs_plain": mp2_close},
        "ok": ok,
    }


def run_mux_pin(args) -> dict:
    import numpy as np

    from fedml_tpu.experiments.distributed_fedavg import launch

    results = {}
    with tempfile.TemporaryDirectory() as td:
        for tag, kw, devices in (
            ("per_process", dict(muxers=0), 1),
            ("muxed_mesh", dict(muxers=1, muxed_clients=args.mux_clients,
                                mesh="4,1"), 4),
        ):
            out = os.path.join(td, f"{tag}.npz")
            info = {}
            rc = launch(
                num_clients=args.mux_clients, rounds=args.mux_rounds,
                seed=args.seed, batch_size=16, out_path=out,
                env=_child_env(devices), server_env=_child_env(1),
                info=info, timeout=args.timeout, **kw,
            )
            z = np.load(out)
            results[tag] = {
                "rc": rc,
                "digests": {k: v for k, v in sorted(info.items())
                            if k.endswith("_upload_digest")},
                "leaves": [np.asarray(z[k]) for k in sorted(z.files)
                           if k.startswith("leaf_")],
            }
    a, b = results["per_process"], results["muxed_mesh"]
    digests_ok = a["digests"] == b["digests"] and len(a["digests"]) > 0
    model_ok = len(a["leaves"]) == len(b["leaves"]) and all(
        np.array_equal(x, y) for x, y in zip(a["leaves"], b["leaves"])
    )
    return {
        "clients": args.mux_clients, "rounds": args.mux_rounds,
        "mesh": "4,1",
        "rc": {t: r["rc"] for t, r in results.items()},
        "digests": a["digests"],
        "digests_identical": digests_ok,
        "final_model_identical": bool(model_ok),
        "ok": bool(a["rc"] == 0 and b["rc"] == 0 and digests_ok and model_ok),
    }


def run_bytes(args) -> dict:
    out = {}
    ok = True
    for codec in ("int8", "int4"):
        cell = _spawn(
            "bytes", devices=4, timeout=args.timeout,
            codec=codec, dp=2, mp=2, seed=args.seed,
        )
        out[codec] = cell
        ok = ok and cell["per_shard_bytes_identical"] \
            and cell["element_accounting_exact"] and cell["decode_finite"] \
            and cell["multi_shard_leaves"] > 0
    out["ok"] = ok
    return out


def run_throughput(args) -> dict:
    arms = {}
    for dp in (1, 8):
        arms[f"dp{dp}"] = _spawn(
            "throughput", devices=dp, timeout=args.timeout,
            dp=dp, mp=1, clients=args.tp_clients, rounds=args.tp_rounds,
            seed=args.seed,
        )
    speedup = arms["dp1"]["p50_s"] / max(arms["dp8"]["p50_s"], 1e-9)
    met = speedup >= args.tp_target
    return {
        "arms": arms,
        "target_speedup": args.tp_target,
        "speedup": round(speedup, 3),
        "met": bool(met),
        "note": (
            "host-mesh devices on this box are threads multiplexed onto "
            "nproc=1 core — dp width adds partition overhead without "
            "parallel compute, so the 2x bar cannot be met here; the "
            "real-chip sweep command is recorded in PROFILE.md (r19 "
            "appendix), same deferral shape as FEDXPORT_r13's chip bars"
        ) if not met else "",
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--child", choices=("pin", "bytes", "throughput"))
    ap.add_argument("--engine", default="rules")
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--mp", type=int, default=1)
    ap.add_argument("--codec", default="")
    ap.add_argument("--ef", type=int, default=0)
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--pin-clients", type=int, default=16)
    ap.add_argument("--pin-rounds", type=int, default=3)
    ap.add_argument("--mux-clients", type=int, default=8)
    ap.add_argument("--mux-rounds", type=int, default=2)
    ap.add_argument("--tp-clients", type=int, default=256)
    ap.add_argument("--tp-rounds", type=int, default=5)
    ap.add_argument("--tp-target", type=float, default=2.0)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--skip-throughput", action="store_true")
    ap.add_argument("--out", default="FEDSHARD_r19.json")
    args = ap.parse_args()

    if args.child:
        fn = {"pin": child_pin, "bytes": child_bytes,
              "throughput": child_throughput}[args.child]
        print(json.dumps(fn(args)))
        return 0

    doc = {
        "experiment": (
            "partition-rule sharding engine: ordered (regex -> "
            "PartitionSpec) tables over one dp x mp mesh covering the "
            "fedllm model AND the virtual-client cohort, with per-shard "
            "QSGD wire encode and bit-exact dp aggregation"
        ),
        "generated_unix": round(time.time(), 1),
    }
    t0 = time.time()
    doc["coverage"] = run_coverage()
    print(f"[coverage] ok={doc['coverage']['ok']}", flush=True)
    doc["digest_pins"] = run_pins(args)
    print(f"[digest_pins] ok={doc['digest_pins']['ok']} "
          f"{doc['digest_pins']['identical_within_codec']}", flush=True)
    doc["mux_pin"] = run_mux_pin(args)
    print(f"[mux_pin] ok={doc['mux_pin']['ok']}", flush=True)
    doc["shard_bytes"] = run_bytes(args)
    print(f"[shard_bytes] ok={doc['shard_bytes']['ok']}", flush=True)
    if not args.skip_throughput:
        doc["throughput_256"] = run_throughput(args)
        print(f"[throughput_256] speedup="
              f"{doc['throughput_256']['speedup']} "
              f"met={doc['throughput_256']['met']}", flush=True)
    doc["wall_s"] = round(time.time() - t0, 1)
    doc["ok"] = all(doc[k]["ok"] for k in
                    ("coverage", "digest_pins", "mux_pin", "shard_bytes"))
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
    print(f"wrote {args.out} ok={doc['ok']}")
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
