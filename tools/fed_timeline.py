#!/usr/bin/env python
"""Merge a federation run's per-process metrics files into ONE timeline.

Input: a run_dir written by ``experiments/distributed_fedavg.py
--run-dir`` with tracing on (``--trace`` / ``FEDML_TPU_TRACE=1``):
``metrics-node<id>.jsonl`` per participant plus ``metrics-hub.jsonl``.
Each file carries that process's ``trace_hop`` chains (per-hop monotonic
stamps: send → hub_in → hub_out → recv → done), its ``clock_sync``
handshake offset estimate, the server's ``round_close`` boundaries, and
the hub's periodic ``hub_stats`` queue-depth samples.

The merger places every stamp on the HUB's monotonic clock
(``t_hub = t_local + offset[node]``, min-RTT NTP estimate from
``obs/trace_ctx.estimate_offset``; loopback uncertainty ~ tens of
microseconds) and reconstructs, per round, the measured critical path:

    serialize → hub queue (broadcast) → fan-out deliver → client train
    → upload serialize → upload wire → hub queue (upload) → deliver
    → decode+fold → close

The per-round critical chain follows the LAST upload the server needed
(the one whose arrival closed the round) — its client's sync copy, its
train span, its upload's hub hops — so the breakdown is an actual path
through one message chain, not a sum of averages.  Cohort-wide stats
(mean/max hub queue wait, train spread) ride alongside.

Outputs:

- human-readable per-round table + aggregate p50 attribution (default);
- ``--json``: the same as one JSON object;
- ``--perfetto OUT.json``: Chrome trace-event JSON (open in Perfetto /
  chrome://tracing) — one track per process, slices for every measured
  span, counter tracks for the hub's per-connection send-queue depth.

Usage: python tools/fed_timeline.py RUN_DIR [--json] [--perfetto OUT]
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
from typing import Dict, List, Optional

HUB = "hub"
SYNC_TYPES = ("S2C_INIT_CONFIG", "S2C_SYNC_MODEL")
UPLOAD_TYPE = "C2S_SEND_MODEL"

# breakdown phases in critical-path order (the report's row order).
# stripe_reasm (striped fan-out: first-stripe arrival -> delivery) and
# decode_wait (pipelined server: reader submit -> decode-pool pickup)
# are zero/absent on the whole-frame / serial paths.
PHASES = [
    "serialize", "bcast_queue", "bcast_deliver", "stripe_reasm",
    "client_train", "upload_serialize", "upload_wire", "upload_queue",
    "upload_deliver", "decode_wait", "decode_fold", "close",
]

# informational rows reported alongside but NOT summed into the
# critical path: encode_overlap is the next broadcast's off-thread
# encode+send (it overlaps other phases by design), bcast_skew is the
# cohort's max-min sync delivery spread (stripe fairness in one number)
EXTRA_ROWS = ["encode_overlap", "bcast_skew"]


def _read_jsonl(path: str) -> List[dict]:
    recs = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail of a killed process: keep the rest
    return recs


def load_run(run_dir: str) -> dict:
    """Parse every metrics-*.jsonl in ``run_dir`` into one bundle."""
    files = sorted(glob.glob(os.path.join(run_dir, "metrics-*.jsonl")))
    if not files:
        raise SystemExit(f"no metrics-*.jsonl files in {run_dir!r} "
                         "(run with --run-dir and --trace)")
    offsets: Dict[object, float] = {HUB: 0.0}
    resynced: Dict[object, int] = {}
    hops: List[dict] = []
    rounds: List[dict] = []
    hub_stats: List[dict] = []
    mux_of: Dict[int, int] = {}  # virtual node -> its muxer's id
    for path in files:
        for rec in _read_jsonl(path):
            kind = rec.get("kind")
            if kind == "mux_members":
                for n in rec.get("nodes") or ():
                    mux_of[int(n)] = int(rec.get("muxer", n))
            elif kind == "clock_sync":
                node, off = rec["node"], float(rec["offset_s"])
                # a second handshake for the same node means the hub
                # process (the clock every offset is relative to) was
                # replaced mid-run: stamps before/after the restart live
                # on unrelated monotonic origins
                if node in offsets and node != HUB and \
                        abs(offsets[node] - off) > 1e-3:
                    resynced[node] = resynced.get(node, 1) + 1
                offsets[node] = off
            elif kind == "trace_hop":
                hops.append(rec)
            elif kind == "round_close":
                rounds.append(rec)
            elif kind == "hub_stats":
                hub_stats.append(rec)
    if resynced:
        print("WARNING: nodes re-ran the clock-sync handshake with a "
              f"materially different offset ({sorted(resynced)}): the hub "
              "was restarted mid-run, so hop stamps from the two hub "
              "processes sit on unrelated monotonic clocks and each "
              "node's PRE-restart stamps are mapped with its POST-restart "
              "offset (last sync wins).  Per-round spans crossing the "
              "restart are unreliable — trust only rounds entirely on "
              "one side of it.", file=sys.stderr)
    # virtual clients stamp on their MUXER's process clock (one
    # handshake per connection, recorded under the muxer's primary id):
    # propagate that offset to every co-located virtual id
    for n, m in mux_of.items():
        if n not in offsets and m in offsets:
            offsets[n] = offsets[m]
    rounds.sort(key=lambda r: r.get("round", -1))
    return {"offsets": offsets, "hops": hops, "rounds": rounds,
            "hub_stats": hub_stats, "files": files, "mux": mux_of,
            "clock_resync_nodes": sorted(resynced)}


def _hub_t(offsets: dict, node, t: float) -> float:
    """Map one stamp onto the hub clock; unknown nodes (inproc runs, a
    node whose handshake predates tracing) fall back to offset 0."""
    return t + offsets.get(node, 0.0)


def _hop_map(rec: dict, offsets: dict) -> Dict[str, float]:
    """hop list -> {event: t_hub}, first occurrence wins (a chaos
    duplicate's re-send restamps are reported via its own copy)."""
    out: Dict[str, float] = {}
    for node, event, t in rec.get("hops", ()):
        out.setdefault(event, _hub_t(offsets, node, float(t)))
    return out


def _span(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if a is None or b is None:
        return None
    return b - a


def build_rounds(bundle: dict) -> List[dict]:
    """Per-round critical-path breakdown (see module doc)."""
    offsets = bundle["offsets"]
    # primary copies only for the critical path; duplicates kept for the
    # chaos section of the report
    syncs: Dict[int, Dict[int, dict]] = {}    # round -> client node -> rec
    uploads: Dict[int, Dict[int, dict]] = {}  # round -> origin node -> rec
    for rec in bundle["hops"]:
        rnd = rec.get("round")
        if rnd is None or rec.get("copy", 0):
            continue
        if rec.get("msg_type") in SYNC_TYPES:
            syncs.setdefault(rnd, {})[rec.get("node")] = rec
        elif rec.get("msg_type") == UPLOAD_TYPE:
            uploads.setdefault(rnd, {}).setdefault(rec.get("org"), rec)
    out = []
    for rc in bundle["rounds"]:
        rnd = rc.get("round")
        ups = uploads.get(rnd, {})
        sys_ = syncs.get(rnd, {})
        row = {
            "round": rnd,
            "wall_s": _span(rc.get("t_open_m"), rc.get("t_close_m")),
            "participants": rc.get("participants"),
            "close": rc.get("time_agg"),
        }
        if ups:
            # the round closed when its LAST needed upload finished
            # folding: that chain is the measured critical path
            def _done_t(rec):
                h = _hop_map(rec, offsets)
                return h.get("done", h.get("recv", float("-inf")))

            crit_org = max(ups, key=lambda o: _done_t(ups[o]))
            up = _hop_map(ups[crit_org], offsets)
            raw_up_t0 = _ctx_t0(ups[crit_org])
            up_t0 = (_hub_t(offsets, crit_org, float(raw_up_t0))
                     if raw_up_t0 is not None else None)
            sy_rec = sys_.get(crit_org)
            sy = _hop_map(sy_rec, offsets) if sy_rec else {}
            raw_sy_t0 = _ctx_t0(sy_rec) if sy_rec else None
            sy_t0 = (_hub_t(offsets, 0, float(raw_sy_t0))
                     if raw_sy_t0 is not None else None)
            row["critical_client"] = crit_org
            row["serialize"] = _span(sy_t0, sy.get("send"))
            row["bcast_queue"] = _span(sy.get("hub_in"), sy.get("hub_out"))
            # striped fan-out: hub_out -> reasm (first stripe landed) is
            # the fan-out leg proper; reasm -> recv is the streaming/
            # reassembly wait.  Whole frames have no reasm hop and the
            # old single-span semantics are preserved.
            sy_arrive = sy.get("reasm", sy.get("recv"))
            row["bcast_deliver"] = _span(sy.get("hub_out"), sy_arrive)
            row["stripe_reasm"] = (_span(sy.get("reasm"), sy.get("recv"))
                                   if "reasm" in sy else None)
            # train = sync arrival -> upload-send entry on the client
            # (the upload ctx's t0 is stamped at send ENTRY, after the
            # local update ran inside the sync handler)
            row["client_train"] = _span(sy.get("recv"), up_t0)
            row["upload_serialize"] = _span(up_t0, up.get("send"))
            row["upload_wire"] = _span(up.get("send"), up.get("hub_in"))
            row["upload_queue"] = _span(up.get("hub_in"), up.get("hub_out"))
            row["upload_deliver"] = _span(up.get("hub_out"), up.get("recv"))
            # the Kth upload's handler RUNS the round close (and the
            # next round's broadcast) before its 'done' stamp, so the
            # critical fold anchors on t_close_m instead: recv ->
            # close-stamp minus the separately-measured normalize
            t_close = (_hub_t(offsets, 0, rc["t_close_m"])
                       if rc.get("t_close_m") is not None else None)
            fold_close = _span(up.get("recv"), t_close)
            # pipelined decode: the closing upload's pool queue wait is
            # its own phase (carried on the round_close record), and
            # decode_fold is the remainder so the chain never double-
            # counts it
            row["decode_wait"] = rc.get("decode_wait_s")
            row["decode_fold"] = (
                fold_close - (rc.get("time_agg") or 0.0)
                - (rc.get("decode_wait_s") or 0.0)
                if fold_close is not None else
                _span(up.get("recv"), up.get("done")))
            row["encode_overlap"] = rc.get("encode_overlap_s")
            # stripe-fairness number: cohort-wide sync delivery skew
            # (max - min recv across receivers) — striping's whole job
            # is to shrink this
            recvs = [h.get("recv")
                     for h in (_hop_map(r, offsets) for r in sys_.values())]
            recvs = [t for t in recvs if t is not None]
            row["bcast_skew"] = (max(recvs) - min(recvs)
                                 if len(recvs) > 1 else None)
            # cohort-wide spread (evidence for contention vs queue wait)
            queues = [_span(h.get("hub_in"), h.get("hub_out"))
                      for h in (_hop_map(r, offsets) for r in ups.values())]
            queues = [q for q in queues if q is not None]
            folds = [_span(h.get("recv"), h.get("done"))
                     for h in (_hop_map(r, offsets) for r in ups.values())]
            folds = [q for q in folds if q is not None]
            row["upload_queue_max"] = max(queues) if queues else None
            row["fold_sum"] = sum(folds) if folds else None
            bq = [_span(h.get("hub_in"), h.get("hub_out"))
                  for h in (_hop_map(r, offsets) for r in sys_.values())]
            bq = [q for q in bq if q is not None]
            row["bcast_queue_max"] = max(bq) if bq else None
            accounted = sum(row.get(p) or 0.0 for p in PHASES)
            row["accounted_s"] = accounted
            row["other_s"] = (row["wall_s"] - accounted
                              if row["wall_s"] is not None else None)
        out.append(row)
    return out


def _ctx_t0(rec: dict) -> Optional[float]:
    # trace_hop events carry hops but not t0 directly; t0 rides the
    # serialized ctx — emitted as its own field when present
    return rec.get("t0")


def percentile(values, q):
    """Nearest-rank percentile over the non-None samples — the SAME
    estimator as ``tools/trace_summary.percentile``, pinned because
    ``fed_trace_run`` mixes both into one artifact (phase p50s from
    here, round-wall p50s from trace_summary): with a handful of
    samples, two estimators pick different ranks."""
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    idx = min(len(vals) - 1, max(0, int(math.ceil(q * len(vals))) - 1))
    return vals[idx]


def summarize(rows: List[dict]) -> dict:
    """Aggregate p50 per phase over rounds + share of round wall."""
    p50 = {p: percentile([r.get(p) for r in rows], 0.5) for p in PHASES}
    p50["other"] = percentile([r.get("other_s") for r in rows], 0.5)
    wall = percentile([r.get("wall_s") for r in rows], 0.5)
    shares = {}
    if wall:
        for k, v in p50.items():
            if v is not None:
                shares[k] = round(v / wall, 4)
    extras = {p: percentile([r.get(p) for r in rows], 0.5)
              for p in EXTRA_ROWS}
    return {"p50_round_wall_s": wall, "p50_phase_s": p50,
            "phase_share_of_wall": shares,
            "p50_extra_s": extras,
            "rounds": len(rows)}


def chaos_copies(bundle: dict) -> List[dict]:
    """Duplicate deliveries (chaos): every copy>0 chain, verbatim —
    each has its own hop stamps by construction."""
    return [
        {"seq": r.get("seq"), "copy": r.get("copy"), "org": r.get("org"),
         "round": r.get("round"), "msg_type": r.get("msg_type"),
         "hops": r.get("hops")}
        for r in bundle["hops"] if r.get("copy", 0)
    ]


# --- Chrome trace-event export ----------------------------------------------

def _pid(node) -> int:
    # hub -> 0, server (node 0) -> 1, client node n -> n + 1
    return 0 if node == HUB else int(node) + 1


def to_perfetto(bundle: dict, rows: List[dict]) -> dict:
    """Chrome trace-event JSON: one process track per participant,
    slices for every measured span (hub-clock microseconds).  Virtual
    clients are grouped UNDER their muxer's process track — one pid per
    muxer, one tid per virtual node (``mux_members`` events) — so the
    critical-path chain stays readable at hundreds of co-located
    clients instead of exploding into hundreds of top-level tracks."""
    offsets = bundle["offsets"]
    mux_of = bundle.get("mux") or {}
    events: List[dict] = []
    names = {0: "hub", 1: "server (node 0)"}
    threads: Dict[tuple, str] = {}

    def track(node):
        """(pid, tid) for one participant's slices."""
        m = mux_of.get(node)
        if m is not None:
            pid = _pid(m)
            if pid not in names:
                count = sum(1 for v in mux_of.values() if v == m)
                names[pid] = f"muxer node {m} ({count} virtual clients)"
            threads[(pid, int(node))] = f"virtual client {node}"
            return pid, int(node)
        pid = _pid(node)
        if pid not in names:
            names[pid] = f"client node {node}"
        return pid, 0

    all_t: List[float] = []
    for rec in bundle["hops"]:
        for node, _, t in rec.get("hops", ()):
            all_t.append(_hub_t(offsets, node, float(t)))
            track(node)
    for rc in bundle["rounds"]:
        if rc.get("t_open_m") is not None:
            all_t.append(_hub_t(offsets, 0, rc["t_open_m"]))
    if not all_t:
        raise SystemExit("no trace_hop stamps found (tracing off?)")
    t_base = min(all_t)

    def us(t_hub: float) -> float:
        return round((t_hub - t_base) * 1e6, 1)

    for pid, name in sorted(names.items()):
        events.append({"ph": "M", "pid": pid, "name": "process_name",
                       "args": {"name": name}})
    for (pid, tid), tname in sorted(threads.items()):
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": tname}})

    def slice_(pid, name, t0, t1, tid=0, **args):
        if t0 is None or t1 is None or t1 < t0:
            return
        events.append({"ph": "X", "pid": pid, "tid": tid, "name": name,
                       "ts": us(t0), "dur": round((t1 - t0) * 1e6, 1),
                       "args": args})

    for rec in bundle["hops"]:
        h = _hop_map(rec, offsets)
        mt, rnd = rec.get("msg_type"), rec.get("round")
        tag = f"{mt} r{rnd}" + (f" c{rec['copy']}" if rec.get("copy") else "")
        org, node = rec.get("org"), rec.get("node")
        t0 = rec.get("t0")
        if t0 is not None and "send" in h:
            opid, otid = track(org)
            slice_(opid, f"serialize {tag}",
                   _hub_t(offsets, org, float(t0)), h["send"], tid=otid,
                   to=node)
        slice_(0, f"hub queue {tag} -> {node}",
               h.get("hub_in"), h.get("hub_out"), receiver=node)
        npid, ntid = track(node)
        slice_(npid, f"reassemble {tag}", h.get("reasm"),
               h.get("recv"), tid=ntid, sender=org)
        slice_(npid, f"handle {tag}", h.get("recv"), h.get("done"),
               tid=ntid, sender=org)
    for rc in bundle["rounds"]:
        if rc.get("t_open_m") is None:
            continue
        slice_(1, f"round {rc.get('round')}",
               _hub_t(offsets, 0, rc["t_open_m"]),
               _hub_t(offsets, 0, rc["t_close_m"]),
               participants=rc.get("participants"))
    for hs in bundle["hub_stats"]:
        t = hs.get("t_m")
        if t is None:
            continue
        for cid, frames in (hs.get("queue_frames") or {}).items():
            # keyed by CONNECTION id since the hello-v2 telemetry split
            # (a muxer's virtual nodes share one queue)
            events.append({"ph": "C", "pid": 0,
                           "name": f"send queue frames conn {cid}",
                           "ts": us(float(t)),
                           "args": {"frames": frames}})
        events.append({"ph": "C", "pid": 0, "name": "backpressure drops",
                       "ts": us(float(t)),
                       "args": {"drops": hs.get("backpressure_drops", 0)}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# --- CLI --------------------------------------------------------------------

def _fmt_ms(v) -> str:
    return f"{v * 1e3:8.2f}" if v is not None else "       -"


def render(rows: List[dict], summary: dict, copies: List[dict]) -> str:
    lines = ["== per-round critical path (ms, hub clock) =="]
    hdr = ["round", "wall"] + PHASES + ["other", "crit_client"] + EXTRA_ROWS
    lines.append(" ".join(f"{h:>12}" for h in hdr))
    for r in rows:
        vals = [f"{r['round']:>12}", _fmt_ms(r.get("wall_s")).rjust(12)]
        vals += [_fmt_ms(r.get(p)).rjust(12) for p in PHASES]
        vals += [_fmt_ms(r.get("other_s")).rjust(12),
                 str(r.get("critical_client", "-")).rjust(12)]
        vals += [_fmt_ms(r.get(p)).rjust(12) for p in EXTRA_ROWS]
        lines.append(" ".join(vals))
    lines.append("")
    lines.append("== aggregate (p50 across rounds) ==")
    wall = summary["p50_round_wall_s"]
    lines.append(f"p50 round wall: {_fmt_ms(wall).strip()} ms")
    for p in PHASES + ["other"]:
        v = summary["p50_phase_s"].get(p)
        share = summary["phase_share_of_wall"].get(p)
        pct = f"{share * 100:5.1f}%" if share is not None else "     -"
        lines.append(f"  {p:>16}: {_fmt_ms(v).strip():>9} ms  {pct}")
    for p in EXTRA_ROWS:
        v = summary.get("p50_extra_s", {}).get(p)
        lines.append(f"  {p:>16}: {_fmt_ms(v).strip():>9} ms  "
                     "(informational, not on the critical path)")
    if copies:
        lines.append("")
        lines.append(f"== chaos duplicate copies: {len(copies)} "
                     "(distinct hop stamps per copy) ==")
        for c in copies[:10]:
            lines.append(f"  seq={c['seq']} copy={c['copy']} "
                         f"{c['msg_type']} r{c['round']} org={c['org']}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("run_dir")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged breakdown as JSON")
    ap.add_argument("--perfetto", default="",
                    help="write Chrome trace-event JSON to this path")
    args = ap.parse_args(argv)
    bundle = load_run(args.run_dir)
    rows = build_rounds(bundle)
    summary = summarize(rows)
    copies = chaos_copies(bundle)
    if args.perfetto:
        trace = to_perfetto(bundle, rows)
        with open(args.perfetto, "w") as fh:
            json.dump(trace, fh)
        print(f"perfetto trace: {args.perfetto} "
              f"({len(trace['traceEvents'])} events)", file=sys.stderr)
    if args.json:
        print(json.dumps({"rounds": rows, "summary": summary,
                          "clock_offsets_s": {
                              str(k): v
                              for k, v in bundle["offsets"].items()},
                          "clock_resync_nodes": bundle.get(
                              "clock_resync_nodes", []),
                          "duplicate_copies": copies}, indent=1,
                         default=float))
    else:
        print(render(rows, summary, copies))


if __name__ == "__main__":
    main()
