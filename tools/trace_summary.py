#!/usr/bin/env python
"""Trace-analysis CLI for the observability layer (fedml_tpu/obs).

Reads one or more ``metrics.jsonl`` streams (pass a run dir or the file
itself) and prints, per input:

- the per-round span breakdown (``time_sample/pack/round/eval/agg`` —
  the reference's scattered manual timers, centralized);
- comm byte / message / latency tables per message type
  (``comm.sent_bytes{msg_type=...}`` naming convention);
- the compile-event timeline (``kind=compile`` records +
  ``jax.compiles{fn=...}`` counters — a recompile storm shows up as a
  count climbing with rounds);
- gauges (device-memory high-water etc.).

``--json`` emits one machine-parseable JSON object so BENCH_* rounds
can consume the same numbers the human table shows.  Deliberately
stdlib-only: usable on any checkout with a bare python, no jax import.

Usage:
    python tools/trace_summary.py runs/fedavg-synthetic-20260803-120000
    python tools/trace_summary.py --json run_a run_b
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Dict, List, Optional, Tuple


def parse_metric_key(key: str) -> Tuple[str, Dict[str, str]]:
    """``name{k=v,...}`` → (name, labels) — mirror of obs.telemetry
    (duplicated so this CLI never needs the package importable)."""
    if not key.endswith("}") or "{" not in key:
        return key, {}
    name, _, inner = key.partition("{")
    labels = {}
    for part in inner[:-1].split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


def load_records(path: str) -> List[dict]:
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # partial last line of a crashed run: skip, keep rest
    return records


def percentile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of raw samples (round wall times are a
    handful of exact numbers, not histogram buckets)."""
    if not values:
        return None
    vals = sorted(values)
    idx = min(len(vals) - 1, max(0, int(math.ceil(q * len(vals))) - 1))
    return vals[idx]


def hist_quantile(hist: dict, q: float) -> Optional[float]:
    """Upper-bound estimate of a quantile from the log2 bucket counts."""
    count = hist.get("count", 0)
    if not count:
        return None
    buckets = sorted(
        (float(le), n) for le, n in (hist.get("buckets") or {}).items()
    )
    target = q * count
    seen = 0
    for le, n in buckets:
        seen += n
        if seen >= target:
            return le
    return buckets[-1][0] if buckets else None


def summarize(records: List[dict]) -> dict:
    rounds = [r for r in records if "round" in r and "kind" not in r]
    compiles = [r for r in records if r.get("kind") == "compile"]
    traces = [r for r in records
              if r.get("kind") in ("trace", "trace_rounds")]
    config = next((r for r in records if r.get("kind") == "config"), None)
    telemetry = None
    for r in records:
        if r.get("kind") == "telemetry":
            telemetry = r  # last snapshot wins (counters are cumulative)

    span_keys = sorted({k for r in rounds for k in r if k.startswith("time_")})
    spans = {}
    for k in span_keys:
        vals = [r[k] for r in rounds if isinstance(r.get(k), (int, float))]
        if vals:
            spans[k] = {
                "count": len(vals),
                "total_s": sum(vals),
                "mean_s": sum(vals) / len(vals),
                "max_s": max(vals),
            }

    comm: Dict[str, dict] = {}
    gauges: Dict[str, float] = {}
    compile_counters: Dict[str, float] = {}
    faults: Dict[str, float] = {}
    # fault/degradation series (the chaos layer's accounting): injected
    # faults, what the tolerance layer observed, degraded rounds, and
    # the comm-resilience counters (retries/reconnects/hub drops)
    _FAULT_PREFIXES = ("faults.", "hub.", "rounds.", "robust.")
    _FAULT_COMM = ("comm.unhandled_msgs", "comm.send_retries",
                   "comm.send_failed", "comm.reconnects")
    if telemetry:
        for key, value in (telemetry.get("counters") or {}).items():
            name, labels = parse_metric_key(key)
            if name.startswith(_FAULT_PREFIXES) or name in _FAULT_COMM:
                faults[key] = value
            if name.startswith("comm."):
                row = comm.setdefault(labels.get("msg_type", "?"), {})
                row[name.split(".", 1)[1]] = value
            elif name.startswith("jax."):
                compile_counters[key] = value
        for key, value in (telemetry.get("gauges") or {}).items():
            gauges[key] = value
        for key, hist in (telemetry.get("hists") or {}).items():
            name, labels = parse_metric_key(key)
            if name == "comm.send_latency_s":
                row = comm.setdefault(labels.get("msg_type", "?"), {})
                row["send_latency"] = {
                    "count": hist.get("count"),
                    "mean_s": hist.get("mean"),
                    "p50_le_s": hist_quantile(hist, 0.5),
                    "p99_le_s": hist_quantile(hist, 0.99),
                    "max_s": hist.get("max"),
                }
            elif name == "comm.handle_latency_s":
                row = comm.setdefault(labels.get("msg_type", "?"), {})
                row["handle_latency"] = {
                    "count": hist.get("count"),
                    "mean_s": hist.get("mean"),
                    "max_s": hist.get("max"),
                }
            elif name in ("span.reconnect_s", "span.server_round_s",
                          "robust.upload_norm"):
                # recovery spans: how long nodes were off the hub / how
                # long the server's rounds ran open (deadline closes
                # show up as max ~= round_timeout); robust.upload_norm
                # is the defense layer's delta-norm distribution (an
                # attack shows up as max >> mean)
                faults[key] = {
                    "count": hist.get("count"),
                    "mean_s": hist.get("mean"),
                    "max_s": hist.get("max"),
                }

    # degraded/resume events ride the record stream (kind-tagged)
    fault_events = [r for r in records
                    if r.get("kind") in ("degraded_round", "resume")]

    # per-round defense activity (robust aggregation): round_close
    # events carry a ``defense`` dict when a defense is configured —
    # clipped / outlier-rejected / DP-noised uploads and capped
    # connections, per round, next to the cumulative robust.* counters
    defense_rounds = [
        {"round": r.get("round"), **r["defense"]}
        for r in records
        if r.get("kind") == "round_close" and isinstance(
            r.get("defense"), dict)
    ]

    # round latency from the server round_log close stamps ("t"): the
    # delta between consecutive closes is one round's wall time — the
    # same numbers FEDLAT artifacts and chaos soaks report, so both
    # read this one section
    stamps = [r["t"] for r in rounds
              if isinstance(r.get("t"), (int, float))]
    deltas = [b - a for a, b in zip(stamps, stamps[1:])]
    round_latency = None
    if deltas:
        round_latency = {
            "rounds_timed": len(deltas),
            "p50_s": percentile(deltas, 0.50),
            "p95_s": percentile(deltas, 0.95),
            "max_s": max(deltas),
            "mean_s": sum(deltas) / len(deltas),
        }
    # span.agg_s trend vs realized cohort size: close-time aggregation
    # cost per participant count (the buffered-vs-streaming stall shows
    # up here as mean_agg_s growing with K)
    agg_by_cohort: Dict[int, dict] = {}
    for r in rounds:
        if (isinstance(r.get("time_agg"), (int, float))
                and isinstance(r.get("participants"), list)):
            row = agg_by_cohort.setdefault(
                len(r["participants"]),
                {"count": 0, "total_agg_s": 0.0, "max_agg_s": 0.0})
            row["count"] += 1
            row["total_agg_s"] += r["time_agg"]
            row["max_agg_s"] = max(row["max_agg_s"], r["time_agg"])
    for row in agg_by_cohort.values():
        row["mean_agg_s"] = row["total_agg_s"] / row["count"]

    # transport split (shm lane + delta broadcast, PR 13): how many of
    # the wire bytes rode shared-memory rings vs inline TCP, what the
    # delta broadcast shipped vs fell back on, and the lane's fallback
    # reasons — the raw-speed levers' accounting in one place
    transport = {}
    if telemetry:
        ctr = telemetry.get("counters") or {}
        sent = recv = shm = 0.0
        shm_fallbacks = {}
        delta_fallbacks = {}
        for key, value in ctr.items():
            name, labels = parse_metric_key(key)
            if name == "comm.sent_bytes":
                sent += value
            elif name == "comm.recv_bytes":
                recv += value
            elif name == "comm.shm_bytes":
                shm += value
            elif name == "comm.shm_fallbacks":
                shm_fallbacks[labels.get("reason", "?")] = value
            elif name == "comm.delta_full_fallbacks":
                delta_fallbacks[labels.get("reason", "?")] = value
        total = sent + recv
        if shm or shm_fallbacks or any(
            parse_metric_key(k)[0].startswith("comm.delta_")
            for k in ctr
        ):
            transport = {
                "wire_bytes_total": total,
                "shm_payload_bytes": shm,
                "shm_share": (shm / total) if total else None,
                "tcp_inline_bytes": max(0.0, total - shm),
                "shm_frames": sum(
                    v for k, v in ctr.items()
                    if parse_metric_key(k)[0] == "comm.shm_frames"),
                "shm_fallbacks": shm_fallbacks,
                "delta_bcast_bytes": ctr.get("comm.delta_bcast_bytes", 0),
                "delta_full_fallbacks": delta_fallbacks,
                "delta_resyncs": ctr.get("comm.delta_resyncs", 0),
            }

    # compression ratios: the comm.raw_bytes / comm.compressed_bytes
    # counter pair the compress subsystem records per message type
    compression = {}
    for mt, row in comm.items():
        raw, comp = row.get("raw_bytes"), row.get("compressed_bytes")
        if raw and comp:
            compression[mt] = {
                "raw_bytes": raw,
                "compressed_bytes": comp,
                "ratio": raw / comp,
            }

    return {
        "num_records": len(records),
        "num_rounds": len(rounds),
        "round_latency": round_latency,
        "agg_by_cohort": agg_by_cohort,
        "config": {k: config[k] for k in ("algorithm", "dataset", "model")
                   if config and k in config} if config else {},
        "rounds": rounds,
        "spans": spans,
        "comm": comm,
        "transport": transport,
        "compression": compression,
        "faults": faults,
        "fault_events": fault_events,
        "defense_rounds": defense_rounds,
        "compiles": [
            {k: c.get(k) for k in ("ts", "fn", "signature", "seconds")}
            for c in compiles
        ],
        "compile_counters": compile_counters,
        "gauges": gauges,
        "traces": traces,
    }


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n):,} B"
        n /= 1024.0
    return f"{n:,.1f} GiB"


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    if v >= 1.0:
        return f"{v:,.2f}s"
    if v >= 1e-3:
        return f"{v * 1e3:,.2f}ms"
    return f"{v * 1e6:,.0f}µs"


def render_text(path: str, s: dict, max_round_rows: int = 30) -> None:
    print(f"== {path} ==")
    if s["config"]:
        print("  config: " + ", ".join(f"{k}={v}" for k, v in s["config"].items()))
    print(f"  records: {s['num_records']}  rounds: {s['num_rounds']}")

    rounds = s["rounds"]
    span_keys = sorted(s["spans"])
    if rounds and span_keys:
        print("\n  per-round spans:")
        header = "    round  " + "".join(f"{k[5:]:>12}" for k in span_keys)
        print(header)
        shown = rounds if len(rounds) <= max_round_rows else (
            rounds[: max_round_rows // 2] + rounds[-max_round_rows // 2:]
        )
        prev_r = None
        for r in shown:
            if prev_r is not None and r.get("round", 0) > prev_r + 1:
                print("    ...")
            prev_r = r.get("round", 0)
            cells = "".join(
                f"{_fmt_s(r.get(k)) if isinstance(r.get(k), (int, float)) else '-':>12}"
                for k in span_keys
            )
            print(f"    {r.get('round', '?'):>5}  {cells}")
        total = "".join(
            f"{_fmt_s(s['spans'][k]['total_s']):>12}" for k in span_keys
        )
        mean = "".join(
            f"{_fmt_s(s['spans'][k]['mean_s']):>12}" for k in span_keys
        )
        print(f"    total  {total}")
        print(f"    mean   {mean}")

    if s.get("round_latency"):
        rl = s["round_latency"]
        print("\n  round latency (close-to-close wall time, "
              f"{rl['rounds_timed']} rounds):")
        print(f"    p50 {_fmt_s(rl['p50_s'])}  p95 {_fmt_s(rl['p95_s'])}  "
              f"max {_fmt_s(rl['max_s'])}  mean {_fmt_s(rl['mean_s'])}")
    if s.get("agg_by_cohort"):
        print("\n  close-time aggregation vs cohort size:")
        for k in sorted(s["agg_by_cohort"]):
            row = s["agg_by_cohort"][k]
            print(f"    K={k:<4} rounds={row['count']:<4}"
                  f"mean {_fmt_s(row['mean_agg_s'])}  "
                  f"max {_fmt_s(row['max_agg_s'])}")

    if s["comm"]:
        print("\n  comm (per message type):")
        print(f"    {'msg_type':<20}{'sent':>8}{'sent_bytes':>14}"
              f"{'recv':>8}{'recv_bytes':>14}{'send p50':>10}{'send p99':>10}")
        for mt in sorted(s["comm"]):
            row = s["comm"][mt]
            lat = row.get("send_latency") or {}
            print(
                f"    {mt:<20}"
                f"{int(row.get('sent_msgs', 0)):>8}"
                f"{_fmt_bytes(row.get('sent_bytes', 0)):>14}"
                f"{int(row.get('recv_msgs', 0)):>8}"
                f"{_fmt_bytes(row.get('recv_bytes', 0)):>14}"
                f"{_fmt_s(lat.get('p50_le_s')):>10}"
                f"{_fmt_s(lat.get('p99_le_s')):>10}"
            )

    if s.get("transport"):
        t = s["transport"]
        print("\n  transport (shm lane / delta broadcast):")
        share = t.get("shm_share")
        print(f"    wire bytes {_fmt_bytes(t['wire_bytes_total']):>14}  "
              f"shm {_fmt_bytes(t['shm_payload_bytes']):>14}"
              + (f" ({share * 100:.1f}%)" if share is not None else "")
              + f"  inline tcp {_fmt_bytes(t['tcp_inline_bytes']):>14}")
        print(f"    shm frames {int(t.get('shm_frames', 0))}"
              + (f"  fallbacks {t['shm_fallbacks']}"
                 if t.get("shm_fallbacks") else ""))
        if t.get("delta_bcast_bytes") or t.get("delta_full_fallbacks") \
                or t.get("delta_resyncs"):
            print(f"    delta bcast {_fmt_bytes(t['delta_bcast_bytes'])}"
                  f"  full fallbacks {t.get('delta_full_fallbacks') or {}}"
                  f"  resyncs {int(t.get('delta_resyncs', 0))}")

    if s.get("compression"):
        print("\n  compression (per message type):")
        for mt in sorted(s["compression"]):
            row = s["compression"][mt]
            print(f"    {mt:<20}raw {_fmt_bytes(row['raw_bytes']):>14}"
                  f"  wire {_fmt_bytes(row['compressed_bytes']):>14}"
                  f"  ratio {row['ratio']:>6.2f}x")

    if s["compiles"] or s["compile_counters"]:
        print("\n  compile events:")
        for c in s["compiles"]:
            print(f"    ts={c.get('ts', 0):.3f}  fn={c.get('fn')}  "
                  f"signature#{c.get('signature')}  {_fmt_s(c.get('seconds'))}")
        for key in sorted(s["compile_counters"]):
            print(f"    {key} = {s['compile_counters'][key]:g}")

    if s.get("faults") or s.get("fault_events"):
        print("\n  faults / degradation:")
        for key in sorted(s.get("faults") or {}):
            v = s["faults"][key]
            if isinstance(v, dict):
                # robust.upload_norm is a unitless L2 norm, not seconds
                fmt = ((lambda x: "-" if x is None else f"{x:g}")
                       if "upload_norm" in key else _fmt_s)
                print(f"    {key}: count={v.get('count')} "
                      f"mean={fmt(v.get('mean_s'))} "
                      f"max={fmt(v.get('max_s'))}")
            else:
                print(f"    {key} = {v:g}")
        for ev in s.get("fault_events") or []:
            extra = {k: v for k, v in ev.items() if k not in ("kind", "ts")}
            print(f"    event {ev.get('kind')}: {extra}")

    if s.get("defense_rounds"):
        print("\n  robust aggregation (per round):")
        print("    round  clipped  outliers  dp_noised  capped_conns")
        for d in s["defense_rounds"]:
            print(f"    {str(d.get('round')):<6} {d.get('clipped', 0):<8} "
                  f"{d.get('outliers', 0):<9} {d.get('dp_noised', 0):<10} "
                  f"{d.get('capped_conns', 0)}"
                  + ("  CAP-INFEASIBLE" if d.get("cap_infeasible") else ""))

    if s["gauges"]:
        print("\n  gauges:")
        for key in sorted(s["gauges"]):
            v = s["gauges"][key]
            shown = _fmt_bytes(v) if "bytes" in key else f"{v:g}"
            print(f"    {key} = {shown}")

    if s["traces"]:
        print("\n  profiler traces:")
        for t in s["traces"]:
            extra = f"  round_s={t['round_s']}" if "round_s" in t else ""
            print(f"    {t.get('trace_dir')}{extra}")
    print()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("inputs", nargs="+",
                   help="run dir(s) containing metrics.jsonl, or file paths")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-parseable output (one object, keyed by input)")
    args = p.parse_args(argv)

    out = {}
    errors = 0
    for path in args.inputs:
        try:
            records = load_records(path)
        except OSError as e:
            print(f"error: {path}: {e}", file=sys.stderr)
            errors += 1
            continue
        out[path] = summarize(records)

    if args.as_json:
        # strict JSON for machine consumers: python's json would emit
        # bare Infinity/NaN tokens, which most parsers reject
        def _clean(v):
            if isinstance(v, dict):
                return {k: _clean(x) for k, x in v.items()}
            if isinstance(v, list):
                return [_clean(x) for x in v]
            if isinstance(v, float) and not math.isfinite(v):
                return None
            return v

        print(json.dumps(_clean(out), default=str))
    else:
        for path, s in out.items():
            render_text(path, s)
    return 2 if errors else 0  # partial failure is failure (BENCH harnesses)


if __name__ == "__main__":
    sys.exit(main())
