#!/usr/bin/env python
"""Striped-fan-out + decode/fold-pipeline evidence run → FEDLAT_r09.json.

FEDTRACE_r08 attributed the 32-client p50 regression to the hub
sender-pool broadcast queue: ``bcast_queue`` 10.6 → 436.7 ms (62% of
the 0.702 s round wall) while client compute DROPPED — the fan-out
wall.  ISSUE 8 attacks it with striped/paced multicast (hub splits the
payload into crc'd stripes; every receiver's stripe 0 is head-started
ahead of any tail, tails drain with per-visit locality) plus an
off-reader-thread decode/fold pipeline and double-buffered encode.
This runner measures all of it at 32 clients on the r8 protocol.

Arms (all on THIS commit, FEDLAT_r07/FEDTRACE_r08 configuration:
``logistic_regression(--input-dim 131072, 2)`` = 1.05 MB fp32 model,
``--train-samples 16`` comm-dominant regime, codec off, tracing ON for
every arm so per-phase hub-clock breakdowns exist and the tracing cost
— measured ≤3% in r8 — cancels out of every comparison):

    striped   fast hotpath, --fanout striped (the new default)
    whole     fast hotpath, --fanout whole   (PR-5 whole-frame mcast)
    legacy    --hotpath legacy               (per-node unicast, buffered
              agg, serial decode — the pre-PR-5 baseline)

Method (the r8 notes, verbatim): ``--reps`` interleaved repetitions in
palindrome order (S,W,L,L,W,S — cancels linear drift), a process
barrier + settle sleep between runs, verdict on the MEDIAN of per-rep
p50s (the box's round wall is bistable under 32-way oversubscription).

Pre-declared thresholds (32 clients):

- ``bcast_queue`` p50 (striped, merged timeline) ≤ 436.7/4 ms — the
  ≥4x reduction of the r8-measured wall (the same-session whole arm is
  reported alongside as the controlled same-commit reference);
- fast-path parity: striped p50 round wall ≤ legacy p50 (erasing the
  PR-5 ~12% regression on this 2-core box);
- decode stall: striped timeline p50(decode_wait) + p50(decode_fold)
  ≤ 5 ms (from 2.4 ms fold + serial decode pre-pipeline).

Usage: python tools/fed_stripe_run.py [--clients 32] [--rounds 9]
       [--reps 2] [--out FEDLAT_r09.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import fed_timeline  # noqa: E402
from tools.trace_summary import percentile  # noqa: E402

R8_BCAST_QUEUE_S = 0.4367  # FEDTRACE_r08 32-client attribution


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--clients", type=int, default=32)
    p.add_argument("--rounds", type=int, default=9)
    p.add_argument("--input-dim", type=int, default=131072)
    p.add_argument("--train-samples", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--round-timeout", type=float, default=180.0)
    p.add_argument("--reps", type=int, default=2,
                   help="palindrome-interleaved repetitions per arm")
    p.add_argument("--out", default="FEDLAT_r09.json")
    args = p.parse_args()

    import numpy as np

    from fedml_tpu.experiments.distributed_fedavg import launch

    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["XLA_FLAGS"] = ""
    log_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "logs")
    os.makedirs(log_dir, exist_ok=True)

    ARMS = {
        "striped": {"hotpath": "fast", "fanout": "striped"},
        "whole": {"hotpath": "fast", "fanout": "whole"},
        "legacy": {"hotpath": "legacy", "fanout": "whole"},
    }

    def barrier(settle: float = 3.0):
        """No federation process from a previous run may overlap the
        next measurement (the r8 contamination lesson)."""
        deadline = time.time() + 60.0
        out = ""
        while time.time() < deadline:
            out = subprocess.run(
                ["pgrep", "-f", "fedml_tpu.experiments.distributed_fedavg"],
                capture_output=True, text=True,
            ).stdout.strip()
            if not out:
                break
            time.sleep(1.0)
        else:
            print(f"WARNING: stray federation processes survive the "
                  f"barrier: {out!r}", file=sys.stderr)
        time.sleep(settle)

    def run_one(arm: str, rep: int) -> dict:
        tag = f"{arm}_r{rep}"
        run_dir = f"/tmp/fedlat9_{tag}"
        shutil.rmtree(run_dir, ignore_errors=True)
        barrier()
        info: dict = {}
        t0 = time.time()
        rc = launch(
            num_clients=args.clients, rounds=args.rounds, seed=args.seed,
            batch_size=args.batch_size, out_path=f"/tmp/fedlat9_{tag}.npz",
            round_timeout=args.round_timeout,
            codec="none", wire=2, input_dim=args.input_dim,
            train_samples=args.train_samples,
            run_dir=run_dir, trace=True,
            info=info, env=env, server_env=env,
            timeout=600.0 + args.rounds * args.round_timeout,
            **ARMS[arm],
        )
        if rc != 0:
            raise SystemExit(f"{tag}: server subprocess failed rc={rc}")
        wall = round(time.time() - t0, 1)
        z = np.load(f"/tmp/fedlat9_{tag}.npz")
        round_log = json.loads(str(z["round_log"]))
        stamps = [r["t"] for r in round_log
                  if isinstance(r.get("t"), (int, float))]
        deltas = [round(b - a, 4) for a, b in zip(stamps, stamps[1:])]
        return {
            "arm": arm, "rep": rep, "wall_s": wall, "run_dir": run_dir,
            "rounds": info.get("rounds"),
            "hub_stats": info.get("hub_stats") or {},
            "round_wall_s": {
                "samples": deltas,
                "p50": percentile(deltas, 0.50),
                "p95": percentile(deltas, 0.95),
            },
        }

    # palindrome interleave over the 3 arms: S,W,L,L,W,S,S,W,L,...
    order = []
    names = list(ARMS)
    for i in range(args.reps):
        seq = names if i % 2 == 0 else names[::-1]
        order += [(a, i) for a in seq]
    reps = {a: [] for a in ARMS}
    for arm, i in order:
        reps[arm].append(run_one(arm, i))

    def breakdown(run_dir):
        bundle = fed_timeline.load_run(run_dir)
        rows = fed_timeline.build_rounds(bundle)
        return fed_timeline.summarize(rows)

    arms_out = {}
    summaries = {}
    for arm, rs in reps.items():
        per_rep_p50 = [r["round_wall_s"]["p50"] for r in rs]
        med = percentile(per_rep_p50, 0.5)
        # breakdown from the median-p50 rep (not rep 0 — the bistable
        # scheduling mode may have caught it)
        rep_med = min(rs, key=lambda r: abs(r["round_wall_s"]["p50"] - med))
        summaries[arm] = breakdown(rep_med["run_dir"])
        arms_out[arm] = {
            "reps": len(rs),
            "per_rep_p50": per_rep_p50,
            "per_rep_wall_s": [r["wall_s"] for r in rs],
            "p50_median_of_reps": med,
            "hub_stats_last": rs[-1]["hub_stats"],
            "breakdown_summary": summaries[arm],
        }

    ph = {a: summaries[a]["p50_phase_s"] for a in summaries}
    bq_striped = ph["striped"].get("bcast_queue")
    bq_whole = ph["whole"].get("bcast_queue")
    decode_stall = sum(ph["striped"].get(k) or 0.0
                       for k in ("decode_wait", "decode_fold"))
    p50_striped = arms_out["striped"]["p50_median_of_reps"]
    p50_legacy = arms_out["legacy"]["p50_median_of_reps"]
    p50_whole = arms_out["whole"]["p50_median_of_reps"]

    verdict = {
        "bcast_queue_p50_s": {
            "striped": bq_striped, "whole_same_commit": bq_whole,
            "r08_reference": R8_BCAST_QUEUE_S,
            "reduction_vs_r08": (round(R8_BCAST_QUEUE_S / bq_striped, 2)
                                 if bq_striped else None),
            "ok": bool(bq_striped is not None
                       and bq_striped <= R8_BCAST_QUEUE_S / 4),
        },
        "fast_path_parity": {
            "striped_p50": p50_striped, "legacy_p50": p50_legacy,
            "whole_p50": p50_whole,
            "striped_vs_legacy": (round(p50_striped / p50_legacy, 4)
                                  if p50_legacy else None),
            "ok": bool(p50_striped is not None and p50_legacy is not None
                       and p50_striped <= p50_legacy),
        },
        "decode_stall": {
            "p50_decode_wait_plus_fold_s": round(decode_stall, 6),
            "ok": bool(decode_stall <= 0.005),
        },
    }

    artifact = {
        "experiment": (
            f"striped/paced hub fan-out + off-thread decode/fold pipeline "
            f"A/B at {args.clients} clients on the real TCP hub "
            f"(FEDTRACE_r08 config: logistic_regression({args.input_dim}, 2)"
            f" = {(args.input_dim * 2 + 2) * 4 / 1e6:.2f} MB fp32 model, "
            f"--train-samples {args.train_samples} comm-dominant, codec "
            f"off, {args.rounds} rounds, tracing ON in every arm).  "
            f"{args.reps} palindrome-interleaved reps per arm, process "
            f"barrier + settle between runs, verdicts on the median of "
            f"per-rep p50s (r8 method notes)."
        ),
        "thresholds_pre_declared": {
            "bcast_queue_p50_max_s": R8_BCAST_QUEUE_S / 4,
            "fast_p50_max_ratio_vs_legacy": 1.0,
            "decode_stall_max_s": 0.005,
        },
        "arms": arms_out,
        "verdict": verdict,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1, default=float)
    print(json.dumps({"out": args.out,
                      "bcast_queue_striped_ms":
                          round(bq_striped * 1e3, 2) if bq_striped else None,
                      "bcast_queue_whole_ms":
                          round(bq_whole * 1e3, 2) if bq_whole else None,
                      "p50": {"striped": p50_striped, "whole": p50_whole,
                              "legacy": p50_legacy},
                      "decode_stall_ms": round(decode_stall * 1e3, 3),
                      "ok": {k: v["ok"] for k, v in verdict.items()}}))
    if not all(v["ok"] for v in verdict.values()):
        raise SystemExit("FEDLAT_r09 verdict FAILED")


if __name__ == "__main__":
    main()
