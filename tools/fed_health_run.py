#!/usr/bin/env python
"""FEDHEALTH campaign: the stats plane at scale → ``FEDHEALTH_r11.json``.

A FEDSCALE-style campaign (the 10k-virtual-client topology from
``tools/fed_scale_run.py``: M muxer processes over M hub connections)
with the in-band stats plane under test.  Pre-declared bars:

1. the stats-plane-ON arm completes all rounds NaN-free;
2. hub-ingested telemetry streams == number of CONNECTIONS (muxers),
   not clients — the O(connections) cost model (10k clients → M
   digest streams);
3. ON-arm p50 round wall within 3% of the OFF arm (the PR-6 tracing
   overhead bar), ABBA-interleaved reps, verdict = median of per-rep
   p50s;
4. the written ``slo_report.json``'s p50/p99 round-wall percentiles
   (log2-bucket upper bounds from the merged histograms) agree with
   ``tools/fed_timeline.py``'s post-hoc exact numbers within ONE log2
   bucket.

Usage:
    python tools/fed_health_run.py --clients 10000 --muxers 4 \
        --out FEDHEALTH_r11.json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.fed_scale_run import _barrier, run_scale_federation  # noqa: E402
from tools.trace_summary import percentile  # noqa: E402


def _log2_bucket(x):
    """The log2 bucket index a value lands in (the telemetry
    histogram's bucketing: upper bound 2**ceil(log2(x)))."""
    if x is None or x <= 0:
        return None
    return int(math.ceil(math.log2(x)))


def _posthoc_walls(run_dir: str):
    """Exact per-round walls from the merged per-process metrics files
    (``fed_timeline``'s round rows — the post-hoc surface the in-band
    percentiles must agree with)."""
    from tools.fed_timeline import build_rounds, load_run

    rows = build_rounds(load_run(run_dir))
    walls = [r["wall_s"] for r in rows if r.get("wall_s") is not None]
    return {
        "rounds": len(walls),
        "p50": percentile(walls, 0.5),
        "p99": percentile(walls, 0.99),
        "samples": [round(w, 4) for w in walls],
    }


def one_arm(tag: str, args, stats_on: bool, run_dir: str = "") -> dict:
    _barrier()
    print(f"== {tag}: {args.clients} virtual clients on {args.muxers} "
          f"muxers, stats plane {'ON' if stats_on else 'OFF'} ==",
          flush=True)
    flags = ["--stats-plane", "on" if stats_on else "off",
             "--report-interval", str(args.report_interval)]
    if stats_on and args.slo:
        flags += ["--slo", args.slo]
    info: dict = {}
    rec = run_scale_federation(
        args.clients, args.muxers, args.rounds, seed=args.seed,
        batch_size=args.batch_size, round_timeout=args.round_timeout,
        timeout=args.timeout, extra_flags=flags, run_dir=run_dir,
        info=info)
    rec["tag"] = tag
    rec["stats_plane"] = info.get("stats_plane") or {}
    rec["run_dir"] = run_dir
    print(json.dumps({k: rec[k] for k in
                      ("tag", "rc", "rounds", "nan_free", "wall_s",
                       "round_wall_s", "stats_plane")}), flush=True)
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="FEDHEALTH_r11.json")
    p.add_argument("--clients", type=int, default=10000)
    p.add_argument("--muxers", type=int, default=4)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--reps", type=int, default=2,
                   help="ABBA-interleaved reps per arm")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--round-timeout", type=float, default=600.0)
    p.add_argument("--timeout", type=float, default=3600.0)
    p.add_argument("--report-interval", type=float, default=1.0)
    p.add_argument("--slo", default=json.dumps(
        {"p99_round_wall_s": 60.0, "max_corrupt_uploads": 0,
         "min_participation": 0.5}),
        help="SLO spec JSON shipped to the server (the campaign's "
             "declared objectives; generous walls — the bar here is "
             "agreement + overhead, not a latency gate)")
    args = p.parse_args(argv)

    on_runs, off_runs = [], []
    report = None
    posthoc = None
    status_seen = False
    for rep in range(args.reps):
        # ABBA: adjacent pairs share box state so slow drift cancels
        order = [True, False] if rep % 2 == 0 else [False, True]
        for stats_on in order:
            run_dir = ""
            if stats_on:
                run_dir = tempfile.mkdtemp(prefix="fedhealth_")
            rec = one_arm(
                f"{'on' if stats_on else 'off'}_r{rep}", args, stats_on,
                run_dir)
            (on_runs if stats_on else off_runs).append(rec)
            if stats_on and run_dir:
                status_seen = status_seen or os.path.exists(
                    os.path.join(run_dir, "status.json"))
                rp = os.path.join(run_dir, "slo_report.json")
                if os.path.exists(rp):
                    with open(rp) as fh:
                        report = json.load(fh)
                    rec["slo_report_path"] = rp
                    try:
                        posthoc = _posthoc_walls(run_dir)
                    except SystemExit as e:
                        posthoc = {"error": str(e)}
                    rec["posthoc"] = posthoc

    def med_p50(runs):
        return percentile(
            [r["round_wall_s"]["p50"] for r in runs
             if r["round_wall_s"]["p50"] is not None], 0.5)

    p50_on, p50_off = med_p50(on_runs), med_p50(off_runs)
    overhead = (p50_on / p50_off) if (p50_on and p50_off) else None
    slo_obs = ((report or {}).get("observed") or {}).get(
        "round_wall_s") or {}
    slo_p50, slo_p99 = slo_obs.get("p50"), slo_obs.get("p99")
    ph_p50 = (posthoc or {}).get("p50")
    ph_p99 = (posthoc or {}).get("p99")

    def bucket_agrees(in_band, exact):
        if in_band is None or exact is None:
            return None
        return abs(_log2_bucket(in_band) - _log2_bucket(exact)) <= 1

    streams = [r["stats_plane"].get("streams_remote")
               for r in on_runs if r.get("stats_plane")]
    checks = {
        "on_arm_complete_nan_free": all(
            r["rc"] == 0 and r["nan_free"] and r["rounds"] >= args.rounds
            for r in on_runs),
        "streams_eq_connections": bool(streams) and all(
            s == args.muxers for s in streams),
        # one-sided overhead bar (the PR-6 tracing convention): the ON
        # arm may not be >3% SLOWER; measuring faster is box noise in
        # the plane's favor, not a failure
        "p50_within_3pct": overhead is not None and overhead <= 1.03,
        "slo_p50_within_one_log2_bucket": bucket_agrees(slo_p50, ph_p50),
        "slo_p99_within_one_log2_bucket": bucket_agrees(slo_p99, ph_p99),
        "status_json_written": status_seen,
        "slo_report_written": report is not None,
    }
    artifact = {
        "experiment": (
            "in-band stats plane at scale: mergeable digest streams + SLO "
            "engine on the 10k-virtual-client muxed topology; overhead A/B "
            "(stats on/off, ABBA reps, median of per-rep p50s) and "
            "in-band-vs-post-hoc percentile agreement"
        ),
        "config": {
            "clients": args.clients, "muxers": args.muxers,
            "rounds": args.rounds, "reps": args.reps,
            "report_interval_s": args.report_interval,
            "slo_spec": json.loads(args.slo) if args.slo else None,
            "protocol": "ABBA interleaved, process barrier + settle, "
                        "verdict = median of per-rep p50s (PR-6/PR-10)",
        },
        "generated_unix": round(time.time(), 1),
        "arms": {"stats_on": on_runs, "stats_off": off_runs},
        "slo_report_final": report,
        "posthoc_fed_timeline": posthoc,
        "thresholds_pre_declared": {
            "overhead_p50_max": 1.03,
            "streams": "== muxer connections, not clients",
            "percentile_agreement": "within one log2 bucket of "
                                    "fed_timeline's exact post-hoc p50/p99",
        },
        "verdict": {
            "p50_on": p50_on,
            "p50_off": p50_off,
            "overhead_ratio": (round(overhead, 4)
                               if overhead is not None else None),
            "streams": streams[0] if streams else None,
            "slo_p50": slo_p50,
            "slo_p99": slo_p99,
            "posthoc_p50": ph_p50,
            "posthoc_p99": ph_p99,
            "checks": checks,
            "ok": all(bool(v) for v in checks.values()),
        },
    }
    with open(args.out, "w") as fh:
        json.dump(artifact, fh, indent=1, default=float)
    print(json.dumps({"out": args.out, "verdict": artifact["verdict"]},
                     default=float))
    return 0 if artifact["verdict"]["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
