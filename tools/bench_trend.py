#!/usr/bin/env python
"""Benchmark trajectory: one trend table over every checked-in artifact.

The repo accumulates measurement artifacts PR after PR (BENCH_*,
FEDLAT_*, FEDSCALE_*, FEDTRACE_*, FAULTS_*, CONVERGENCE_*, COMPRESS_*,
MULTICHIP_*, SCALING_*, FEDERATION_*, FEDHEALTH_*) but until this tool
had zero trajectory tooling — answering "did round-wall p50 regress
since r07?" meant opening five JSON files by hand.  This parses them
all into one table keyed by (round, artifact) with each artifact's
headline numbers, so the trend is a single read — and CI uploads the
JSON form on every run as a downloadable trajectory artifact.

    python tools/bench_trend.py                  # table over the repo root
    python tools/bench_trend.py --json           # machine-readable records
    python tools/bench_trend.py --metric p50     # filter headline keys
    python tools/bench_trend.py --gate           # regression gate (exit 1)

``--gate`` compares each family's NEWEST artifact against the same
family's artifact from the prior round, within a per-family tolerance
(``GATE_RULES``): latency/overhead metrics must not grow past it,
accuracy/survival metrics must not shrink past it, ok-booleans must
not flip false.  Exit 1 on any warn-only regression — committed
measurements from dev machines are review prompts, not build breakers
— but a flipped ok/digest-pin boolean in a correctness family
(``ENFORCED_FAMILIES``: byte-identity pins, not timings) exits 2 and
CI fails the build on it.

Stdlib-only (runs in the CI lint job's bare interpreter).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

PREFIXES = (
    "BENCH_", "FEDLAT_", "FEDSCALE_", "FEDTRACE_", "FEDHEALTH_",
    "FAULTS_", "CONVERGENCE_", "COMPRESS_", "MULTICHIP_", "SCALING_",
    "FEDERATION_", "ROBUST_", "FEDXPORT_", "FEDCHURN_", "FEDFLIGHT_",
    "FEDTREE_", "FEDBUFF_", "FEDTRAFFIC_", "FEDSHARD_", "FEDHUB_",
)

_ROUND_RE = re.compile(r"[_-]r(\d+)")


def _round_of(fname: str):
    m = _ROUND_RE.search(fname)
    return int(m.group(1)) if m else None


def _num(v):
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) \
        else None


def _deep_get(doc, path, default=None):
    cur = doc
    for key in path.split("."):
        if not isinstance(cur, dict) or key not in cur:
            return default
        cur = cur[key]
    return cur


def _first(doc, *paths):
    for p in paths:
        v = _deep_get(doc, p)
        if v is not None:
            return v
    return None


def _convergence_metrics(doc: dict) -> dict:
    out = {}
    arms = doc.get("arms")
    if isinstance(arms, dict):
        for arm, rec in arms.items():
            if isinstance(rec, dict):
                acc = _num(rec.get("final_test_acc") or rec.get("final_acc"))
                if acc is not None:
                    out[f"acc[{arm}]"] = acc
    runs = doc.get("runs")
    if isinstance(runs, dict):
        for arm, rec in runs.items():
            if isinstance(rec, dict):
                acc = _num(rec.get("final_test_acc") or rec.get("final_acc"))
                if acc is not None:
                    out[f"acc[{arm}]"] = acc
    for key in ("final_test_acc", "final_acc"):
        v = _num(doc.get(key))
        if v is not None:
            out["acc"] = v
    rtt = _deep_get(doc, "verdict.rounds_to_target")
    if isinstance(rtt, dict):
        for arm, v in rtt.items():
            if _num(v) is not None:
                out[f"rounds_to_target[{arm}]"] = v
    return out


def _extract(doc: dict, fname: str) -> dict:
    """Headline numbers per artifact family — tolerant by design: an
    extractor that finds nothing leaves an empty metrics dict rather
    than failing the whole table (artifact shapes evolve PR to PR)."""
    out = {}
    if fname.startswith("BENCH_"):
        # three generations of bench artifact shape: headline{}, parsed{},
        # and the bare top-level {metric, value, vs_baseline} form
        for sec in (doc.get("headline"), doc.get("parsed"), doc):
            if isinstance(sec, dict) and _num(sec.get("value")) is not None:
                name = str(sec.get("metric", "value"))
                out[name] = sec["value"]
                if _num(sec.get("vs_baseline")) is not None:
                    out["vs_baseline"] = sec["vs_baseline"]
                break
    elif fname.startswith("FEDLAT_"):
        for arm in ("striped", "whole", "legacy", "fast"):
            v = _num(_first(doc, f"arms.{arm}.p50_median_of_reps",
                            f"arms.{arm}.p50_pooled"))
            if v is not None:
                out[f"p50[{arm}]"] = v
        v = _num(_deep_get(doc, "verdict.bcast_queue_p50_s.striped"))
        if v is not None:
            out["bcast_queue_p50"] = v
        p50s = _deep_get(doc, "verdict.p50_round_wall_s")
        if isinstance(p50s, dict):
            for arm, v in p50s.items():
                if _num(v) is not None and len(out) < 6:
                    out[f"p50[{arm}]"] = v
    elif fname.startswith("FEDSCALE_"):
        out["clients"] = _num(_deep_get(doc, "scale.scale_run.clients"))
        out["scale_p50"] = _num(
            _deep_get(doc, "scale.scale_run.round_wall_s.p50"))
        out["hub_rss_ratio"] = _num(_deep_get(doc, "scale.hub_rss_ratio"))
        for arm in ("mux", "proc_fast", "proc_legacy"):
            v = _num(_deep_get(doc, f"latency_ab.verdict.{arm}_p50"))
            if v is not None:
                out[f"p50[{arm}]"] = v
    elif fname.startswith("FEDHEALTH_"):
        for k in ("p50_on", "p50_off", "overhead_ratio", "streams",
                  "slo_p50", "posthoc_p50"):
            v = _num(_deep_get(doc, f"verdict.{k}"))
            if v is not None:
                out[k] = v
        ok = _deep_get(doc, "verdict.ok")
        if ok is not None:
            out["ok"] = bool(ok)
    elif fname.startswith("FEDTRACE_"):
        for arm in ("off_16", "on_16"):
            v = _num(_first(doc, f"arms.{arm}.p50_median_of_reps",
                            f"arms.{arm}.round_wall_s.p50"))
            if v is not None:
                out[f"p50[{arm}]"] = v
    elif fname.startswith("ROBUST_"):
        for k in ("honest_acc", "undefended_acc_at_30pct",
                  "defended_acc_at_30pct", "latency_ratio",
                  "muxer_defended_acc"):
            v = _num(_deep_get(doc, f"verdict.{k}"))
            if v is not None:
                out[k] = v
        ok = _deep_get(doc, "verdict.ok")
        if ok is not None:
            out["ok"] = bool(ok)
    elif fname.startswith("FEDXPORT_"):
        for arm in ("tcp_full", "shm_full", "tcp_delta", "shm_delta"):
            v = _num(_deep_get(doc, f"ab32.p50_by_arm.{arm}"))
            if v is not None:
                out[f"p50[{arm}]"] = v
        v = _num(_deep_get(doc, "ab32.bcast_bytes_per_round.ratio"))
        if v is not None:
            out["delta_bytes_ratio"] = v
        v = _num(_deep_get(doc, "big256.shm_speedup"))
        if v is not None:
            out["shm_speedup_256"] = v
        for k in ("digest_pins", "ab32", "big256"):
            ok = _deep_get(doc, f"{k}.ok")
            if ok is not None:
                out[f"ok[{k}]"] = bool(ok)
    elif fname.startswith("FEDTREE_"):
        ladder = doc.get("ladder")
        if isinstance(ladder, list) and ladder:
            # headline = the LARGEST ladder point (the scale claim)
            pt = max((p for p in ladder if isinstance(p, dict)),
                     key=lambda p: p.get("clients") or 0, default=None)
            if pt:
                out["clients"] = _num(pt.get("clients"))
                out["root_rss_ratio"] = _num(
                    pt.get("root_rss_ratio_tree_vs_flat"))
                out["p50_factor"] = _num(pt.get("p50_factor_tree_vs_flat"))
                v = _num(_deep_get(pt, "tree.round_wall_s.p50"))
                if v is not None:
                    out["tree_p50"] = v
        ok = _deep_get(doc, "digest_pin.ok")
        if ok is not None:
            out["ok[digest_pin]"] = bool(ok)
        if doc.get("ok") is not None:
            out["ok"] = bool(doc["ok"])
    elif fname.startswith("FEDCHURN_"):
        v = _num(_deep_get(doc, "churn.node_rebinds"))
        if v is not None:
            out["node_rebinds"] = v
        v = _num(_deep_get(doc, "churn.run.hub_peak_rss_mb"))
        if v is not None:
            out["hub_rss_mb"] = v
        ok = _deep_get(doc, "churn.ok")
        if ok is not None:
            out["ok"] = bool(ok)
    elif fname.startswith("FEDFLIGHT_"):
        for k in ("p50_on", "p50_off", "overhead_ratio", "attributed"):
            v = _num(_deep_get(doc, f"verdict.{k}"))
            if v is not None:
                out[k] = v
        ok = _deep_get(doc, "verdict.ok")
        if ok is not None:
            out["ok"] = bool(ok)
    elif fname.startswith("FEDBUFF_"):
        for arm in ("sync", "async"):
            v = _num(_first(doc, f"openloop.{arm}.p99_round_s",
                            f"openloop.{arm}.round_wall_s.p99"))
            if v is not None:
                out[f"p99[{arm}]"] = v
        v = _num(_deep_get(doc, "openloop.p99_factor_sync_over_async"))
        if v is not None:
            out["p99_factor"] = v
        v = _num(_deep_get(doc, "openloop.acc_margin"))
        if v is not None:
            out["acc_margin"] = v
        for k in ("digest_pin", "determinism", "openloop"):
            ok = _deep_get(doc, f"{k}.ok")
            if ok is not None:
                out[f"ok[{k}]"] = bool(ok)
        if doc.get("ok") is not None:
            out["ok"] = bool(doc["ok"])
    elif fname.startswith("FEDTRAFFIC_"):
        for k in ("offline_rounds", "delayed_uploads", "rebinds",
                  "straggler_draws"):
            v = _num(_deep_get(doc, f"traffic.{k}"))
            if v is not None:
                out[k] = v
        ok = _deep_get(doc, "traffic.replay_ok")
        if ok is None:
            ok = doc.get("ok")
        if ok is not None:
            out["ok"] = bool(ok)
    elif fname.startswith("FEDSHARD_"):
        for k in ("coverage", "digest_pins", "mux_pin", "shard_bytes"):
            ok = _deep_get(doc, f"{k}.ok")
            if ok is not None:
                out[f"ok[{k}]"] = bool(ok)
        v = _num(_deep_get(doc, "throughput_256.speedup"))
        if v is not None:
            # trend-only: the 2x bar is a chip claim, recorded honestly
            # as met:false on 1-core boxes (throughput_256.note)
            out["speedup_256"] = v
        v = _num(_deep_get(doc, "coverage.fedllm.leaves_sharded"))
        if v is not None:
            out["fedllm_sharded_leaves"] = v
        if doc.get("ok") is not None:
            out["ok"] = bool(doc["ok"])
    elif fname.startswith("FEDHUB_"):
        for k in ("pins", "threads", "churn", "round_wall", "zero_copy",
                  "chaos"):
            ok = _deep_get(doc, f"{k}.ok")
            if ok is not None:
                out[f"ok[{k}]"] = bool(ok)
        v = _num(_deep_get(doc, "threads.reactor_threads_512"))
        if v is not None:
            out["threads_512"] = v
        v = _num(_deep_get(doc, "round_wall.ratio"))
        if v is not None:
            out["p50_ratio"] = v
        v = _num(_deep_get(doc, "churn.rss_ratio"))
        if v is not None:
            out["rss_ratio"] = v
        v = _num(_deep_get(doc, "zero_copy.zero_copy_forwards"))
        if v is not None:
            out["zero_copy_forwards"] = v
        if doc.get("ok") is not None:
            out["ok"] = bool(doc["ok"])
    elif fname.startswith("FAULTS_"):
        scenarios = doc.get("scenarios")
        if isinstance(scenarios, list):
            out["scenarios"] = len(scenarios)
            out["survived"] = sum(
                1 for s in scenarios if s.get("survived"))
        out["all_nan_free"] = bool(doc.get("all_nan_free"))
    elif fname.startswith("CONVERGENCE_"):
        out.update(_convergence_metrics(doc))
    elif fname.startswith("COMPRESS_"):
        v = _num(_deep_get(doc, "verdict.reduction_ratio"))
        if v is not None:
            out["reduction_ratio"] = v
    elif fname.startswith("MULTICHIP_"):
        out["ok"] = bool(doc.get("ok"))
        if _num(doc.get("n_devices")) is not None:
            out["n_devices"] = doc["n_devices"]
    elif fname.startswith("SCALING_"):
        v = _num(_deep_get(doc, "model.headline.comm_compute_ratio_at_256"))
        if v is not None:
            out["comm_compute_ratio_at_256"] = v
    elif fname.startswith("FEDERATION_"):
        out["wall_s"] = _num(_deep_get(doc, "clean_run.total_wall_s"))
        out["oracle_ok"] = bool(_deep_get(doc, "oracle_parity.ok"))
    return {k: v for k, v in out.items() if v is not None}


def collect(root: str):
    records = []
    for prefix in PREFIXES:
        for path in sorted(glob.glob(os.path.join(root, prefix + "*.json"))):
            fname = os.path.basename(path)
            try:
                with open(path) as fh:
                    doc = json.load(fh)
            except (OSError, json.JSONDecodeError) as e:
                records.append({"artifact": fname, "round": _round_of(fname),
                                "error": f"{type(e).__name__}: {e}",
                                "metrics": {}})
                continue
            if not isinstance(doc, dict):
                continue
            records.append({
                "artifact": fname,
                "round": _round_of(fname),
                "kind": prefix.rstrip("_").lower(),
                "metrics": _extract(doc, fname),
            })
    records.sort(key=lambda r: (r["round"] if r["round"] is not None
                                else -1, r["artifact"]))
    return records


# --gate rules: family prefix -> (metric -> direction, tolerance).
# Directions: "lower" (regression = grew past tol), "higher"
# (regression = shrank past tol), "true" (regression = flipped falsy).
# Metric names ending in "*" match by prefix (per-arm keys vary).
# Only explicitly listed metrics gate — everything else is trend-only
# (ambiguous direction must never fail a build by guesswork).
GATE_RULES = {
    "FEDLAT_": ({"p50[*": "lower"}, 0.15),
    "FEDTRACE_": ({"p50[*": "lower"}, 0.15),
    "FEDSCALE_": ({"scale_p50": "lower", "hub_rss_ratio": "lower"}, 0.15),
    "FEDHEALTH_": ({"overhead_ratio": "lower", "ok": "true"}, 0.10),
    "FEDXPORT_": ({"p50[*": "lower", "delta_bytes_ratio": "lower",
                   "ok[*": "true"}, 0.15),
    "FEDCHURN_": ({"hub_rss_mb": "lower", "ok": "true"}, 0.20),
    "FEDTREE_": ({"root_rss_ratio": "lower", "p50_factor": "lower",
                  "clients": "higher", "ok": "true",
                  "ok[*": "true"}, 0.15),
    "FAULTS_": ({"survived": "higher", "all_nan_free": "true"}, 0.0),
    "ROBUST_": ({"defended_acc_at_30pct": "higher", "ok": "true"}, 0.05),
    "CONVERGENCE_": ({"acc*": "higher"}, 0.05),
    "COMPRESS_": ({"reduction_ratio": "lower"}, 0.10),
    "FEDFLIGHT_": ({"overhead_ratio": "lower",
                    "attributed": "higher", "ok": "true"}, 0.10),
    "FEDBUFF_": ({"p99_factor": "higher", "acc_margin": "higher",
                  "ok": "true", "ok[*": "true"}, 0.15),
    "FEDTRAFFIC_": ({"ok": "true"}, 0.0),
    # speedup_256 stays trend-only: it is a chip bar, honestly missed
    # on 1-core CI boxes (FEDSHARD throughput_256.note)
    "FEDSHARD_": ({"ok": "true", "ok[*": "true",
                   "fedllm_sharded_leaves": "higher"}, 0.0),
    "FEDHUB_": ({"ok": "true", "ok[*": "true", "threads_512": "lower",
                 "p50_ratio": "lower", "rss_ratio": "lower"}, 0.10),
}

# Correctness-ENFORCING families: a flipped ok/digest-pin boolean here
# is a broken byte-identity invariant (the pins re-measure determinism,
# not speed), so the gate exits HARD (2) on it and CI fails the build —
# while latency-family breaches keep exit 1, which CI downgrades to a
# warning (committed measurements from dev machines are review prompts,
# not build breakers).  Only "true"-direction metrics enforce; numeric
# metrics inside these families stay warn-only like everywhere else.
ENFORCED_FAMILIES = {"FEDSHARD_", "FEDBUFF_", "FEDHUB_"}


def _rule_for(metric: str, rules: dict):
    if metric in rules:
        return rules[metric]
    for pat, d in rules.items():
        if pat.endswith("*") and metric.startswith(pat[:-1]):
            return d
    return None


def gate(records):
    """Newest artifact per family vs the SAME family's prior-round
    artifact -> (failures, comparisons).  Families with fewer than two
    rounds of history, unreadable artifacts, and unlisted metrics are
    skipped, never failed."""
    by_family = {}
    for r in records:
        if "error" in r or r.get("round") is None:
            continue
        fam = r.get("kind", "").upper() + "_"
        # same round + family: the lexically last artifact wins (the
        # sort in collect() already ordered them)
        by_family.setdefault(fam, {})[r["round"]] = r
    failures, comparisons = [], []
    for fam, (rules, tol) in sorted(GATE_RULES.items()):
        rounds = sorted(by_family.get(fam, {}))
        if len(rounds) < 2:
            continue
        new = by_family[fam][rounds[-1]]
        old = by_family[fam][rounds[-2]]
        for metric, nv in sorted((new.get("metrics") or {}).items()):
            direction = _rule_for(metric, rules)
            ov = (old.get("metrics") or {}).get(metric)
            if direction is None or ov is None:
                continue
            cmp = {"family": fam.rstrip("_"), "metric": metric,
                   "old": ov, "new": nv, "tolerance": tol,
                   "enforced": (fam in ENFORCED_FAMILIES
                                and direction == "true"),
                   "old_artifact": old["artifact"],
                   "new_artifact": new["artifact"]}
            if direction == "true":
                bad = bool(ov) and not bool(nv)
            elif direction == "lower":
                bad = _num(nv) is not None and _num(ov) is not None \
                    and nv > ov * (1 + tol) + 1e-12
            else:  # "higher"
                bad = _num(nv) is not None and _num(ov) is not None \
                    and nv < ov * (1 - tol) - 1e-12
            cmp["regressed"] = bad
            comparisons.append(cmp)
            if bad:
                failures.append(cmp)
    return failures, comparisons


def _fmt_val(v):
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render(records, metric_filter: str = "") -> str:
    lines = ["round  artifact                                  headline",
             "-" * 100]
    for r in records:
        metrics = r.get("metrics") or {}
        if metric_filter:
            metrics = {k: v for k, v in metrics.items()
                       if metric_filter in k}
            if not metrics:
                continue
        headline = "  ".join(f"{k}={_fmt_val(v)}"
                             for k, v in list(metrics.items())[:6])
        if "error" in r:
            headline = f"UNREADABLE ({r['error']})"
        rnd = r["round"] if r["round"] is not None else "-"
        lines.append(f"{str(rnd):<6} {r['artifact']:<41} {headline}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="artifact directory (default: the repo root)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--out", default="",
                   help="also write the JSON records to this path")
    p.add_argument("--metric", default="",
                   help="filter headline keys by substring (table mode)")
    p.add_argument("--gate", action="store_true",
                   help="newest-vs-prior-round regression gate; exit 1 "
                        "on any per-family tolerance breach")
    args = p.parse_args(argv)
    records = collect(args.dir)
    if not records:
        print(f"no benchmark artifacts under {args.dir!r}", file=sys.stderr)
        return 2
    if args.gate:
        failures, comparisons = gate(records)
        hard = [f for f in failures if f.get("enforced")]
        doc = {"compared": len(comparisons), "regressions": failures,
               "enforced_regressions": hard}
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(doc, fh, indent=1)
        if args.json:
            print(json.dumps(doc, indent=1))
        else:
            for c in comparisons:
                mark = "ok"
                if c["regressed"]:
                    mark = "ENFORCED" if c.get("enforced") else "REGRESSED"
                print(f"{mark:>9}  {c['family']:<12} {c['metric']:<28} "
                      f"{_fmt_val(c['old'])} -> {_fmt_val(c['new'])} "
                      f"(tol {c['tolerance']:.0%}, "
                      f"{c['old_artifact']} -> {c['new_artifact']})")
            print(f"{len(comparisons)} comparisons, "
                  f"{len(failures)} regression(s), "
                  f"{len(hard)} enforced")
        # exit 2 = enforced correctness breach (CI fails the build),
        # exit 1 = warn-only latency breach (CI logs a warning)
        return 2 if hard else (1 if failures else 0)
    doc = {"artifacts": len(records), "records": records}
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(doc, fh, indent=1)
    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        print(render(records, args.metric))
    return 0


if __name__ == "__main__":
    sys.exit(main())
