"""North-star convergence trajectory on CIFAR-shaped synthetic data.

VERDICT r1 #6b: commit accuracy-trajectory evidence toward the north
star (CIFAR-10 + ResNet-56, non-IID LDA a=0.5, 87.12 @ 100 rounds —
``/root/reference/benchmark/README.md:105``).  Real CIFAR-10 cannot be
downloaded in this zero-egress environment, so this runs the EXACT
north-star hyperparameters (10 clients all participating, LDA a=0.5,
SGD lr 1e-3 wd 1e-3, E=20 local epochs, batch 64, 100 rounds — the
reference's cross-silo benchmark row) on CIFAR-shaped synthetic data
(50k train / 10k test, 32x32x3, 10 classes) and records the full
trajectory to ``CONVERGENCE_r02.json``.

The synthetic task's absolute accuracy is not comparable to real
CIFAR-10; what the artifact certifies is that the full north-star
configuration — model, partitioner, cohort, optimizer, mixed precision,
100 federated rounds — runs end-to-end on the TPU chip and the global
model's test accuracy climbs monotonically to near-ceiling.

A second preset, ``--preset mnist_lr``, covers the reference's
cross-DEVICE benchmark row (``benchmark/README.md:12``: MNIST +
LogisticRegression, 1000 clients power-law partitioned, 10 sampled per
round, SGD lr 0.03, E=1, batch 10, >75 acc past 100 rounds) on the
MNIST-shaped synthetic stand-in — the sampled-cohort regime the
north-star preset doesn't touch.

Usage: python tools/convergence_run.py [--preset northstar|mnist_lr]
       [--rounds 100] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))



def write_artifact(out, *, experiment, reference_target, config, t0, hist,
                   extra_traj_keys=()):
    """Shared artifact assembly for every preset (one schema, one writer)."""
    import jax

    evals = [h for h in hist if "test_acc" in h]
    artifact = {
        "experiment": experiment,
        "reference_target": reference_target,
        "config": config,
        "platform": jax.devices()[0].platform,
        "wall_clock_s": round(time.time() - t0, 1),
        "final_test_acc": evals[-1]["test_acc"] if evals else None,
        "trajectory": [
            {"round": h["round"], "test_acc": round(h["test_acc"], 5),
             "test_loss": round(h["test_loss"], 5),
             **{k: round(h.get(k, float("nan")), 5) for k in extra_traj_keys}}
            for h in evals
        ],
    }
    if hist and "train_acc" in hist[-1]:
        artifact["final_train_acc"] = hist[-1]["train_acc"]
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out}: final_test_acc={artifact['final_test_acc']}")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", choices=["northstar", "mnist_lr"],
                   default="northstar")
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--num-train", type=int, default=None)
    p.add_argument("--num-test", type=int, default=None)
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--eval-every", type=int, default=5)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation

    if args.preset == "mnist_lr":
        run_mnist_lr(args)
        return

    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models.resnet import resnet56

    args.num_train = args.num_train or 50000
    args.num_test = args.num_test or 10000
    args.epochs = 20 if args.epochs is None else args.epochs
    args.out = args.out or "CONVERGENCE_r02.json"
    cfg = FedAvgConfig(
        num_clients=10,
        clients_per_round=10,          # all participating (BASELINE.md)
        comm_rounds=args.rounds,
        epochs=args.epochs,            # E=20
        batch_size=64,
        client_optimizer="sgd",
        lr=1e-3,
        weight_decay=1e-3,
        frequency_of_the_test=args.eval_every,
        compute_dtype="bf16",
        seed=0,
    )
    ds = synthetic_classification(
        num_train=args.num_train,
        num_test=args.num_test,
        input_shape=(32, 32, 3),
        num_classes=10,
        num_clients=cfg.num_clients,
        partition="hetero",            # LDA, alpha below
        partition_alpha=0.5,
        seed=0,
        name="cifar10(synthetic-standin)",
    )
    sim = FedAvgSimulation(resnet56(num_classes=10), ds, cfg)

    t0 = time.time()

    def log_fn(m):
        line = {k: round(v, 5) if isinstance(v, float) else v
                for k, v in m.items()}
        line["elapsed_s"] = round(time.time() - t0, 1)
        print(json.dumps(line), flush=True)

    hist = sim.run(log_fn=log_fn)
    write_artifact(
        args.out,
        experiment="north-star convergence (synthetic CIFAR-10 stand-in)",
        reference_target={
            "dataset": "CIFAR-10 (real, unavailable offline)",
            "non_iid_acc": 87.12,
            "rounds": 100,
            "source": "/root/reference/benchmark/README.md:105",
        },
        config={
            "model": "resnet56",
            "clients": cfg.num_clients,
            "clients_per_round": cfg.clients_per_round,
            "partition": "LDA alpha=0.5",
            "optimizer": "sgd",
            "lr": cfg.lr,
            "weight_decay": cfg.weight_decay,
            "local_epochs": cfg.epochs,
            "batch_size": cfg.batch_size,
            "rounds": args.rounds,
            "compute_dtype": "bf16",
            "train_samples": args.num_train,
            "test_samples": args.num_test,
        },
        t0=t0,
        hist=hist,
        extra_traj_keys=("train_acc",),
    )


def run_mnist_lr(args):
    """Cross-device preset: the reference's MNIST + LogisticRegression
    benchmark row (1000 power-law clients, 10 sampled/round, SGD lr
    0.03, E=1, batch 10 — ``benchmark/README.md:12``), on the
    MNIST-shaped synthetic stand-in."""
    if args.num_train is not None or args.num_test is not None:
        raise SystemExit(
            "--num-train/--num-test apply to the northstar preset only "
            "(mnist_lr follows the reference's LEAF sizing)"
        )

    from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation
    from fedml_tpu.data.mnist import load_mnist
    from fedml_tpu.models.linear import logistic_regression

    out = args.out or "CONVERGENCE_r02_mnist_lr.json"
    cfg = FedAvgConfig(
        num_clients=1000,
        clients_per_round=10,
        comm_rounds=args.rounds,
        epochs=1 if args.epochs is None else args.epochs,
        batch_size=10,
        client_optimizer="sgd",
        lr=0.03,
        frequency_of_the_test=args.eval_every,
        seed=0,
    )
    ds = load_mnist(num_clients=1000, partition="power_law")
    sim = FedAvgSimulation(logistic_regression(784, 10), ds, cfg)

    t0 = time.time()

    def log_fn(m):
        if "test_acc" in m:
            print(json.dumps({k: round(v, 5) if isinstance(v, float) else v
                              for k, v in m.items()}), flush=True)

    hist = sim.run(log_fn=log_fn)
    write_artifact(
        out,
        experiment="cross-device convergence (synthetic MNIST stand-in)",
        reference_target={
            "dataset": "MNIST LEAF power-law (real, unavailable offline)",
            "acc": ">75",
            "rounds": ">100",
            "source": "/root/reference/benchmark/README.md:12",
        },
        config={
            "model": "logistic_regression(784, 10)",
            "clients": cfg.num_clients,
            "clients_per_round": cfg.clients_per_round,
            "partition": "power_law",
            "optimizer": "sgd", "lr": cfg.lr,
            "local_epochs": cfg.epochs, "batch_size": cfg.batch_size,
            "rounds": args.rounds,
        },
        t0=t0,
        hist=hist,
    )


if __name__ == "__main__":
    main()
