"""North-star convergence evidence (round 4: the full reference recipe).

VERDICT r3 weak #1: the r3 run omitted the one ingredient of the
reference recipe the repo already shipped — data augmentation — so the
net memorized (train acc 1.0 by round 10) and both runs stalled below
the pre-declared 0.81 target.  The reference's 93.19/87.12 numbers are
trained WITH RandomCrop(32, pad 4) + RandomHorizontalFlip + Cutout(16)
(``/root/reference/fedml_api/data_preprocessing/cifar10/data_loader.py:57-99``).
Round 4 wires the repo's jit-compiled equivalent (``data/augment.py``,
``cifar_augment()``) into the preset — the ONLY change to the r3
configuration — and reports rounds-to-target against the pre-declared
0.9×ceiling target alone (the r3 post-hoc ``relative_target`` is gone).

The r3 fixes this builds on:

- **Hardness**: the synthetic task gets ``label_noise`` η — that
  fraction of train AND test labels flipped to a uniformly random wrong
  class — giving a documented irreducible ceiling ≈ 1−η (a model that
  perfectly learns the clean prototypes scores ≈ 1−η on the noisy test
  set).  Trajectories can no longer saturate at 1.0.
- **IID vs non-IID pair**: the EXACT north-star hyperparameters
  (ResNet-56, 10 clients all participating, SGD lr 1e-3 wd 1e-3, E=20,
  batch 64 — ``/root/reference/benchmark/README.md:105``, 93.19 IID vs
  87.12 non-IID on real CIFAR-10) run twice with ONE flag changed:
  ``partition homo`` (IID) vs ``partition hetero`` LDA α=0.5.  The
  artifact records both trajectories, the fixed-round accuracy gap, and
  rounds-to-target (first round reaching 90% of ceiling) — reproducing
  the reference's ordering (IID ≥ non-IID, fewer rounds to target).
- **Fused driver**: rounds between evals run through
  ``FedAvgSimulation.run_fused`` (``make_multi_round_fn`` chunks — the
  benchmarked fast path, bit-identical to ``run()``), so
  wall-clock/round is the framework's real number.

A second preset, ``--preset mnist_lr``, covers the reference's
cross-DEVICE benchmark row (``benchmark/README.md:12``: MNIST + LR,
1000 power-law clients, 10 sampled/round) — the sampled-cohort regime
— on the per-round driver (sampling 10/1000 on a resident 1000-client
block would waste 100× the compute).

Round 5 additions: ``--model mobilenet`` runs the cross-silo recipe on
the reference's second conv family (README.md:108); presets
``emnist_lr`` / ``synthetic_lr`` (the README.md:13-14 linear rows —
synthetic_lr needs NO stand-in, the dataset is the reference's own
generative family) and ``stackoverflow_nwp`` (README.md:57, the
342,477-client population-scale row on a ceiling-calibrated peaked
chain); fed_cifar100 defaults to the full 4000-round horizon.

Usage: python tools/convergence_run.py
       [--preset northstar|mnist_lr|femnist_cnn|shakespeare_rnn|
                 fed_cifar100|stackoverflow_nwp|emnist_lr|synthetic_lr]
       [--model resnet56|mobilenet]
       [--rounds N] [--partitions both|iid|noniid] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def trajectory_rows(hist):
    return [
        {"round": h["round"], "test_acc": round(h["test_acc"], 5),
         "test_loss": round(h["test_loss"], 5),
         **({"train_acc": round(h["train_acc"], 5)} if "train_acc" in h
            else {})}
        for h in hist if "test_acc" in h
    ]


def rounds_to_target(hist, target):
    for h in hist:
        if "test_acc" in h and h["test_acc"] >= target:
            return h["round"]
    return None


def build_comparison(runs):
    """IID vs non-IID comparison: final-acc gap, ordering, and
    rounds-to-target at the single PRE-DECLARED target
    (0.9 × the label-noise ceiling).  The r3 post-hoc relative target is
    deliberately gone: a comparison that moves its own goalposts after
    seeing the data certifies nothing (VERDICT r3 weak #1).

    Mismatched horizons (one arm truncated mid-run — the r5 c100
    non-IID arm stopped at round 53 vs iid's 100) are compared at
    ``min(rounds_completed)``: a final-vs-final gap across different
    horizons silently assumes matched training budgets, so the verdict
    additionally carries ``truncated_arm``/``compared_at_round``
    (ADVICE r5)."""
    a, b = runs["iid"], runs["noniid_lda0.5"]
    if a["final_test_acc"] is None or b["final_test_acc"] is None:
        # a run with per-round rows but no eval rows (crashed before its
        # first eval) must not fabricate a comparison
        return {"incomplete": True,
                "reason": "a run has no evaluation rows; no comparison"}

    def last_eval_round(run):
        traj = run.get("trajectory") or []
        return traj[-1]["round"] if traj else None

    def eval_at_or_before(run, r):
        """Last (round, acc) eval row at or before ``r`` — None when
        the arm has no eval that early (mis-aligned cadences)."""
        rows = [t for t in (run.get("trajectory") or [])
                if t["round"] <= r]
        return (rows[-1]["round"], rows[-1]["test_acc"]) if rows else None

    ra, rb = last_eval_round(a), last_eval_round(b)
    truncation = {}
    acc_a, acc_b = a["final_test_acc"], b["final_test_acc"]
    if ra is not None and rb is not None and ra != rb:
        common = min(ra, rb)
        ea, eb = eval_at_or_before(a, common), eval_at_or_before(b, common)
        if ea is None or eb is None:
            # the longer arm has no eval row inside the truncated
            # horizon: no comparable operating point exists
            return {"incomplete": True,
                    "truncated_arm": "iid" if ra < rb else "noniid",
                    "horizons": {"iid": ra, "noniid": rb},
                    "reason": "an arm has no eval at or before the "
                              "common horizon; no comparison"}
        acc_a, acc_b = ea[1], eb[1]

        def censor(rtt):
            # a crossing AFTER the common horizon used training budget
            # the truncated arm never had — not comparable
            return rtt if (rtt is not None and rtt <= common) else None

        truncation = {
            "truncated_arm": "iid" if ra < rb else "noniid",
            # eval cadences can mis-align: record the ACTUAL round each
            # arm's compared accuracy comes from, not one nominal round
            "compared_at_round": {"iid": ea[0], "noniid": eb[0]},
            "horizons": {"iid": ra, "noniid": rb},
            "note": "arms ran to different horizons; gap/ordering "
                    "computed from each arm's last eval inside the "
                    "common horizon — the longer arm's extra rounds "
                    "are NOT part of this verdict",
            # rounds_to_target under the SAME budget for both arms;
            # the raw full-horizon values stay below for the record
            "rounds_to_target_within_common_horizon": {
                "iid": censor(a["rounds_to_target"]),
                "noniid": censor(b["rounds_to_target"]),
            },
        }
    gap = round(acc_a - acc_b, 5)
    return {
        "final_acc_gap_iid_minus_noniid": gap,
        # a gap within +-0.001 (10 test images) is below the eval's
        # resolution — when both arms sit at the stand-in ceiling that
        # is a TIE (the saturation phenomenon documented in
        # CONVERGENCE_r04_hard.json), not an ordering result
        **({"ordering_matches_reference": gap >= 0}
           if abs(gap) > 0.001 else
           {"ordering_matches_reference": None,
            "tie_within_eval_resolution": True}),
        **truncation,
        "rounds_to_target": {
            "iid": a["rounds_to_target"],
            "noniid": b["rounds_to_target"],
            **({"caveat": "per-arm full-horizon values; see "
                          "rounds_to_target_within_common_horizon for "
                          "the budget-matched comparison"}
               if truncation else {}),
        },
    }


def per_round_seconds(stamps, burst_gap: float = 0.2):
    """Per-round wall seconds from one log's timestamps.

    ``run_fused`` logs a fused chunk's rows in one burst, so rows are
    grouped into bursts (gap < ``burst_gap``) and each burst's wall
    delta is normalized by its row count — a raw per-row delta would
    collapse to ~0 whenever rounds_per_call > 1.  The first burst
    (compile + first chunk) has no predecessor and is excluded, like
    bench warmup.  ``stamps[0]`` must be the 0.0 pre-run marker.
    Returns the unsorted per-round list (callers pool lists across
    resumed-run segments before taking a median)."""
    bursts = []  # (last stamp of burst, rows in burst)
    for s in stamps[1:]:
        if bursts and s - bursts[-1][0] < burst_gap:
            bursts[-1] = (s, bursts[-1][1] + 1)
        else:
            bursts.append((s, 1))
    return [(b[0] - a[0]) / b[1] for a, b in zip(bursts, bursts[1:])]


def median_round_seconds(stamps, burst_gap: float = 0.2):
    """Steady-state per-round seconds: median of ``per_round_seconds``."""
    per_round = sorted(per_round_seconds(stamps, burst_gap))
    return per_round[len(per_round) // 2] if per_round else None


def northstar_metadata(*, noise=1.2, label_noise=0.1, epochs=20,
                       rounds=100, num_train=50000, num_test=10000,
                       augment=True, smooth_sigma=2.0,
                       flip_symmetric=True, model="resnet56",
                       num_classes=10):
    """The artifact's standard header sections (shared with
    tools/convergence_from_log.py so a log-reconstructed artifact has
    the same schema as a tool-written one)."""
    ceiling = 1.0 - label_noise
    # the four cross-silo (model, dataset) rows, benchmark/README.md
    # :105/:106/:108/:109 — (iid acc, non-iid acc, line)
    rows = {("resnet56", 10): (93.19, 87.12, 105),
            ("resnet56", 100): (68.91, 64.70, 106),
            ("mobilenet", 10): (91.12, 86.32, 108),
            ("mobilenet", 100): (55.12, 53.54, 109)}
    iid_acc, noniid_acc, line = rows[(model, num_classes)]
    return {
        "experiment": "north-star convergence, IID vs non-IID pair "
                      f"(synthetic CIFAR-{num_classes} stand-in, "
                      "fused driver)",
        "reference_target": {
            "dataset": f"CIFAR-{num_classes} (real, unavailable "
                       "offline: zero egress)",
            "iid_acc": iid_acc,
            "non_iid_acc": noniid_acc,
            "rounds": 100,
            "source": f"/root/reference/benchmark/README.md:{line}",
            "claim_reproduced": "ordering (IID >= non-IID at fixed "
                                "rounds) + rounds-to-target worsening "
                                "under LDA, on a task with a documented "
                                "accuracy ceiling",
        },
        "hardness": {
            "feature_noise_sigma": noise,
            "label_noise_eta": label_noise,
            "accuracy_ceiling": ceiling,
            "target_for_rounds_to_target": round(0.9 * ceiling, 4),
        },
        "standin_statistics": {
            "prototype_smooth_sigma_px": smooth_sigma,
            "flip_symmetric_signal": flip_symmetric,
            "why": "the two natural-image statistics that make the "
                   "reference's crop/flip/cutout recipe label-preserving; "
                   "with iid-pixel prototypes the augmented run is pinned "
                   "at chance (measured, data/synthetic.py docstring)",
        },
        "config": {
            "model": model, "clients": 10, "clients_per_round": 10,
            "optimizer": "sgd", "lr": 1e-3, "weight_decay": 1e-3,
            "local_epochs": epochs, "batch_size": 64,
            "rounds": rounds, "compute_dtype": "bf16",
            "train_samples": num_train, "test_samples": num_test,
            "augmentation": (
                "crop(pad 4) + horizontal flip + Cutout(16), jit-compiled "
                "inside the local update (data/augment.py cifar_augment; "
                "reference recipe fedml_api/data_preprocessing/cifar10/"
                "data_loader.py:57-99)" if augment else "none"),
            "driver": "FedAvgSimulation.run_fused (make_multi_round_fn "
                      "between evals)",
        },
    }


def write_artifact(out, artifact, summary):
    """One writer for every preset: platform stamp + dump + summary line
    (schema changes happen in ONE place)."""
    import jax

    artifact["platform"] = jax.devices()[0].platform
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out}: {json.dumps(summary)}")


def cleanup_partial(out: str) -> None:
    """Remove the crash-recovery ``.partial`` sidecar once its rows are
    merged into the FINAL artifact: a stale sidecar outliving its merge
    shadows the merged rows for the NEXT resumed session (the repo root
    carried three such orphans before this existed)."""
    partial = out + ".partial"
    if os.path.exists(partial):
        os.remove(partial)


def run_northstar_once(partition, args, log_prefix):
    import jax

    from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation
    from fedml_tpu.core.checkpoint import CheckpointManager
    from fedml_tpu.data.augment import cifar_augment
    from fedml_tpu.data.synthetic import synthetic_classification

    cfg = FedAvgConfig(
        num_clients=10,
        clients_per_round=10,          # all participating (BASELINE.md)
        comm_rounds=args.rounds,
        epochs=args.epochs,            # E=20
        batch_size=64,
        client_optimizer="sgd",
        lr=1e-3,
        weight_decay=1e-3,
        frequency_of_the_test=args.eval_every,
        compute_dtype="bf16",
        seed=0,
    )
    ds = synthetic_classification(
        num_train=args.num_train,
        num_test=args.num_test,
        input_shape=(32, 32, 3),
        num_classes=args.num_classes,
        num_clients=cfg.num_clients,
        partition=partition,           # "homo" = IID, "hetero" = LDA
        partition_alpha=0.5,
        noise=args.noise,
        label_noise=args.label_noise,
        seed=0,
        name=f"cifar{args.num_classes}-standin-{partition}",
        # natural-image statistics (spatial smoothness + flip-invariant
        # class signal) — without them the reference's crop/flip/cutout
        # recipe erases an iid-pixel prototype signal entirely (measured:
        # train acc pinned at 0.11 for 12 rounds on the real chip); see
        # data/synthetic.py
        smooth_sigma=args.smooth_sigma,
        flip_symmetric=bool(args.flip_symmetric),
    )
    if args.model == "mobilenet":
        # reference cross-silo row benchmark/README.md:108 — same
        # recipe/hyperparameters as the ResNet-56 row, MobileNet model
        # (fedml_api/model/cv/mobilenet.py)
        from fedml_tpu.models.mobilenet import mobilenet

        bundle = mobilenet(num_classes=args.num_classes)
    else:
        from fedml_tpu.models.resnet import resnet56

        bundle = resnet56(num_classes=args.num_classes)
    sim = FedAvgSimulation(
        bundle, ds, cfg,
        augment_fn=cifar_augment() if args.augment else None,
    )

    # resume support: the axon tunnel wedges/crashes mid-session (a 2.7 h
    # two-run session died at noniid round 44 this round) — checkpoint
    # the full ServerState at every eval chunk and continue from the
    # latest on restart.  run_fused keys its eval cadence on the ABSOLUTE
    # state.round_idx, so a resumed run evaluates on the same rounds.
    mgr = None
    start_round = 0
    if getattr(args, "checkpoint_dir", ""):
        tag = "iid" if partition == "homo" else "noniid"
        if args.model != "resnet56":
            tag = f"{args.model}_{tag}"
        if args.num_classes != 10:
            tag = f"c{args.num_classes}_{tag}"
        ckdir = os.path.join(args.checkpoint_dir, tag)
        # config stamp: a checkpoint from a DIFFERENT experiment (other
        # noise/seed/epochs — same pytree shapes, so the shape guard
        # can't catch it) must never be silently resumed into this run
        stamp = {"model": args.model, "num_classes": args.num_classes,
                 "noise": args.noise, "label_noise": args.label_noise,
                 "epochs": args.epochs,
                 "num_train": args.num_train, "seed": 0,
                 "augment": bool(args.augment),
                 "smooth_sigma": args.smooth_sigma,
                 "flip_symmetric": bool(args.flip_symmetric)}
        check_config_stamp(ckdir, stamp,
                           legacy_fill={"model": "resnet56",
                                        "num_classes": 10})
        mgr = CheckpointManager(ckdir, max_to_keep=2)
        if mgr.latest_step() is not None:
            sim.state = mgr.restore(like=sim.state)
            start_round = int(sim.state.round_idx)
            if start_round >= args.rounds:
                raise SystemExit(
                    f"checkpoint at round {start_round} >= --rounds "
                    f"{args.rounds}: this run already completed — "
                    "remove the checkpoint dir to start fresh (a "
                    "0-round 'run' would write a degenerate artifact)"
                )
            print(f"{log_prefix} resumed from checkpoint at round "
                  f"{start_round}", flush=True)

    t0 = time.time()
    stamps = [0.0]

    def log_fn(m):
        line = {k: round(v, 5) if isinstance(v, float) else v
                for k, v in m.items()}
        line["elapsed_s"] = round(time.time() - t0, 1)
        stamps.append(time.time() - t0)
        print(f"{log_prefix} {json.dumps(line)}", flush=True)
        if mgr is not None and "test_acc" in m:
            mgr.save(m["round"] + 1, sim.state)

    # default 1 round/call: the ~70 s tunnel execution deadline (see
    # --rounds-per-call help); an explicit value is honored as given
    hist = sim.run_fused(
        rounds=args.rounds - start_round, log_fn=log_fn,
        rounds_per_call=(1 if args.rounds_per_call is None
                         else args.rounds_per_call) or None,
    )
    wall = time.time() - t0
    # median per-round wall = the framework's steady-state number (see
    # median_round_seconds: burst-aware, first/compile burst excluded);
    # the MEAN additionally carries the tunnel's 250-900 s stalls, which
    # are environment, not framework
    return hist, wall, median_round_seconds(stamps), cfg, start_round


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset",
                   choices=["northstar", "mnist_lr", "femnist_cnn",
                            "shakespeare_rnn", "fed_cifar100",
                            "stackoverflow_nwp", "emnist_lr",
                            "synthetic_lr"],
                   default="northstar")
    p.add_argument("--rounds", type=int, default=None,
                   help="horizon (default: northstar 100, mnist_lr 400, "
                   "femnist_cnn 1500, shakespeare_rnn 1200, fed_cifar100 "
                   "4000, stackoverflow_nwp 1500 — the reference rows' "
                   "scales)")
    p.add_argument("--num-train", type=int, default=None)
    p.add_argument("--num-test", type=int, default=None)
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--eval-every", type=int, default=None,
                   help="test-eval cadence (default: northstar 5, "
                   "cross-device presets 25 — chunks end on eval "
                   "rounds, so a tighter cadence also caps the fused "
                   "chunk length)")
    p.add_argument("--noise", type=float, default=1.2,
                   help="feature noise sigma (cluster overlap hardness; "
                   "1.6 measured too hard — the net memorizes instead of "
                   "generalizing; 0.8 saturates — r2's flaw)")
    p.add_argument("--label-noise", type=float, default=None,
                   help="label flip rate eta: test ceiling ~= 1 - eta "
                   "(image presets; default 0.1).  For the text presets "
                   "it is the peaked chain's JUMP RATE: shakespeare "
                   "default 0.1 (ceiling ~0.9); stackoverflow_nwp "
                   "default 0.75, putting the Bayes ceiling (0.2501) "
                   "just above the reference row's absolute 0.195 "
                   "target so rounds-to-target stays meaningful "
                   "(VERDICT r4 weak #2)")
    p.add_argument("--augment", type=int, choices=[0, 1], default=1,
                   help="train with the reference CIFAR recipe "
                   "(crop+flip+cutout, data/augment.py) — the reference "
                   "numbers are produced WITH it; 0 reproduces the r3 "
                   "memorizing configuration")
    p.add_argument("--smooth-sigma", type=float, default=2.0,
                   help="prototype spatial smoothness (px); natural-image "
                   "statistic the augmentation recipe relies on")
    p.add_argument("--flip-symmetric", type=int, choices=[0, 1], default=1,
                   help="flip-invariant class signal (natural-image "
                   "statistic RandomHorizontalFlip relies on)")
    p.add_argument("--partitions", choices=["both", "iid", "noniid"],
                   default="both")
    p.add_argument("--model", choices=["resnet56", "mobilenet"],
                   default="resnet56",
                   help="northstar-preset model: resnet56 (README.md:105) "
                   "or mobilenet (README.md:108 — same recipe, second "
                   "conv family: depthwise-separable MXU profile)")
    p.add_argument("--num-classes", type=int, default=10,
                   choices=[10, 100],
                   help="northstar-preset class count: 10 = the CIFAR-10 "
                   "rows; 100 = the CIFAR-100 cross-silo rows "
                   "(README.md:106/109 — same recipe, 100-way head)")
    p.add_argument("--rounds-per-call", type=int, default=None,
                   help="cap on rounds fused per device call (default: "
                   "northstar 1, cross-device presets 25).  Bisected on "
                   "the axon tunnel: single device executions of ~40 s "
                   "(n=1) and ~66 s complete, ~75 s and ~108 s crash the "
                   "TPU worker ('kernel fault') — the tunnel enforces a "
                   "~70 s execution deadline.  At north-star scale "
                   "(~36 s/round) only n=1 fits; on direct-attached "
                   "hardware raise this (bench.py measures rpc=40 at "
                   "28.4k samples/s in ~22 s calls)")
    p.add_argument("--out", default=None)
    p.add_argument("--checkpoint-dir", default="/tmp/conv_r04_ckpt",
                   help="ServerState checkpoints per eval chunk; on "
                   "restart the run resumes from the latest (tunnel "
                   "wedges kill multi-hour sessions). '' disables")
    args = p.parse_args()

    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    if args.rounds is None:
        args.rounds = {"northstar": 100, "mnist_lr": 400,
                       "femnist_cnn": 1500,
                       "shakespeare_rnn": 1200,
                       "fed_cifar100": 4000,
                       "stackoverflow_nwp": 1500,
                       "emnist_lr": 400, "synthetic_lr": 400}[args.preset]
    if args.eval_every is None:
        args.eval_every = 5 if args.preset == "northstar" else 25
    if args.label_noise is None:
        args.label_noise = 0.75 if args.preset == "stackoverflow_nwp" else 0.1
    if args.preset in ("mnist_lr", "femnist_cnn", "shakespeare_rnn",
                       "fed_cifar100", "stackoverflow_nwp",
                       "emnist_lr", "synthetic_lr"):
        run_cross_device(args)
        return

    args.num_train = args.num_train or 50000
    args.num_test = args.num_test or 10000
    args.epochs = 20 if args.epochs is None else args.epochs
    suffix = ("" if args.model == "resnet56" else f"_{args.model}") + (
        "" if args.num_classes == 10 else f"_c{args.num_classes}")
    args.out = args.out or f"CONVERGENCE_r05{suffix}.json"
    ceiling = 1.0 - args.label_noise
    target = 0.9 * ceiling

    runs = {}
    wants = {"both": ["homo", "hetero"], "iid": ["homo"],
             "noniid": ["hetero"]}[args.partitions]
    for partition in wants:
        tag = "iid" if partition == "homo" else "noniid_lda0.5"
        hist, wall, med_s, cfg, resumed_from = run_northstar_once(
            partition, args, f"[{tag}]"
        )
        evals = [h for h in hist if "test_acc" in h]
        runs[tag] = {
            "partition": ("IID (homo)" if partition == "homo"
                          else "LDA alpha=0.5"),
            "final_test_acc": evals[-1]["test_acc"] if evals else None,
            "rounds_to_target": rounds_to_target(hist, target),
            "wall_clock_s": round(wall, 1),
            # rounds run IN THIS PROCESS (a resumed run does fewer)
            "wall_clock_per_round_s": round(wall / max(1, len(hist)), 2),
            "steady_state_s_per_round_median": (
                round(med_s, 2) if med_s is not None else None
            ),
            # a resumed process only holds post-resume history: the
            # trajectory below starts at this round and rounds_to_target
            # may miss an earlier first-crossing — rebuild the complete
            # artifact from the streamed logs (convergence_from_log.py)
            # when this is set
            **({"resumed_from_round": resumed_from,
                "trajectory_truncated_before_resume": True}
               if resumed_from else {}),
            "trajectory": trajectory_rows(hist),
        }
        # incremental write after EVERY partition: a multi-hour two-run
        # session that dies mid-second-run must not lose the first run's
        # on-chip evidence (the axon tunnel stalls minutes at a time and
        # has crashed workers mid-session)
        write_artifact(args.out + ".partial", {"runs": dict(runs)},
                       {"partial_after": tag})

    artifact = {**northstar_metadata(
        noise=args.noise, label_noise=args.label_noise,
        epochs=args.epochs, rounds=args.rounds,
        num_train=args.num_train, num_test=args.num_test,
        augment=bool(args.augment), smooth_sigma=args.smooth_sigma,
        flip_symmetric=bool(args.flip_symmetric), model=args.model,
        num_classes=args.num_classes,
    ), "runs": runs}
    if {"iid", "noniid_lda0.5"} <= set(runs):
        artifact["comparison"] = build_comparison(runs)
    write_artifact(args.out, artifact, {
        t: {"final": r["final_test_acc"], "rtt": r["rounds_to_target"],
            "s_per_round": r["wall_clock_per_round_s"]}
        for t, r in runs.items()})
    cleanup_partial(args.out)


def run_cross_device(args):
    """Cross-device presets: the reference's sampled-cohort benchmark
    rows (``mnist_lr``: MNIST + LR, 1000 clients, README.md:12;
    ``femnist_cnn``: FEMNIST + CNN_DropOut, 3400 clients, README.md:54)
    on matched synthetic stand-ins, via the ``run_fused_sampled``
    scheduled-cohort fast path."""
    if args.num_train is not None or args.num_test is not None:
        raise SystemExit(
            "--num-train/--num-test apply to the northstar preset only "
            "(the cross-device presets follow the reference's sizing)"
        )
    spec = {"mnist_lr": _mnist_lr_spec,
            "femnist_cnn": _femnist_cnn_spec,
            "shakespeare_rnn": _shakespeare_rnn_spec,
            "fed_cifar100": _fed_cifar100_spec,
            "stackoverflow_nwp": _stackoverflow_nwp_spec,
            "emnist_lr": _emnist_lr_spec,
            "synthetic_lr": _synthetic_lr_spec}[args.preset](args)
    run_sampled_preset(args, spec)


def _mnist_lr_spec(args):
    """Reference row ``benchmark/README.md:12``: MNIST + LR, 1000
    power-law clients, 10/round, SGD lr 0.03, E=1, batch 10,
    >75 @ >100 rounds."""
    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.data.mnist import load_mnist
    from fedml_tpu.models.linear import logistic_regression

    cfg = FedAvgConfig(
        num_clients=1000, clients_per_round=10, comm_rounds=args.rounds,
        epochs=1 if args.epochs is None else args.epochs, batch_size=10,
        client_optimizer="sgd", lr=0.03,
        frequency_of_the_test=args.eval_every, seed=0,
    )
    ds = load_mnist(num_clients=1000, partition="power_law",
                    standin_label_noise=args.label_noise)
    return {
        "tag": "mnist_lr",
        "standin_rev": 4,
        "out": "CONVERGENCE_r05_mnist_lr.json",
        "cfg": cfg,
        "ds": ds,
        "bundle": logistic_regression(784, 10),
        "model_desc": "logistic_regression(784, 10)",
        "experiment": "cross-device convergence (synthetic MNIST stand-in)",
        "reference_target": {
            "dataset": "MNIST LEAF power-law (real, unavailable offline)",
            "acc": ">75", "rounds": ">100",
            "source": "/root/reference/benchmark/README.md:12",
        },
        # ">75" on real MNIST (ceiling ~1.0): ceiling-relative analogue
        "target_frac": 0.75,
    }


def _femnist_cnn_spec(args):
    """Reference row ``benchmark/README.md:54``: Federated EMNIST +
    CNN (2 conv + 2 FC = CNN_DropOut), 3400 power-law clients, 10/round,
    SGD lr 0.1, E=1, batch 20, 84.9 @ >1500 rounds.

    ONE documented deviation: lr .03 instead of the row's .1.  Measured
    on the real chip (r4): lr .1 NaN'd within round 0 on the stand-in
    even at the real dataset's pixel mean/std, because the Gaussian
    stand-in's variance is PATCH-DENSE (every 5×5 conv patch carries
    σ≈.33 signal) while real FEMNIST ink is sparse — most real patches
    are constant background, so real per-patch gradients are far
    smaller at the same global pixel moments.  CPU bisect: epoch-3 mean
    loss 6.12 (diverging) at .1, 1.80 at .03, 1.10 at .01 — .03 is the
    largest stable step.  All other knobs are reference-exact."""
    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.data.emnist import load_femnist
    from fedml_tpu.models.cnn import cnn_dropout

    cfg = FedAvgConfig(
        num_clients=3400, clients_per_round=10, comm_rounds=args.rounds,
        epochs=1 if args.epochs is None else args.epochs, batch_size=20,
        client_optimizer="sgd", lr=0.03,
        frequency_of_the_test=args.eval_every, seed=0,
    )
    ds = load_femnist(num_clients=3400, only_digits=False,
                      standin_label_noise=args.label_noise,
                      standin_max_clients=3400)
    return {
        "standin_rev": 4,
        "deviations": {
            "lr": "0.03 vs the reference row's 0.1 — the row lr "
                  "diverges on the patch-dense Gaussian stand-in "
                  "(measured NaN at round 0 on the real chip even at "
                  "matched pixel mean/std; real FEMNIST ink is sparse, "
                  "so its per-patch gradients are smaller). Largest "
                  "stable step from a CPU bisect (.1 diverges, .03 "
                  "learns)."},
        "tag": "femnist_cnn",
        "out": "CONVERGENCE_r05_femnist_cnn.json",
        "cfg": cfg,
        "ds": ds,
        "bundle": cnn_dropout(only_digits=False),
        "model_desc": "CNN_DropOut (2 conv + 2 FC, 62 classes)",
        "experiment": ("cross-device convergence "
                       "(synthetic FEMNIST stand-in, 3400 clients)"),
        "reference_target": {
            "dataset": "Federated EMNIST TFF h5 (real, unavailable offline)",
            "acc": "84.9", "rounds": ">1500",
            "source": "/root/reference/benchmark/README.md:54",
        },
        # 84.9 on real FEMNIST (ceiling ~1.0): ceiling-relative analogue
        "target_frac": 0.849,
    }


def _shakespeare_rnn_spec(args):
    """Reference row ``benchmark/README.md:56``: Shakespeare (LEAF
    realistic partition) + RNN (2 LSTM + 1 FC), 715 clients, 10/round,
    SGD lr 1.0, E=1, batch 4, 56.9 @ >1200 rounds.  The stand-in is the
    peaked Markov chain (``data/shakespeare.py _synthetic_text``):
    --label-noise is reused as the chain's jump rate η, giving the
    documented Bayes next-char ceiling (1-η) + η/86."""
    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.data.shakespeare import VOCAB_SIZE, load_shakespeare
    from fedml_tpu.models.rnn import rnn_shakespeare

    ds = load_shakespeare(num_clients=715, windows_per_client=64,
                          standin_peak_eta=args.label_noise,
                          standin_test_windows=2000)
    cfg = FedAvgConfig(
        # real LEAF json ignores the stand-in kwargs and brings its own
        # user count — cfg must follow the DATASET or cohort sampling
        # would draw client ids the partition doesn't hold
        num_clients=ds.num_clients, clients_per_round=10,
        comm_rounds=args.rounds,
        epochs=1 if args.epochs is None else args.epochs, batch_size=4,
        client_optimizer="sgd", lr=1.0,
        frequency_of_the_test=args.eval_every, seed=0,
    )
    eta = args.label_noise
    return {
        "tag": "shakespeare_rnn",
        "out": "CONVERGENCE_r05_shakespeare_rnn.json",
        "cfg": cfg,
        "ds": ds,
        "bundle": rnn_shakespeare(),
        "model_desc": "rnn_shakespeare (embed 8 + 2xLSTM(256) + FC, "
                      "90-symbol vocab)",
        "experiment": ("cross-device convergence "
                       "(peaked-Markov Shakespeare stand-in, 715 clients)"),
        "reference_target": {
            "dataset": "Shakespeare LEAF (real, unavailable offline)",
            "acc": "56.9", "rounds": ">1200",
            "source": "/root/reference/benchmark/README.md:56",
        },
        # 56.9 on real Shakespeare (~1.0-style ceiling-relative analogue)
        "target_frac": 0.569,
        # honest stand-in description: shard SIZES are heterogeneous
        # (lognormal, mirroring LEAF), the text DISTRIBUTION is one
        # shared chain — iid across clients, unlike real LEAF roles
        "partition": "lognormal shard sizes, iid shared-chain text "
                     "(stand-in; no distributional heterogeneity)",
        # Bayes next-char accuracy of the peaked chain, NOT 1-eta
        "ceiling": (1.0 - eta) + eta / (VOCAB_SIZE - 4),
        # the --label-noise flag is the chain's JUMP RATE here (no
        # labels are flipped); record it under an accurate key
        "hardness_knob": "standin_markov_jump_eta",
    }


def _fed_cifar100_spec(args):
    """Reference row ``benchmark/README.md:55``: fed_CIFAR100 (TFF
    natural 500-client partition) + ResNet-18-GN, 10/round, SGD lr 0.1,
    E=1, batch 20, 44.7 @ >4000 rounds.  The reference trains on
    normalized 24×24 crops with crop+flip
    (``fed_cifar100/utils.py:8-26``); the stand-in's unit-variance
    features already sit at that scale, and the preset trains with the
    same crop+flip (no cutout — the reference recipe has none here).
    The default horizon is the reference's full 4000 rounds (r4 stopped
    at a declared-truncated 600; r5 resumed that checkpoint to the full
    horizon — the 600→4000 extension is why the config stamp excludes
    --rounds)."""
    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.data.augment import make_image_augment
    from fedml_tpu.data.emnist import load_fed_cifar100
    from fedml_tpu.models.resnet_gn import resnet18_gn

    ds = load_fed_cifar100(num_clients=500,
                           standin_label_noise=args.label_noise,
                           standin_natural_stats=True)
    if "standin" not in ds.name:
        # the real TFF h5 path returns raw 32×32 /255 images; the
        # reference recipe (32→24 crop + Normalize, utils.py:8-26) is
        # applied by the experiments dispatcher, not this preset —
        # training resnet18_gn(24) on un-normalized 32×32 would neither
        # run nor mean anything
        raise SystemExit(
            "real fed_cifar100 h5 detected: this convergence preset "
            "targets the offline stand-in; run the real dataset via "
            "experiments/run.py --dataset fed_cifar100 instead")
    cfg = FedAvgConfig(
        num_clients=ds.num_clients, clients_per_round=10,
        comm_rounds=args.rounds,
        epochs=1 if args.epochs is None else args.epochs, batch_size=20,
        client_optimizer="sgd", lr=0.1,
        frequency_of_the_test=args.eval_every, compute_dtype="bf16",
        seed=0,
    )
    return {
        "tag": "fed_cifar100",
        "out": "CONVERGENCE_r05_fed_cifar100.json",
        "cfg": cfg,
        "ds": ds,
        "bundle": resnet18_gn(num_classes=100, image_size=24),
        "model_desc": "ResNet-18-GN (GroupNorm, 24x24 input)",
        "experiment": ("cross-device convergence "
                       "(synthetic fed-CIFAR100 stand-in, 500 clients)"),
        "reference_target": {
            "dataset": "fed_CIFAR100 TFF h5 (real, unavailable offline)",
            "acc": "44.7", "rounds": ">4000",
            "source": "/root/reference/benchmark/README.md:55",
        },
        "target_frac": 0.447,
        "partition": "homo, 100 samples/client (TFF natural-partition "
                     "analogue)",
        # reference recipe: RandomCrop(24, pad implied by 32->24 crop)
        # + flip + Normalize; the stand-in is generated at 24x24, so
        # crop uses the same pad-4 shift convention as cifar_augment
        "augment_fn": make_image_augment(pad=4, flip=True, cutout=None),
    }


def _emnist_lr_spec(args):
    """Reference row ``benchmark/README.md:13``: Federated EMNIST + LR,
    200 power-law clients, 10/round, SGD lr 0.003, E=1, batch 10,
    10~40 @ >200 rounds.  The row publishes a BAND, not a point: the
    only level it guarantees is the band's floor (10), so
    rounds_to_target pre-declares THAT, and the artifact additionally
    reports where the final accuracy lands relative to the full band.
    Same 62-class FEMNIST stand-in as the femnist_cnn row (rev-4
    mean+std calibration); a linear model on the patch-dense stand-in
    is stable at the reference lr, so no lr deviation is needed."""
    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.data.emnist import load_femnist
    from fedml_tpu.models.linear import logistic_regression

    cfg = FedAvgConfig(
        num_clients=200, clients_per_round=10, comm_rounds=args.rounds,
        epochs=1 if args.epochs is None else args.epochs, batch_size=10,
        client_optimizer="sgd", lr=0.003,
        frequency_of_the_test=args.eval_every, seed=0,
    )
    ds = load_femnist(num_clients=200, only_digits=False,
                      standin_label_noise=args.label_noise,
                      standin_max_clients=200)
    return {
        "tag": "emnist_lr",
        "standin_rev": 4,
        "out": "CONVERGENCE_r05_emnist_lr.json",
        "cfg": cfg,
        "ds": ds,
        "bundle": logistic_regression(28 * 28, 62),
        "model_desc": "logistic_regression(784, 62)",
        "experiment": ("cross-device convergence "
                       "(synthetic FEMNIST stand-in, 200 clients, LR)"),
        "reference_target": {
            "dataset": "Federated EMNIST TFF h5 (real, unavailable "
                       "offline)",
            "acc": "10~40 (band)", "rounds": ">200",
            "source": "/root/reference/benchmark/README.md:13",
        },
        # floor of the published 10~40 band (the level the row
        # guarantees), ceiling-relative analogue; the band's top is
        # recorded so the final accuracy can be read against it
        "target_frac": 0.10,
        "deviations": {
            "target": "the reference publishes a 10~40 BAND; "
                      "rounds_to_target pre-declares its floor (0.10 x "
                      "ceiling). Measured r5: the run passes THROUGH "
                      "the ceiling-relative band (rounds 25-125) and "
                      "keeps climbing to ~0.84 — the linearly-separable "
                      "prototype stand-in cannot reproduce real "
                      "EMNIST's linear-capacity plateau"},
    }


def _synthetic_lr_spec(args):
    """Reference row ``benchmark/README.md:14``: Synthetic(α,β) + LR,
    30 clients, 10/round, SGD lr 0.01, E=1, batch 10, >60 @ >200
    rounds.  UNLIKE the other rows this needs NO stand-in: the
    reference's dataset is itself generated (the LEAF/FedProx
    Synthetic(1,1) process — client-specific softmax weights
    W_k ~ N(u_k, 1), u_k ~ N(0, α); features x ~ N(v_k, diag(j^-1.2)),
    v_k ~ N(B_k, 1), B_k ~ N(0, β); lognormal shard sizes), and
    ``data/synthetic.synthetic_alpha_beta`` implements the same
    generative family — so the run's accuracy is DIRECTLY comparable
    to the published >60 with real distributional heterogeneity
    (every client owns a different W_k)."""
    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.data.synthetic import synthetic_alpha_beta
    from fedml_tpu.models.linear import logistic_regression

    ds = synthetic_alpha_beta(alpha=1.0, beta=1.0, num_clients=30)
    cfg = FedAvgConfig(
        num_clients=30, clients_per_round=10, comm_rounds=args.rounds,
        epochs=1 if args.epochs is None else args.epochs, batch_size=10,
        client_optimizer="sgd", lr=0.01,
        frequency_of_the_test=args.eval_every, seed=0,
    )
    return {
        "tag": "synthetic_lr",
        "out": "CONVERGENCE_r05_synthetic_lr.json",
        "cfg": cfg,
        "ds": ds,
        "bundle": logistic_regression(60, 10),
        "model_desc": "logistic_regression(60, 10)",
        "experiment": ("cross-device convergence (Synthetic(1,1) — the "
                       "reference's own generative dataset family, no "
                       "stand-in)"),
        "reference_target": {
            "dataset": "Synthetic(1,1), LEAF/FedProx generator "
                       "(re-implemented; directly comparable)",
            "acc": ">60", "rounds": ">200",
            "source": "/root/reference/benchmark/README.md:14",
        },
        # absolute: the dataset is the real generative family, ceiling 1.0
        "target_frac": 0.60,
        "ceiling": 1.0,
        "has_target": True,
        "partition": "natural (client-specific W_k; lognormal sizes)",
    }


def _stackoverflow_nwp_spec(args):
    """Reference row ``benchmark/README.md:57``: StackOverflow NWP
    (TFF natural partition, **342,477 clients**) + RNN (1 LSTM(670),
    embed 96), 50/round, SGD lr 10^-0.5, E=1, batch 16,
    19.5 @ >1500 rounds — the one published row that stresses
    cross-device machinery at real population scale: host sampling
    from 342k-client metadata + scheduled-cohort packing
    (VERDICT r4 missing #1).

    Stand-in: the calibrated peaked-Markov methodology
    (``data/stackoverflow._peaked_chain``) with jump rate η = 0.75 by
    default and ZIPF(1.1) jump targets, so the Bayes next-token
    ceiling ≈ 0.2501 sits JUST ABOVE the reference row's 19.5 — the
    pre-declared target is the row's ABSOLUTE accuracy (0.195 ≈ 78% of
    ceiling), keeping rounds-to-target a genuine signal rather than an
    early crossing on a saturating task.  The zipf unigram is the
    learnability-critical refinement: a UNIFORM-unigram chain was
    measured unlearnable at the row's SGD lr (100-round chip pilots:
    loss 9.211→9.207 at lr 10^-0.5, 3x faster but still glacial at
    1.0, NaN at 3.0 — every one of 10k classes needs its own
    averaged-over-clients signal), while real text's zipf head gives
    frequent words many sightings per round, the same head start real
    NWP training has.  Per-token CE/accuracy over all 20 positions
    (the reference NWP convention); the stand-in emits full windows,
    so there are no pad positions to mask."""
    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.data.stackoverflow import load_stackoverflow_nwp
    from fedml_tpu.models.rnn import rnn_stackoverflow

    import resource

    eta = args.label_noise
    t0 = time.time()
    ds = load_stackoverflow_nwp(num_clients=342477,
                                standin_peak_eta=eta)
    gen_s = time.time() - t0
    host_note = {
        "what": "342,477-client population on ONE host: sampling reads "
                "metadata only (host_sample_ids is O(K log N)); the "
                "scheduled-cohort driver ships just the 50-client "
                "cohort block per round",
        "standin_generation_s": round(gen_s, 1),
        "train_array_bytes": int(ds.train_x.nbytes + ds.train_y.nbytes),
        "peak_rss_gb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 1),
        "client_metadata_entries": ds.num_clients,
    }
    cfg = FedAvgConfig(
        num_clients=ds.num_clients, clients_per_round=50,
        comm_rounds=args.rounds,
        epochs=1 if args.epochs is None else args.epochs, batch_size=16,
        client_optimizer="sgd", lr=10 ** -0.5,
        frequency_of_the_test=args.eval_every, seed=0,
    )
    # empirical Bayes ceiling of the generated chain (zipf jumps make
    # the additive eta*E[q(perm(cur))] term chain-dependent); only the
    # stand-in branch sets it — with the real h5 present this preset's
    # absolute-target calibration doesn't apply (same guard as the
    # fed_cifar100 spec's real-data path)
    ceiling = getattr(ds, "standin_bayes_ceiling", None)
    if ceiling is None:
        raise SystemExit(
            "real stackoverflow h5 detected: this convergence preset "
            "targets the calibrated offline stand-in; run the real "
            "dataset via experiments/run.py --dataset stackoverflow_nwp")
    return {
        "tag": "stackoverflow_nwp",
        "out": "CONVERGENCE_r05_stackoverflow_nwp.json",
        "cfg": cfg,
        "ds": ds,
        "bundle": rnn_stackoverflow(),
        "model_desc": "RNNStackOverflow (embed 96 + LSTM(670) + "
                      "2 dense, 10004-way per-token head)",
        "experiment": ("cross-device convergence at population scale "
                       "(peaked-Markov StackOverflow NWP stand-in, "
                       "342,477 clients, 50/round)"),
        "reference_target": {
            "dataset": "StackOverflow NWP TFF h5 (real, unavailable "
                       "offline)",
            "acc": "19.5", "rounds": ">1500",
            "source": "/root/reference/benchmark/README.md:57",
        },
        # ABSOLUTE-target calibration: target = 0.195 exactly (the
        # reference row's number); expressed as a fraction of the
        # chain's Bayes ceiling for the shared target machinery
        "target_frac": 0.195 / ceiling,
        "ceiling": ceiling,
        "partition": "clipped-lognormal shard sizes [16, 512], iid "
                     "shared-chain text (stand-in; no distributional "
                     "heterogeneity)",
        "hardness_knob": "standin_markov_jump_eta",
        "host_note": host_note,
        "deviations": {
            "shard_sizes": "stand-in mean ~130 sequences/client vs the "
                           "real TFF partition's ~397 (135.8M examples "
                           "/ 342,477 users): per-round token volume "
                           "is ~1/3 of the real row's — full scale "
                           "would cost ~13 GB host generation per run"},
    }


def check_config_stamp(ckdir: str, stamp: dict,
                       legacy_fill: dict = None) -> None:
    """One stamp policy for BOTH preset families: the stamp holds every
    knob that changes the training dynamics a checkpoint encodes; the
    horizon (``--rounds``) is deliberately NOT in it — per-round
    randomness is ``fold_in``-keyed on the absolute round index, so a
    state at round R is identical whether the run was launched with
    ``--rounds 600`` or ``4000``, and extending a finished run to a
    longer horizon (fed_cifar100 600→4000) is exactly the resume use
    case.  Stamps written by the pre-r5 code carried a legacy
    ``rounds`` key (dropped — it never affected dynamics) and lacked
    keys later ADDED to the stamp (``legacy_fill`` maps those to the
    value every pre-r5 run implicitly had, e.g. model=resnet56);
    migrated stamps are rewritten in the new format."""
    stamp_path = os.path.join(ckdir, "config_stamp.json")
    os.makedirs(ckdir, exist_ok=True)

    def write_atomic():
        # the tunnel wedging mid-session is this repo's normal failure
        # mode — never truncate a good stamp in place
        with open(stamp_path + ".tmp", "w") as f:
            json.dump(stamp, f)
        os.replace(stamp_path + ".tmp", stamp_path)

    if os.path.exists(stamp_path):
        prior = json.load(open(stamp_path))
        legacy = prior.pop("rounds", None)
        for k, v in (legacy_fill or {}).items():
            if k not in prior:
                prior[k] = v
                legacy = True
        if prior != stamp:
            raise SystemExit(
                f"checkpoint dir {ckdir} holds a run with a different "
                f"config ({prior} != {stamp}); pass --checkpoint-dir "
                "'' or remove the directory")
        if legacy is not None:
            write_atomic()
    else:
        write_atomic()


def run_sampled_preset(args, spec):
    """Shared driver for the sampled-cohort (cross-device) benchmark
    rows: ``run_fused_sampled`` fast path (the host pre-draws each
    chunk's cohorts, one device call per chunk — the per-round dispatch
    loop measured 6.6 s/round through the tunnel, almost all host
    overhead), checkpoint/resume, and a resume-merged streamed
    artifact."""
    from fedml_tpu.algorithms.fedavg import FedAvgSimulation
    from fedml_tpu.core.checkpoint import CheckpointManager

    tag, cfg, ds = spec["tag"], spec["cfg"], spec["ds"]
    out = args.out or spec["out"]
    ceiling = spec.get("ceiling", 1.0 - args.label_noise)
    target = spec["target_frac"] * ceiling
    has_target = spec.get("has_target", False) or "standin" in spec["ds"].name
    sim = FedAvgSimulation(spec["bundle"], ds, cfg,
                           augment_fn=spec.get("augment_fn"))

    # checkpoint/resume mirrors the north-star preset: multi-hundred-
    # round horizons outlive the tunnel's session stability.
    # standin_rev chronicles each PRESET's stand-in DATA changes a
    # same-shape checkpoint can't detect (specs carry their own rev so
    # one dataset's recalibration doesn't invalidate another's
    # checkpoints): mnist/femnist are at rev 4 — 2 = pixel-scale
    # matching, 3 = FEMNIST moved to the raw TFF white-background
    # scale, 4 = mean+std affine matching (match_pixel_moments;
    # variance-only placement of the white-background second moment
    # NaN'd femnist at the reference lr).  A checkpoint trained on
    # differently-scaled gradients must never resume into a rescaled
    # run.  The .partial-merge stamp is the SAME dict (advisor r4:
    # dropping epochs let a stale .partial from a different --epochs
    # merge into a resumed run); stamp policy, incl. why the horizon
    # is excluded, lives in check_config_stamp's docstring.
    stamp = {"label_noise": args.label_noise,
             "epochs": cfg.epochs, "lr": cfg.lr, "seed": 0,
             "standin_rev": spec.get("standin_rev", 1)}
    stamp_for_partial = stamp
    mgr = None
    start_round = 0
    if getattr(args, "checkpoint_dir", ""):
        ckdir = os.path.join(args.checkpoint_dir, tag)
        check_config_stamp(ckdir, stamp)
        mgr = CheckpointManager(ckdir, max_to_keep=2)
        if mgr.latest_step() is not None:
            sim.state = mgr.restore(like=sim.state)
            start_round = int(sim.state.round_idx)
            if start_round >= args.rounds:
                raise SystemExit(
                    f"checkpoint at round {start_round} >= --rounds "
                    f"{args.rounds}: already completed — remove the "
                    "checkpoint dir to start fresh")
            print(f"[{tag}] resumed from checkpoint at round "
                  f"{start_round}", flush=True)

    # resume-correct trajectory: the in-process history only holds
    # post-resume rounds, so eval rows are streamed into a .partial
    # artifact and a resumed session prepends the prior partial's
    # pre-resume rows — rounds_to_target and wall_clock then cover the
    # WHOLE run, not just the surviving session (advisor: a target first
    # crossed before the crash must not be reported as later/None)
    prior_traj: list = []
    prior_wall = 0.0
    if start_round and os.path.exists(out + ".partial"):
        prior = json.load(open(out + ".partial"))
        if prior.get("stamp") == stamp_for_partial:
            prior_traj = [r for r in prior.get("trajectory", [])
                          if r["round"] < start_round]
            prior_wall = prior.get("wall_clock_s", 0.0)
        else:
            # a resumed run whose pre-resume rows are silently dropped
            # mis-reports rounds_to_target (the exact bug the merge
            # exists to fix) — make the skip LOUD (review r5); legacy
            # pre-r5 partials (stamp carried 'rounds', lacked 'epochs')
            # also land here rather than re-opening the epochs hole
            print(f"[{tag}] WARNING: {out}.partial stamp "
                  f"{prior.get('stamp')} != {stamp_for_partial}; "
                  "pre-resume trajectory rows will NOT be merged — "
                  "rounds_to_target/wall_clock cover only this session",
                  flush=True)

    t0 = time.time()

    def merged_traj(hist_now):
        return prior_traj + trajectory_rows(hist_now)

    def log_fn(m):
        if "test_acc" in m:
            line = {k: round(v, 5) if isinstance(v, float) else v
                    for k, v in m.items()}
            line["elapsed_s"] = round(time.time() - t0, 1)
            print(f"[{tag}] {json.dumps(line)}", flush=True)
            # save ONLY when this row is the fused chunk's last round:
            # sim.state already sits at end-of-chunk while log_fn
            # replays the chunk's rows, so labeling that state with an
            # intermediate round would make resume re-apply rounds the
            # state already contains (review r4)
            if mgr is not None and m["round"] + 1 == int(
                sim.state.round_idx
            ):
                mgr.save(m["round"] + 1, sim.state)
            with open(out + ".partial", "w") as f:
                json.dump({"stamp": stamp_for_partial,
                           "trajectory": merged_traj(sim.history),
                           "wall_clock_s": round(
                               prior_wall + time.time() - t0, 1)}, f)

    # fused chunks: default 25 rounds/device-call; an EXPLICIT
    # --rounds-per-call (including 1) is honored as given
    rpc = 25 if args.rounds_per_call is None else args.rounds_per_call
    hist = sim.run_fused_sampled(rounds=args.rounds - start_round,
                                 log_fn=log_fn, rounds_per_call=rpc)
    full_traj = merged_traj(hist)
    artifact = {
        "experiment": spec["experiment"],
        "reference_target": spec["reference_target"],
        "dataset_loaded": ds.name,
        # the noise ceiling exists ONLY for the synthetic stand-in —
        # the loaders never modify real on-disk data, so claiming an
        # irreducible-error ceiling there would misdescribe the run
        **({"hardness": {
                spec.get("hardness_knob",
                         "standin_label_noise"): args.label_noise,
                "accuracy_ceiling": round(ceiling, 4),
                # reference accuracy is on a ~1.0-ceiling real dataset:
                # the ceiling-relative analogue, pre-declared
                "target_for_rounds_to_target": round(target, 4)}}
           if "standin" in ds.name else {}),
        # a preset whose dataset IS the reference's generative family
        # (synthetic_lr) declares its target without a stand-in ceiling
        **({"pre_declared_target": round(target, 4)}
           if has_target and "standin" not in ds.name else {}),
        **({"host_note": spec["host_note"]} if "host_note" in spec else {}),
        "config": {
            "model": spec["model_desc"],
            "clients": cfg.num_clients,
            "clients_per_round": cfg.clients_per_round,
            "partition": spec.get("partition", "power_law"),
            "optimizer": "sgd", "lr": cfg.lr,
            "local_epochs": cfg.epochs, "batch_size": cfg.batch_size,
            "rounds": args.rounds,
            "driver": ("run_fused_sampled (scheduled cohorts, "
                       f"{min(rpc, args.eval_every)} rounds/device call"
                       " — chunks end on eval rounds)"),
            # stand-in-specific departures from the reference row,
            # stated in the artifact itself (not just the code)
            **({"deviations_from_reference_row": spec["deviations"]}
               if "deviations" in spec else {}),
        },
        # merged across crash/resume sessions via the .partial sidecar
        "wall_clock_s": round(prior_wall + time.time() - t0, 1),
        "final_test_acc": (full_traj[-1]["test_acc"] if full_traj else None),
        "rounds_to_target": (rounds_to_target(full_traj, target)
                             if has_target else None),
        **({"resumed_from_round": start_round,
            "pre_resume_rounds_recovered": len(prior_traj)}
           if start_round else {}),
        "trajectory": full_traj,
    }
    write_artifact(out, artifact,
                   {"final_test_acc": artifact["final_test_acc"],
                    "rounds_to_target": artifact["rounds_to_target"]})
    cleanup_partial(out)


if __name__ == "__main__":
    main()
