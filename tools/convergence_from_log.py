"""Reconstruct a CONVERGENCE artifact from a convergence_run.py log.

The north-star pair is a multi-hour, two-run session on a tunnel that
stalls for minutes at a time and has crashed TPU workers mid-session;
``tools/convergence_run.py`` streams every round row to stdout exactly
so the evidence survives the process.  This tool rebuilds the artifact
(trajectories, finals, rounds-to-target, per-round wall stats) from
that log, marking its provenance.

Usage: python tools/convergence_from_log.py LOG [--out FILE]
       [--label-noise 0.1] [--rounds 100]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from convergence_run import (build_comparison,  # noqa: E402
                             median_round_seconds, northstar_metadata,
                             rounds_to_target, trajectory_rows)


def parse_log(path):
    runs = {}
    for line in open(path):
        if not line.startswith("["):
            continue
        tag, _, payload = line.partition(" ")
        tag = tag.strip("[]")
        try:
            row = json.loads(payload)
        except json.JSONDecodeError:
            continue
        runs.setdefault(tag, []).append(row)
    return runs


def pick_runs(per_log):
    """One row-list per tag across logs.  Same-tag rows from DIFFERENT
    logs are never concatenated (each log's elapsed_s restarts at 0, so
    a blind merge corrupts wall-clock, the steady-state median, and
    mixes stale partial rounds with rerun rounds) — the log with the
    most completed rounds wins, with a stderr note."""
    chosen = {}
    for log, runs in per_log:
        for tag, rows in runs.items():
            if tag in chosen and len(chosen[tag][1]) >= len(rows):
                print(f"note: {tag} also in {log} ({len(rows)} rows) — "
                      f"keeping {chosen[tag][0]} "
                      f"({len(chosen[tag][1])} rows)", file=sys.stderr)
                continue
            if tag in chosen:
                print(f"note: {tag} in {chosen[tag][0]} superseded by "
                      f"{log} ({len(rows)} rows)", file=sys.stderr)
            chosen[tag] = (log, rows)
    return {tag: rows for tag, (log, rows) in chosen.items()}


def summarize(rows, target):
    evals = [r for r in rows if "test_acc" in r]
    stamps = [0.0] + [r["elapsed_s"] for r in rows]
    med = median_round_seconds(stamps)
    return {
        "rounds_completed": rows[-1]["round"] + 1 if rows else 0,
        "final_test_acc": evals[-1]["test_acc"] if evals else None,
        "rounds_to_target": rounds_to_target(rows, target),
        "wall_clock_s": stamps[-1] if stamps else None,
        "steady_state_s_per_round_median": (
            round(med, 2) if med is not None else None
        ),
        "trajectory": trajectory_rows(rows),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("logs", nargs="+",
                   help="one or more convergence_run logs; their [tag] "
                   "rows are merged (e.g. an iid log + a noniid rerun "
                   "after a tunnel wedge)")
    p.add_argument("--out", default="CONVERGENCE_r03.json")
    p.add_argument("--label-noise", type=float, default=0.1)
    p.add_argument("--noise", type=float, default=1.2)
    # config-fidelity flags: the reconstructed artifact's config section
    # must describe the run the LOG came from, not the tool defaults
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--num-train", type=int, default=50000)
    p.add_argument("--num-test", type=int, default=10000)
    p.add_argument("--platform", default="tpu")
    args = p.parse_args()

    ceiling = 1.0 - args.label_noise
    target = 0.9 * ceiling
    merged = pick_runs([(log, parse_log(log)) for log in args.logs])
    runs = {tag: summarize(rows, target) for tag, rows in merged.items()}
    out = {
        **northstar_metadata(noise=args.noise,
                             label_noise=args.label_noise,
                             epochs=args.epochs, rounds=args.rounds,
                             num_train=args.num_train,
                             num_test=args.num_test),
        "provenance": "reconstructed from the streamed run logs "
                      f"({', '.join(os.path.basename(l) for l in args.logs)}) "
                      "by tools/convergence_from_log.py",
        "platform": args.platform,
        "runs": runs,
    }
    if {"iid", "noniid_lda0.5"} <= set(runs):
        out["comparison"] = build_comparison(
            runs, {t: r["trajectory"] for t, r in runs.items()}
        )
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({t: {"final": r["final_test_acc"],
                          "rtt": r["rounds_to_target"]}
                      for t, r in runs.items()}))


if __name__ == "__main__":
    main()
