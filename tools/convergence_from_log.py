"""Reconstruct a CONVERGENCE artifact from a convergence_run.py log.

The north-star pair is a multi-hour, two-run session on a tunnel that
stalls for minutes at a time and has crashed TPU workers mid-session;
``tools/convergence_run.py`` streams every round row to stdout exactly
so the evidence survives the process.  This tool rebuilds the artifact
(trajectories, finals, rounds-to-target, per-round wall stats) from
that log, marking its provenance.

Usage: python tools/convergence_from_log.py LOG [--out FILE]
       [--label-noise 0.1] [--rounds 100]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from convergence_run import (build_comparison,  # noqa: E402
                             northstar_metadata, per_round_seconds,
                             rounds_to_target, trajectory_rows)


def parse_log(path):
    runs = {}
    for line in open(path):
        if not line.startswith("["):
            continue
        tag, _, payload = line.partition(" ")
        tag = tag.strip("[]")
        try:
            row = json.loads(payload)
        except json.JSONDecodeError:
            continue
        runs.setdefault(tag, []).append(row)
    return runs


def pick_runs(per_log):
    """One merged trajectory per tag across logs, plus the per-log
    segments for wall-clock stats.

    A resumed continuation log holds FEWER rows but LATER rounds than
    the pre-crash log (e.g. rounds 44-99 after a crash at 60), so
    picking by row count silently drops the post-resume trajectory
    (r3 advisor finding).  Instead the rows are merged by round index:
    logs are applied in order of their last round, so on an overlap
    (pre-crash rounds past the resume checkpoint) the continuation's
    rerun row wins.  elapsed_s restarts at 0 per log, so wall-clock
    stats are computed per SEGMENT and pooled, never across the merge
    boundary."""
    chosen = {}
    for log, runs in per_log:
        for tag, rows in runs.items():
            if rows:
                chosen.setdefault(tag, []).append((log, rows))
    out = {}
    for tag, entries in chosen.items():
        entries.sort(key=lambda e: e[1][-1]["round"])
        if len(entries) > 1:
            spans = ", ".join(
                f"{os.path.basename(l)} [{r[0]['round']}-{r[-1]['round']}]"
                for l, r in entries)
            print(f"note: {tag} merged from {spans} (later rounds win "
                  "on overlap)", file=sys.stderr)
        byround = {}
        for _, rows in entries:
            for r in rows:
                byround[r["round"]] = r
        merged = [byround[k] for k in sorted(byround)]
        out[tag] = (merged, [rows for _, rows in entries])
    return out


def summarize(merged_and_segments, target):
    rows, segments = merged_and_segments
    evals = [r for r in rows if "test_acc" in r]
    per_round = []
    for seg in segments:
        per_round.extend(per_round_seconds([0.0] + [r["elapsed_s"]
                                                    for r in seg]))
    per_round.sort()
    med = per_round[len(per_round) // 2] if per_round else None
    return {
        "rounds_completed": rows[-1]["round"] + 1 if rows else 0,
        "final_test_acc": evals[-1]["test_acc"] if evals else None,
        "rounds_to_target": rounds_to_target(rows, target),
        # sum of segment walls: the run's total on-chip time across
        # crash/resume sessions (tunnel stalls included)
        "wall_clock_s": round(sum(s[-1]["elapsed_s"] for s in segments), 1),
        "steady_state_s_per_round_median": (
            round(med, 2) if med is not None else None
        ),
        "trajectory": trajectory_rows(rows),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("logs", nargs="+",
                   help="one or more convergence_run logs; their [tag] "
                   "rows are merged (e.g. an iid log + a noniid rerun "
                   "after a tunnel wedge)")
    p.add_argument("--out", default="CONVERGENCE_r04.json")
    # config-fidelity flags (like --rounds below): the reconstructed
    # artifact must describe the run the LOG came from
    p.add_argument("--augment", type=int, choices=[0, 1], default=1)
    p.add_argument("--smooth-sigma", type=float, default=2.0)
    p.add_argument("--flip-symmetric", type=int, choices=[0, 1], default=1)
    p.add_argument("--label-noise", type=float, default=0.1)
    p.add_argument("--noise", type=float, default=1.2)
    # config-fidelity flags: the reconstructed artifact's config section
    # must describe the run the LOG came from, not the tool defaults
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--num-train", type=int, default=50000)
    p.add_argument("--num-test", type=int, default=10000)
    p.add_argument("--platform", default="tpu")
    # the reconstructed artifact must describe the run the LOG came
    # from — the r5 northstar matrix spans (model, num_classes) rows
    p.add_argument("--model", choices=["resnet56", "mobilenet"],
                   default="resnet56")
    p.add_argument("--num-classes", type=int, choices=[10, 100],
                   default=10)
    args = p.parse_args()

    ceiling = 1.0 - args.label_noise
    target = 0.9 * ceiling
    merged = pick_runs([(log, parse_log(log)) for log in args.logs])
    # this tool reconstructs NORTH-STAR artifacts only: summarizing a
    # [mnist_lr] (or other-preset) log with the north-star target and
    # resnet56 config header would misdescribe the run — the mnist_lr
    # preset streams its own resume-merged .partial artifact instead
    # (trajectory AND wall-clock survive crashes there)
    for tag in [t for t in merged if t not in ("iid", "noniid_lda0.5")]:
        print(f"note: dropping [{tag}] rows — not a north-star tag; "
              "this tool only reconstructs the north-star pair",
              file=sys.stderr)
        del merged[tag]
    if not merged:
        raise SystemExit("no [iid]/[noniid_lda0.5] rows in the logs")
    runs = {tag: summarize(rows, target) for tag, rows in merged.items()}
    out = {
        **northstar_metadata(noise=args.noise,
                             label_noise=args.label_noise,
                             epochs=args.epochs, rounds=args.rounds,
                             num_train=args.num_train,
                             num_test=args.num_test,
                             augment=bool(args.augment),
                             smooth_sigma=args.smooth_sigma,
                             flip_symmetric=bool(args.flip_symmetric),
                             model=args.model,
                             num_classes=args.num_classes),
        "provenance": "reconstructed from the streamed run logs "
                      f"({', '.join(os.path.basename(l) for l in args.logs)}) "
                      "by tools/convergence_from_log.py",
        "platform": args.platform,
        "runs": runs,
    }
    if {"iid", "noniid_lda0.5"} <= set(runs):
        out["comparison"] = build_comparison(runs)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({t: {"final": r["final_test_acc"],
                          "rtt": r["rounds_to_target"]}
                      for t, r in runs.items()}))


if __name__ == "__main__":
    main()
