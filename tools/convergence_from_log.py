"""Reconstruct a CONVERGENCE artifact from a convergence_run.py log.

The north-star pair is a multi-hour, two-run session on a tunnel that
stalls for minutes at a time and has crashed TPU workers mid-session;
``tools/convergence_run.py`` streams every round row to stdout exactly
so the evidence survives the process.  This tool rebuilds the artifact
(trajectories, finals, rounds-to-target, per-round wall stats) from
that log, marking its provenance.

Usage: python tools/convergence_from_log.py LOG [--out FILE]
       [--label-noise 0.1] [--rounds 100]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from convergence_run import (median_round_seconds,  # noqa: E402
                             rounds_to_target)


def parse_log(path):
    runs = {}
    for line in open(path):
        if not line.startswith("["):
            continue
        tag, _, payload = line.partition(" ")
        tag = tag.strip("[]")
        try:
            row = json.loads(payload)
        except json.JSONDecodeError:
            continue
        runs.setdefault(tag, []).append(row)
    return runs


def summarize(rows, target):
    evals = [r for r in rows if "test_acc" in r]
    stamps = [0.0] + [r["elapsed_s"] for r in rows]
    med = median_round_seconds(stamps)
    return {
        "rounds_completed": rows[-1]["round"] + 1 if rows else 0,
        "final_test_acc": evals[-1]["test_acc"] if evals else None,
        "rounds_to_target": rounds_to_target(rows, target),
        "wall_clock_s": stamps[-1] if stamps else None,
        "steady_state_s_per_round_median": (
            round(med, 2) if med is not None else None
        ),
        "trajectory": [
            {"round": r["round"], "test_acc": r["test_acc"],
             "test_loss": r["test_loss"],
             **({"train_acc": r["train_acc"]} if "train_acc" in r else {})}
            for r in evals
        ],
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("log")
    p.add_argument("--out", default="CONVERGENCE_r03.json")
    p.add_argument("--label-noise", type=float, default=0.1)
    args = p.parse_args()

    ceiling = 1.0 - args.label_noise
    target = 0.9 * ceiling
    runs = {tag: summarize(rows, target)
            for tag, rows in parse_log(args.log).items()}
    out = {
        "provenance": f"reconstructed from the streamed run log "
                      f"({os.path.basename(args.log)}) by "
                      "tools/convergence_from_log.py",
        "hardness": {"label_noise_eta": args.label_noise,
                     "accuracy_ceiling": ceiling,
                     "target_for_rounds_to_target": round(target, 4)},
        "runs": runs,
    }
    if {"iid", "noniid_lda0.5"} <= set(runs):
        a, b = runs["iid"], runs["noniid_lda0.5"]
        out["comparison"] = {
            "final_acc_gap_iid_minus_noniid": round(
                (a["final_test_acc"] or 0) - (b["final_test_acc"] or 0), 5),
            "ordering_matches_reference": (
                (a["final_test_acc"] or 0) >= (b["final_test_acc"] or 0)),
            "rounds_to_target": {"iid": a["rounds_to_target"],
                                 "noniid": b["rounds_to_target"]},
        }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({t: {"final": r["final_test_acc"],
                          "rtt": r["rounds_to_target"]}
                      for t, r in runs.items()}))


if __name__ == "__main__":
    main()
