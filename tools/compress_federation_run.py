#!/usr/bin/env python
"""Wire-bytes evidence for update compression on the REAL TCP path.

The engine-side counters prove the codec math; this tool proves the
WIRE: hub + server + N client OS processes (``comm/tcp.py``,
``experiments/distributed_fedavg.py``) run the same federation three
times —

1. ``baseline``  — legacy v1 frames (JSON lines, base64 fp32 full-model
   uploads): the pre-subsystem wire, byte-for-byte;
2. ``int8`` (A)  — wiretree-v2 binary frames + qsgd8-encoded update
   deltas negotiated via the sync envelope's codec key;
3. ``int8`` (B)  — the SAME federation re-run at the same seed.

and reads, from each server process's exit line, the exact received
wire bytes per message type (``TcpBackend`` counts header + binary
payload).  The verdict requires ``C2S_SEND_MODEL`` bytes reduced
>= 3.5x vs baseline, and every client's accumulated encoded-upload
sha256 identical between runs A and B (bit-reproducible encoding).

The model is ``logistic_regression(--input-dim, 2)`` — sized so the
payload dominates the frame envelope (the default 18-param federation
model would measure JSON overhead, not compression).

Usage: python tools/compress_federation_run.py
       [--clients 16] [--rounds 3] [--input-dim 4096]
       [--out COMPRESS_FEDERATION_r06.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--clients", type=int, default=16)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--input-dim", type=int, default=4096)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--round-timeout", type=float, default=120.0)
    p.add_argument("--out", default="COMPRESS_FEDERATION_r06.json")
    args = p.parse_args()

    from fedml_tpu.experiments.distributed_fedavg import launch

    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["XLA_FLAGS"] = ""

    def run_one(tag, codec, wire):
        info = {}
        t0 = time.time()
        rc = launch(
            num_clients=args.clients, rounds=args.rounds, seed=args.seed,
            batch_size=args.batch_size,
            out_path=f"/tmp/compress_fed_{tag}.npz",
            round_timeout=args.round_timeout,
            codec=codec, wire=wire, input_dim=args.input_dim,
            info=info, env=env, server_env=env,
            timeout=300.0 + args.rounds * args.round_timeout,
        )
        if rc != 0:
            raise SystemExit(f"{tag}: server subprocess failed rc={rc}")
        wall = round(time.time() - t0, 1)
        comm = info.get("comm_bytes", {})
        digests = {k: v for k, v in info.items()
                   if k.endswith("_upload_digest")}
        c2s = comm.get("comm.recv_bytes{msg_type=C2S_SEND_MODEL}", 0)
        uploads = comm.get("comm.recv_msgs{msg_type=C2S_SEND_MODEL}", 0)
        return {
            "rounds": info.get("rounds"),
            "wall_s": wall,
            "c2s_send_model_bytes": c2s,
            "c2s_uploads": uploads,
            "c2s_bytes_per_upload": round(c2s / uploads, 1) if uploads else None,
            "server_comm_bytes": comm,
            "client_upload_digests": digests,
        }

    base = run_one("baseline_v1_fp32", "none", 1)
    run_a = run_one("int8_run_a", "int8", 2)
    run_b = run_one("int8_run_b", "int8", 2)

    ratio = (base["c2s_bytes_per_upload"] / run_a["c2s_bytes_per_upload"]
             if base["c2s_bytes_per_upload"] and run_a["c2s_bytes_per_upload"]
             else None)
    digests_match = (
        bool(run_a["client_upload_digests"])
        and run_a["client_upload_digests"] == run_b["client_upload_digests"]
    )
    params = args.input_dim * 2 + 2
    artifact = {
        "experiment": f"wire-bytes measurement on the real TCP hub: "
                      f"{args.clients} client processes + server + hub, "
                      f"logistic_regression({args.input_dim}, 2) "
                      f"({params} params), {args.rounds} rounds",
        "arms": {
            "baseline_v1_fp32": base,
            "int8_run_a": run_a,
            "int8_run_b": run_b,
        },
        "verdict": {
            "what": "C2S_SEND_MODEL wire bytes per upload (server-side "
                    "exact frame accounting), fp32/base64 JSON frames "
                    "vs wiretree-v2 binary frames + qsgd8 deltas",
            "reduction_ratio": round(ratio, 2) if ratio else None,
            "required_ratio": 3.5,
            "ratio_ok": bool(ratio and ratio >= 3.5),
            "encoded_uploads_bit_identical_across_reruns": digests_match,
        },
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({"out": args.out,
                      "bytes_per_upload": {
                          "baseline": base["c2s_bytes_per_upload"],
                          "int8": run_a["c2s_bytes_per_upload"]},
                      "ratio": artifact["verdict"]["reduction_ratio"],
                      "digests_match": digests_match}))
    if not artifact["verdict"]["ratio_ok"] or not digests_match:
        raise SystemExit("compression federation verdict FAILED")


if __name__ == "__main__":
    main()
