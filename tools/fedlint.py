#!/usr/bin/env python
"""fedlint — the project's AST invariant linters (``fedml_tpu/analysis``).

Runs the five rule checkers over the given paths (default: the
``fedml_tpu`` package next to this script) and exits nonzero when any
finding survives pragma filtering.

    $ python tools/fedlint.py fedml_tpu            # human output
    $ python tools/fedlint.py fedml_tpu --json     # machine output
    $ python tools/fedlint.py --rules determinism,lock-discipline fedml_tpu
    $ python tools/fedlint.py --list-rules

Suppression (justification REQUIRED — a bare disable is itself a
finding):

    something_flagged()  # fedlint: disable=<rule> -- <why this is safe>

Lock-discipline caller-holds annotation (verified at runtime by
``analysis.locks.assert_held`` when ``FEDML_TPU_CHECKED_LOCKS=1``):

    def _close_round(self):  # fedlint: holds=_round_lock

Runs on a bare interpreter: the analysis package is stdlib-only, and a
stub parent module keeps ``fedml_tpu/__init__`` (which imports jax)
from executing in environments that don't have it — the CI lint job
installs nothing.
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
import types
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent


def _import_analysis():
    """Import ``fedml_tpu.analysis`` without executing the package's
    real ``__init__`` (it imports jax, absent on lint-only
    environments).  A stub parent with the right ``__path__`` lets the
    normal import machinery load the analysis subpackage directly; when
    fedml_tpu is already imported (tests), the stub is skipped."""
    if "fedml_tpu" not in sys.modules:
        stub = types.ModuleType("fedml_tpu")
        stub.__path__ = [str(REPO_ROOT / "fedml_tpu")]
        sys.modules["fedml_tpu"] = stub
    sys.path.insert(0, str(REPO_ROOT))
    return importlib.import_module("fedml_tpu.analysis")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fedlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the fedml_tpu package)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable findings on stdout",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated subset of rules to run",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule names and exit",
    )
    args = parser.parse_args(argv)

    analysis = _import_analysis()
    if args.list_rules:
        for rule in analysis.RULES:
            print(rule)
        return 0

    paths = args.paths or [str(REPO_ROOT / "fedml_tpu")]
    for p in paths:
        if not Path(p).exists():
            print(f"fedlint: no such path: {p}", file=sys.stderr)
            return 2
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    files = analysis.load_files(paths)
    try:
        findings = analysis.run_all(files, rules=rules)
    except ValueError as e:
        print(f"fedlint: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        by_rule: dict = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        print(json.dumps({
            "files_scanned": len(files),
            "rules": list(rules or analysis.RULES),
            "findings": [f.to_dict() for f in findings],
            "counts": by_rule,
            "ok": not findings,
        }, indent=2))
    else:
        for f in findings:
            print(f.format())
        print(
            f"fedlint: {len(findings)} finding(s) in {len(files)} file(s) "
            f"[{', '.join(rules or analysis.RULES)}]",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
