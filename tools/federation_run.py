"""Medium-N real-process federation evidence (VERDICT r4 next #9).

The cross-device DCN-role path's only prior evidence was 2-3 client
processes on CPU (``tests/test_distributed_process.py``).  This tool
runs the SAME machinery at a medium process count with the real chip
serving aggregation: hub + server + N client OS processes over the TCP
hub (``comm/tcp.py``), round deadline armed, one SAMPLED client
SIGKILLed mid-round — then

- pins the final global model against the compiled masked-participation
  oracle (``make_round_fn`` with the server's LOGGED participation per
  round — the inject_dropout semantics), and
- records per-round wall-clock (from the server's round-close stamps)
  next to the inproc simulation's wall-clock for the same problem.

The server process runs on the default backend (the tunneled TPU under
the driver env — only one process may hold the tunnel lease); clients
are forced to CPU via FEDML_TPU_FORCE_CPU.

Usage: python tools/federation_run.py [--clients 16] [--rounds 8]
       [--out FEDERATION_r05.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--clients", type=int, default=16)
    p.add_argument("--rounds", type=int, default=8)
    p.add_argument("--round-timeout", type=float, default=60.0,
                   help="per-round deadline; generous because a 1-core "
                   "host serializes N client processes' first-round jit "
                   "compiles")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--server-on-cpu", action="store_true",
                   help="run the server on CPU too (when no chip is "
                   "attached)")
    p.add_argument("--out", default="FEDERATION_r05.json")
    args = p.parse_args()

    import numpy as np

    from fedml_tpu.experiments.distributed_fedavg import (
        _build_problem,
        launch,
    )

    client_env = dict(os.environ)
    client_env["FEDML_TPU_FORCE_CPU"] = "1"
    client_env["XLA_FLAGS"] = ""
    server_env = dict(client_env) if args.server_on_cpu else dict(os.environ)

    # TWO federations: a CLEAN one (every client lives) whose round-close
    # stamps give the real per-round wall-clock, and a STRAGGLER one
    # (one sampled client SIGKILLed mid-round) whereevery round necessarily
    # closes BY deadline — the honest price of a dead sampled client
    # under the timeout policy, but useless as a wall-clock measure.
    def run_one(tag, rounds, **kw):
        npz = f"/tmp/federation_{tag}.npz"
        t0 = time.time()
        rc = launch(
            num_clients=args.clients, rounds=rounds, seed=args.seed,
            batch_size=args.batch_size, out_path=npz,
            round_timeout=args.round_timeout,
            env=client_env, server_env=server_env,
            timeout=300.0 + rounds * args.round_timeout, **kw,
        )
        if rc != 0:
            raise SystemExit(f"{tag} server subprocess failed rc={rc}")
        z = np.load(npz)
        log = json.loads(str(z["round_log"]))
        recs = [r for r in log if "participants" in r]
        return z, log, recs, round(time.time() - t0, 1)

    z, log, rounds, wall = run_one("clean", args.rounds)
    per_round_s = [round(b["t"] - a["t"], 3)
                   for a, b in zip(rounds, rounds[1:])]
    zs, slog, srounds, swall = run_one(
        "straggler", max(2, args.rounds // 2),
        # the LAST sampled client sleeps, then is SIGKILLed mid-round
        slow_client_delay=600.0, kill_slow_client_after=2.0,
    )

    # compiled masked-participation oracle, driven by the LOGGED
    # participants (the per-round deadline decided them, not us)
    import jax
    import jax.numpy as jnp

    from fedml_tpu.algorithms.fedavg import ServerState, make_round_fn
    from fedml_tpu.core.types import cohort_steps_per_epoch, pack_clients

    ds, bundle, init, lu = _build_problem(seed=args.seed,
                                          num_clients=args.clients)
    steps = cohort_steps_per_epoch(ds, args.batch_size)
    pack = pack_clients(ds, list(range(args.clients)), args.batch_size,
                        steps_per_epoch=steps, seed=args.seed)
    rf = jax.jit(make_round_fn(lu))

    def oracle_err(z_, recs):
        st = ServerState(variables=init, opt_state=(),
                         round_idx=jnp.zeros((), jnp.int32),
                         key=jax.random.PRNGKey(args.seed))
        for rec in recs:
            if not rec["participants"]:
                # the server treats a zero-participant round as a no-op
                # for the MODEL but still advances round_idx
                # (fedavg_cross_device._close_round) — and clients key
                # their next round's rng on that index, so the oracle
                # must advance it too (review r5: replaying with an
                # all-zero mask would zero the model; skipping without
                # advancing would desync every later round's shuffle)
                st = st._replace(round_idx=st.round_idx + 1)
                continue
            part = np.zeros(args.clients, np.float32)
            part[[n - 1 for n in rec["participants"]]] = 1.0
            st, _ = rf(st, jnp.asarray(pack.x), jnp.asarray(pack.y),
                       jnp.asarray(pack.mask),
                       jnp.asarray(pack.num_samples), jnp.asarray(part),
                       jnp.arange(args.clients, dtype=jnp.int32))
        want = jax.tree_util.tree_leaves(st.variables)
        got = [np.asarray(z_[f"leaf_{i}"]) for i in range(len(want))]
        return max(float(np.abs(a - np.asarray(b)).max())
                   for a, b in zip(got, want))

    # threshold: f32 weighted sums accumulate order-dependent rounding
    # over N clients x R rounds; 16x8 measured ~1.6e-4 max abs on O(1)
    # weights — 5e-4 bounds that with margin while still catching any
    # REAL divergence (a missed round or client is O(1e-2))
    max_err = oracle_err(z, rounds)
    straggler_err = oracle_err(zs, srounds)
    parity_ok = max_err < 5e-4 and straggler_err < 5e-4

    # inproc comparison: same problem, same rounds, simulation driver
    from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation

    sim = FedAvgSimulation(bundle, ds, FedAvgConfig(
        num_clients=args.clients, clients_per_round=args.clients,
        comm_rounds=args.rounds, epochs=1, batch_size=args.batch_size,
        lr=0.1, seed=args.seed, frequency_of_the_test=10 ** 9,
    ))
    t1 = time.time()
    sim.run_fused()
    inproc_wall = time.time() - t1

    artifact = {
        "experiment": f"real-process federation: hub + server + "
                      f"{args.clients} client OS processes over the TCP "
                      "hub (clean run for wall-clock; straggler run "
                      "with one sampled client SIGKILLed mid-round)",
        "server_backend": ("cpu" if args.server_on_cpu
                           else jax.devices()[0].platform),
        "host": "1-core box: client processes TIMESHARE one CPU — "
                "per-round wall is an upper bound on a real multi-host "
                "deployment's",
        "processes": args.clients + 2,
        "round_timeout_s": args.round_timeout,
        "clean_run": {
            "rounds": int(z["rounds"]),
            "round_log": log,
            "per_round_wall_s": per_round_s,
            "total_wall_s": wall,
            "oracle_max_abs_err": max_err,
        },
        "straggler_run": {
            "rounds": int(zs["rounds"]),
            "killed_client_node": args.clients,
            "round_log": slog,
            "total_wall_s": swall,
            "oracle_max_abs_err": straggler_err,
            "note": "every round necessarily closes BY the deadline "
                    "(the dead sampled client never uploads) — the "
                    "timeout policy's price, not a throughput figure",
        },
        "oracle_parity": {
            "what": "final global model vs the compiled round kernel "
                    "driven by the server's LOGGED per-round "
                    "participation (masked-psum semantics), both runs",
            "threshold": 5e-4,
            "ok": bool(parity_ok),
        },
        "inproc_comparison": {
            "driver": "FedAvgSimulation.run_fused, full participation, "
                      "same problem/rounds",
            "wall_s": round(inproc_wall, 2),
            "note": "the gap is the DCN-role price: process spawn + jax "
                    "import + per-round socket round-trips vs one "
                    "compiled program",
        },
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({"out": args.out,
                      "clean_rounds": int(z["rounds"]),
                      "straggler_rounds": int(zs["rounds"]),
                      "parity_max_abs_err": [max_err, straggler_err],
                      "per_round_wall_s": per_round_s,
                      "inproc_wall_s": artifact["inproc_comparison"]["wall_s"]}))
    if not parity_ok:
        raise SystemExit("PARITY FAILURE vs masked oracle")


if __name__ == "__main__":
    main()
