"""Quantitative 8→256-chip scaling model with measured inputs
(VERDICT r2 next #5).

The 1-core CPU box cannot measure ICI, so the r2 chips-mode ladder's
"efficiency" numbers were harness validation only.  This tool replaces
them with a MODEL whose every input is either measured on the real chip
or a cited hardware constant:

- ``t_compute``: measured seconds/round of the north-star workload on
  ONE v5e chip via the fused driver (``bench.py`` protocol: warmup to
  agreement, median, scalar readback inside the timed window).  This
  already CONTAINS the on-chip partial aggregation (the einsum over the
  local client axis) and the optimizer/server update.
- ``payload_bytes``: the exact fp32 byte size of the aggregated
  variable tree (params + BN stats), counted from the model's pytree.
- ``ici_bw``: v5e per-link one-way ICI bandwidth, 4.5e10 B/s, 2D torus
  up to 16x16 = 256 chips (public v5e spec / jax-ml scaling book).  The
  model conservatively uses ONE axis, ONE direction — a real 2D
  bidirectional torus is up to 4x faster.
- ``hop_latency``: 1 us/hop over the 2(N-1) sequential ring steps —
  conservative (ICI hop latency is sub-microsecond).

Weak-scaling scenario (SURVEY.md §7.8 north star): clients-per-chip
fixed, chips grow; per round each chip trains its resident clients
(t_compute, constant) then joins ONE all-reduce of the variable tree
(``lax.psum`` over the ``clients`` mesh axis — ``parallel/spmd.py``).

    t_allreduce(N) = 2 * V * (N-1)/N / ici_bw  +   2 * (N-1) * hop_latency
    efficiency(N)  = t_compute / (t_compute + t_allreduce(N))

The communication/compute ratio is what makes federated rounds scale:
one 2.4 MB all-reduce amortized over E local epochs of ResNet-56
training (~530 ms) is a ~1.2e-3 overhead at 256 chips — efficiency
stays >99% even with the conservative single-axis model.  Cross-host
DCN (beyond one 256-chip slice) at 2.5e10 B/s/host stays >99% too.

Usage: python tools/scaling_model.py [--measure] [--out SCALING_r04.json]
  --measure re-times the workload on the local chip (else uses
  --t-compute, default = the r3 bench measurement).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5E_ICI_BW = 4.5e10          # B/s, per link, one way (scaling-book v5e)
# DCN and hop-latency have no single citable per-deployment constant
# (NIC provisioning varies by pod generation); the model therefore
# treats them as ASSUMPTIONS and reports break-even sensitivity bounds
# instead of resting the conclusion on the point values (VERDICT r3
# weak #6: "the 1024-chip dcn_point cites no NIC-bandwidth source").
V5E_DCN_BW = 2.5e10          # B/s per host NIC — assumption, see bounds
HOP_LATENCY = 1e-6           # s/hop — assumption, see bounds


def sensitivity_bounds(t_compute: float, v_bytes: int,
                       target_eff: float = 0.90) -> dict:
    """How wrong could the assumed constants be before the >=90%%
    efficiency claim breaks?  Solve eff(N) = target for each constant
    with the other at its assumed value — the claim then rests on
    'bandwidth is above X / latency is below Y', which IS checkable
    against any deployment, instead of on an uncited point value."""
    budget = t_compute * (1.0 - target_eff) / target_eff  # max t_allreduce
    n = 1024
    # each break-even holds the OTHER constant at its assumed value
    # (the docstring's method, verbatim)
    bw_min = (2.0 * v_bytes * (n - 1) / n
              / (budget - 2.0 * (n - 1) * HOP_LATENCY))
    lat_max = (budget - 2.0 * v_bytes * (n - 1) / n / V5E_DCN_BW) \
        / (2.0 * (n - 1))
    return {
        "claim_holds_if": {
            "dcn_bandwidth_at_least_bytes_per_s": float(f"{bw_min:.3g}"),
            "hop_latency_at_most_s": float(f"{lat_max:.3g}"),
        },
        "margin_vs_assumed": {
            "bandwidth_x": round(V5E_DCN_BW / bw_min, 1),
            "latency_x": round(lat_max / HOP_LATENCY, 1),
        },
        "note": "break-even at 1024 chips, 90% efficiency target: the "
                "conclusion survives any NIC above ~{:.0f} Mbit/s and "
                "any hop latency below ~{:.0f} us — orders of magnitude "
                "of slack, so the uncited point constants cannot carry "
                "the claim".format(bw_min * 8 / 1e6, lat_max * 1e6),
    }


def payload_bytes():
    import jax

    from fedml_tpu.models.resnet import resnet56

    bundle = resnet56(num_classes=10)
    shapes = jax.eval_shape(lambda k: bundle.init(k), jax.random.PRNGKey(0))
    return int(sum(np.prod(l.shape) * 4  # fp32 aggregation masters
                   for l in jax.tree_util.tree_leaves(shapes)))


def measure_t_compute():
    """bench.py's exact workload + timing protocol, returning s/round.
    The workload is IMPORTED from bench.py (build_north_star) so the two
    can never diverge — same model, dtype, unroll, rounds_per_call."""
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    from bench import build_north_star
    from fedml_tpu.utils.timing import measure_rounds

    rpc = 80  # bench.py default
    round_fn, state, call_args, samples = build_north_star(
        rounds_per_call=rpc
    )
    med, _ = measure_rounds(round_fn, state, call_args, 3)
    return med / rpc


def model_efficiency(t_compute: float, v_bytes: int, n: int,
                     bw: float = V5E_ICI_BW) -> dict:
    # bandwidth term: reduce-scatter + all-gather move 2V(N-1)/N bytes
    # through each link.  Latency term: a ring all-reduce is 2(N-1)
    # SEQUENTIAL steps, each paying hop latency — not N/2 (an earlier
    # draft used the ring DIAMETER, which understates latency ~4x and
    # would contradict the "conservative" framing).
    t_ar = (2.0 * v_bytes * (n - 1) / n / bw
            + 2.0 * (n - 1) * HOP_LATENCY)
    return {
        "chips": n,
        "t_allreduce_ms": round(t_ar * 1e3, 4),
        "round_time_s": round(t_compute + t_ar, 5),
        "efficiency": round(t_compute / (t_compute + t_ar), 5),
    }


_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}
_SHAPE_RE = None  # compiled lazily (module imports stay cheap)
_COLL_RE = None


def _replica_group_size(line_tail: str):
    """Per-group participant count from an HLO op's ``replica_groups``
    attribute: explicit list form ``{{0,1,...},...}`` (size of the
    first group) or iota form ``[n_groups,group_size]<=[total]``."""
    import re

    m = re.search(r"replica_groups=\{\{([0-9, ]*)\}", line_tail)
    if m:
        ids = [t for t in m.group(1).replace(" ", "").split(",") if t]
        return len(ids) or None
    m = re.search(r"replica_groups=\[\d+,(\d+)\]<=\[", line_tail)
    if m:
        return int(m.group(1))
    return None


def parse_collective_bytes(hlo_text: str) -> dict:
    """Per-kind LOGICAL payload bytes V of the cross-device collectives
    in an optimized-HLO dump: for each ``all-reduce``/``all-gather``/
    ``reduce-scatter``/``collective-permute``/``all-to-all`` op (and
    async ``-start`` form; ``-done`` consumes the started op and is
    skipped) sum the byte size of its OUTPUT shape(s).  For an
    all-reduce the output equals the payload V, so the ring wire
    traffic is 2·V·(N−1)/N per link — the exact term
    ``model_efficiency`` charges.  A reduce-scatter's OUTPUT is only
    V/N, so its bytes are scaled up by the replica-group size parsed
    from the op's ``replica_groups`` attribute (ADVICE r5: the raw
    output sum would under-count its wire volume N×); an unparsable
    group on a reduce-scatter raises rather than under-counting — the
    no-unmodeled-collectives assertion in the tests stays the net."""
    import re

    global _SHAPE_RE, _COLL_RE
    if _SHAPE_RE is None:
        _SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")
        _COLL_RE = re.compile(
            r"=\s+((?:\([^)]*\))|(?:[a-z]+[0-9]*\[[0-9,]*\]\S*))\s+"
            r"(all-reduce|all-gather|reduce-scatter|collective-permute"
            r"|all-to-all)(-start)?\(")
    out: dict = {}
    for m in _COLL_RE.finditer(hlo_text):
        sig, kind = m.group(1), m.group(2)
        shapes = []
        for dt, dims in _SHAPE_RE.findall(sig):
            if dt not in _DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            shapes.append(n * _DTYPE_BYTES[dt])
        if kind == "reduce-scatter":
            eol = hlo_text.find("\n", m.end())
            tail = hlo_text[m.end(): eol if eol >= 0 else len(hlo_text)]
            group = _replica_group_size(tail)
            if group is None:
                raise ValueError(
                    "reduce-scatter without a parsable replica_groups "
                    f"attribute: cannot scale its V/N output to the "
                    f"payload V ({tail.strip()[:120]!r})"
                )
            # the async -start form's signature tuple carries the
            # OPERAND alongside the V/N output — scale only the output
            # (last shape); summing the whole tuple and scaling would
            # over-count ~(N+1)x.  (A variadic async reduce-scatter
            # would need operand/output splitting; none appears in any
            # program the model charges — the no-unmodeled-collectives
            # test is the net.)
            total = shapes[-1] * group if shapes else 0
        elif kind == "all-gather" and m.group(3):
            # all-gather-START's tuple is (operand_alias, output): the
            # gathered output alone is the logical payload V.  (Plain
            # tuple-result all-gathers are the combiner pass's VARIADIC
            # form — those sum, like all-reduce.)
            total = shapes[-1] if shapes else 0
        else:
            # all-reduce tuples are VARIADIC OUTPUTS (one per reduced
            # tensor, each of size V) — summing them is correct
            total = sum(shapes)
        out[kind] = out.get(kind, 0) + total
        out["n_ops"] = out.get("n_ops", 0) + 1
    return out


def measure_hlo_volume(n_devices: int = 8, model: str = "resnet56") -> dict:
    """Compile the ACTUAL north-star SPMD round program
    (``parallel/spmd.py make_spmd_round_fn``, one client per chip) on
    the current backend's n-device mesh and count the bytes its
    compiled collectives move — turning the scaling model's
    ``payload_bytes`` volume term from an assumption into a
    measurement (VERDICT r4 weak #3).  Needs n_devices visible (the
    faked-CPU-mesh recipe); ``main()`` runs it via a subprocess so the
    real-chip session can still produce the artifact.

    ``model='logreg'`` swaps in a small model for CI (the collective
    payload is the variable tree — model-dependent — so the test pins
    the MECHANISM; the artifact records the resnet56 number)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.algorithms.fedavg import ServerState
    from fedml_tpu.core.client import make_client_optimizer, make_local_update
    from fedml_tpu.core.types import pack_clients
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.parallel.spmd import (
        make_client_mesh,
        make_spmd_round_fn,
        replicate,
        shard_client_block,
    )

    if model == "resnet56":
        from fedml_tpu.models.resnet import resnet56

        bundle = resnet56(num_classes=10)
        input_shape = (32, 32, 3)
    else:
        from fedml_tpu.models.linear import logistic_regression

        bundle = logistic_regression(64, 10)
        input_shape = (64,)

    mesh = make_client_mesh(n_devices)
    ds = synthetic_classification(
        num_train=n_devices * 4, num_test=8, input_shape=input_shape,
        num_classes=10, num_clients=n_devices, partition="homo", seed=0,
    )
    opt = make_client_optimizer("sgd", 0.1, momentum=0.9)
    local_update = make_local_update(bundle, opt, epochs=1)
    round_fn = make_spmd_round_fn(mesh, local_update, donate=False)
    key = jax.random.PRNGKey(0)
    state = ServerState(variables=bundle.init(key), opt_state=(),
                        round_idx=jnp.zeros((), jnp.int32), key=key)
    pack = pack_clients(ds, list(range(n_devices)), batch_size=4)
    args = shard_client_block(mesh, (
        jnp.asarray(pack.x), jnp.asarray(pack.y), jnp.asarray(pack.mask),
        jnp.asarray(pack.num_samples), jnp.ones(n_devices, jnp.float32),
        jnp.arange(n_devices, dtype=jnp.int32),
    ))
    hlo = round_fn.lower(replicate(mesh, state), *args).compile().as_text()
    tree_bytes = int(sum(
        np.prod(l.shape) * 4
        for l in jax.tree_util.tree_leaves(jax.eval_shape(bundle.init, key))
    ))
    return {
        "n_devices": n_devices,
        "model": model,
        "variable_tree_fp32_bytes": tree_bytes,
        "hlo_collective_bytes": parse_collective_bytes(hlo),
    }


def hlo_volume_via_subprocess(n_devices: int = 8) -> dict:
    """Run measure_hlo_volume on a faked n-device CPU mesh in a fresh
    interpreter (the current session may hold the real single-chip TPU
    backend, which cannot fake devices)."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count="
                          f"{n_devices}").strip()
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--hlo-volume",
         "--devices", str(n_devices)],
        env=env, capture_output=True, text=True,
    )
    if out.returncode != 0:
        # surface the subprocess's own diagnostics — a bare
        # CalledProcessError would discard the only useful error text
        raise RuntimeError(
            f"--hlo-volume subprocess failed (exit {out.returncode}):\n"
            f"{out.stderr.strip()[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def measure_sampled_pack(chunk_rounds: int = 25):
    """HOST cost of the scheduled-cohort driver's chunk assembly
    (``run_fused_sampled``): draw + pack ``chunk_rounds`` mnist_lr
    cohorts (10 of 1000 power-law clients each).  Deliberately times
    the NUMPY pack only (``pack_clients``, the host work) — going
    through ``_cohort_block`` would fold the host→device transfer into
    the number and double-count it against the model's separate
    ``chunk_transfer/(R*bw)`` term.  Transfer bytes count ALL four
    block arrays (x, y, mask, num_samples)."""
    import time

    from fedml_tpu.core.sampling import host_sample_ids
    from fedml_tpu.core.types import cohort_steps_per_epoch, pack_clients
    from fedml_tpu.data.mnist import load_mnist

    ds = load_mnist(num_clients=1000, partition="power_law",
                    standin_label_noise=0.1)
    steps = cohort_steps_per_epoch(ds, 10)
    t0 = time.time()
    bytes_per_chunk = 0
    for i in range(chunk_rounds):
        ids = host_sample_ids(0, i, 1000, 10)
        pack = pack_clients(ds, list(ids), batch_size=10,
                            steps_per_epoch=steps, seed=0)
        bytes_per_chunk += (pack.x.nbytes + pack.y.nbytes
                            + pack.mask.nbytes + pack.num_samples.nbytes)
    return (time.time() - t0) / chunk_rounds, int(bytes_per_chunk)


def sampled_regime_section(measured_round_s=None):
    """The cross-device (sampled-cohort) regime the r3 model omitted
    (VERDICT r3 weak #6): scaling here is HOST-bound, not ICI-bound —
    the collective is the same one small all-reduce, but every round's
    cohort data must be drawn, packed, and shipped.

    Two execution models, both measured:
    - r3 per-round dispatch: 6.6 s/round (mnist_lr through the tunnel,
      CONVERGENCE_r03_mnist_lr.json) — dominated by per-round host
      round-trips, and at north-star CIFAR scale a per-round cohort
      repack costs ~240 s/round vs ~65 s resident
      (algorithms/fedavg.py _device_pack, measured r3).
    - r4 scheduled-cohort driver (``run_fused_sampled``): the host packs
      the next R cohorts while the device is IDLE only between chunks;
      per-round host cost = measured pack time below, amortized 1/R.
    """
    pack_s, chunk_bytes = measure_sampled_pack()
    section = {
        "scenario": "cross-device sampled cohorts (10 of 1000+ clients "
                    "per round): host-bound, not ICI-bound",
        "host_pack_s_per_round": round(pack_s, 4),
        "host_pack_source": "measured on this host: scheduled-cohort "
                            "chunk assembly (draw + pack, mnist_lr "
                            "preset shapes), 25-round chunk",
        "chunk_transfer_bytes": chunk_bytes,
        "r3_dispatch_round_s": 6.6,
        "r3_dispatch_source": "CONVERGENCE_r03_mnist_lr.json (per-round "
                              "dispatch through the axon tunnel)",
        "resident_vs_repack_s": [65, 240],
        "resident_vs_repack_source": "algorithms/fedavg.py _device_pack "
                                     "docstring (measured r3, north-star "
                                     "CIFAR scale)",
        "model": "per-round wall = t_device + host_pack_s_per_round + "
                 "chunk_transfer/(R*bw); host term already amortized "
                 "per round (pack cost scales with cohort size K, NOT "
                 "with population N — the draw is O(K log N))",
    }
    if measured_round_s is not None:
        section["measured_fused_round_s"] = measured_round_s
        section["measured_fused_source"] = (
            "CONVERGENCE_r04_mnist_lr.json steady state on the real "
            "chip (run_fused_sampled, 25-round chunks)")
    return section


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--measure", action="store_true",
                   help="re-time the workload on the local real chip")
    p.add_argument("--sampled-round-s", type=float, default=None,
                   help="measured fused cross-device s/round (from the "
                   "CONVERGENCE_r04_mnist_lr run) to embed in the "
                   "sampled-regime section")
    p.add_argument("--t-compute", type=float, default=0.5330,
                   help="s/round on one chip (bench r3 measured ladder, "
                   "rpc=80 default: 28,818 samples/s over 15,360 "
                   "samples/round — PROFILE.md r3 table)")
    p.add_argument("--out", default="SCALING_r05.json")
    p.add_argument("--merge", default="SCALING_r02.json",
                   help="carry over the measured clients-per-chip ladder")
    p.add_argument("--hlo-volume", action="store_true",
                   help="(internal) print measure_hlo_volume JSON on the "
                   "current backend and exit — run with a faked CPU mesh")
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--hlo-model", default="resnet56")
    args = p.parse_args()

    if args.hlo_volume:
        # sitecustomize pins JAX_PLATFORMS=axon at interpreter start, so
        # the subprocess env alone is too late — override via config
        # before the first device query (the conftest recipe); the
        # XLA_FLAGS device-count fake was set before interpreter start
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(measure_hlo_volume(args.devices, args.hlo_model)))
        return

    t_compute = measure_t_compute() if args.measure else args.t_compute
    v = payload_bytes()

    # pin the volume term to what XLA actually emits: compile the SPMD
    # round on a faked 8-device CPU mesh and count collective payloads
    # (VERDICT r4 weak #3 — the model's most load-bearing constant)
    hlo = hlo_volume_via_subprocess(8)
    ar_bytes = hlo["hlo_collective_bytes"].get("all-reduce", 0)
    hlo_section = {
        "method": "compiled the north-star SPMD round "
                  "(make_spmd_round_fn, one client/chip, resnet56) on a "
                  "faked 8-device CPU mesh; summed collective payloads "
                  "from the optimized HLO (parse_collective_bytes)",
        "hlo_collective_bytes": hlo["hlo_collective_bytes"],
        "assumed_payload_bytes": v,
        "allreduce_vs_assumed_ratio": round(ar_bytes / v, 5) if v else None,
        "note": "all-reduce payload = V in the 2V(N-1)/N ring wire "
                "term; the excess over the variable tree is the psum'd "
                "scalar train metrics",
    }

    chips = [model_efficiency(t_compute, v, n) for n in (8, 64, 256)]
    dcn = model_efficiency(t_compute, v, 1024, bw=V5E_DCN_BW)
    dcn["note"] = ("multi-slice via DCN (beyond one 256-chip v5e torus); "
                   "the NIC bandwidth is an ASSUMPTION — see "
                   "sensitivity_bounds for the break-even values the "
                   "claim actually rests on")
    dcn["sensitivity"] = sensitivity_bounds(t_compute, v)

    artifact = {
        "round": 5,
        "model": {
            "scenario": "weak scaling, north-star cross-silo FedAvg: "
                        "fixed clients/chip, one psum all-reduce of the "
                        "variable tree per round (parallel/spmd.py)",
            "inputs": {
                "t_compute_s_per_round": t_compute,
                "t_compute_source": "measured, one real v5e chip, fused "
                                    "driver (bench.py protocol; includes "
                                    "on-chip aggregation + optimizer)",
                "payload_bytes": v,
                "payload_source": "fp32 byte size of the aggregated "
                                  "resnet56 variable tree (params + BN "
                                  "stats), counted from the pytree; "
                                  "VALIDATED against compiled HLO — see "
                                  "hlo_validation",
                "hlo_validation": hlo_section,
                "ici_bw_bytes_per_s": V5E_ICI_BW,
                "ici_source": "v5e per-link one-way ICI (scaling book); "
                              "model uses ONE axis ONE direction of the "
                              "2D torus — conservative by up to 4x",
                "hop_latency_s": HOP_LATENCY,
            },
            "formula": "eff(N) = t_c / (t_c + 2V(N-1)/(N*BW) + 2(N-1)*lat)",
            "points": chips,
            "dcn_point": dcn,
            "headline": {
                "comm_compute_ratio_at_256": round(
                    chips[-1]["t_allreduce_ms"] / 1e3 / t_compute, 6
                ),
                "claim": ">=90% weak-scaling efficiency 8->256 chips "
                         "holds with large margin: one small all-reduce "
                         "per E-epoch round is ~1.2e-3 of round time "
                         "at 256 chips",
            },
        },
    }
    artifact["sampled_cohort_regime"] = sampled_regime_section(
        measured_round_s=args.sampled_round_s
    )
    if os.path.exists(args.merge):
        prior = json.load(open(args.merge))
        kept = []
        for pt in prior.get("points", []):
            if pt.get("metric") == "clients_per_chip_throughput":
                kept.append(pt)  # measured on the real chip in r2
            elif pt.get("metric") == "weak_scaling_round_time":
                pt["note"] = ("faked CPU mesh: validates the shard_map "
                              "harness ONLY; its efficiency numbers are "
                              "1-core timeslicing, NOT an ICI claim — "
                              "see model section")
                pt.pop("efficiency", None)
                kept.append(pt)
        artifact["measured"] = {
            "source": "SCALING_r02.json (real-chip clients ladder; CPU "
                      "harness rows de-fanged)",
            "points": kept,
        }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({"out": args.out, "t_compute": t_compute,
                      "payload_bytes": v,
                      "eff": {c["chips"]: c["efficiency"] for c in chips}}))


if __name__ == "__main__":
    main()
