"""Quantitative 8→256-chip scaling model with measured inputs
(VERDICT r2 next #5).

The 1-core CPU box cannot measure ICI, so the r2 chips-mode ladder's
"efficiency" numbers were harness validation only.  This tool replaces
them with a MODEL whose every input is either measured on the real chip
or a cited hardware constant:

- ``t_compute``: measured seconds/round of the north-star workload on
  ONE v5e chip via the fused driver (``bench.py`` protocol: warmup to
  agreement, median, scalar readback inside the timed window).  This
  already CONTAINS the on-chip partial aggregation (the einsum over the
  local client axis) and the optimizer/server update.
- ``payload_bytes``: the exact fp32 byte size of the aggregated
  variable tree (params + BN stats), counted from the model's pytree.
- ``ici_bw``: v5e per-link one-way ICI bandwidth, 4.5e10 B/s, 2D torus
  up to 16x16 = 256 chips (public v5e spec / jax-ml scaling book).  The
  model conservatively uses ONE axis, ONE direction — a real 2D
  bidirectional torus is up to 4x faster.
- ``hop_latency``: 1 us/hop over the 2(N-1) sequential ring steps —
  conservative (ICI hop latency is sub-microsecond).

Weak-scaling scenario (SURVEY.md §7.8 north star): clients-per-chip
fixed, chips grow; per round each chip trains its resident clients
(t_compute, constant) then joins ONE all-reduce of the variable tree
(``lax.psum`` over the ``clients`` mesh axis — ``parallel/spmd.py``).

    t_allreduce(N) = 2 * V * (N-1)/N / ici_bw  +   2 * (N-1) * hop_latency
    efficiency(N)  = t_compute / (t_compute + t_allreduce(N))

The communication/compute ratio is what makes federated rounds scale:
one 2.4 MB all-reduce amortized over E local epochs of ResNet-56
training (~530 ms) is a ~1.2e-3 overhead at 256 chips — efficiency
stays >99% even with the conservative single-axis model.  Cross-host
DCN (beyond one 256-chip slice) at 2.5e10 B/s/host stays >99% too.

Usage: python tools/scaling_model.py [--measure] [--out SCALING_r03.json]
  --measure re-times the workload on the local chip (else uses
  --t-compute, default = the r3 bench measurement).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5E_ICI_BW = 4.5e10          # B/s, per link, one way (scaling-book v5e)
V5E_DCN_BW = 2.5e10          # B/s per host NIC, conservative
HOP_LATENCY = 1e-6           # s/hop, conservative


def payload_bytes():
    import jax
    import numpy as np

    from fedml_tpu.models.resnet import resnet56

    bundle = resnet56(num_classes=10)
    shapes = jax.eval_shape(lambda k: bundle.init(k), jax.random.PRNGKey(0))
    return int(sum(np.prod(l.shape) * 4  # fp32 aggregation masters
                   for l in jax.tree_util.tree_leaves(shapes)))


def measure_t_compute():
    """bench.py's exact workload + timing protocol, returning s/round.
    The workload is IMPORTED from bench.py (build_north_star) so the two
    can never diverge — same model, dtype, unroll, rounds_per_call."""
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    from bench import build_north_star
    from fedml_tpu.utils.timing import measure_rounds

    rpc = 80  # bench.py default
    round_fn, state, call_args, samples = build_north_star(
        rounds_per_call=rpc
    )
    med, _ = measure_rounds(round_fn, state, call_args, 3)
    return med / rpc


def model_efficiency(t_compute: float, v_bytes: int, n: int,
                     bw: float = V5E_ICI_BW) -> dict:
    # bandwidth term: reduce-scatter + all-gather move 2V(N-1)/N bytes
    # through each link.  Latency term: a ring all-reduce is 2(N-1)
    # SEQUENTIAL steps, each paying hop latency — not N/2 (an earlier
    # draft used the ring DIAMETER, which understates latency ~4x and
    # would contradict the "conservative" framing).
    t_ar = (2.0 * v_bytes * (n - 1) / n / bw
            + 2.0 * (n - 1) * HOP_LATENCY)
    return {
        "chips": n,
        "t_allreduce_ms": round(t_ar * 1e3, 4),
        "round_time_s": round(t_compute + t_ar, 5),
        "efficiency": round(t_compute / (t_compute + t_ar), 5),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--measure", action="store_true",
                   help="re-time the workload on the local real chip")
    p.add_argument("--t-compute", type=float, default=0.5330,
                   help="s/round on one chip (bench r3 measured ladder, "
                   "rpc=80 default: 28,818 samples/s over 15,360 "
                   "samples/round — PROFILE.md r3 table)")
    p.add_argument("--out", default="SCALING_r03.json")
    p.add_argument("--merge", default="SCALING_r02.json",
                   help="carry over the measured clients-per-chip ladder")
    args = p.parse_args()

    t_compute = measure_t_compute() if args.measure else args.t_compute
    v = payload_bytes()

    chips = [model_efficiency(t_compute, v, n) for n in (8, 64, 256)]
    dcn = model_efficiency(t_compute, v, 1024, bw=V5E_DCN_BW)
    dcn["note"] = ("multi-slice via DCN (beyond one 256-chip v5e torus), "
                   "per-host NIC bandwidth, same formula")

    artifact = {
        "round": 3,
        "model": {
            "scenario": "weak scaling, north-star cross-silo FedAvg: "
                        "fixed clients/chip, one psum all-reduce of the "
                        "variable tree per round (parallel/spmd.py)",
            "inputs": {
                "t_compute_s_per_round": t_compute,
                "t_compute_source": "measured, one real v5e chip, fused "
                                    "driver (bench.py protocol; includes "
                                    "on-chip aggregation + optimizer)",
                "payload_bytes": v,
                "payload_source": "fp32 byte size of the aggregated "
                                  "resnet56 variable tree (params + BN "
                                  "stats), counted from the pytree",
                "ici_bw_bytes_per_s": V5E_ICI_BW,
                "ici_source": "v5e per-link one-way ICI (scaling book); "
                              "model uses ONE axis ONE direction of the "
                              "2D torus — conservative by up to 4x",
                "hop_latency_s": HOP_LATENCY,
            },
            "formula": "eff(N) = t_c / (t_c + 2V(N-1)/(N*BW) + 2(N-1)*lat)",
            "points": chips,
            "dcn_point": dcn,
            "headline": {
                "comm_compute_ratio_at_256": round(
                    chips[-1]["t_allreduce_ms"] / 1e3 / t_compute, 6
                ),
                "claim": ">=90% weak-scaling efficiency 8->256 chips "
                         "holds with large margin: one small all-reduce "
                         "per E-epoch round is ~1.2e-3 of round time "
                         "at 256 chips",
            },
        },
    }
    if os.path.exists(args.merge):
        prior = json.load(open(args.merge))
        kept = []
        for pt in prior.get("points", []):
            if pt.get("metric") == "clients_per_chip_throughput":
                kept.append(pt)  # measured on the real chip in r2
            elif pt.get("metric") == "weak_scaling_round_time":
                pt["note"] = ("faked CPU mesh: validates the shard_map "
                              "harness ONLY; its efficiency numbers are "
                              "1-core timeslicing, NOT an ICI claim — "
                              "see model section")
                pt.pop("efficiency", None)
                kept.append(pt)
        artifact["measured"] = {
            "source": "SCALING_r02.json (real-chip clients ladder; CPU "
                      "harness rows de-fanged)",
            "points": kept,
        }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({"out": args.out, "t_compute": t_compute,
                      "payload_bytes": v,
                      "eff": {c["chips"]: c["efficiency"] for c in chips}}))


if __name__ == "__main__":
    main()
