"""Scaling-efficiency harness (BASELINE north star: >=90% efficiency
8 -> 256 client-chips; SURVEY.md §7.8).

Two modes, one JSON line per measured point:

- ``--mode chips`` (weak scaling across devices): fixed per-chip load,
  one FL client per chip on a ``clients`` mesh, D in a doubling ladder
  up to the available device count.  Efficiency_D = t_round(1) /
  t_round(D) — ideal 1.0 when aggregation rides the interconnect and
  the round stays compiled end-to-end.  On a TPU slice this measures
  ICI; under ``--platform cpu`` with
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` it validates
  the harness + collective path without hardware.
- ``--mode clients`` (clients-per-chip scaling, runs on ONE chip): the
  packed client axis grows while per-client work is fixed; reports
  samples/s per point.  This is how a single v5e chip hosts many FL
  clients (sequential lax.map, full MXU tiles each).

Timing per point follows bench.py: warm until two consecutive
fully-synced calls agree, then median of synced per-call times.  In
chips mode one call == one round (dispatch-inclusive).  In clients mode
one call == ``--rounds-per-call`` rounds fused by ``make_multi_round_fn``
and ``s_per_round`` = call time / rounds_per_call — the per-dispatch
tunnel round-trip is deliberately amortized out (PROFILE.md measured it
at ~40% of per-round wall-clock), so the points report compute scaling;
pass ``--rounds-per-call 1`` for dispatch-inclusive points.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _measure(round_fn, state, args_dev, rounds):
    from fedml_tpu.utils.timing import measure_rounds

    return measure_rounds(round_fn, state, args_dev, rounds)


def _make_inputs(C, S, B, shape, classes, seed=0):
    rng = np.random.RandomState(seed)
    return (
        rng.rand(C, S, B, *shape).astype(np.float32),
        rng.randint(0, classes, (C, S, B)).astype(np.int32),
        np.ones((C, S, B), np.float32),
        np.full((C,), S * B, np.float32),
        np.ones((C,), np.float32),
        np.arange(C, dtype=np.int32),
    )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=["chips", "clients"], default="clients")
    p.add_argument("--platform", default=None,
                   help="cpu to run on the faked host mesh")
    p.add_argument("--devices", type=int, default=8,
                   help="host devices to fake when --platform cpu")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument(
        "--rounds-per-call", type=int, default=5,
        help="clients mode: rounds fused per compiled call "
        "(make_multi_round_fn) so the point measures compute scaling, "
        "not per-dispatch tunnel latency (PROFILE.md)",
    )
    p.add_argument("--model", default="resnet20",
                   help="resnet20 (cpu-friendly), resnet56, or mlp "
                   "(near-zero compile — CI harness validation)")
    args = p.parse_args()

    if args.platform == "cpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from fedml_tpu.algorithms.fedavg import (
        ServerState,
        resolve_compute_dtype,
    )
    from fedml_tpu.core.client import make_client_optimizer, make_local_update
    from fedml_tpu.models import resnet as resnet_mod

    if args.model == "mlp":
        # 8x8 inputs through a small MLP: the harness logic (meshes,
        # ladders, fused rounds, timing) without conv compile cost
        from fedml_tpu.models.linear import mlp2

        image = 8
        bundle = mlp2(image * image * 3, 32, 10, input_shape=(image, image, 3))
    else:
        image = 32 if args.model == "resnet56" else 16
        bundle = getattr(resnet_mod, args.model)(num_classes=10, image_size=image)
    opt = make_client_optimizer("sgd", 0.01, momentum=0.9)
    local_update = make_local_update(
        bundle, opt, epochs=1,
        compute_dtype=resolve_compute_dtype(
            "bf16" if args.platform != "cpu" else None
        ),
    )

    def fresh_state():
        key = jax.random.PRNGKey(0)
        return ServerState(
            variables=bundle.init(key), opt_state=(),
            round_idx=jnp.zeros((), jnp.int32), key=key,
        )

    S, B = args.steps, args.batch
    results = []
    if args.mode == "chips":
        from fedml_tpu.parallel.spmd import (
            make_client_mesh, make_spmd_round_fn, replicate,
            shard_client_block,
        )

        ladder, d = [], 1
        while d <= jax.device_count():
            ladder.append(d)
            d *= 2
        t1 = None
        for D in ladder:
            mesh = make_client_mesh(D)
            rf = make_spmd_round_fn(mesh, local_update, donate=False)
            inputs = shard_client_block(
                mesh, _make_inputs(D, S, B, (image, image, 3), 10)
            )
            t, _ = _measure(rf, replicate(mesh, fresh_state()), inputs,
                            args.rounds)
            t1 = t1 if t1 is not None else t
            point = {
                "metric": "weak_scaling_round_time",
                "devices": D, "clients": D, "value": round(t, 4),
                "unit": "s/round", "efficiency": round(t1 / t, 3),
            }
            if args.platform == "cpu" and (os.cpu_count() or 1) < D:
                # D faked devices time-share fewer physical cores: the
                # efficiency number measures the host, not the design
                point["note"] = (
                    f"{D} virtual devices on {os.cpu_count()} core(s) — "
                    "correctness/harness validation only"
                )
            results.append(point)
    else:
        from fedml_tpu.algorithms.fedavg import make_multi_round_fn

        rpc = args.rounds_per_call
        rf = jax.jit(make_multi_round_fn(local_update, rpc))
        for C in (1, 2, 4, 8, 16):
            inputs = tuple(
                jnp.asarray(a)
                for a in _make_inputs(C, S, B, (image, image, 3), 10)
            )
            t, _ = _measure(rf, fresh_state(), inputs, args.rounds)
            results.append({
                "metric": "clients_per_chip_throughput",
                "clients": C, "value": round(C * S * B * rpc / t, 1),
                "unit": "samples/sec", "s_per_round": round(t / rpc, 4),
                "rounds_per_call": rpc,
            })

    for r in results:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
