#!/usr/bin/env python
"""Automated postmortem over a run_dir's flight-recorder bundles.

Input: the ``flight-<node>.json`` black boxes that
``fedml_tpu/obs/flight.py`` dumps into a run_dir — one per process
(hub, server ``node0``, clients ``node<id>``, muxers ``mux<id>``),
written on trigger (crash, deadline overrun, reject, conn death, chaos
fault, SLO violation, ...) and flushed once more at clean exit.  No
metrics files, no tracing, no live processes required: the verdict is
built from what each process's own rings recorded before it died.

Pipeline:

1. **Merge onto one clock.**  Every bundle pins its dial-time
   ``clock_sync`` offset estimate — the SAME min-RTT estimate
   ``tools/fed_timeline.py`` uses (``t_hub = t_local + offset_s``).
   When every bundle has one, all stamps are mapped onto the hub's
   monotonic clock exactly like the timeline merges metrics files;
   otherwise the merge falls back to the wall clock through each
   bundle's own ``(t_m_dump, t_wall_dump)`` anchor (ms-level, plenty
   for round-scale forensics — and immune to hub restarts resetting
   the monotonic origin).

2. **Locate the rounds.**  The server bundle's ``round_close`` events
   carry ``t_open_m``/``t_close_m``; mapped onto the shared clock they
   give per-round intervals every other bundle's evidence is bucketed
   into.

3. **Attribute the fault.**  An ordered decision tree over the merged
   evidence — explicit crash dumps beat chaos-injection records beat
   inferred signatures (reconnect storms, every-frame shm fallbacks,
   repeated deadline overruns) beat server-side tolerance observations
   — names a fault kind, the round it hit, and the evidence chain.

4. **Diff the anomalous round** against the nearest healthy one:
   span medians (decode waits, fold stalls, round walls —
   ``fed_timeline.percentile``, same estimator), hub queue samples,
   comm bytes/frames, fallback + fault counts.

Output: a machine-readable verdict JSON (stdout and/or ``--out``) and
optionally a Perfetto/Chrome trace-event export of the final recorded
window (``--perfetto``): one process track per bundle, one thread per
ring category, an instant event per ring entry plus trigger markers.

Usage:

    python tools/fed_forensics.py <run_dir> --out verdict.json
    python tools/fed_forensics.py <run_dir> --perfetto flight.trace.json

``tools/chaos_run.py`` runs this automatically per scenario and
attaches the verdict to each scenario record.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import fed_timeline  # noqa: E402  (shared percentile + offset conventions)

SCHEMA = 1

# chaos-layer action names (faults/chaos.py ``_inject``) -> fault kind
STRIPE_ACTIONS = ("drop_stripe", "corrupt_stripe")
BYZANTINE_ACTIONS = ("sign_flip", "scale_grad")
TELEMETRY_MSG_TYPES = ("C2S_TELEMETRY",)


def parse_metric_key(key: str):
    """``name{k=v,...}`` -> (name, labels) — mirror of
    ``obs.telemetry.parse_metric_key`` (this tool must run on a bare
    interpreter with no fedml_tpu import)."""
    if not key.endswith("}") or "{" not in key:
        return key, {}
    name, _, inner = key.partition("{")
    labels = {}
    for part in inner[:-1].split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


# -- loading ----------------------------------------------------------------

def load_bundles(run_dir: str) -> Tuple[Dict[str, dict], Dict[str, str]]:
    """tag -> bundle for every parseable flight-*.json; unparseable
    files (a process killed mid-``os.replace`` cannot produce one, but
    a truncated copy can) are reported, never fatal."""
    bundles: Dict[str, dict] = {}
    errors: Dict[str, str] = {}
    for path in sorted(glob.glob(os.path.join(run_dir, "flight-*.json"))):
        tag = os.path.basename(path)[len("flight-"):-len(".json")]
        try:
            with open(path) as fh:
                b = json.load(fh)
            if b.get("schema") != SCHEMA:
                raise ValueError(f"unknown bundle schema {b.get('schema')}")
            b["_path"] = path
            bundles[tag] = b
        except (OSError, ValueError, json.JSONDecodeError) as e:
            errors[path] = f"{type(e).__name__}: {e}"
    return bundles, errors


class Clock:
    """Map each bundle's local monotonic stamps onto ONE shared axis
    (see module doc, step 1)."""

    def __init__(self, bundles: Dict[str, dict]):
        self.offsets: Dict[str, float] = {}
        have_all = True
        for tag, b in bundles.items():
            cs = b.get("clock_sync") or {}
            off = cs.get("offset_s")
            if tag == "hub":
                self.offsets[tag] = 0.0
            elif off is not None:
                self.offsets[tag] = float(off)
            else:
                have_all = False
        self.mode = "hub_monotonic" if (have_all and bundles) else "wall"
        self._anchors = {
            tag: (float(b.get("t_m_dump") or 0.0),
                  float(b.get("t_wall_dump") or 0.0))
            for tag, b in bundles.items()
        }

    def t(self, tag: str, t_m) -> Optional[float]:
        """One bundle-local monotonic stamp -> shared axis."""
        if t_m is None:
            return None
        t_m = float(t_m)
        if self.mode == "hub_monotonic":
            return t_m + self.offsets.get(tag, 0.0)
        t_m_dump, t_wall_dump = self._anchors.get(tag, (0.0, 0.0))
        return t_wall_dump - (t_m_dump - t_m)


# -- rounds -----------------------------------------------------------------

def round_intervals(bundles: Dict[str, dict],
                    clock: Clock) -> List[dict]:
    """[{round, t_open, t_close}] on the shared clock, from the first
    bundle carrying ``round_close`` events (the server's, normally)."""
    for tag in (["node0"] + sorted(bundles)):
        b = bundles.get(tag)
        if b is None:
            continue
        rows = [r for r in (b.get("rings") or {}).get("events", ())
                if r.get("kind") == "round_close" and r.get("round")
                is not None]
        if not rows:
            continue
        out = []
        for r in sorted(rows, key=lambda r: r["round"]):
            out.append({
                "round": int(r["round"]),
                "t_open": clock.t(tag, r.get("t_open_m")),
                "t_close": clock.t(tag, r.get("t_close_m", r.get("t_m"))),
            })
        return out
    return []


def locate_round(t: Optional[float], intervals: List[dict],
                 slack_s: float = 0.5) -> Optional[int]:
    """Which round was active at shared-clock time ``t``: containment
    first (with slack for queue/wire latency ahead of the open stamp),
    else nearest interval midpoint."""
    if t is None or not intervals:
        return None
    for iv in intervals:
        lo = iv["t_open"] - slack_s if iv["t_open"] is not None else None
        hi = iv["t_close"] + slack_s if iv["t_close"] is not None else None
        if lo is not None and hi is not None and lo <= t <= hi:
            return iv["round"]
    best, best_d = None, None
    for iv in intervals:
        pts = [p for p in (iv["t_open"], iv["t_close"]) if p is not None]
        if not pts:
            continue
        d = min(abs(t - p) for p in pts)
        if best_d is None or d < best_d:
            best, best_d = iv["round"], d
    return best


# -- evidence ---------------------------------------------------------------

def _counters(bundle: dict) -> Dict[str, float]:
    return (bundle.get("telemetry") or {}).get("counters") or {}


def collect_evidence(bundles: Dict[str, dict], clock: Clock) -> dict:
    """Flatten every bundle's triggers, fault-ring records, and
    headline counters into one evidence pool."""
    ev = {
        "crashes": [],            # {tag, round, reason, t}
        "exceptions": [],         # {tag, reason, t}
        "conn_deaths": [],        # {tag, reason, t}
        "deadline_overruns": [],  # {tag, round, reason, t}
        "rejects": [],            # {tag, round, reason, what, t}
        "slo_violations": [],     # {tag, round, reason, t}
        "decisions": [],          # {tag, direction, msg_type, round,
                                  #  actions, t}
        "injections": {},         # action -> {count, msg_types, tags,
                                  #  first_t, first_round}
        "shm_refusals": [],       # {tag, reason, t}
        "reconnects": 0.0,
        "shm_frames": defaultdict(float),     # tag -> frames sent
        "shm_fallbacks": defaultdict(float),  # reason -> count
        "capped_conns": 0.0,
    }
    trig_dst = {"crash": "crashes", "exception": "exceptions",
                "conn_death": "conn_deaths",
                "deadline_overrun": "deadline_overruns",
                "reject": "rejects", "slo_violation": "slo_violations"}
    for tag, b in bundles.items():
        for rec in b.get("history") or ():
            dst = trig_dst.get(rec.get("kind"))
            if dst is None:
                continue
            ev[dst].append({"tag": tag, "round": rec.get("round"),
                            "reason": rec.get("reason"),
                            "t": clock.t(tag, rec.get("t_m"))})
        rings = b.get("rings") or {}
        for row in rings.get("faults", ()):
            k = row.get("kind")
            t = clock.t(tag, row.get("t_m"))
            if k == "decision":
                ev["decisions"].append({
                    "tag": tag, "direction": row.get("direction"),
                    "msg_type": row.get("msg_type"),
                    "round": row.get("round"),
                    "actions": row.get("actions") or [], "t": t})
            elif k == "observed" and row.get("what"):
                ev["rejects"].append({"tag": tag, "round": None,
                                      "reason": None,
                                      "what": row.get("what"), "t": t})
        for row in rings.get("comm", ()):
            if row.get("kind") == "shm_refusal":
                ev["shm_refusals"].append({
                    "tag": tag, "reason": row.get("reason"),
                    "t": clock.t(tag, row.get("t_m"))})
        for key, val in _counters(b).items():
            name, labels = parse_metric_key(key)
            if name == "faults.injected":
                a = labels.get("action", "?")
                slot = ev["injections"].setdefault(
                    a, {"count": 0.0, "msg_types": set(), "tags": set(),
                        "first_t": None, "first_round": None})
                slot["count"] += val
                if labels.get("msg_type"):
                    slot["msg_types"].add(labels["msg_type"])
                slot["tags"].add(tag)
            elif name == "comm.reconnects":
                ev["reconnects"] += val
            elif name == "comm.shm_frames":
                ev["shm_frames"][tag] += val
            elif name == "comm.shm_fallbacks":
                ev["shm_fallbacks"][labels.get("reason", "?")] += val
            elif name == "robust.capped_conns":
                ev["capped_conns"] += val
    # stamp each injected action's first sighting from the fault rings
    for d in ev["decisions"]:
        for a in d["actions"]:
            # ring decisions use the plan action name; stripe decisions
            # surface in counters as drop_stripe/corrupt_stripe
            keys = [a] if a in ev["injections"] else \
                [f"{a}_stripe"] if f"{a}_stripe" in ev["injections"] else []
            for key in keys:
                slot = ev["injections"][key]
                if slot["first_t"] is None or (d["t"] is not None
                                               and d["t"] < slot["first_t"]):
                    slot["first_t"] = d["t"]
                if d["round"] is not None and (
                        slot["first_round"] is None
                        or d["round"] < slot["first_round"]):
                    slot["first_round"] = d["round"]
    return ev


# -- attribution ------------------------------------------------------------

def _first(rows: List[dict]) -> dict:
    known = [r for r in rows if r.get("t") is not None]
    return min(known, key=lambda r: r["t"]) if known else rows[0]


def _inj_round(slot: dict, intervals: List[dict]) -> Optional[int]:
    if slot.get("first_round") is not None:
        return int(slot["first_round"])
    return locate_round(slot.get("first_t"), intervals)


_CONF_RANK = {"high": 0, "medium": 1, "low": 2}


def attribute(bundles: Dict[str, dict], clock: Clock,
              intervals: List[dict], ev: dict) -> dict:
    """Evidence channels -> RANKED verdict set.

    Each independent evidence channel — crash dumps per process, each
    chaos injection family, inferred signatures (reconnect storms, shm
    saturation, deadline overruns), tolerance observations — contributes
    its own candidate ``{fault_kind, fault_round, confidence,
    evidence}``, so SIMULTANEOUS faults (a muxer crash DURING a
    telemetry-drop plan; a straggler riding an overload burst) each get
    a verdict instead of the highest-priority one shadowing the rest.
    The full ranked list rides ``verdicts`` (explicit beats injected
    beats inferred, stable within a confidence tier); the dominant
    verdict's fields stay top-level for single-fault consumers
    (``chaos_run``'s per-scenario record).  Channels that merely
    RESTATE a higher channel's root cause (overrun-inferred straggler
    when a ``delay`` plan injected one; reject-inferred corruption when
    a ``corrupt`` plan is on record) stay suppressed — the set is of
    distinct faults, not of evidence echoes."""

    def verdict(kind, rnd, conf, evidence):
        return {"fault_kind": kind, "fault_round": rnd,
                "confidence": conf, "evidence": evidence}

    cands: List[dict] = []

    # 1. processes that dumped crash bundles on the way down — one
    # verdict PER crashed process (two workers dying in one run are
    # two faults, not one)
    crashes_by_tag: Dict[str, List[dict]] = defaultdict(list)
    for c in ev["crashes"]:
        crashes_by_tag[c["tag"]].append(c)
    for tag in sorted(crashes_by_tag):
        c = _first(crashes_by_tag[tag])
        if tag.startswith("mux"):
            shm = ev["shm_frames"].get(tag, 0.0) or any(
                r["tag"] == tag for r in ev["shm_refusals"])
            kind = "shm_peer_crash" if shm else "muxer_crash"
        elif tag.startswith("edge"):
            kind = "edge_hub_crash"
        elif tag.startswith("node") and tag != "node0":
            kind = "client_crash"
        else:
            kind = "crash"
        rnd = c.get("round")
        if rnd is None:
            rnd = locate_round(c.get("t"), intervals)
        cands.append(verdict(kind, rnd, "high", [
            {"source": tag, "kind": "crash_trigger",
             "reason": c.get("reason"), "round": c.get("round")}]))

    # 2. chaos-layer injections recorded by the injecting process —
    # one verdict per injected FAMILY, all of them (a plan that both
    # delays and drops is two concurrent faults)
    inj = ev["injections"]
    claimed: set = set()
    if inj:
        def ivd(action):
            slot = inj[action]
            return {"source": sorted(slot["tags"]),
                    "kind": "faults.injected", "action": action,
                    "count": slot["count"],
                    "msg_types": sorted(slot["msg_types"])}

        stripe = [a for a in STRIPE_ACTIONS if a in inj]
        if stripe:
            claimed.update(stripe)
            rnd = _inj_round(inj[stripe[0]], intervals)
            cands.append(verdict("stripe_fault", rnd, "high",
                                 [ivd(a) for a in stripe]))
        byz = [a for a in BYZANTINE_ACTIONS if a in inj]
        if byz:
            claimed.update(byz)
            a = byz[0]
            from_mux = any(t.startswith("mux") for t in inj[a]["tags"])
            kind = "malicious_muxer" if from_mux else "malicious_client"
            extra = []
            if ev["capped_conns"]:
                extra.append({"source": "server", "kind": "counter",
                              "name": "robust.capped_conns",
                              "count": ev["capped_conns"]})
            cands.append(verdict(kind, _inj_round(inj[a], intervals),
                                 "high", [ivd(x) for x in byz] + extra))
        if "corrupt" in inj:
            claimed.add("corrupt")
            rnd = _inj_round(inj["corrupt"], intervals)
            if rnd is None:
                served = [r for r in ev["rejects"]
                          if r.get("round") is not None]
                rnd = min(r["round"] for r in served) if served else None
            cands.append(verdict("corrupt_upload", rnd, "high",
                                 [ivd("corrupt")]))
        if "delay" in inj:
            claimed.add("delay")
            cands.append(verdict(
                "straggler", _inj_round(inj["delay"], intervals),
                "high", [ivd("delay")]))
        if "drop" in inj:
            claimed.add("drop")
            slot = inj["drop"]
            if slot["msg_types"] and slot["msg_types"] <= set(
                    TELEMETRY_MSG_TYPES):
                rnd = _inj_round(slot, intervals)
                if rnd is None and ev["slo_violations"]:
                    rnd = _first(ev["slo_violations"]).get("round")
                cands.append(verdict("telemetry_loss", rnd, "high",
                                     [ivd("drop")]))
            else:
                cands.append(verdict(
                    "message_drop", _inj_round(slot, intervals),
                    "high", [ivd("drop")]))
        for a in sorted(set(inj) - claimed):
            cands.append(verdict(
                f"chaos:{a}", _inj_round(inj[a], intervals),
                "medium", [ivd(a)]))

    # 3. hub restart: dialers saw their hub connection die AND come
    # back — suppressed when a crash verdict already explains the
    # conn deaths (a dead worker's peers see its connection die too)
    if ev["reconnects"] and ev["conn_deaths"] and not crashes_by_tag:
        deaths = [d for d in ev["conn_deaths"] if d["tag"] != "hub"]
        d = _first(deaths or ev["conn_deaths"])
        cands.append(verdict(
            "hub_restart", locate_round(d.get("t"), intervals),
            "medium", [
                {"source": d["tag"], "kind": "conn_death",
                 "reason": d.get("reason")},
                {"source": "dialers", "kind": "counter",
                 "name": "comm.reconnects", "count": ev["reconnects"]}]))

    # 4. shm ring saturation: every payload took the counted fallback
    ring_full = ev["shm_fallbacks"].get("ring_full", 0.0) + \
        ev["shm_fallbacks"].get("desc_full", 0.0)
    if ring_full:
        refusals = [r for r in ev["shm_refusals"]
                    if r.get("reason") in ("ring_full", "desc_full")]
        rnd = locate_round(_first(refusals)["t"], intervals) \
            if refusals else (intervals[0]["round"] if intervals else None)
        cands.append(verdict("shm_ring_full", rnd, "medium", [
            {"source": "senders", "kind": "counter",
             "name": "comm.shm_fallbacks",
             "by_reason": dict(ev["shm_fallbacks"])}]))

    # 5. repeated deadline overruns with no DELAY injected: a
    # straggler the plans didn't schedule (open-loop traffic, a slow
    # device) — an injected delay already claimed this signature
    overruns = [o for o in ev["deadline_overruns"]
                if o.get("round") is not None]
    if overruns and "delay" not in inj:
        rounds = sorted({o["round"] for o in overruns})
        conf = "medium" if len(rounds) >= 2 else "low"
        cands.append(verdict("straggler", rounds[0], conf, [
            {"source": sorted({o["tag"] for o in overruns}),
             "kind": "deadline_overrun", "rounds": rounds}]))

    # 6. lock contention: the CheckedLock tap recorded real blocking
    # (acquire waits past the flight threshold) somewhere in the
    # federation — surfaced as its own verdict so a reactor-loop or
    # round-lock stall is attributable evidence, not a wall-time
    # hunch.  Low confidence: contention usually EXPLAINS a latency
    # symptom rather than being the injected fault, so it must never
    # shadow a crash/injection verdict (rank keeps it below those).
    hot = [row for row in lock_contention(bundles)
           if row["wait_total_s"] >= 0.05 or row["wait_max_s"] >= 0.02]
    if hot:
        worst = hot[0]
        cands.append(verdict("lock_contention", None, "low", [
            {"source": row["tag"], "kind": "lock_wait",
             "lock": row["lock"], "contended": row["contended"],
             "wait_total_s": row["wait_total_s"],
             "wait_max_s": row["wait_max_s"]}
            for row in hot[:6]] + [
            {"source": worst["tag"], "kind": "hottest_lock",
             "lock": worst["lock"]}]))

    # 7. server-side tolerance observations without injector bundles
    # (with injections on record the rejects are their echo, not a
    # second fault)
    if ev["rejects"] and not inj:
        whats = {r.get("what") for r in ev["rejects"]} - {None}
        served = [r for r in ev["rejects"] if r.get("round") is not None]
        rnd = min(r["round"] for r in served) if served else \
            locate_round(_first(ev["rejects"]).get("t"), intervals)
        kind = "malicious_client" if "outlier_upload" in whats \
            else "corrupt_upload"
        cands.append(verdict(kind, rnd, "low", [
            {"source": "server", "kind": "rejects",
             "what": sorted(whats), "count": len(ev["rejects"])}]))

    # 8. weakest channels: only when nothing stronger found anything
    if not cands and ev["slo_violations"]:
        v = _first(ev["slo_violations"])
        cands.append(verdict("telemetry_loss", v.get("round"), "low", [
            {"source": v["tag"], "kind": "slo_violation",
             "reason": v.get("reason")}]))
    if not cands and ev["exceptions"]:
        e = _first(ev["exceptions"])
        cands.append(verdict(
            "exception", locate_round(e.get("t"), intervals),
            "low", [{"source": e["tag"], "kind": "exception",
                     "reason": e.get("reason")}]))

    if not cands:
        cands.append(verdict(
            "none", None, "high",
            [{"kind": "no_anomaly",
              "detail": "no trigger, injection, or tolerance "
                        "observation in any bundle"}]))

    # rank: confidence tier first, channel priority (generation order)
    # within a tier — python's sort is stable
    cands.sort(key=lambda v: _CONF_RANK.get(v["confidence"], 3))
    return {**cands[0], "verdicts": cands}


# -- round diff -------------------------------------------------------------

def round_profiles(bundles: Dict[str, dict], clock: Clock,
                   intervals: List[dict]) -> Dict[int, dict]:
    """Per-round aggregates over every bundle's rings: span medians
    (queue waits, fold stalls), hub queue-depth samples, comm volume,
    fault/fallback activity."""
    spans: Dict[int, Dict[str, list]] = defaultdict(lambda: defaultdict(list))
    hubq: Dict[int, Dict[str, list]] = defaultdict(lambda: defaultdict(list))
    scal: Dict[int, Dict[str, float]] = defaultdict(lambda: defaultdict(float))
    for tag, b in bundles.items():
        rings = b.get("rings") or {}
        for row in rings.get("spans", ()):
            r = locate_round(clock.t(tag, row.get("t_m")), intervals)
            if r is not None and isinstance(row.get("v"), (int, float)):
                spans[r][row["kind"]].append(float(row["v"]))
        for row in rings.get("comm", ()):
            r = locate_round(clock.t(tag, row.get("t_m")), intervals)
            if r is None:
                continue
            k = row.get("kind")
            if k in ("send", "recv"):
                scal[r]["comm_frames"] += 1
                scal[r]["comm_bytes"] += float(row.get("nbytes") or 0)
            elif k == "shm_refusal":
                scal[r]["shm_refusals"] += 1
        for row in rings.get("faults", ()):
            r = row.get("round")
            if r is None:
                r = locate_round(clock.t(tag, row.get("t_m")), intervals)
            if r is None:
                continue
            if row.get("kind") == "decision":
                scal[r]["fault_decisions"] += 1
            elif row.get("kind") == "observed":
                scal[r]["tolerance_observations"] += 1
        for row in rings.get("events", ()):
            k = row.get("kind")
            if k == "hub_stats":
                r = locate_round(clock.t(tag, row.get("t_m")), intervals)
                if r is None:
                    continue
                for fk, fv in row.items():
                    if fk in ("t_m", "kind", "ts"):
                        continue
                    if isinstance(fv, (int, float)):
                        hubq[r][fk].append(float(fv))
            elif k == "degraded_round" and row.get("round") is not None:
                scal[int(row["round"])]["degraded"] = 1
    out: Dict[int, dict] = {}
    for iv in intervals:
        r = iv["round"]
        out[r] = {
            "spans_p50": {name: fed_timeline.percentile(vals, 0.5)
                          for name, vals in sorted(spans[r].items())},
            "hub_stats_max": {name: max(vals)
                              for name, vals in sorted(hubq[r].items())},
            **{k: v for k, v in sorted(scal[r].items())},
        }
    return out


def diff_rounds(profiles: Dict[int, dict], bad: Optional[int],
                anomalous: set) -> Optional[dict]:
    """Anomalous round vs the NEAREST round not itself implicated."""
    if bad is None or bad not in profiles:
        return None
    healthy = [r for r in profiles if r not in anomalous]
    if not healthy:
        return None
    ref = min(healthy, key=lambda r: (abs(r - bad), r))
    pb, ph = profiles[bad], profiles[ref]

    def flat(p):
        out = {}
        for k, v in p.items():
            if isinstance(v, dict):
                for k2, v2 in v.items():
                    out[f"{k}.{k2}"] = v2
            else:
                out[k] = v
        return out

    fb, fh = flat(pb), flat(ph)
    metrics = {}
    for k in sorted(set(fb) | set(fh)):
        a, h = fb.get(k), fh.get(k)
        row = {"anomalous": a, "healthy": h}
        if isinstance(a, (int, float)) and isinstance(h, (int, float)) \
                and h:
            row["ratio"] = round(a / h, 3)
        metrics[k] = row
    return {"round": bad, "vs_round": ref, "metrics": metrics}


# -- lock contention --------------------------------------------------------

def lock_contention(bundles: Dict[str, dict]) -> List[dict]:
    """Rank locks by recorded wait time across every bundle's ``locks``
    ring (the CheckedLock tap's ``wait_s`` measurements — present only
    when the run had ``FEDML_TPU_CHECKED_LOCKS=1``).  A hot aggregation
    lock shows up here as nonzero total/max wait with the owning
    process tag, instead of as a wall-time hunch."""
    agg: Dict[tuple, dict] = {}
    for tag, b in bundles.items():
        rings = b.get("rings") or {}
        for row in rings.get("locks", ()):
            name = row.get("lock")
            if not name:
                continue
            w = row.get("wait_s")
            w = float(w) if isinstance(w, (int, float)) else 0.0
            ent = agg.setdefault((tag, name), {
                "tag": tag, "lock": name, "acquires": 0,
                "contended": 0, "wait_total_s": 0.0, "wait_max_s": 0.0,
            })
            ent["acquires"] += 1
            if w > 1e-4:  # >100 us of blocking = a real contention event
                ent["contended"] += 1
            ent["wait_total_s"] += w
            ent["wait_max_s"] = max(ent["wait_max_s"], w)
    out = sorted(agg.values(),
                 key=lambda e: (-e["wait_total_s"], e["tag"], e["lock"]))
    for e in out:
        e["wait_total_s"] = round(e["wait_total_s"], 6)
        e["wait_max_s"] = round(e["wait_max_s"], 6)
    return out[:24]


# -- perfetto ---------------------------------------------------------------

def to_perfetto(bundles: Dict[str, dict], clock: Clock) -> dict:
    """Chrome trace-event JSON of the final recorded window: one
    process track per bundle, one thread per ring category, an instant
    event per ring entry, a marker per trigger."""
    events: List[dict] = []
    tags = sorted(bundles)
    all_t: List[float] = []
    for tag in tags:
        b = bundles[tag]
        for rows in (b.get("rings") or {}).values():
            for row in rows:
                t = clock.t(tag, row.get("t_m"))
                if t is not None:
                    all_t.append(t)
    if not all_t:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(all_t)

    def us(t: Optional[float]) -> Optional[float]:
        return None if t is None else round((t - base) * 1e6, 1)

    for pid, tag in enumerate(tags, start=1):
        b = bundles[tag]
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": f"flight {tag}"}})
        cats = sorted((b.get("rings") or {}))
        for tid, cat in enumerate(cats, start=1):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": cat}})
            for row in (b.get("rings") or {})[cat]:
                t = us(clock.t(tag, row.get("t_m")))
                if t is None:
                    continue
                args = {k: v for k, v in row.items()
                        if k not in ("t_m",) and isinstance(
                            v, (str, int, float, bool))}
                events.append({"ph": "i", "s": "t", "pid": pid, "tid": tid,
                               "ts": t, "cat": cat,
                               "name": str(row.get("kind")), "args": args})
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": 0, "args": {"name": "triggers"}})
        for rec in b.get("history") or ():
            t = us(clock.t(tag, rec.get("t_m")))
            if t is None:
                continue
            events.append({"ph": "i", "s": "p", "pid": pid, "tid": 0,
                           "ts": t, "cat": "trigger",
                           "name": f"trigger:{rec.get('kind')}",
                           "args": {"reason": rec.get("reason"),
                                    "round": rec.get("round")}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- top level --------------------------------------------------------------

def analyze(run_dir: str) -> dict:
    """run_dir -> verdict document (the chaos_run / CLI entry point)."""
    bundles, errors = load_bundles(run_dir)
    doc = {
        "schema": SCHEMA,
        "run_dir": run_dir,
        "bundles": {tag: b["_path"] for tag, b in bundles.items()},
        "bundle_errors": errors,
    }
    if not bundles:
        doc.update({"fault_kind": "no_bundles", "fault_round": None,
                    "confidence": "none", "evidence": [], "rounds": [],
                    "round_diff": None})
        return doc
    clock = Clock(bundles)
    intervals = round_intervals(bundles, clock)
    ev = collect_evidence(bundles, clock)
    v = attribute(bundles, clock, intervals, ev)
    # every ranked verdict's round is implicated, not just the top one
    anomalous = {c["fault_round"] for c in v.get("verdicts", [v])
                 if c.get("fault_round") is not None}
    for o in ev["deadline_overruns"]:
        if o.get("round") is not None:
            anomalous.add(o["round"])
    for r in ev["rejects"]:
        if r.get("round") is not None:
            anomalous.add(r["round"])
    profiles = round_profiles(bundles, clock, intervals)
    doc.update({
        "clock_mode": clock.mode,
        "rounds": intervals,
        **v,
        "triggers": [
            {"tag": tag, "kind": rec.get("kind"),
             "reason": rec.get("reason"), "round": rec.get("round"),
             "t": clock.t(tag, rec.get("t_m"))}
            for tag, b in sorted(bundles.items())
            for rec in (b.get("history") or ())
            if rec.get("kind") != "manual"
        ],
        "round_profiles": {str(r): p for r, p in profiles.items()},
        "round_diff": diff_rounds(profiles, v["fault_round"], anomalous),
        "lock_contention": lock_contention(bundles),
    })
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir")
    ap.add_argument("--out", default="",
                    help="also write the verdict JSON to this path")
    ap.add_argument("--perfetto", default="",
                    help="write a Chrome trace-event export of the "
                         "final recorded window to this path")
    args = ap.parse_args(argv)
    doc = analyze(args.run_dir)
    if args.perfetto:
        bundles, _ = load_bundles(args.run_dir)
        trace = to_perfetto(bundles, Clock(bundles))
        with open(args.perfetto, "w") as fh:
            json.dump(trace, fh)
        print(f"perfetto trace: {args.perfetto} "
              f"({len(trace['traceEvents'])} events)", file=sys.stderr)
    out = json.dumps(doc, indent=1, default=str)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(out + "\n")
    print(out)
    return 0 if doc.get("fault_kind") != "no_bundles" else 1


if __name__ == "__main__":
    sys.exit(main())
