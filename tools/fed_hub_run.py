#!/usr/bin/env python
"""Reactor-hub evidence run → ``FEDHUB_r20.json``.

A/B campaign over the PR-20 data plane — the selector-driven reactor
hub (``comm/tcp.py`` mode="reactor") against the retained threaded
plane — with every bar pre-declared:

**pins** — the byte-identity matrix: {fp32, int8+EF} x {tcp, shm} x
{full, delta} x {muxed, per-process}, each cell run ONCE per plane at
the same seed; the per-client sha256 upload digests must be identical
reactor-vs-threaded in all 16 cells (the reactor is a pure scheduling
change — same frames, same bytes, different thread inventory).

**threads** — the O(1)-threads claim, measured from /proc: a hub
subprocess under 512 raw dialer connections must hold ≤ 8 OS threads
(the threaded plane holds ~1 + senders + 2/conn ≈ 1040 at that point,
measured here at 32 conns where it is ~70).

**churn** — 512-conn accept/churn soak vs the threaded plane at 32:
reactor hub RSS and churn-wave accept p50 must stay ≤ 1.1x the
threaded-at-32 baseline (the reactor may not buy its fd scale with
per-conn memory or accept-path latency).

**round_wall** — end-to-end p50 round wall, 32 per-process clients in
the FEDLAT comm-dominant regime, ABBA-interleaved reps, verdict =
median of per-rep p50s (PR-6 protocol): reactor ≤ 1.05x threaded.

**zero_copy** — on the laned path (shm ring + muxer) the reactor hub
must report ``shm_hub_copies == 0`` with ``zero_copy_forwards > 0``:
inbound payloads stay pinned slab/pool regions end to end, released at
drain, never materialized.

**chaos** — summarized from the separate 17-scenario soak artifact:
``python tools/chaos_run.py --matrix default --out FAULTS_r20.json``
(run it first; this tool folds its verdict in by reference).

Usage:
    python tools/fed_hub_run.py --mode all --out FEDHUB_r20.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.trace_summary import percentile  # noqa: E402

ENV_HUB_MODE = "FEDML_TPU_HUB_MODE"


def _env(mode: str):
    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env[ENV_HUB_MODE] = mode
    return env


def _barrier(settle: float = 2.0):
    deadline = time.time() + 60.0
    while time.time() < deadline:
        out = subprocess.run(
            ["pgrep", "-f", "fedml_tpu.experiments.distributed_fedavg"],
            capture_output=True, text=True,
        ).stdout.strip()
        if not out:
            break
        time.sleep(1.0)
    time.sleep(settle)


def _round_walls(npz_path: str):
    import numpy as np

    z = np.load(npz_path)
    log = json.loads(str(z["round_log"]))
    stamps = [r["t"] for r in log if isinstance(r.get("t"), (int, float))]
    deltas = [round(b - a, 4) for a, b in zip(stamps, stamps[1:])]
    finite = all(
        bool(np.isfinite(z[k]).all())
        for k in z.files if k.startswith("leaf_")
    )
    return int(z["rounds"]), deltas, finite


def _digests(info):
    return {k: v for k, v in sorted(info.items())
            if k.endswith("_upload_digest")}


def _one(tag, mode, *, clients, rounds, seed, input_dim, train_samples,
         lane="tcp", bcast="full", codec="none", muxers=0,
         timeout=900.0, round_timeout=600.0):
    from fedml_tpu.experiments.distributed_fedavg import launch

    _barrier()
    out = os.path.join(tempfile.mkdtemp(prefix=f"fedhub_{tag}_"),
                       "final.npz")
    info: dict = {}
    t0 = time.time()
    rc = launch(
        num_clients=clients, rounds=rounds, seed=seed, batch_size=16,
        out_path=out, env=_env(mode), server_env=_env(mode), info=info,
        timeout=timeout, round_timeout=round_timeout,
        input_dim=input_dim, train_samples=train_samples,
        lane=lane, bcast=bcast, codec=codec, muxers=muxers,
    )
    if rc != 0:
        raise SystemExit(f"{tag}: federation failed rc={rc}")
    rounds_done, walls, finite = _round_walls(out)
    hub = info.get("hub_stats") or {}
    rec = {
        "tag": tag, "mode": mode, "clients": clients, "muxers": muxers,
        "lane": lane, "bcast": bcast, "codec": codec,
        "rounds": rounds_done, "nan_free": finite,
        "wall_s": round(time.time() - t0, 1),
        "round_wall_s": {"samples": walls,
                         "p50": percentile(walls, 0.5),
                         "p95": percentile(walls, 0.95)},
        "hub": {k: hub.get(k) for k in
                ("mode", "threads", "open_fds", "shm_frames",
                 "shm_hub_copies", "zero_copy_forwards") if k in hub},
        "digests": _digests(info),
    }
    print(json.dumps({k: rec[k] for k in
                      ("tag", "mode", "rounds", "nan_free", "wall_s")}),
          flush=True)
    return rec


# ---- pins: 16-cell reactor-vs-threaded byte identity ------------------------

def run_pins(args) -> dict:
    cells = {}
    ok = True
    for codec_tag, codec in (("fp32", "none"), ("int8ef", "int8")):
        for lane in ("tcp", "shm"):
            for bcast in ("full", "delta"):
                for topo_tag, muxers in (("mux", 1), ("proc", 0)):
                    cell = f"{codec_tag}|{lane}|{bcast}|{topo_tag}"
                    digs = {}
                    for mode in ("reactor", "threaded"):
                        rec = _one(
                            f"pin_{codec_tag}_{lane}_{bcast}_"
                            f"{topo_tag}_{mode}",
                            mode, clients=args.pin_clients,
                            rounds=args.pin_rounds, seed=args.seed,
                            input_dim=args.pin_input_dim,
                            train_samples=30, lane=lane, bcast=bcast,
                            codec=codec, muxers=muxers)
                        digs[mode] = rec["digests"]
                    same = (digs["reactor"] == digs["threaded"]
                            and bool(digs["reactor"]))
                    cells[cell] = {
                        "identical": same,
                        "n_digests": len(digs["reactor"]),
                    }
                    ok = ok and same
    return {
        "config": {"clients": args.pin_clients,
                   "rounds": args.pin_rounds,
                   "input_dim": args.pin_input_dim, "seed": args.seed,
                   "protocol": "one run per plane per cell, same seed; "
                               "per-client sha256 upload digests must "
                               "match exactly"},
        "cells": cells,
        "ok": ok,
    }


# ---- threads / churn: raw-dialer soak against a hub subprocess --------------

def _proc_status(pid: int):
    with open(f"/proc/{pid}/status") as fh:
        txt = fh.read()
    threads = int(re.search(r"Threads:\s*(\d+)", txt).group(1))
    rss_kb = int(re.search(r"VmRSS:\s*(\d+)", txt).group(1))
    return threads, rss_kb


def _spawn_hub(mode: str):
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "fedml_tpu.experiments.distributed_fedavg",
         "--role", "hub", "--port", "0"],
        stdout=subprocess.PIPE, text=True, env=_env(mode))
    line = proc.stdout.readline()
    if not line:
        raise SystemExit(f"{mode} hub died before announcing its port")
    return proc, json.loads(line)["hub_port"]


def _dial(port: int, node_id: int, timeout=15.0) -> float:
    """Hand-rolled hello-v1 dialer; returns connect->ACK latency."""
    t0 = time.perf_counter()
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    f = s.makefile("rb")
    s.sendall((json.dumps({"node_id": node_id}) + "\n").encode())
    ack = json.loads(f.readline())
    assert ack.get("__hub__") == "ack"
    lat = time.perf_counter() - t0
    s.sendall((json.dumps({"__hub__": "ping_done"}) + "\n").encode())
    f.close()
    return lat, s


def _soak_arm(mode: str, conns: int, churn_waves: int) -> dict:
    proc, port = _spawn_hub(mode)
    socks = {}
    try:
        fill_lat = []
        for i in range(conns):
            lat, s = _dial(port, 1000 + i)
            fill_lat.append(lat)
            socks[i] = s
        time.sleep(1.0)  # let registration settle before sampling
        threads, rss_kb = _proc_status(proc.pid)
        churn_lat = []
        wave = max(1, conns // 4)
        for w in range(churn_waves):
            for i in range(wave):
                socks.pop(i).close()
            time.sleep(0.5)
            for i in range(wave):
                lat, s = _dial(port, 1000 + i)
                churn_lat.append(lat)
                socks[i] = s
        threads2, rss2_kb = _proc_status(proc.pid)
        return {
            "mode": mode, "conns": conns, "churn_waves": churn_waves,
            "threads": max(threads, threads2),
            "rss_mb": round(max(rss_kb, rss2_kb) / 1024, 1),
            "accept_p50_s": percentile(sorted(fill_lat), 0.5),
            "churn_accept_p50_s": (percentile(sorted(churn_lat), 0.5)
                                   if churn_lat else None),
        }
    finally:
        for s in socks.values():
            try:
                s.close()
            except OSError:
                pass
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def run_soak(args) -> dict:
    reactor = _soak_arm("reactor", args.soak_conns, churn_waves=3)
    threaded = _soak_arm("threaded", 32, churn_waves=3)
    rss_ratio = (reactor["rss_mb"] / threaded["rss_mb"]
                 if threaded["rss_mb"] else None)
    accept_ratio = (
        reactor["churn_accept_p50_s"] / threaded["churn_accept_p50_s"]
        if threaded.get("churn_accept_p50_s") else None)
    threads_section = {
        "reactor_threads_512": reactor["threads"],
        "threaded_threads_32": threaded["threads"],
        "bar": "reactor process <= 8 OS threads at 512 conns",
        "ok": reactor["threads"] <= 8,
    }
    churn_section = {
        "reactor": reactor,
        "threaded_32": threaded,
        "rss_ratio": round(rss_ratio, 3) if rss_ratio else None,
        "accept_ratio": (round(accept_ratio, 3)
                         if accept_ratio else None),
        "thresholds_pre_declared": {
            "rss_ratio_max": 1.1,
            "accept_ratio_max": 1.1,
        },
        "ok": bool(rss_ratio is not None and rss_ratio <= 1.1
                   and accept_ratio is not None and accept_ratio <= 1.1),
    }
    return {"threads": threads_section, "churn": churn_section}


# ---- round wall: end-to-end ABBA A/B ----------------------------------------

def run_round_wall(args) -> dict:
    arms = {"reactor": [], "threaded": []}
    for i in range(args.reps):
        order = list(arms) if i % 2 == 0 else list(arms)[::-1]
        for mode in order:
            arms[mode].append(_one(
                f"p50_{mode}_r{i}", mode, clients=args.ab_clients,
                rounds=args.ab_rounds, seed=args.seed,
                input_dim=args.input_dim,
                train_samples=args.train_samples,
                timeout=args.timeout))
    p50 = {k: percentile([r["round_wall_s"]["p50"] for r in v], 0.5)
           for k, v in arms.items()}
    ratio = (p50["reactor"] / p50["threaded"]
             if p50.get("threaded") else None)
    return {
        "config": {"clients": args.ab_clients, "rounds": args.ab_rounds,
                   "input_dim": args.input_dim,
                   "train_samples": args.train_samples,
                   "reps": args.reps,
                   "protocol": "ABBA interleaved, process barrier + "
                               "settle, verdict = median of per-rep "
                               "p50s (PR-6)"},
        "arms": arms,
        "p50_by_arm": p50,
        "ratio": round(ratio, 3) if ratio else None,
        "thresholds_pre_declared": {"ratio_max": 1.05},
        "ok": bool(ratio is not None and ratio <= 1.05),
    }


# ---- zero copy: laned path, reactor -----------------------------------------

def run_zero_copy(args) -> dict:
    rec = _one("zcopy_shm_mux", "reactor", clients=8, rounds=3,
               seed=args.seed, input_dim=65536, train_samples=16,
               lane="shm", muxers=1)
    hub = rec["hub"]
    copies = hub.get("shm_hub_copies", -1)
    fwds = hub.get("zero_copy_forwards", 0)
    return {
        "run": {k: rec[k] for k in ("tag", "rounds", "nan_free")},
        "hub": hub,
        "shm_hub_copies": copies,
        "zero_copy_forwards": fwds,
        "thresholds_pre_declared": {
            "shm_hub_copies": 0,
            "zero_copy_forwards_min": 1,
        },
        "ok": bool(copies == 0 and fwds > 0),
    }


# ---- chaos: fold the separate FAULTS artifact in by reference ---------------

def run_chaos(args) -> dict:
    try:
        with open(args.faults) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return {"artifact": args.faults, "ok": False,
                "note": f"unreadable ({type(e).__name__}) — run "
                        f"tools/chaos_run.py --matrix default first"}
    scenarios = doc.get("scenarios") or []
    survived = sum(1 for s in scenarios if s.get("survived"))
    return {
        "artifact": args.faults,
        "scenarios": len(scenarios),
        "survived": survived,
        "all_nan_free": bool(doc.get("all_nan_free")),
        "ok": bool(doc.get("all_nan_free") and len(scenarios) >= 17
                   and survived == len(scenarios)),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mode",
                   choices=["pins", "soak", "round_wall", "zero_copy",
                            "chaos", "all"],
                   default="all")
    p.add_argument("--out", default="FEDHUB_r20.json")
    p.add_argument("--faults", default="FAULTS_r20.json")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--reps", type=int, default=2)
    p.add_argument("--ab-clients", type=int, default=32)
    p.add_argument("--ab-rounds", type=int, default=5)
    p.add_argument("--input-dim", type=int, default=131072)
    p.add_argument("--train-samples", type=int, default=16)
    p.add_argument("--pin-clients", type=int, default=4)
    p.add_argument("--pin-rounds", type=int, default=3)
    p.add_argument("--pin-input-dim", type=int, default=4096)
    p.add_argument("--soak-conns", type=int, default=512)
    p.add_argument("--timeout", type=float, default=900.0,
                   help="per-federation launch timeout for the A/B "
                        "round-wall arms (32 comm-heavy processes on "
                        "an oversubscribed box need headroom)")
    args = p.parse_args(argv)

    artifact = {}
    if os.path.exists(args.out):
        # partial re-runs MERGE into the existing artifact
        try:
            with open(args.out) as fh:
                artifact = json.load(fh)
        except (OSError, json.JSONDecodeError):
            artifact = {}
    artifact["experiment"] = (
        "reactor hub data plane: one selectors event-loop thread "
        "multiplexes every hub connection (streaming frame parser, "
        "bounded send queues, writability-driven drain) with "
        "end-to-end zero-copy routing (refcounted slab/pool pins, "
        "released at drain) — vs the retained threaded plane"
    )
    artifact["generated_unix"] = round(time.time(), 1)

    def _save():
        # verdict spans every section measured so far (this run or a
        # prior partial one), and the artifact lands on disk after EACH
        # section — a multi-hour campaign that dies mid-section keeps
        # everything already measured
        oks = [artifact[k].get("ok") for k in
               ("pins", "threads", "churn", "round_wall", "zero_copy",
                "chaos") if k in artifact]
        artifact["ok"] = bool(oks) and all(bool(o) for o in oks)
        with open(args.out, "w") as fh:
            json.dump(artifact, fh, indent=1, default=float)

    if args.mode in ("pins", "all"):
        artifact["pins"] = run_pins(args)
        _save()
    if args.mode in ("soak", "all"):
        soak = run_soak(args)
        artifact["threads"] = soak["threads"]
        artifact["churn"] = soak["churn"]
        _save()
    if args.mode in ("round_wall", "all"):
        artifact["round_wall"] = run_round_wall(args)
        _save()
    if args.mode in ("zero_copy", "all"):
        artifact["zero_copy"] = run_zero_copy(args)
        _save()
    if args.mode in ("chaos", "all"):
        artifact["chaos"] = run_chaos(args)
        _save()
    print(json.dumps({"out": args.out, "ok": artifact["ok"]}))
    return 0 if artifact["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
