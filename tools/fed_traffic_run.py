#!/usr/bin/env python
"""FEDBUFF evidence campaign: async buffered rounds vs the synchronous
barrier under open-loop production traffic.

The async server (``--round-mode async``) exists for exactly one
claim: when arrivals are an open-loop process — heavy-tailed straggler
delays, churn, diurnal load — cutting a round at K arrivals and
folding honest-but-late work at a staleness discount degrades
GRACEFULLY, where the barrier pays the full deadline every time one
device is slow or gone.  This campaign measures that claim as a
controlled experiment and writes the machine-readable verdict
(``FEDBUFF_r18.json``) that ``tools/bench_trend.py`` trends and gates.

Stages (each independently ok-flagged):

1. **determinism** — the traffic day replays bit-identically: the
   seeded ``TrafficModel``'s full (node x round) decision trace hashes
   to the same ``schedule_digest`` across a JSON ship-and-parse
   round trip, and a reseeded model diverges.  Both arms of stage 3
   therefore see the IDENTICAL arrival process — the A/B is
   controlled, not anecdotal.
2. **digest_pin** — the equivalence anchor: an in-process federation
   run sync and then async with ``stale_alpha=0`` (w == 1) at the same
   seed must produce BYTE-IDENTICAL final models (sha256 over the
   leaves).  Cut-based rounds are a superset of the barrier, not a
   different algorithm.
3. **openloop** — the headline A/B: >= 32 virtual clients over muxer
   processes, one seeded heavy-tailed straggler + churn + diurnal
   traffic plan shipped to both arms, sync vs async at the same seed.
   p99 round wall (sync, barrier/deadline-closed) vs p99 round-cut
   latency (async), both from the server's ``round_log``
   ``t_open_m/t_close_m`` stamps, plus final held-out accuracy per
   arm.

Pre-declared bars (``BARS`` below, declared before any measurement):
the sync p99 must exceed the async p99 by at least
``p99_factor_min``, and the async arm's final accuracy must not trail
sync by more than ``-acc_margin_min``.

Usage (CPU is fine — the contrast is protocol stalls, not FLOPs):

    python tools/fed_traffic_run.py --out FEDBUFF_r18.json
    python tools/fed_traffic_run.py --quick        # small smoke form
"""

from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# pre-declared acceptance bars — set BEFORE the campaign runs, never
# tuned to a measurement after the fact
BARS = {
    # sync p99 round wall / async p99 cut latency must be >= this
    "p99_factor_min": 1.2,
    # async final acc - sync final acc must be >= this (async may not
    # trail the barrier by more than 5 points under the same traffic)
    "acc_margin_min": -0.05,
}


def _worker_env():
    import chaos_run

    return chaos_run._worker_env()


def percentile(vals, q: float):
    """Nearest-rank percentile (the fed_timeline convention)."""
    if not vals:
        return None
    s = sorted(vals)
    return s[min(len(s) - 1, max(0, int(math.ceil(q * len(s))) - 1))]


def campaign_traffic(seed: int):
    """The campaign's one traffic day: heavy-tailed stragglers + churn
    + a diurnal swing.  Caps stay well under the round deadline so the
    tail hurts the BARRIER (it waits) rather than erasing uploads."""
    from fedml_tpu.faults.traffic import TrafficModel

    return TrafficModel(
        seed=seed,
        jitter_s=0.05,
        straggler_prob=0.3,
        straggler_shape=1.1,       # heavy tail: infinite variance
        straggler_scale_s=0.3,
        straggler_cap_s=2.0,
        churn_prob=0.08,
        flap_prob=0.02,
        diurnal_amplitude=0.5,
        diurnal_period_rounds=4,
    )


# -- stage 1: replay determinism ---------------------------------------------

def stage_determinism(seed: int, clients: int, rounds: int) -> dict:
    from fedml_tpu.faults.traffic import TrafficModel

    tm = campaign_traffic(seed)
    nodes = list(range(1, clients + 1))
    d1 = tm.schedule_digest(nodes, rounds)
    # the digest must survive the exact path the plan takes to worker
    # subprocesses: JSON out, env ride, JSON in
    d2 = TrafficModel.from_json(tm.to_json()).schedule_digest(nodes, rounds)
    d_other = TrafficModel.from_json(
        campaign_traffic(seed + 1).to_json()).schedule_digest(nodes, rounds)
    # deterministic trace statistics — the open-loop day in numbers
    # (computed from the pure model, identical in every process)
    offline = stragglers = delayed = rebinds = 0
    for r in range(rounds):
        for n in nodes:
            d = tm.decide(n, r)
            offline += d["offline"]
            stragglers += d["straggler"]
            rebinds += d["rebind"]
            delayed += d["delay_s"] > 0
    return {
        "schedule_digest": d1,
        "replay_digest": d2,
        "reseeded_digest": d_other,
        "replay_ok": d1 == d2,
        "reseeded_differs": d1 != d_other,
        "trace": {"node_rounds": clients * rounds, "offline": offline,
                  "stragglers": stragglers, "delayed": delayed,
                  "rebinds": rebinds},
        "ok": d1 == d2 and d1 != d_other,
    }


# -- stage 2: async == sync byte-identity at w == 1 --------------------------

def _model_digest(variables) -> str:
    import numpy as np

    import jax

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(variables):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def stage_digest_pin(seed: int) -> dict:
    """In-process 3-client federation, sync vs async(w==1), same seed:
    final models must hash identically — the byte-identity anchor."""
    import numpy as np

    import jax

    from fedml_tpu.algorithms.fedavg_cross_device import (
        FedAvgClientManager, FedAvgServerManager)
    from fedml_tpu.comm.inproc import InprocBus
    from fedml_tpu.core.client import make_client_optimizer, make_local_update
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models.linear import logistic_regression

    ds = synthetic_classification(
        num_train=240, num_test=60, input_shape=(16,), num_classes=4,
        num_clients=3, partition="hetero", partition_alpha=0.4, seed=seed)
    bundle = logistic_regression(16, 4)
    init = bundle.init(jax.random.PRNGKey(seed))
    lu = make_local_update(bundle, make_client_optimizer("sgd", 0.1,
                                                         momentum=0.9), 1)
    steps = int(np.ceil(ds.client_sample_counts().max() / 16))

    def run(**kw):
        bus = InprocBus()
        server = FedAvgServerManager(
            bus.register(0), init, num_clients=3, clients_per_round=3,
            comm_rounds=3, seed=seed, steps_per_epoch=steps, **kw)
        for i in range(3):
            FedAvgClientManager(bus.register(i + 1), lu, ds, batch_size=16,
                                template_variables=init, seed=seed)
        server.start()
        bus.drain()
        return _model_digest(server.variables)

    d_sync = run()
    d_async = run(round_mode="async", stale_alpha=0.0)
    return {"sync_digest": d_sync, "async_digest": d_async,
            "ok": d_sync == d_async}


# -- stage 3: open-loop A/B --------------------------------------------------

def _run_arm(name: str, *, clients: int, muxers: int, rounds: int,
             seed: int, round_timeout: float, traffic_json: str,
             timeout: float, extra: dict) -> dict:
    import numpy as np

    import chaos_run
    from fedml_tpu.experiments.distributed_fedavg import launch

    out_path = os.path.join(
        tempfile.mkdtemp(prefix=f"fedbuff_{name}_"), "final.npz")
    info: dict = {}
    t0 = time.time()
    print(f"== arm {name} ({clients} clients, {rounds} rounds) ==",
          flush=True)
    rc = launch(
        num_clients=clients, rounds=rounds, seed=seed, batch_size=16,
        out_path=out_path, muxers=muxers, round_timeout=round_timeout,
        traffic_plan=traffic_json, auto_reconnect=60,
        env=_worker_env(), info=info, timeout=timeout, **extra,
    )
    rec = {"arm": name, "rc": rc, "survived": rc == 0,
           "wall_s": round(time.time() - t0, 1),
           "rounds": info.get("rounds"),
           "rounds_degraded": info.get("rounds_degraded"),
           "rejected_uploads": info.get("rejected_uploads")}
    if os.path.exists(out_path):
        z = np.load(out_path)
        round_log = json.loads(str(z["round_log"]))
        walls = [r["t_close_m"] - r["t_open_m"] for r in round_log
                 if "t_open_m" in r and "t_close_m" in r]
        rec["round_wall_s"] = {
            "p50": percentile(walls, 0.5),
            "p99": percentile(walls, 0.99),
            "max": max(walls) if walls else None,
            "n": len(walls),
        }
        rec["p99_round_s"] = rec["round_wall_s"]["p99"]
        try:
            rec.update(chaos_run._final_model_eval(out_path, seed, clients))
        except Exception as e:
            rec["eval_error"] = f"{type(e).__name__}: {e}"
            rec["nan_free"] = False
    # server-side async/traffic counter evidence (the faults dict on
    # the server's exit line carries faults.* only; async.* counters
    # ride stats_plane rollup when on — keep what launch() collected)
    rec["stats_plane"] = info.get("stats_plane") or {}
    return rec


def stage_openloop(*, clients: int, muxers: int, rounds: int, seed: int,
                   round_timeout: float, cut_frac: float,
                   timeout: float) -> dict:
    traffic_json = campaign_traffic(seed).to_json()
    sync = _run_arm("sync", clients=clients, muxers=muxers, rounds=rounds,
                    seed=seed, round_timeout=round_timeout,
                    traffic_json=traffic_json, timeout=timeout, extra={})
    cut = max(1, int(clients * cut_frac))
    asyn = _run_arm("async", clients=clients, muxers=muxers, rounds=rounds,
                    seed=seed, round_timeout=round_timeout,
                    traffic_json=traffic_json, timeout=timeout,
                    extra={"round_mode": "async", "cut_size": cut})
    out = {"clients": clients, "muxers": muxers, "rounds": rounds,
           "cut_size": cut, "round_timeout_s": round_timeout,
           "sync": sync, "async": asyn}
    sp = (sync.get("round_wall_s") or {}).get("p99")
    ap = (asyn.get("round_wall_s") or {}).get("p99")
    factor = (sp / ap) if (sp and ap) else None
    margin = (asyn["final_acc"] - sync["final_acc"]) \
        if ("final_acc" in asyn and "final_acc" in sync) else None
    out["p99_factor_sync_over_async"] = factor
    out["acc_margin"] = margin
    out["bars"] = dict(BARS)
    out["ok"] = bool(
        sync.get("survived") and asyn.get("survived")
        and sync.get("nan_free") and asyn.get("nan_free")
        and factor is not None and factor >= BARS["p99_factor_min"]
        and margin is not None and margin >= BARS["acc_margin_min"]
    )
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="FEDBUFF_r18.json")
    p.add_argument("--clients", type=int, default=32)
    p.add_argument("--muxers", type=int, default=2)
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--round-timeout", type=float, default=15.0,
                   help="sync barrier deadline AND async cut deadline; "
                        "must exceed cold jit+train on the host")
    p.add_argument("--cut-frac", type=float, default=0.75,
                   help="async cut target as a fraction of the cohort")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--quick", action="store_true",
                   help="small smoke form (8 clients, 2 rounds)")
    p.add_argument("--skip-openloop", action="store_true",
                   help="stages 1-2 only (no subprocess federation)")
    args = p.parse_args(argv)
    if args.quick:
        args.clients, args.muxers, args.rounds = 8, 1, 2
        args.round_timeout = min(args.round_timeout, 12.0)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    doc = {
        "bars": dict(BARS),
        "config": {"clients": args.clients, "muxers": args.muxers,
                   "rounds": args.rounds, "seed": args.seed,
                   "cut_frac": args.cut_frac,
                   "round_timeout_s": args.round_timeout},
        "generated_unix": round(time.time(), 1),
    }
    doc["determinism"] = stage_determinism(args.seed, args.clients,
                                           args.rounds)
    print(json.dumps({"determinism_ok": doc["determinism"]["ok"]}),
          flush=True)
    doc["digest_pin"] = stage_digest_pin(args.seed)
    print(json.dumps({"digest_pin_ok": doc["digest_pin"]["ok"]}),
          flush=True)
    if not args.skip_openloop:
        doc["openloop"] = stage_openloop(
            clients=args.clients, muxers=args.muxers, rounds=args.rounds,
            seed=args.seed, round_timeout=args.round_timeout,
            cut_frac=args.cut_frac, timeout=args.timeout)
    oks = [doc["determinism"]["ok"], doc["digest_pin"]["ok"]] + \
        ([doc["openloop"]["ok"]] if "openloop" in doc else [])
    doc["ok"] = all(oks)
    with open(args.out, "w") as fh:
        json.dump(doc, fh, indent=1, default=float)
    print(json.dumps({"out": args.out, "ok": doc["ok"],
                      "stage_oks": oks}))
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
