#!/usr/bin/env python
"""Chaos soak driver: run a fault matrix over the multi-process TCP
federation and record per-scenario outcomes.

Each scenario spawns the REAL process topology (hub + server + N client
OS processes over sockets, ``experiments/distributed_fedavg.launch``)
and injects one failure mode; the federation must survive to the final
round with a finite global model.  Default matrix:

    fault_free           no injection — the accuracy baseline
    client_crash         a SAMPLED client os._exit()s at round 1
                         (SIGKILL semantics: no FINISH, dangling socket)
    hub_restart          the hub is SIGKILLed mid-run and restarted on
                         the same port; every worker must re-dial
    drop30               every client's model frames (send+recv) drop
                         with p=0.3 (seeded ``FaultPlan`` via the
                         FEDML_TPU_CHAOS env)
    straggler_deadline   one client sleeps past the round deadline
                         every round — permanently dropped
    corrupt_payload      one client's uploads are NaN-corrupted every
                         round; the server must reject them pre-
                         aggregation
    stripe_faults        striped broadcast, 1 KiB stripes: one node
                         loses a stripe (gap), another gets a corrupted
                         one (crc) — each must cost exactly one node's
                         sync (deadline straggler), never a wedged
                         reassembly
    muxer_crash          half the cohort rides ONE muxer process
                         (virtual-client multiplexing) that os._exit()s
                         at round 1 — hundreds of clients (here: half
                         the federation) vanish in one SIGKILL-shaped
                         event; the spares/stale firewall and the den>0
                         empty-round guard must keep the survivors
                         NaN-free and the degradation visible
                         (rounds.degraded)
    telemetry_loss       one node loses every digest frame; rounds
                         untouched, the SLO report names the dark node
    malicious_client     one client uploads x-25 scaled-gradient
                         mutations every round; the streaming defense's
                         outlier reject must exclude them (counted
                         faults.observed{kind=outlier_upload})
    malicious_muxer      one muxer sign-flips its WHOLE virtual
                         cohort's uploads (the PR-10 Sybil surface);
                         norm clipping + per-connection contribution
                         caps must keep the aggregate finite
    shm_ring_full        shm lane with a 1 MiB ring under a 2 MB model:
                         EVERY model payload exceeds the ring, so every
                         frame must take the counted per-frame TCP
                         fallback — the run completes with zero stalls
                         (the genuine ring_full/desc_full reasons are
                         pinned at unit level in tests/test_shm.py)
    shm_peer_crash       muxer on an shm lane os._exit()s mid-round:
                         the hub's lane detach must look exactly like a
                         dropped connection — survivors aggregate,
                         degraded rounds, never a wedged slab
    edge_hub_crash       two-tier topology: the FIRST edge hub
                         os._exit()s when round 1's sync arrives — a
                         whole cohort (its local hub, its partial fold,
                         its uplink) vanishes in one SIGKILL-shaped
                         event; the root's deadline closes the round on
                         the surviving edge's partials, degradation
                         visible, NaN-free to the final round
    flapping_client      open-loop traffic engine: the muxed cohort's
                         connection flaps (drop + re-hello mid-run, PR
                         13's rebind primitive) and nodes churn
                         offline per round — rounds degrade by
                         deadline, never wedge
    overload_burst       traffic engine at the diurnal peak: arrival
                         delays + heavy-tailed straggler draws spike
                         together mid-run; the deadline (sync) or cut
                         (async) absorbs the burst NaN-free
    compound_crash_telemetry
                         TWO simultaneous faults: a sampled client
                         crashes at round 1 WHILE another node's digest
                         stream is blacked out — the forensics verdict
                         SET must attribute both (client_crash AND
                         telemetry_loss), not just the dominant one

    ``--lane shm`` / ``--bcast delta`` re-run the WHOLE matrix over the
    new transport path (FEDXPORT acceptance: all prior scenarios
    NaN-free over shm+delta); ``--topology tree --edge-hubs N`` re-runs
    it over the hierarchical aggregation tree (PR 17 acceptance: every
    fault mode that held flat must hold with an edge tier terminating
    the cohort — scenario-pinned keys still win, so edge_hub_crash is
    a tree run even in the default flat matrix).

Per scenario the output records: survived, rounds completed, rounds
aggregated empty (``zero_participant_rounds``), degraded rounds,
rejected uploads, fault counters (server process + hub), final test
accuracy and its delta vs the fault-free arm, and a NaN check over the
final global model.

Usage (CPU is fine — this is a protocol soak, not a perf benchmark):

    python tools/chaos_run.py --matrix default --out FAULTS_r06.json
    python tools/chaos_run.py --scenario corrupt_payload
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker_env():
    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # keep the children lean: no faked mesh
    return env


def _scenarios(round_timeout: float, num_clients: int = 3):
    """name -> launch() kwargs.  Every faulted arm runs with a round
    deadline: without one a single lost upload wedges the federation
    forever (the exact failure mode this subsystem exists to kill)."""
    from fedml_tpu.faults import FaultPlan, FaultRule, FaultSpec
    from fedml_tpu.faults.traffic import TrafficModel

    drop_plan = FaultPlan(
        seed=0,
        send_spec=FaultSpec(drop_prob=0.3),
        recv_spec=FaultSpec(drop_prob=0.3),
        roles=("client",),
    ).to_json()
    corrupt_plan = FaultPlan(
        seed=0,
        rules=[FaultRule(action="corrupt", node=3,
                         msg_type="C2S_SEND_MODEL", direction="send")],
        roles=("client",),
    ).to_json()
    # stripe-level faults on the striped broadcast path, harshest
    # sustained form: node 2 loses EVERY sync stripe (never assembles a
    # sync — a full broadcast blackout) and node 3 gets every stripe
    # corrupted (crc mismatch aborts each round's frame).  Both nodes
    # must degrade to deadline stragglers round after round without
    # wedging reassembly or the federation.  The surgical single-stripe
    # cases (one dropped stripe -> gap abort, one corrupted -> crc
    # abort, logical frame dies, connection survives) are pinned at
    # unit level in tests/test_comm.py.
    stripe_plan = FaultPlan(
        seed=0,
        rules=[FaultRule(action="drop", node=2,
                         msg_type="S2C_SYNC_MODEL", direction="stripe"),
               FaultRule(action="corrupt", node=3,
                         msg_type="S2C_SYNC_MODEL", direction="stripe")],
        roles=("client",),
    ).to_json()
    # stats-plane blackout: node 2 loses EVERY digest frame it emits
    # (C2S_TELEMETRY is outside DEFAULT_FAULTABLE, so the explicit rule
    # is the only way observability loss happens — never as a side
    # effect of a model-frame mix).  Rounds must be untouched and the
    # rollup un-wedged; the SLO report must flag node 2 as MISSING
    # coverage (counted + named, never silent).
    telemetry_plan = FaultPlan(
        seed=0,
        rules=[FaultRule(action="drop", node=2,
                         msg_type="C2S_TELEMETRY", direction="send")],
        roles=("client",),
    ).to_json()
    # Byzantine arms (fedml_tpu/robust): a scaled-gradient malicious
    # client (x-25: sign-flipped AND amplified — norm ~25x honest, so
    # the streaming outlier reject must fire every round), and a
    # malicious MUXER sign-flipping its whole virtual cohort's uploads
    # through one connection (the PR-10 Sybil surface) — conn caps +
    # norm clipping must bound it.  Both finite: the non-finite
    # firewall never sees them; only the defense layer can.
    malicious_client_plan = FaultPlan(
        seed=0,
        rules=[FaultRule(action="scale_grad", node=3,
                         msg_type="C2S_SEND_MODEL", direction="send",
                         attack_scale=-25.0)],
        roles=("client",),
    ).to_json()
    muxed_half = (num_clients + 1) // 2
    malicious_muxer_plan = FaultPlan(
        seed=0,
        rules=[FaultRule(action="sign_flip", node=n,
                         msg_type="C2S_SEND_MODEL", direction="send")
               for n in range(1, muxed_half + 1)],
        roles=("client",),
    ).to_json()
    # open-loop traffic arms (faults/traffic.py): seeded arrival
    # processes shipped via FEDML_TPU_TRAFFIC — a deterministic day of
    # churn, not a flake.  Probabilities are per (node x round).
    flapping_traffic = TrafficModel(
        seed=0, jitter_s=0.1, churn_prob=0.25, flap_prob=0.5,
    ).to_json()
    # diurnal peak: amplitude 1 on a 2-round period puts every other
    # round at ~2x load — delays and heavy-tailed straggler draws spike
    # together; the straggler cap stays well under the round deadline
    # so most late uploads still arrive (and in async mode fold at the
    # staleness discount) instead of all vanishing at once
    burst_traffic = TrafficModel(
        seed=0, jitter_s=0.1, straggler_prob=0.6,
        straggler_scale_s=0.3, straggler_cap_s=2.0,
        diurnal_amplitude=1.0, diurnal_period_rounds=2,
    ).to_json()
    return {
        "fault_free": {},
        "client_crash": {
            "crash_client_at_round": 1,
            "round_timeout": round_timeout,
        },
        "hub_restart": {
            "restart_hub_after": 1.0,
            "auto_reconnect": 60,
            "round_timeout": round_timeout,
        },
        "drop30": {
            "chaos_plan": drop_plan,
            "round_timeout": round_timeout,
        },
        "straggler_deadline": {
            "slow_client_delay": 10 * round_timeout,
            "round_timeout": round_timeout,
        },
        "corrupt_payload": {
            "chaos_plan": corrupt_plan,
            "round_timeout": round_timeout,
        },
        "stripe_faults": {
            "chaos_plan": stripe_plan,
            "round_timeout": round_timeout,
            # 1 KiB stripes AND a model big enough to cross the
            # threshold: the default 8-dim model's ~450 B sync payload
            # never striped, so this scenario silently injected NOTHING
            # from PR 9 through PR 13 (every FAULTS_r*.json shows
            # degraded=0 and an empty fault-counter set) — caught by
            # the r16 forensics pass when the bundle-only verdict came
            # back "none".  8.2 KB model -> every sync is ~8 stripes.
            "stripe_kib": 1,
            "input_dim": 1024,
        },
        # killing one muxer drops its WHOLE virtual cohort at once (in
        # production: hundreds of clients; here: half the federation —
        # clients 1..ceil(N/2) ride the one muxer, the rest run as
        # plain processes so the survivors keep reporting).  The rounds
        # after the crash must close degraded by deadline with finite
        # aggregates, never NaN or a wedge.
        "muxer_crash": {
            "muxers": 1,
            "muxed_clients": -1,  # resolved to ceil(N/2) in run_scenario
            "crash_muxer_at_round": 1,
            "round_timeout": round_timeout,
        },
        # dropped digest frames must never affect rounds or wedge the
        # rollup: the run completes normally while the SLO report flags
        # the silenced node (run_dir="auto" -> a tmpdir; run_scenario
        # reads slo_report.json back as scenario evidence)
        "telemetry_loss": {
            "chaos_plan": telemetry_plan,
            "round_timeout": round_timeout,
            "run_dir": "auto",
            # short staleness threshold so the blacked-out node trips
            # the coverage objective within this few-round run (the
            # engine's startup grace = one threshold of uptime)
            "slo": json.dumps({"max_stale_streams": 0,
                               "stale_after_s": 1.5}),
        },
        # the x-25 attacker's every upload must be outlier-rejected
        # (counted, never folded), the round closing by deadline with
        # the honest reporters — accuracy within noise of fault_free
        "malicious_client": {
            "chaos_plan": malicious_client_plan,
            "round_timeout": round_timeout,
            "defense": "streaming",
            "norm_bound": 2.0,
            "outlier_mult": 3.0,
        },
        # one muxer sign-flips its whole co-located cohort (half the
        # federation) through ONE connection: norm clipping bounds each
        # upload, the conn cap bounds the connection's total weight —
        # the aggregate must stay finite and the run NaN-free
        # conn_cap 0.5, not lower: at 3 clients the topology has only
        # TWO client connections (the muxer + one dialer), and a cap
        # below 1/2 is unsatisfiable by construction — the engine
        # refuses it loudly (robust.cap_infeasible) rather than
        # half-applying.  norm_bound 1.0 (~5x the honest delta norm):
        # a clipped sign-flip cannot cross zero, only shrink.
        "malicious_muxer": {
            "muxers": 1,
            "muxed_clients": -1,  # resolved to ceil(N/2) in run_scenario
            "chaos_plan": malicious_muxer_plan,
            "round_timeout": round_timeout,
            "defense": "streaming",
            "norm_bound": 1.0,
            "outlier_mult": 6.0,
            "conn_cap": 0.5,
        },
        # every 2.1 MB model payload overflows the 1 MiB/direction ring:
        # the lane must take the counted per-frame TCP fallback every
        # time and the federation must finish with no stall (hub_stats
        # + server shm counters carry the evidence)
        "shm_ring_full": {
            "lane": "shm",
            "shm_mib": 1,
            "shm_min_bytes": 0,
            "input_dim": 262144,
            "round_timeout": round_timeout,
        },
        # a muxer whose payloads ride an shm lane dies mid-round: slab
        # detach == dropped connection (doorbells stop, hub cleans up),
        # survivors keep aggregating — the muxer_crash contract over
        # the new lane
        "shm_peer_crash": {
            "lane": "shm",
            "shm_min_bytes": 0,
            "muxers": 1,
            "muxed_clients": -1,  # resolved to ceil(N/2) in run_scenario
            "crash_muxer_at_round": 1,
            "round_timeout": round_timeout,
        },
        # the FIRST edge hub of a two-edge tree hard-exits when round
        # 1's sync arrives: its whole cohort is orphaned at once (their
        # local hub died under them — reconnects dial a dead port).
        # The root must close every later round by deadline on the
        # surviving edge's partials: degraded rounds, finite model,
        # rc=0.  Topology keys are pinned HERE so the scenario is a
        # tree run even inside the default flat matrix.
        "edge_hub_crash": {
            "topology": "tree",
            "edge_hubs": 2,
            "crash_edge_hub_at_round": 1,
            "round_timeout": round_timeout,
        },
        # churn mid-round via the traffic engine: the muxed half-cohort
        # flaps its ONE connection (drop + re-hello between rounds —
        # PR 13's rebind_connection) while nodes churn offline per
        # round; the reconnect machinery absorbs the flaps and the
        # deadline closes churned rounds degraded, never wedged
        "flapping_client": {
            "muxers": 1,
            "muxed_clients": -1,  # resolved to ceil(N/2) in run_scenario
            "traffic_plan": flapping_traffic,
            "auto_reconnect": 60,
            "round_timeout": round_timeout,
        },
        # arrival spike at the diurnal peak: every node's delay +
        # straggler draw inflates together on peak rounds — the
        # deadline (sync) or the cut + staleness discount (async) must
        # absorb the burst with finite aggregates
        "overload_burst": {
            "traffic_plan": burst_traffic,
            "round_timeout": round_timeout,
        },
        # TWO simultaneous faults: the last sampled client hard-exits
        # at round 1 WHILE node 2's digest stream is blacked out.  The
        # forensics verdict SET must attribute both (client_crash AND
        # telemetry_loss) — the compound-attribution contract
        "compound_crash_telemetry": {
            "crash_client_at_round": 1,
            "chaos_plan": telemetry_plan,
            "round_timeout": round_timeout,
            "slo": json.dumps({"max_stale_streams": 0,
                               "stale_after_s": 1.5}),
        },
    }


def _final_model_eval(out_path: str, seed: int, num_clients: int,
                      input_dim: int = 8):
    """Load the server's final leaves and evaluate on the shared
    synthetic test split (every process builds the same problem from the
    seed, so this is the federation's real held-out accuracy)."""
    import numpy as np

    import jax

    from fedml_tpu.core.client import eval_summary, make_evaluator
    from fedml_tpu.core.types import batch_eval_pack
    from fedml_tpu.experiments.distributed_fedavg import _build_problem

    ds, bundle, init, _ = _build_problem(seed, num_clients,
                                         input_dim=input_dim)
    leaves_like, treedef = jax.tree_util.tree_flatten(init)
    z = np.load(out_path)
    leaves = [np.asarray(z[f"leaf_{i}"]) for i in range(len(leaves_like))]
    nan_free = bool(all(np.isfinite(l).all() for l in leaves))
    variables = jax.tree_util.tree_unflatten(treedef, leaves)
    x, y, m = batch_eval_pack(ds.test_x, ds.test_y, 32)
    summary = eval_summary(make_evaluator(bundle)(variables, x, y, m))
    round_log = json.loads(str(z["round_log"]))
    return {
        "nan_free": nan_free,
        "final_acc": float(summary["test_acc"]),
        "final_loss": float(summary["test_loss"]),
        "rounds_recorded": int(z["rounds"]),
        "round_participants": [
            r.get("participants") for r in round_log if "participants" in r
        ],
    }


def _forensics(run_dir: str) -> dict:
    """Postmortem verdict over the scenario's flight-recorder bundles
    (``tools/fed_forensics.py``) — the scenario record's evidence that
    the black box alone names the injected fault."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import fed_forensics

        v = fed_forensics.analyze(run_dir)
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    return {
        "fault_kind": v.get("fault_kind"),
        "fault_round": v.get("fault_round"),
        "confidence": v.get("confidence"),
        "clock_mode": v.get("clock_mode"),
        "evidence": v.get("evidence"),
        # the RANKED verdict set (compound faults get one entry each);
        # the top-level fields above are its dominant entry
        "verdicts": [
            {"fault_kind": c.get("fault_kind"),
             "fault_round": c.get("fault_round"),
             "confidence": c.get("confidence")}
            for c in (v.get("verdicts") or ())
        ],
        "bundle_errors": v.get("bundle_errors"),
    }


def run_scenario(name: str, kwargs: dict, *, num_clients: int, rounds: int,
                 seed: int, timeout: float, transport=None) -> dict:
    from fedml_tpu.experiments.distributed_fedavg import launch

    if transport:
        # matrix-wide transport overrides (--lane/--bcast): scenario-
        # specific keys win (the shm scenarios pin their own lane)
        kwargs = {**transport, **kwargs}

    out_path = os.path.join(
        tempfile.mkdtemp(prefix=f"chaos_{name}_"), "final.npz"
    )
    if kwargs.get("muxed_clients") == -1:
        kwargs = dict(kwargs, muxed_clients=(num_clients + 1) // 2)
    if not kwargs.get("run_dir") or kwargs.get("run_dir") == "auto":
        # every scenario gets a run_dir now: the flight recorders in
        # each child process dump their black-box bundles there, and
        # the record below carries the forensics verdict built from
        # them (telemetry_loss additionally reads slo_report.json back)
        kwargs = dict(kwargs, run_dir=os.path.dirname(out_path))
    run_dir = kwargs["run_dir"]
    info: dict = {}
    t0 = time.time()
    print(f"== scenario {name} ==", flush=True)
    try:
        rc = launch(
            num_clients=num_clients, rounds=rounds, seed=seed,
            batch_size=16, out_path=out_path, env=_worker_env(),
            info=info, timeout=timeout, **kwargs,
        )
    except Exception as e:  # harness failure IS a scenario failure
        return {"scenario": name, "survived": False,
                "error": f"{type(e).__name__}: {e}",
                "flight_bundles": sorted(
                    glob.glob(os.path.join(run_dir, "flight-*.json"))),
                "forensics": _forensics(run_dir),
                "wall_s": round(time.time() - t0, 1)}
    rec = {
        "scenario": name,
        "survived": rc == 0,
        "rc": rc,
        "rounds": info.get("rounds"),
        "rounds_aggregated_empty": info.get("zero_participant_rounds"),
        "rounds_degraded": info.get("rounds_degraded"),
        "rejected_uploads": info.get("rejected_uploads"),
        "server_fault_counters": info.get("faults") or {},
        "hub_stats": info.get("hub_stats") or {},
        "stats_plane": info.get("stats_plane") or {},
        "wall_s": round(time.time() - t0, 1),
    }
    rec["flight_bundles"] = sorted(
        glob.glob(os.path.join(run_dir, "flight-*.json")))
    rec["forensics"] = _forensics(run_dir)
    report_path = os.path.join(os.path.dirname(out_path), "slo_report.json")
    if kwargs.get("run_dir") and os.path.exists(report_path):
        # telemetry-loss evidence: the SLO report must NAME the node(s)
        # whose digest stream went dark (missing coverage), while the
        # round outcome above stays untouched
        try:
            with open(report_path) as fh:
                rep = json.load(fh)
            sp = rep.get("stats_plane") or {}
            rec["slo_report"] = {
                "ok": rep.get("ok"),
                "by_objective": rep.get("by_objective"),
                "missing_nodes": sp.get("missing_nodes"),
                "stale_streams": sp.get("stale_streams"),
                "streams": sp.get("streams"),
            }
        except (OSError, json.JSONDecodeError) as e:
            rec["slo_report"] = {"error": f"{type(e).__name__}: {e}"}
    if os.path.exists(out_path):
        try:
            rec.update(_final_model_eval(out_path, seed, num_clients,
                                         kwargs.get("input_dim", 8)))
        except Exception as e:
            rec["eval_error"] = f"{type(e).__name__}: {e}"
            rec["nan_free"] = False
    print(f"   -> rc={rc} acc={rec.get('final_acc')} "
          f"empty_rounds={rec.get('rounds_aggregated_empty')} "
          f"({rec['wall_s']}s)", flush=True)
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--matrix", default="default", choices=["default"])
    p.add_argument("--scenario", default="",
                   help="run one scenario by name instead of the matrix")
    p.add_argument("--out", default="FAULTS_r06.json")
    p.add_argument("--num-clients", type=int, default=3)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--round-timeout", type=float, default=20.0,
                   help="per-round deadline for the faulted arms; must "
                        "exceed a client's cold jit+train time on the "
                        "host (~5-10 s on a loaded 1-core CI box)")
    p.add_argument("--timeout", type=float, default=240.0,
                   help="per-scenario hard cap on the server process")
    # transport-path overrides: soak the WHOLE matrix over the shm lane
    # and/or the delta broadcast (FEDXPORT acceptance re-run); the tiny
    # chaos model's frames only exercise the lane at --shm-min-bytes 0
    p.add_argument("--lane", choices=["tcp", "shm"], default="tcp")
    p.add_argument("--bcast", choices=["full", "delta"], default="full")
    p.add_argument("--shm-min-bytes", type=int, default=0)
    # topology override: soak the whole matrix over the hierarchical
    # aggregation tree (PR 17) — scenario-pinned keys still win
    p.add_argument("--topology", choices=["flat", "tree"], default="flat")
    p.add_argument("--edge-hubs", type=int, default=2)
    # round-mode override: soak the whole matrix over the async
    # buffered server (fold-on-arrival, cut-based rounds, staleness
    # discounts) — every fault mode that held under the barrier must
    # hold under cuts
    p.add_argument("--round-mode", choices=["sync", "async"],
                   default="sync")
    p.add_argument("--max-staleness", type=int, default=2)
    args = p.parse_args(argv)

    scenarios = _scenarios(args.round_timeout, args.num_clients)
    if args.scenario:
        if args.scenario not in scenarios:
            print(f"unknown scenario {args.scenario!r}; "
                  f"have {sorted(scenarios)}", file=sys.stderr)
            return 2
        scenarios = {args.scenario: scenarios[args.scenario]}

    transport = {}
    if args.lane != "tcp":
        transport["lane"] = args.lane
        transport["shm_min_bytes"] = args.shm_min_bytes
    if args.bcast != "full":
        transport["bcast"] = args.bcast
    if args.topology == "tree":
        transport["topology"] = "tree"
        transport["edge_hubs"] = args.edge_hubs
    if args.round_mode != "sync":
        transport["round_mode"] = args.round_mode
        transport["max_staleness"] = args.max_staleness

    results = []
    for name, kwargs in scenarios.items():
        results.append(run_scenario(
            name, kwargs, num_clients=args.num_clients, rounds=args.rounds,
            seed=args.seed, timeout=args.timeout, transport=transport,
        ))

    baseline = next(
        (r for r in results
         if r["scenario"] == "fault_free" and "final_acc" in r), None
    )
    for r in results:
        if baseline is not None and "final_acc" in r:
            r["acc_delta_vs_fault_free"] = round(
                r["final_acc"] - baseline["final_acc"], 6
            )

    doc = {
        "matrix": args.matrix if not args.scenario else args.scenario,
        "lane": args.lane,
        "bcast": args.bcast,
        "round_mode": args.round_mode,
        "num_clients": args.num_clients,
        "rounds": args.rounds,
        "seed": args.seed,
        "round_timeout_s": args.round_timeout,
        "generated_unix": round(time.time(), 1),
        "scenarios": results,
        "all_survived": all(r.get("survived") for r in results),
        "all_nan_free": all(r.get("nan_free", False) for r in results),
    }
    with open(args.out, "w") as fh:
        json.dump(doc, fh, indent=2)
    print(json.dumps({"out": args.out,
                      "all_survived": doc["all_survived"],
                      "all_nan_free": doc["all_nan_free"]}))
    return 0 if doc["all_survived"] and doc["all_nan_free"] else 1


if __name__ == "__main__":
    sys.exit(main())
