#!/usr/bin/env python
"""Compression convergence evidence: int8+EF vs fp32 on mnist_lr.

The acceptance claim of the compression subsystem is NOT "smaller
bytes" alone — it is "smaller bytes at unchanged convergence".  This
tool runs the ``mnist_lr`` cross-device preset (the reference's
benchmark/README.md:12 row, 1000 power-law clients, 10/round, the
sampled-cohort fused driver) TWICE with one knob changed:

- ``fp32``   — the uncompressed control arm;
- ``int8ef`` — ``compress_codec="qsgd8"`` + error feedback: the lossy
  uplink simulated inside the compiled round
  (``make_round_fn(codec=...)``), bit-identical to what the TCP wire
  form ships.

and records rounds-to-target against the preset's PRE-DECLARED target
(0.75 x the 0.9 label-noise ceiling = 0.675 — the same target every
prior mnist_lr artifact used).  The verdict requires the int8+EF arm's
crossing within +-20% of the fp32 arm's (both arms evaluated on the
same cadence).  Byte savings ride along from the telemetry counter
pair (``comm.raw_bytes`` / ``comm.compressed_bytes``).

Usage: python tools/compress_convergence_run.py
       [--rounds 60] [--eval-every 2] [--codec qsgd8]
       [--out CONVERGENCE_r06_mnist_lr_int8ef.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from convergence_run import rounds_to_target, trajectory_rows, write_artifact  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=60)
    p.add_argument("--eval-every", type=int, default=2,
                   help="tight cadence: rounds-to-target resolution must "
                   "be finer than the +-20%% acceptance band")
    p.add_argument("--label-noise", type=float, default=0.1)
    p.add_argument("--codec", default="qsgd8")
    p.add_argument("--rounds-per-call", type=int, default=None)
    p.add_argument("--out", default="CONVERGENCE_r06_mnist_lr_int8ef.json")
    args = p.parse_args()
    args.epochs = None  # preset default (E=1)

    from convergence_run import _mnist_lr_spec

    from fedml_tpu.algorithms.fedavg import FedAvgSimulation
    from fedml_tpu.compress import encoded_nbytes, get_codec

    spec = _mnist_lr_spec(args)
    ceiling = 1.0 - args.label_noise
    target = spec["target_frac"] * ceiling

    def run_arm(tag, codec, ef):
        cfg = dataclasses.replace(
            spec["cfg"], comm_rounds=args.rounds,
            frequency_of_the_test=args.eval_every,
            compress_codec=codec, compress_ef=ef,
        )
        sim = FedAvgSimulation(spec["bundle"], spec["ds"], cfg)
        t0 = time.time()
        hist = sim.run_fused_sampled(
            rounds=args.rounds,
            rounds_per_call=args.rounds_per_call or args.eval_every,
            log_fn=lambda m: ("test_acc" in m and print(
                f"[{tag}] " + json.dumps({
                    k: round(v, 5) if isinstance(v, float) else v
                    for k, v in m.items()}), flush=True)),
        )
        wall = time.time() - t0
        traj = trajectory_rows(hist)
        snap = sim.metrics.telemetry.snapshot()["counters"]
        return {
            "codec": codec or "fp32",
            "error_feedback": bool(ef),
            "final_test_acc": traj[-1]["test_acc"] if traj else None,
            "rounds_to_target": rounds_to_target(hist, target),
            "wall_clock_s": round(wall, 1),
            "uplink_bytes_per_round_per_client": (
                sim._enc_nbytes if codec else sim._model_nbytes
            ),
            "comm_counters": {k: v for k, v in snap.items()
                              if "bytes" in k},
            "trajectory": traj,
        }

    arms = {
        "fp32": run_arm("fp32", None, False),
        "int8ef": run_arm("int8ef", args.codec, True),
    }
    rtt_fp, rtt_q = (arms["fp32"]["rounds_to_target"],
                     arms["int8ef"]["rounds_to_target"])
    within = None
    if rtt_fp is not None and rtt_q is not None:
        # the acceptance band, resolution-floored: at a crossing this
        # early the eval cadence (not the optimizer) quantizes rtt, so
        # the band can never be narrower than one eval interval
        band = max(0.2 * rtt_fp, args.eval_every)
        within = abs(rtt_q - rtt_fp) <= band
    codec_obj = get_codec(args.codec)
    model_template = spec["bundle"].init(__import__("jax").random.PRNGKey(0))
    artifact = {
        "experiment": "update-compression convergence: int8(QSGD)+EF vs "
                      "fp32 on the mnist_lr preset (1000 power-law "
                      "clients, 10/round, run_fused_sampled driver)",
        "reference_target": spec["reference_target"],
        "hardness": {
            "standin_label_noise": args.label_noise,
            "accuracy_ceiling": round(ceiling, 4),
            "target_for_rounds_to_target": round(target, 4),
        },
        "codec": {
            "name": args.codec,
            "scheme": "QSGD stochastic uniform quantization, int8, "
                      "256-value chunks with fp32 max-abs scales, "
                      "error feedback (compress/codecs.py)",
            "model_fp32_bytes": encoded_nbytes(None, model_template),
            "model_encoded_bytes": encoded_nbytes(codec_obj,
                                                  model_template),
        },
        "eval_every": args.eval_every,
        "verdict": {
            "rounds_to_target": {"fp32": rtt_fp, "int8ef": rtt_q},
            "within_20pct_band": within,
            "band_note": "acceptance band max(0.2*fp32_rtt, eval_every) "
                         "— the eval cadence floors the resolution",
        },
        "arms": arms,
    }
    write_artifact(args.out, artifact, {
        "rtt_fp32": rtt_fp, "rtt_int8ef": rtt_q, "within_band": within,
        "final": {t: a["final_test_acc"] for t, a in arms.items()}})
    if within is False:
        raise SystemExit("int8+EF rounds-to-target outside the band")


if __name__ == "__main__":
    main()
