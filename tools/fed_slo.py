#!/usr/bin/env python
"""Live federation health view — render ``status.json`` / ``slo_report.json``.

The in-band stats plane (``fedml_tpu/obs/digest`` + ``obs/slo``) makes
the server write an ATOMIC ``status.json`` snapshot every report
interval and at every round close, plus a final ``slo_report.json`` —
so a running (or killed, or wedged) federation always has a current,
machine-readable picture on disk.  This tool renders it:

    python tools/fed_slo.py RUN_DIR            one-shot human summary
    python tools/fed_slo.py RUN_DIR --watch    live TUI (re-reads each
                                               interval; ^C to leave)
    python tools/fed_slo.py RUN_DIR --json     the raw document(s)

``RUN_DIR`` may also be a direct path to a status.json.  Stdlib-only:
this must run on a bare interpreter next to a live run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _resolve(path: str):
    """(status_path, report_path) from a run_dir or a direct file."""
    if os.path.isdir(path):
        return (os.path.join(path, "status.json"),
                os.path.join(path, "slo_report.json"))
    if path.endswith("slo_report.json"):
        return os.path.join(os.path.dirname(path), "status.json"), path
    return path, os.path.join(os.path.dirname(path), "slo_report.json")


def _load(path: str):
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    if v >= 1:
        return f"{v:.3f}s"
    return f"{v * 1e3:.1f}ms"


def render_status(status: dict, report=None) -> str:
    """Human block for one status snapshot (the --watch frame body)."""
    lines = []
    slo = status.get("slo") or {}
    state = "FINISHED" if status.get("finished") else "RUNNING"
    verdict = "OK" if slo.get("ok") else \
        f"VIOLATED x{slo.get('violations_total', '?')}"
    lines.append(
        f"federation {state}  round {status.get('round')}/"
        f"{status.get('rounds_total')}  SLO {verdict}"
    )
    wall = status.get("round_wall_s") or {}
    lines.append(
        f"round wall  p50 {_fmt_s(wall.get('p50'))}  "
        f"p99 {_fmt_s(wall.get('p99'))}  max {_fmt_s(wall.get('max'))}  "
        f"(n={wall.get('count', 0)}; log2-bucket upper bounds)"
    )
    sp = status.get("stats_plane") or {}
    lines.append(
        f"stats plane  streams {sp.get('streams', 0)}  "
        f"frames {sp.get('frames', 0)}  rejected {sp.get('rejected', 0)}  "
        f"dup {sp.get('duplicates', 0)}  "
        f"nodes covered {sp.get('nodes_covered', 0)}  "
        f"missing {sp.get('missing_nodes_total', 0)}"
    )
    stale = sp.get("stale_streams") or []
    if stale:
        lines.append(f"STALE streams: {', '.join(map(str, stale))}")
    sources = status.get("sources") or {}
    if sources:
        lines.append("per-stream liveness:")
        lines.append("  src      seq   age     nodes  frames  lost  state")
        for src in sorted(sources, key=lambda s: int(s) if str(s).lstrip(
                "-").isdigit() else 1 << 30):
            st = sources[src]
            lines.append(
                f"  {str(src):<8} {st.get('seq', 0):<5} "
                f"{st.get('age_s', 0):<7} {st.get('nodes', 0):<6} "
                f"{st.get('frames', 0):<7} {st.get('lost_frames', 0):<5} "
                f"{'STALE' if st.get('stale') else 'live'}"
            )
    recent = slo.get("recent_violations") or []
    if recent:
        lines.append("recent violations:")
        for v in recent:
            lines.append(
                f"  round {v.get('round')}: {v.get('objective')} "
                f"observed={v.get('observed')} threshold={v.get('threshold')}"
            )
    counters = (status.get("rollup") or {}).get("counters") or {}
    interesting = {k: v for k, v in sorted(counters.items())
                   if k.startswith(("rounds.", "faults.observed",
                                    "comm.reconnects", "digest.",
                                    "robust.", "slo.violations",
                                    "async.", "traffic."))}
    if interesting:
        lines.append("rollup counters (merged across the federation):")
        for k, v in list(interesting.items())[:20]:
            lines.append(f"  {k} = {v:g}")
    if report is not None:
        obs = report.get("observed") or {}
        lines.append(
            f"final report: ok={report.get('ok')}  "
            f"violations={report.get('violations_total')}  "
            f"by_objective={report.get('by_objective')}"
        )
        rb = obs.get("round_bytes") or {}
        lines.append(
            f"  bytes/round p50 {rb.get('p50')}  "
            f"participation min {(obs.get('participation') or {}).get('min')}"
        )
        st = obs.get("upload_staleness") or {}
        if st.get("count"):
            lines.append(
                f"  async staleness p99 {st.get('p99')} rounds "
                f"(n={st.get('count')})  discarded weight frac "
                f"{obs.get('discarded_weight_frac')}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="run_dir (or a status.json path)")
    p.add_argument("--watch", action="store_true",
                   help="live mode: redraw every --interval seconds")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--json", action="store_true",
                   help="emit {status, report} as one JSON object")
    args = p.parse_args(argv)
    status_path, report_path = _resolve(args.path)

    if args.json:
        doc = {"status": _load(status_path), "report": _load(report_path)}
        if doc["status"] is None and doc["report"] is None:
            print(f"no status.json / slo_report.json at {args.path!r}",
                  file=sys.stderr)
            return 2
        print(json.dumps(doc, indent=1))
        return 0

    if not args.watch:
        status = _load(status_path)
        if status is None:
            print(f"no readable status.json at {status_path!r} (run with "
                  "--run-dir and --stats-plane on)", file=sys.stderr)
            return 2
        print(render_status(status, _load(report_path)))
        return 0

    # --watch: the file is written atomically (tmp + os.replace), so a
    # re-read mid-write never sees a torn document
    try:
        while True:
            status = _load(status_path)
            frame = (render_status(status, _load(report_path))
                     if status is not None
                     else f"waiting for {status_path} ...")
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            sys.stdout.write(
                frame + f"\n\n[fed_slo --watch {args.path}; ^C to exit]\n"
            )
            sys.stdout.flush()
            if status is not None and status.get("finished"):
                return 0
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
