#!/usr/bin/env python
"""Distributed-tracing evidence run → ``FEDTRACE_r08.json``.

Answers the question PR 5 left open: at 32 clients on this box the hub
multicast path wins 32x on bytes but p50 round wall is ~12% WORSE than
legacy — WHERE does the time go?  Per-process telemetry could not say;
the per-hop trace context + clock-aligned merger (``fed_timeline``) can.

Arms (all on THIS commit, FEDLAT_r07 configuration: ≥1 MB model =
``logistic_regression(--input-dim 131072, 2)``, ``--train-samples 16``
comm-dominant regime, fast hotpath, codec off):

1. ``off_16`` / ``on_16`` — 16 clients, tracing off vs on: the tracing
   OVERHEAD A/B.  Threshold (pre-declared): p50 round wall with tracing
   on ≤ 1.03x off (the header-only restamp must be ~free).  On this
   2-core box a 16-client federation is ~9x oversubscribed and single
   runs vary by far more than 3%, so the A/B is run as ``--reps``
   interleaved repetitions in ABBA order (off,on,on,off — cancels
   linear drift: page-cache warmup, governor state), with a process
   barrier + settle sleep between runs (a leaked client from run N
   polluting run N+1 is exactly the failure mode that produced a
   bogus 2x "overhead" on the first attempt — the mechanism itself
   bisects to ~0 at small scale).  Both arms write ``--run-dir``
   metrics files; the ONLY flipped variable is ``FEDML_TPU_TRACE``.
   The verdict compares the MEDIAN of per-rep p50s (the box's round
   wall is bistable under 16-way concurrent 1 MB uploads — whole runs
   land in a ~70 ms-slower scheduling mode regardless of arm; a
   median over reps is robust to one such run, a single run is not);
   the pooled-delta p50s ride along for transparency.  A quiet-box
   micro benchmark of the mechanism itself (one sender → hub → one
   receiver at the SAME model size, per-message e2e latency off vs
   on) is embedded in the artifact: the per-message cost is the
   number the scheduling noise cannot fake.
2. ``off_32`` / ``on_32`` — 32 clients: ``on_32``'s merged timeline is
   the ATTRIBUTION of the 32-client regression — the per-phase p50
   breakdown (hub queue wait / sender-pool drain / client compute /
   upload fold) compared against ``on_16``'s, phases that grow
   superlinearly named in the verdict.  ``off_32`` pins this session's
   untraced 32-client p50 alongside.

Both measurements read the same series FEDLAT_r07 used (server
``round_log`` close-stamp t-deltas), so the numbers are directly
comparable.  The 32-client Perfetto trace and the merged breakdown are
written next to the artifact (``tools/logs/``).

Usage: python tools/fed_trace_run.py [--clients 16] [--rounds 9]
       [--input-dim 131072] [--out FEDTRACE_r08.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import fed_timeline  # noqa: E402
from tools.trace_summary import percentile  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--clients", type=int, default=16)
    p.add_argument("--clients-big", type=int, default=32)
    p.add_argument("--rounds", type=int, default=9)
    p.add_argument("--input-dim", type=int, default=131072)
    p.add_argument("--train-samples", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--round-timeout", type=float, default=180.0)
    p.add_argument("--reps", type=int, default=2,
                   help="interleaved repetitions per 16-client A/B arm")
    p.add_argument("--skip-32", action="store_true",
                   help="skip the 32-client arms (slow-box escape hatch)")
    p.add_argument("--out", default="FEDTRACE_r08.json")
    args = p.parse_args()

    import numpy as np

    from fedml_tpu.experiments.distributed_fedavg import launch

    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["XLA_FLAGS"] = ""
    log_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "logs")
    os.makedirs(log_dir, exist_ok=True)

    def micro_mechanism(nfloat, n=60):
        """Quiet-box per-message mechanism cost at the A/B's model
        size: one sender → hub → one receiver in THIS process, no
        oversubscription.  Median e2e (send entry → handler entry) and
        send() latency per arm — the overhead floor the federation
        numbers are judged against."""
        import numpy as np

        from fedml_tpu.comm.backend import NodeManager
        from fedml_tpu.comm.message import Message, tree_to_wire
        from fedml_tpu.comm.tcp import TcpBackend, TcpHub
        from fedml_tpu.obs import trace_ctx

        def one(trace):
            trace_ctx.set_enabled(trace)
            hub = TcpHub()
            got = []

            class Mgr(NodeManager):
                def register_message_receive_handlers(self):
                    self.register_message_receive_handler(
                        "T", lambda m: got.append(time.perf_counter()))

            recv = TcpBackend(1, hub.host, hub.port)
            Mgr(recv)
            recv.run_in_thread()
            send = TcpBackend(2, hub.host, hub.port)
            send.await_peers([1])
            w = np.zeros(nfloat, dtype=np.float32)
            e2e, snd = [], []
            try:
                for i in range(n):
                    m = Message("T", 2, 1)
                    m.add_params("model", tree_to_wire({"w": w}))
                    m.add_params("round_idx", i)
                    t0 = time.perf_counter()
                    send.send_message(m)
                    t1 = time.perf_counter()
                    while len(got) <= i:
                        time.sleep(0.0002)
                    e2e.append(got[i] - t0)
                    snd.append(t1 - t0)
            finally:
                send.stop()
                recv.stop()
                hub.stop()
                trace_ctx.set_enabled(None)
            return {"e2e_p50_s": percentile(e2e, 0.5),
                    "send_p50_s": percentile(snd, 0.5),
                    "msgs": n}
        off, on = one(False), one(True)
        return {
            "model_floats": nfloat,
            "off": off, "on": on,
            "per_msg_overhead_s": round(
                on["e2e_p50_s"] - off["e2e_p50_s"], 6),
        }

    def barrier(settle: float = 3.0):
        """No federation process from a previous run may overlap the
        next measurement (the contamination that sank the first A/B
        attempt: a dry run's 18 leaked processes time-sharing the box
        with the 'on' arm).  Wait for every distributed_fedavg child to
        exit, then give the scheduler/page cache a beat to settle."""
        deadline = time.time() + 60.0
        while time.time() < deadline:
            out = subprocess.run(
                ["pgrep", "-f", "fedml_tpu.experiments.distributed_fedavg"],
                capture_output=True, text=True,
            ).stdout.strip()
            if not out:
                break
            time.sleep(1.0)
        else:
            print(f"WARNING: stray federation processes survive the "
                  f"barrier: {out!r}", file=sys.stderr)
        time.sleep(settle)

    def run_one(tag, clients, trace):
        # BOTH arms get a run_dir (per-process metrics emission is part
        # of the baseline): the only variable the A/B flips is
        # FEDML_TPU_TRACE itself
        run_dir = f"/tmp/fedtrace_{tag}"
        shutil.rmtree(run_dir, ignore_errors=True)
        barrier()
        info = {}
        t0 = time.time()
        rc = launch(
            num_clients=clients, rounds=args.rounds, seed=args.seed,
            batch_size=args.batch_size, out_path=f"/tmp/fedtrace_{tag}.npz",
            round_timeout=args.round_timeout,
            codec="none", wire=2, input_dim=args.input_dim,
            hotpath="fast", train_samples=args.train_samples,
            run_dir=run_dir, trace=trace,
            info=info, env=env, server_env=env,
            timeout=600.0 + args.rounds * args.round_timeout,
        )
        if rc != 0:
            raise SystemExit(f"{tag}: server subprocess failed rc={rc}")
        wall = round(time.time() - t0, 1)
        z = np.load(f"/tmp/fedtrace_{tag}.npz")
        round_log = json.loads(str(z["round_log"]))
        stamps = [r["t"] for r in round_log
                  if isinstance(r.get("t"), (int, float))]
        deltas = [round(b - a, 4) for a, b in zip(stamps, stamps[1:])]
        return {
            "clients": clients,
            "trace": trace,
            "rounds": info.get("rounds"),
            "wall_s": wall,
            "run_dir": run_dir,
            "round_wall_s": {
                "samples": deltas,
                "p50": percentile(deltas, 0.50),
                "p95": percentile(deltas, 0.95),
            },
        }

    def pooled(reps):
        samples = [s for r in reps for s in r["round_wall_s"]["samples"]]
        return {
            "clients": reps[0]["clients"],
            "trace": reps[0]["trace"],
            "reps": len(reps),
            "rounds": reps[0]["rounds"],
            "run_dir": reps[-1]["run_dir"],
            "per_rep_p50": [r["round_wall_s"]["p50"] for r in reps],
            "per_rep_wall_s": [r["wall_s"] for r in reps],
            "round_wall_s": {
                "samples": samples,
                "p50": percentile(samples, 0.50),
                "p95": percentile(samples, 0.95),
            },
        }

    def breakdown(run_dir, perfetto_out=None):
        bundle = fed_timeline.load_run(run_dir)
        rows = fed_timeline.build_rounds(bundle)
        summary = fed_timeline.summarize(rows)
        if perfetto_out:
            trace = fed_timeline.to_perfetto(bundle, rows)
            with open(perfetto_out, "w") as fh:
                json.dump(trace, fh)
        return rows, summary

    # ABBA interleave: off,on,on,off,off,on,... — each adjacent pair
    # shares its box state, so drift (cache warmth, governor, memory
    # pressure) cancels instead of loading onto one arm
    order = []
    for i in range(args.reps):
        order += [(False, i), (True, i)] if i % 2 == 0 \
            else [(True, i), (False, i)]
    reps = {False: [], True: []}
    for trace, i in order:
        tag = f"{'on' if trace else 'off'}_16_r{i}"
        reps[trace].append(run_one(tag, args.clients, trace=trace))
    arms = {}
    arms["off_16"] = pooled(reps[False])
    arms["on_16"] = pooled(reps[True])
    # breakdown from the MEDIAN-p50 traced rep (not rep 0 — which may
    # be the one run the box's slow scheduling mode caught)
    med16 = percentile(arms["on_16"]["per_rep_p50"], 0.5)
    rep16 = min(reps[True],
                key=lambda r: abs(r["round_wall_s"]["p50"] - med16))
    rows16, sum16 = breakdown(rep16["run_dir"])
    if not args.skip_32:
        arms["off_32"] = run_one("off_32", args.clients_big, trace=False)
        arms["on_32"] = run_one("on_32", args.clients_big, trace=True)
        pf_path = os.path.join(log_dir, "fedtrace_32_perfetto.json")
        rows32, sum32 = breakdown(arms["on_32"]["run_dir"], pf_path)
        with open(os.path.join(log_dir, "fedtrace_32_breakdown.json"),
                  "w") as fh:
            json.dump({"rounds": rows32, "summary": sum32}, fh, indent=1,
                      default=float)
    else:
        rows32 = sum32 = pf_path = None

    micro = micro_mechanism(args.input_dim * 2 + 2)

    # verdict estimator: median of per-rep p50s (robust to one run
    # caught in the box's slow scheduling mode — see module doc)
    p50_off = percentile(arms["off_16"]["per_rep_p50"], 0.5)
    p50_on = percentile(arms["on_16"]["per_rep_p50"], 0.5)
    overhead = (p50_on / p50_off - 1.0) if p50_off else None

    attribution = None
    if sum32 is not None:
        # phases that grow when clients double (same per-client bytes,
        # same compute): the named attribution of the 32-client wall
        growth = {}
        for ph in fed_timeline.PHASES + ["other"]:
            a = sum16["p50_phase_s"].get(ph)
            b = sum32["p50_phase_s"].get(ph)
            if a is not None and b is not None:
                growth[ph] = {
                    "p50_16_s": round(a, 6), "p50_32_s": round(b, 6),
                    "delta_s": round(b - a, 6),
                    "share_of_32_wall": sum32["phase_share_of_wall"].get(ph),
                }
        # materiality floor: a phase only counts as "dominant growth"
        # when it gains ≥5 ms — sub-ms jitter must not share a verdict
        # line with a 400 ms queue blowup
        ranked = sorted(((k, v) for k, v in growth.items()
                         if v["delta_s"] >= 0.005),
                        key=lambda kv: -(kv[1]["delta_s"]))
        attribution = {
            "p50_round_wall_16_s": sum16["p50_round_wall_s"],
            "p50_round_wall_32_s": sum32["p50_round_wall_s"],
            "per_phase": growth,
            "dominant_growth_phases": [k for k, _ in ranked[:3]],
        }

    artifact = {
        "experiment": (
            f"federation-wide distributed tracing on the real TCP hub "
            f"(FEDLAT_r07 config: logistic_regression({args.input_dim}, 2) "
            f"= {(args.input_dim * 2 + 2) * 4 / 1e6:.2f} MB fp32 model, "
            f"--train-samples {args.train_samples} comm-dominant, fast "
            f"hotpath, codec off, {args.rounds} rounds).  A/B arms flip "
            f"ONLY FEDML_TPU_TRACE on the same commit ({args.reps} "
            f"interleaved ABBA reps per arm, process barrier + settle "
            f"between runs, verdict = median of per-rep p50s); deltas are "
            f"the same server round_log t-deltas FEDLAT_r07 reports."
        ),
        "thresholds_pre_declared": {
            "trace_overhead_p50_max": 0.03,
        },
        "arms": arms,
        "mechanism_micro": micro,
        "breakdown_16": {"summary": sum16},
        "breakdown_32": ({"summary": sum32,
                          "perfetto": pf_path,
                          "rows": "tools/logs/fedtrace_32_breakdown.json"}
                         if sum32 is not None else None),
        "attribution_32_client_regression": attribution,
        "verdict": {
            "trace_overhead_p50": {
                "estimator": "median of per-rep p50s",
                "off": p50_off, "on": p50_on,
                "overhead": round(overhead, 4) if overhead is not None
                else None,
                "per_msg_mechanism_overhead_s":
                    micro["per_msg_overhead_s"],
                "ok": bool(overhead is not None and overhead <= 0.03),
            },
        },
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1, default=float)
    print(json.dumps({"out": args.out,
                      "p50_off_16": p50_off, "p50_on_16": p50_on,
                      "overhead": artifact["verdict"]
                      ["trace_overhead_p50"]["overhead"],
                      "dominant_growth_phases":
                      attribution and
                      attribution["dominant_growth_phases"]}))
    if not artifact["verdict"]["trace_overhead_p50"]["ok"]:
        raise SystemExit("fed trace overhead verdict FAILED")


if __name__ == "__main__":
    main()
