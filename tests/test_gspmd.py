"""DP×TP federated round on a 2-D (clients, model) mesh.

Oracle: the GSPMD-partitioned round equals the same round function run
unsharded on one device (the parallelism-equivalence strategy of
tests/test_tensor_pipeline.py applied to the full FL round)."""

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from fedml_tpu.algorithms.fedavg import ServerState, make_round_fn
from fedml_tpu.core.client import make_client_optimizer, make_local_update
from fedml_tpu.core.types import pack_clients
from fedml_tpu.data.shakespeare import load_fed_shakespeare
from fedml_tpu.models.transformer import transformer_lm
from fedml_tpu.parallel.gspmd import make_dp_tp_mesh, make_dp_tp_round_fn


def _setup(num_clients=4, seq_len=80):
    # per-position targets; /nonexistent forces the synthetic stand-in
    # even when real data was downloaded (cf. tests/test_data.py)
    ds = load_fed_shakespeare(data_dir="/nonexistent", num_clients=num_clients)
    bundle = transformer_lm(
        vocab_size=128, embed_dim=32, num_heads=4, num_layers=2,
        seq_len=seq_len,
    )
    opt = make_client_optimizer("sgd", 0.1)
    local_update = make_local_update(bundle, opt, epochs=1)
    pack = pack_clients(ds, list(range(num_clients)), batch_size=4,
                        steps_per_epoch=2)
    key = jax.random.PRNGKey(0)
    state = ServerState(
        variables=bundle.init(key), opt_state=(),
        round_idx=jnp.zeros((), jnp.int32), key=key,
    )
    args = (
        pack.x, pack.y, pack.mask, pack.num_samples,
        np.ones(num_clients, np.float32),
        np.arange(num_clients, dtype=np.int32),
    )
    return bundle, local_update, state, args


def test_dp_tp_round_matches_single_device():
    bundle, local_update, state, args = _setup()
    # single-device oracle (identical round code, vmap client axis)
    ref_fn = jax.jit(make_round_fn(local_update, client_axis_impl="vmap"))
    ref_state, ref_metrics = ref_fn(state, *[jnp.asarray(a) for a in args])

    mesh = make_dp_tp_mesh(2, 4)  # 2-way client DP x 4-way TP
    round_fn, shard_state, shard_data = make_dp_tp_round_fn(
        mesh, local_update, state.variables
    )
    new_state, metrics = round_fn(shard_state(state), *shard_data(args))

    assert int(new_state.round_idx) == 1
    np.testing.assert_allclose(
        float(metrics["loss_sum"]), float(ref_metrics["loss_sum"]),
        rtol=1e-4,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        ),
        new_state.variables,
        ref_state.variables,
    )


def test_dp_tp_params_sharded_over_model_axis():
    _, local_update, state, args = _setup()
    mesh = make_dp_tp_mesh(2, 4)
    round_fn, shard_state, shard_data = make_dp_tp_round_fn(
        mesh, local_update, state.variables
    )
    st = shard_state(state)
    qkv = st.variables["params"]["Block_0"]["MultiHeadAttention_0"]["Dense_0"]["kernel"]
    assert qkv.sharding.spec == P(None, "model")
    # round output preserves the TP layout (no silent re-replication)
    new_state, _ = round_fn(st, *shard_data(args))
    qkv2 = new_state.variables["params"]["Block_0"]["MultiHeadAttention_0"]["Dense_0"]["kernel"]
    assert qkv2.sharding.spec == P(None, "model")


def test_dp_tp_fedadam_server_opt_state_sharded():
    """FedAdam moments mirror the params, so their sharding must follow
    the TP plan rather than be replicated (bigger-than-one-chip server
    state)."""
    from fedml_tpu.algorithms.fedopt import make_fedopt_server_update
    from fedml_tpu.core.optrepo import get_server_optimizer
    from fedml_tpu.parallel.gspmd import opt_state_sharding_like

    _, local_update, state, args = _setup()
    server_opt = get_server_optimizer("adam", lr=0.01)
    opt_state = server_opt.init(state.variables["params"])
    state = ServerState(
        variables=state.variables, opt_state=opt_state,
        round_idx=state.round_idx, key=state.key,
    )
    mesh = make_dp_tp_mesh(2, 4)
    opt_sharding = opt_state_sharding_like(
        mesh, state.variables, opt_state, axis="model"
    )
    round_fn, shard_state, shard_data = make_dp_tp_round_fn(
        mesh, local_update, state.variables,
        server_update=make_fedopt_server_update(server_opt),
        opt_state_sharding=opt_sharding,
    )
    st = shard_state(state)
    # find the adam mu for a column-parallel kernel and check its layout
    mu = None
    for s in jax.tree_util.tree_leaves(st.opt_state):
        if s.ndim == 2 and s.shape[1] == 3 * 32:  # qkv moment [E, 3E]
            mu = s
            break
    assert mu is not None
    assert mu.sharding.spec == P(None, "model")
    new_state, metrics = round_fn(st, *shard_data(args))
    assert np.isfinite(float(metrics["loss_sum"]))
    assert int(new_state.round_idx) == 1
