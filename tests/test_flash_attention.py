"""Pallas flash attention vs dense reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ops.flash_attention import flash_attention, flash_attn_fn
from fedml_tpu.parallel.ring_attention import dense_attention


def _qkv(L=64, H=2, D=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(L, H, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    want = dense_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_multi_qkv_blocks_carry_state():
    # several q blocks × several kv blocks exercises the scratch carry
    q, k, v = _qkv(L=96, H=1, D=8, seed=3)
    want = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_rejects_ragged():
    q, k, v = _qkv(L=60)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)


def test_flash_attn_fn_plugs_into_transformer():
    from fedml_tpu.models.transformer import TransformerLM

    m = TransformerLM(vocab_size=40, embed_dim=32, num_heads=2,
                      num_layers=1, max_len=128,
                      attn_fn=flash_attn_fn(block_q=16, block_k=16,
                                            interpret=True))
    ref = TransformerLM(vocab_size=40, embed_dim=32, num_heads=2,
                        num_layers=1, max_len=128)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 40, (2, 32)))
    variables = ref.init({"params": jax.random.PRNGKey(0)}, tokens)
    want = ref.apply(variables, tokens)
    got = m.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-4)
