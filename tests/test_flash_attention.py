"""Pallas flash attention vs dense reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ops.flash_attention import flash_attention, flash_attn_fn
from fedml_tpu.parallel.ring_attention import dense_attention


def _qkv(L=64, H=2, D=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(L, H, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    want = dense_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_multi_qkv_blocks_carry_state():
    # several q blocks × several kv blocks exercises the scratch carry
    q, k, v = _qkv(L=96, H=1, D=8, seed=3)
    want = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_rejects_ragged():
    q, k, v = _qkv(L=60)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)


def test_flash_attn_fn_plugs_into_transformer():
    from fedml_tpu.models.transformer import TransformerLM

    m = TransformerLM(vocab_size=40, embed_dim=32, num_heads=2,
                      num_layers=1, max_len=128,
                      attn_fn=flash_attn_fn(block_q=16, block_k=16,
                                            interpret=True))
    ref = TransformerLM(vocab_size=40, embed_dim=32, num_heads=2,
                        num_layers=1, max_len=128)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 40, (2, 32)))
    variables = ref.init({"params": jax.random.PRNGKey(0)}, tokens)
    want = ref.apply(variables, tokens)
    got = m.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(causal):
    """The custom O(L)-memory backward must produce the same dq/dk/dv as
    differentiating dense softmax attention."""
    L, H, D = 32, 2, 8
    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(3), 4)
    q = jax.random.normal(k1, (L, H, D), jnp.float32)
    k = jax.random.normal(k2, (L, H, D), jnp.float32)
    v = jax.random.normal(k3, (L, H, D), jnp.float32)
    cot = jax.random.normal(k4, (L, H, D), jnp.float32)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                              interpret=True)
        return (out * cot).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=causal) * cot).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=f"{name} diverged from dense-attention gradient",
        )


def test_flash_trains_through_local_update():
    """End-to-end: a transformer local update differentiating THROUGH the
    flash kernel (interpret mode on CPU) runs and produces finite loss,
    matching the blockwise-attention update."""
    from fedml_tpu.core.client import make_client_optimizer, make_local_update
    from fedml_tpu.models.transformer import transformer_lm
    from fedml_tpu.ops.flash_attention import flash_attn_fn
    from fedml_tpu.parallel.ring_attention import blockwise_attention

    L, V = 16, 32
    x = jax.random.randint(jax.random.PRNGKey(0), (2, 4, L), 0, V)
    y = jnp.roll(x, -1, -1)
    m = jnp.ones((2, 4), jnp.float32)
    opt = make_client_optimizer("sgd", 0.1)

    results = []
    for attn in (
        flash_attn_fn(block_q=8, block_k=8, interpret=True),
        lambda q, k, v, causal: blockwise_attention(q, k, v, causal=causal,
                                                    block_size=8),
    ):
        b = transformer_lm(vocab_size=V, embed_dim=16, num_heads=2,
                           num_layers=1, seq_len=L, attn_fn=attn)
        lu = make_local_update(b, opt, epochs=1)
        new_vars, met = jax.jit(lu.fn)(
            b.init(jax.random.PRNGKey(0)), x, y, m, jax.random.PRNGKey(1)
        )
        results.append((new_vars, float(met["loss_sum"])))
    (vf, lf), (vb, lb) = results
    assert np.isfinite(lf)
    np.testing.assert_allclose(lf, lb, rtol=1e-4)
    for a, b_ in zip(jax.tree_util.tree_leaves(vf),
                     jax.tree_util.tree_leaves(vb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-4)
