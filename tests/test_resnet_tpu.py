"""The TPU-retiled ResNet variants must be EXECUTION changes only:
identical variable tree, identical function, identical gradients
(models/resnet_tpu.py vs models/resnet.py).  Uses resnet20-scale
Bottleneck stacks ([1,1,1]/[2,2,2]) to keep CPU compile time sane —
every code path (stem s2d, stride-1 s2d blocks, s2d→normal and
s2d→s2d transitions, lane-padded stage) is exercised."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.models.base import ModelBundle
from fedml_tpu.models.resnet import Bottleneck, CifarResNet
from fedml_tpu.models.resnet_tpu import (
    CifarResNetTPU,
    depth_to_space,
    s2d_kernel_stride1,
    space_to_depth,
)


def _baseline(layers=(1, 1, 1)):
    return ModelBundle(
        module=CifarResNet(block=Bottleneck, layers=layers, num_classes=10),
        input_shape=(32, 32, 3),
    )


def _variant(layers=(1, 1, 1), **kw):
    return ModelBundle(
        module=CifarResNetTPU(layers=layers, num_classes=10, **kw),
        input_shape=(32, 32, 3),
    )


def test_s2d_roundtrip_and_kernel_equivalence():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 5))
    np.testing.assert_array_equal(
        np.asarray(depth_to_space(space_to_depth(x))), np.asarray(x)
    )
    # conv(s2d(x), W') == s2d(conv(x, w)) for stride-1 SAME convs
    for k in (1, 3):
        w = jax.random.normal(jax.random.PRNGKey(k), (k, k, 5, 7))
        ref = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        got = jax.lax.conv_general_dilated(
            space_to_depth(x), s2d_kernel_stride1(w), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(space_to_depth(ref)),
            rtol=1e-5, atol=1e-5,
        )


@pytest.mark.parametrize("kw", [
    {},                      # plain re-implementation parity
    {"s2d_stages": 1},       # stage-1 s2d, s2d->normal transition
    {"s2d_stages": 2},       # s2d->s2d transition exercised
    {"s2d_stages": 3},       # all stages + s2d global pool
    {"pad_stage1_to": 32},   # lane padding
    {"conv_variant": "pallas"},  # implicit-GEMM kernel + moment-fused BN
])
def test_variant_matches_baseline(kw):
    base = _baseline((2, 2, 2))
    var = _variant((2, 2, 2), **kw)
    rng = jax.random.PRNGKey(0)
    variables = base.init(rng)
    # identical variable tree: the variant consumes baseline variables
    vshapes = jax.tree_util.tree_map(jnp.shape, var.init(rng))
    bshapes = jax.tree_util.tree_map(jnp.shape, variables)
    assert jax.tree_util.tree_structure(vshapes) == \
        jax.tree_util.tree_structure(bshapes)
    assert vshapes == bshapes

    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    np.testing.assert_allclose(
        np.asarray(var.apply_eval(variables, x)),
        np.asarray(base.apply_eval(variables, x)),
        rtol=2e-4, atol=2e-5,
    )

    # train mode: logits, updated BatchNorm stats, and parameter
    # gradients of a softmax-CE loss must all agree
    y = jnp.arange(4) % 10

    def loss(b):
        def f(params):
            logits, newv = b.apply_train({**variables, "params": params}, x)
            logp = jax.nn.log_softmax(logits)
            return -jnp.take_along_axis(logp, y[:, None], 1).mean(), newv
        return jax.value_and_grad(f, has_aux=True)(variables["params"])

    (lb, nvb), gb = loss(base)
    (lv, nvv), gv = loss(var)
    np.testing.assert_allclose(float(lv), float(lb), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(nvv["batch_stats"]),
                    jax.tree_util.tree_leaves(nvb["batch_stats"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(gv),
                    jax.tree_util.tree_leaves(gb)):
        # atol 5e-4 (was 5e-5): XLA CPU versions differ in conv-grad
        # accumulation order — measured 9.8e-5 max on 4/2304 elements
        # for the bit-identical kw0 re-implementation and 3.3e-4 on
        # 12/2304 for the s2d re-scattered kernels on this box; the
        # forward/loss/BN pins above stay at their tight tolerances
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_pallas_variant_excludes_dense_retilings():
    """conv_variant='pallas' is normal-space: combining it with the
    (r5-measured-negative) s2d / lane-padding transforms must raise
    rather than silently run a partial variant."""
    rng = jax.random.PRNGKey(0)
    for kw in ({"s2d_stages": 1}, {"pad_stage1_to": 32}):
        var = _variant((1, 1, 1), conv_variant="pallas", **kw)
        with pytest.raises(ValueError):
            var.init(rng)
