"""Delta/dedup broadcast tests (fedavg_cross_device ``bcast='delta'``):
chain byte-identity pins, ack grouping, stale-base eviction, resync
recovery, and the mux/lane compositions."""

import os
import time

import numpy as np
import pytest

import jax

from fedml_tpu.algorithms.fedavg_cross_device import (
    FedAvgClientManager,
    FedAvgServerManager,
    apply_bcast_delta,
    encode_bcast_delta,
)
from fedml_tpu.comm.backend import CommBackend
from fedml_tpu.comm.inproc import InprocBus
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_DELTA_BASE,
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_ROUND_INDEX,
    MSG_TYPE_C2S_RESYNC,
    MSG_TYPE_S2C_SYNC_MODEL,
    Message,
    tree_from_wire,
)
from fedml_tpu.core.client import make_client_optimizer, make_local_update
from fedml_tpu.data.synthetic import synthetic_classification
from fedml_tpu.models.linear import logistic_regression
from fedml_tpu.obs.telemetry import get_telemetry


def _counters():
    return dict(get_telemetry().snapshot()["counters"])


def _problem(seed=1, num_clients=2):
    ds = synthetic_classification(
        num_train=60 * num_clients, num_test=30, input_shape=(8,),
        num_classes=2, num_clients=num_clients, partition="homo", seed=seed,
    )
    bundle = logistic_regression(8, 2)
    init = bundle.init(jax.random.PRNGKey(seed))
    lu = make_local_update(bundle, make_client_optimizer("sgd", 0.1), 1)
    return ds, init, lu


def _run_inproc(bcast, bcast_codec="", codec="none", rounds=4, seed=1):
    ds, init, lu = _problem(seed)
    bus = InprocBus()
    sb = bus.register(0)
    cbs = [bus.register(i + 1) for i in range(2)]
    server = FedAvgServerManager(
        sb, init, num_clients=2, clients_per_round=2, comm_rounds=rounds,
        seed=seed, codec=codec, stats_plane=False,
        bcast=bcast, bcast_codec=bcast_codec,
    )
    clients = [
        FedAvgClientManager(cb, lu, ds, batch_size=16,
                            template_variables=init, seed=seed)
        for cb in cbs
    ]
    server.start()
    bus.drain()
    assert server.round_idx == rounds
    leaves = [np.asarray(l).copy()
              for l in jax.tree_util.tree_leaves(server.variables)]
    return leaves, [c.upload_digest for c in clients]


@pytest.mark.parametrize("codec", ["none", "int8"])
def test_delta_vs_full_same_chain_byte_identical(codec):
    """THE delta pin: ``--bcast delta`` is a pure WIRE change — at the
    same chain codec, a delta run and a full-broadcast run produce
    byte-identical upload digests and final models, for fp32 AND
    int8+EF uplinks."""
    delta = _run_inproc("delta", codec=codec)
    full = _run_inproc("full", bcast_codec="qsgd8", codec=codec)
    assert delta[1] == full[1], "upload digests differ"
    for a, b in zip(delta[0], full[0]):
        assert a.tobytes() == b.tobytes(), "final model differs"


def test_delta_rerun_deterministic():
    a = _run_inproc("delta")
    b = _run_inproc("delta")
    assert a[1] == b[1]
    for x, y in zip(a[0], b[0]):
        assert x.tobytes() == y.tobytes()


def test_delta_counts_bcast_bytes_and_shrinks_payload():
    """The int8 chain update is ~4x smaller than the fp32 model it
    replaces on the wire (per-chunk scales cost a little)."""
    before = _counters()
    _run_inproc("delta")
    after = _counters()
    model_bytes = (8 * 2 + 2) * 4
    sent = after.get("comm.delta_bcast_bytes", 0) \
        - before.get("comm.delta_bcast_bytes", 0)
    assert sent > 0
    # 3 delta syncs (rounds 1..3) x 2 groups at most; each update must
    # be well under the fp32 model it replaces
    assert sent < 3 * model_bytes


def test_chain_quantization_error_is_fed_back():
    """The downlink EF recurrence: each round's residual rides into the
    next encode, so the chain tracks the exact aggregate to within one
    quantization step instead of a random walk."""
    tree = {"w": np.zeros(512, np.float32)}
    target = {"w": np.linspace(-0.1, 0.1, 512).astype(np.float32)}
    model = tree
    resid = {"w": np.zeros(512, np.float32)}
    for r in range(6):
        raw = {"w": target["w"] - np.asarray(model["w"], np.float32)
               + resid["w"]}
        wire = encode_bcast_delta("qsgd8", raw, seed=0, round_idx=r)
        dec = tree_from_wire(wire, tree)
        resid = {"w": raw["w"] - np.asarray(dec["w"], np.float32)}
        model = apply_bcast_delta(model, dec)
    err = np.abs(model["w"] - target["w"]).max()
    assert err < 2e-3, f"chain drifted: {err}"


class _Capture(CommBackend):
    def __init__(self, node_id: int = 0):
        super().__init__(node_id)
        self.unicasts = []
        self.mcasts = []

    def send_message(self, msg):
        self.unicasts.append(msg)

    def send_multicast(self, msg, receivers):
        self.mcasts.append((msg, list(receivers)))

    def run(self):
        ...

    def stop(self):
        ...


def test_broadcast_delta_grouping_window_and_no_ack():
    """Grouping unit: acked-in-window nodes share a delta mcast per
    base round; a base older than the bounded delta log (stale-base
    eviction) and a node with no ack both force the counted full-model
    fallback."""
    _, init, _ = _problem()
    cap = _Capture()
    server = FedAvgServerManager(
        cap, init, num_clients=3, clients_per_round=3, comm_rounds=20,
        seed=1, stats_plane=False, bcast="delta", delta_base_window=2,
    )
    zeros = jax.tree_util.tree_map(
        lambda l: np.zeros_like(np.asarray(l, np.float32)), init)
    with server._ack_lock:
        server._delta_log[4] = encode_bcast_delta(
            "qsgd8", zeros, seed=1, round_idx=4)
        server._delta_log[5] = encode_bcast_delta(
            "qsgd8", zeros, seed=1, round_idx=5)
        server._acked.update({1: 4, 2: 2})  # node 3: no ack at all
    server.round_idx = 5
    before = _counters()
    server._broadcast_model(MSG_TYPE_S2C_SYNC_MODEL)
    after = _counters()
    deltas = [(m, r) for m, r in cap.mcasts
              if m.get(MSG_ARG_KEY_DELTA_BASE) is not None]
    fulls = [(m, r) for m, r in cap.mcasts
             if m.get(MSG_ARG_KEY_DELTA_BASE) is None]
    assert len(deltas) == 1
    msg, rcv = deltas[0]
    assert rcv == [1] and msg.get(MSG_ARG_KEY_DELTA_BASE) == 4
    assert len(msg.get(MSG_ARG_KEY_MODEL_PARAMS)) == 1  # delta for r=5
    assert len(fulls) == 1 and sorted(fulls[0][1]) == [2, 3]
    for reason in ("window", "no_ack"):
        key = f"comm.delta_full_fallbacks{{reason={reason}}}"
        assert after.get(key, 0) - before.get(key, 0) == 1, reason


def test_client_resync_on_unknown_base():
    """A delta against a base the client never saw: no training, one
    RESYNC upstream — and the server's handler clears the ack and
    unicasts the full current model."""
    _, init, lu = _problem()
    ds, _, _ = _problem()
    cap = _Capture(node_id=1)
    client = FedAvgClientManager(cap, lu, ds, batch_size=16,
                                 template_variables=init, seed=1)
    msg = Message(MSG_TYPE_S2C_SYNC_MODEL, 0, 1)
    msg.add_params(MSG_ARG_KEY_MODEL_PARAMS,
                   [encode_bcast_delta("qsgd8", init, seed=1, round_idx=3)])
    msg.add_params(MSG_ARG_KEY_DELTA_BASE, 2)
    msg.add_params(MSG_ARG_KEY_ROUND_INDEX, 3)
    msg.add_params("delta_window", 4)
    client._on_sync(msg)
    assert len(cap.unicasts) == 1
    assert cap.unicasts[0].type == MSG_TYPE_C2S_RESYNC
    assert cap.unicasts[0].get(MSG_ARG_KEY_ROUND_INDEX) == 3

    # server side: the resync clears the ack and resends full
    scap = _Capture()
    server = FedAvgServerManager(
        scap, init, num_clients=3, clients_per_round=3, comm_rounds=20,
        seed=1, stats_plane=False, bcast="delta",
    )
    with server._ack_lock:
        server._acked[1] = 2
    server.round_idx = 3
    server._on_resync(cap.unicasts[0].clone_for(0))
    with server._ack_lock:
        assert 1 not in server._acked
    assert len(scap.unicasts) == 1
    resent = scap.unicasts[0]
    assert resent.type == MSG_TYPE_S2C_SYNC_MODEL
    assert resent.get(MSG_ARG_KEY_DELTA_BASE) is None
    assert resent.get(MSG_ARG_KEY_ROUND_INDEX) == 3


def test_resync_recovery_preserves_chain_byte_identity():
    """Mid-run amnesia (the rejoin shape): wipe one client's base cache
    after a couple of rounds — the resync walkback must land it on the
    SAME chain, so the final model equals an uninterrupted delta run's,
    byte for byte."""
    from fedml_tpu.comm.tcp import TcpBackend, TcpHub

    def run(amnesia: bool):
        ds, init, lu = _problem()
        hub = TcpHub()
        backends = []
        try:
            sb = TcpBackend(0, hub.host, hub.port)
            backends.append(sb)
            cbs = [TcpBackend(i + 1, hub.host, hub.port) for i in range(2)]
            backends += cbs
            server = FedAvgServerManager(
                sb, init, num_clients=2, clients_per_round=2,
                comm_rounds=5, seed=1, stats_plane=False, bcast="delta",
                round_timeout=30.0,
            )
            clients = [
                FedAvgClientManager(cb, lu, ds, batch_size=16,
                                    template_variables=init, seed=1)
                for cb in cbs
            ]
            threads = [cb.run_in_thread() for cb in cbs]
            st = sb.run_in_thread()
            server.start()
            if amnesia:
                deadline = time.monotonic() + 60
                while server.round_idx < 2 and time.monotonic() < deadline:
                    time.sleep(0.01)
                clients[1]._bases.clear()  # fresh-process simulation
            st.join(timeout=120)
            assert not st.is_alive()
            assert server.round_idx == 5
            for t in threads:
                t.join(timeout=15)
            return ([np.asarray(l).copy() for l in
                     jax.tree_util.tree_leaves(server.variables)],
                    [c.upload_digest for c in clients])
        finally:
            for b in backends:
                b.stop()
            hub.stop()

    # the wipe races the federation from the test thread: on a starved
    # box all 5 rounds can finish before clear() lands, so the run
    # triggers no resync — retry the setup (bounded), the identity
    # assertion itself is unconditional
    for _attempt in range(3):
        before = _counters()
        wiped = run(amnesia=True)
        after = _counters()
        if after.get("comm.delta_resyncs", 0) \
                > before.get("comm.delta_resyncs", 0):
            break
    assert after.get("comm.delta_resyncs", 0) \
        > before.get("comm.delta_resyncs", 0), "amnesia never triggered"
    clean = run(amnesia=False)
    for a, b in zip(wiped[0], clean[0]):
        assert a.tobytes() == b.tobytes(), "resync diverged the chain"


def _fed_env():
    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    return env


def test_muxed_shm_delta_matches_per_process_full(tmp_path):
    """Composition pin across EVERY new lever at once: a muxed
    federation over the shm lane with delta broadcast equals a
    one-process-per-client pure-TCP full-broadcast federation at the
    same chain codec — upload digests and final model byte-identical."""
    from fedml_tpu.experiments.distributed_fedavg import launch

    env = _fed_env()
    results = {}
    arms = {
        "mux_shm_delta": dict(muxers=1, lane="shm", shm_min_bytes=0,
                              bcast="delta"),
        "proc_tcp_full": dict(muxers=0, bcast="full",
                              bcast_codec="qsgd8"),
    }
    for tag, kw in arms.items():
        out = str(tmp_path / f"final_{tag}.npz")
        info = {}
        rc = launch(num_clients=3, rounds=2, seed=0, batch_size=16,
                    out_path=out, env=env, info=info, timeout=240.0,
                    **kw)
        assert rc == 0, f"{tag} federation failed"
        z = np.load(out)
        leaves = [np.asarray(z[k]) for k in sorted(z.files)
                  if k.startswith("leaf_")]
        digests = {k: v for k, v in sorted(info.items())
                   if k.endswith("_upload_digest")}
        results[tag] = (leaves, digests)
    a, b = results["mux_shm_delta"], results["proc_tcp_full"]
    assert a[1] == b[1], "upload digests differ across topologies"
    for x, y in zip(a[0], b[0]):
        np.testing.assert_array_equal(x, y)


@pytest.mark.slow
def test_connection_churn_soak_rejoin_every_round(tmp_path):
    """PR 10's leftover, over the new transport: muxers drop +
    re-hello every round with amnesia — rebind counters grow, the delta
    broadcast walks every rejoiner through the full-model path, and the
    federation still finishes finite."""
    from fedml_tpu.experiments.distributed_fedavg import launch

    out = str(tmp_path / "final_churn.npz")
    info = {}
    rc = launch(num_clients=6, rounds=5, seed=0, batch_size=16,
                out_path=out, muxers=2, bcast="delta", lane="shm",
                shm_min_bytes=0, mux_rejoin_every_round=True,
                auto_reconnect=1000, round_timeout=15.0,
                env=_fed_env(), info=info, timeout=400.0)
    assert rc == 0
    z = np.load(out)
    assert all(np.isfinite(np.asarray(z[k])).all()
               for k in z.files if k.startswith("leaf_"))
    hub_stats = info.get("hub_stats") or {}
    assert hub_stats.get("node_rebinds", 0) >= 2 * 3, hub_stats
    faults = info.get("faults") or {}
    fallbacks = sum(v for k, v in faults.items()
                    if k.startswith("comm.delta_full_fallbacks"))
    assert fallbacks > 0, faults
