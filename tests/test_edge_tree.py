"""Hierarchical edge-hub aggregation (PR 17): two-tier topology where
edge hubs terminate their cohort's connections, partially fold uploads
with the same O(1) streaming aggregation the server runs, and forward
ONE ``(sum n*model, sum n)`` pair upstream per round.

The in-process tests pin the algebra the topology relies on: fp64
num/den partials COMPOSE EXACTLY, so folding per-edge partials at the
root is bit-equal to folding every upload flat.  The federation tests
spawn the true multi-process tree (``--role edge_hub``) and hold the
tentpole acceptance bar — same seed, same codec, tree vs flat: upload
digests equal byte for byte and the final global models bit-equal —
across fp32/int8+EF, muxed/per-process, and the full downlink
composition (striped fanout + delta broadcast + shm lanes) crossing
the extra hop.
"""

import json
import os

import numpy as np
import pytest

from fedml_tpu.core import tree as treelib


def _fed_env():
    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    return env


def _digests(info):
    return {k: v for k, v in sorted(info.items())
            if k.endswith("_upload_digest")}


def _leaves(out_path):
    z = np.load(out_path)
    return [np.asarray(z[k]) for k in sorted(z.files)
            if k.startswith("leaf_")]


# --- in-process: the partial-fold algebra ------------------------------------

def _rand_tree(rng):
    return {
        "w": rng.standard_normal((5, 3)).astype(np.float32),
        "b": rng.standard_normal((3,)).astype(np.float32),
    }


def test_tiered_fold_composes_bitwise():
    """Edge hubs fold their cohort into fp64 (num, den) partials; the
    root folds the PARTIALS.  Exactness of the composition is what
    makes the tree topology-invisible: fold(fold(A), fold(B)) must be
    bit-equal to fold(A + B) in one flat pass, for any contiguous
    partition of the cohort."""
    rng = np.random.default_rng(17)
    uploads = [(_rand_tree(rng), float(w))
               for w in rng.integers(1, 90, size=12)]

    def fold(pairs):
        acc, total = None, 0.0
        for t, w in pairs:
            acc = treelib.tree_fold_weighted(acc, t, w)
            total += w
        return acc, total

    flat_acc, flat_n = fold(uploads)
    for split in (1, 4, 7, 11):
        # tier 1: per-edge partials; tier 2: root folds partials with
        # weight 1 (the num is already n-weighted, the den rides along)
        root_acc, root_n = None, 0.0
        for g in (uploads[:split], uploads[split:]):
            part_acc, part_n = fold(g)
            root_acc = treelib.tree_fold_weighted(root_acc, part_acc, 1.0)
            root_n += part_n
        assert root_n == flat_n
        for a, b in zip(_flat(root_acc), _flat(flat_acc)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        flat_mean = treelib.tree_finalize_weighted_mean(
            flat_acc, flat_n, uploads[0][0])
        tree_mean = treelib.tree_finalize_weighted_mean(
            root_acc, root_n, uploads[0][0])
        for a, b in zip(_flat(tree_mean), _flat(flat_mean)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _flat(t):
    import jax

    return jax.tree_util.tree_flatten(t)[0]


# --- federation: tree vs flat byte-identity ----------------------------------

def _run(tmp_path, tag, **kw):
    from fedml_tpu.experiments.distributed_fedavg import launch

    out = str(tmp_path / f"final_{tag}.npz")
    info = {}
    rc = launch(seed=0, batch_size=16, out_path=out,
                env=_fed_env(), info=info, timeout=300.0, **kw)
    assert rc == 0, f"{tag} federation failed (rc={rc})"
    return _digests(info), _leaves(out), info


def _assert_tree_matches_flat(tmp_path, codec, muxers):
    # muxers=2 (not 1): a muxer owns its whole virtual range and is
    # indivisible under the tree partition — one muxer for the full
    # cohort would collapse the tree to a single edge
    base = dict(num_clients=6, rounds=2, codec=codec, muxers=muxers)
    dig_flat, leaves_flat, _ = _run(tmp_path, f"flat_{codec}", **base)
    dig_tree, leaves_tree, info = _run(
        tmp_path, f"tree_{codec}", topology="tree", edge_hubs=2, **base)
    assert len(dig_flat) == 6 and dig_flat == dig_tree
    for a, b in zip(leaves_flat, leaves_tree):
        np.testing.assert_array_equal(a, b)
    stats = [v for k, v in info.items() if k.endswith("_stats")
             and k.startswith("edge_")]
    assert len(stats) == 2
    for s in stats:
        assert s["folded_uploads"] > 0
        assert s["flat_fallbacks"] == 0


@pytest.mark.parametrize("codec,muxers", [("none", 0), ("int8", 2)])
def test_tree_vs_flat_byte_identical(tmp_path, codec, muxers):
    """THE tentpole pin: same seed, same codec — a two-edge tree
    federation's per-client upload digests equal the flat federation's
    byte for byte, and the final global models are bit-equal.  Covers
    fp32 per-process clients and int8+EF muxed virtual clients (the
    slow-marked cross pairs complete the matrix)."""
    _assert_tree_matches_flat(tmp_path, codec, muxers)


@pytest.mark.slow
@pytest.mark.parametrize("codec,muxers", [("none", 2), ("int8", 0)])
def test_tree_vs_flat_byte_identical_cross(tmp_path, codec, muxers):
    """The other half of the codec x process-shape matrix."""
    _assert_tree_matches_flat(tmp_path, codec, muxers)


def test_tree_downlink_composition_byte_identical(tmp_path):
    """The downlink stack crosses the extra hop once per EDGE link and
    the edge re-fans out: striped fanout + delta-chain broadcast + shm
    lanes + int8 uploads on one muxed tree federation must still match
    the flat run bit-for-bit.  The tree side runs with inline decodes
    (decode_workers=0) against the flat side's pooled decodes, so
    byte-equality also pins decode-pool invariance across topologies."""
    base = dict(num_clients=6, rounds=3, codec="int8", muxers=2,
                lane="shm", bcast="delta", fanout="striped")
    dig_flat, leaves_flat, _ = _run(
        tmp_path, "flat_comp", decode_workers=2, **base)
    dig_tree, leaves_tree, _ = _run(
        tmp_path, "tree_comp", topology="tree", edge_hubs=2,
        decode_workers=0, **base)
    assert len(dig_flat) == 6 and dig_flat == dig_tree
    for a, b in zip(leaves_flat, leaves_tree):
        np.testing.assert_array_equal(a, b)


def test_tree_smoke_64_virtual_clients(tmp_path):
    """Tier-1 smoke at the scale shape FEDTREE_r17 extrapolates from:
    64 virtual clients on two muxers behind two edge hubs — the root
    sees 2 aggregation connections instead of 64.  Every round
    aggregates the full cohort, leaves stay finite, and both edges
    report clean folds (no flat fallbacks)."""
    from fedml_tpu.experiments.distributed_fedavg import launch

    out = str(tmp_path / "final_tree64.npz")
    info = {}
    rc = launch(num_clients=64, rounds=2, seed=0, batch_size=16,
                out_path=out, muxers=2, topology="tree", edge_hubs=2,
                env=_fed_env(), info=info, timeout=300.0)
    assert rc == 0
    z = np.load(out)
    assert int(z["rounds"]) == 2
    log = json.loads(str(z["round_log"]))
    rounds = [r for r in log if "participants" in r]
    assert all(r["participants"] == list(range(1, 65)) for r in rounds)
    for k in z.files:
        if k.startswith("leaf_"):
            assert np.isfinite(z[k]).all()
    stats = [v for k, v in info.items() if k.startswith("edge_")
             and k.endswith("_stats")]
    assert len(stats) == 2
    for s in stats:
        assert s["folded_uploads"] > 0
        assert s["flat_fallbacks"] == 0
        # the whole cohort's uploads left the edge as O(groups) partial
        # frames, not O(clients) — the point of the tier
        assert s["uplink_frames"] <= 2 * 2 + 2  # rounds * groups + slack


# --- range-claim hellos: O(edges) root state ---------------------------------

def _wait(cond, timeout=15.0):
    import time

    deadline = time.monotonic() + timeout
    while not cond() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert cond(), "condition never held"


class _Collect:
    def __init__(self, sink, key):
        self.sink, self.key = sink, key

    def receive_message(self, t, m):
        self.sink.setdefault(self.key, []).append(m)


def test_range_hello_keeps_root_state_o_edges():
    """A contiguous edge cohort registers as ONE ``[lo, hi]`` range
    claim: the root hub's per-id map stays empty for the cohort (its
    routing state is O(edges), the fix for the measured +33 MB
    registration tax at 100k per-id claims) while the ``nodes`` gauge
    still counts every virtual client — and the peers barrier is
    satisfied through the range, so coordinators need no change."""
    from fedml_tpu.comm.edge import EdgeUplinkBackend
    from fedml_tpu.comm.tcp import TcpBackend, TcpHub

    hub = TcpHub()
    edge = sender = None
    try:
        cohort = list(range(10, 210))  # 200 contiguous ids
        edge = EdgeUplinkBackend(cohort, hub.host, hub.port)
        assert edge._hello_obj() == {"node_ranges": [[10, 209]]}
        edge.run_in_thread()
        sender = TcpBackend(500, hub.host, hub.port)
        # the barrier resolves the cohort against the [lo, hi] claim
        sender.await_peers(cohort + [500], timeout=15.0)
        stats = hub.stats()
        assert stats["nodes"] == 201  # 200 claimed by range + sender
        assert stats["connections"] == 2
        assert stats["range_conns"] == 1
        with hub._lock:
            assert not any(n in hub._conns for n in cohort)
    finally:
        for b in (edge, sender):
            if b is not None:
                b.stop()
        hub.stop()


def test_range_mcast_compacts_meta_and_expands_at_edge():
    """A broadcast covering the WHOLE cohort ships one wrapped copy
    whose meta is the two-int ``range`` (never a 100k-id list — the
    689 KB sync-frame tax); the edge expands it locally so the re-fan
    target list is unchanged.  A partial broadcast falls back to the
    explicit ``nodes`` list."""
    import numpy as np

    from fedml_tpu.comm.edge import EdgeUplinkBackend
    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.tcp import TcpBackend, TcpHub

    frames = []

    class _Spy(EdgeUplinkBackend):
        def _on_mux_frame(self, frame, payload, nbytes, region=None):
            frames.append(dict(frame))
            super()._on_mux_frame(frame, payload, nbytes, region=region)

    hub = TcpHub()
    got = {}
    edge = sender = None
    try:
        cohort = list(range(1, 9))
        edge = _Spy(cohort, hub.host, hub.port)
        edge.add_observer(_Collect(got, "edge"))
        edge.run_in_thread()
        sender = TcpBackend(99, hub.host, hub.port)
        sender.await_peers(cohort, timeout=15.0)
        m = Message("SYNC", 99, -1)
        m.add_params("model", np.arange(8, dtype=np.float32))
        sender.send_multicast(m, cohort)
        _wait(lambda: len(got.get("edge", ())) >= 1)
        assert frames[0].get("range") == [1, 8]
        assert frames[0].get("nodes") is None
        assert getattr(got["edge"][0], "_mux_nodes", None) == cohort
        # partial cohort: explicit list, no range compaction
        sender.send_multicast(m, cohort[:3])
        _wait(lambda: len(got.get("edge", ())) >= 2)
        assert frames[1].get("range") is None
        assert frames[1].get("nodes") == cohort[:3]
        assert getattr(got["edge"][1], "_mux_nodes", None) == cohort[:3]
    finally:
        for b in (edge, sender):
            if b is not None:
                b.stop()
        hub.stop()


def test_range_claim_displaced_as_one_atom():
    """Ranges are rebind ATOMS: a later hello overlapping ANY id in a
    range claim displaces the whole connection (counted as one rebind
    per covered id), never a partial carve-out — partial range
    mutation would reintroduce per-id bookkeeping at the root."""
    from fedml_tpu.comm.edge import EdgeUplinkBackend
    from fedml_tpu.comm.tcp import TcpBackend, TcpHub

    hub = TcpHub()
    edge = thief = None
    try:
        edge = EdgeUplinkBackend(list(range(1, 9)), hub.host, hub.port)
        edge.run_in_thread()
        _wait(lambda: hub.stats()["range_conns"] == 1)
        thief = TcpBackend(4, hub.host, hub.port)  # overlaps the claim
        thief.run_in_thread()
        _wait(lambda: hub.stats()["node_rebinds"] >= 8)
        stats = hub.stats()
        assert stats["range_conns"] == 0
        assert stats["node_rebinds"] == 8  # all 8 covered ids, at once
        assert stats["nodes"] == 1  # only the thief remains
    finally:
        for b in (edge, thief):
            if b is not None:
                b.stop()
        hub.stop()


def test_noncontiguous_cohort_falls_back_to_per_id_hello():
    """A gap in the cohort disables range compaction: the hello lists
    ids (hello v2) and the hub registers per-id — correctness never
    depends on the launcher's contiguous partitioning."""
    from fedml_tpu.comm.edge import EdgeUplinkBackend
    from fedml_tpu.comm.tcp import TcpHub

    hub = TcpHub()
    edge = None
    try:
        cohort = [1, 2, 3, 5]  # hole at 4
        edge = EdgeUplinkBackend(cohort, hub.host, hub.port)
        assert edge._hello_obj() == {"node_ids": cohort}
        edge.run_in_thread()
        _wait(lambda: hub.stats()["nodes"] == 4)
        stats = hub.stats()
        assert stats["range_conns"] == 0
        assert stats["connections"] == 1
        with hub._lock:
            assert all(n in hub._conns for n in cohort)
    finally:
        if edge is not None:
            edge.stop()
        hub.stop()
