"""Client-optimizer parity against the reference's torch semantics.

The reference's client Adam is ``torch.optim.Adam(lr, weight_decay=1e-4,
amsgrad=True)`` (``MyModelTrainer.py:38-40``) — COUPLED L2 weight decay
and the torch amsgrad variant (running max over the RAW second moment).
Both differ subtly from optax's adamw/amsgrad; rounds-to-accuracy parity
depends on getting them right, so we pin them against torch itself."""

import numpy as np
import optax
import pytest
import torch

import jax
import jax.numpy as jnp

from fedml_tpu.core.client import make_client_optimizer


def _run_pair(name, lr, torch_factory, steps=8, **kw):
    rng = np.random.RandomState(0)
    w0 = rng.randn(6, 4).astype(np.float32)
    grads = [rng.randn(6, 4).astype(np.float32) for _ in range(steps)]

    wt = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt_t = torch_factory([wt])
    for g in grads:
        opt_t.zero_grad()
        wt.grad = torch.tensor(g)
        opt_t.step()

    opt_j = make_client_optimizer(name, lr, **kw)
    state = opt_j.init(jnp.asarray(w0))
    wj = jnp.asarray(w0)
    for g in grads:
        upd, state = opt_j.update(jnp.asarray(g), state, wj)
        wj = optax.apply_updates(wj, upd)
    np.testing.assert_allclose(
        np.asarray(wj), wt.detach().numpy(), rtol=1e-5, atol=1e-6
    )


def test_adam_matches_torch_amsgrad_coupled_l2():
    _run_pair(
        "adam", 0.01,
        lambda ps: torch.optim.Adam(ps, lr=0.01, weight_decay=1e-4,
                                    amsgrad=True),
    )


def test_sgd_momentum_wd_matches_torch():
    _run_pair(
        "sgd", 0.1,
        lambda ps: torch.optim.SGD(ps, lr=0.1, momentum=0.9,
                                   weight_decay=1e-3),
        momentum=0.9, weight_decay=1e-3,
    )


def test_plain_sgd_matches_torch():
    _run_pair("sgd", 0.05, lambda ps: torch.optim.SGD(ps, lr=0.05))


def test_adam_explicit_wd_zero_honored():
    """weight_decay=0.0 must mean ZERO decay (ADVICE r1): only None falls
    back to the reference's 1e-4 torch default."""
    _run_pair(
        "adam", 0.01,
        lambda ps: torch.optim.Adam(ps, lr=0.01, weight_decay=0.0,
                                    amsgrad=True),
        weight_decay=0.0,
    )
