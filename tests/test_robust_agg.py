"""Robust + private aggregation (fedml_tpu/robust + core/robust):

- np-vs-jnp parity of the ONE shared defense-math implementation
  (sim transform and server hot path cannot drift);
- streaming screening: clip semantics, outlier-reject counted-never-
  silent, honest uploads untouched (byte-identity with undefended);
- buffered median / trimmed-mean leaf-exact vs an independent numpy
  oracle;
- per-connection contribution caps (water-filling math + a dominant
  muxer connection through the server close);
- client-level DP noise bit-reproducible from the fold_in stream;
- arrival-order independence of the defended close;
- Byzantine FaultRule attacks (sign_flip / scale_grad) through the
  chaos layer;
- the SLO engine's max_outlier_uploads budget;
- muxed-vs-per-process defended federations producing identical
  models (real OS processes).
"""

import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms.fedavg_cross_device import FedAvgServerManager
from fedml_tpu.comm.inproc import InprocBus
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
    MSG_ARG_KEY_ROUND_INDEX,
    MSG_TYPE_C2S_SEND_MODEL,
    Message,
    tree_from_wire,
    tree_to_wire,
)
from fedml_tpu.core import robust as robustlib
from fedml_tpu.core import tree as treelib
from fedml_tpu.faults import (
    ChaosBackend,
    FaultPlan,
    FaultRule,
    attack_message,
)
from fedml_tpu.obs.telemetry import get_telemetry
from fedml_tpu.robust import (
    DefenseConfig,
    RobustAggregator,
    cap_connection_weights,
)

RNG = np.random.RandomState(42)


def _params(shape_seed=0):
    rng = np.random.RandomState(shape_seed)
    return {"w": rng.randn(4, 3).astype(np.float32),
            "b": rng.randn(3).astype(np.float32)}


def _stacked(k, scale=1.0, seed=1):
    rng = np.random.RandomState(seed)
    return {"w": (rng.randn(k, 4, 3) * scale).astype(np.float32),
            "b": (rng.randn(k, 3) * scale).astype(np.float32)}


# ---------------------------------------------------------------------------
# one implementation: np == jnp


def test_defense_math_np_jnp_parity():
    gp, sp = _params(), _stacked(5, scale=3.0)
    for fn in (
        lambda xp: robustlib.param_delta_norms(gp, sp, xp=xp),
        lambda xp: robustlib.clip_stacked_params(gp, sp, 1.0, xp=xp),
        lambda xp: robustlib.coordinate_median(sp, xp=xp),
        lambda xp: robustlib.trimmed_mean(sp, 0.2, xp=xp),
    ):
        a = jax.tree_util.tree_leaves(fn(np))
        b = jax.tree_util.tree_leaves(fn(jnp))
        for x, y in zip(a, b):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-6, atol=1e-7)


def test_screen_clip_matches_sim_transform_row():
    """The server's per-upload (K=1, numpy) clip equals the compiled
    transform's row for the same client — the sim-vs-cross-device
    parity pin the dedup satellite asks for."""
    gvars = {"params": _params()}
    sp = _stacked(3, scale=2.0)
    transform = robustlib.make_robust_transform(
        "norm_diff_clipping", norm_bound=0.7)
    stacked_out = transform(gvars, {"params": sp}, None, None)
    ra = RobustAggregator(
        DefenseConfig(defense="streaming", norm_bound=0.7), seed=0)
    for k in range(3):
        row = {"params": jax.tree_util.tree_map(lambda s, k=k: s[k], sp)}
        out, _ = ra.screen(row, gvars, round_idx=0, slot=k)
        for a, b in zip(
            jax.tree_util.tree_leaves(out["params"]),
            [np.asarray(l)[k]
             for l in jax.tree_util.tree_leaves(stacked_out["params"])],
        ):
            np.testing.assert_allclose(np.asarray(a), b,
                                       rtol=1e-6, atol=1e-7)


def test_weak_dp_noise_key_parity_with_engine_stream():
    """Server-side DP noise uses the engine's exact aggregation-noise
    key chain — fold_in(fold_in(fold_in(seed_key, round), AGG_STREAM),
    slot) — so for the same (seed, round, slot) the noise is the
    engine's weak-DP noise bit-for-bit."""
    gp = _params()
    key_engine = jax.random.fold_in(
        jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(7), 3),
            robustlib.AGG_STREAM,
        ),
        11,
    )
    a = robustlib.noise_params(key_engine, gp, 0.05)
    b = robustlib.noise_params(
        robustlib.agg_noise_key(jax.random.PRNGKey(7), 3, 11), gp, 0.05)
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_dp_noise_reproducible_and_slot_independent():
    cfg = DefenseConfig(defense="streaming", norm_bound=10.0,
                        dp_clip=5.0, dp_noise=0.1)
    base = {"params": _params()}
    up = {"params": jax.tree_util.tree_map(lambda g: g + 0.1,
                                           base["params"])}
    outs = [RobustAggregator(cfg, seed=3).screen(
        dict(up), base, round_idx=2, slot=4)[0] for _ in range(2)]
    for x, y in zip(jax.tree_util.tree_leaves(outs[0]),
                    jax.tree_util.tree_leaves(outs[1])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    other_slot, _ = RobustAggregator(cfg, seed=3).screen(
        dict(up), base, round_idx=2, slot=5)
    assert any(
        not np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(outs[0]),
                        jax.tree_util.tree_leaves(other_slot))
    )


def test_defense_config_validation():
    with pytest.raises(ValueError):
        DefenseConfig(defense="nope")
    with pytest.raises(ValueError):
        DefenseConfig(defense="streaming", outlier_mult=2.0)  # no bound
    with pytest.raises(ValueError):
        DefenseConfig(defense="median", conn_cap=0.4)  # caps = streaming
    with pytest.raises(ValueError):
        DefenseConfig(defense="streaming", conn_cap=1.5)
    with pytest.raises(ValueError):
        DefenseConfig(dp_noise=0.1)  # noise without a clip bound
    with pytest.raises(ValueError):
        # a bound without its mode would be silently inert
        DefenseConfig(norm_bound=1.0)
    assert not DefenseConfig().enabled
    assert DefenseConfig(defense="median").buffered


def test_conn_cap_refused_on_legacy_hotpath():
    """conn_cap is enforced by the streaming fold's per-conn
    accumulators — on the legacy buffered path it would be silently
    unenforced, so the manager refuses the combination outright."""
    bus = InprocBus()
    backend = bus.register(0)
    init = {"params": {"w": np.zeros((2, 2), np.float32)}}
    with pytest.raises(ValueError):
        FedAvgServerManager(
            backend, init, num_clients=2, clients_per_round=2,
            comm_rounds=1, seed=0, streaming_agg=False, stats_plane=False,
            defense=DefenseConfig(defense="streaming", norm_bound=1.0,
                                  conn_cap=0.5),
        )


def test_dp_clip_only_counts_as_clipped():
    """A clip triggered by dp_clip (no streaming norm bound) must still
    count — a mutation with zero telemetry violates the
    counted-never-silent discipline."""
    cfg = DefenseConfig(dp_clip=0.2)
    ra = RobustAggregator(cfg, seed=0)
    base = {"params": _params()}
    up = {"params": jax.tree_util.tree_map(lambda g: g + 1.0,
                                           base["params"])}
    out, flags = ra.screen(up, base, round_idx=0, slot=0)
    assert flags["clipped"] is True
    norm = float(robustlib.param_delta_norms(
        jax.tree_util.tree_map(lambda x: np.asarray(x, np.float32),
                               base["params"]),
        {k: np.asarray(v)[None] for k, v in out["params"].items()},
        xp=np)[0])
    assert norm == pytest.approx(0.2, rel=1e-5)


# ---------------------------------------------------------------------------
# connection caps


def test_cap_connection_weights_math():
    # dominant conn capped to exactly the cap fraction of the new total
    scales, inf = cap_connection_weights({"a": 80.0, "b": 10.0, "c": 10.0},
                                         0.4)
    assert not inf
    w = {"a": 80.0, "b": 10.0, "c": 10.0}
    total = sum(scales[k] * w[k] for k in w)
    assert scales["b"] == scales["c"] == 1.0
    assert scales["a"] * w["a"] / total == pytest.approx(0.4)
    # two conns over the cap: both land exactly at cap
    w2 = {"a": 50.0, "b": 30.0, "c": 20.0}
    scales2, inf2 = cap_connection_weights(w2, 0.34)
    assert not inf2
    t2 = sum(scales2[k] * w2[k] for k in w2)
    assert scales2["a"] * 50.0 / t2 == pytest.approx(0.34)
    assert scales2["b"] * 30.0 / t2 == pytest.approx(0.34)
    assert scales2["c"] == 1.0
    # infeasible: equal weights under the cap — loudly unapplied
    scales3, inf3 = cap_connection_weights({"a": 10.0, "b": 10.0}, 0.4)
    assert inf3 and all(v == 1.0 for v in scales3.values())
    # single conn carrying the whole round: its fraction is 1 > cap
    # by definition — infeasible, loudly (never silently uncapped)
    assert cap_connection_weights({"a": 5.0}, 0.4) == ({"a": 1.0}, True)


def _mk_server(defense, *, num_clients=4, clients_per_round=4, spares=0,
               comm_rounds=1, init=None):
    bus = InprocBus()
    backend = bus.register(0)
    for i in range(1, num_clients + 1):
        bus.register(i)
    init = init if init is not None else {
        "params": {"w": np.zeros((4, 3), np.float32),
                   "b": np.zeros((3,), np.float32)}}
    server = FedAvgServerManager(
        backend, init, num_clients=num_clients,
        clients_per_round=clients_per_round, comm_rounds=comm_rounds,
        seed=0, spares=spares, stats_plane=False, defense=defense,
    )
    return server


def _upload(server, sender, tree, n, round_idx=0):
    m = Message(MSG_TYPE_C2S_SEND_MODEL, sender, 0)
    m.add_params(MSG_ARG_KEY_ROUND_INDEX, round_idx)
    m.add_params(MSG_ARG_KEY_MODEL_PARAMS, tree_to_wire(tree))
    m.add_params(MSG_ARG_KEY_NUM_SAMPLES, float(n))
    server._on_model(m)


def test_conn_cap_dominant_muxer_through_close():
    """Clients 1-3 share one connection (a muxer) with a dominant
    weight share; client 4 dials alone.  The close must rescale the
    muxed connection to exactly the cap fraction — oracle recomputed
    from the raw uploads + the cap math."""
    cfg = DefenseConfig(defense="streaming", conn_cap=0.5)
    server = _mk_server(cfg)
    server._robust.set_conn_map({1: [1, 2, 3], 2: [4]})
    trees = [{"params": {"w": np.full((4, 3), float(i + 1), np.float32),
                         "b": np.full((3,), float(i + 1), np.float32)}}
             for i in range(4)]
    ns = [30.0, 30.0, 30.0, 10.0]  # conn1 = 90 vs conn2 = 10
    for i, (t, n) in enumerate(zip(trees, ns)):
        _upload(server, i + 1, t, n)
    assert server.round_idx == 1
    # oracle: per-conn num/den, conn1 rescaled so its share == cap
    scales, inf = cap_connection_weights({"conn1": 90.0, "conn2": 10.0},
                                         0.5)
    assert not inf and scales["conn1"] < 1.0
    # direct oracle: scaled fp64 num/den
    num64 = None
    den = 0.0
    for conn, idxs in (("conn1", (0, 1, 2)), ("conn2", (3,))):
        cacc = None
        cn = 0.0
        for i in idxs:
            cacc = treelib.tree_fold_weighted(cacc, trees[i], ns[i])
            cn += ns[i]
        scaled = treelib.tree_scale(cacc, scales[conn])
        num64 = scaled if num64 is None else treelib.tree_add(num64, scaled)
        den += scales[conn] * cn
    expected = treelib.tree_finalize_weighted_mean(
        num64, den, trees[0])
    for a, b in zip(jax.tree_util.tree_leaves(server.variables),
                    jax.tree_util.tree_leaves(expected)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    rec = server.round_log[-1]
    assert rec["defense"]["capped_conns"] == 1


def test_conn_cap_infeasible_is_loud_noop():
    cfg = DefenseConfig(defense="streaming", conn_cap=0.3)
    server = _mk_server(cfg, num_clients=2, clients_per_round=2)
    server._robust.set_conn_map({1: [1], 2: [2]})
    t = get_telemetry()
    before = t.counter_value("robust.cap_infeasible")
    trees = [{"params": {"w": np.ones((4, 3), np.float32),
                         "b": np.ones((3,), np.float32)}}] * 2
    for i in range(2):
        _upload(server, i + 1, trees[i], 10.0)
    assert server.round_idx == 1
    assert server.round_log[-1]["defense"].get("cap_infeasible") is True
    assert t.counter_value("robust.cap_infeasible") == before + 1
    # weights left unscaled: plain mean
    for a in jax.tree_util.tree_leaves(server.variables):
        np.testing.assert_array_equal(np.asarray(a),
                                      np.ones_like(np.asarray(a)))


# ---------------------------------------------------------------------------
# outlier reject / buffered estimators through the server


def test_outlier_reject_counted_never_silent():
    cfg = DefenseConfig(defense="streaming", norm_bound=1.0,
                        outlier_mult=3.0)
    server = _mk_server(cfg, num_clients=3, clients_per_round=2, spares=1)
    t = get_telemetry()
    before = t.counter_value("faults.observed", kind="outlier_upload",
                             msg_type=MSG_TYPE_C2S_SEND_MODEL)
    huge = {"params": {"w": np.full((4, 3), 50.0, np.float32),
                       "b": np.zeros((3,), np.float32)}}
    _upload(server, 1, huge, 5.0)
    assert server.round_idx == 0 and not server.pending
    assert server.rejected_uploads == 1
    assert t.counter_value("faults.observed", kind="outlier_upload",
                           msg_type=MSG_TYPE_C2S_SEND_MODEL) == before + 1
    assert any(e.get("kind") == "outlier_upload"
               for e in server.round_log if "rejected_from" in e)
    # the honest cohort still closes the round (K=2 of 3 with a spare)
    ok = {"params": {"w": np.full((4, 3), 0.01, np.float32),
                     "b": np.zeros((3,), np.float32)}}
    _upload(server, 2, ok, 5.0)
    _upload(server, 3, ok, 5.0)
    assert server.round_idx == 1
    assert server.round_log[-1]["defense"]["outliers"] == 1


@pytest.mark.parametrize("defense,trim", [("median", 0.2),
                                          ("trimmed_mean", 0.25)])
def test_buffered_estimators_leaf_exact_vs_numpy_oracle(defense, trim):
    cfg = DefenseConfig(defense=defense, trim_frac=trim)
    server = _mk_server(cfg, num_clients=5, clients_per_round=5)
    rng = np.random.RandomState(9)
    trees = [{"params": {"w": rng.randn(4, 3).astype(np.float32),
                         "b": rng.randn(3).astype(np.float32)}}
             for _ in range(5)]
    ns = [1.0, 2.0, 3.0, 4.0, 5.0]
    for i, (t, n) in enumerate(zip(trees, ns)):
        _upload(server, i + 1, t, n)
    assert server.round_idx == 1
    stack = {k: np.stack([t["params"][k] for t in trees])
             for k in ("w", "b")}
    if defense == "median":
        oracle = {k: np.median(stack[k].astype(np.float32), axis=0)
                  for k in stack}
    else:
        cut = int(trim * 5)
        srt = {k: np.sort(stack[k].astype(np.float32), axis=0)
               for k in stack}
        oracle = {k: np.mean(srt[k][cut:5 - cut], axis=0) for k in stack}
    for k in ("w", "b"):
        np.testing.assert_array_equal(
            np.asarray(server.variables["params"][k]),
            oracle[k].astype(np.float32),
        )
    # a Byzantine minority cannot move the median past honest values:
    # re-run with two wildly hostile uploads among five
    server2 = _mk_server(DefenseConfig(defense="median"),
                         num_clients=5, clients_per_round=5)
    hostile = [{"params": {"w": np.full((4, 3), s, np.float32),
                           "b": np.full((3,), s, np.float32)}}
               for s in (1e4, -1e4)]
    honest = trees[:3]
    for i, t in enumerate(honest + hostile):
        _upload(server2, i + 1, t, 1.0)
    med = np.asarray(server2.variables["params"]["w"])
    lo = np.min(np.stack([t["params"]["w"] for t in honest]), axis=0)
    hi = np.max(np.stack([t["params"]["w"] for t in honest]), axis=0)
    assert (med >= lo).all() and (med <= hi).all()


def test_streaming_defense_arrival_order_independent():
    """Same uploads, two arrival orders, defended streaming close →
    byte-identical models (per-upload screening is a pure function of
    (upload, base, seed, round, slot); the fp64 fold is exact at these
    magnitudes)."""
    rng = np.random.RandomState(5)
    trees = [{"params": {"w": rng.randn(4, 3).astype(np.float32) * s,
                         "b": rng.randn(3).astype(np.float32) * s}}
             for s in (0.1, 2.0, 0.3, 5.0)]
    ns = [3.0, 7.0, 11.0, 2.0]

    def run(order):
        cfg = DefenseConfig(defense="streaming", norm_bound=0.5,
                            dp_clip=0.4, dp_noise=0.02)
        server = _mk_server(cfg)
        for i in order:
            _upload(server, i + 1, trees[i], ns[i])
        assert server.round_idx == 1
        return server.variables

    a = run([0, 1, 2, 3])
    b = run([3, 1, 0, 2])
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_honest_uploads_bitwise_untouched_by_streaming_defense():
    """Defended and undefended rounds stay digest-comparable: uploads
    inside every bound take the EXACT undefended code path (no fp32
    rewrite), so an honest defended run is byte-identical to the
    undefended one."""
    rng = np.random.RandomState(6)
    trees = [{"params": {"w": rng.randn(4, 3).astype(np.float32) * 0.1,
                         "b": rng.randn(3).astype(np.float32) * 0.1}}
             for _ in range(4)]
    ns = [3.0, 7.0, 11.0, 2.0]

    def run(defense):
        server = _mk_server(defense)
        for i in range(4):
            _upload(server, i + 1, trees[i], ns[i])
        assert server.round_idx == 1
        return server.variables

    a = run(None)
    b = run(DefenseConfig(defense="streaming", norm_bound=100.0,
                          outlier_mult=10.0))
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Byzantine FaultRules through the chaos layer


def test_attack_rule_plan_roundtrip():
    plan = FaultPlan(
        seed=0,
        rules=[FaultRule(action="scale_grad", node=3,
                         msg_type="C2S_SEND_MODEL", attack_scale=-10.0),
               FaultRule(action="sign_flip", node=4,
                         msg_type="C2S_SEND_MODEL")],
    )
    back = FaultPlan.from_json(plan.to_json())
    assert back.rules[0].attack_scale == -10.0
    assert back.rules[1].action == "sign_flip"
    acts = back.decide(3, "send", "C2S_SEND_MODEL", 0)
    assert acts and acts[0]["action"] == "scale_grad"
    assert acts[0]["attack_scale"] == -10.0
    with pytest.raises(ValueError):
        FaultRule(action="sign_flip", direction="stripe")


def test_attack_message_scales_every_float_leaf():
    tree = {"params": {"w": np.ones((2, 2), np.float32),
                       "steps": np.array([3], np.int32)}}
    m = Message(MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    m.add_params(MSG_ARG_KEY_MODEL_PARAMS, tree_to_wire(tree))
    twin = attack_message(m, -1.0)
    assert twin is not None and twin is not m
    back = tree_from_wire(twin.get(MSG_ARG_KEY_MODEL_PARAMS), tree)
    np.testing.assert_array_equal(np.asarray(back["params"]["w"]),
                                  -np.ones((2, 2), np.float32))
    np.testing.assert_array_equal(np.asarray(back["params"]["steps"]),
                                  [3])  # int leaves untouched
    # the original message payload is untouched (copy-on-write)
    orig = tree_from_wire(m.get(MSG_ARG_KEY_MODEL_PARAMS), tree)
    np.testing.assert_array_equal(np.asarray(orig["params"]["w"]),
                                  np.ones((2, 2), np.float32))


def test_chaos_sign_flip_and_scale_through_inproc():
    bus = InprocBus()
    plan = FaultPlan(
        seed=0,
        rules=[FaultRule(action="scale_grad", node=1,
                         msg_type="C2S_SEND_MODEL", direction="send",
                         attack_scale=10.0)],
    )
    sender = ChaosBackend(bus.register(1), plan)
    receiver = bus.register(0)
    got = []

    class Obs:
        def receive_message(self, t, m):
            got.append(m)

    receiver.add_observer(Obs())
    tree = {"params": {"w": np.full((2, 2), 2.0, np.float32)}}
    t = get_telemetry()
    before = t.counter_value("faults.injected", action="scale_grad",
                             msg_type=MSG_TYPE_C2S_SEND_MODEL)
    m = Message(MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    m.add_params(MSG_ARG_KEY_MODEL_PARAMS, tree_to_wire(tree))
    sender.send_message(m)
    bus.drain()
    assert len(got) == 1
    back = tree_from_wire(got[0].get(MSG_ARG_KEY_MODEL_PARAMS), tree)
    np.testing.assert_array_equal(np.asarray(back["params"]["w"]),
                                  np.full((2, 2), 20.0, np.float32))
    assert t.counter_value("faults.injected", action="scale_grad",
                           msg_type=MSG_TYPE_C2S_SEND_MODEL) == before + 1


def test_attack_message_reaches_codec_payloads():
    """A sign-flip on a codec-encoded DELTA upload flips the decoded
    update (the stealth attack shape: honest norm, hostile direction)."""
    from fedml_tpu.compress import get_codec

    codec = get_codec("int8")
    tree = {"w": np.linspace(-1, 1, 16, dtype=np.float32).reshape(4, 4)}
    key = jax.random.PRNGKey(0)
    wire = tree_to_wire(tree, codec=codec, key=key, delta=True)
    m = Message(MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    m.add_params(MSG_ARG_KEY_MODEL_PARAMS, wire)
    twin = attack_message(m, -1.0)
    assert twin is not None
    dec = tree_from_wire(twin.get(MSG_ARG_KEY_MODEL_PARAMS), tree)
    ref = tree_from_wire(wire, tree)
    np.testing.assert_allclose(np.asarray(dec["w"]),
                               -np.asarray(ref["w"]), rtol=1e-6)


# ---------------------------------------------------------------------------
# SLO budget


def test_slo_max_outlier_uploads_budget():
    from fedml_tpu.obs.slo import SloEngine, SloSpec

    spec = SloSpec.from_obj({"max_outlier_uploads": 2})
    engine = SloEngine(spec)
    digest = {"counters": {
        "faults.observed{kind=outlier_upload,msg_type=C2S_SEND_MODEL}": 5
    }, "hists": {}}
    found = engine.evaluate(0, digest, {}, expected_nodes=None)
    assert any(v["objective"] == "outlier_uploads" and v["observed"] == 5
               for v in found)
    report = engine.report(digest, {})
    assert report["observed"]["outlier_uploads"] == 5
    assert not report["ok"]
    # inside budget: quiet
    engine2 = SloEngine(SloSpec.from_obj({"max_outlier_uploads": 10}))
    assert engine2.evaluate(0, digest, {}) == []


# ---------------------------------------------------------------------------
# defended muxed-vs-per-process determinism (real OS processes)


def _final_leaf_digest(path):
    z = np.load(path)
    h = hashlib.sha256()
    for k in sorted(k for k in z.files if k.startswith("leaf_")):
        h.update(np.ascontiguousarray(z[k]).tobytes())
    return h.hexdigest(), int(z["rounds"])


def test_defended_federation_muxed_vs_per_process_identical(tmp_path):
    """Same seed, streaming defense with the clip ACTIVE (bound below
    the honest delta norm), muxed vs one-process-per-client topology:
    final models byte-identical — the defended twin of the PR-10
    muxed-vs-per-process pin."""
    import os

    from fedml_tpu.experiments.distributed_fedavg import launch

    env = dict(os.environ)
    env["FEDML_TPU_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    digests = {}
    for name, muxers in (("proc", 0), ("mux", 2)):
        out = str(tmp_path / f"final_{name}.npz")
        rc = launch(
            num_clients=4, rounds=2, seed=0, batch_size=16,
            out_path=out, muxers=muxers, env=env,
            defense="streaming", norm_bound=0.1, outlier_mult=50.0,
            timeout=240.0,
        )
        assert rc == 0
        digests[name], rounds = _final_leaf_digest(out)
        assert rounds == 2
    assert digests["proc"] == digests["mux"]


def test_robust_counters_registered_in_metric_schema():
    from fedml_tpu.obs import metric_schema as ms

    for name in ("robust.clipped_uploads", "robust.dp_noised_uploads",
                 "robust.capped_conns", "robust.cap_infeasible"):
        assert ms.metric_type(name) == "counter"
    assert ms.metric_type("robust.upload_norm") == "histogram"


def test_defense_rec_serializable():
    """round_log defense records must be JSON-able (they ride the out
    npz round_log and the round_close telemetry event)."""
    cfg = DefenseConfig(defense="streaming", norm_bound=0.5)
    server = _mk_server(cfg, num_clients=2, clients_per_round=2)
    big = {"params": {"w": np.full((4, 3), 1.0, np.float32),
                      "b": np.zeros((3,), np.float32)}}
    _upload(server, 1, big, 1.0)
    _upload(server, 2, big, 1.0)
    assert server.round_idx == 1
    json.dumps(server.round_log)
    assert server.round_log[-1]["defense"]["clipped"] == 2
