"""Ring attention / sequence parallelism: exactness vs dense attention."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.parallel.compat import shard_map

from fedml_tpu.parallel.ring_attention import (blockwise_attention,
                                               dense_attention,
                                               ring_attention)


def _qkv(L=64, H=2, D=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(L, H, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_dense(causal):
    q, k, v = _qkv()
    want = dense_attention(q, k, v, causal=causal)
    got = blockwise_attention(q, k, v, causal=causal, block_size=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_blockwise_ragged_blocks():
    q, k, v = _qkv(L=48)
    want = dense_attention(q, k, v, causal=True)
    got = blockwise_attention(q, k, v, causal=True, block_size=20)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense_on_8_devices(causal):
    L, H, D = 64, 2, 8
    q, k, v = _qkv(L=L, H=H, D=D, seed=1)
    want = dense_attention(q, k, v, causal=causal)

    mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))
    fn = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=causal,
                          block_size=8),
        mesh=mesh, in_specs=(P("sp"), P("sp"), P("sp")),
        out_specs=P("sp"), check_vma=False,
    )
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_ragged_shards(causal):
    """Shard length NOT divisible by block_size must still be exact
    (regression: unpadded ring partials double-counted clamped keys)."""
    L, H, D = 48, 2, 8   # 4 devices -> shard length 12, block_size 8
    q, k, v = _qkv(L=L, H=H, D=D, seed=5)
    want = dense_attention(q, k, v, causal=causal)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    fn = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=causal,
                          block_size=8),
        mesh=mesh, in_specs=(P("sp"), P("sp"), P("sp")),
        out_specs=P("sp"), check_vma=False,
    )
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_sequence_parallel_lm_matches_single_device():
    from fedml_tpu.models.transformer import TransformerLM
    from fedml_tpu.parallel.sequence import (make_sequence_mesh,
                                             sequence_parallel_lm)

    mesh = make_sequence_mesh(8)
    module, init, apply = sequence_parallel_lm(
        mesh, vocab_size=50, embed_dim=32, num_heads=2, num_layers=2,
        max_len=256, block_size=8,
    )
    variables = init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 50, (2, 64)), jnp.int32
    )
    got = apply(variables, tokens)
    ref = TransformerLM(vocab_size=50, embed_dim=32, num_heads=2,
                        num_layers=2, max_len=256)
    want = ref.apply(variables, tokens, train=False)
    assert got.shape == (2, 64, 50)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-4)


def test_transformer_trains_through_local_update():
    """The LM plugs into the same federated engine as every other model."""
    from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation
    from fedml_tpu.core.types import FedDataset
    from fedml_tpu.models.transformer import transformer_lm

    rng = np.random.RandomState(0)
    seq = 16
    x = rng.randint(0, 30, (60, seq)).astype(np.int32)
    y = np.roll(x, -1, axis=1)
    ds = FedDataset(
        train_x=x[:48], train_y=y[:48], test_x=x[48:], test_y=y[48:],
        train_client_idx={c: np.arange(c * 16, (c + 1) * 16) for c in range(3)},
        test_client_idx=None, num_classes=30, name="lm-synth",
    )
    cfg = FedAvgConfig(num_clients=3, clients_per_round=3, comm_rounds=2,
                       epochs=1, batch_size=8, lr=0.1,
                       frequency_of_the_test=1)
    sim = FedAvgSimulation(
        transformer_lm(vocab_size=30, embed_dim=16, num_heads=2,
                       num_layers=1, seq_len=seq),
        ds, cfg,
    )
    hist = sim.run()
    assert np.isfinite(hist[-1]["train_loss"])
    assert "test_acc" in hist[-1]


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_matches_dense(causal):
    """The flash-kernel ring path (per-step pallas attention + lse
    merging, interpret mode on CPU) must equal dense attention — and
    therefore the lax ring — exactly."""
    from fedml_tpu.parallel.ring_attention import ring_flash_attention

    L, H, D = 128, 2, 8  # 16 per shard -> no >=128 block; pass block=8
    q, k, v = _qkv(L=L, H=H, D=D, seed=3)
    want = dense_attention(q, k, v, causal=causal)

    mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))
    fn = shard_map(
        functools.partial(ring_flash_attention, axis_name="sp",
                          causal=causal, block=8, interpret=True),
        mesh=mesh, in_specs=(P("sp"), P("sp"), P("sp")),
        out_specs=P("sp"), check_vma=False,
    )
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_gradients_match_lax_ring():
    """grad through the flash ring (custom VJP incl. the lse cotangent
    from the merge weights) must equal grad through the lax ring."""
    from fedml_tpu.parallel.ring_attention import ring_flash_attention

    L, H, D = 64, 2, 8
    q, k, v = _qkv(L=L, H=H, D=D, seed=5)
    cot = jnp.asarray(np.random.RandomState(9).randn(L, H, D).astype(np.float32))
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))

    def make_loss(impl):
        fn = shard_map(
            impl, mesh=mesh, in_specs=(P("sp"), P("sp"), P("sp")),
            out_specs=P("sp"), check_vma=False,
        )
        return lambda q, k, v: (fn(q, k, v) * cot).sum()

    for causal in (False, True):
        g_flash = jax.grad(make_loss(functools.partial(
            ring_flash_attention, axis_name="sp", causal=causal, block=8,
            interpret=True)), argnums=(0, 1, 2))(q, k, v)
        g_lax = jax.grad(make_loss(functools.partial(
            ring_attention, axis_name="sp", causal=causal, block_size=8)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_flash, g_lax, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5,
                err_msg=f"{name} (causal={causal})",
            )


def test_sequence_parallel_lm_flash_impl():
    """The public attn_impl='flash' path (interpret on the CPU mesh)
    matches the default lax impl through a full LM forward; unknown impl
    names raise."""
    from fedml_tpu.parallel.sequence import (
        make_sequence_mesh, sequence_parallel_lm,
    )

    mesh = make_sequence_mesh(4)
    kwargs = dict(vocab_size=32, embed_dim=16, num_heads=2, num_layers=1,
                  max_len=64)
    _, init, apply_lax = sequence_parallel_lm(mesh, **kwargs, block_size=8)
    _, _, apply_flash = sequence_parallel_lm(
        mesh, **kwargs, attn_impl="flash", flash_block=8,
        flash_interpret=True,
    )
    vs = init(jax.random.PRNGKey(0), sample_len=16)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 64), 0, 32)
    np.testing.assert_allclose(
        np.asarray(apply_flash(vs, toks)), np.asarray(apply_lax(vs, toks)),
        rtol=3e-4, atol=3e-4,
    )
    with pytest.raises(ValueError):
        sequence_parallel_lm(mesh, **kwargs, attn_impl="pallas")
    with pytest.raises(ValueError):  # block_size is a lax-path knob
        sequence_parallel_lm(mesh, **kwargs, attn_impl="flash", block_size=8)
